"""Deterministic soak: many cycles of workload churn through the full
operator loop with disruption enabled. At every stable point the cluster
must be coherent — all pods bound, no orphan NodeClaims/Nodes, bindings
consistent with capacity, state cache synced (the failure-detection /
recovery story of SURVEY §5 exercised end-to-end, not per-controller).
"""
import random

import pytest

from tests.helpers import make_nodepool, make_pod
from tests.test_e2e import new_operator, replicated

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.objects import NodeSelectorRequirement


def assert_coherent(op):
    pods = op.kube.list_pods()
    nodes = {n.name for n in op.kube.list_nodes()}
    for p in pods:
        assert p.node_name, f"{p.name} unbound at stable point"
        assert p.node_name in nodes, f"{p.name} bound to ghost {p.node_name}"
    # claim <-> node coherence: every registered claim's node exists and
    # every managed node traces to a claim
    claims = op.kube.list_nodeclaims()
    for c in claims:
        if c.status.node_name:
            assert c.status.node_name in nodes, f"claim {c.name} orphaned"
    by_pid = {c.status.provider_id for c in claims if c.status.provider_id}
    for n in op.kube.list_nodes():
        if n.labels.get(L.NODEPOOL_LABEL_KEY):
            assert n.provider_id in by_pid, f"node {n.name} has no claim"
    # per-node requests within allocatable
    for n in op.kube.list_nodes():
        used = 0.0
        for p in pods:
            if p.node_name == n.name:
                used += p.resource_requests.get("cpu", 0.0)
        assert used <= n.status.allocatable.get("cpu", 0.0) + 1e-9, n.name
    assert op.cluster.synced()
    assert not op.disruption.in_flight


@pytest.mark.parametrize("solver", ["greedy", "tpu"])
def test_churn_soak_20_cycles(solver):
    rng = random.Random(7)
    op = new_operator(solver)
    op.kube.create(make_nodepool(requirements=[NodeSelectorRequirement(
        L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a", "zone-b", "zone-c"))]))
    live = {}
    serial = 0

    for cycle in range(20):
        # add a wave of workload
        for _ in range(rng.randint(3, 10)):
            name = f"w{serial}"
            serial += 1
            kwargs = {}
            if rng.random() < 0.25:
                kwargs["spread_zone"] = True
            p = replicated(make_pod(
                cpu=rng.choice([0.25, 0.5, 1.0, 2.0, 4.0]),
                memory_gib=rng.choice([0.5, 1.0, 2.0]),
                name=name,
                **kwargs,
            ))
            op.kube.create(p)
            live[name] = p
        # remove a random slice of the old workload
        for name in rng.sample(sorted(live), min(len(live), rng.randint(0, 6))):
            pod = op.kube.get(type(live[name]), name)
            if pod is not None:
                op.kube.delete(pod)
            del live[name]
        op.run_until_idle(max_iters=200)
        # age the cluster so consolidation conditions mature and fire
        op.clock.step(rng.choice([5.0, 45.0, 400.0]))
        op.run_until_idle(max_iters=200)
        assert_coherent(op)

    # final deep consolidation pass: drop most of the load and verify the
    # cluster shrinks without stranding anything
    nodes_before = len(op.kube.list_nodes())
    for name in sorted(live)[: max(len(live) - 3, 0)]:
        pod = op.kube.get(type(live[name]), name)
        if pod is not None:
            op.kube.delete(pod)
        del live[name]
    for _ in range(6):
        op.clock.step(60.0)
        op.run_until_idle(max_iters=200)
    assert_coherent(op)
    assert len(op.kube.list_nodes()) < nodes_before
    assert len(op.kube.list_pods()) == len(live)
