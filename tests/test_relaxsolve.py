"""relaxsolve (ISSUE 13): the convex-relaxation solver backend.

Contract under test:
* the relax backend STRICTLY improves node count AND $-cost on problems
  where first-template-wins is suboptimal, and NEVER regresses anywhere
  (the scored fallback serves the FFD answer when rounding loses);
* every relax result passes the UNMODIFIED ResultVerifier — on plain,
  topology, tier, and gang problems — with the rejection counter unmoved
  (the relaxation composes the constraints, it doesn't special-case them);
* the anytime contract: a spent budget serves the FFD answer;
* the verdict cache: warm re-solves of a won problem dispatch once
  (p50 parity with ffd mode) and keep the improved packing;
* mode isolation: relax and ffd problems never share a vmapped dispatch
  (codec.problem_bucket component + _KernelRequest.shape_key component);
* the wire: solver_mode field + X-Solver-Mode header + solverd/operator
  flag plumbing.
"""
import copy

import pytest

from tests.helpers import GIB, make_nodepool, make_pod
from tests.test_fuzz_parity import fuzz_scenario

from karpenter_core_tpu.cloudprovider.kwok import build_catalog
from karpenter_core_tpu.models.provisioner import (
    DeviceScheduler,
    _KernelRequest,
    solve_batch,
)
from karpenter_core_tpu.solver import codec
from karpenter_core_tpu.solver.gangs import (
    GANG_ANNOTATION,
    GANG_SAME_TEMPLATE_ANNOTATION,
)
from karpenter_core_tpu.solver.verify import ResultVerifier


def _rejections():
    from karpenter_core_tpu.metrics import wiring as m

    return dict(m.SOLVER_RESULT_REJECTED.values)


def two_pool_world(cheaper_dense: float = 0.9):
    """The shape where first-template-wins provably loses: pool 'a-first'
    (first by name at equal weight) offers only 4-cpu nodes, pool
    'b-dense' 16-cpu nodes at ``cheaper_dense``x the per-cpu price — the
    FFD backend packs everything onto a-first (4 pods/node for 1-cpu
    pods), the relaxation onto b-dense (16 pods/node, cheaper)."""
    cat_a = build_catalog(cpu_grid=[4], mem_factors=[4], oses=["linux"],
                          arches=["amd64"])
    cat_b = build_catalog(cpu_grid=[16], mem_factors=[4], oses=["linux"],
                          arches=["amd64"])
    for it in cat_b:
        for off in it.offerings:
            off.price *= cheaper_dense
    pools = [make_nodepool("a-first"), make_nodepool("b-dense")]
    return pools, {"a-first": cat_a, "b-dense": cat_b}


def _cost(results, its):
    """$-cost proxy of a Results: cheapest available offering among each
    claim's instance-type options."""
    total = 0.0
    for c in results.new_node_claims:
        total += min(
            off.price
            for it in c.instance_type_options
            for off in it.offerings
            if off.available
        )
    return total


def _pods(n, cpu=1.0):
    return [make_pod(cpu=cpu, memory_gib=1.0, name=f"p{i}")
            for i in range(n)]


# ---------------------------------------------------------------------------
# the headline: relax strictly beats FFD where template choice matters
# ---------------------------------------------------------------------------


def test_relax_strictly_beats_ffd_on_two_pool_problem():
    pools, its = two_pool_world()
    pods = _pods(64)

    ffd = DeviceScheduler(copy.deepcopy(pools), its, max_slots=256)
    res_f = ffd.solve(copy.deepcopy(pods))
    assert res_f.all_pods_scheduled()

    before = _rejections()
    rx = DeviceScheduler(copy.deepcopy(pools), its, max_slots=256,
                         solver_mode="relax")
    res_r = rx.solve(copy.deepcopy(pods))
    assert _rejections() == before, "relax result tripped the verifier"
    assert res_r.all_pods_scheduled()

    assert res_r.node_count() < res_f.node_count(), (
        f"relax={res_r.node_count()} ffd={res_f.node_count()}"
    )
    assert _cost(res_r, its) < _cost(res_f, its)
    assert rx.last_phase_stats["relax"]["outcome"] == "won"
    assert rx.last_phase_stats["solver_mode"] == "relax"


def test_relax_verdict_cache_warm_solves_dispatch_once():
    pools, its = two_pool_world()
    pods = _pods(48)
    rx = DeviceScheduler(pools, its, max_slots=256, solver_mode="relax")
    cold = rx.solve(copy.deepcopy(pods))
    cold_nodes = cold.node_count()
    # warm until the adaptive slot axis settles, then the verdict must hit
    rx.solve(copy.deepcopy(pods))
    warm = rx.solve(copy.deepcopy(pods))
    assert warm.node_count() == cold_nodes
    assert rx.last_phase_stats["relax"]["outcome"] == "cached_won"
    assert rx.last_phase_stats["relax"]["cached"] is True


def test_relax_noop_on_single_template_matches_ffd_exactly():
    """One nodepool -> one template -> rounding cannot move anything: the
    relax solve must serve the byte-same packing as ffd mode and record
    the short-circuit."""
    catalog = build_catalog(cpu_grid=[2, 4, 8], mem_factors=[4],
                            oses=["linux"], arches=["amd64"])
    pools = [make_nodepool()]
    its = {"default": catalog}
    pods = _pods(40)

    res_f = DeviceScheduler(copy.deepcopy(pools), its,
                            max_slots=128).solve(copy.deepcopy(pods))
    rx = DeviceScheduler(copy.deepcopy(pools), its, max_slots=128,
                         solver_mode="relax")
    res_r = rx.solve(copy.deepcopy(pods))
    assert res_r.node_count() == res_f.node_count()
    assert set(res_r.pod_errors) == set(res_f.pod_errors)
    assert rx.last_phase_stats["relax"]["outcome"] == "noop"


# ---------------------------------------------------------------------------
# anytime contract
# ---------------------------------------------------------------------------


def test_relax_deadline_serves_the_ffd_answer():
    """A spent budget must serve the FFD packing — inside budget, not
    after finishing the optimizer anyway."""
    pools, its = two_pool_world()
    pods = _pods(48)

    res_f = DeviceScheduler(copy.deepcopy(pools), its,
                            max_slots=256).solve(copy.deepcopy(pods))
    rx = DeviceScheduler(copy.deepcopy(pools), its, max_slots=256,
                         solver_mode="relax", relax_budget_s=0.0)
    res_r = rx.solve(copy.deepcopy(pods))
    assert rx.last_phase_stats["relax"]["outcome"] == "deadline"
    # the anytime answer IS the FFD answer
    assert res_r.node_count() == res_f.node_count()
    assert set(res_r.pod_errors) == set(res_f.pod_errors)
    # and a roomy budget on the same scheduler improves it (the expired
    # round cached no verdict — the optimizer re-runs)
    rx.relax_budget_s = None
    res_r2 = rx.solve(copy.deepcopy(pods))
    assert res_r2.node_count() < res_f.node_count()


# ---------------------------------------------------------------------------
# fuzz battery: the unmodified verifier accepts every relax result
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(14))
def test_relax_passes_verifier_on_every_fuzz_seed(seed):
    """Every existing fuzz seed (mixed topology/taints/selectors/volumes/
    existing nodes), solved in relax mode with verification ON: the
    rejection counter must not move, and pod conservation must hold."""
    pods, existing, pools, its = fuzz_scenario(seed)
    before = _rejections()
    rx = DeviceScheduler(copy.deepcopy(pools), its,
                         existing_nodes=copy.deepcopy(existing),
                         max_slots=128, solver_mode="relax")
    rp = copy.deepcopy(pods)
    res = rx.solve(rp)
    assert _rejections() == before, (
        "verifier false-positive on a relax-mode result"
    )
    placed = sum(len(c.pods) for c in res.new_node_claims) + sum(
        len(s.pods) for s in res.existing_nodes
    )
    assert placed == len(pods) - len(res.pod_errors)
    # independent re-check (belt and braces beyond the counter)
    violations = ResultVerifier(
        pools, its, existing_nodes=copy.deepcopy(existing)
    ).verify(res, rp)
    assert not violations, [str(v) for v in violations]


def _topology_pods(n):
    pods = []
    for i in range(n):
        kind = i % 3
        if kind == 0:
            pods.append(make_pod(cpu=1.0, name=f"t{i}"))
        elif kind == 1:
            pods.append(make_pod(
                cpu=1.0, name=f"t{i}", labels={"app": f"sz-{i % 2}"},
                spread_zone=True,
            ))
        else:
            pods.append(make_pod(
                cpu=1.0, name=f"t{i}", labels={"app": f"sh-{i % 2}"},
                spread_hostname=True,
            ))
    return pods


def test_relax_passes_verifier_on_topology_problems():
    pools, its = two_pool_world()
    pods = _topology_pods(36)
    before = _rejections()
    rx = DeviceScheduler(copy.deepcopy(pools), its, max_slots=256,
                         solver_mode="relax")
    rp = copy.deepcopy(pods)
    res = rx.solve(rp)
    assert _rejections() == before
    assert res.all_pods_scheduled(), list(res.pod_errors.items())[:3]
    violations = ResultVerifier(pools, its).verify(res, rp)
    assert not violations, [str(v) for v in violations]


def _gang_tier_pods(n_gangs=3, gang_size=4, n_plain=12):
    pods = []
    for g in range(n_gangs):
        for j in range(gang_size):
            pods.append(make_pod(
                cpu=1.0, memory_gib=1.0, name=f"g{g}-{j}",
            ))
            pods[-1].metadata.annotations = {
                GANG_ANNOTATION: f"gang-{g}",
                GANG_SAME_TEMPLATE_ANNOTATION: "true",
            }
    for i in range(n_plain):
        p = make_pod(cpu=1.0, name=f"c{i}")
        p.priority = 1_000_000 * (1 + i % 2)  # two positive tiers
        pods.append(p)
    pods.extend(_pods(8))
    return pods


def test_relax_passes_verifier_on_tier_and_gang_problems():
    """Tiers and same-template gangs are CONSTRAINTS of the relaxation:
    the relax result must verify clean (gang atomicity + co-location
    re-derived from annotations by the unmodified verifier) and every
    gang must land whole on one template."""
    pools, its = two_pool_world()
    pods = _gang_tier_pods()
    before = _rejections()
    rx = DeviceScheduler(copy.deepcopy(pools), its, max_slots=256,
                         solver_mode="relax")
    rp = copy.deepcopy(pods)
    res = rx.solve(rp)
    assert _rejections() == before
    assert res.all_pods_scheduled(), list(res.pod_errors.items())[:3]
    violations = ResultVerifier(pools, its).verify(res, rp)
    assert not violations, [str(v) for v in violations]
    # same-template co-location holds through the relax override
    pool_of_pod = {
        p.uid: c.template.nodepool_name
        for c in res.new_node_claims
        for p in c.pods
    }
    by_gang = {}
    for p in rp:
        ann = p.metadata.annotations or {}
        if ann.get(GANG_ANNOTATION):
            by_gang.setdefault(ann[GANG_ANNOTATION], set()).add(
                pool_of_pod.get(p.uid)
            )
    assert by_gang and all(
        len(pools_used) == 1 for pools_used in by_gang.values()
    ), by_gang


# ---------------------------------------------------------------------------
# mode isolation: shape keys, buckets, mixed-mode batches
# ---------------------------------------------------------------------------


def test_kernel_request_shape_key_carries_mode():
    import jax.numpy as jnp

    def req(mode):
        return _KernelRequest(
            init_state=jnp.zeros((4,)), steps=jnp.zeros((4,)),
            statics=jnp.zeros((4,)), level_iters=8,
            step_class=jnp.zeros((4,), dtype=jnp.int32), num_classes=8,
            devices=1, n_slots=4, mode=mode,
        )

    assert req("ffd").shape_key() != req("relax").shape_key()
    assert req("ffd").shape_key() == req("ffd").shape_key()


def test_problem_bucket_carries_solver_mode():
    pools, its = two_pool_world()
    pods = _pods(8)

    def bucket(mode):
        body = codec.encode_solve_request(
            pools, its, [], [], pods, solver_mode=mode
        )
        return codec.problem_bucket(codec._json_header(body))

    assert bucket("ffd") != bucket("relax")
    assert bucket("ffd") == bucket("ffd")


def test_mixed_mode_solve_batch_never_shares_a_vmapped_dispatch():
    """One ffd and one relax problem of the SAME compile shape under one
    solve_batch window: their solve dispatches must run solo (zero
    batched dispatches) yet both complete — the shape_key mode component
    in action. The same pair in a single mode IS coalesced (positive
    control, so this test can't pass vacuously)."""
    pools, its = two_pool_world()
    pods = _pods(32)

    def sched(mode):
        return DeviceScheduler(copy.deepcopy(pools), its, max_slots=256,
                               solver_mode=mode)

    # positive control: same mode, same shape -> coalesces
    outcomes, stats = solve_batch([
        (sched("ffd"), copy.deepcopy(pods)),
        (sched("ffd"), copy.deepcopy(pods)),
    ])
    assert all(st == "ok" for st, _ in outcomes)
    assert stats["batched_dispatches"] >= 1, stats

    # mixed modes: identical tensor shapes, yet nothing coalesces
    outcomes, stats = solve_batch([
        (sched("ffd"), copy.deepcopy(pods)),
        (sched("relax"), copy.deepcopy(pods)),
    ])
    assert all(st == "ok" for st, _ in outcomes)
    assert stats["batched_dispatches"] == 0, stats
    res_f, res_r = outcomes[0][1], outcomes[1][1]
    assert res_r.node_count() < res_f.node_count()


def test_two_relax_problems_coalesce_their_dispatches():
    """Two relax problems in one window DO coalesce — including the
    relax_choose assignment dispatch (the batched twin)."""
    pools, its = two_pool_world()
    pods = _pods(32)
    outcomes, stats = solve_batch([
        (DeviceScheduler(copy.deepcopy(pools), its, max_slots=256,
                         solver_mode="relax"), copy.deepcopy(pods)),
        (DeviceScheduler(copy.deepcopy(pools), its, max_slots=256,
                         solver_mode="relax"), copy.deepcopy(pods)),
    ])
    assert all(st == "ok" for st, _ in outcomes)
    assert stats["batched_dispatches"] >= 2, stats  # solve + relax rounds
    assert outcomes[0][1].node_count() == outcomes[1][1].node_count()


# ---------------------------------------------------------------------------
# the wire: field, header, flags
# ---------------------------------------------------------------------------


def test_codec_rejects_unknown_mode_both_sides():
    pools, its = two_pool_world()
    with pytest.raises(ValueError, match="unknown solver mode"):
        codec.encode_solve_request(pools, its, [], [], [],
                                   solver_mode="zzz")
    body = codec.encode_solve_request(pools, its, [], [], [])
    h = codec._json_header(body)
    h["solver_mode"] = "zzz"
    with pytest.raises(ValueError, match="unknown solver mode"):
        codec.decode_solve_request(codec._json_payload(h))


def test_solve_wire_version_bumped_for_mode_field():
    # v4 introduced solver_mode; v5 the delta wire (segmentstore) — the
    # mode field's skew protection carries forward unchanged
    assert codec.SOLVE_WIRE_VERSION >= 4
    body = codec.encode_solve_request(*two_pool_world(), [], [], [])
    h = codec._json_header(body)
    h["version"] = 3
    with pytest.raises(ValueError, match="unsupported solve wire version"):
        codec.decode_solve_request(codec._json_payload(h))


def test_daemon_header_overrides_wire_mode():
    """X-Solver-Mode wins over the wire field; the override lands a
    DIFFERENT scheduler-cache fingerprint, so the two modes never share
    one (single-solve-stateful, mode-bound) DeviceScheduler."""
    from karpenter_core_tpu.solver.service import SolverDaemon

    pools, its = two_pool_world()
    pods = _pods(48)
    body = codec.encode_solve_request(pools, its, [], [], pods,
                                      solver_mode="ffd")
    d = SolverDaemon()
    out_f, _ = d.solve(body)
    claims_f = len(codec.decode_solve_results(out_f)["claims"])
    out_r, _ = d.solve(body, solver_mode="relax")
    claims_r = len(codec.decode_solve_results(out_r)["claims"])
    assert claims_r < claims_f, (claims_r, claims_f)


def test_daemon_default_mode_applies_to_modeless_wire():
    """A request whose wire names no mode (back-compat / foreign client)
    gets the daemon's --solver-mode default."""
    from karpenter_core_tpu.solver.service import SolverDaemon

    pools, its = two_pool_world()
    pods = _pods(48)
    body = codec.encode_solve_request(pools, its, [], [], pods)
    h = codec._json_header(body)
    h.pop("solver_mode")
    modeless = codec._json_payload(h)
    assert codec.decode_solve_request(modeless)["solver_mode"] == ""

    claims = {}
    for mode in ("ffd", "relax"):
        d = SolverDaemon(default_mode=mode)
        out, _ = d.solve(modeless)
        claims[mode] = len(codec.decode_solve_results(out)["claims"])
    assert claims["relax"] < claims["ffd"], claims


def test_supervisor_spawn_argv_carries_solver_mode():
    from karpenter_core_tpu.solver.supervisor import default_command

    cmd = default_command(0, solve_mode="relax")
    i = cmd.index("--solver-mode")
    assert cmd[i + 1] == "relax"
    assert "--solver-mode" not in default_command(0)


def test_operator_solver_backend_flag():
    from karpenter_core_tpu.operator import Options

    opts = Options.parse(["--solver-backend", "relax"])
    assert opts.solver_backend == "relax"
    assert Options.parse([]).solver_backend == "ffd"
    with pytest.raises(ValueError, match="unknown solver backend"):
        Options.parse(["--solver-backend", "zzz"])


def test_device_scheduler_rejects_unknown_mode():
    pools, its = two_pool_world()
    with pytest.raises(ValueError, match="unknown solver mode"):
        DeviceScheduler(pools, its, solver_mode="zzz")
