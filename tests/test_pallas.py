"""pallas (ISSUE 18): the hand-fused FFD hot-core kernel behind
``--kernel=xla|pallas``.

The correctness contract is BYTE PARITY: the Pallas backend
(ops/pallas_ffd.py, one fused kernel invocation per class step, slot
state resident in VMEM) must produce the byte-identical result wire of
the classic XLA backend on every problem family — the PR 9 battery
pattern, applied to the kernel seam:

* every fuzz seed (the full mixed-constraint scenario generator), with
  the ResultVerifier rejection counter pinned unmoved — verification
  runs inside the pallas solves, so a parity break would first surface
  as a silent fleet-wide greedy degrade;
* topology, gang/preemption, and relax-mode problems — the gang,
  preempt, and relax dispatches stay on the XLA kernels under either
  backend, so these pin that the fused FFD scan composes with them
  without perturbing a placement;
* batched: a mixed-backend ``solve_batch`` must never coalesce xla and
  pallas problems into one vmapped dispatch (``_KernelRequest.shape_key``
  backend component), while each member still matches its solo twin;
* multi-device: the forced 8-device virtual mesh, where the pallas path
  commits its planes replicated (parallel/mesh.pallas_slot_shardings —
  the pallas_call boundary is opaque to GSPMD) yet must reproduce the
  slot-sharded XLA wire byte-for-byte;
* incremental warm-replay: a pallas daemon's warm replay is
  byte-identical to its own fresh solve AND to an xla daemon's answer.

Plus the flag surface (operator --kernel / KARPENTER_SOLVER_KERNEL /
solverd --kernel / supervisor argv): unknown values reject loudly at
every layer, the xla default stays untouched.
"""
import copy

import pytest

from tests.helpers import make_nodepool, make_pod
from tests.test_fuzz_parity import fuzz_scenario
from tests.test_gangsched import (
    SYSTEM_CLUSTER_CRITICAL,
    full_node,
    gang_pod,
    small_catalog,
)
from tests.test_incremental import _encode, _fp, _strip
from tests.test_relaxsolve import two_pool_world

from karpenter_core_tpu.cloudprovider.kwok import build_catalog
from karpenter_core_tpu.metrics import wiring as m
from karpenter_core_tpu.models.provisioner import (
    DeviceScheduler,
    solve_batch,
)
from karpenter_core_tpu.solver import codec, service


def _wire(results):
    # solve_seconds is timing, not packing: pin it so wire comparison is
    # exact over the decision content
    return codec.encode_solve_results(results, 0.0)


def _rejections():
    return dict(m.SOLVER_RESULT_REJECTED.values)


def _solve_both(pools, its, pods, existing=(), max_slots=128, devices=1,
                solver_mode="ffd"):
    """The same problem under both kernel backends (verification ON, the
    production default); returns (wire_xla, wire_pallas, sched_pallas)."""
    x = DeviceScheduler(
        copy.deepcopy(pools), its,
        existing_nodes=copy.deepcopy(list(existing)),
        max_slots=max_slots, devices=devices, solver_mode=solver_mode,
    )
    rx = x.solve(copy.deepcopy(pods))
    p = DeviceScheduler(
        copy.deepcopy(pools), its,
        existing_nodes=copy.deepcopy(list(existing)),
        max_slots=max_slots, devices=devices, solver_mode=solver_mode,
        kernel_backend="pallas",
    )
    rp = p.solve(copy.deepcopy(pods))
    return _wire(rx), _wire(rp), p


# ---------------------------------------------------------------------------
# the headline: byte-identical wire across the full fuzz battery
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(14))
def test_fuzz_seed_wire_parity(seed):
    pods, existing, pools, its = fuzz_scenario(seed)
    before = _rejections()
    wx, wp, sched = _solve_both(pools, its, pods, existing)
    assert wp == wx, f"pallas wire diverged from xla on seed {seed}"
    # the trust anchor never moved: both backends' results verified clean
    assert _rejections() == before, (
        "verifier rejection counter moved during the parity battery"
    )
    # the phase stats carry which backend answered the scan dispatches
    assert sched.last_phase_stats["kernel_backend"] == "pallas"


def test_topology_wire_parity():
    """Zone + hostname spread exercises the device-topology fetch planes
    (valmask/defines/zcount ride the post-scan window) on both backends."""
    pools = [make_nodepool()]
    its = {"default": build_catalog()[:16]}
    pods = []
    for i in range(24):
        if i % 3 == 0:
            pods.append(make_pod(cpu=0.25, name=f"t{i}",
                                 spread_hostname=True, labels={"app": "t"}))
        elif i % 3 == 1:
            pods.append(make_pod(cpu=0.5, name=f"t{i}", spread_zone=True))
        else:
            pods.append(make_pod(cpu=0.25 * (1 + i % 4), name=f"t{i}"))
    wx, wp, _ = _solve_both(pools, its, pods, max_slots=64)
    assert wp == wx


def test_gang_preempt_wire_parity():
    """Gang atomicity + the preemption pass (both stay on XLA kernels)
    over a pallas-answered FFD scan: the composed wire must not move."""
    pools = [make_nodepool()]
    its = {"default": small_catalog()}
    # fresh nodes top out at 2 cpu: the critical pod can only land through
    # preemption on the existing node's evictable population, while the
    # gang places atomically on fresh nodes — both passes in one solve
    existing = [full_node()]
    crit = make_pod(cpu=8.0, memory_gib=1.0, name="critical")
    crit.priority = SYSTEM_CLUSTER_CRITICAL
    pods = [crit] + [
        gang_pod(f"g{i}", "job-g", cpu=1.0) for i in range(4)
    ] + [make_pod(cpu=1.0, name=f"f{i}") for i in range(4)]
    before = _rejections()
    wx, wp, _ = _solve_both(pools, its, pods, existing, max_slots=64)
    assert wp == wx
    assert _rejections() == before


def test_relax_wire_parity():
    """relax mode's FFD baseline and candidate scans ride the selected
    kernel backend (the relax_choose assignment dispatch stays XLA);
    the adopted winner must be identical under both."""
    pools, its = two_pool_world()
    pods = [make_pod(cpu=1.0, memory_gib=1.0, name=f"p{i}")
            for i in range(48)]
    wx, wp, sched = _solve_both(pools, its, pods, max_slots=256,
                                solver_mode="relax")
    assert wp == wx
    assert sched.last_phase_stats["solver_mode"] == "relax"
    assert sched.last_phase_stats["kernel_backend"] == "pallas"


# ---------------------------------------------------------------------------
# batched: mixed-backend fleets never share a vmapped dispatch
# ---------------------------------------------------------------------------


def _batch_problem(name, n_pods=20, cpu_step=0.25):
    pool = make_nodepool(name=name)
    pods = [
        make_pod(cpu=cpu_step * (1 + i % 4), memory_gib=0.5 * (1 + i % 3),
                 name=f"{name}-{i}")
        for i in range(n_pods)
    ]
    return pool, pods


def test_mixed_backend_batch_never_coalesces():
    """Two xla + two pallas problems of identical compile shapes: the
    shape_key backend component must split them into TWO vmapped
    dispatches (never one of four), and every member's wire must match
    its solo twin under its own backend."""
    specs = [("bxa", "xla"), ("bxb", "xla"), ("bpa", "pallas"),
             ("bpb", "pallas")]
    probs = {n: _batch_problem(n) for n, _k in specs}
    solo = {}
    for n, kernel in specs:
        pool, pods = probs[n]
        sched = DeviceScheduler(
            [pool], {n: list(build_catalog()[:16])}, max_slots=64,
            kernel_backend=kernel,
        )
        solo[n] = _wire(sched.solve(copy.deepcopy(pods)))

    entries = [
        (
            DeviceScheduler(
                [probs[n][0]], {n: list(build_catalog()[:16])},
                max_slots=64, kernel_backend=kernel,
            ),
            copy.deepcopy(probs[n][1]),
        )
        for n, kernel in specs
    ]
    outcomes, stats = solve_batch(entries)
    # one batched dispatch per backend group — the backends split even at
    # byte-identical tensor shapes
    assert stats["batched_dispatches"] == 2, stats
    assert stats["batched_problems"] == 4, stats
    for (n, _k), (status, res) in zip(specs, outcomes):
        assert status == "ok", res
        assert _wire(res) == solo[n]
    # and the backends agree with EACH OTHER: same-shaped problems under
    # different names, so the xla pair's wires equal the pallas pair's
    # modulo the problem name embedded in the claims — checked upstream
    # by every solo test; here the split itself is the contract


# ---------------------------------------------------------------------------
# multi-device: replicated pallas planes vs the slot-sharded xla mesh
# ---------------------------------------------------------------------------


def test_multidevice_wire_parity():
    """On the conftest-forced 8-device virtual mesh the pallas path
    commits its planes replicated (pallas_slot_shardings) while xla
    shards the slot axis — the wires must still match each other AND the
    single-device answer (the slot-axis-invariance property)."""
    pools = [make_nodepool()]
    its = {"default": build_catalog()[:16]}
    pods = [
        make_pod(cpu=0.25 * (1 + i % 4), memory_gib=0.5 * (1 + i % 3),
                 name=f"m{i}")
        for i in range(26)
    ]
    wx1, wp1, _ = _solve_both(pools, its, pods, max_slots=64, devices=1)
    wx8, wp8, _ = _solve_both(pools, its, pods, max_slots=64, devices=8)
    assert wp1 == wx1
    assert wp8 == wx8
    assert wp8 == wx1


# ---------------------------------------------------------------------------
# incremental warm-replay: the ledger is backend-blind because the wire is
# ---------------------------------------------------------------------------


def test_incremental_warm_replay_parity():
    pods, existing, pools, its = fuzz_scenario(3)
    body = _encode(pools, its, existing, [], pods, max_slots=128)
    inc = _encode(
        pools, its, existing, [], pods, max_slots=128,
        prev_fingerprint=_fp(body),
    )
    dx = service.SolverDaemon()
    outx_full, _ = dx.solve(inc)
    outx_warm, _ = dx.solve(inc)
    assert dx.incremental.last["outcome"] == "warm"

    dp = service.SolverDaemon(kernel="pallas")
    outp_full, _ = dp.solve(inc)
    assert dp.incremental.last["outcome"] == "full"  # own ledger, cold
    outp_warm, _ = dp.solve(inc)
    assert dp.incremental.last["outcome"] == "warm"
    # warm == fresh within a backend, and both backends agree on the wire
    assert _strip(outp_warm) == _strip(outp_full)
    assert _strip(outp_full) == _strip(outx_full)
    assert _strip(outx_warm) == _strip(outx_full)


# ---------------------------------------------------------------------------
# the flag surface: reject loudly everywhere, xla default untouched
# ---------------------------------------------------------------------------


class TestKernelFlagSurface:
    def test_scheduler_rejects_unknown_kernel(self):
        with pytest.raises(ValueError, match="kernel"):
            DeviceScheduler(
                [make_nodepool()], {"default": build_catalog()[:4]},
                kernel_backend="mosaic",
            )

    def test_scheduler_default_is_xla(self):
        sched = DeviceScheduler(
            [make_nodepool()], {"default": build_catalog()[:4]}
        )
        assert sched.kernel_backend == "xla"

    def test_daemon_rejects_unknown_kernel(self):
        with pytest.raises(ValueError, match="kernel"):
            service.SolverDaemon(kernel="cuda")

    def test_daemon_health_reports_kernel(self):
        assert service.SolverDaemon().health()["kernel"] == "xla"
        assert (
            service.SolverDaemon(kernel="pallas").health()["kernel"]
            == "pallas"
        )

    def test_options_parse_kernel_flag_and_env(self):
        from karpenter_core_tpu.operator import Options

        assert Options.parse([], env={}).solver_kernel == "xla"
        assert (
            Options.parse(["--kernel", "pallas"], env={}).solver_kernel
            == "pallas"
        )
        assert (
            Options.parse(
                [], env={"KARPENTER_SOLVER_KERNEL": "pallas"}
            ).solver_kernel
            == "pallas"
        )
        # explicit flag beats the env var (the resolution order contract)
        assert (
            Options.parse(
                ["--kernel", "xla"],
                env={"KARPENTER_SOLVER_KERNEL": "pallas"},
            ).solver_kernel
            == "xla"
        )

    def test_options_parse_rejects_unknown_kernel(self):
        from karpenter_core_tpu.operator import Options

        with pytest.raises(ValueError, match="kernel"):
            Options.parse(["--kernel", "mlir"], env={})
        with pytest.raises(ValueError, match="kernel"):
            Options.parse([], env={"KARPENTER_SOLVER_KERNEL": "triton"})

    def test_supervisor_argv_carries_non_default_kernel(self):
        from karpenter_core_tpu.solver.supervisor import default_command

        cmd = default_command(0, kernel="pallas")
        i = cmd.index("--kernel")
        assert cmd[i + 1] == "pallas"
        # the default never rides the argv: a respawned child re-reads
        # the daemon default instead of a frozen flag
        assert "--kernel" not in default_command(0)
        assert "--kernel" not in default_command(0, kernel=None)

    def test_shape_key_splits_on_backend(self):
        """Two requests identical in every tensor shape but the backend
        field must never share a vmapped dispatch."""
        pods, existing, pools, its = fuzz_scenario(0)
        x = DeviceScheduler(copy.deepcopy(pools), its,
                            existing_nodes=copy.deepcopy(existing),
                            max_slots=128)
        p = DeviceScheduler(copy.deepcopy(pools), its,
                            existing_nodes=copy.deepcopy(existing),
                            max_slots=128, kernel_backend="pallas")
        gx = x._solve_gen(copy.deepcopy(pods))
        gp = p._solve_gen(copy.deepcopy(pods))
        rx = next(gx)
        rp = next(gp)
        try:
            assert rx.backend == "xla" and rp.backend == "pallas"
            kx, kp = rx.shape_key(), rp.shape_key()
            assert kx != kp
            # and ONLY the backend component differs — the tensors bucket
            # identically, so coalescing would have merged them but for it
            assert [a for a, b in zip(kx, kp) if a != b] == ["xla"]
        finally:
            gx.close()
            gp.close()
