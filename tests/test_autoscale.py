"""fleetscale (ISSUE 17): the TierAutoscaler policy + dynamic membership.

Three contract layers, pinned separately:

* the POLICY — hysteresis streaks with a dead band between the
  thresholds, per-direction cooldowns, hard min/max bounds, respawn-storm
  scale-up suppression, victim selection that never drains a spilling or
  already-draining member, and the brownout ladder that climbs 1->2->3
  only at max size and descends fully before any scale-down;
* the ROUTER's dynamic membership — rendezvous hashing over stable
  member ids, so a resize remaps ONLY the departing/arriving member's
  keys, a stale index raises the typed ``UnknownMemberError``, and a
  lineage whose affinity winner remaps clears ``prev_fingerprint``
  proactively (a planned full solve, not daemon amnesia);
* the SpawnedTier ADAPTER — statz/loads fold into MemberSignal rows, a
  dead member reads as draining (never a victim), and sheds bump
  pressure over budget whatever the percentiles say.
"""
import copy

import pytest

from tests.helpers import make_nodepool, make_pod

from karpenter_core_tpu.cloudprovider.fake import fake_instance_types
from karpenter_core_tpu.metrics import wiring as m
from karpenter_core_tpu.solver import remote, service
from karpenter_core_tpu.solver.autoscale import (
    BROWNOUT_MAX_RUNG,
    MemberSignal,
    SpawnedTier,
    TierAutoscaler,
    TierSignals,
)
from karpenter_core_tpu.solver.fleet import UnknownMemberError


class FakeTier:
    """Policy-test adapter: observable load is set directly, actuations
    are recorded and applied to the member list."""

    def __init__(self, n=1):
        self.members = [MemberSignal(member=str(i)) for i in range(n)]
        self.pressure = 0.0
        self.storm = False
        self.rung = 0
        self.calls = []

    def observe(self):
        return TierSignals(
            members=[copy.deepcopy(ms) for ms in self.members],
            pressure=self.pressure,
            storm=self.storm,
        )

    def scale_up(self):
        self.calls.append(("up", len(self.members) + 1))
        self.members.append(MemberSignal(member=str(len(self.members))))

    def scale_down(self, index):
        self.calls.append(("down", index))
        self.members.pop(index)

    def set_rung(self, rung):
        self.calls.append(("rung", rung))
        self.rung = rung


def _autoscaler(tier, mn, mx, now, **kw):
    kw.setdefault("up_stable", 2)
    kw.setdefault("down_stable", 2)
    kw.setdefault("up_cooldown_s", 0.0)
    kw.setdefault("down_cooldown_s", 0.0)
    kw.setdefault("rung_up_stable", 1)
    kw.setdefault("rung_down_stable", 1)
    return TierAutoscaler(tier, mn, mx, time_fn=lambda: now[0], **kw)


class TestPolicy:
    def test_ctor_rejects_bad_bounds(self):
        with pytest.raises(ValueError):
            TierAutoscaler(FakeTier(), 0, 2)
        with pytest.raises(ValueError):
            TierAutoscaler(FakeTier(), 3, 2)
        with pytest.raises(ValueError):
            TierAutoscaler(
                FakeTier(), 1, 2, up_pressure=1.0, down_pressure=1.0
            )

    def test_up_requires_a_streak_then_scales_one(self):
        tier, now = FakeTier(1), [0.0]
        auto = _autoscaler(tier, 1, 4, now)
        tier.pressure = 2.0
        assert auto.step() == []  # streak 1 of 2
        before = m.SOLVER_FLEET_SCALE.value({"direction": "up"})
        actions = auto.step()
        assert [a for a, _ in actions] == ["up"]
        assert len(tier.members) == 2
        assert m.SOLVER_FLEET_SCALE.value({"direction": "up"}) == before + 1
        assert m.SOLVER_FLEET_SIZE.value({}) == 2.0

    def test_dead_band_resets_both_streaks(self):
        tier, now = FakeTier(1), [0.0]
        auto = _autoscaler(tier, 1, 4, now)
        tier.pressure = 2.0
        auto.step()  # up streak 1
        tier.pressure = 0.5  # between down (0.3) and up (1.0): the band
        auto.step()
        tier.pressure = 2.0
        assert auto.step() == []  # streak restarted at 1
        assert [a for a, _ in auto.step()] == ["up"]

    def test_up_cooldown_spaces_consecutive_grows(self):
        tier, now = FakeTier(1), [0.0]
        auto = _autoscaler(
            tier, 1, 4, now, up_stable=1, up_cooldown_s=30.0
        )
        tier.pressure = 2.0
        assert [a for a, _ in auto.step()] == ["up"]
        now[0] = 10.0
        assert auto.step() == []  # hot: inside the cooldown
        now[0] = 31.0
        assert [a for a, _ in auto.step()] == ["up"]

    def test_down_requires_streak_and_respects_min(self):
        tier, now = FakeTier(3), [0.0]
        auto = _autoscaler(tier, 2, 4, now)
        tier.pressure = 0.1
        assert auto.step() == []
        actions = auto.step()
        assert actions == [("down", 0)]  # all idle: lowest index wins
        assert len(tier.members) == 2
        # at min: under-pressure forever never goes below the floor
        for _ in range(6):
            now[0] += 1000.0
            assert auto.step() == []
        assert len(tier.members) == 2

    def test_storm_suppresses_scale_up_but_not_the_streak(self):
        tier, now = FakeTier(1), [0.0]
        auto = _autoscaler(tier, 1, 4, now, up_stable=2)
        tier.pressure, tier.storm = 2.0, True
        auto.step()
        actions = auto.step()  # streak satisfied, storm holds it back
        assert actions == [("hold", "respawn storm suppresses scale-up")]
        assert tier.calls == []
        tier.storm = False
        assert [a for a, _ in auto.step()] == ["up"]

    def test_victim_skips_draining_and_spilling_members(self):
        tier, now = FakeTier(4), [0.0]
        tier.members[0].draining = True
        tier.members[1].spilling = 1
        tier.members[2].inflight = 5
        auto = _autoscaler(tier, 1, 4, now, down_stable=1)
        tier.pressure = 0.0
        assert auto.step() == [("down", 3)]  # the only idle retirable

    def test_all_members_busy_holds_instead_of_draining(self):
        tier, now = FakeTier(2), [0.0]
        tier.members[0].draining = True
        tier.members[1].spilling = 2
        auto = _autoscaler(tier, 1, 4, now, down_stable=1)
        tier.pressure = 0.0
        actions = auto.step()
        assert actions == [
            ("hold", "no drainable member (all spilling or draining)")
        ]
        assert len(tier.members) == 2

    def test_rung_ladder_climbs_and_descends_in_order(self):
        tier, now = FakeTier(1), [0.0]
        auto = _autoscaler(tier, 1, 1, now, up_stable=1)
        tier.pressure = 2.0
        rungs = []
        for _ in range(BROWNOUT_MAX_RUNG + 2):
            now[0] += 1.0
            for action, arg in auto.step():
                assert action == "rung_up"
                rungs.append(arg)
        assert rungs == [1, 2, 3]  # capped at BROWNOUT_MAX_RUNG
        tier.pressure = 0.0
        for _ in range(BROWNOUT_MAX_RUNG + 2):
            now[0] += 1.0
            for action, arg in auto.step():
                assert action == "rung_down"
                rungs.append(arg)
        assert rungs == [1, 2, 3, 2, 1, 0]
        assert [c for c in tier.calls if c[0] == "rung"] == [
            ("rung", r) for r in rungs
        ]

    def test_no_rung_below_max_size(self):
        tier, now = FakeTier(1), [0.0]
        auto = _autoscaler(
            tier, 1, 3, now, up_stable=1, up_cooldown_s=10_000.0
        )
        tier.pressure = 2.0
        assert [a for a, _ in auto.step()] == ["up"]
        for _ in range(5):  # cooling down, still below max: no ladder
            now[0] += 1.0
            assert auto.step() == []
        assert auto.rung == 0

    def test_ladder_descends_fully_before_scale_down(self):
        tier, now = FakeTier(2), [0.0]
        auto = _autoscaler(
            tier, 1, 2, now, up_stable=1, down_stable=1
        )
        tier.pressure = 2.0
        now[0] += 1.0
        assert auto.step() == [("rung_up", 1)]  # at max: climb
        tier.pressure = 0.0
        now[0] += 1.0
        assert auto.step() == [("rung_down", 0)]  # rung clears first
        now[0] += 1.0
        assert [a for a, _ in auto.step()] == ["down"]

    def test_decision_log_and_callback_are_stringly_stable(self):
        tier, now = FakeTier(1), [0.0]
        seen = []
        auto = _autoscaler(
            tier, 1, 2, now, up_stable=1,
            on_decision=lambda action, arg: seen.append((action, arg)),
        )
        tier.pressure = 2.0
        now[0] = 1.23456
        auto.step()
        assert auto.decisions == [(1.235, "up", "pressure=2.000 n=1->2")]
        assert seen == [("up", "pressure=2.000 n=1->2")]
        assert all(
            isinstance(t, float) and isinstance(a, str) and isinstance(d, str)
            for t, a, d in auto.decisions
        )


# ---------------------------------------------------------------------------
# the SpawnedTier adapter: statz/loads -> signals
# ---------------------------------------------------------------------------


class _FakeSup:
    def __init__(self, members, storm=False):
        self.members = members
        self.storm = storm

    def respawn_storm(self):
        return self.storm


class _FakeMember:
    def __init__(self, addr, member, up=True):
        self.addr = addr
        self.member = member
        self.up = up

    def alive(self):
        return self.up


class _FakeRouter:
    def __init__(self, loads):
        self.loads = loads

    def member_loads(self):
        return self.loads


class TestSpawnedTierObserve:
    def _tier(self, sup, loads, stats):
        tier = SpawnedTier(sup, [_FakeRouter(loads)], make_client=None)
        tier._statz = lambda addr: stats.get(addr)
        return tier

    def test_rejects_nonpositive_budget(self):
        with pytest.raises(ValueError):
            SpawnedTier(_FakeSup([]), [], None, wait_budget_s=0.0)

    def test_statz_and_loads_fold_into_signals(self):
        sup = _FakeSup(
            [_FakeMember("a:1", "0"), _FakeMember("b:2", "1")]
        )
        stats = {
            "a:1": {
                "tenants": {"t": {"wait_p99_s": 2.5}},
                "sheds": {},
                "depth": 4,
                "draining": False,
            },
            "b:2": {
                "tenants": {},
                "sheds": {},
                "depth": 0,
                "draining": True,
            },
        }
        sig = self._tier(
            sup, {"0": (2, 1), "1": (0, 0)}, stats
        ).observe()
        assert sig.pressure == pytest.approx(2.5)  # worst p99 / 1s budget
        assert not sig.storm
        first = sig.members[0]
        assert (first.member, first.depth, first.inflight, first.spilling) \
            == ("0", 4, 2, 1)
        assert first.wait_p99_s == pytest.approx(2.5)
        assert sig.members[1].draining  # the gateway said so

    def test_dead_member_reads_as_draining(self):
        sup = _FakeSup(
            [_FakeMember("a:1", "0"), _FakeMember("b:2", "1", up=False)],
            storm=True,
        )
        stats = {
            "a:1": {
                "tenants": {}, "sheds": {}, "depth": 0, "draining": False,
            }
        }
        sig = self._tier(sup, {}, stats).observe()
        assert sig.storm
        assert sig.members[1].draining  # respawn in flight: never a victim

    def test_sheds_bump_pressure_over_budget(self):
        sup = _FakeSup([_FakeMember("a:1", "0")])
        stats = {
            "a:1": {
                "tenants": {"t": {"wait_p99_s": 0.01}},
                "sheds": {"t": 3},
                "depth": 8,
                "draining": False,
            }
        }
        sig = self._tier(sup, {}, stats).observe()
        assert sig.pressure >= 1.0  # a shed IS the over-budget signal


# ---------------------------------------------------------------------------
# router dynamic membership: resize remaps only the touched member's keys
# ---------------------------------------------------------------------------


def _fake_members(n):
    return [
        remote.SolverClient(f"127.0.0.1:{9000 + i}", member=str(i))
        for i in range(n)
    ]


def _owners(router, keys):
    return {k: router._ids[router._pick(k)] for k in keys}


class TestRouterDynamicMembership:
    def test_remove_remaps_only_the_retired_members_keys(self):
        router = remote.FleetRouter(_fake_members(4))
        keys = [f"catalog-{i}" for i in range(64)]
        before = _owners(router, keys)
        victim = before[keys[0]]
        router.remove_member(router._ids.index(victim))
        after = _owners(router, keys)
        for k in keys:
            if before[k] == victim:
                assert after[k] != victim
            else:
                assert after[k] == before[k], (
                    "a surviving member lost an affinity key on resize"
                )

    def test_add_gives_the_new_member_only_its_own_wins(self):
        router = remote.FleetRouter(_fake_members(3))
        keys = [f"catalog-{i}" for i in range(64)]
        before = _owners(router, keys)
        idx = router.add_member(
            remote.SolverClient("127.0.0.1:9100", member="new"),
            member_id="new",
        )
        assert idx == 3 and router._ids[3] == "new"
        after = _owners(router, keys)
        for k in keys:
            assert after[k] in (before[k], "new")
        # quarantine stays ONE verdict ledger across the grown fleet
        assert router.members[idx].quarantine is router.quarantine

    def test_member_ids_are_never_reused(self):
        router = remote.FleetRouter(_fake_members(2))
        router.remove_member(1)
        i = router.add_member(remote.SolverClient("127.0.0.1:9101"))
        j = router.add_member(remote.SolverClient("127.0.0.1:9102"))
        assert len({router._ids[0], router._ids[i], router._ids[j]}) == 3

    def test_remove_last_member_refused(self):
        router = remote.FleetRouter(_fake_members(1))
        with pytest.raises(ValueError):
            router.remove_member(0)

    def test_stale_index_raises_typed_lookup_error(self):
        router = remote.FleetRouter(_fake_members(2))
        with pytest.raises(UnknownMemberError) as ei:
            router.remove_member(7)
        assert isinstance(ei.value, LookupError)
        assert ei.value.index == 7 and ei.value.size == 2
        assert ei.value.site == "remove_member"
        with pytest.raises(UnknownMemberError):
            router.set_member_addr(-1, "127.0.0.1:9999")

    def test_lineage_clears_only_when_the_winner_remaps(self):
        router = remote.FleetRouter(_fake_members(4))
        with router._lock:
            router._lineage_key = "catalog-lineage"
        router.prev_fingerprint = "fp-alive"
        winner = router._lineage_winner_locked()
        assert winner is not None
        loser = next(mid for mid in router._ids if mid != winner)
        router.remove_member(router._ids.index(loser))
        # the winner survived: the predecessor reference is still valid
        assert router.prev_fingerprint == "fp-alive"
        router.remove_member(router._ids.index(winner))
        assert router.prev_fingerprint == ""  # planned full, not amnesia


class TestLineageAcrossResize:
    def test_incremental_chain_survives_a_scale_down(self):
        """Satellite regression (ISSUE 17): chain a lineage over a fleet,
        retire its affinity winner, and the next round must be a PLANNED
        full solve — no predecessor named (the miss counter does not
        move), then the survivor warms like a fresh lineage."""
        daemons = [service.SolverDaemon(), service.SolverDaemon()]
        srvs = [service.serve(0, daemon=d) for d in daemons]
        try:
            members = [
                remote.SolverClient(
                    f"127.0.0.1:{s.server_address[1]}",
                    timeout=120,
                    member=str(i),
                )
                for i, s in enumerate(srvs)
            ]
            router = remote.FleetRouter(members)
            pools = [make_nodepool()]
            its = {"default": fake_instance_types(4)}
            pods = [make_pod(cpu=1.0, name=f"rs{i}") for i in range(5)]

            def solve_once():
                return remote.RemoteScheduler(
                    router, copy.deepcopy(pools), its,
                    device_scheduler_opts={"incremental": True},
                ).solve(copy.deepcopy(pods))

            for _ in range(3):
                assert solve_once().all_pods_scheduled()
            assert router.prev_fingerprint
            winner = router._lineage_winner_locked()
            served = next(
                i for i, c in enumerate(members) if len(c.segcache) > 0
            )
            assert router._ids[served] == winner  # affinity pinned it
            router.remove_member(served)
            assert router.prev_fingerprint == ""
            outcomes = dict(m.SOLVER_INCREMENTAL.values)
            assert solve_once().all_pods_scheduled()
            # named no predecessor: neither a miss nor an amnesia event
            assert dict(m.SOLVER_INCREMENTAL.values) == outcomes
            assert router.prev_fingerprint  # a NEW lineage began
            solve_once()  # names the survivor's own entry: full on miss
            solve_once()
            assert daemons[1 - served].incremental.last["outcome"] == "warm"
        finally:
            for s in srvs:
                s.shutdown()
                s.server_close()
