"""Shared test fixtures: pod generators and nodepool builders, modeled on the
reference's test object builders (pkg/test/pods.go:399-438 MakeDiversePodOptions,
scheduling_benchmark_test.go:233-247)."""
from __future__ import annotations

import random
from typing import List, Optional

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.nodepool import NodePool, NodePoolSpec
from karpenter_core_tpu.api.objects import (
    Affinity,
    LabelSelector,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    Toleration,
    TopologySpreadConstraint,
    resource_list,
)

GIB = 2.0**30


def selector_for(labels: dict) -> LabelSelector:
    return LabelSelector(match_labels=tuple(sorted(labels.items())))


def make_pod(
    cpu: float = 0.5,
    memory_gib: float = 1.0,
    name: Optional[str] = None,
    labels: Optional[dict] = None,
    node_selector: Optional[dict] = None,
    zone_in: Optional[List[str]] = None,
    tolerations: Optional[list] = None,
    spread_zone: bool = False,
    spread_hostname: bool = False,
    max_skew: int = 1,
    affinity_to: Optional[dict] = None,
    anti_affinity_to: Optional[dict] = None,
    affinity_key: str = L.LABEL_TOPOLOGY_ZONE,
) -> Pod:
    """Spread constraints self-select on the pod's labels (defaulted to
    app=<spread kind> like the reference's test deployments); affinity_to /
    anti_affinity_to give required pod-(anti-)affinity over affinity_key."""
    node_affinity = None
    if zone_in:
        node_affinity = NodeAffinity(
            required=[
                NodeSelectorTerm(
                    match_expressions=(
                        NodeSelectorRequirement(
                            L.LABEL_TOPOLOGY_ZONE, "In", tuple(zone_in)
                        ),
                    )
                )
            ]
        )
    labels = dict(labels or {})
    constraints = []
    if spread_zone or spread_hostname:
        labels.setdefault("app", "spread")
    if spread_zone:
        constraints.append(
            TopologySpreadConstraint(
                max_skew=max_skew,
                topology_key=L.LABEL_TOPOLOGY_ZONE,
                when_unsatisfiable="DoNotSchedule",
                label_selector=selector_for({"app": labels["app"]}),
            )
        )
    if spread_hostname:
        constraints.append(
            TopologySpreadConstraint(
                max_skew=max_skew,
                topology_key=L.LABEL_HOSTNAME,
                when_unsatisfiable="DoNotSchedule",
                label_selector=selector_for({"app": labels["app"]}),
            )
        )
    pod_affinity = None
    pod_anti_affinity = None
    if affinity_to is not None:
        pod_affinity = PodAffinity(
            required=[
                PodAffinityTerm(
                    topology_key=affinity_key,
                    label_selector=selector_for(affinity_to),
                )
            ]
        )
    if anti_affinity_to is not None:
        pod_anti_affinity = PodAffinity(
            required=[
                PodAffinityTerm(
                    topology_key=affinity_key,
                    label_selector=selector_for(anti_affinity_to),
                )
            ]
        )
    affinity = None
    if node_affinity or pod_affinity or pod_anti_affinity:
        affinity = Affinity(
            node_affinity=node_affinity,
            pod_affinity=pod_affinity,
            pod_anti_affinity=pod_anti_affinity,
        )
    return Pod(
        metadata=ObjectMeta(name=name or f"pod-{ObjectMeta().uid}", labels=labels),
        resource_requests={"cpu": cpu, "memory": memory_gib * GIB},
        node_selector=dict(node_selector or {}),
        affinity=affinity,
        tolerations=list(tolerations or []),
        topology_spread_constraints=constraints,
    )


def make_diverse_pods(n: int, seed: int = 0, with_topology: bool = False) -> List[Pod]:
    """~1/6 each: generic, zonal-affinity, spread variants (benchmark mix)."""
    rng = random.Random(seed)
    pods = []
    for i in range(n):
        kind = rng.randrange(6) if with_topology else rng.randrange(3)
        cpu = rng.choice([0.1, 0.25, 0.5, 1.0, 2.0])
        mem = rng.choice([0.25, 0.5, 1.0, 2.0, 4.0])
        if kind == 0:
            pods.append(make_pod(cpu, mem, name=f"generic-{i}"))
        elif kind == 1:
            pods.append(
                make_pod(cpu, mem, name=f"zonal-{i}", zone_in=["zone-a", "zone-b"])
            )
        elif kind == 2:
            pods.append(
                make_pod(
                    cpu,
                    mem,
                    name=f"selector-{i}",
                    node_selector={L.LABEL_OS: "linux"},
                )
            )
        elif kind == 3:
            pods.append(make_pod(cpu, mem, name=f"spread-z-{i}", spread_zone=True))
        elif kind == 4:
            pods.append(make_pod(cpu, mem, name=f"spread-h-{i}", spread_hostname=True))
        else:
            # self anti-affinity on hostname: one pod per node (the
            # reference benchmark's anti-affinity slice)
            pods.append(
                make_pod(
                    cpu,
                    mem,
                    name=f"anti-{i}",
                    labels={"app": "anti"},
                    anti_affinity_to={"app": "anti"},
                    affinity_key=L.LABEL_HOSTNAME,
                )
            )
    return pods


def make_nodepool(
    name: str = "default",
    requirements: Optional[list] = None,
    taints: Optional[list] = None,
    limits: Optional[dict] = None,
    weight: int = 0,
) -> NodePool:
    np = NodePool(metadata=ObjectMeta(name=name))
    np.spec = NodePoolSpec()
    np.spec.weight = weight
    if requirements:
        np.spec.template.requirements = list(requirements)
    if taints:
        np.spec.template.taints = list(taints)
    if limits:
        from karpenter_core_tpu.api.nodepool import Limits

        np.spec.limits = Limits()
        np.spec.limits.update(limits)
    return np
