"""Ported reference topology/scheduling scenario blocks, on BOTH solvers.

Each scenario re-expresses a named case from the reference's provisioning
suite (pkg/controllers/provisioning/scheduling/topology_test.go, 3,889 LoC,
plus suite_test.go taints cases), prioritized per VERDICT r5 item 6:
spread x affinity interaction, relaxation ordering, ScheduleAnyway x
minDomains, capacity-type/arch spreads, selector-limited spreads, and
daemonset x topology. Every scenario solves through the greedy oracle AND
the device solver; behavioral assertions run on both results.
"""
import copy

import pytest

from tests.helpers import GIB, make_nodepool, make_pod, selector_for
from tests.test_topology import CATALOG, three_zone_pool, zone_counts

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.objects import (
    Affinity,
    LabelSelector,
    LabelSelectorRequirement,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
    Scheduler,
)
from karpenter_core_tpu.models.provisioner import DeviceScheduler

APP = {"app": "ported"}


def spread(key, max_skew=1, when="DoNotSchedule", labels=APP,
           min_domains=None):
    return TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=key,
        when_unsatisfiable=when,
        label_selector=selector_for(labels),
        min_domains=min_domains,
    )


def pod(name, cpu=0.5, labels=APP, constraints=(), affinity=None,
        node_selector=None, tolerations=None):
    return Pod(
        metadata=ObjectMeta(name=name, labels=dict(labels)),
        resource_requests={"cpu": cpu, "memory": 0.25 * GIB},
        topology_spread_constraints=list(constraints),
        affinity=affinity,
        node_selector=dict(node_selector or {}),
        tolerations=list(tolerations or []),
    )


def solve_both(pods, pools=None, daemonsets=None, catalog=None,
               max_slots=128):
    pools = pools or [three_zone_pool()]
    catalog = catalog or CATALOG
    its = {p.name: list(catalog) for p in pools}
    rg = Scheduler(
        copy.deepcopy(pools), {k: list(v) for k, v in its.items()},
        daemonset_pods=copy.deepcopy(list(daemonsets or [])),
    ).solve(copy.deepcopy(pods))
    rd = DeviceScheduler(
        pools, its, daemonset_pods=list(daemonsets or []),
        max_slots=max_slots,
    ).solve(pods)
    return rg, rd


def domain_counts(res, key) -> dict:
    """Pods per committed domain of `key` over new claims + existing."""
    counts = {}
    for claim in res.new_node_claims:
        req = claim.requirements.get(key)
        vals = req.sorted_values()
        if req.complement or len(vals) != 1:
            continue
        counts[vals[0]] = counts.get(vals[0], 0) + len(claim.pods)
    for sim in res.existing_nodes:
        if sim.pods:
            v = sim.node.labels.get(key)
            counts[v] = counts.get(v, 0) + len(sim.pods)
    return counts


def scheduled_count(res) -> int:
    return sum(len(c.pods) for c in res.new_node_claims) + sum(
        len(s.pods) for s in res.existing_nodes
    )


# --------------------------------------------------------------------------
# A. zonal spread + NodePool constraint interaction (topology_test.go:94-252)


class TestZonalSpread:
    def test_balance_across_zones_match_labels(self):
        pods = [pod(f"p{i}", constraints=[spread(L.LABEL_TOPOLOGY_ZONE)])
                for i in range(5)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            assert sorted(
                domain_counts(res, L.LABEL_TOPOLOGY_ZONE).values()
            ) == [1, 2, 2]

    def test_balance_across_zones_match_expressions(self):
        sel = LabelSelector(match_expressions=(
            LabelSelectorRequirement("app", "In", ("ported",)),
        ))
        c = TopologySpreadConstraint(
            max_skew=1, topology_key=L.LABEL_TOPOLOGY_ZONE,
            when_unsatisfiable="DoNotSchedule", label_selector=sel,
        )
        pods = [pod(f"p{i}", constraints=[c]) for i in range(5)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            assert sorted(
                domain_counts(res, L.LABEL_TOPOLOGY_ZONE).values()
            ) == [1, 2, 2]

    def test_respects_nodepool_zonal_constraint(self):
        # pool limited to two zones: spread covers exactly those
        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a", "zone-b"))])
        pods = [pod(f"p{i}", constraints=[spread(L.LABEL_TOPOLOGY_ZONE)])
                for i in range(4)]
        for res in solve_both(pods, pools=[pool]):
            assert res.all_pods_scheduled(), res.pod_errors
            counts = domain_counts(res, L.LABEL_TOPOLOGY_ZONE)
            assert set(counts) == {"zone-a", "zone-b"}
            assert sorted(counts.values()) == [2, 2]

    def test_subset_via_pod_requirements(self):
        # pod node-affinity narrows the spread universe to its zones
        aff = Affinity(node_affinity=NodeAffinity(required=[
            NodeSelectorTerm(match_expressions=(NodeSelectorRequirement(
                L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a", "zone-b")),))
        ]))
        pods = [pod(f"p{i}", constraints=[spread(L.LABEL_TOPOLOGY_ZONE)],
                    affinity=aff) for i in range(4)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            counts = domain_counts(res, L.LABEL_TOPOLOGY_ZONE)
            assert set(counts) == {"zone-a", "zone-b"}

    def test_subset_via_node_selector(self):
        pods = [pod(f"p{i}", constraints=[spread(L.LABEL_TOPOLOGY_ZONE)],
                    node_selector={L.LABEL_TOPOLOGY_ZONE: "zone-b"})
                for i in range(3)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            assert set(domain_counts(res, L.LABEL_TOPOLOGY_ZONE)) == {"zone-b"}

    def test_spread_across_nodepools_union(self):
        # two pools covering disjoint zones: the spread universe is the union
        pa = make_nodepool("pool-a", requirements=[NodeSelectorRequirement(
            L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a",))])
        pb = make_nodepool("pool-b", requirements=[NodeSelectorRequirement(
            L.LABEL_TOPOLOGY_ZONE, "In", ("zone-b",))])
        pods = [pod(f"p{i}", constraints=[spread(L.LABEL_TOPOLOGY_ZONE)])
                for i in range(4)]
        for res in solve_both(pods, pools=[pa, pb]):
            assert res.all_pods_scheduled(), res.pod_errors
            counts = domain_counts(res, L.LABEL_TOPOLOGY_ZONE)
            assert set(counts) == {"zone-a", "zone-b"}
            assert sorted(counts.values()) == [2, 2]

    def test_unknown_topology_key_ignored(self):
        # topology_test.go:59 — an unknown key builds no domains; the pod
        # must still fail DoNotSchedule (no admissible domain) rather than
        # crash, matching the reference's unschedulable outcome
        pods = [pod("p0", constraints=[spread("company.com/made-up")])]
        for res in solve_both(pods):
            assert not res.all_pods_scheduled()


# --------------------------------------------------------------------------
# B. minDomains (topology_test.go:468-530) + ScheduleAnyway interaction


class TestMinDomains:
    def test_unsatisfied_min_domains_caps_each_domain(self):
        # 2 available zones, minDomains 3: min pins at zero so each domain
        # caps at maxSkew — exactly 2 of 3 pods schedule (skew 1,1)
        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a", "zone-b"))])
        pods = [pod(f"p{i}", constraints=[spread(
            L.LABEL_TOPOLOGY_ZONE, min_domains=3)]) for i in range(3)]
        for res in solve_both(pods, pools=[pool]):
            assert scheduled_count(res) == 2
            counts = domain_counts(res, L.LABEL_TOPOLOGY_ZONE)
            assert sorted(counts.values()) == [1, 1]

    def test_satisfied_min_domains_equal(self):
        pods = [pod(f"p{i}", constraints=[spread(
            L.LABEL_TOPOLOGY_ZONE, min_domains=3)]) for i in range(11)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            assert sorted(
                domain_counts(res, L.LABEL_TOPOLOGY_ZONE).values()
            ) == [3, 4, 4]

    def test_satisfied_min_domains_below_available(self):
        pods = [pod(f"p{i}", constraints=[spread(
            L.LABEL_TOPOLOGY_ZONE, min_domains=2)]) for i in range(11)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            assert sorted(
                domain_counts(res, L.LABEL_TOPOLOGY_ZONE).values()
            ) == [3, 4, 4]

    def test_schedule_anyway_with_unsatisfiable_min_domains(self):
        # ScheduleAnyway x minDomains (VERDICT item): the soft constraint
        # relaxes instead of leaving pods pending
        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a",))])
        pods = [pod(f"p{i}", constraints=[spread(
            L.LABEL_TOPOLOGY_ZONE, when="ScheduleAnyway", min_domains=3)])
            for i in range(4)]
        for res in solve_both(pods, pools=[pool]):
            assert res.all_pods_scheduled(), res.pod_errors


# --------------------------------------------------------------------------
# C. hostname spread (topology_test.go:531-638)


class TestHostnameSpread:
    def test_balance_across_nodes(self):
        pods = [pod(f"p{i}", constraints=[spread(L.LABEL_HOSTNAME)])
                for i in range(4)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            per_node = [len(c.pods) for c in res.new_node_claims]
            assert per_node and max(per_node) == 1

    def test_same_hostname_up_to_maxskew(self):
        # skew 4: a single node may take 4 before a second must open
        pods = [pod(f"p{i}", constraints=[spread(L.LABEL_HOSTNAME,
                                                 max_skew=4)])
                for i in range(4)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            assert len(res.new_node_claims) == 1

    def test_multiple_deployments_independent_spreads(self):
        # two apps each spread over hostname: constraints are independent
        pods = []
        for app in ("alpha", "beta"):
            for i in range(2):
                pods.append(pod(
                    f"{app}{i}", labels={"app": app},
                    constraints=[spread(L.LABEL_HOSTNAME,
                                        labels={"app": app})],
                ))
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            for claim in res.new_node_claims:
                apps = [p.metadata.labels["app"] for p in claim.pods]
                assert apps.count("alpha") <= 1
                assert apps.count("beta") <= 1

    def test_combined_hostname_and_zonal(self):
        # topology_test.go:927 — both constraints hold simultaneously
        cs = [spread(L.LABEL_TOPOLOGY_ZONE), spread(L.LABEL_HOSTNAME)]
        pods = [pod(f"p{i}", constraints=list(cs)) for i in range(6)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            zc = domain_counts(res, L.LABEL_TOPOLOGY_ZONE)
            assert max(zc.values()) - min(zc.values()) <= 1
            assert all(len(c.pods) <= 1 for c in res.new_node_claims)


# --------------------------------------------------------------------------
# D. capacity-type / arch spreads (topology_test.go:639-926)


class TestCapacityTypeAndArchSpread:
    def test_balance_across_capacity_types(self):
        pods = [pod(f"p{i}", constraints=[spread(
            L.CAPACITY_TYPE_LABEL_KEY)]) for i in range(4)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            counts = domain_counts(res, L.CAPACITY_TYPE_LABEL_KEY)
            assert sorted(counts.values()) == [2, 2]

    def test_respects_nodepool_capacity_type_constraint(self):
        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            L.CAPACITY_TYPE_LABEL_KEY, "In", (L.CAPACITY_TYPE_SPOT,))])
        pods = [pod(f"p{i}", constraints=[spread(
            L.CAPACITY_TYPE_LABEL_KEY)]) for i in range(4)]
        for res in solve_both(pods, pools=[pool]):
            assert res.all_pods_scheduled(), res.pod_errors
            assert set(domain_counts(res, L.CAPACITY_TYPE_LABEL_KEY)) == {
                L.CAPACITY_TYPE_SPOT
            }

    def test_do_not_schedule_capacity_type_skew_holds(self):
        pods = [pod(f"p{i}", cpu=1.1, constraints=[spread(
            L.CAPACITY_TYPE_LABEL_KEY)]) for i in range(5)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            counts = domain_counts(res, L.CAPACITY_TYPE_LABEL_KEY)
            assert max(counts.values()) - min(counts.values()) <= 1

    def test_schedule_anyway_violates_when_pool_pins_capacity_type(self):
        # topology_test.go:702 — on-demand-only pool, soft spread: all pods
        # land on-demand, skew violated but everything schedules
        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            L.CAPACITY_TYPE_LABEL_KEY, "In", (L.CAPACITY_TYPE_ON_DEMAND,))])
        pods = [pod(f"p{i}", cpu=1.1, constraints=[spread(
            L.CAPACITY_TYPE_LABEL_KEY, when="ScheduleAnyway")])
            for i in range(5)]
        for res in solve_both(pods, pools=[pool]):
            assert res.all_pods_scheduled(), res.pod_errors
            assert set(domain_counts(res, L.CAPACITY_TYPE_LABEL_KEY)) == {
                L.CAPACITY_TYPE_ON_DEMAND
            }

    def test_balance_across_arch(self):
        pods = [pod(f"p{i}", constraints=[spread(L.LABEL_ARCH)])
                for i in range(4)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            counts = domain_counts(res, L.LABEL_ARCH)
            assert sorted(counts.values()) == [2, 2]


# --------------------------------------------------------------------------
# E. spread limited by selectors/affinity (topology_test.go:1207-1392)


class TestSelectorLimitedSpread:
    def test_node_selector_limits_spread_options(self):
        pods = [pod(f"p{i}", constraints=[spread(L.LABEL_TOPOLOGY_ZONE)],
                    node_selector={L.LABEL_TOPOLOGY_ZONE: "zone-a"})
                for i in range(2)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            assert set(domain_counts(res, L.LABEL_TOPOLOGY_ZONE)) == {"zone-a"}

    def test_required_node_affinity_limits_spread(self):
        aff = Affinity(node_affinity=NodeAffinity(required=[
            NodeSelectorTerm(match_expressions=(NodeSelectorRequirement(
                L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a", "zone-c")),))
        ]))
        pods = [pod(f"p{i}", constraints=[spread(L.LABEL_TOPOLOGY_ZONE)],
                    affinity=aff) for i in range(4)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            counts = domain_counts(res, L.LABEL_TOPOLOGY_ZONE)
            assert set(counts) == {"zone-a", "zone-c"}
            assert sorted(counts.values()) == [2, 2]

    def test_preferred_node_affinity_does_not_limit_spread(self):
        # topology_test.go:1299 — preferences don't narrow the domain
        # universe for spreads
        aff = Affinity(node_affinity=NodeAffinity(preferred=[
            PreferredSchedulingTerm(weight=1, preference=NodeSelectorTerm(
                match_expressions=(NodeSelectorRequirement(
                    L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a",)),)))
        ]))
        pods = [pod(f"p{i}", constraints=[spread(L.LABEL_TOPOLOGY_ZONE)],
                    affinity=aff) for i in range(6)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            assert len(domain_counts(res, L.LABEL_TOPOLOGY_ZONE)) == 3

    def test_capacity_type_affinity_limits_spread(self):
        aff = Affinity(node_affinity=NodeAffinity(required=[
            NodeSelectorTerm(match_expressions=(NodeSelectorRequirement(
                L.CAPACITY_TYPE_LABEL_KEY, "In",
                (L.CAPACITY_TYPE_SPOT,)),))
        ]))
        pods = [pod(f"p{i}", constraints=[spread(
            L.CAPACITY_TYPE_LABEL_KEY)], affinity=aff) for i in range(3)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            assert set(domain_counts(res, L.CAPACITY_TYPE_LABEL_KEY)) == {
                L.CAPACITY_TYPE_SPOT
            }


# --------------------------------------------------------------------------
# F. pod affinity (topology_test.go:1393-1696, 2194-2306)


def pod_affinity(labels, key=L.LABEL_HOSTNAME):
    return Affinity(pod_affinity=PodAffinity(required=[
        PodAffinityTerm(topology_key=key, label_selector=selector_for(labels))
    ]))


def pod_anti_affinity(labels, key=L.LABEL_HOSTNAME):
    return Affinity(pod_anti_affinity=PodAffinity(required=[
        PodAffinityTerm(topology_key=key, label_selector=selector_for(labels))
    ]))


class TestPodAffinityScenarios:
    def test_empty_affinity_schedules(self):
        pods = [pod("p0", affinity=Affinity(
            pod_affinity=PodAffinity(), pod_anti_affinity=PodAffinity()))]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors

    def test_affinity_hostname_collocates(self):
        target = pod("target", labels={"role": "target"})
        followers = [pod(f"f{i}", labels={"role": "f"},
                         affinity=pod_affinity({"role": "target"}))
                     for i in range(5)]
        for res in solve_both([target] + followers):
            assert res.all_pods_scheduled(), res.pod_errors
            homes = [c for c in res.new_node_claims if c.pods]
            with_target = [c for c in homes if any(
                p.metadata.labels.get("role") == "target" for p in c.pods)]
            assert len(with_target) == 1
            assert len(with_target[0].pods) == 6

    def test_affinity_zone_collocates(self):
        # zone affinity follows a COMMITTED target (the late-committal
        # model: an unpinned target's claim keeps its zone set open, see
        # test_affinity_to_uncommitted_target_fails)
        target = pod("target", cpu=2.0, labels={"role": "target"},
                     node_selector={L.LABEL_TOPOLOGY_ZONE: "zone-b"})
        followers = [pod(f"f{i}", labels={"role": "f"},
                         affinity=pod_affinity({"role": "target"},
                                               key=L.LABEL_TOPOLOGY_ZONE))
                     for i in range(5)]
        for res in solve_both([target] + followers):
            assert res.all_pods_scheduled(), res.pod_errors
            assert set(domain_counts(res, L.LABEL_TOPOLOGY_ZONE)) == {"zone-b"}

    def test_self_affinity_hostname_single_node(self):
        pods = [pod(f"p{i}", labels={"app": "self"},
                    affinity=pod_affinity({"app": "self"}))
                for i in range(4)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            assert len([c for c in res.new_node_claims if c.pods]) == 1

    def test_affinity_to_missing_target_fails(self):
        pods = [pod("p0", affinity=pod_affinity({"role": "ghost"}))]
        for res in solve_both(pods):
            assert not res.all_pods_scheduled()

    def test_dependent_affinity_chain(self):
        # a (zone-pinned) <- b (affine to a) <- c (affine to b): the
        # commitment propagates down the chain
        a = pod("a", cpu=2.0, labels={"tier": "a"},
                node_selector={L.LABEL_TOPOLOGY_ZONE: "zone-c"})
        b = pod("b", labels={"tier": "b"},
                affinity=pod_affinity({"tier": "a"},
                                      key=L.LABEL_TOPOLOGY_ZONE))
        c = pod("c", labels={"tier": "c"},
                affinity=pod_affinity({"tier": "b"},
                                      key=L.LABEL_TOPOLOGY_ZONE))
        for res in solve_both([a, b, c]):
            assert res.all_pods_scheduled(), res.pod_errors
            assert set(domain_counts(res, L.LABEL_TOPOLOGY_ZONE)) == {"zone-c"}

    def test_unsatisfiable_dependency_fails(self):
        # b depends on a missing tier
        b = pod("b", labels={"tier": "b"},
                affinity=pod_affinity({"tier": "missing"},
                                      key=L.LABEL_TOPOLOGY_ZONE))
        c = pod("c", labels={"tier": "c"},
                affinity=pod_affinity({"tier": "b"},
                                      key=L.LABEL_TOPOLOGY_ZONE))
        for res in solve_both([b, c]):
            assert not res.all_pods_scheduled()

    def test_preferred_affinity_violated_when_impossible(self):
        # topology_test.go:1698 — preference to a non-existent pod relaxes
        aff = Affinity(pod_affinity=PodAffinity(preferred=[
            WeightedPodAffinityTerm(weight=100, pod_affinity_term=
                                    PodAffinityTerm(
                                        topology_key=L.LABEL_HOSTNAME,
                                        label_selector=selector_for(
                                            {"role": "ghost"}),
                                    ))
        ]))
        pods = [pod("p0", affinity=aff)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors


# --------------------------------------------------------------------------
# G. pod anti-affinity (topology_test.go:1731-2193)


class TestPodAntiAffinityScenarios:
    def test_hostname_anti_affinity_separates(self):
        pods = [pod(f"p{i}", labels={"app": "anti"},
                    affinity=pod_anti_affinity({"app": "anti"}))
                for i in range(3)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors
            assert all(len(c.pods) <= 1 for c in res.new_node_claims)

    def test_zone_anti_affinity_fourth_pod_fails(self):
        # zone-committed anti pods: three land in distinct zones, the
        # fourth (re-pinning zone-a) conflicts and stays pending
        zones = ["zone-a", "zone-b", "zone-c", "zone-a"]
        pods = [pod(f"p{i}", labels={"app": "anti"},
                    node_selector={L.LABEL_TOPOLOGY_ZONE: zones[i]},
                    affinity=pod_anti_affinity({"app": "anti"},
                                               key=L.LABEL_TOPOLOGY_ZONE))
                for i in range(4)]
        for res in solve_both(pods):
            assert scheduled_count(res) == 3
            assert len(res.pod_errors) == 1
            assert set(domain_counts(res, L.LABEL_TOPOLOGY_ZONE)) == {
                "zone-a", "zone-b", "zone-c"}

    def test_anti_affinity_other_schedules_first(self):
        # the zone-committed target schedules; the anti pod avoids its zone
        target = pod("target", cpu=2.0, labels={"role": "t"},
                     node_selector={L.LABEL_TOPOLOGY_ZONE: "zone-a"})
        anti = pod("anti", labels={"role": "a"},
                   affinity=pod_anti_affinity({"role": "t"},
                                              key=L.LABEL_TOPOLOGY_ZONE))
        for res in solve_both([target, anti]):
            assert res.all_pods_scheduled(), res.pod_errors
            by_name = {
                p.metadata.name: claim
                for claim in res.new_node_claims for p in claim.pods
            }
            assert not by_name["anti"].requirements.get(
                L.LABEL_TOPOLOGY_ZONE).has("zone-a")

    def test_preferred_anti_affinity_violated_when_needed(self):
        aff = Affinity(pod_anti_affinity=PodAffinity(preferred=[
            WeightedPodAffinityTerm(weight=1, pod_affinity_term=
                                    PodAffinityTerm(
                                        topology_key=L.LABEL_TOPOLOGY_ZONE,
                                        label_selector=selector_for(
                                            {"app": "anti"}),
                                    ))
        ]))
        # 4 zone-committed pods, 3 zones: the 4th violates the preference
        zones = ["zone-a", "zone-b", "zone-c", "zone-a"]
        pods = [pod(f"p{i}", labels={"app": "anti"}, affinity=aff,
                    node_selector={L.LABEL_TOPOLOGY_ZONE: zones[i]})
                for i in range(4)]
        for res in solve_both(pods):
            assert res.all_pods_scheduled(), res.pod_errors

    def test_conflicting_required_beats_affinity_preference(self):
        # topology_test.go:2097 — required zone-a + preferred affinity to a
        # pod pinned in zone-b: the preference loses
        pinned = pod("pinned", labels={"role": "pin"},
                     node_selector={L.LABEL_TOPOLOGY_ZONE: "zone-b"})
        aff = Affinity(
            node_affinity=NodeAffinity(required=[
                NodeSelectorTerm(match_expressions=(NodeSelectorRequirement(
                    L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a",)),))
            ]),
            pod_affinity=PodAffinity(preferred=[
                WeightedPodAffinityTerm(weight=100, pod_affinity_term=
                                        PodAffinityTerm(
                                            topology_key=L.LABEL_TOPOLOGY_ZONE,
                                            label_selector=selector_for(
                                                {"role": "pin"}),
                                        ))
            ]),
        )
        wants = pod("wants", affinity=aff)
        for res in solve_both([pinned, wants]):
            assert res.all_pods_scheduled(), res.pod_errors
            by_name = {
                p.metadata.name: claim
                for claim in res.new_node_claims for p in claim.pods
            }
            zone = by_name["wants"].requirements.get(
                L.LABEL_TOPOLOGY_ZONE
            ).sorted_values()
            assert zone == ["zone-a"]


# --------------------------------------------------------------------------
# H. daemonset x topology (scheduler daemon overhead vs spread selectors)


class TestDaemonSetTopology:
    def daemon(self, cpu=0.5, node_selector=None):
        d = Pod(
            metadata=ObjectMeta(name="ds", labels={"app": "daemon"}),
            resource_requests={"cpu": cpu, "memory": 0.25 * GIB},
            node_selector=dict(node_selector or {}),
            is_daemonset=True,
        )
        return d

    def test_daemon_overhead_charged_on_spread_nodes(self):
        # hostname-spread pods open one node each; every node carries the
        # daemon's overhead, so a type must fit pod + daemon
        daemons = [self.daemon(cpu=0.5)]
        pods = [pod(f"p{i}", cpu=1.0,
                    constraints=[spread(L.LABEL_HOSTNAME)])
                for i in range(3)]
        for res in solve_both(pods, daemonsets=daemons):
            assert res.all_pods_scheduled(), res.pod_errors
            for claim in res.new_node_claims:
                if not claim.pods:
                    continue
                assert claim.requests.get("cpu", 0.0) >= 1.5

    def test_daemon_does_not_count_toward_workload_spread(self):
        # the daemon's labels don't match the workload selector: skew is
        # computed over workload pods only
        daemons = [self.daemon(cpu=0.1)]
        pods = [pod(f"p{i}", constraints=[spread(L.LABEL_TOPOLOGY_ZONE)])
                for i in range(3)]
        for res in solve_both(pods, daemonsets=daemons):
            assert res.all_pods_scheduled(), res.pod_errors
            counts = domain_counts(res, L.LABEL_TOPOLOGY_ZONE)
            assert sorted(counts.values()) == [1, 1, 1]

    def test_incompatible_daemon_not_charged_on_template(self):
        # daemon overhead is computed per NodeClaimTemplate
        # (scheduler.go:318-354): a daemon whose selector the template can
        # never satisfy contributes nothing
        daemons = [self.daemon(cpu=0.5,
                               node_selector={L.LABEL_TOPOLOGY_ZONE:
                                              "zone-a"})]
        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            L.LABEL_TOPOLOGY_ZONE, "In", ("zone-b",))])
        pods = [pod("p0", cpu=1.0)]
        for res in solve_both(pods, pools=[pool], daemonsets=daemons):
            assert res.all_pods_scheduled(), res.pod_errors
            claim = [c for c in res.new_node_claims if c.pods][0]
            assert claim.requests.get("cpu", 0.0) < 1.5


# --------------------------------------------------------------------------
# I. taints (suite_test.go:2450-2495)


class TestNodePoolTaints:
    def test_intolerant_pods_fail_tolerant_schedule(self):
        pool = make_nodepool(taints=[Taint(key="example.com/special",
                                           value="true",
                                           effect="NoSchedule")])
        tolerant = pod("tol", tolerations=[Toleration(
            key="example.com/special", operator="Equal", value="true",
            effect="NoSchedule")])
        intolerant = pod("intol")
        for res in solve_both([tolerant, intolerant], pools=[pool]):
            assert scheduled_count(res) == 1
            # exactly the intolerant pod failed
            assert set(res.pod_errors) == {intolerant.uid}
            placed = {
                p.metadata.name
                for c in res.new_node_claims for p in c.pods
            }
            assert placed == {"tol"}

    def test_startup_taint_does_not_block(self):
        pool = make_nodepool()
        pool.spec.template.startup_taints = [Taint(
            key="example.com/starting", value="true", effect="NoSchedule")]
        pods = [pod("p0")]
        for res in solve_both(pods, pools=[pool]):
            assert res.all_pods_scheduled(), res.pod_errors


class TestHostFloorOrdering:
    def test_anti_affinity_with_affinity_dependency_not_promoted(self):
        """A class owning hostname anti-affinity PLUS a pod affinity to
        another class must keep size order: promoted ahead of its target it
        would find no count>0 domain and fail pods the oracle places."""
        db = pod("db", cpu=2.0, labels={"app": "db"},
                 node_selector={L.LABEL_TOPOLOGY_ZONE: "zone-a"})
        followers = [
            pod(
                f"w{i}", cpu=0.3, labels={"app": "worker"},
                affinity=Affinity(
                    pod_affinity=PodAffinity(required=[PodAffinityTerm(
                        topology_key=L.LABEL_TOPOLOGY_ZONE,
                        label_selector=selector_for({"app": "db"}),
                    )]),
                    pod_anti_affinity=PodAffinity(required=[PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME,
                        label_selector=selector_for({"app": "worker"}),
                    )]),
                ),
            )
            for i in range(3)
        ]
        for res in solve_both([db] + followers):
            assert res.all_pods_scheduled(), res.pod_errors
            # workers separated by host, co-zoned with db
            assert set(domain_counts(res, L.LABEL_TOPOLOGY_ZONE)) == {"zone-a"}

    def test_pure_hostname_anti_classes_promoted_pack_denser(self):
        """The promotion itself: a diverse mix where anti-h classes run
        first must pack at least as tight as the greedy oracle."""
        pods = []
        for d in range(3):
            for i in range(6):
                pods.append(pod(
                    f"a{d}-{i}", cpu=0.2, labels={"app": f"anti-{d}"},
                    affinity=pod_anti_affinity({"app": f"anti-{d}"}),
                ))
        for i in range(12):
            pods.append(pod(f"g{i}", cpu=1.5, labels={"app": "bulk"}))
        rg, rd = solve_both(pods)
        assert rg.all_pods_scheduled() and rd.all_pods_scheduled()
        assert rd.node_count() <= rg.node_count()
