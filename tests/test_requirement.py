"""Property tests for the Requirement set algebra.

Strategy: instead of porting the reference's table tests
(pkg/scheduling/requirement_test.go), every operator pair is checked
against brute-force set semantics over a closed universe — r1 ∩ r2 must
agree with pointwise has() for every probe value, including values outside
the universe and integer probes for Gt/Lt.
"""
import itertools
import random

import pytest

from karpenter_core_tpu.scheduling.requirement import (
    MAX_LEN,
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
    Requirement,
)

UNIVERSE = ["A", "B", "C", "1", "2", "3", "5", "10", "100", "zz"]
PROBES = UNIVERSE + ["D", "0", "4", "7", "11", "99", "101", "-1", "x/y"]


def gen_requirements(key="key"):
    """A representative spread of requirements across all operators."""
    out = []
    value_sets = [
        [],
        ["A"],
        ["A", "B"],
        ["1", "2", "3"],
        ["B", "C", "10"],
        ["1", "100"],
        UNIVERSE,
    ]
    for vs in value_sets:
        if vs:
            out.append(Requirement.new(key, OP_IN, vs))
        out.append(Requirement.new(key, OP_NOT_IN, vs))
    out.append(Requirement.new(key, OP_EXISTS))
    out.append(Requirement.new(key, OP_DOES_NOT_EXIST))
    for bound in ["0", "1", "2", "9", "100"]:
        out.append(Requirement.new(key, OP_GT, [bound]))
        out.append(Requirement.new(key, OP_LT, [bound]))
    return out


class TestOperator:
    def test_in(self):
        r = Requirement.new("k", OP_IN, ["A", "B"])
        assert r.operator() == OP_IN
        assert r.length() == 2
        assert r.has("A") and r.has("B") and not r.has("C")

    def test_not_in(self):
        r = Requirement.new("k", OP_NOT_IN, ["A"])
        assert r.operator() == OP_NOT_IN
        assert r.length() == MAX_LEN - 1
        assert not r.has("A") and r.has("B")

    def test_exists(self):
        r = Requirement.new("k", OP_EXISTS)
        assert r.operator() == OP_EXISTS
        assert r.length() == MAX_LEN
        assert r.has("anything")

    def test_does_not_exist(self):
        r = Requirement.new("k", OP_DOES_NOT_EXIST)
        assert r.operator() == OP_DOES_NOT_EXIST
        assert r.length() == 0
        assert not r.has("anything")

    def test_gt(self):
        r = Requirement.new("k", OP_GT, ["5"])
        # Gt/Lt read as Exists-with-bounds (requirement.go:224-235)
        assert r.operator() == OP_EXISTS
        assert r.has("6") and r.has("100")
        assert not r.has("5") and not r.has("4")
        assert not r.has("abc")  # non-integers excluded by bounds

    def test_lt(self):
        r = Requirement.new("k", OP_LT, ["5"])
        assert r.has("4") and r.has("0")
        assert not r.has("5") and not r.has("6")
        assert not r.has("abc")

    def test_empty_in_is_does_not_exist(self):
        assert Requirement.new("k", OP_IN, []).operator() == OP_DOES_NOT_EXIST

    def test_label_normalization(self):
        r = Requirement.new("beta.kubernetes.io/arch", OP_IN, ["amd64"])
        assert r.key == "kubernetes.io/arch"


class TestIntersectionProperty:
    @pytest.mark.parametrize("seed", range(3))
    def test_pointwise_semantics(self, seed):
        reqs = gen_requirements()
        rng = random.Random(seed)
        pairs = list(itertools.product(reqs, reqs))
        rng.shuffle(pairs)
        for r1, r2 in pairs:
            inter = r1.intersection(r2)
            for v in PROBES:
                expected = r1.has(v) and r2.has(v)
                # The closed intersection may be lossy only in one documented
                # way: concrete (non-complement) results drop Gt/Lt bounds
                # after filtering known values (requirement.go:183-186), which
                # is exact for values in the explicit set. So has() must agree
                # everywhere.
                assert inter.has(v) == expected, (
                    f"({r1!r}) ∩ ({r2!r}) at {v!r}: "
                    f"got {inter.has(v)}, want {expected}"
                )

    def test_commutative_cardinality(self):
        reqs = gen_requirements()
        for r1, r2 in itertools.product(reqs, reqs):
            a = r1.intersection(r2)
            b = r2.intersection(r1)
            assert a.length() == b.length(), f"{r1!r} vs {r2!r}"
            assert a.operator() == b.operator()

    def test_crossed_bounds_become_does_not_exist(self):
        gt = Requirement.new("k", OP_GT, ["5"])
        lt = Requirement.new("k", OP_LT, ["3"])
        inter = gt.intersection(lt)
        assert inter.operator() == OP_DOES_NOT_EXIST
        assert inter.length() == 0

    def test_min_values_max_wins(self):
        r1 = Requirement.new("k", OP_IN, ["A", "B", "C"], min_values=2)
        r2 = Requirement.new("k", OP_IN, ["A", "B"], min_values=3)
        assert r1.intersection(r2).min_values == 3


class TestAnyValue:
    def test_in(self):
        assert Requirement.new("k", OP_IN, ["A"]).any_value() == "A"

    def test_not_in_avoids_excluded(self):
        r = Requirement.new("k", OP_NOT_IN, ["0", "1"])
        v = r.any_value()
        assert v not in ("0", "1")
        assert r.has(v)

    def test_gt_bound_respected(self):
        r = Requirement.new("k", OP_GT, ["10"])
        assert int(r.any_value()) > 10
