"""Multi-chip sharding parity tests on the 8-device virtual CPU mesh.

VERDICT r1 gap: nothing in tests/ actually sharded. These tests run the two
flagship device programs — the provisioning FFD solve (ops/ffd.py, the
batched Scheduler.Solve of scheduler.go:208-266) and the consolidation
prefix scan (models/consolidation.py, the batched binary search of
multinodeconsolidation.go:110-162) — with real `NamedSharding`s over the
conftest-forced 8-device CPU mesh at realistic size (>=1k slots, >=100 pod
classes) and assert *bit-exact* equality with single-device execution.

Exactness is a design property, not luck: the only cross-slot reduction in
the solve is the int32 first-fit prefix sum; everything else is elementwise
per slot, so resharding cannot reorder float accumulations.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tests.helpers import GIB, make_nodepool, make_pod

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.cloudprovider.kwok import bench_catalog, build_catalog
from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import SimNode
from karpenter_core_tpu.controllers.provisioning.scheduling.topology import Topology
from karpenter_core_tpu.models.consolidation import _prefix_scan
from karpenter_core_tpu.models.provisioner import DeviceScheduler
from karpenter_core_tpu.ops.ffd import ffd_solve
from karpenter_core_tpu.parallel import (
    batch_sharding,
    replicated,
    slot_mesh,
    slot_shardings,
)

MAX_SLOTS = 1024
N_DEVICES = 8


def _existing_nodes(n: int, cpu: float = 8.0):
    return [
        SimNode(
            name=f"existing-{i}",
            labels={
                L.LABEL_ARCH: "amd64",
                L.LABEL_OS: "linux",
                L.LABEL_TOPOLOGY_ZONE: "zone-a",
                L.NODEPOOL_LABEL_KEY: "default",
                L.LABEL_INSTANCE_TYPE: "s-8x-amd64-linux",
            },
            taints=[],
            available={"cpu": cpu, "memory": 16 * GIB, "pods": 200.0},
            capacity={"cpu": cpu, "memory": 16 * GIB, "pods": 210.0},
        )
        for i in range(n)
    ]


def _problem(n_pods: int, n_types: int, n_existing: int = 0):
    """>=100 pod equivalence classes (16 cpu shapes x 12 mem shapes)."""
    catalog = (
        bench_catalog(n_types) if n_types > 144 else build_catalog()[:n_types]
    )
    pods = [
        make_pod(
            cpu=0.1 * (1 + i % 16),
            memory_gib=0.25 * (1 + (i // 16) % 12),
            name=f"p{i}",
        )
        for i in range(n_pods)
    ]
    sched = DeviceScheduler(
        [make_nodepool()],
        {"default": catalog},
        existing_nodes=_existing_nodes(n_existing),
        max_slots=MAX_SLOTS,
    )
    prep = sched._prepare(pods, MAX_SLOTS, Topology())
    assert len(prep.classes) >= 100, len(prep.classes)
    return sched, prep


def _assert_trees_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


class TestShardedFFDSolve:
    def test_slot_sharded_solve_bit_exact(self):
        sched, prep = _problem(n_pods=3000, n_types=160)
        classes = sched._class_steps(prep)

        ref_final, ref_takes, ref_unplaced = jax.jit(ffd_solve)(
            prep.init_state, classes, prep.statics
        )
        jax.block_until_ready(ref_takes)
        assert int(np.asarray(ref_unplaced).sum()) == 0

        mesh = slot_mesh(N_DEVICES)
        state_sh = slot_shardings(mesh, prep.init_state, MAX_SLOTS)
        repl = replicated(mesh)
        class_sh = jax.tree.map(lambda _: repl, classes)
        static_sh = jax.tree.map(lambda _: repl, prep.statics)

        state = jax.device_put(prep.init_state, state_sh)
        cls = jax.device_put(classes, class_sh)
        statics = jax.device_put(prep.statics, static_sh)

        step = jax.jit(
            ffd_solve,
            in_shardings=(state_sh, class_sh, static_sh),
            out_shardings=(state_sh, repl, repl),
        )
        final, takes, unplaced = step(state, cls, statics)
        jax.block_until_ready(takes)

        # output really was computed under the slot sharding
        kind_sh = final.kind.sharding
        assert kind_sh.is_equivalent_to(
            NamedSharding(mesh, P("slots")), final.kind.ndim
        )

        _assert_trees_equal(final, ref_final)
        np.testing.assert_array_equal(np.asarray(takes), np.asarray(ref_takes))
        np.testing.assert_array_equal(
            np.asarray(unplaced), np.asarray(ref_unplaced)
        )


class TestShardedPrefixScan:
    def test_prefix_sharded_consolidation_bit_exact(self):
        n_prefixes = 8
        sched, prep = _problem(
            n_pods=1500, n_types=96, n_existing=n_prefixes * 2
        )
        classes = sched._class_steps(prep)
        C = len(prep.classes)

        base_kind = np.asarray(prep.init_state.kind)
        kind_batch = np.tile(base_kind, (n_prefixes, 1))
        for p in range(n_prefixes):
            kind_batch[p, : p + 1] = 0  # mask candidates [0, p]

        base_counts = np.asarray(classes.count)
        count_batch = np.tile(base_counts, (n_prefixes, 1))
        for p in range(n_prefixes):
            # prefix p reschedules p+1 candidates' pods: bump a few classes
            count_batch[p, (p * 7) % C] += 3
            count_batch[p, (p * 13 + 1) % C] += 2

        from karpenter_core_tpu.models.consolidation import _it_price_vector

        args = (
            prep.init_state,
            classes,
            prep.statics,
            jnp.asarray(kind_batch),
            jnp.asarray(count_batch),
            jnp.asarray(_it_price_vector(prep)),
            jnp.int32(len(sched.existing_nodes)),
        )
        ref = _prefix_scan(*args)
        jax.block_until_ready(ref)

        mesh = slot_mesh(N_DEVICES, axis="prefixes")
        repl = replicated(mesh)
        pref = batch_sharding(mesh, 1, axis="prefixes")
        pref2 = batch_sharding(mesh, 2, axis="prefixes")
        in_sh = (
            jax.tree.map(lambda _: repl, prep.init_state),
            jax.tree.map(lambda _: repl, classes),
            jax.tree.map(lambda _: repl, prep.statics),
            pref2,
            pref2,
            repl,
            repl,
        )
        step = jax.jit(
            lambda st, cl, sx, kb, cb, pv, ne: _prefix_scan(
                st, cl, sx, kb, cb, pv, ne
            ),
            in_shardings=in_sh,
            out_shardings=(pref, pref, pref, pref),
        )
        sharded = step(*jax.device_put(args, in_sh))
        jax.block_until_ready(sharded)

        assert sharded[0].sharding.is_equivalent_to(pref, 1)
        _assert_trees_equal(sharded, ref)
