"""Tier-1 enforcement + per-rule unit tests for tools/graftlint.

Two jobs:

1. ``test_tree_is_clean`` runs the full engine over ``karpenter_core_tpu/``
   and fails on ANY unsuppressed finding — the invariants the rules encode
   (canonical encode order, jit purity, lock discipline, wire/metric
   parity) become CI properties of every future diff.
2. The fixture battery proves each rule FIRES on its bad fixture and stays
   quiet on the good one, so a refactor of the engine cannot silently turn
   a rule into a no-op (a linter that never fires passes every tree).
"""
from __future__ import annotations

import json
import time
from pathlib import Path

import pytest

from tools.graftlint import RULES, run
from tools.graftlint.engine import (
    BASELINE_PATH,
    LINT_BUDGET_SECONDS,
    REPO_ROOT,
    write_baseline,
)

FIXTURES = Path(__file__).parent / "graftlint_fixtures"


def _fixture_pairs():
    pairs = []
    for bad in sorted(FIXTURES.rglob("*_bad*.py")):
        rule = bad.name.split("_")[0].upper()
        good_matches = sorted(
            FIXTURES.rglob(f"{rule.lower()}_good*.py")
        )
        assert good_matches, f"no good fixture for {rule}"
        # a rule may ship several bad/good pairs (e.g. the GL702 base pair
        # plus the fair-queue-shaped pair): prefer the good twin with the
        # matching suffix so every good fixture is actually exercised
        twin = bad.with_name(bad.name.replace("_bad", "_good"))
        good = twin if twin in good_matches else good_matches[0]
        pairs.append((rule, bad, good))
    return pairs


_PAIRS = _fixture_pairs()


# -- tier-1 gate -----------------------------------------------------------


def test_tree_is_clean():
    t0 = time.perf_counter()
    result = run(["karpenter_core_tpu"])
    elapsed = time.perf_counter() - t0
    rendered = "\n".join(f.render() for f, _src in result.new)
    assert result.ok, (
        f"graftlint found new violations:\n{rendered}\n"
        "fix them, or add an inline '# graftlint: disable=RULE -- why'"
    )
    # the lint pass must stay cheap enough to run on every test invocation
    assert elapsed < LINT_BUDGET_SECONDS, (
        f"graftlint took {elapsed:.1f}s (budget {LINT_BUDGET_SECONDS}s)"
    )


def test_rule_inventory():
    """At least 24 rules across the seven invariant families."""
    run([str(FIXTURES / "gl000_good.py")])  # force registration
    # GL000 runs engine-side (suppression hygiene), outside the registry —
    # the CLI's rule count includes it, and so does this pin
    ids = set(RULES) | {"GL000"}
    assert len(ids) >= 24, f"only {len(ids)} rules registered: {sorted(ids)}"
    families = {rid[:3] for rid in ids if rid != "GL000"}
    assert {"GL1", "GL2", "GL3", "GL4", "GL5", "GL6", "GL7"} <= families, (
        "expected jax-purity (GL1xx), determinism (GL2xx), concurrency"
        " (GL3xx), parity (GL4xx), shardcheck (GL5xx), rangecheck"
        f" (GL6xx) and lockgraph (GL7xx) families, got {sorted(families)}"
    )
    assert "GL104" not in ids, "GL104 was retired into GL503 (shardcheck)"
    assert "GL302" not in ids, "GL302 was retired into GL702 (lockgraph)"
    assert "GL303" not in ids, "GL303 was retired into GL702 (lockgraph)"
    assert {"GL403", "GL501", "GL502", "GL503", "GL504"} <= ids
    # ISSUE 11: the rangecheck family + the I/O-under-grant lint
    assert {"GL304", "GL601", "GL602", "GL603", "GL604"} <= ids
    # ISSUE 19: the lockgraph family
    assert {"GL701", "GL702", "GL703", "GL704", "GL705"} <= ids


def test_baseline_is_frozen_empty():
    """Repo policy (ISSUE 4): no baselined debt for the shipped families —
    violations are fixed or inline-justified, never parked."""
    data = json.loads(BASELINE_PATH.read_text())
    assert data == {"entries": {}}


# -- per-rule fixtures -----------------------------------------------------


@pytest.mark.parametrize(
    "rule,bad,good", _PAIRS, ids=[p[0] for p in _PAIRS]
)
def test_rule_fires_on_bad_fixture(rule, bad, good):
    result = run([str(bad)], use_baseline=False, rule_ids=[rule])
    assert result.new, f"{rule} did not fire on {bad.name}"
    assert all(f.rule == rule for f, _ in result.new)


@pytest.mark.parametrize(
    "rule,bad,good", _PAIRS, ids=[p[0] for p in _PAIRS]
)
def test_rule_quiet_on_good_fixture(rule, bad, good):
    result = run([str(good)], use_baseline=False, rule_ids=[rule])
    rendered = "\n".join(f.render() for f, _ in result.new)
    assert not result.new, f"{rule} over-fired on {good.name}:\n{rendered}"


def test_every_rule_has_a_failing_fixture():
    covered = {rule for rule, _b, _g in _PAIRS}
    run([str(FIXTURES / "gl000_good.py")])  # force registration
    missing = set(RULES) - covered - {"GL000"}
    assert not missing, (
        f"rules without a bad fixture proving they fire: {sorted(missing)}"
    )
    assert "GL000" in covered  # the suppression-hygiene meta rule too


# -- suppression + baseline mechanics --------------------------------------


def test_inline_suppression_silences_and_is_counted():
    result = run(
        [str(FIXTURES / "gl000_good.py")],
        use_baseline=False,
        rule_ids=["GL201"],
    )
    assert not result.new
    assert len(result.suppressed) == 1


def test_suppression_without_justification_is_flagged():
    result = run(
        [str(FIXTURES / "gl000_bad.py")],
        use_baseline=False,
        rule_ids=["GL000", "GL201"],
    )
    assert [f.rule for f, _ in result.new] == ["GL000"]
    # the (unjustified) disable still silences the underlying finding;
    # GL000 is what forces the justification to appear
    assert len(result.suppressed) == 1


def test_baseline_roundtrip(tmp_path):
    """--baseline freezes current findings; a rerun against that file is
    clean; the baseline does NOT absorb findings on new lines."""
    bad = FIXTURES / "gl201_bad.py"
    fresh = run([str(bad)], use_baseline=False, rule_ids=["GL201"])
    assert fresh.new
    bl = tmp_path / "baseline.json"
    write_baseline(fresh, bl)
    again = run(
        [str(bad)], use_baseline=True, rule_ids=["GL201"], baseline_path=bl
    )
    assert not again.new
    assert len(again.baselined) == len(fresh.new)

    # a NEW copy of the same violations in another file is not absorbed
    # (the dir name keeps the clone inside GL201's fixture scope)
    clone_dir = tmp_path / "graftlint_fixtures"
    clone_dir.mkdir()
    clone = clone_dir / "gl201_clone.py"
    clone.write_text(bad.read_text())
    grown = run(
        [str(clone)], use_baseline=True, rule_ids=["GL201"], baseline_path=bl
    )
    assert grown.new, "baseline must not absorb violations in new files"


def test_cli_exit_codes(tmp_path):
    from tools.graftlint.engine import main

    assert main([str(FIXTURES / "gl201_good.py"), "--rule", "GL201"]) == 0
    assert main([str(FIXTURES / "gl201_bad.py"), "--rule", "GL201"]) == 1


def test_repo_paths_resolve_relative_to_root():
    """The default path works no matter the CWD (engine anchors on the
    repo root, so CI and `python -m` from anywhere agree)."""
    assert (REPO_ROOT / "karpenter_core_tpu").is_dir()


# -- wire-schema lock mechanics (GL403) ------------------------------------


_MINI_CODEC = '''\
import json

SOLVE_WIRE_VERSION = {version}


def encode_solve_request(pods, max_slots{extra_param}):
    header = {{
        "version": SOLVE_WIRE_VERSION,
        "pods": pods,
        "max_slots": max_slots,{extra_field}
    }}
    return json.dumps(header).encode()


def decode_solve_request(data):
    h = json.loads(data.decode())
    return {{"pods": h["pods"], "max_slots": h["max_slots"]{extra_read}}}
'''


def _mini_codec(version=2, with_priority=False):
    return _MINI_CODEC.format(
        version=version,
        extra_param=", priority" if with_priority else "",
        extra_field='\n        "priority": priority,' if with_priority else "",
        extra_read=', "priority": h["priority"]' if with_priority else "",
    )


def _codec_fixture(tmp_path, source, name="gl403_tmp_codec.py"):
    d = tmp_path / "graftlint_fixtures"
    d.mkdir(exist_ok=True)
    p = d / name
    p.write_text(source)
    return p, p.with_name(p.stem + ".lock.json")


def test_wire_lock_field_added_without_bump_fails(tmp_path):
    from tools.graftlint.rules.parity import update_wire_lock

    p, lock = _codec_fixture(tmp_path, _mini_codec(version=2))
    update_wire_lock(codec_path=p, lock_path=lock)
    clean = run([str(p)], use_baseline=False, rule_ids=["GL403"])
    assert clean.ok

    # grow the field set, keep the version: GL403 must fail the lint
    p.write_text(_mini_codec(version=2, with_priority=True))
    grown = run([str(p)], use_baseline=False, rule_ids=["GL403"])
    assert len(grown.new) == 1
    assert "without a SOLVE_WIRE_VERSION bump" in grown.new[0][0].message
    assert "priority" in grown.new[0][0].message


def test_wire_lock_bump_plus_regen_passes(tmp_path):
    from tools.graftlint.rules.parity import update_wire_lock

    p, lock = _codec_fixture(tmp_path, _mini_codec(version=2))
    update_wire_lock(codec_path=p, lock_path=lock)

    # bump alone (stale lock) still fails — the lock must be regenerated
    p.write_text(_mini_codec(version=3, with_priority=True))
    stale = run([str(p)], use_baseline=False, rule_ids=["GL403"])
    assert not stale.ok
    assert any("stale" in f.message for f, _ in stale.new)

    update_wire_lock(codec_path=p, lock_path=lock)
    again = run([str(p)], use_baseline=False, rule_ids=["GL403"])
    assert again.ok, [f.render() for f, _ in again.new]


def test_update_wire_lock_refuses_unbumped_change(tmp_path):
    """--update-wire-lock enforces the bump: it must never absorb an
    unversioned field-set change into the lock."""
    from tools.graftlint.rules.parity import update_wire_lock

    p, lock = _codec_fixture(tmp_path, _mini_codec(version=2))
    update_wire_lock(codec_path=p, lock_path=lock)
    p.write_text(_mini_codec(version=2, with_priority=True))
    with pytest.raises(SystemExit, match="without a version bump"):
        update_wire_lock(codec_path=p, lock_path=lock)
    # after bumping, the regeneration goes through
    p.write_text(_mini_codec(version=3, with_priority=True))
    n = update_wire_lock(codec_path=p, lock_path=lock)
    assert n == 1
    data = json.loads(lock.read_text())
    assert data["versions"]["SOLVE_WIRE_VERSION"] == 3
    assert "priority" in data["encoders"]["encode_solve_request"]["fields"]


def test_real_codec_matches_committed_lock():
    """The committed lock and solver/codec.py agree — the moment a codec
    PR changes a field set, this (and the tree gate) forces the version
    bump + `--update-wire-lock` ritual."""
    result = run(
        ["karpenter_core_tpu/solver/codec.py"],
        use_baseline=False,
        rule_ids=["GL403"],
    )
    assert result.ok, "\n".join(f.render() for f, _ in result.new)


def test_wire_lock_extraction_expands_mask_helper():
    """The one-level interprocedural expansion: _masks_to_arrays'
    f-string keys land in encode_request's locked field set."""
    from tools.graftlint.engine import ParsedFile
    from tools.graftlint.rules.parity import CODEC_PATH, extract_wire_schema

    pf = ParsedFile(CODEC_PATH, "solver/codec.py", CODEC_PATH.read_text())
    schema = extract_wire_schema(pf)
    fields = set(schema["encoders"]["encode_request"]["fields"])
    assert {"class_mask", "class_gt", "it_mask", "it_negative"} <= fields
    assert schema["encoders"]["encode_request"]["versioned_by"] == [
        "SNAPSHOT_WIRE_VERSION"
    ]
    # private helpers are locked too, attributed through the call graph
    assert schema["encoders"]["_encode_sim_node"]["versioned_by"] == [
        "SOLVE_WIRE_VERSION"
    ]


# -- incremental cache + parallel lint -------------------------------------


def test_incremental_cache_hits_and_matches(tmp_path):
    cache = tmp_path / "cache.json"
    cold = run([str(FIXTURES / "ops")], use_baseline=False, cache_path=cache)
    assert cold.cache_hits == 0 and cold.cache_misses == cold.files
    warm = run([str(FIXTURES / "ops")], use_baseline=False, cache_path=cache)
    assert warm.cache_hits == warm.files and warm.cache_misses == 0
    assert [(f, s) for f, s in warm.new] == [(f, s) for f, s in cold.new]
    assert [f for f in warm.suppressed] == [f for f in cold.suppressed]


def test_incremental_cache_busts_on_rule_change(tmp_path, monkeypatch):
    import tools.graftlint.engine as engine

    cache = tmp_path / "cache.json"
    run([str(FIXTURES / "ops")], use_baseline=False, cache_path=cache)
    # any rule-implementation change flips the rule-set hash and must
    # invalidate every cached entry
    monkeypatch.setattr(engine, "_rules_hash", lambda: "different")
    busted = run([str(FIXTURES / "ops")], use_baseline=False, cache_path=cache)
    assert busted.cache_hits == 0 and busted.cache_misses == busted.files


def test_incremental_cache_busts_on_content_change(tmp_path):
    d = tmp_path / "graftlint_fixtures"
    d.mkdir()
    f = d / "gl201_edit.py"
    f.write_text((FIXTURES / "gl201_good.py").read_text())
    cache = tmp_path / "cache.json"
    run([str(d)], use_baseline=False, cache_path=cache)
    f.write_text((FIXTURES / "gl201_bad.py").read_text())
    changed = run([str(d)], use_baseline=False, cache_path=cache)
    assert changed.cache_misses == 1
    assert changed.new, "edited file must re-lint, not serve stale results"


def test_rule_restricted_runs_bypass_cache(tmp_path):
    cache = tmp_path / "cache.json"
    result = run(
        [str(FIXTURES / "gl201_bad.py")],
        use_baseline=False,
        rule_ids=["GL201"],
        cache_path=cache,
    )
    assert result.cache_hits == 0 and result.cache_misses == 0
    assert not cache.exists()


def test_jobs_parallel_matches_serial():
    serial = run([str(FIXTURES)], use_baseline=False)
    parallel = run([str(FIXTURES)], use_baseline=False, jobs=2)
    assert [(f, s) for f, s in parallel.new] == [(f, s) for f, s in serial.new]
    assert parallel.suppressed == serial.suppressed


# -- machine-readable output -----------------------------------------------


def test_json_format_stable_ids(capsys):
    from tools.graftlint.engine import main

    rc = main(
        [str(FIXTURES / "gl201_bad.py"), "--rule", "GL201", "--format", "json"]
    )
    out1 = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out1["schema"] == "graftlint-json/1"
    assert out1["findings"], "bad fixture must produce findings"
    for f in out1["findings"]:
        assert set(f) == {"id", "rule", "path", "line", "message"}
    # ids are content-addressed: a second run yields identical ids
    main([str(FIXTURES / "gl201_bad.py"), "--rule", "GL201", "--format", "json"])
    out2 = json.loads(capsys.readouterr().out)
    assert [f["id"] for f in out1["findings"]] == [
        f["id"] for f in out2["findings"]
    ]
    assert len({f["id"] for f in out1["findings"]}) == len(out1["findings"])


def test_sarif_format_shape(capsys):
    from tools.graftlint.engine import main

    rc = main(
        [str(FIXTURES / "gl201_bad.py"), "--rule", "GL201", "--format", "sarif"]
    )
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    run_ = doc["runs"][0]
    assert run_["tool"]["driver"]["name"] == "graftlint"
    assert {r["id"] for r in run_["tool"]["driver"]["rules"]} == {"GL201"}
    for res in run_["results"]:
        assert res["ruleId"] == "GL201"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith("gl201_bad.py")
        assert loc["region"]["startLine"] >= 1
        assert res["partialFingerprints"]["graftlint/v1"]


def test_text_format_unchanged_default(capsys):
    from tools.graftlint.engine import main

    rc = main([str(FIXTURES / "gl201_bad.py"), "--rule", "GL201"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "GL201" in out and "graftlint:" in out
    assert not out.lstrip().startswith("{"), "text stays the default format"


# -- shardcheck pins against the real tree ---------------------------------


def test_slotstate_specs_match_state_fields():
    """The GL502 property, pinned at runtime against the real modules:
    SLOT_STATE_SPECS classifies exactly the SlotState fields."""
    from karpenter_core_tpu.ops.ffd import SlotState
    from karpenter_core_tpu.parallel.mesh import SLOT_STATE_SPECS

    assert set(SlotState._fields) == set(SLOT_STATE_SPECS)


def test_shardcheck_clean_on_solve_path():
    """GL501/GL503: the production solve path (models/, ops/, parallel/)
    satisfies the pre-sharded-placement invariant with all shardcheck
    rules enabled."""
    result = run(
        [
            "karpenter_core_tpu/models",
            "karpenter_core_tpu/ops",
            "karpenter_core_tpu/parallel",
        ],
        use_baseline=False,
        rule_ids=["GL501", "GL502", "GL503", "GL504"],
    )
    assert result.ok, "\n".join(f.render() for f, _ in result.new)


# -- review-hardening regressions ------------------------------------------


def test_update_wire_lock_refuses_unbumped_add_and_remove(tmp_path):
    """Encoder ADDITION and REMOVAL are schema changes too: the update
    must refuse both without a bump, not silently absorb them."""
    from tools.graftlint.rules.parity import update_wire_lock

    p, lock = _codec_fixture(tmp_path, _mini_codec(version=2))
    update_wire_lock(codec_path=p, lock_path=lock)

    p.write_text(
        _mini_codec(version=2)
        + '\n\ndef encode_extra(x):\n'
        '    return {"version": SOLVE_WIRE_VERSION, "x": x}\n'
    )
    with pytest.raises(SystemExit, match="new encoder"):
        update_wire_lock(codec_path=p, lock_path=lock)

    p.write_text("SOLVE_WIRE_VERSION = 2\n")
    with pytest.raises(SystemExit, match="removed encoder"):
        update_wire_lock(codec_path=p, lock_path=lock)

    # with the bump, both go through
    p.write_text(
        _mini_codec(version=3)
        + '\n\ndef encode_extra(x):\n'
        '    return {"version": SOLVE_WIRE_VERSION, "x": x}\n'
    )
    assert update_wire_lock(codec_path=p, lock_path=lock) == 2


def test_gl503_mixed_host_attr_name_stays_silent(tmp_path):
    """The attribute-summary fallback joins same-named stores project-
    wide; a name that ALSO carries host stores must not flag — ambiguity
    degrades to silence, never noise (tier-1 gates on zero findings)."""
    d = tmp_path / "ops"
    d.mkdir()
    (d / "sharded_store.py").write_text(
        "import jax\n"
        "from karpenter_core_tpu.parallel import mesh as pmesh\n\n\n"
        "class Prep:\n"
        "    pass\n\n\n"
        "def build(mesh, x):\n"
        "    return Prep(init_state=jax.device_put("
        "x, pmesh.axis_sharding(mesh, 2, 0)))\n"
    )
    (d / "host_reuse.py").write_text(
        "import numpy as np\n\n\n"
        "class HostPlan:\n"
        "    def __init__(self):\n"
        "        self.init_state = np.zeros(4)\n\n\n"
        "def use(plan):\n"
        "    return np.asarray(plan.init_state)\n"
    )
    result = run([str(d)], use_baseline=False, rule_ids=["GL503"])
    assert result.ok, "\n".join(f.render() for f, _ in result.new)

    # the UNAMBIGUOUS shape (no host store anywhere) still fires — the
    # consolidation.py prefix_batches pattern the justified suppression
    # covers
    (d / "host_reuse.py").write_text(
        "import numpy as np\n\n\n"
        "def use(plan):\n"
        "    return np.asarray(plan.init_state)\n"
    )
    result = run([str(d)], use_baseline=False, rule_ids=["GL503"])
    assert len(result.new) == 1
    assert "implicit full gather" in result.new[0][0].message


def test_gl503_fires_on_module_defining_own_entry(tmp_path):
    """The retired GL104's second trigger, carried over: a module that
    DEFINES its own SlotState-carrying jit entry (not just one calling
    ffd_solve) is still policed for bare device_put placement."""
    d = tmp_path / "ops"
    d.mkdir()
    f = d / "own_entry.py"
    f.write_text(
        "import jax\n\n\n"
        "@jax.jit\n"
        "def topo_solve(state, weights):\n"
        "    return state\n\n\n"
        "def drive(state_np, weights):\n"
        "    return topo_solve(jax.device_put(state_np), weights)\n"
    )
    result = run([str(f)], use_baseline=False, rule_ids=["GL503"])
    assert len(result.new) == 1
    assert "was GL104" in result.new[0][0].message


def test_incremental_cache_survives_subset_runs(tmp_path):
    """A subset-path run must merge into the cache, not evict the
    entries it didn't scan — or every partial lint destroys the warm
    full-tree hit rate."""
    cache = tmp_path / "cache.json"
    full = run([str(FIXTURES)], use_baseline=False, cache_path=cache)
    subset = run(
        [str(FIXTURES / "ops")], use_baseline=False, cache_path=cache
    )
    assert subset.cache_hits == subset.files
    again = run([str(FIXTURES)], use_baseline=False, cache_path=cache)
    assert again.cache_hits == full.files and again.cache_misses == 0


def test_gl501_off_path_helper_not_flagged(tmp_path):
    """GL501's documented scope: only call sites reachable from
    DeviceScheduler/frontier_core. An off-path models/ helper
    deliberately driving a single-device solve stays silent."""
    d = tmp_path / "models"
    d.mkdir()
    f = d / "off_path.py"
    f.write_text(
        "import numpy as np\n"
        "from karpenter_core_tpu.ops.ffd import SlotState, ffd_solve\n\n\n"
        "def debug_single_device_solve(steps, statics):\n"
        "    state = SlotState(kind=np.zeros(4, dtype=np.int8))\n"
        "    return ffd_solve(state, steps, statics)\n"
    )
    result = run([str(f)], use_baseline=False, rule_ids=["GL501"])
    assert result.ok, "\n".join(fi.render() for fi, _ in result.new)

    # the same host-built state INSIDE DeviceScheduler is on-path: flagged
    f.write_text(
        "import numpy as np\n"
        "from karpenter_core_tpu.ops.ffd import SlotState, ffd_solve\n\n\n"
        "class DeviceScheduler:\n"
        "    def _helper(self, steps, statics):\n"
        "        state = SlotState(kind=np.zeros(4, dtype=np.int8))\n"
        "        return ffd_solve(state, steps, statics)\n"
    )
    result = run([str(f)], use_baseline=False, rule_ids=["GL501"])
    assert len(result.new) == 1


def test_dataflow_queries_survive_reparse():
    """The dataflow index is content-hash cached across run() calls while
    every run hands it freshly parsed AST nodes — queries on the new
    nodes must resolve correctly (memo keys retain their nodes; a
    recycled id() must never alias a dead entry)."""
    import gc

    for _ in range(3):
        result = run(
            ["karpenter_core_tpu/models", "karpenter_core_tpu/ops",
             "karpenter_core_tpu/parallel"],
            use_baseline=False,
            rule_ids=["GL501", "GL503"],
        )
        assert result.ok, "\n".join(f.render() for f, _ in result.new)
        gc.collect()  # free the run's parse; the next run re-parses


def test_cache_ignores_out_of_repo_paths_and_prunes_dead_entries(tmp_path):
    d = tmp_path / "graftlint_fixtures"
    d.mkdir()
    (d / "outside.py").write_text("x = 1\n")
    cache = tmp_path / "cache.json"
    # seed the cache with an entry for a repo file that no longer exists
    cache.write_text(json.dumps({
        "karpenter_core_tpu/gone_forever.py": {
            "key": "stale", "new": [], "suppressed": []
        }
    }))
    result = run([str(d)], use_baseline=False, cache_path=cache)
    assert result.cache_hits == 0 and result.cache_misses == 1
    data = json.loads(cache.read_text())
    assert data == {}, (
        "out-of-repo paths must not be absorbed and dead entries must"
        f" be pruned, got {sorted(data)}"
    )


def test_gl501_keyword_state_call_still_flagged(tmp_path):
    """A keyword-style entry call (`ffd_solve(state=...)`) must not
    disarm GL501."""
    d = tmp_path / "models"
    d.mkdir()
    f = d / "kw_call.py"
    f.write_text(
        "import numpy as np\n"
        "from karpenter_core_tpu.ops.ffd import SlotState, ffd_solve\n\n\n"
        "class DeviceScheduler:\n"
        "    def solve(self, steps, statics):\n"
        "        st = SlotState(kind=np.zeros(4, dtype=np.int8))\n"
        "        return ffd_solve(state=st, classes=steps, statics=statics)\n"
    )
    result = run([str(f)], use_baseline=False, rule_ids=["GL501"])
    assert len(result.new) == 1


def test_gl503_skips_call_form_jit_interior(tmp_path):
    """The traced-interior exclusion covers call-form jit wrapping
    (`solve = jax.jit(_impl)`), not just decorators — that interior is
    GL101's territory, and GL503 must not double-report there."""
    d = tmp_path / "ops"
    d.mkdir()
    f = d / "call_form.py"
    f.write_text(
        "import jax\n"
        "import numpy as np\n"
        "from karpenter_core_tpu.parallel import mesh as pmesh\n\n\n"
        "def _impl(plane, mesh):\n"
        "    sharded = jax.device_put(plane, pmesh.axis_sharding(mesh, 2, 0))\n"
        "    return np.asarray(sharded)\n\n\n"
        "solve = jax.jit(_impl)\n"
    )
    result = run([str(f)], use_baseline=False, rule_ids=["GL503"])
    assert result.ok, "\n".join(fi.render() for fi, _ in result.new)


def test_dataflow_memo_does_not_grow_across_runs():
    """prov() queries from later re-parses memoize under weak keys: once
    the caller's parse is freed, the entries evict — repeated lint runs
    in one process must not grow the cached index's memos (editor
    integrations, the tier-1 gate)."""
    import gc

    from tools.graftlint import dataflow

    paths = ["karpenter_core_tpu/models", "karpenter_core_tpu/ops",
             "karpenter_core_tpu/parallel"]
    run(paths, use_baseline=False, rule_ids=["GL501", "GL503"])
    gc.collect()
    sizes = []
    for _ in range(3):
        run(paths, use_baseline=False, rule_ids=["GL501", "GL503"])
        gc.collect()
        sizes.append(max(len(df._envs) for df in dataflow._CACHE.values()))
    assert sizes[0] == sizes[-1], f"memo grew across runs: {sizes}"


# -- rangecheck / ISSUE 11 regressions ---------------------------------------


def test_retro_detection_gl601_evictable_priority_store():
    """Acceptance pin: the PR 10 bug shape — an unclamped int64 wire
    priority stored into the int32 EvPlanes plane — fires GL601."""
    result = run(
        [str(FIXTURES / "solver" / "gl601_bad.py")],
        use_baseline=False,
        rule_ids=["GL601"],
    )
    assert result.new, "the retro PR 10 fixture must fire GL601"
    assert "int32" in result.new[0][0].message


def test_retro_detection_gl304_journal_io_under_grant():
    """Acceptance pin: journal file I/O between await_grant and release
    (the PR 8/9 review finding) fires GL304."""
    result = run(
        [str(FIXTURES / "gl304_bad.py")],
        use_baseline=False,
        rule_ids=["GL304"],
    )
    held = {f.message.split("while ")[1].split(" is held")[0]
            for f, _ in result.new}
    assert "the exclusive device grant" in held
    assert "_state_lock" in held


def test_retro_detection_gl701_gateway_coalescer_abba():
    """Acceptance pin (ISSUE 19): the two-lock ABBA shape from the
    gateway/coalescer seam — each object calls into the other under its
    own lock — fires GL701 with the full cycle in the message."""
    result = run(
        [str(FIXTURES / "solver" / "gl701_bad.py")],
        use_baseline=False,
        rule_ids=["GL701"],
    )
    assert result.new, "the retro ABBA fixture must fire GL701"
    msg = result.new[0][0].message
    assert "lock-order cycle" in msg
    assert "TicketCoalescer._lock" in msg
    assert "FleetGatewayStub._lock" in msg


def test_retro_detection_gl702_daemon_cache_counter():
    """Acceptance pin (ISSUE 19): the PR 5 truthiness-adjacent
    daemon-cache shape — a handler-thread counter bump outside the
    ``_state_lock`` every other write site holds — fires GL702."""
    result = run(
        [str(FIXTURES / "solver" / "gl702_bad.py")],
        use_baseline=False,
        rule_ids=["GL702"],
    )
    assert result.new, "the retro daemon-cache fixture must fire GL702"
    msg = result.new[0][0].message
    assert "self.solves" in msg and "_state_lock" in msg
    assert "spawned thread" in msg


def test_rangecheck_clean_on_tree_paths():
    """GL6xx + GL304: the solver/models/ops tree satisfies the numeric
    contracts with only the justified inline suppressions."""
    result = run(
        [
            "karpenter_core_tpu/solver",
            "karpenter_core_tpu/models",
            "karpenter_core_tpu/ops",
            "karpenter_core_tpu/utils",
            "karpenter_core_tpu/parallel",
        ],
        use_baseline=False,
        rule_ids=["GL304", "GL601", "GL602", "GL603", "GL604"],
    )
    assert result.ok, "\n".join(f.render() for f, _ in result.new)


def test_changed_only_restricts_file_scope_not_project_scope(tmp_path):
    """--changed-only semantics: file-scope rules skip unchanged files,
    project-scope rules still see (and report over) the full set."""
    d = tmp_path / "graftlint_fixtures"
    d.mkdir()
    changed = d / "gl201_changed.py"
    unchanged = d / "gl201_unchanged.py"
    src = (FIXTURES / "gl201_bad.py").read_text()
    changed.write_text(src)
    unchanged.write_text(src)

    full = run([str(d)], use_baseline=False)
    assert {f.path for f, _ in full.new if f.rule == "GL201"} == {
        str(changed), str(unchanged)
    }

    restricted = run(
        [str(d)], use_baseline=False, restrict_to={str(changed)}
    )
    flagged = {f.path for f, _ in restricted.new if f.rule == "GL201"}
    assert flagged == {str(changed)}, (
        "file-scope findings must come only from the restricted set,"
        f" got {flagged}"
    )


def test_changed_relpaths_returns_py_set():
    from tools.graftlint.engine import changed_relpaths

    changed = changed_relpaths("HEAD")
    assert isinstance(changed, set)
    assert all(p.endswith(".py") for p in changed)


def test_project_verdict_cache_roundtrip(tmp_path):
    """The project-scope verdict cache: a warm identical run reproduces
    the project findings without re-running the rules, and any file edit
    busts it."""
    cache = tmp_path / "cache.json"
    # exercised against the real (in-repo) fixture dir: out-of-repo tmp
    # paths deliberately bypass the project cache key
    cold = run([str(FIXTURES)], use_baseline=False, cache_path=cache)
    data = json.loads(cache.read_text())
    assert "__project__" in data
    warm = run([str(FIXTURES)], use_baseline=False, cache_path=cache)
    assert [(fi, s) for fi, s in warm.new] == [(fi, s) for fi, s in cold.new]
    assert warm.suppressed == cold.suppressed
    # a rule-hash change must bust the project verdict too
    import tools.graftlint.engine as engine
    old = engine._rules_hash
    engine._RULES_HASH = None
    try:
        engine._rules_hash = lambda: "different"
        busted = run([str(FIXTURES)], use_baseline=False, cache_path=cache)
        assert [(fi, s) for fi, s in busted.new] == [
            (fi, s) for fi, s in cold.new
        ]
    finally:
        engine._rules_hash = old
        engine._RULES_HASH = None
