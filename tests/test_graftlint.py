"""Tier-1 enforcement + per-rule unit tests for tools/graftlint.

Two jobs:

1. ``test_tree_is_clean`` runs the full engine over ``karpenter_core_tpu/``
   and fails on ANY unsuppressed finding — the invariants the rules encode
   (canonical encode order, jit purity, lock discipline, wire/metric
   parity) become CI properties of every future diff.
2. The fixture battery proves each rule FIRES on its bad fixture and stays
   quiet on the good one, so a refactor of the engine cannot silently turn
   a rule into a no-op (a linter that never fires passes every tree).
"""
from __future__ import annotations

import json
import time
from pathlib import Path

import pytest

from tools.graftlint import RULES, run
from tools.graftlint.engine import (
    BASELINE_PATH,
    LINT_BUDGET_SECONDS,
    REPO_ROOT,
    write_baseline,
)

FIXTURES = Path(__file__).parent / "graftlint_fixtures"


def _fixture_pairs():
    pairs = []
    for bad in sorted(FIXTURES.rglob("*_bad*.py")):
        rule = bad.name.split("_")[0].upper()
        good_matches = sorted(
            FIXTURES.rglob(f"{rule.lower()}_good*.py")
        )
        assert good_matches, f"no good fixture for {rule}"
        # a rule may ship several bad/good pairs (e.g. the GL302 base pair
        # plus the fair-queue-shaped pair): prefer the good twin with the
        # matching suffix so every good fixture is actually exercised
        twin = bad.with_name(bad.name.replace("_bad", "_good"))
        good = twin if twin in good_matches else good_matches[0]
        pairs.append((rule, bad, good))
    return pairs


_PAIRS = _fixture_pairs()


# -- tier-1 gate -----------------------------------------------------------


def test_tree_is_clean():
    t0 = time.perf_counter()
    result = run(["karpenter_core_tpu"])
    elapsed = time.perf_counter() - t0
    rendered = "\n".join(f.render() for f, _src in result.new)
    assert result.ok, (
        f"graftlint found new violations:\n{rendered}\n"
        "fix them, or add an inline '# graftlint: disable=RULE -- why'"
    )
    # the lint pass must stay cheap enough to run on every test invocation
    assert elapsed < LINT_BUDGET_SECONDS, (
        f"graftlint took {elapsed:.1f}s (budget {LINT_BUDGET_SECONDS}s)"
    )


def test_rule_inventory():
    """At least 8 rules across the four invariant families."""
    run([str(FIXTURES / "gl000_good.py")])  # force registration
    ids = set(RULES)
    assert len(ids) >= 8, f"only {len(ids)} rules registered: {sorted(ids)}"
    families = {rid[:3] for rid in ids if rid != "GL000"}
    assert {"GL1", "GL2", "GL3", "GL4"} <= families, (
        "expected jax-purity (GL1xx), determinism (GL2xx), concurrency"
        f" (GL3xx) and parity (GL4xx) families, got {sorted(families)}"
    )


def test_baseline_is_frozen_empty():
    """Repo policy (ISSUE 4): no baselined debt for the shipped families —
    violations are fixed or inline-justified, never parked."""
    data = json.loads(BASELINE_PATH.read_text())
    assert data == {"entries": {}}


# -- per-rule fixtures -----------------------------------------------------


@pytest.mark.parametrize(
    "rule,bad,good", _PAIRS, ids=[p[0] for p in _PAIRS]
)
def test_rule_fires_on_bad_fixture(rule, bad, good):
    result = run([str(bad)], use_baseline=False, rule_ids=[rule])
    assert result.new, f"{rule} did not fire on {bad.name}"
    assert all(f.rule == rule for f, _ in result.new)


@pytest.mark.parametrize(
    "rule,bad,good", _PAIRS, ids=[p[0] for p in _PAIRS]
)
def test_rule_quiet_on_good_fixture(rule, bad, good):
    result = run([str(good)], use_baseline=False, rule_ids=[rule])
    rendered = "\n".join(f.render() for f, _ in result.new)
    assert not result.new, f"{rule} over-fired on {good.name}:\n{rendered}"


def test_every_rule_has_a_failing_fixture():
    covered = {rule for rule, _b, _g in _PAIRS}
    run([str(FIXTURES / "gl000_good.py")])  # force registration
    missing = set(RULES) - covered - {"GL000"}
    assert not missing, (
        f"rules without a bad fixture proving they fire: {sorted(missing)}"
    )
    assert "GL000" in covered  # the suppression-hygiene meta rule too


# -- suppression + baseline mechanics --------------------------------------


def test_inline_suppression_silences_and_is_counted():
    result = run(
        [str(FIXTURES / "gl000_good.py")],
        use_baseline=False,
        rule_ids=["GL201"],
    )
    assert not result.new
    assert len(result.suppressed) == 1


def test_suppression_without_justification_is_flagged():
    result = run(
        [str(FIXTURES / "gl000_bad.py")],
        use_baseline=False,
        rule_ids=["GL000", "GL201"],
    )
    assert [f.rule for f, _ in result.new] == ["GL000"]
    # the (unjustified) disable still silences the underlying finding;
    # GL000 is what forces the justification to appear
    assert len(result.suppressed) == 1


def test_baseline_roundtrip(tmp_path):
    """--baseline freezes current findings; a rerun against that file is
    clean; the baseline does NOT absorb findings on new lines."""
    bad = FIXTURES / "gl201_bad.py"
    fresh = run([str(bad)], use_baseline=False, rule_ids=["GL201"])
    assert fresh.new
    bl = tmp_path / "baseline.json"
    write_baseline(fresh, bl)
    again = run(
        [str(bad)], use_baseline=True, rule_ids=["GL201"], baseline_path=bl
    )
    assert not again.new
    assert len(again.baselined) == len(fresh.new)

    # a NEW copy of the same violations in another file is not absorbed
    # (the dir name keeps the clone inside GL201's fixture scope)
    clone_dir = tmp_path / "graftlint_fixtures"
    clone_dir.mkdir()
    clone = clone_dir / "gl201_clone.py"
    clone.write_text(bad.read_text())
    grown = run(
        [str(clone)], use_baseline=True, rule_ids=["GL201"], baseline_path=bl
    )
    assert grown.new, "baseline must not absorb violations in new files"


def test_cli_exit_codes(tmp_path):
    from tools.graftlint.engine import main

    assert main([str(FIXTURES / "gl201_good.py"), "--rule", "GL201"]) == 0
    assert main([str(FIXTURES / "gl201_bad.py"), "--rule", "GL201"]) == 1


def test_repo_paths_resolve_relative_to_root():
    """The default path works no matter the CWD (engine anchors on the
    repo root, so CI and `python -m` from anywhere agree)."""
    assert (REPO_ROOT / "karpenter_core_tpu").is_dir()
