"""The client seam and the solver wire codec (VERDICT r3 missing #1:
"a client abstraction that could ever be pointed at a real apiserver",
plus the snapshot codec for a gRPC-hosted solver).
"""
import numpy as np

from tests.helpers import make_nodepool, make_pod

from karpenter_core_tpu.kube.client import KubeClient
from karpenter_core_tpu.kube.store import KubeStore
from karpenter_core_tpu.solver import codec
from karpenter_core_tpu.solver.snapshot import encode_snapshot


class TestKubeClientProtocol:
    def test_store_satisfies_protocol(self):
        assert isinstance(KubeStore(), KubeClient)

    def test_minimal_third_party_impl_passes(self):
        # a skeleton adapter (what a kubernetes-client shim provides)
        class Adapter:
            def create(self, obj): ...
            def get(self, cls, name, namespace="default"): ...
            def update(self, obj): ...
            def delete(self, obj): ...
            def watch(self, fn): ...
            def list_pods(self): ...
            def list_nodes(self): ...
            def list_nodeclaims(self): ...
            def list_nodepools(self): ...
            def list_daemonsets(self): ...
            def list_volume_attachments(self): ...
            def list_pdbs(self): ...
            def get_node_by_provider_id(self, provider_id): ...
            def bind(self, pod, node_name): ...
            def evict(self, pod): ...

        assert isinstance(Adapter(), KubeClient)

    def test_operator_accepts_protocol_impl(self):
        # the operator + controllers type against the seam: a store-backed
        # run is just one implementation choice
        from tests.test_e2e import new_operator

        op = new_operator()
        assert isinstance(op.kube, KubeClient)
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="p0"))
        op.run_until_idle()
        assert all(p.node_name for p in op.kube.list_pods())


class TestSerialContainers:
    def test_frozenset_roundtrips_hashable(self):
        """serial.py's docstring promise: frozen dataclass fields stay
        hashable through the wire — frozenset must NOT decode to set."""
        from karpenter_core_tpu.kube import serial

        value = frozenset({"a", "b"})
        decoded = serial.decode(serial.encode(value))
        assert decoded == value
        assert isinstance(decoded, frozenset)
        hash(decoded)  # the actual contract: usable as a dict key
        # plain sets keep their own tag (mutable on arrival)
        plain = serial.decode(serial.encode({"x", "y"}))
        assert plain == {"x", "y"}
        assert isinstance(plain, set) and not isinstance(plain, frozenset)

    def test_frozen_dataclass_field_roundtrip(self):
        # NodeSelectorRequirement is the frozen in-tree carrier: its values
        # ride as a tuple; frozensets inside registered objects must come
        # back frozen too
        from karpenter_core_tpu.api.objects import NodeSelectorRequirement
        from karpenter_core_tpu.kube import serial

        req = NodeSelectorRequirement("zone", "In", ("a", "b"))
        back = serial.decode(serial.encode(req))
        assert back == req
        hash(back)


class TestSnapshotCodec:
    def test_request_roundtrip(self):
        from karpenter_core_tpu.cloudprovider.kwok import build_catalog

        catalog = build_catalog(cpu_grid=[1, 2, 4], mem_factors=[2])
        pods = [make_pod(cpu=0.5, name=f"p{i}") for i in range(6)]
        pods += [
            make_pod(cpu=1.0, name=f"z{i}", zone_in=["zone-a"])
            for i in range(3)
        ]
        snap, _, _ = encode_snapshot(pods, catalog)
        data = codec.encode_request(
            snap.vocab,
            snap.resource_names,
            snap.class_masks,
            snap.class_requests,
            snap.class_counts,
            snap.it_masks,
            snap.it_allocatable,
        )
        assert isinstance(data, bytes) and len(data) > 0
        (
            vocab2,
            resource_names2,
            class_masks2,
            class_requests2,
            class_counts2,
            it_masks2,
            it_alloc2,
        ) = codec.decode_request(data)
        assert resource_names2 == snap.resource_names
        assert vocab2.keys == snap.vocab.keys
        assert vocab2.value_names == snap.vocab.value_names
        np.testing.assert_array_equal(vocab2.int_values, snap.vocab.int_values)
        np.testing.assert_array_equal(class_masks2.mask, snap.class_masks.mask)
        np.testing.assert_array_equal(
            class_masks2.defines, snap.class_masks.defines
        )
        np.testing.assert_array_equal(class_requests2, snap.class_requests)
        np.testing.assert_array_equal(class_counts2, snap.class_counts)
        np.testing.assert_array_equal(it_masks2.gt, snap.it_masks.gt)
        np.testing.assert_array_equal(it_alloc2, snap.it_allocatable)

    def test_response_roundtrip(self):
        takes = np.arange(12, dtype=np.int32).reshape(3, 4)
        unplaced = np.array([0, 1, 0], dtype=np.int32)
        slot_template = np.array([-1, 0, 0, 1], dtype=np.int32)
        t2, u2, s2 = codec.decode_response(
            codec.encode_response(takes, unplaced, slot_template)
        )
        np.testing.assert_array_equal(t2, takes)
        np.testing.assert_array_equal(u2, unplaced)
        np.testing.assert_array_equal(s2, slot_template)
