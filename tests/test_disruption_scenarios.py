"""Ported reference disruption scenario blocks: candidate gating, budget
counting, disruption cost, taint hygiene.

Re-expresses the candidate/budget/cost families of the reference's
disruption suite (pkg/controllers/disruption/suite_test.go:654-1833 and
types.go:71-117 gates, helpers.go:197-245 budget mapping,
utils/disruption/disruption.go:37-79 costs) against the operator-driven
stack: provision real nodes, mutate the state the gate reads, and assert
whether `get_candidates` still yields them.
"""
import pytest

from tests.helpers import make_nodepool, make_pod
from tests.test_disruption import new_operator, provision, replicated

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.nodepool import Budget
from karpenter_core_tpu.api.objects import Node, Pod
from karpenter_core_tpu.controllers.disruption.helpers import (
    build_disruption_budget_mapping,
    get_candidates,
)
from karpenter_core_tpu.utils import disruption as disutil


def candidates(op):
    return get_candidates(
        op.clock, op.cluster, op.kube, op.cloud_provider, lambda c: True
    )


def one_node_cluster(op=None):
    op = op or new_operator()
    provision(op, [make_pod(cpu=1.0, name="w0", labels={"app": "web"})])
    assert len(op.kube.list_nodes()) == 1
    return op


class TestCandidateGating:
    def test_healthy_node_is_a_candidate(self):
        op = one_node_cluster()
        assert len(candidates(op)) == 1

    def test_do_not_disrupt_pod_blocks(self):
        op = one_node_cluster()
        pod = op.kube.get(Pod, "w0")
        pod.metadata.annotations[L.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        op.kube.update(pod)
        assert candidates(op) == []

    def test_do_not_disrupt_daemonset_pod_blocks(self):
        op = one_node_cluster()
        node = op.kube.list_nodes()[0]
        ds = make_pod(cpu=0.1, name="ds0")
        ds.is_daemonset = True
        ds.metadata.annotations[L.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        ds.node_name = node.name
        ds.phase = "Running"
        op.kube.create(ds)
        op.reconcile_once(disrupt=False)
        assert candidates(op) == []

    def test_do_not_disrupt_mirror_pod_blocks(self):
        op = one_node_cluster()
        node = op.kube.list_nodes()[0]
        mirror = make_pod(cpu=0.1, name="m0")
        mirror.is_mirror = True
        mirror.metadata.annotations[L.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        mirror.node_name = node.name
        mirror.phase = "Running"
        op.kube.create(mirror)
        op.reconcile_once(disrupt=False)
        assert candidates(op) == []

    def test_do_not_disrupt_on_node_blocks(self):
        # suite_test.go:1234 — the NODE-level annotation gates too
        op = one_node_cluster()
        node = op.kube.list_nodes()[0]
        node.metadata.annotations[L.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        op.kube.update(node)
        assert candidates(op) == []

    def test_fully_blocking_pdb_blocks(self):
        from tests.test_pdb import make_pdb

        op = one_node_cluster()
        op.kube.create(make_pdb(min_available=1, app="web"))
        op.reconcile_once(disrupt=False)
        assert candidates(op) == []

    def test_pdb_on_mirror_pods_does_not_block(self):
        # suite_test.go:1340 — mirror pods never hit the eviction API, so a
        # PDB matching only them cannot gate the candidate
        from tests.test_pdb import make_pdb

        op = new_operator()
        provision(op, [make_pod(cpu=1.0, name="w0")])
        node = op.kube.list_nodes()[0]
        mirror = make_pod(cpu=0.1, name="m0", labels={"app": "static"})
        mirror.is_mirror = True
        mirror.node_name = node.name
        mirror.phase = "Running"
        op.kube.create(mirror)
        op.kube.create(make_pdb(min_available=1, app="static"))
        op.reconcile_once(disrupt=False)
        assert len(candidates(op)) == 1

    def test_nominated_node_not_considered(self):
        op = one_node_cluster()
        sn = op.cluster.nodes()[0]
        sn.nominate(op.clock.now() + 60.0)
        assert candidates(op) == []

    def test_marked_for_deletion_not_considered(self):
        op = one_node_cluster()
        sn = op.cluster.nodes()[0]
        sn.marked_for_deletion = True
        assert candidates(op) == []

    def test_deleting_node_not_considered(self):
        op = one_node_cluster()
        node = op.kube.list_nodes()[0]
        node.metadata.deletion_timestamp = op.clock.now()
        op.kube.update(node)
        assert candidates(op) == []

    def test_unknown_nodepool_not_considered(self):
        op = one_node_cluster()
        node = op.kube.list_nodes()[0]
        node.metadata.labels[L.NODEPOOL_LABEL_KEY] = "ghost-pool"
        op.kube.update(node)
        op.reconcile_once(disrupt=False)
        assert candidates(op) == []

    def test_unresolvable_instance_type_still_considered(self):
        # suite_test.go:1750 — candidate survives with instance_type=None
        op = one_node_cluster()
        node = op.kube.list_nodes()[0]
        node.metadata.labels[L.LABEL_INSTANCE_TYPE] = "retired-type"
        op.kube.update(node)
        op.reconcile_once(disrupt=False)
        cands = candidates(op)
        assert len(cands) == 1
        assert cands[0].instance_type is None


class TestBudgetCounting:
    def _grow(self, op, n):
        op.kube.create(make_nodepool())
        for i in range(n):
            op.kube.create(replicated(make_pod(cpu=9.0, name=f"b{i}")))
        op.run_until_idle(disrupt=False)
        assert len(op.kube.list_nodes()) == n

    def test_percentage_budget_rounds_up_over_total(self):
        op = new_operator()
        self._grow(op, 3)
        pool = op.kube.list_nodepools()[0]
        pool.spec.disruption.budgets = [Budget(nodes="50%")]
        mapping = build_disruption_budget_mapping(
            op.clock, op.cluster, op.kube
        )
        assert mapping.remaining("default", "underutilized") == 2  # ceil(1.5)

    def test_disrupting_nodes_consume_budget(self):
        op = new_operator()
        self._grow(op, 3)
        pool = op.kube.list_nodepools()[0]
        pool.spec.disruption.budgets = [Budget(nodes="2")]
        op.cluster.nodes()[0].marked_for_deletion = True
        mapping = build_disruption_budget_mapping(
            op.clock, op.cluster, op.kube
        )
        assert mapping.remaining("default", "underutilized") == 1

    def test_budget_never_negative(self):
        op = new_operator()
        self._grow(op, 2)
        pool = op.kube.list_nodepools()[0]
        pool.spec.disruption.budgets = [Budget(nodes="1")]
        for sn in op.cluster.nodes():
            sn.marked_for_deletion = True
        mapping = build_disruption_budget_mapping(
            op.clock, op.cluster, op.kube
        )
        assert mapping.remaining("default", "underutilized") == 0

    def test_per_reason_budgets_are_separate(self):
        op = new_operator()
        self._grow(op, 4)
        pool = op.kube.list_nodepools()[0]
        pool.spec.disruption.budgets = [
            Budget(nodes="1", reasons=["Drifted"]),
            Budget(nodes="3", reasons=["Underutilized"]),
        ]
        mapping = build_disruption_budget_mapping(
            op.clock, op.cluster, op.kube
        )
        assert mapping.remaining("default", "Drifted") == 1
        assert mapping.remaining("default", "Underutilized") == 3

    def test_uninitialized_nodes_not_in_total(self):
        op = new_operator()
        self._grow(op, 2)
        # a managed claim that never initialized: its node joins the store
        # but the Initialized condition stays unset
        from karpenter_core_tpu.api.nodeclaim import NodeClaim
        from karpenter_core_tpu.api.objects import ObjectMeta

        claim = NodeClaim(metadata=ObjectMeta(
            name="stray-claim", labels={L.NODEPOOL_LABEL_KEY: "default"}
        ))
        claim.status.provider_id = "stray-pid"
        op.kube.create(claim)
        op.kube.create(Node(
            metadata=ObjectMeta(
                name="stray", labels={L.NODEPOOL_LABEL_KEY: "default"}
            ),
            provider_id="stray-pid",
        ))
        op.cluster.sync()
        pool = op.kube.list_nodepools()[0]
        pool.spec.disruption.budgets = [Budget(nodes="50%")]
        mapping = build_disruption_budget_mapping(
            op.clock, op.cluster, op.kube
        )
        # ceil(0.5 x 2 initialized) = 1, the stray never counted
        assert mapping.remaining("default", "underutilized") == 1


class TestDisruptionCost:
    def test_standard_cost(self):
        assert disutil.eviction_cost(make_pod(cpu=1.0)) == 1.0

    def test_deletion_cost_annotation_raises_cost(self):
        lo = make_pod(cpu=1.0)
        hi = make_pod(cpu=1.0)
        hi.metadata.annotations[disutil.POD_DELETION_COST_ANNOTATION] = "10000"
        assert disutil.eviction_cost(hi) > disutil.eviction_cost(lo)

    def test_negative_deletion_cost_lowers(self):
        lo = make_pod(cpu=1.0)
        lo.metadata.annotations[disutil.POD_DELETION_COST_ANNOTATION] = "-10000"
        assert disutil.eviction_cost(lo) < 1.0

    def test_priority_raises_cost_and_clamps(self):
        hi = make_pod(cpu=1.0)
        hi.priority = 2**25  # one cost unit over base
        assert disutil.eviction_cost(hi) == 2.0
        vast = make_pod(cpu=1.0)
        vast.priority = 10**10
        # priority term clamps to +8 (base 1.0 -> 9.0), leaving headroom
        # under the 10.0 ceiling so deletion costs still order critical pods
        assert disutil.eviction_cost(vast) == 9.0

    def test_expiring_soon_costs_less(self):
        from karpenter_core_tpu.api.duration import NillableDuration

        op = one_node_cluster()
        (cand,) = candidates(op)
        baseline = cand.disruption_cost
        claim = op.kube.list_nodeclaims()[0]
        claim.spec.expire_after = NillableDuration(1000.0)
        op.clock.step(900.0)  # 90% of lifetime burned
        (aged,) = candidates(op)
        assert aged.disruption_cost < baseline


class TestTaintHygiene:
    def test_stale_disruption_taint_removed_on_restart(self):
        """controller.go:127-141: a taint from an interrupted command (the
        restarted operator has no in-flight record of it) is removed."""
        from karpenter_core_tpu.scheduling.taints import (
            DISRUPTED_NO_SCHEDULE_TAINT,
        )

        op = one_node_cluster()
        node = op.kube.list_nodes()[0]
        node.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
        op.kube.update(node)
        assert not op.disruption.in_flight  # "restarted": no command memory
        op.disruption.reconcile()
        fresh = op.kube.get(Node, node.name)
        assert all(
            t.key != DISRUPTED_NO_SCHEDULE_TAINT.key for t in fresh.taints
        )

    def test_active_command_taint_survives(self):
        from karpenter_core_tpu.scheduling.taints import (
            DISRUPTED_NO_SCHEDULE_TAINT,
        )

        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=9.0, name="d0")))
        op.run_until_idle(disrupt=False)
        pool = op.kube.list_nodepools()[0]
        pool.spec.template.labels["drifted"] = "yes"
        op.kube.update(pool)
        op.run_until_idle(disrupt=False)  # matures the Drifted condition
        op.disruption.reconcile()  # drift command: taints + launches
        assert op.disruption.in_flight
        node = next(
            n for n in op.kube.list_nodes()
            if any(t.key == DISRUPTED_NO_SCHEDULE_TAINT.key for t in n.taints)
        )
        op.disruption.reconcile()  # next poll must NOT untaint it
        fresh = op.kube.get(Node, node.name)
        assert any(
            t.key == DISRUPTED_NO_SCHEDULE_TAINT.key for t in fresh.taints
        )


class TestBudgetedConsolidation:
    """consolidation_test.go:247-366 — budgets bound each decision type."""

    def _empty_nodes(self, op, n, budget):
        pool = make_nodepool()
        pool.spec.disruption.budgets = [Budget(nodes=budget)]
        op.kube.create(pool)
        pods = [replicated(make_pod(cpu=9.0, name=f"e{i}")) for i in range(n)]
        for p in pods:
            op.kube.create(p)
        op.run_until_idle(disrupt=False)
        assert len(op.kube.list_nodes()) == n
        for p in pods:
            fresh = op.kube.get(Pod, p.name)
            fresh.metadata.owner_references = []
            op.kube.delete(fresh)
        op.clock.step(40.0)  # matures Consolidatable

    def test_empty_disruption_honors_node_budget(self):
        op = new_operator()
        self._empty_nodes(op, 5, budget="3")
        op.disruption.reconcile()  # one emptiness command, budget-bounded
        pending = op.disruption.pending
        assert pending, "no emptiness command computed"
        assert len(pending[0].command.candidates) == 3

    def test_empty_disruption_budget_zero_blocks_all(self):
        op = new_operator()
        self._empty_nodes(op, 4, budget="0")
        op.clock.step(100.0)
        op.run_until_idle()
        assert len(op.kube.list_nodes()) == 4  # nothing disrupted

    def test_empty_disruption_full_budget_allows_all(self):
        op = new_operator()
        self._empty_nodes(op, 4, budget="100%")
        op.run_until_idle()
        assert len(op.kube.list_nodes()) == 0

    def test_budgets_apply_per_nodepool(self):
        # consolidation_test.go:414 — 2 from each pool
        op = new_operator()
        pods = []
        for pool_name in ("alpha", "beta"):
            pool = make_nodepool(pool_name)
            pool.spec.disruption.budgets = [Budget(nodes="2")]
            pool.spec.template.labels["pool"] = pool_name
            op.kube.create(pool)
            for i in range(3):
                p = replicated(make_pod(
                    cpu=9.0, name=f"{pool_name}{i}",
                    node_selector={"pool": pool_name},
                ))
                pods.append(p)
                op.kube.create(p)
        op.run_until_idle(disrupt=False)
        assert len(op.kube.list_nodes()) == 6
        for p in pods:
            fresh = op.kube.get(Pod, p.name)
            fresh.metadata.owner_references = []
            op.kube.delete(fresh)
        op.clock.step(40.0)
        op.disruption.reconcile()
        pending = op.disruption.pending
        assert pending
        from collections import Counter

        per_pool = Counter(
            c.nodepool.name for p in pending for c in p.command.candidates
        )
        assert per_pool == {"alpha": 2, "beta": 2}

    def test_budget_blocked_cluster_recovers_when_budget_opens(self):
        # consolidation_test.go:608 family — a budget-starved cluster must
        # keep polling and act the moment the budget allows
        op = new_operator()
        self._empty_nodes(op, 2, budget="0")
        op.run_until_idle()
        assert len(op.kube.list_nodes()) == 2  # starved
        pool = op.kube.list_nodepools()[0]
        pool.spec.disruption.budgets = [Budget(nodes="100%")]
        op.kube.update(pool)
        op.run_until_idle()
        assert len(op.kube.list_nodes()) == 0


class TestConsolidationEconomics:
    def test_wont_replace_when_replacement_not_cheaper(self):
        """consolidation_test.go:2048/2132 — a right-sized node stays."""
        op = new_operator()
        op.kube.create(make_nodepool())
        # fills its node well: replacement would be the same type
        op.kube.create(replicated(make_pod(cpu=14.0, name="full")))
        op.run_until_idle(disrupt=False)
        nodes = len(op.kube.list_nodes())
        op.clock.step(40.0)
        op.run_until_idle()
        assert len(op.kube.list_nodes()) == nodes
        assert all(p.node_name for p in op.kube.list_pods())

    def test_replaces_oversized_node_with_cheaper(self):
        from karpenter_core_tpu.api.objects import NodeSelectorRequirement

        op = new_operator()
        # on-demand pool: a spot node would instead hit the spot-to-spot
        # gate (disabled by default, consolidation.go:48-49)
        op.kube.create(make_nodepool(requirements=[NodeSelectorRequirement(
            L.CAPACITY_TYPE_LABEL_KEY, "In", (L.CAPACITY_TYPE_ON_DEMAND,))]))
        big = replicated(make_pod(cpu=14.0, name="big"))
        keeper = replicated(make_pod(cpu=0.4, name="keeper"))
        op.kube.create(big)
        op.kube.create(keeper)
        op.run_until_idle(disrupt=False)
        before = {n.name for n in op.kube.list_nodes()}
        # the big pod leaves; its node is now oversized for the keeper
        fresh = op.kube.get(Pod, "big")
        fresh.metadata.owner_references = []
        op.kube.delete(fresh)
        op.clock.step(40.0)
        op.run_until_idle()
        after = op.kube.list_nodes()
        assert all(p.node_name for p in op.kube.list_pods())
        # consolidated: fewer nodes, or the remaining capacity shrank
        total_cpu = sum(n.status.capacity.get("cpu", 0.0) for n in after)
        assert total_cpu < 16.0 or {n.name for n in after} != before

    def test_when_empty_policy_skips_underutilized(self):
        op = new_operator()
        pool = make_nodepool()
        pool.spec.disruption.consolidation_policy = "WhenEmpty"
        op.kube.create(pool)
        op.kube.create(replicated(make_pod(cpu=9.0, name="big")))
        op.kube.create(replicated(make_pod(cpu=0.3, name="small")))
        op.run_until_idle(disrupt=False)
        nodes = len(op.kube.list_nodes())
        big = op.kube.get(Pod, "big")
        big.metadata.owner_references = []
        op.kube.delete(big)
        op.clock.step(40.0)
        op.run_until_idle()
        # node is underutilized but NOT empty: WhenEmpty leaves it
        assert len(op.kube.list_nodes()) == nodes


class TestEmptiness:
    """Ported emptiness family (emptiness_test.go): what counts as empty,
    the consolidatable gate, and the TTL wait."""

    def _emptyable(self, op=None, consolidate_after=0.0):
        from karpenter_core_tpu.api.duration import NillableDuration

        op = op or new_operator()
        pool = make_nodepool()
        pool.spec.disruption.consolidate_after = NillableDuration(
            consolidate_after
        )
        op.kube.create(pool)
        p = replicated(make_pod(cpu=1.0, name="only"))
        op.kube.create(p)
        op.run_until_idle(disrupt=False)
        assert len(op.kube.list_nodes()) == 1
        fresh = op.kube.get(Pod, "only")
        fresh.metadata.owner_references = []
        op.kube.delete(fresh)
        return op

    def test_deletes_empty_node(self):
        op = self._emptyable()
        op.clock.step(40.0)
        op.run_until_idle()
        assert op.kube.list_nodes() == []
        assert op.kube.list_nodeclaims() == []

    def test_node_with_pods_is_not_empty(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="keeper")))
        op.run_until_idle(disrupt=False)
        op.clock.step(40.0)
        op.run_until_idle()
        # the keeper pod's node survives (single node: consolidation has
        # nowhere cheaper either)
        assert len(op.kube.list_nodes()) == 1

    def test_daemonset_only_node_is_empty(self):
        op = self._emptyable()
        node = op.kube.list_nodes()[0]
        ds = make_pod(cpu=0.1, name="ds0")
        ds.is_daemonset = True
        ds.node_name = node.name
        ds.phase = "Running"
        op.kube.create(ds)
        op.clock.step(40.0)
        op.run_until_idle()
        assert op.kube.list_nodes() == []

    def test_waits_for_consolidate_after_ttl(self):
        op = self._emptyable(consolidate_after=600.0)
        op.clock.step(40.0)
        for _ in range(5):
            op.reconcile_once()
        assert len(op.kube.list_nodes()) == 1  # inside the window
        op.clock.step(600.0)
        op.run_until_idle()
        assert op.kube.list_nodes() == []

    def test_consolidate_after_never_blocks_emptiness(self):
        from karpenter_core_tpu.api.duration import NillableDuration

        op = new_operator()
        pool = make_nodepool()
        pool.spec.disruption.consolidate_after = NillableDuration(None)
        op.kube.create(pool)
        p = replicated(make_pod(cpu=1.0, name="only"))
        op.kube.create(p)
        op.run_until_idle(disrupt=False)
        fresh = op.kube.get(Pod, "only")
        fresh.metadata.owner_references = []
        op.kube.delete(fresh)
        op.clock.step(3600.0)
        op.run_until_idle()
        assert len(op.kube.list_nodes()) == 1  # Never: no consolidation

    def test_do_not_disrupt_node_annotation_blocks_emptiness(self):
        op = self._emptyable()
        node = op.kube.list_nodes()[0]
        node.metadata.annotations[L.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        op.kube.update(node)
        op.clock.step(40.0)
        op.run_until_idle()
        assert len(op.kube.list_nodes()) == 1

    def test_pending_pods_reuse_empty_node_instead_of_new(self):
        # "considers pending pods when consolidating": a pending pod that
        # fits the empty node keeps it alive (nominated) rather than
        # deleting + relaunching
        op = self._emptyable()
        op.kube.create(replicated(make_pod(cpu=1.0, name="reuser")))
        op.run_until_idle()
        assert len(op.kube.list_nodes()) == 1
        assert op.kube.get(Pod, "reuser").node_name
