"""Volume subsystem: PVC zone injection, CSI attach limits, detach-wait
(reference: volumetopology.go:42-196, volumeusage.go:44-229,
node/termination/controller.go:140-143,190-237).
"""
import pytest

from tests.helpers import GIB, make_nodepool, make_pod
from tests.test_e2e import new_operator, replicated

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.objects import (
    CSINode,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    PodVolume,
    StorageClass,
    VolumeAttachment,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.volumetopology import (
    VolumeTopology,
)
from karpenter_core_tpu.scheduling.volumeusage import VolumeUsage, get_volumes


def make_zonal_pv(name: str, zone: str, driver: str = "ebs.csi.aws.com"):
    return PersistentVolume(
        metadata=ObjectMeta(name=name),
        node_affinity_required=[
            NodeSelectorTerm(match_expressions=(
                NodeSelectorRequirement(L.LABEL_TOPOLOGY_ZONE, "In", (zone,)),
            ))
        ],
        csi_driver=driver,
    )


def make_pvc(name: str, volume_name: str = "", storage_class: str = None):
    return PersistentVolumeClaim(
        metadata=ObjectMeta(name=name),
        storage_class_name=storage_class,
        volume_name=volume_name,
    )


def pod_with_pvc(name: str, pvc: str, cpu: float = 1.0):
    p = make_pod(cpu=cpu, name=name)
    p.volumes = [PodVolume(name="data", pvc_name=pvc)]
    return p


class TestVolumeTopologyInjection:
    def test_bound_pv_zone_injected(self):
        op = new_operator()
        op.kube.create(make_zonal_pv("pv-b", "zone-b"))
        op.kube.create(make_pvc("claim-b", volume_name="pv-b"))
        vt = VolumeTopology(op.kube)
        p = pod_with_pvc("p1", "claim-b")
        vt.inject(p)
        assert any(
            r.key == L.LABEL_TOPOLOGY_ZONE and r.values == ("zone-b",)
            for r in p.volume_requirements
        )
        # idempotent: re-inject replaces, never accumulates
        vt.inject(p)
        assert len(p.volume_requirements) == 1

    def test_storage_class_topology_injected(self):
        op = new_operator()
        op.kube.create(StorageClass(
            metadata=ObjectMeta(name="zonal-sc"),
            provisioner="ebs.csi.aws.com",
            allowed_topologies=[(L.LABEL_TOPOLOGY_ZONE, ("zone-c",))],
        ))
        op.kube.create(make_pvc("claim-c", storage_class="zonal-sc"))
        vt = VolumeTopology(op.kube)
        p = pod_with_pvc("p1", "claim-c")
        vt.inject(p)
        assert any(
            r.key == L.LABEL_TOPOLOGY_ZONE and r.values == ("zone-c",)
            for r in p.volume_requirements
        )

    def test_local_pv_hostname_dropped(self):
        op = new_operator()
        pv = PersistentVolume(
            metadata=ObjectMeta(name="pv-local"),
            node_affinity_required=[
                NodeSelectorTerm(match_expressions=(
                    NodeSelectorRequirement(L.LABEL_HOSTNAME, "In", ("old-node",)),
                    NodeSelectorRequirement(
                        L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a",)),
                ))
            ],
            csi_driver="",
            local=True,
        )
        op.kube.create(pv)
        op.kube.create(make_pvc("claim-l", volume_name="pv-local"))
        vt = VolumeTopology(op.kube)
        p = pod_with_pvc("p1", "claim-l")
        vt.inject(p)
        keys = {r.key for r in p.volume_requirements}
        assert L.LABEL_HOSTNAME not in keys and L.LABEL_TOPOLOGY_ZONE in keys

    def test_validation_missing_pvc(self):
        op = new_operator()
        vt = VolumeTopology(op.kube)
        assert "not found" in vt.validate_pvcs(pod_with_pvc("p1", "ghost"))

    def test_validation_dangling_storage_class(self):
        op = new_operator()
        op.kube.create(make_pvc("claim-x", storage_class="ghost-sc"))
        vt = VolumeTopology(op.kube)
        err = vt.validate_pvcs(pod_with_pvc("p1", "claim-x"))
        assert "missing storage class" in err


@pytest.mark.parametrize("solver", ["greedy", "tpu"])
class TestZonalSchedulingE2E:
    def test_zonal_pvc_pod_lands_in_its_zone(self, solver):
        # the VERDICT gap: "a zonal PVC pod will be packed into the wrong
        # zone today" — end-to-end through the operator on both solvers
        op = new_operator(solver)
        op.kube.create(make_nodepool(requirements=[NodeSelectorRequirement(
            L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a", "zone-b", "zone-c"))]))
        op.kube.create(make_zonal_pv("pv-b", "zone-b"))
        op.kube.create(make_pvc("claim-b", volume_name="pv-b"))
        op.kube.create(pod_with_pvc("zonal-pod", "claim-b"))
        for i in range(5):
            op.kube.create(make_pod(cpu=1.0, name=f"filler-{i}"))
        op.run_until_idle()
        pod = op.kube.get(type(make_pod()), "zonal-pod")
        assert pod.node_name, "zonal pod did not bind"
        node = op.kube.get(
            type(op.kube.list_nodes()[0]), pod.node_name
        )
        assert node.labels[L.LABEL_TOPOLOGY_ZONE] == "zone-b", node.labels
        # a VolumeAttachment materialized on bind
        vas = op.kube.list_volume_attachments()
        assert any(
            va.pv_name == "pv-b" and va.node_name == pod.node_name
            for va in vas
        )

    def test_unschedulable_when_zone_outside_pool(self, solver):
        op = new_operator(solver)
        op.kube.create(make_nodepool(requirements=[NodeSelectorRequirement(
            L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a",))]))
        op.kube.create(make_zonal_pv("pv-b", "zone-b"))
        op.kube.create(make_pvc("claim-b", volume_name="pv-b"))
        op.kube.create(pod_with_pvc("zonal-pod", "claim-b"))
        op.run_until_idle()
        pod = op.kube.get(type(make_pod()), "zonal-pod")
        assert not pod.node_name


class TestAttachLimits:
    def test_get_volumes_resolves_drivers(self):
        op = new_operator()
        op.kube.create(make_zonal_pv("pv-1", "zone-a", driver="csi.x"))
        op.kube.create(make_pvc("c1", volume_name="pv-1"))
        op.kube.create(StorageClass(
            metadata=ObjectMeta(name="sc-y"), provisioner="csi.y"))
        op.kube.create(make_pvc("c2", storage_class="sc-y"))
        p = make_pod(cpu=1.0, name="p")
        p.volumes = [
            PodVolume(name="a", pvc_name="c1"),
            PodVolume(name="b", pvc_name="c2"),
            PodVolume(name="c", pvc_name=None),  # emptyDir: ignored
        ]
        vols = get_volumes(op.kube, p)
        assert vols == {"csi.x": {"default/c1"}, "csi.y": {"default/c2"}}

    def test_usage_limit_and_dedupe(self):
        u = VolumeUsage()
        u.add_limit("csi.x", 2)
        u.add({"csi.x": {"default/a"}})
        assert u.exceeds_limits({"csi.x": {"default/b", "default/c"}})
        # the same claim shared by another pod doesn't double-count
        assert u.exceeds_limits({"csi.x": {"default/a", "default/b"}}) is None

    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    def test_attach_limit_pushes_pod_to_new_node(self, solver):
        # existing node with attach limit 1 and one volume already attached:
        # a second volume pod must go to a fresh node despite spare cpu
        op = new_operator(solver)
        op.kube.create(make_nodepool())
        for i in (1, 2):
            op.kube.create(make_zonal_pv(f"pv-{i}", "zone-a", driver="csi.x"))
            op.kube.create(make_pvc(f"c{i}", volume_name=f"pv-{i}"))
        op.kube.create(pod_with_pvc("vol-pod-1", "c1", cpu=0.5))
        op.run_until_idle()
        p1 = op.kube.get(type(make_pod()), "vol-pod-1")
        assert p1.node_name
        n1 = p1.node_name
        # stamp the node's CSINode with limit 1
        op.kube.create(CSINode(
            metadata=ObjectMeta(name=n1), drivers=[("csi.x", 1)]
        ))
        op.kube.create(pod_with_pvc("vol-pod-2", "c2", cpu=0.5))
        op.run_until_idle()
        p2 = op.kube.get(type(make_pod()), "vol-pod-2")
        assert p2.node_name and p2.node_name != n1, (p2.node_name, n1)


class TestDetachWait:
    def test_termination_waits_for_volume_detach(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(make_zonal_pv("pv-1", "zone-a"))
        op.kube.create(make_pvc("c1", volume_name="pv-1"))
        op.kube.create(replicated(pod_with_pvc("vol-pod", "c1")))
        op.run_until_idle()
        node = op.kube.list_nodes()[0]
        # slow CSI driver: an attachment that outlives the pod
        op.kube.create(VolumeAttachment(
            metadata=ObjectMeta(name="va-slow"),
            attacher="csi.x", node_name=node.name, pv_name="pv-1",
        ))
        op.kube.delete(node)
        op.run_until_idle()
        # drained but the attachment blocks the finalizer
        assert op.kube.get(type(node), node.name) is not None
        va = op.kube.get(VolumeAttachment, "va-slow")
        op.kube.delete(va)
        op.run_until_idle()
        assert op.kube.get(type(node), node.name) is None

    def test_nondrainable_pod_attachment_does_not_block(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(make_zonal_pv("pv-1", "zone-a"))
        op.kube.create(make_pvc("c1", volume_name="pv-1"))
        daemon = pod_with_pvc("ds-pod", "c1")
        daemon.is_daemonset = True
        op.kube.create(replicated(make_pod(cpu=0.5, name="plain")))
        op.run_until_idle()
        node = op.kube.list_nodes()[0]
        daemon.node_name = node.name
        op.kube.create(daemon)
        op.cluster  # daemon binding flows via watch on create
        op.kube.create(VolumeAttachment(
            metadata=ObjectMeta(name="va-ds"),
            attacher="csi.x", node_name=node.name, pv_name="pv-1",
        ))
        op.kube.delete(node)
        op.run_until_idle()
        # the daemonset pod's attachment is filtered out; node terminates
        assert op.kube.get(type(node), node.name) is None
