"""The multi-device solve as the PRODUCTION path (ISSUE 6).

Two layers over the existing ops-level sharded tests
(tests/test_sharded_solver.py, which hand-shard a raw ffd_solve call):

1. ``parallel/mesh.py`` hardening — slot_shardings matches SlotState
   leaves BY FIELD NAME (SLOT_STATE_SPECS), so a non-slot array whose
   leading dim coincidentally equals n_slots replicates, an unclassified
   field refuses to guess, and a mis-sized slot plane fails loudly.

2. ``DeviceScheduler(devices=N)`` end-to-end parity on the conftest-forced
   8-device virtual CPU mesh: identical node counts, identical takes
   (per-claim pod sets), and identical result WIRE BYTES vs the
   single-device path — including a slot axis that is not divisible by the
   device count (padding case), a 3-device mesh, the device topology
   kernel, and the consolidation prefix sweep.

Sizes stay small: these are correctness gates, not benchmarks (throughput
on a virtual CPU mesh is meaningless — bench.py cfg8_multidev owns that).
"""
from __future__ import annotations

from collections import namedtuple

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tests.helpers import GIB, make_nodepool, make_pod

from karpenter_core_tpu.cloudprovider.kwok import build_catalog
from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
    Topology,
)
from karpenter_core_tpu.models.provisioner import DeviceScheduler
from karpenter_core_tpu.ops.ffd import SlotState
from karpenter_core_tpu.parallel import (
    SLOT_STATE_SPECS,
    pad_to_devices,
    resolve_devices,
    slot_mesh,
    slot_shardings,
)
from karpenter_core_tpu.solver import codec

N_DEVICES = 8


def _catalog():
    return build_catalog()[:16]


def _plain_pods(n):
    return [
        make_pod(
            cpu=0.25 * (1 + i % 5),
            memory_gib=1.0 * (1 + i % 3),
            name=f"shard-{i}",
        )
        for i in range(n)
    ]


def _topo_pods(n):
    pods = []
    for i in range(n):
        kind = i % 4
        if kind == 0:
            pods.append(
                make_pod(cpu=0.25, name=f"tsp-{i}", spread_zone=True,
                         labels={"app": "zspread"})
            )
        elif kind == 1:
            pods.append(
                make_pod(cpu=0.25, name=f"tsp-{i}", spread_hostname=True,
                         labels={"app": "hspread"})
            )
        else:
            pods.append(
                make_pod(cpu=0.25 * (1 + i % 3), name=f"tsp-{i}")
            )
    return pods


def _solve(pods, max_slots, devices, existing_nodes=None):
    sched = DeviceScheduler(
        [make_nodepool()],
        {"default": _catalog()},
        existing_nodes=existing_nodes,
        max_slots=max_slots,
        devices=devices,
    )
    return sched, sched.solve(pods)


def _assert_full_parity(res_sharded, res_single):
    """Node counts, per-claim takes (pod-uid sets), and wire bytes."""
    assert res_sharded.all_pods_scheduled(), res_sharded.pod_errors
    assert res_single.all_pods_scheduled(), res_single.pod_errors
    assert res_sharded.node_count() == res_single.node_count()
    takes_sharded = sorted(
        tuple(sorted(p.uid for p in c.pods))
        for c in res_sharded.new_node_claims
    )
    takes_single = sorted(
        tuple(sorted(p.uid for p in c.pods))
        for c in res_single.new_node_claims
    )
    assert takes_sharded == takes_single
    assert codec.encode_solve_results(
        res_sharded, 0.0
    ) == codec.encode_solve_results(res_single, 0.0)


# -- parallel/mesh.py hardening (satellite 1) ------------------------------


class TestSlotShardings:
    def _tiny_state(self, n_slots=8, gz=8, k=2, v=3):
        """SlotState with Gz == n_slots: the old leading-dim heuristic
        would misclassify zcount as a slot plane."""
        z = np.zeros
        return SlotState(
            valmask=z((n_slots, k, v), bool),
            defines=z((n_slots, k), bool),
            complement=z((n_slots, k), bool),
            negative=z((n_slots, k), bool),
            gt=z((n_slots, k), np.int32),
            lt=z((n_slots, k), np.int32),
            itmask=z((n_slots, 4), bool),
            requests=z((n_slots, 2), np.float32),
            capacity=z((n_slots, 2), np.float32),
            kind=z((n_slots,), np.int8),
            template=z((n_slots,), np.int32),
            podcount=z((n_slots,), np.int32),
            next_free=np.int32(0),
            overflow=np.asarray(False),
            hcount=z((n_slots, 1), np.int32),
            zcount=z((gz, v), np.int32),  # leading dim == n_slots!
            carry=np.int32(0),
        )

    def test_every_slotstate_field_is_classified(self):
        assert set(SlotState._fields) == set(SLOT_STATE_SPECS), (
            "SlotState and parallel.mesh.SLOT_STATE_SPECS drifted apart"
        )

    def test_zcount_with_coincident_leading_dim_replicates(self):
        mesh = slot_mesh(N_DEVICES)
        sh = slot_shardings(mesh, self._tiny_state(), 8)
        assert sh.zcount.is_fully_replicated
        assert not sh.kind.is_fully_replicated
        assert sh.kind.is_equivalent_to(NamedSharding(mesh, P("slots")), 1)
        assert sh.hcount.is_equivalent_to(
            NamedSharding(mesh, P("slots", None)), 2
        )

    def test_unclassified_field_refuses_to_guess(self):
        mesh = slot_mesh(N_DEVICES)
        Fake = namedtuple("Fake", ("kind", "mystery"))
        fake = Fake(kind=np.zeros((8,), np.int8), mystery=np.zeros((8,)))
        with pytest.raises(ValueError, match="mystery"):
            slot_shardings(mesh, fake, 8)

    def test_missized_slot_plane_fails_loudly(self):
        mesh = slot_mesh(N_DEVICES)
        state = self._tiny_state()._replace(kind=np.zeros((4,), np.int8))
        with pytest.raises(ValueError, match="kind"):
            slot_shardings(mesh, state, 8)

    def test_generic_pytree_keeps_heuristic(self):
        mesh = slot_mesh(N_DEVICES)
        sh = slot_shardings(
            mesh, {"a": np.zeros((8, 2)), "b": np.zeros((3,))}, 8
        )
        assert not sh["a"].is_fully_replicated
        assert sh["b"].is_fully_replicated

    def test_pad_to_devices(self):
        assert pad_to_devices(100, 8) == 104
        assert pad_to_devices(64, 8) == 64
        assert pad_to_devices(64, 3) == 66
        assert pad_to_devices(7, 1) == 7

    def test_resolve_devices(self):
        assert resolve_devices(1) == 1
        assert resolve_devices(0) == len(jax.devices())
        assert resolve_devices(None) == len(jax.devices())
        # over-asking clamps to the box instead of crashing
        assert resolve_devices(10_000) == len(jax.devices())


# -- production-path parity (tentpole) -------------------------------------


class TestShardedProductionSolve:
    def test_init_state_lands_pre_sharded(self):
        sched = DeviceScheduler(
            [make_nodepool()], {"default": _catalog()},
            max_slots=64, devices=N_DEVICES,
        )
        prep = sched._prepare(_plain_pods(16), 64, Topology())
        mesh = sched._mesh
        expect = slot_shardings(mesh, prep.init_state, prep.n_slots)
        for field in SlotState._fields:
            leaf = getattr(prep.init_state, field)
            want = getattr(expect, field)
            if not hasattr(leaf, "sharding"):
                continue
            if want.is_fully_replicated:
                # head scalars may stay uncommitted; committed ones must
                # not be slot-sharded
                assert leaf.sharding.is_fully_replicated or (
                    len(leaf.sharding.device_set) == 1
                ), field
            else:
                assert leaf.sharding.is_equivalent_to(want, leaf.ndim), field
        # the scanned exist_taint_ok plane shards its SLOT axis (dim 1)
        steps = sched._class_steps(prep)
        assert steps.exist_taint_ok.sharding.is_equivalent_to(
            NamedSharding(mesh, P(None, "slots")), 2
        )

    def test_plain_parity_and_wire_bytes(self):
        pods = _plain_pods(120)
        s1, r1 = _solve(pods, 64, 1)
        s8, r8 = _solve(pods, 64, N_DEVICES)
        _assert_full_parity(r8, r1)
        assert s8.last_phase_stats["n_devices"] == N_DEVICES
        assert s1.last_phase_stats["n_devices"] == 1
        # per-device traffic must undercut the single-device bytes: the
        # slot planes divide across the mesh
        assert (
            s8.last_phase_stats["h2d_dev_bytes"]
            < s1.last_phase_stats["h2d_dev_bytes"]
        )
        assert (
            s8.last_phase_stats["fetch_dev_bytes"]
            < s1.last_phase_stats["fetch_dev_bytes"]
        )

    def test_padded_slot_axis_parity(self):
        """n_slots not divisible by n_devices: 100 -> 104 on the mesh."""
        pods = _plain_pods(120)
        _, r1 = _solve(pods, 100, 1)
        s8, r8 = _solve(pods, 100, N_DEVICES)
        assert s8.devices == N_DEVICES
        _assert_full_parity(r8, r1)

    def test_three_device_mesh_parity(self):
        pods = _plain_pods(120)
        _, r1 = _solve(pods, 64, 1)
        s3, r3 = _solve(pods, 64, 3)
        assert s3.devices == 3
        _assert_full_parity(r3, r1)

    def test_device_request_clamps_to_available(self):
        pods = _plain_pods(40)
        _, r1 = _solve(pods, 64, 1)
        s, r = _solve(pods, 64, 10_000)
        assert s.devices == len(jax.devices())
        _assert_full_parity(r, r1)

    def test_topology_kernel_parity(self):
        pods = _topo_pods(96)
        _, r1 = _solve(pods, 64, 1)
        _, r8 = _solve(pods, 64, N_DEVICES)
        _assert_full_parity(r8, r1)

    def test_existing_nodes_parity(self):
        from karpenter_core_tpu.api import labels as L
        from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
            SimNode,
        )

        nodes = [
            SimNode(
                name=f"exist-{i}",
                labels={
                    L.LABEL_ARCH: "amd64",
                    L.LABEL_OS: "linux",
                    L.LABEL_TOPOLOGY_ZONE: "zone-a",
                    L.NODEPOOL_LABEL_KEY: "default",
                    L.LABEL_INSTANCE_TYPE: _catalog()[5].name,
                },
                taints=[],
                available={"cpu": 7.0, "memory": 14 * GIB, "pods": 200.0},
                capacity={"cpu": 8.0, "memory": 16 * GIB, "pods": 210.0},
            )
            for i in range(6)
        ]
        pods = _plain_pods(60)
        _, r1 = _solve(pods, 64, 1, existing_nodes=list(nodes))
        _, r8 = _solve(pods, 64, N_DEVICES, existing_nodes=list(nodes))
        assert r1.all_pods_scheduled() and r8.all_pods_scheduled()
        assert r8.node_count() == r1.node_count()
        # existing-node placements (by node name) must match too
        by_node = lambda res: sorted(  # noqa: E731
            (sim.name, tuple(sorted(p.uid for p in sim.pods)))
            for sim in res.existing_nodes
        )
        assert by_node(r8) == by_node(r1)


class TestDeviceCountPlumbing:
    """--solver-devices threads operator -> in-proc opts / supervisor argv
    -> solverd; the sidecar owns its own count via ``--devices``."""

    def test_operator_flag_parses_and_validates(self):
        from karpenter_core_tpu.operator import Options

        assert Options.parse([]).solver_devices == 1
        assert Options.parse(["--solver-devices", "8"]).solver_devices == 8
        assert Options.parse(["--solver-devices=0"]).solver_devices == 0
        assert (
            Options.parse(
                [], env={"KARPENTER_SOLVER_DEVICES": "4"}
            ).solver_devices
            == 4
        )
        with pytest.raises(ValueError, match="solver-devices"):
            Options.parse(["--solver-devices", "-1"])

    def test_operator_threads_devices_into_inproc_opts(self):
        from karpenter_core_tpu.operator import Operator, Options

        op = Operator(
            options=Options.parse(
                ["--solver", "tpu", "--solver-devices", "2"]
            )
        )
        assert op.provisioner.device_scheduler_opts.get("devices") == 2
        # an explicit device_scheduler_opts entry wins over the flag
        opts = Options.parse(["--solver", "tpu", "--solver-devices", "2"])
        opts.device_scheduler_opts = {"devices": 3}
        op2 = Operator(options=opts)
        assert op2.provisioner.device_scheduler_opts.get("devices") == 3

    def test_supervisor_command_carries_devices(self):
        from karpenter_core_tpu.solver.supervisor import default_command

        cmd = default_command(0, devices=8)
        assert cmd[cmd.index("--devices") + 1] == "8"
        assert "--devices" not in default_command(0)

    def test_daemon_constructs_sharded_schedulers(self):
        """A devices=N daemon builds devices=N DeviceSchedulers for both
        /solve and the prewarm path (driven directly, no HTTP)."""
        from karpenter_core_tpu.solver import codec, service

        daemon = service.SolverDaemon(devices=N_DEVICES)
        pods = _plain_pods(24)
        body = codec.encode_solve_request(
            [make_nodepool()], {"default": _catalog()}, [], [], pods,
            Topology(), max_slots=64,
        )
        out, _dt = daemon.solve(body)
        decoded = codec.decode_solve_results(out)
        assert not decoded["errors"]
        cached = next(iter(daemon._sched_cache._entries.values()))[0]
        assert cached.devices == N_DEVICES


class TestShardedConsolidationFrontier:
    def test_frontier_parity_with_prefix_padding(self):
        """P=5 prefixes on an 8-device mesh: the prefix axis pads to a
        device multiple and the verdicts slice back."""
        from karpenter_core_tpu.api import labels as L
        from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
            SimNode,
        )
        from karpenter_core_tpu.models.consolidation import frontier_core

        catalog = _catalog()
        nodes = [
            SimNode(
                name=f"n{i}",
                labels={
                    L.LABEL_ARCH: "amd64",
                    L.LABEL_OS: "linux",
                    L.LABEL_TOPOLOGY_ZONE: "zone-a",
                    L.NODEPOOL_LABEL_KEY: "default",
                    L.LABEL_INSTANCE_TYPE: catalog[5].name,
                },
                taints=[],
                available={"cpu": 7.0, "memory": 14 * GIB, "pods": 200.0},
                capacity={"cpu": 8.0, "memory": 16 * GIB, "pods": 210.0},
            )
            for i in range(12)
        ]
        cand, keep = nodes[:5], nodes[5:]
        cand_pods = [
            [make_pod(cpu=0.25, name=f"c{i}-{j}") for j in range(2)]
            for i in range(5)
        ]
        args = ([make_nodepool()], {"default": catalog}, cand, keep, [], [])
        f1 = frontier_core(*args, cand_pods, max_slots=64, devices=1)
        f8 = frontier_core(*args, cand_pods, max_slots=64, devices=N_DEVICES)
        assert f1 is not None and len(f1) == 5
        assert f1 == f8
