"""Tier-4 scale e2e (SURVEY §4 blueprint item (d), CI-sized): thousands of
pods through the FULL operator stack — batcher, tpu solve, NodeClaim
lifecycle, kwok node materialization, binding — not just the solver.
"""
import random

from tests.helpers import make_nodepool, make_pod
from tests.test_e2e import new_operator, replicated


def test_two_thousand_pods_bind_through_the_operator():
    rng = random.Random(0)
    op = new_operator("tpu")
    op.kube.create(make_nodepool())
    for i in range(2000):
        op.kube.create(replicated(make_pod(
            cpu=rng.choice([0.1, 0.25, 0.5, 1.0, 2.0]),
            memory_gib=rng.choice([0.25, 0.5, 1.0, 2.0]),
            name=f"w{i}",
        )))
    op.run_until_idle(max_iters=300)
    pods = op.kube.list_pods()
    assert all(p.node_name for p in pods), sum(
        1 for p in pods if not p.node_name
    )
    nodes = op.kube.list_nodes()
    assert nodes and len(nodes) < 400  # packed, not one-pod-per-node
    assert op.cluster.synced()
    # every node's bound cpu stays within allocatable
    by_node = {}
    for p in pods:
        by_node.setdefault(p.node_name, 0.0)
        by_node[p.node_name] += p.resource_requests.get("cpu", 0.0)
    for n in nodes:
        assert by_node.get(n.name, 0.0) <= n.status.allocatable["cpu"] + 1e-9
