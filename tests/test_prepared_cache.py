"""Prepared-state reuse + shape-bucket jit stability (ISSUE 3 tentpole).

Three contracts:

* identical packings with and without the cache — a steady-state re-solve
  that hits the class-batch cache must produce the same claims a fresh
  scheduler produces, and a relaxation round must reuse the round-1 vocab
  fingerprint (union semantics) instead of forking the cache;
* slot-axis invariance — the adaptive slot shrink (warm solves run at a
  bucket sized from observed usage, overflow retries grow) relies on
  padding slots being inert: the same problem at max_slots=64 and 1024
  must pack identically;
* the shape buckets actually hold the jit cache — a drifting sequence of
  pod counts/class mixes inside one bucket must trigger ZERO new kernel
  compilations, observed through JAX's compilation-count monitoring hook
  (catches future compile-cliff regressions that only show up as latency).
"""
import jax
import numpy as np

from tests.helpers import make_nodepool

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.objects import (
    Affinity,
    LabelSelector,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    TopologySpreadConstraint,
)
from karpenter_core_tpu.cloudprovider.kwok import bench_catalog
from karpenter_core_tpu.models.provisioner import DeviceScheduler

GIB = 2.0**30


def _pods(n, a=4, b=4, prefix="p"):
    """n pods over an a x b shape grid -> min(n, a*b) equivalence classes."""
    return [
        Pod(
            metadata=ObjectMeta(name=f"{prefix}{i}"),
            resource_requests={
                "cpu": 0.1 * (1 + i % a),
                "memory": 0.25 * GIB * (1 + (i // a) % b),
            },
        )
        for i in range(n)
    ]


def _topo_pods(n, n_deploys=2):
    """Mixed topology pods: zone spread + hostname anti-affinity cohorts."""
    pods = []
    for i in range(n):
        dep = i % n_deploys
        requests = {"cpu": 0.2 * (1 + i % 3), "memory": 0.5 * GIB}
        if i % 2 == 0:
            labels = {"app": f"spread-{dep}"}
            pods.append(Pod(
                metadata=ObjectMeta(name=f"t{i}", labels=labels),
                resource_requests=requests,
                topology_spread_constraints=[TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=L.LABEL_TOPOLOGY_ZONE,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=LabelSelector(
                        match_labels=tuple(sorted(labels.items()))
                    ),
                )],
            ))
        else:
            labels = {"app": f"anti-{dep}"}
            pods.append(Pod(
                metadata=ObjectMeta(name=f"t{i}", labels=labels),
                resource_requests=requests,
                affinity=Affinity(pod_anti_affinity=PodAffinity(required=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME,
                        label_selector=LabelSelector(
                            match_labels=tuple(sorted(labels.items()))
                        ),
                    )
                ])),
            ))
    return pods


def _claim_shape(res):
    """Order-free packing signature: sorted (pod count, instance count)."""
    return sorted(
        (len(c.pods), len(c.instance_type_options))
        for c in res.new_node_claims
    )


def _sched(catalog, max_slots=256):
    pool = make_nodepool("default")
    return DeviceScheduler([pool], {"default": list(catalog)},
                           max_slots=max_slots)


class TestCachedResolveParity:
    def test_steady_state_resolve_identical_packing(self):
        catalog = bench_catalog(16)
        pods = _topo_pods(120)
        cached = _sched(catalog)
        first = cached.solve(pods)
        second = cached.solve(pods)
        third = cached.solve(pods)
        # by the third solve the batch cache must be hot (the second may
        # rebuild once for the adaptive slot shrink)
        assert cached.last_phase_stats["prep_cache_hits"] >= 1
        fresh = _sched(catalog).solve(pods)
        assert first.all_pods_scheduled() and third.all_pods_scheduled()
        assert first.node_count() == second.node_count() == third.node_count()
        assert first.node_count() == fresh.node_count()
        assert _claim_shape(third) == _claim_shape(fresh)

    def test_relaxation_round_keeps_fingerprint(self):
        from karpenter_core_tpu.api.objects import (
            NodeAffinity,
            NodeSelectorRequirement,
            NodeSelectorTerm,
            PreferredSchedulingTerm,
        )

        catalog = bench_catalog(8)
        pods = []
        for i in range(30):
            pods.append(Pod(
                metadata=ObjectMeta(name=f"r{i}"),
                resource_requests={"cpu": 0.5, "memory": 1.0 * GIB},
                affinity=Affinity(node_affinity=NodeAffinity(
                    preferred=[PreferredSchedulingTerm(
                        weight=1,
                        preference=NodeSelectorTerm(match_expressions=(
                            NodeSelectorRequirement(
                                "no-such-label", "In", ("nope",)
                            ),
                        )),
                    )],
                )),
            ))
        sched = _sched(catalog, max_slots=64)
        res = sched.solve(pods)
        assert res.all_pods_scheduled()
        assert sched.last_phase_stats["rounds"] >= 2
        # the relax stripped a preferred term (specs shrank); the round-2
        # vocab unions round 1's, so the fingerprint — and the fp-keyed
        # catalog tensors — must not fork
        assert len(sched._fp_cache) == 1

    def test_drifting_mix_correct_across_cache_generations(self):
        catalog = bench_catalog(12)
        sched = _sched(catalog)
        for n in (40, 72, 40, 96):
            res = sched.solve(_pods(n))
            assert res.all_pods_scheduled()
            fresh = _sched(catalog).solve(_pods(n))
            assert res.node_count() == fresh.node_count()


class TestSlotAxisInvariance:
    def test_same_packing_at_any_slot_budget(self):
        catalog = bench_catalog(16)
        pods = _topo_pods(90)
        small = _sched(catalog, max_slots=64).solve(pods)
        large = _sched(catalog, max_slots=1024).solve(pods)
        assert small.all_pods_scheduled() and large.all_pods_scheduled()
        assert small.node_count() == large.node_count()
        assert _claim_shape(small) == _claim_shape(large)

    def test_overflow_retry_recovers_from_low_hint(self):
        catalog = bench_catalog(8)
        sched = _sched(catalog, max_slots=256)
        tiny = sched.solve(_pods(4))
        assert tiny.all_pods_scheduled()
        assert sched._slots_hint  # hint now tiny
        # hostname anti-affinity forces ~one node per pod: far past the
        # shrunken first attempt, so the solve must overflow-retry upward
        pods = []
        for i in range(40):
            labels = {"app": "wide"}
            pods.append(Pod(
                metadata=ObjectMeta(name=f"w{i}", labels=labels),
                resource_requests={"cpu": 0.1, "memory": 0.25 * GIB},
                affinity=Affinity(pod_anti_affinity=PodAffinity(required=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME,
                        label_selector=LabelSelector(
                            match_labels=(("app", "wide"),)
                        ),
                    )
                ])),
            ))
        res = sched.solve(pods)
        assert res.all_pods_scheduled()
        assert res.node_count() == 40


class TestShapeBucketsHoldJitCache:
    def test_zero_new_compilations_inside_one_bucket(self):
        """Solve a drifting sequence of pod counts / class mixes that stays
        inside one shape bucket on every bucketed axis (classes 13..16 ->
        Cp=16, steps -> 16, level_iters window 65..127 pods, slots settle
        at one used-bucket) and assert zero new kernel compilations via
        jax.monitoring — the compile-cliff canary."""
        catalog = bench_catalog(8)
        sched = _sched(catalog, max_slots=64)
        # warm: first solve at the cold slot budget, second at the shrunken
        # adaptive budget (its one legitimate recompile), third confirms
        # the hint fixed point before we start listening
        for n in (80, 84, 88):
            assert sched.solve(_pods(n, a=4, b=4)).all_pods_scheduled()

        from karpenter_core_tpu.ops.ffd import ffd_solve

        compiles = []

        def listener(name, **kw):
            if name == "/jax/compilation_cache/compile_requests_use_cache":
                compiles.append(name)

        jax.monitoring.register_event_listener(listener)
        try:
            cache_before = ffd_solve._cache_size()
            for n, (a, b) in ((92, (4, 4)), (108, (8, 2)), (123, (2, 8))):
                res = sched.solve(_pods(n, a=a, b=b))
                assert res.all_pods_scheduled()
            assert ffd_solve._cache_size() == cache_before
            assert compiles == [], (
                f"{len(compiles)} new compilations inside one shape bucket"
            )
        finally:
            from jax._src import monitoring as _monitoring

            _monitoring._unregister_event_listener_by_callback(listener)
