"""Test configuration: force an 8-device virtual CPU mesh.

Real TPU hardware (one chip under axon) is reserved for bench.py; the test
suite exercises the multi-chip sharding paths on a virtual CPU mesh the same
way the driver's dryrun does.

This box's axon sitecustomize imports jax and programmatically selects the
axon platform at interpreter start, so env vars (JAX_PLATFORMS /
JAX_PLATFORM_NAME) set here are too late — the working override is
jax.config.update after import, before first backend use.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    assert jax.default_backend() == "cpu", (
        f"tests must run on the virtual CPU mesh, got {jax.default_backend()}"
    )
    assert len(jax.devices()) == 8, f"expected 8 CPU devices, got {jax.devices()}"
