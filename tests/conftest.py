"""Test configuration: force an 8-device virtual CPU mesh before jax loads.

Real TPU hardware (one chip under axon) is reserved for bench.py; the test
suite exercises the multi-chip sharding paths on a virtual CPU mesh the same
way the driver's dryrun does.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
