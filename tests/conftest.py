"""Test configuration: force an 8-device virtual CPU mesh.

Real TPU hardware (one chip under axon) is reserved for bench.py; the test
suite exercises the multi-chip sharding paths on a virtual CPU mesh the
same way the driver's dryrun does. The shared bootstrap (and the why) lives
in karpenter_core_tpu/utils/jaxenv.py.
"""
from karpenter_core_tpu.utils.jaxenv import force_virtual_cpu_mesh

force_virtual_cpu_mesh(8)

import jax


def pytest_configure(config):
    # force_virtual_cpu_mesh already raised if this doesn't hold; re-assert
    # here so a future conftest edit that drops the forcing fails loudly
    assert jax.default_backend() == "cpu" and len(jax.devices()) >= 8, (
        f"tests must run on the >=8-device virtual CPU mesh, got "
        f"{jax.default_backend()} with {jax.devices()}"
    )
