"""gangsched (ISSUE 10): priority-preemptive packing and gang-atomic
placement as first-class solver scenarios.

Six layers of proof:

* units — the pod-group annotation contract (solver/gangs), the canonical
  priority tier and the eviction-cost clamp regression (the 2^25 priority
  term used to saturate the documented [-10, 10] contract for any
  PriorityClass >= ~3e8, erasing the deletion-cost ordering among
  critical pods), and the snapshot class split on tier/gang;
* off-by-default parity — problems with no priorities and no gangs never
  dispatch a gang kernel and produce BYTE-IDENTICAL result wires with the
  gangsched preparation surgically disabled, on the single-device path,
  the conftest-forced 8-device virtual mesh, and the batched driver;
* preemption — a critical pod that fits no fresh instance is admitted
  onto a full existing node by evicting the minimal-cost prefix of
  strictly-lower-tier bound pods; claims come back on the result wire,
  the verifier accepts, and the 8-device mesh reproduces the identical
  eviction set;
* gang atomicity — a gang that cannot reach its min-count rolls back ON
  DEVICE (the freed capacity is reused by gang-free pods in the same
  solve), min-count commits partial-above-min placements, same-zone and
  same-node-template co-location hold, and the batched driver keeps gang
  problems out of plain problems' vmap batches (distinct shape keys and
  codec buckets) while still coalescing same-shaped gang problems;
* verifier mutations — forged eviction of an equal-tier victim, a claim
  naming an unknown uid or node, a dangling claim that admits nothing,
  and a partially-materialized gang each reject with their own typed
  reason riding solver_result_rejected_total{reason};
* end-to-end — the operator executes eviction claims as drain-before-bind
  (victims evicted, Preempted events, critical bound, victims reschedule)
  and gang atomicity holds through the seeded chaos harness and a real
  sidecar murder (greedy degradation preserves the semantics).
"""
from __future__ import annotations

import copy

import pytest

from tests.helpers import GIB, make_nodepool, make_pod
from tests.test_e2e import new_operator, replicated
from tests.test_soak import assert_coherent

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.objects import NodeSelectorRequirement
from karpenter_core_tpu.cloudprovider.kwok import build_catalog
from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
    EvictablePod,
    SimNode,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
    Scheduler,
)
from karpenter_core_tpu.metrics import wiring as m
from karpenter_core_tpu.models.provisioner import DeviceScheduler, solve_batch
from karpenter_core_tpu.solver import codec
from karpenter_core_tpu.solver import gangs as gangmod
from karpenter_core_tpu.solver import verify as verifymod
from karpenter_core_tpu.solver.gangs import (
    GANG_ANNOTATION,
    GANG_MIN_SIZE_ANNOTATION,
    GANG_SAME_TEMPLATE_ANNOTATION,
    GANG_SAME_ZONE_ANNOTATION,
    collect_gangs,
    gang_min_count,
    pod_gang_sig,
)
from karpenter_core_tpu.solver.snapshot import group_pods
from karpenter_core_tpu.solver.verify import ResultVerifier
from karpenter_core_tpu.utils.disruption import (
    eviction_cost,
    priority_tier,
)

SYSTEM_CLUSTER_CRITICAL = 2_000_000_000
NODE_LABELS = {
    L.LABEL_TOPOLOGY_ZONE: "zone-a",
    L.LABEL_OS: "linux",
    L.LABEL_ARCH: "amd64",
    L.CAPACITY_TYPE_LABEL_KEY: "on-demand",
    L.NODEPOOL_LABEL_KEY: "default",
}


def gang_pod(name, gang, cpu=1.0, memory_gib=0.5, min_size=None,
             same_zone=False, same_template=False, priority=0, **kw):
    p = make_pod(cpu=cpu, memory_gib=memory_gib, name=name, **kw)
    p.priority = priority
    p.metadata.annotations[GANG_ANNOTATION] = gang
    if min_size is not None:
        p.metadata.annotations[GANG_MIN_SIZE_ANNOTATION] = str(min_size)
    if same_zone:
        p.metadata.annotations[GANG_SAME_ZONE_ANNOTATION] = "true"
    if same_template:
        p.metadata.annotations[GANG_SAME_TEMPLATE_ANNOTATION] = "true"
    return p


def full_node(name="exist-0", available_cpu=0.5, victims=4,
              victim_cpu=3.0, victim_tier=0):
    """An existing node with scarce headroom and a cost-ordered evictable
    population (cost ascending with the index, so the minimal-cost prefix
    is victims[0:k])."""
    return SimNode(
        name=name,
        labels={**NODE_LABELS, L.LABEL_HOSTNAME: name},
        taints=[],
        available={"cpu": available_cpu, "memory": 8 * GIB, "pods": 100.0},
        capacity={"cpu": 16.0, "memory": 16 * GIB, "pods": 110.0},
        initialized=True,
        evictable=tuple(
            EvictablePod(
                uid=f"victim-{i}",
                priority=victim_tier,
                requests={"cpu": victim_cpu, "memory": 0.5 * GIB},
                cost=1.0 + 0.1 * i,
            )
            for i in range(victims)
        ),
    )


def small_catalog():
    """Fresh nodes top out at 2 cpu: any larger pod can only place through
    preemption on an existing node."""
    return build_catalog(cpu_grid=[1, 2])


def _wire(results):
    # solve_seconds is timing, not packing: pin it so wire comparison is
    # exact over the decision content
    return codec.encode_solve_results(results, 0.0)


def _scheduler(pools, catalog, existing=(), devices=1, max_slots=64):
    return DeviceScheduler(
        pools, {p.name: list(catalog) for p in pools},
        existing_nodes=list(existing), max_slots=max_slots, devices=devices,
    )


# ---------------------------------------------------------------------------
# units: annotation contract, tiers, eviction-cost clamp
# ---------------------------------------------------------------------------


class TestAnnotationContract:
    def test_gang_free_pod_has_no_signature(self):
        assert pod_gang_sig(make_pod(cpu=1.0, name="plain")) is None

    def test_signature_components(self):
        p = gang_pod("a", "job-1", min_size=3, same_zone=True)
        assert pod_gang_sig(p) == ("job-1", 3, True, False, None)

    def test_garbage_min_size_defaults_to_whole_group(self):
        p = gang_pod("a", "job-1")
        p.metadata.annotations[GANG_MIN_SIZE_ANNOTATION] = "not-a-number"
        assert pod_gang_sig(p) == ("job-1", 0, False, False, None)
        assert gang_min_count([p, gang_pod("b", "job-1")]) == 2

    def test_min_count_resolves_largest_declared_capped_at_size(self):
        pods = [gang_pod(f"p{i}", "j", min_size=s)
                for i, s in enumerate((2, 5, 0))]
        # declared max (5) exceeds the group size (3) -> the full group
        assert gang_min_count(pods) == 3
        pods = [gang_pod(f"q{i}", "j", min_size=2) for i in range(4)]
        assert gang_min_count(pods) == 2

    def test_collect_gangs_ors_colocation_and_sums_members(self):
        pods = (
            [gang_pod(f"a{i}", "alpha", cpu=1.0) for i in range(3)]
            + [gang_pod("a-big", "alpha", cpu=2.0, same_zone=True)]
            + [gang_pod("b0", "beta", cpu=1.0, min_size=1)]
            + [make_pod(cpu=1.0, name="plain")]
        )
        classes = group_pods(pods)
        gangs = {g.name: g for g in collect_gangs(classes)}
        assert set(gangs) == {"alpha", "beta"}
        alpha = gangs["alpha"]
        # same_zone=True on one member binds the gang, members span the
        # (1cpu x plain) and (2cpu x same-zone) classes
        assert alpha.same_zone and not alpha.same_template
        assert alpha.total == 4 and alpha.min_count == 4
        assert len(alpha.class_indices) == 2
        assert gangs["beta"].min_count == 1


class TestPriorityTier:
    def test_unset_and_garbage_are_tier_zero(self):
        assert priority_tier(None) == 0
        assert priority_tier(0) == 0
        assert priority_tier("garbage") == 0

    def test_value_is_the_tier_clamped_to_int32(self):
        assert priority_tier(100) == 100
        assert priority_tier(-7) == -7
        assert priority_tier(SYSTEM_CLUSTER_CRITICAL) == SYSTEM_CLUSTER_CRITICAL
        assert priority_tier(2**40) == 2**31 - 1

    def test_eviction_cost_clamp_regression(self):
        """ISSUE 10 satellite: the raw priority/2^25 term saturated the
        documented [-10, 10] contract for any PriorityClass >= ~3e8 —
        system-cluster-critical (2e9) landed at 59.6 pre-clamp, so two
        critical pods with different pod-deletion-cost annotations costed
        identically. Per-term clamps (deletion +-1, priority +-8) keep the
        2^-27-scale deletion term a live tiebreak on BOTH signs: a single
        +-9 priority clamp still parked critical pods at the 10.0 ceiling,
        erasing positive deletion costs."""
        from karpenter_core_tpu.utils.disruption import (
            POD_DELETION_COST_ANNOTATION,
        )

        def crit(name, deletion_cost):
            p = make_pod(cpu=1.0, name=name)
            p.priority = SYSTEM_CLUSTER_CRITICAL
            p.metadata.annotations[POD_DELETION_COST_ANNOTATION] = str(
                deletion_cost
            )
            return p

        ladder = [crit(f"crit-{i}", dc) for i, dc in enumerate(
            [-1000000, 1000000, 2000000]  # mixed AND positive-vs-positive
        )]
        costs = [eviction_cost(p) for p in ladder]
        assert costs == sorted(costs) and len(set(costs)) == 3, (
            f"deletion-cost ordering erased among critical pods: {costs}"
        )
        assert all(-10.0 <= c <= 10.0 for c in costs)

    def test_victim_order_is_cost_within_legal_tiers(self):
        """The victim ordering contract both halves share: eligibility is
        tier-based (strictly lower only), selection within the eligible
        set is (cost, uid) — NOT tier-then-cost. A dear low-tier pod is
        passed over for a cheap slightly-higher (still legal) one."""
        from karpenter_core_tpu.utils.disruption import (
            POD_DELETION_COST_ANNOTATION,
        )

        dear = make_pod(cpu=1.0, name="low-dear")
        dear.metadata.annotations[POD_DELETION_COST_ANNOTATION] = "100000000"
        cheap = make_pod(cpu=1.0, name="mid-cheap")
        cheap.priority = 5
        assert eviction_cost(cheap) < eviction_cost(dear)


class TestSnapshotSplit:
    def test_priority_splits_classes(self):
        a = make_pod(cpu=1.0, name="a")
        b = make_pod(cpu=1.0, name="b")
        b.priority = 100
        classes = group_pods([a, b])
        assert len(classes) == 2
        assert sorted(c.tier for c in classes) == [0, 100]

    def test_gang_splits_classes(self):
        a = make_pod(cpu=1.0, name="a")
        b = gang_pod("b", "job-1", cpu=1.0, memory_gib=1.0)
        classes = group_pods([a, b])
        assert len(classes) == 2
        gangs = [c.gang for c in classes]
        assert None in gangs and ("job-1", 0, False, False, None) in gangs

    def test_default_pods_share_the_pre_gang_signature(self):
        """The off-by-default contract's root: a default-tier gang-free
        pod's signature (hence every prepared-cache key derived from it)
        carries NO gangsched suffix."""
        a = make_pod(cpu=1.0, name="a")
        b = make_pod(cpu=1.0, name="b")
        b.priority = 0  # explicitly default
        classes = group_pods([a, b])
        assert len(classes) == 1
        assert classes[0].tier == 0 and classes[0].gang is None
        # fast-path signature stays the pre-gang 3-tuple shape
        (label_aware, sig) = classes[0].signature
        assert not any(
            isinstance(part, tuple) and len(part) == 2
            and isinstance(part[0], int) and part[0] != 0
            for part in sig[-1:]
        )


# ---------------------------------------------------------------------------
# off-by-default parity
# ---------------------------------------------------------------------------


def _neutralized(monkeypatch):
    """Surgically disable every gangsched hook — the closest in-process
    stand-in for 'main before this PR'. Plain problems must not be able to
    tell the difference, byte for byte."""
    monkeypatch.setattr(
        DeviceScheduler, "_prepare_gangsched",
        lambda self, prep, plan, entry, N: None,
    )
    monkeypatch.setattr(gangmod, "has_gangsched", lambda pods: False)


def _forbid_gang_kernels(monkeypatch):
    from karpenter_core_tpu.ops import gangsched as gops

    def boom(*a, **k):
        raise AssertionError("gang kernel dispatched on a plain problem")

    for entry in ("gang_solve", "gang_solve_donated", "gang_solve_batched",
                  "gang_solve_batched_donated", "preempt_pass",
                  "preempt_pass_batched"):
        monkeypatch.setattr(gops, entry, boom)


def _plain_problem(n=40):
    pods = [
        make_pod(cpu=0.25 * (1 + i % 4), memory_gib=0.5 * (1 + i % 3),
                 name=f"p{i}")
        for i in range(n)
    ]
    return [make_nodepool()], build_catalog()[:16], pods


class TestOffByDefaultParity:
    @pytest.mark.parametrize("devices", [1, 8])
    def test_plain_problem_byte_identical_wire(self, devices, monkeypatch):
        pools, catalog, pods = _plain_problem()
        existing = [full_node(victims=0)]
        live = _scheduler(pools, catalog, existing, devices=devices).solve(
            copy.deepcopy(pods)
        )
        wire_live = _wire(live)

        _neutralized(monkeypatch)
        _forbid_gang_kernels(monkeypatch)
        off = _scheduler(pools, catalog, existing, devices=devices).solve(
            copy.deepcopy(pods)
        )
        assert wire_live == _wire(off)
        # and the wire carries no eviction key at all (pre-gang decoders
        # would parse it unchanged)
        assert b"evictions" not in wire_live

    def test_plain_problem_never_dispatches_gang_kernels(self, monkeypatch):
        _forbid_gang_kernels(monkeypatch)
        pools, catalog, pods = _plain_problem()
        res = _scheduler(pools, catalog).solve(pods)
        assert not res.pod_errors and not res.evictions

    def test_plain_batched_path_byte_identical(self, monkeypatch):
        """The batched driver on plain problems is equally gangsched-blind:
        solo wire == batched wire with the hooks disabled."""
        pools_a, catalog, pods_a = _plain_problem(24)
        solo = _wire(_scheduler(pools_a, catalog).solve(
            copy.deepcopy(pods_a)
        ))
        _neutralized(monkeypatch)
        _forbid_gang_kernels(monkeypatch)
        pools_b, _, pods_b = _plain_problem(24)
        outcomes, stats = solve_batch([
            (_scheduler(pools_a, catalog), copy.deepcopy(pods_a)),
            (_scheduler(pools_b, catalog), copy.deepcopy(pods_b)),
        ])
        assert [k for k, _ in outcomes] == ["ok", "ok"]
        assert stats["batched_problems"] == 2  # same shapes still coalesce
        assert _wire(outcomes[0][1]) == solo


# ---------------------------------------------------------------------------
# preemption
# ---------------------------------------------------------------------------


def preemption_problem():
    pools = [make_nodepool()]
    catalog = small_catalog()
    existing = [full_node()]
    crit = make_pod(cpu=8.0, memory_gib=1.0, name="critical")
    crit.priority = SYSTEM_CLUSTER_CRITICAL
    return pools, catalog, existing, [crit]


class TestPreemption:
    def test_minimal_cost_eviction_set_admits_the_critical_pod(self):
        pools, catalog, existing, pods = preemption_problem()
        rejected = dict(m.SOLVER_RESULT_REJECTED.values)
        res = _scheduler(pools, catalog, existing).solve(pods)
        assert not res.pod_errors
        # needs 8 - 0.5 = 7.5 cpu freed; victims carry 3.0 each, cost
        # ascending -> the minimal-cost sufficient prefix is exactly the 3
        # cheapest of the 4
        assert res.evictions == {
            "exist-0": ["victim-0", "victim-1", "victim-2"]
        }
        assert [p.name for s in res.existing_nodes for p in s.pods] == [
            "critical"
        ]
        # the production verifier accepted (no rejection counter movement)
        assert dict(m.SOLVER_RESULT_REJECTED.values) == rejected

    def test_sharded_mesh_reproduces_the_identical_claims(self):
        pools, catalog, existing, pods = preemption_problem()
        solo = _scheduler(pools, catalog, existing).solve(
            copy.deepcopy(pods)
        )
        sharded = _scheduler(pools, catalog, existing, devices=8).solve(
            copy.deepcopy(pods)
        )
        assert _wire(solo) == _wire(sharded)
        assert sharded.evictions == solo.evictions

    def test_equal_tier_population_is_not_evictable(self):
        pools, catalog, _, pods = preemption_problem()
        existing = [full_node(victim_tier=SYSTEM_CLUSTER_CRITICAL)]
        res = _scheduler(pools, catalog, existing).solve(pods)
        # nothing strictly lower -> no preemption, pod unschedulable
        assert not res.evictions
        assert len(res.pod_errors) == 1

    def test_negative_tier_pending_pod_does_not_preempt(self):
        pools, catalog, existing, _ = preemption_problem()
        low = make_pod(cpu=8.0, memory_gib=1.0, name="low")
        low.priority = -5  # below the k8s default; victims are tier 0
        res = _scheduler(pools, catalog, existing).solve([low])
        assert not res.evictions and len(res.pod_errors) == 1

    def test_gang_members_never_preempt(self):
        """Documented interplay limit: the preemption pass serves gang-FREE
        classes only (a preempted gang member would bypass the in-kernel
        co-location state)."""
        pools, catalog, existing, _ = preemption_problem()
        member = gang_pod("g0", "job-g", cpu=8.0, memory_gib=1.0,
                          priority=SYSTEM_CLUSTER_CRITICAL)
        res = _scheduler(pools, catalog, existing).solve([member])
        assert not res.evictions and len(res.pod_errors) == 1

    def test_fallback_straddling_gang_member_never_preempts(self):
        """A gang with one member forced host-fallback (non-trivial spread
        node filter) is kernel-excluded — but its DEVICE members are still
        gang members: the preemption pass must not evict real workload to
        place a pod the atomicity backstop may strip."""
        pools, catalog, existing, _ = preemption_problem()
        # device-class member: only placeable through preemption
        big = gang_pod("gs-big", "job-s", cpu=8.0, memory_gib=1.0,
                       priority=SYSTEM_CLUSTER_CRITICAL)
        # fallback-forcing member: zone spread + zone pin = non-trivial
        # spread node filter, a host-only group (topoplan fallback)
        small = gang_pod("gs-small", "job-s", cpu=0.5, memory_gib=0.5,
                         priority=SYSTEM_CLUSTER_CRITICAL,
                         spread_zone=True, zone_in=["zone-a"])
        res = _scheduler(pools, catalog, existing).solve([big, small])
        assert not res.evictions
        # atomicity holds degraded: the whole gang is unschedulable
        assert set(res.pod_errors) == {big.uid, small.uid}

    def test_batched_driver_preempts_with_solo_parity(self):
        """Two same-shaped preemption problems ride one vmapped dispatch
        pair (solve + preempt) and each reproduces its solo wire."""
        pools, catalog, existing, pods = preemption_problem()
        solo = _wire(_scheduler(pools, catalog, existing).solve(
            copy.deepcopy(pods)
        ))
        outcomes, stats = solve_batch([
            (_scheduler(pools, catalog, existing), copy.deepcopy(pods)),
            (_scheduler(pools, catalog, existing), copy.deepcopy(pods)),
        ])
        assert [k for k, _ in outcomes] == ["ok", "ok"]
        assert stats["batched_problems"] >= 2
        assert _wire(outcomes[0][1]) == solo
        assert _wire(outcomes[1][1]) == solo


# ---------------------------------------------------------------------------
# gang atomicity
# ---------------------------------------------------------------------------


class TestGangAtomicity:
    def test_failed_gang_rolls_back_and_frees_capacity_on_device(self):
        """A 3x4cpu gang over 9 available cpu (no fresh fits) cannot reach
        min-count: every member reports unschedulable AND the two slots it
        transiently held serve gang-free pods in the SAME solve — the
        rollback happened on device, not by post-hoc stripping."""
        pools = [make_nodepool()]
        catalog = small_catalog()
        node = full_node(available_cpu=9.0, victims=0)
        gang = [gang_pod(f"g{i}", "job-a", cpu=4.0) for i in range(3)]
        fillers = [make_pod(cpu=4.0, memory_gib=0.5, name=f"f{i}")
                   for i in range(2)]
        rejected = dict(m.SOLVER_RESULT_REJECTED.values)
        res = _scheduler(pools, catalog, [node]).solve(gang + fillers)
        assert set(res.pod_errors) == {p.uid for p in gang}
        placed = [p.name for s in res.existing_nodes for p in s.pods]
        assert sorted(placed) == ["f0", "f1"]
        assert dict(m.SOLVER_RESULT_REJECTED.values) == rejected

    def test_min_count_commits_partial_above_min(self):
        pools = [make_nodepool()]
        catalog = small_catalog()
        node = full_node(available_cpu=9.0, victims=0)
        gang = [gang_pod(f"g{i}", "job-a", cpu=4.0, min_size=2)
                for i in range(3)]
        res = _scheduler(pools, catalog, [node]).solve(gang)
        assert len(res.pod_errors) == 1  # 2 of 3 placed >= min 2
        placed = [p.name for s in res.existing_nodes for p in s.pods]
        assert len(placed) == 2

    def test_whole_gang_unschedulable_metric_moves(self):
        pools = [make_nodepool()]
        catalog = small_catalog()
        node = full_node(available_cpu=9.0, victims=0)
        gang = [gang_pod(f"g{i}", "job-a", cpu=4.0) for i in range(3)]
        before = m.SOLVER_GANG_UNSCHEDULABLE.value()
        _scheduler(pools, catalog, [node]).solve(gang)
        assert m.SOLVER_GANG_UNSCHEDULABLE.value() == before + 1

    def test_same_zone_gang_follows_the_pinned_member(self):
        """One member zone-pinned to zone-b drags the whole gang there —
        the synthetic zone-affinity group in action."""
        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a", "zone-b", "zone-c"),
        )])
        pods = [
            gang_pod(f"z{i}", "job-z", cpu=1.0, same_zone=True,
                     **({"zone_in": ["zone-b"]} if i == 0 else {}))
            for i in range(4)
        ]
        rejected = dict(m.SOLVER_RESULT_REJECTED.values)
        res = _scheduler([pool], small_catalog()).solve(pods)
        assert not res.pod_errors
        zones = set()
        for c in res.new_node_claims:
            zr = c.requirements.get(L.LABEL_TOPOLOGY_ZONE)
            assert zr is not None
            zones.update(zr.sorted_values())
        assert zones == {"zone-b"}
        assert dict(m.SOLVER_RESULT_REJECTED.values) == rejected

    def test_same_template_gang_lands_on_one_nodepool(self):
        """Two pools at different weights; a same-template gang whose
        members individually prefer different pools must resolve to ONE
        (the joint template mask AND-reduces viability before
        first-template-wins)."""
        heavy = make_nodepool(name="heavy", weight=10, requirements=[
            NodeSelectorRequirement(L.LABEL_ARCH, "In", ("amd64",)),
        ])
        light = make_nodepool(name="light")
        catalog = small_catalog()
        pods = [
            gang_pod(f"t{i}", "job-t", cpu=1.0, same_template=True)
            for i in range(4)
        ]
        sched = DeviceScheduler(
            [heavy, light],
            {"heavy": list(catalog), "light": list(catalog)},
            max_slots=64,
        )
        res = sched.solve(pods)
        assert not res.pod_errors
        pools_used = {
            c.requirements.get(L.NODEPOOL_LABEL_KEY).sorted_values()[0]
            for c in res.new_node_claims if c.pods
        }
        assert len(pools_used) == 1

    def test_same_zone_flag_on_one_member_binds_the_whole_gang(self):
        """Co-location flags OR across members (collect_gangs contract):
        the zone-pinned member declares NOTHING — the other members'
        same_zone flag must still drag the whole gang to its zone."""
        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a", "zone-b", "zone-c"),
        )])
        pods = [gang_pod("z0", "job-z", cpu=1.0, zone_in=["zone-b"])] + [
            gang_pod(f"z{i}", "job-z", cpu=1.0, same_zone=True)
            for i in range(1, 4)
        ]
        res = _scheduler([pool], small_catalog()).solve(pods)
        assert not res.pod_errors
        zones = set()
        for c in res.new_node_claims:
            zr = c.requirements.get(L.LABEL_TOPOLOGY_ZONE)
            assert zr is not None
            zones.update(zr.sorted_values())
        assert zones == {"zone-b"}, zones

    def test_same_template_flag_on_one_member_binds_the_whole_gang(self):
        """One member pool-pinned WITHOUT the flag, another member flagged
        same_template: the OR-resolved gang must land on one pool."""
        heavy = make_nodepool(name="heavy", weight=10)
        light = make_nodepool(name="light")
        catalog = small_catalog()
        pods = [
            gang_pod("t0", "job-t", cpu=1.0, same_template=True),
            gang_pod("t1", "job-t", cpu=1.0,
                     node_selector={L.NODEPOOL_LABEL_KEY: "light"}),
        ]
        sched = DeviceScheduler(
            [heavy, light],
            {"heavy": list(catalog), "light": list(catalog)},
            max_slots=64,
        )
        res = sched.solve(pods)
        assert not res.pod_errors
        pools_used = {
            c.requirements.get(L.NODEPOOL_LABEL_KEY).sorted_values()[0]
            for c in res.new_node_claims if c.pods
        }
        assert pools_used == {"light"}, pools_used

    def test_gang_joint_templates_mask_unit(self):
        import numpy as np

        from karpenter_core_tpu.ops import masks as mops

        tmpl_ok = np.array([
            [True, True, False],
            [False, True, True],
            [True, False, True],
        ])
        gang_id = np.array([0, 0, -1], dtype=np.int32)
        out = np.asarray(mops.gang_joint_templates(
            tmpl_ok, gang_id, num_gangs=1
        ))
        # gang members 0/1 AND-reduce to their common template (1);
        # the gang-free class 2 passes through untouched
        assert out.tolist() == [
            [False, True, False],
            [False, True, False],
            [True, False, True],
        ]


# ---------------------------------------------------------------------------
# batching seams: buckets and shape keys
# ---------------------------------------------------------------------------


class TestBatchingSeams:
    def _bucket_for(self, pods):
        data = codec.encode_solve_request(
            [make_nodepool()], {"default": build_catalog()[:4]},
            [], [], pods, max_slots=64,
        )
        return codec.decode_solve_request(data)["bucket"]

    def test_problem_bucket_splits_gangs_and_tiers(self):
        plain = [make_pod(cpu=1.0, name="a")]
        ganged = [gang_pod("a", "job-1", cpu=1.0)]
        # tiers-ACTIVE is the shape-relevant bit (step-tier rows attach
        # exactly when any tier is non-zero), so even an all-one-tier
        # problem splits from the plain bucket; two active-tier problems
        # with the same distinct-tier count still share one
        one_tier = [make_pod(cpu=1.0, name="a")]
        one_tier[0].priority = 100
        other_tier = [make_pod(cpu=1.0, name="a")]
        other_tier[0].priority = -7
        b_plain, b_gang, b_one, b_other = (
            self._bucket_for(plain), self._bucket_for(ganged),
            self._bucket_for(one_tier), self._bucket_for(other_tier),
        )
        assert b_one == b_other  # values don't ride the bucket, count does
        assert len({b_plain, b_gang, b_one}) == 3

    def test_evictable_capacity_splits_the_bucket(self):
        pods = [make_pod(cpu=1.0, name="a")]
        bare = codec.decode_solve_request(codec.encode_solve_request(
            [make_nodepool()], {"default": build_catalog()[:4]},
            [full_node(victims=0)], [], pods, max_slots=64,
        ))["bucket"]
        armed = codec.decode_solve_request(codec.encode_solve_request(
            [make_nodepool()], {"default": build_catalog()[:4]},
            [full_node(victims=2)], [], pods, max_slots=64,
        ))["bucket"]
        assert bare != armed

    def test_mixed_gang_plain_batch_never_coalesces_but_stays_correct(self):
        """ISSUE 10 satellite: a gang problem and a plain problem of
        identical pod shapes land in ONE solve_batch call, are never
        vmapped together (distinct kernel shape keys), and each yields its
        solo result wire byte-for-byte."""
        pools_g = [make_nodepool()]
        pools_p = [make_nodepool()]
        catalog = small_catalog()
        node_g = full_node(name="exist-g", available_cpu=9.0, victims=0)
        node_p = full_node(name="exist-p", available_cpu=9.0, victims=0)
        gang = [gang_pod(f"g{i}", "job-a", cpu=4.0) for i in range(2)]
        plain = [make_pod(cpu=4.0, memory_gib=0.5, name=f"p{i}")
                 for i in range(2)]
        solo_g = _wire(_scheduler(pools_g, catalog, [node_g]).solve(
            copy.deepcopy(gang)
        ))
        solo_p = _wire(_scheduler(pools_p, catalog, [node_p]).solve(
            copy.deepcopy(plain)
        ))
        outcomes, stats = solve_batch([
            (_scheduler(pools_g, catalog, [node_g]), copy.deepcopy(gang)),
            (_scheduler(pools_p, catalog, [node_p]), copy.deepcopy(plain)),
        ])
        assert [k for k, _ in outcomes] == ["ok", "ok"]
        assert stats["batched_problems"] == 0, (
            "a gang problem coalesced into a plain problem's vmap batch"
        )
        assert _wire(outcomes[0][1]) == solo_g
        assert _wire(outcomes[1][1]) == solo_p

    def test_same_shaped_gang_problems_do_coalesce(self):
        pools_a = [make_nodepool()]
        pools_b = [make_nodepool()]
        catalog = small_catalog()
        node_a = full_node(name="exist-a", available_cpu=9.0, victims=0)
        node_b = full_node(name="exist-b", available_cpu=9.0, victims=0)
        gang_a = [gang_pod(f"a{i}", "job-a", cpu=4.0) for i in range(2)]
        gang_b = [gang_pod(f"b{i}", "job-b", cpu=4.0) for i in range(2)]
        solo_a = _wire(_scheduler(pools_a, catalog, [node_a]).solve(
            copy.deepcopy(gang_a)
        ))
        outcomes, stats = solve_batch([
            (_scheduler(pools_a, catalog, [node_a]), copy.deepcopy(gang_a)),
            (_scheduler(pools_b, catalog, [node_b]), copy.deepcopy(gang_b)),
        ])
        assert [k for k, _ in outcomes] == ["ok", "ok"]
        assert stats["batched_problems"] >= 2
        assert _wire(outcomes[0][1]) == solo_a


# ---------------------------------------------------------------------------
# verifier mutations: every forgery rejects with its own typed reason
# ---------------------------------------------------------------------------


class TestVerifierGangschedMutations:
    def _preemption_solved(self):
        pools, catalog, existing, pods = preemption_problem()
        sched = DeviceScheduler(
            pools, {"default": list(catalog)},
            existing_nodes=existing, max_slots=64, verify=False,
        )
        sp = copy.deepcopy(pods)
        res = sched.solve(sp)
        assert res.evictions
        verifier = ResultVerifier(pools, {"default": list(catalog)},
                                  existing_nodes=existing)
        assert not verifier.verify(res, sp)  # precondition: clean
        return res, sp, pools, {"default": list(catalog)}, existing

    def _reasons(self, pools, its, existing, res, sp):
        violations = ResultVerifier(
            pools, its, existing_nodes=existing
        ).verify(res, sp)
        # the production rejection path: one counter bump per reason
        if violations:
            verifymod.reject(violations, path="test")
        return {v.reason for v in violations}

    def test_forged_equal_tier_eviction_is_rejected(self):
        res, sp, pools, its, existing = self._preemption_solved()
        # victim-3 re-badged to the admitted pod's own tier: no longer
        # strictly below anything its capacity admitted
        node = existing[0]
        forged = tuple(
            EvictablePod(uid=e.uid, priority=SYSTEM_CLUSTER_CRITICAL,
                         requests=e.requests, cost=e.cost)
            for e in node.evictable
        )
        existing = [SimNode(
            name=node.name, labels=node.labels, taints=node.taints,
            available=node.available, capacity=node.capacity,
            initialized=node.initialized, evictable=forged,
        )]
        before = dict(m.SOLVER_RESULT_REJECTED.values)
        reasons = self._reasons(pools, its, existing, res, sp)
        assert "eviction" in reasons, reasons
        moved = {
            k: v for k, v in m.SOLVER_RESULT_REJECTED.values.items()
            if dict(k).get("reason") == "eviction"
        }
        assert moved, "no eviction-reason rejection counter moved"
        assert dict(m.SOLVER_RESULT_REJECTED.values) != before

    def test_forged_eviction_on_all_default_tier_solve_is_rejected(self):
        """A lying sidecar appends a claim naming a genuinely lower-tier
        victim to a solve where every pod is tier 0: preemption serves
        positive tiers only, so no admitted pod can have enabled it."""
        pools = [make_nodepool()]
        catalog = small_catalog()
        its = {"default": list(catalog)}
        existing = [full_node(available_cpu=2.0, victims=1,
                              victim_cpu=3.0, victim_tier=-5)]
        sched = DeviceScheduler(pools, its, existing_nodes=existing,
                                max_slots=64, verify=False)
        sp = [make_pod(cpu=1.0, name="plain")]  # tier 0
        res = sched.solve(sp)
        assert not res.evictions
        assert any(s.pods for s in res.existing_nodes)
        res.evictions = {"exist-0": ["victim-0"]}
        reasons = self._reasons(pools, its, existing, res, sp)
        assert "eviction" in reasons, reasons

    def test_non_load_bearing_eviction_claim_is_rejected(self):
        """A forged claim riding a LEGITIMATE high-tier placement: the
        pod landed through ordinary free capacity, so a tier comparison
        alone would legalize draining the lower-tier victim for nothing."""
        pools = [make_nodepool()]
        catalog = small_catalog()
        its = {"default": list(catalog)}
        existing = [full_node(available_cpu=2.0, victims=1,
                              victim_cpu=3.0, victim_tier=0)]
        sched = DeviceScheduler(pools, its, existing_nodes=existing,
                                max_slots=64, verify=False)
        hi = make_pod(cpu=1.0, name="hi")
        hi.priority = 100
        sp = [hi]
        res = sched.solve(sp)
        assert not res.evictions
        assert any(s.pods for s in res.existing_nodes)
        res.evictions = {"exist-0": ["victim-0"]}
        reasons = self._reasons(pools, its, existing, res, sp)
        assert "eviction" in reasons, reasons

    def test_eviction_claim_admitting_only_gang_members_is_rejected(self):
        """Both preemption halves serve GANG-FREE pods only (device:
        gang_j == gangs.GANG_FREE; host: pod_gang_sig is None), so a claim
        whose only positive-tier admitted pod is a gang member cannot be
        legitimate preemption output — re-badging the admitted pod as a
        gang member must flip a clean solve to rejected (ISSUE 11)."""
        res, sp, pools, its, existing = self._preemption_solved()
        claimed = set(res.evictions)
        for sim in res.existing_nodes:
            if sim.name in claimed:
                for p in sim.pods:
                    p.metadata.annotations[GANG_ANNOTATION] = "forged-gang"
        reasons = self._reasons(pools, its, existing, res, sp)
        assert "eviction" in reasons, reasons

    def test_eviction_claim_naming_unknown_uid_is_rejected(self):
        res, sp, pools, its, existing = self._preemption_solved()
        res.evictions["exist-0"].append("never-existed")
        reasons = self._reasons(pools, its, existing, res, sp)
        assert "eviction_unknown" in reasons, reasons
        moved = {
            k: v for k, v in m.SOLVER_RESULT_REJECTED.values.items()
            if dict(k).get("reason") == "eviction_unknown"
        }
        assert moved

    def test_eviction_claim_on_unknown_node_is_rejected(self):
        res, sp, pools, its, existing = self._preemption_solved()
        res.evictions["ghost-node"] = ["victim-0"]
        reasons = self._reasons(pools, its, existing, res, sp)
        assert "eviction_unknown" in reasons, reasons

    def test_dangling_claim_that_admits_nothing_is_rejected(self):
        res, sp, pools, its, existing = self._preemption_solved()
        # strip the placement the evictions were load-bearing for: the
        # claim now drains three pods to enable nothing
        for sim in res.existing_nodes:
            sim.pods = []
        res.pod_errors = {p.uid: "unschedulable" for p in sp}
        reasons = self._reasons(pools, its, existing, res, sp)
        assert "eviction" in reasons, reasons

    def test_scattered_same_zone_gang_is_rejected(self):
        """A structurally-valid lying result that spreads a same-zone gang
        over two zones must reject: atomicity alone is not the whole gang
        contract — the verifier re-derives co-location from annotations."""
        from karpenter_core_tpu.scheduling.requirement import Requirement

        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a", "zone-b", "zone-c"),
        )])
        its = {"default": list(small_catalog())}
        pods = [
            gang_pod(f"z{i}", "job-z", cpu=1.0, same_zone=True)
            for i in range(4)
        ]
        sched = DeviceScheduler([pool], its, max_slots=64, verify=False)
        sp = copy.deepcopy(pods)
        res = sched.solve(sp)
        assert not res.pod_errors
        claims = [c for c in res.new_node_claims if c.pods]
        # the forgery moves ONE claim's zone: the gang must span >= 2
        # claims or the whole group would move together
        assert len(claims) >= 2
        verifier = ResultVerifier([pool], its)
        assert not verifier.verify(res, sp)  # precondition: clean
        claims[0].requirements[L.LABEL_TOPOLOGY_ZONE] = Requirement(
            L.LABEL_TOPOLOGY_ZONE, values={"zone-c"}
        )
        reasons = {v.reason for v in verifier.verify(res, sp)}
        assert "gang" in reasons, reasons

    def test_partially_materialized_gang_is_rejected(self):
        pools = [make_nodepool()]
        catalog = small_catalog()
        its = {"default": list(catalog)}
        gang = [gang_pod(f"g{i}", "job-a", cpu=1.0) for i in range(4)]
        sched = DeviceScheduler(pools, its, max_slots=64, verify=False)
        sp = copy.deepcopy(gang)
        res = sched.solve(sp)
        verifier = ResultVerifier(pools, its)
        assert not verifier.verify(res, sp)  # fully placed: clean
        # drop one member from its claim -> below min-count (the whole
        # group), leaving the rest partially materialized
        victim = sp[0]
        for c in res.new_node_claims:
            c.pods = [p for p in c.pods if p.uid != victim.uid]
        res.pod_errors[victim.uid] = "lost at the decode seam"
        reasons = {v.reason for v in ResultVerifier(pools, its).verify(
            res, sp
        )}
        assert "gang" in reasons, reasons

    def test_reasons_are_registered_counter_labels(self):
        """The three new reasons are part of the verifier's typed-reason
        contract (REASONS) so dashboards can pre-provision the series."""
        assert {"eviction", "eviction_unknown", "gang"} <= set(
            verifymod.REASONS
        )


# ---------------------------------------------------------------------------
# the host fallback: tiered greedy with preemption
# ---------------------------------------------------------------------------


class TestHostFallback:
    def test_higher_tier_claims_scarce_capacity_first(self):
        """Pods arrive low-priority-first; the tier-banded fallback must
        still give the existing node's last 3 cpu to the critical pod (a
        tier-blind greedy would hand it to 'low' by arrival order)."""
        catalog = small_catalog()
        node = full_node(available_cpu=3.0, victims=0)
        low = make_pod(cpu=3.0, memory_gib=0.5, name="low")
        high = make_pod(cpu=3.0, memory_gib=0.5, name="high")
        high.priority = 100

        def make_scheduler():
            return Scheduler([make_nodepool()], {"default": list(catalog)},
                             existing_nodes=[node])

        res = gangmod.host_gang_solve(make_scheduler, [low, high], [node])
        on_node = [p.name for s in res.existing_nodes for p in s.pods]
        assert on_node == ["high"]
        assert low.uid in res.pod_errors  # 3cpu fits no fresh instance

    def test_host_preemption_matches_the_kernel_rule(self):
        pools, catalog, existing, pods = preemption_problem()

        def make_scheduler():
            return Scheduler(pools, {"default": list(catalog)},
                             existing_nodes=list(existing))

        res = gangmod.host_gang_solve(make_scheduler, pods, existing)
        assert not res.pod_errors
        assert res.evictions == {
            "exist-0": ["victim-0", "victim-1", "victim-2"]
        }

    def test_host_preemption_serves_the_overshoot_residual(self):
        """An eviction prefix usually frees MORE than the first pod needs;
        a second capacity-starved pod must be admitted into that residual
        with zero further evictions (the kernel's bonus-carry admission)."""
        catalog = small_catalog()
        node = full_node(available_cpu=0.5, victims=4, victim_cpu=3.0)
        big = make_pod(cpu=4.0, memory_gib=0.5, name="big")
        big.priority = 100
        mid = make_pod(cpu=2.5, memory_gib=0.5, name="mid")
        mid.priority = 100

        def make_scheduler():
            return Scheduler([make_nodepool()], {"default": list(catalog)},
                             existing_nodes=[node])

        res = gangmod.host_gang_solve(
            make_scheduler, [big, mid], [node]
        )
        # big: 0.5 free + 2 victims x 3.0 = 6.5 >= 4.0 (overshoot 2.5);
        # mid then fits the residual exactly — no third eviction
        assert not res.pod_errors
        assert res.evictions == {"exist-0": ["victim-0", "victim-1"]}

    def test_fallback_strips_partial_gangs(self):
        catalog = small_catalog()
        node = full_node(available_cpu=9.0, victims=0)
        gang = [gang_pod(f"g{i}", "job-a", cpu=4.0) for i in range(3)]

        def make_scheduler():
            return Scheduler([make_nodepool()], {"default": list(catalog)},
                             existing_nodes=[node])

        res = gangmod.host_gang_solve(make_scheduler, gang, [node])
        assert set(res.pod_errors) == {p.uid for p in gang}
        assert not [p for s in res.existing_nodes for p in s.pods]

    def test_degraded_device_path_preserves_semantics(self, monkeypatch):
        """Force the device result to fail verification: the re-solve must
        go through the tiered wrapper, not the flat greedy."""
        pools, catalog, existing, pods = preemption_problem()
        sched = _scheduler(pools, catalog, existing)
        seen = {}
        orig = gangmod.host_gang_solve

        def spy(make_scheduler, spods, enodes=()):
            seen["pods"] = list(spods)
            return orig(make_scheduler, spods, enodes)

        monkeypatch.setattr(gangmod, "host_gang_solve", spy)
        monkeypatch.setattr(
            verifymod.ResultVerifier, "verify",
            lambda self, res, p: [verifymod.Violation("capacity", "forged")],
        )
        res = sched.solve(pods)
        assert seen, "gang problem degraded through the flat greedy path"
        assert res.evictions == {
            "exist-0": ["victim-0", "victim-1", "victim-2"]
        }


# ---------------------------------------------------------------------------
# end-to-end: drain-before-bind, chaos, sidecar murder
# ---------------------------------------------------------------------------


class TestOperatorEndToEnd:
    def test_preemption_drains_before_bind_and_victims_reschedule(self):
        """The full story: a zone-a node fills with low-priority pods, the
        pool moves to zone-b, a critical zone-a-pinned pod arrives. The
        operator executes the eviction claims (Preempted events), binds
        the critical pod into the freed capacity, and the victims — being
        replicated — reschedule onto fresh zone-b capacity."""
        catalog = build_catalog(cpu_grid=[4])
        op = new_operator("tpu", catalog=catalog)
        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a",),
        )])
        op.kube.create(pool)
        for i in range(3):
            op.kube.create(replicated(make_pod(cpu=1.0, name=f"low{i}")))
        op.run_until_idle()
        (node_a,) = op.kube.list_nodes()
        assert node_a.labels[L.LABEL_TOPOLOGY_ZONE] == "zone-a"

        pool = op.kube.get(type(pool), "default")
        pool.spec.template.requirements = [NodeSelectorRequirement(
            L.LABEL_TOPOLOGY_ZONE, "In", ("zone-b",),
        )]
        op.kube.update(pool)
        evicted_before = m.SOLVER_PREEMPTION_EVICTIONS.value()
        crit = replicated(make_pod(cpu=3.0, name="crit",
                                   zone_in=["zone-a"]))
        crit.priority = SYSTEM_CLUSTER_CRITICAL
        op.kube.create(crit)
        op.run_until_idle()

        pods = {p.name: p for p in op.kube.list_pods()}
        assert pods["crit"].node_name == node_a.name
        # all three victims drained and rescheduled elsewhere
        for i in range(3):
            low = pods[f"low{i}"]
            assert low.node_name and low.node_name != node_a.name
        assert m.SOLVER_PREEMPTION_EVICTIONS.value() == evicted_before + 3
        preempted = [e for e in op.recorder.events if e.reason == "Preempted"]
        assert len(preempted) == 3
        assert_coherent(op)

    def test_gang_binds_atomically_through_the_operator(self):
        op = new_operator("tpu")
        op.kube.create(make_nodepool())
        for i in range(6):
            op.kube.create(replicated(gang_pod(f"g{i}", "job-a", cpu=1.0)))
        op.run_until_idle()
        pods = op.kube.list_pods()
        assert all(p.node_name for p in pods)
        assert_coherent(op)


def _assert_gangs_atomic(op):
    """Zero partially-materialized gangs over the LIVE bindings."""
    by_gang = {}
    for p in op.kube.list_pods():
        g = pod_gang_sig(p)
        if g is not None:
            by_gang.setdefault(g[0], []).append(p)
    for name, mpods in sorted(by_gang.items()):
        bound = [p for p in mpods if p.node_name]
        assert not bound or len(bound) >= gang_min_count(mpods), (
            f"gang {name!r} partially materialized:"
            f" {len(bound)}/{len(mpods)} bound"
        )


class TestGangChaos:
    def test_gang_atomicity_under_seeded_chaos(self):
        """Waves of mixed gang/priority/plain workload through the seeded
        chaos harness (conflicts, 429s, ICE, provider faults) on the
        device path: the cluster converges with every gang whole and the
        rejection counters unmoved (clean-run contract)."""
        from tests.test_chaos import _chaos_operator

        rejected = dict(m.SOLVER_RESULT_REJECTED.values)
        op, schedule, store = _chaos_operator(seed=1310, solver="tpu")
        store.create(make_nodepool())
        serial = 0
        for wave in range(3):
            for gi in range(2):
                gname = f"gang-{wave}-{gi}"
                for _ in range(3):
                    store.create(replicated(gang_pod(
                        f"w{serial}", gname,
                        cpu=[0.5, 1.0][serial % 2],
                    )))
                    serial += 1
            for _ in range(3):
                p = replicated(make_pod(cpu=1.0, name=f"w{serial}"))
                p.priority = [0, 100, SYSTEM_CLUSTER_CRITICAL][serial % 3]
                store.create(p)
                serial += 1
            op.run_until_idle(max_iters=400)
            op.clock.step(61.0)
            op.run_until_idle(max_iters=400)
            _assert_gangs_atomic(op)
        assert schedule.draws > 0
        assert_coherent(op)
        _assert_gangs_atomic(op)
        assert dict(m.SOLVER_RESULT_REJECTED.values) == rejected, (
            "verifier rejected a clean gangsched solve under chaos"
        )

    def test_gang_atomicity_survives_sidecar_murder(self):
        """Kill a real sidecar mid-churn: the greedy degradation path must
        hold the same gang-atomicity contract the device path does."""
        from tests.test_solverd import new_operator as solverd_operator

        op = solverd_operator("sidecar", batch_idle_duration=0.0)
        try:
            sup = op.solver_supervisor
            op.solver_client.max_retries = 0
            op.solver_client.sleep = lambda s: None
            op.kube.create(make_nodepool())
            # wave 1 rides the live sidecar
            for i in range(4):
                op.kube.create(replicated(gang_pod(
                    f"alive{i}", "gang-alive", cpu=1.0
                )))
            op.run_until_idle(disrupt=False)
            _assert_gangs_atomic(op)
            assert all(p.node_name for p in op.kube.list_pods())
            # murder the sidecar; hold the respawn window shut so wave 2
            # really degrades to the tiered host fallback
            op.solver_client.timeout = 1.0
            sup._delay = 9999.0
            sup.proc.kill()
            sup.proc.wait(timeout=10)
            fb = m.SOLVER_RPC_FALLBACKS.value({"endpoint": "solve"})
            for i in range(4):
                op.kube.create(replicated(gang_pod(
                    f"dead{i}", "gang-dead", cpu=1.0
                )))
            op.run_until_idle(disrupt=False)
            assert m.SOLVER_RPC_FALLBACKS.value(
                {"endpoint": "solve"}
            ) > fb
            _assert_gangs_atomic(op)
            assert all(p.node_name for p in op.kube.list_pods())
            assert_coherent(op)
        finally:
            op.shutdown()
