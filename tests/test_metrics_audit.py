"""AST-driven metrics audit: emitted == registered, no grep involved.

graftlint's GL402 gates one direction on every run (nothing emits an
unregistered instrument); this audit pins the full equality so dashboards
never reference a phantom series AND the registry never carries dead
instruments that a dashboard author would reasonably chart against:

* every instrument emitted anywhere in ``karpenter_core_tpu/`` resolves
  to a ``REGISTRY.counter/gauge/histogram`` definition;
* every defined instrument is emitted somewhere (or sits on the explicit
  exemption list below, with a reason);
* metric string names are unique across all definitions.

Built on the same collectors the lint rule uses
(tools/graftlint/rules/parity.py), so the test and the gate can never
drift apart on what counts as an emission site.
"""
from __future__ import annotations

from collections import Counter

from tools.graftlint.engine import _collect_files
from tools.graftlint.rules.parity import (
    collect_defined_instruments,
    collect_used_instruments,
)

# instruments that are registered but legitimately never .inc()'d from
# karpenter_core_tpu/ source; every entry needs a reason
_DEFINED_NOT_EMITTED_OK: dict = {
    # (none today — keep it that way)
}


def _files():
    return _collect_files(["karpenter_core_tpu"])


def test_every_emission_site_is_registered():
    files = _files()
    defined = collect_defined_instruments(files)
    used = collect_used_instruments(files)
    phantoms = {
        name: [f"{f.path}:{f.line}" for f in sites]
        for name, sites in used.items()
        if name not in defined
    }
    assert not phantoms, f"emission sites with no registration: {phantoms}"


def test_every_registered_instrument_is_emitted():
    files = _files()
    defined = collect_defined_instruments(files)
    used = collect_used_instruments(files)
    dead = set(defined) - set(used) - set(_DEFINED_NOT_EMITTED_OK)
    assert not dead, (
        f"registered instruments never emitted: {sorted(dead)} — emit"
        " them, or move them to _DEFINED_NOT_EMITTED_OK with a reason"
    )


def test_metric_string_names_are_unique():
    files = _files()
    defined = collect_defined_instruments(files)
    all_metrics = [m for metrics in defined.values() for m in metrics]
    dupes = {
        name: n for name, n in Counter(all_metrics).items() if n > 1 and name
    }
    assert not dupes, f"metric string registered twice: {dupes}"
    # and no instrument VARIABLE is bound twice either — a second binding
    # would shadow the first at the emission sites
    rebound = {k: v for k, v in defined.items() if len(v) > 1}
    assert not rebound, f"instrument name bound more than once: {rebound}"


def test_audit_sees_a_realistic_surface():
    """Sanity floor so a collector regression can't silently pass the
    equality tests by seeing nothing at all."""
    files = _files()
    defined = collect_defined_instruments(files)
    used = collect_used_instruments(files)
    assert len(defined) >= 30, f"only {len(defined)} definitions found"
    assert len(used) >= 30, f"only {len(used)} emission sites found"
