"""Tests for Requirements (keyed sets) — Add/Compatible/Intersects rules
mirroring pkg/scheduling/requirements_test.go behavior."""
from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.objects import (
    Affinity,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
    PreferredSchedulingTerm,
)
from karpenter_core_tpu.scheduling.requirement import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    OP_NOT_IN,
    Requirement,
)
from karpenter_core_tpu.scheduling.requirements import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
    Requirements,
)

ZONE = apilabels.LABEL_TOPOLOGY_ZONE


def reqs(*items) -> Requirements:
    return Requirements([Requirement.new(k, op, vals) for k, op, vals in items])


class TestAdd:
    def test_add_intersects_on_collision(self):
        r = reqs((ZONE, OP_IN, ["a", "b"]))
        r.add(Requirement.new(ZONE, OP_IN, ["b", "c"]))
        assert r.get(ZONE).sorted_values() == ["b"]

    def test_add_disjoint_becomes_empty(self):
        r = reqs((ZONE, OP_IN, ["a"]))
        r.add(Requirement.new(ZONE, OP_IN, ["b"]))
        assert r.get(ZONE).length() == 0
        assert r.get(ZONE).operator() == OP_DOES_NOT_EXIST

    def test_undefined_key_reads_as_exists(self):
        r = Requirements()
        assert r.get("anything").operator() == OP_EXISTS


class TestIntersects:
    def test_overlap_ok(self):
        a = reqs((ZONE, OP_IN, ["a", "b"]))
        b = reqs((ZONE, OP_IN, ["b", "c"]))
        assert not a.intersects(b)

    def test_disjoint_fails(self):
        a = reqs((ZONE, OP_IN, ["a"]))
        b = reqs((ZONE, OP_IN, ["b"]))
        assert a.intersects(b)

    def test_disjoint_keys_ignored(self):
        a = reqs((ZONE, OP_IN, ["a"]))
        b = reqs(("other", OP_IN, ["b"]))
        assert not a.intersects(b)

    def test_both_negative_empty_intersection_ok(self):
        # NotIn vs DoesNotExist: empty intersection allowed when both negative
        # (requirements.go:288-296)
        a = reqs((ZONE, OP_DOES_NOT_EXIST, []))
        b = reqs((ZONE, OP_NOT_IN, ["a"]))
        assert not a.intersects(b)

    def test_positive_vs_does_not_exist_fails(self):
        a = reqs((ZONE, OP_IN, ["a"]))
        b = reqs((ZONE, OP_DOES_NOT_EXIST, []))
        assert a.intersects(b)


class TestCompatible:
    def test_well_known_undefined_allowed(self):
        node = Requirements()
        pod = reqs((ZONE, OP_IN, ["a"]))
        assert node.is_compatible(pod, ALLOW_UNDEFINED_WELL_KNOWN_LABELS)

    def test_custom_undefined_denied(self):
        node = Requirements()
        pod = reqs(("mycompany.io/team", OP_IN, ["infra"]))
        assert not node.is_compatible(pod, ALLOW_UNDEFINED_WELL_KNOWN_LABELS)

    def test_custom_undefined_negative_allowed(self):
        node = Requirements()
        pod = reqs(("mycompany.io/team", OP_NOT_IN, ["infra"]))
        assert node.is_compatible(pod, ALLOW_UNDEFINED_WELL_KNOWN_LABELS)

    def test_custom_defined_intersecting_allowed(self):
        node = reqs(("mycompany.io/team", OP_IN, ["infra", "web"]))
        pod = reqs(("mycompany.io/team", OP_IN, ["infra"]))
        assert node.is_compatible(pod, ALLOW_UNDEFINED_WELL_KNOWN_LABELS)

    def test_compatible_is_directional(self):
        # node side defines; pod side undefined custom key is fine
        node = reqs(("mycompany.io/team", OP_IN, ["infra"]))
        pod = Requirements()
        assert node.is_compatible(pod, ALLOW_UNDEFINED_WELL_KNOWN_LABELS)


class TestPodRequirements:
    def test_node_selector(self):
        pod = Pod(node_selector={ZONE: "a"})
        r = Requirements.from_pod(pod)
        assert r.get(ZONE).sorted_values() == ["a"]

    def test_required_affinity_first_term(self):
        pod = Pod(
            affinity=Affinity(
                node_affinity=NodeAffinity(
                    required=[
                        NodeSelectorTerm(
                            match_expressions=(
                                NodeSelectorRequirement(ZONE, OP_IN, ("a", "b")),
                            )
                        ),
                        NodeSelectorTerm(
                            match_expressions=(
                                NodeSelectorRequirement(ZONE, OP_IN, ("c",)),
                            )
                        ),
                    ]
                )
            )
        )
        r = Requirements.from_pod(pod)
        # only the first term is used; the relaxation loop pops terms
        assert r.get(ZONE).sorted_values() == ["a", "b"]

    def test_preferred_promoted_when_no_required(self):
        pod = Pod(
            affinity=Affinity(
                node_affinity=NodeAffinity(
                    preferred=[
                        PreferredSchedulingTerm(
                            weight=1,
                            preference=NodeSelectorTerm(
                                match_expressions=(
                                    NodeSelectorRequirement(ZONE, OP_IN, ("low",)),
                                )
                            ),
                        ),
                        PreferredSchedulingTerm(
                            weight=10,
                            preference=NodeSelectorTerm(
                                match_expressions=(
                                    NodeSelectorRequirement(ZONE, OP_IN, ("high",)),
                                )
                            ),
                        ),
                    ]
                )
            )
        )
        r = Requirements.from_pod(pod)
        assert r.get(ZONE).sorted_values() == ["high"]

    def test_preferred_folds_even_with_required(self):
        # heaviest preferred term is treated as required unconditionally;
        # the relaxation loop removes it later (requirements.go:96-103)
        pod = Pod(
            affinity=Affinity(
                node_affinity=NodeAffinity(
                    required=[
                        NodeSelectorTerm(
                            match_expressions=(
                                NodeSelectorRequirement("inst", OP_IN, ("t1",)),
                            )
                        )
                    ],
                    preferred=[
                        PreferredSchedulingTerm(
                            weight=5,
                            preference=NodeSelectorTerm(
                                match_expressions=(
                                    NodeSelectorRequirement(ZONE, OP_IN, ("a",)),
                                )
                            ),
                        )
                    ],
                )
            )
        )
        r = Requirements.from_pod(pod)
        assert r.get(ZONE).sorted_values() == ["a"]
        assert r.get("inst").sorted_values() == ["t1"]

    def test_to_labels_excludes_well_known(self):
        r = Requirements(
            [
                Requirement.new(ZONE, OP_IN, ["a"]),
                Requirement.new("mycompany.io/team", OP_IN, ["infra"]),
            ]
        )
        assert r.to_labels() == {"mycompany.io/team": "infra"}

    def test_strict_ignores_preferred(self):
        pod = Pod(
            affinity=Affinity(
                node_affinity=NodeAffinity(
                    preferred=[
                        PreferredSchedulingTerm(
                            weight=1,
                            preference=NodeSelectorTerm(
                                match_expressions=(
                                    NodeSelectorRequirement(ZONE, OP_IN, ("x",)),
                                )
                            ),
                        )
                    ]
                )
            )
        )
        r = Requirements.from_pod_strict(pod)
        assert not r.has(ZONE)


class TestTaints:
    def test_tolerates(self):
        from karpenter_core_tpu.api.objects import Taint, Toleration
        from karpenter_core_tpu.scheduling.taints import Taints

        taints = Taints([Taint(key="gpu", value="true", effect="NoSchedule")])
        assert taints.tolerates(Pod())  # fails: no toleration
        assert not taints.tolerates(
            Pod(tolerations=[Toleration(key="gpu", operator="Exists")])
        )
        assert not taints.tolerates(
            Pod(
                tolerations=[
                    Toleration(key="gpu", operator="Equal", value="true")
                ]
            )
        )
        assert taints.tolerates(
            Pod(
                tolerations=[
                    Toleration(key="gpu", operator="Equal", value="false")
                ]
            )
        )
        # wildcard toleration (empty key + Exists)
        assert not taints.tolerates(
            Pod(tolerations=[Toleration(operator="Exists")])
        )


class TestResources:
    def test_arithmetic(self):
        from karpenter_core_tpu.utils import resources

        a = {"cpu": 1.0, "memory": 2.0}
        b = {"cpu": 0.5, "pods": 1.0}
        assert resources.merge(a, b) == {"cpu": 1.5, "memory": 2.0, "pods": 1.0}
        assert resources.subtract(a, b) == {"cpu": 0.5, "memory": 2.0}
        assert resources.fits({"cpu": 1.0}, {"cpu": 1.0, "memory": 5})
        assert not resources.fits({"cpu": 1.1}, {"cpu": 1.0})
        # negative totals never fit (resources.go:217-222)
        assert not resources.fits({}, {"cpu": -1.0})

    def test_requests_for_pods_adds_pod_count(self):
        from karpenter_core_tpu.utils import resources

        pods = [Pod(resource_requests={"cpu": 1.0}) for _ in range(3)]
        total = resources.requests_for_pods(*pods)
        assert total["cpu"] == 3.0
        assert total["pods"] == 3.0

    def test_parse_quantity(self):
        from karpenter_core_tpu.api.objects import parse_quantity

        assert parse_quantity("100m") == 0.1
        assert parse_quantity("1Gi") == 2**30
        assert parse_quantity("2") == 2.0
        assert parse_quantity(1.5) == 1.5
