"""ISSUE 11 — the rangecheck abstract domain and the decode-net clamps.

Three layers under test:

1. the RangeDataflow engine (tools/graftlint/dataflow.py): interval
   arithmetic and hull joins, the union/intersection taint-vs-guard
   split, cross-file call-graph propagation through constructor/attribute
   summaries, and termination under recursion (widening to top);
2. the sentinel registry: GL602's gang domain seeds from
   solver/gangs.GANG_SENTINELS — the single source the kernel and the
   prep layer import;
3. the decode-net fixes the GL601 audit landed: Gt/Lt bounds clamp to the
   sentinel range before the int32 narrowing in vocab, and the wire's
   max_slots clamps to the slot hard cap at decode.
"""
from __future__ import annotations

import ast
import textwrap
from pathlib import Path

import numpy as np
import pytest

from tools.graftlint import dataflow
from tools.graftlint.engine import ParsedFile


def _pf(src: str, relpath: str = "karpenter_core_tpu/solver/mini.py"):
    return ParsedFile(Path(relpath), relpath, textwrap.dedent(src))


def _absval(df, pf, expr: str, fn_name: str):
    fn = next(
        n for n in pf.walk(ast.FunctionDef) if n.name == fn_name
    )
    return df.absval(pf, ast.parse(expr, mode="eval").body, fn)


class TestRangeDataflowEngine:
    def test_interval_hull_join_across_reassignment(self):
        pf = _pf(
            """
            def f(flag):
                x = 1
                if flag:
                    x = 5
                return x
            """
        )
        df = dataflow.RangeDataflow([pf])
        v = _absval(df, pf, "x", "f")
        assert (v.lo, v.hi) == (1, 5)
        assert v.values == {1, 5}

    def test_clamp_pattern_bounds_unknown_input(self):
        pf = _pf(
            """
            def f(t):
                y = min(max(float(t), -1.0), 1.0)
                return y
            """
        )
        df = dataflow.RangeDataflow([pf])
        v = _absval(df, pf, "y", "f")
        assert (v.lo, v.hi) == (-1.0, 1.0)

    def test_augassign_accumulates_the_hull(self):
        pf = _pf(
            """
            def f(a, b):
                cost = 1.0
                cost += min(max(float(a), -1.0), 1.0)
                cost += min(max(float(b), -8.0), 8.0)
                return cost
            """
        )
        df = dataflow.RangeDataflow([pf])
        v = _absval(df, pf, "cost", "f")
        assert (v.lo, v.hi) == (-8.0, 10.0)

    def test_guards_intersect_taints_union_on_join(self):
        a = dataflow.AbsVal(taints={dataflow.WIRE}, guards={dataflow.CLAMPED})
        b = dataflow.AbsVal(taints=set(), guards=set())
        a.join(b)
        assert dataflow.WIRE in a.taints  # union: tainted anywhere
        assert dataflow.CLAMPED not in a.guards  # intersection: all paths

    def test_normalizer_call_grants_the_clamped_guard(self):
        pf = _pf(
            """
            def f(raw):
                t = priority_tier(int(raw))
                return t
            """
        )
        df = dataflow.RangeDataflow([pf])
        v = _absval(df, pf, "t", "f")
        assert dataflow.CLAMPED in v.guards
        assert v.fits_dtype("int32")

    def test_wire_seed_and_cross_function_attr_summary(self):
        """The interprocedural chain GL601 resolves: a decode function's
        constructor kwarg records a wire-tainted attribute summary that an
        attribute read in ANOTHER function (file) observes."""
        pf = _pf(
            """
            class Claim:
                pass

            def _decode_claim(d):
                return Claim(weight=int(d["weight"]))
            """
        )
        pf2 = _pf(
            """
            def use(c):
                w = c.weight
                return w
            """,
            relpath="karpenter_core_tpu/models/mini_use.py",
        )
        df = dataflow.RangeDataflow([pf, pf2])
        v = _absval(df, pf2, "w", "use")
        assert dataflow.WIRE in v.taints
        assert dataflow.CLAMPED not in v.guards

    def test_recursion_widens_to_top_and_terminates(self):
        """Widening termination: a self-recursive accumulator must yield
        the unknown interval instead of looping the fixpoint."""
        pf = _pf(
            """
            def grow(n):
                if n <= 0:
                    return 0
                return grow(n - 1) + 1

            def f(n):
                g = grow(n)
                return g
            """
        )
        df = dataflow.RangeDataflow([pf])  # must terminate
        v = _absval(df, pf, "g", "f")
        assert not v.within(-(2 ** 31), 2 ** 31)  # unknown, never "fits"

    def test_sentinel_liveness_through_named_constants(self):
        """Module-level constants resolve, so the hoisted GANG_* names
        keep -2 positively live where the literal used to be."""
        pf = _pf(
            """
            import numpy as np

            GANG_FREE = -1
            GANG_FALLBACK_STRADDLING = -2

            def f():
                gang_of_class = np.full((4,), GANG_FREE, dtype=np.int32)
                gang_of_class[0] = GANG_FALLBACK_STRADDLING
                return gang_of_class
            """,
            relpath="karpenter_core_tpu/ops/mini_gang.py",
        )
        df = dataflow.RangeDataflow([pf])
        v = _absval(df, pf, "gang_of_class", "f")
        assert v.values == {-1, -2}
        assert "gang" in v.sentinels

    def test_pad_taint_set_by_pad_and_cleared_by_where(self):
        pf = _pf(
            """
            import jax.numpy as jnp

            def f(scores, n):
                padded = jnp.pad(scores, (0, 8))
                masked = jnp.where(jnp.arange(16) < n, padded, 1e30)
                return masked
            """,
            relpath="karpenter_core_tpu/ops/mini_pad.py",
        )
        df = dataflow.RangeDataflow([pf])
        p = _absval(df, pf, "padded", "f")
        m = _absval(df, pf, "masked", "f")
        assert dataflow.PAD in p.taints and dataflow.MASKED not in p.guards
        assert dataflow.MASKED in m.guards

    def test_astype_narrowing_widens_unproven_interval(self):
        pf = _pf(
            """
            import numpy as np

            def f(x64):
                small = x64.astype(np.int32)
                return small
            """
        )
        df = dataflow.RangeDataflow([pf])
        v = _absval(df, pf, "small", "f")
        assert v.dtype == "int32"
        assert not v.known  # the cast wraps; nothing is proven


class TestSentinelRegistry:
    def test_gang_domain_seeds_from_solver_gangs(self):
        from karpenter_core_tpu.solver import gangs

        dom = dataflow.SENTINEL_DOMAINS["gang"]["values"]
        assert dom == gangs.GANG_SENTINELS
        assert gangs.GANG_SENTINELS["gang-free"] == gangs.GANG_FREE == -1
        assert (
            gangs.GANG_SENTINELS["fallback-straddling"]
            == gangs.GANG_FALLBACK_STRADDLING
            == -2
        )

    def test_kernel_and_prep_import_the_constants(self):
        from karpenter_core_tpu.models import provisioner
        from karpenter_core_tpu.ops import gangsched

        assert gangsched.GANG_FREE == -1
        assert provisioner.gangmod.GANG_FREE == -1
        assert provisioner.gangmod.GANG_FALLBACK_STRADDLING == -2


class TestDecodeNetClamps:
    def test_vocab_gt_lt_clamp_to_sentinel_bounds(self):
        """A hostile 2**40 Gt bound must not wrap inside the int32 device
        planes — it clamps to the sentinel range, which is exact within
        the closed world (every vocab value lies strictly inside)."""
        from karpenter_core_tpu.scheduling.requirement import Requirement
        from karpenter_core_tpu.scheduling.requirements import Requirements
        from karpenter_core_tpu.solver.vocab import (
            GT_NONE,
            LT_NONE,
            Vocab,
            encode_requirements_batch,
        )

        reqs = Requirements()
        reqs.add(Requirement("size", complement=True, greater_than=2 ** 40))
        reqs.add(Requirement("rank", complement=True, less_than=-(2 ** 40)))
        v = Vocab()
        v.observe_requirements(reqs)
        frozen = v.finalize()
        masks = encode_requirements_batch(frozen, [reqs])
        assert masks.gt.dtype == np.int32 and masks.lt.dtype == np.int32
        kid_size = frozen.keys["size"]
        kid_rank = frozen.keys["rank"]
        # pre-fix this wrapped to a NEGATIVE int32 (2**40 % 2**32 ... sign
        # flip), silently admitting everything the bound excluded
        assert masks.gt[0, kid_size] == LT_NONE
        assert masks.lt[0, kid_rank] == GT_NONE
        assert (masks.gt[0] >= GT_NONE).all()
        assert (masks.lt[0] <= LT_NONE).all()

    def test_codec_clamp_slots(self):
        from karpenter_core_tpu.solver.codec import _MAX_SLOTS_CAP, _clamp_slots

        assert _clamp_slots(256) == 256
        assert _clamp_slots(2 ** 40) == _MAX_SLOTS_CAP
        assert _clamp_slots(0) == 1
        assert _clamp_slots(-5) == 1
        with pytest.raises(ValueError):
            _clamp_slots("not-a-number")

    def test_decode_solve_request_clamps_hostile_max_slots(self):
        from karpenter_core_tpu.solver import codec

        wire = codec.encode_solve_request(
            nodepools=[],
            instance_types={},
            existing_nodes=[],
            daemonset_pods=[],
            pods=[],
            topology=None,
            max_slots=2 ** 40,
        )
        decoded = codec.decode_solve_request(wire)
        assert decoded["max_slots"] == codec._MAX_SLOTS_CAP

    def test_decode_frontier_request_clamps_hostile_max_slots(self):
        from karpenter_core_tpu.solver import codec

        wire = codec.encode_frontier_request(
            nodepools=[],
            instance_types={},
            cand_nodes=[],
            keep_nodes=[],
            daemonset_pods=[],
            base_pods=[],
            candidate_pods=[],
            max_slots=2 ** 40,
        )
        decoded = codec.decode_frontier_request(wire)
        assert decoded["max_slots"] == codec._MAX_SLOTS_CAP

    def test_legit_max_slots_roundtrips_unchanged(self):
        from karpenter_core_tpu.solver import codec

        wire = codec.encode_solve_request(
            nodepools=[], instance_types={}, existing_nodes=[],
            daemonset_pods=[], pods=[], topology=None, max_slots=1024,
        )
        assert codec.decode_solve_request(wire)["max_slots"] == 1024
