"""Observability wiring: after an e2e run the registry carries non-zero
values for scheduler, disruption, state, exporter, and solver metrics
(VERDICT r3 item 7; reference scheduling/metrics.go, disruption/metrics.go,
state/metrics.go, pkg/controllers/metrics/).
"""
from tests.helpers import make_nodepool, make_pod
from tests.test_e2e import new_operator, replicated

from karpenter_core_tpu.metrics import wiring as m
from karpenter_core_tpu.metrics.registry import REGISTRY


class TestMetricsWiring:
    def test_e2e_run_populates_registry(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        for i in range(6):
            op.kube.create(replicated(make_pod(cpu=3.0, name=f"w{i}")))
        op.run_until_idle()
        # scheduler metrics
        assert m.SCHEDULING_DURATION.totals, "no solve timed"
        assert m.QUEUE_DEPTH.value() > 0
        # state + exporters
        assert m.CLUSTER_NODE_COUNT.value() >= 1
        assert m.CLUSTER_SYNCED.value() == 1.0
        assert m.PODS_STATE.value({"phase": "Running"}) == 6
        assert m.NODES_ALLOCATABLE.value({"resource_type": "cpu"}) > 0
        assert m.NODEPOOL_USAGE.value(
            {"nodepool": "default", "resource_type": "cpu"}
        ) > 0
        # drive a consolidation so disruption metrics move
        for p in op.kube.list_pods()[2:]:
            op.kube.delete(p)
        op.clock.step(40.0)
        op.run_until_idle()
        eligible_seen = any(
            v > 0 for v in m.DISRUPTION_ELIGIBLE_NODES.values.values()
        )
        decisions_seen = any(
            v > 0 for v in m.DISRUPTION_DECISIONS.values.values()
        )
        assert eligible_seen and decisions_seen
        # render carries it all in exposition format
        text = REGISTRY.render()
        assert "karpenter_provisioner_scheduling_duration_seconds_count" in text
        assert "karpenter_voluntary_disruption_decisions_total" in text

    def test_device_solver_metrics_and_fallback_counter(self):
        before_fallback = sum(m.SOLVER_HOST_FALLBACK_PODS.values.values())
        op = new_operator("tpu")
        op.kube.create(make_nodepool())
        # hostPort + spread pods are topology-ineligible -> host fallback
        from tests.helpers import make_diverse_pods

        for p in make_diverse_pods(12, seed=0, with_topology=True):
            op.kube.create(p)
        hp = make_pod(cpu=0.5, name="hp", spread_zone=True)
        hp.host_ports = [("0.0.0.0", 9000, "TCP")]
        op.kube.create(hp)
        op.run_until_idle()
        assert m.SOLVER_SOLVE_DURATION.totals, "device solve not timed"
        assert m.SOLVER_PREPARE_DURATION.totals
        assert m.SOLVER_KERNEL_DURATION.totals
        assert m.SOLVER_DECODE_DURATION.totals
        after_fallback = sum(m.SOLVER_HOST_FALLBACK_PODS.values.values())
        assert after_fallback > before_fallback, "fallback went uncounted"


class TestConditionTransitions:
    """Status-controller role (controllers.go:103-105): every condition flip
    emits a transition counter + event; deleted objects drop their series."""

    def test_transitions_counted_and_events_published(self):
        op = new_operator()
        before = sum(m.STATUS_CONDITION_TRANSITIONS.values.values())
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle()
        after = sum(m.STATUS_CONDITION_TRANSITIONS.values.values())
        # a claim went Launched/Registered/Initialized at minimum
        assert after - before >= 3
        assert m.STATUS_CONDITION_TRANSITIONS.value(
            {"kind": "NodeClaim", "type": "Launched", "status": "True"}
        ) >= 1
        assert any(
            e.involved_object.startswith("NodeClaim/")
            and "Initialized" in e.reason
            for e in op.recorder.events
        )

    def test_repeat_reconcile_does_not_recount(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle()
        snap = dict(m.STATUS_CONDITION_TRANSITIONS.values)
        op.run_until_idle()
        assert dict(m.STATUS_CONDITION_TRANSITIONS.values) == snap

    def test_deleted_object_drops_condition_series(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle()
        assert m.STATUS_CONDITION_COUNT.value(
            {"kind": "NodeClaim", "type": "Launched", "status": "True"}
        ) >= 1
        pod = op.kube.get(
            __import__("karpenter_core_tpu.api.objects", fromlist=["Pod"]).Pod,
            "p0",
        )
        pod.metadata.owner_references = []
        op.kube.delete(pod)
        op.run_until_idle()  # consolidation deletes the empty node + claim
        assert not op.kube.list_nodeclaims()
        assert m.STATUS_CONDITION_COUNT.value(
            {"kind": "NodeClaim", "type": "Launched", "status": "True"}
        ) == 0


class TestStaleGaugeCleanup:
    def test_phase_and_nodepool_series_clear(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle()
        assert m.PODS_STATE.value({"phase": "Running"}) == 1
        assert m.NODEPOOL_USAGE.value(
            {"nodepool": "default", "resource_type": "cpu"}
        ) > 0
        pod = op.kube.get(
            __import__("karpenter_core_tpu.api.objects", fromlist=["Pod"]).Pod,
            "p0",
        )
        pod.metadata.owner_references = []
        op.kube.delete(pod)
        for pool in op.kube.list_nodepools():
            op.kube.delete(pool)
        op.run_until_idle()
        # the Running phase and the nodepool usage series are gone, not
        # frozen at their last values
        assert m.PODS_STATE.value({"phase": "Running"}) == 0
        assert m.NODEPOOL_USAGE.value(
            {"nodepool": "default", "resource_type": "cpu"}
        ) == 0
