"""Tests for the cloud-provider layer and kwok catalog."""
from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider, fake_instance_types
from karpenter_core_tpu.cloudprovider.kwok import bench_catalog, build_catalog
from karpenter_core_tpu.cloudprovider.types import (
    order_by_price,
    satisfies_min_values,
    truncate_instance_types,
)
from karpenter_core_tpu.scheduling import Requirement, Requirements


class TestKwokCatalog:
    def test_default_catalog_size(self):
        catalog = build_catalog()
        # 12 cpu x 3 families x 2 os x 2 arch = 144 (gen_instance_types.go:73-115)
        assert len(catalog) == 144
        names = {it.name for it in catalog}
        assert len(names) == 144

    def test_offerings_lattice(self):
        it = build_catalog()[0]
        # 4 zones x {spot, on-demand}
        assert len(it.offerings) == 8
        spot = [o for o in it.offerings if o.capacity_type == L.CAPACITY_TYPE_SPOT]
        od = [o for o in it.offerings if o.capacity_type == L.CAPACITY_TYPE_ON_DEMAND]
        assert len(spot) == 4 and len(od) == 4
        assert abs(spot[0].price - 0.7 * od[0].price) < 1e-9

    def test_bench_catalog_is_800(self):
        assert len(bench_catalog(800)) == 800

    def test_allocatable_subtracts_overhead(self):
        it = build_catalog()[0]
        assert it.allocatable()["cpu"] < it.capacity["cpu"]

    def test_order_by_price(self):
        catalog = build_catalog()
        reqs = Requirements(
            [Requirement.new(L.CAPACITY_TYPE_LABEL_KEY, "In", [L.CAPACITY_TYPE_ON_DEMAND])]
        )
        ordered = order_by_price(catalog, reqs)
        prices = [
            it.offerings.available().compatible(reqs).cheapest().price
            for it in ordered
        ]
        assert prices == sorted(prices)


class TestMinValues:
    def test_satisfied(self):
        its = fake_instance_types(5)
        reqs = Requirements(
            [
                Requirement.new(
                    L.LABEL_INSTANCE_TYPE,
                    "In",
                    [it.name for it in its],
                    min_values=3,
                )
            ]
        )
        _, err = satisfies_min_values(its, reqs)
        assert err is None

    def test_unsatisfied(self):
        its = fake_instance_types(2)
        reqs = Requirements(
            [
                Requirement.new(
                    L.LABEL_INSTANCE_TYPE,
                    "In",
                    [it.name for it in its],
                    min_values=5,
                )
            ]
        )
        _, err = satisfies_min_values(its, reqs)
        assert err is not None

    def test_truncate_preserves_min_values(self):
        its = fake_instance_types(10)
        reqs = Requirements(
            [
                Requirement.new(
                    L.LABEL_INSTANCE_TYPE,
                    "In",
                    [it.name for it in its],
                    min_values=8,
                )
            ]
        )
        truncated, err = truncate_instance_types(its, reqs, 5)
        # truncation to 5 would violate minValues=8 -> keeps original + error
        assert err is not None
        assert len(truncated) == 10


class TestFakeProvider:
    def test_create_records_and_hydrates(self):
        from karpenter_core_tpu.api.nodeclaim import NodeClaim

        cp = FakeCloudProvider()
        nc = NodeClaim()
        nc.metadata.name = "test-claim"
        out = cp.create(nc)
        assert out.status.provider_id.startswith("fake://")
        assert out.is_launched()
        assert len(cp.create_calls) == 1
        assert cp.get(out.status.provider_id) is out

    def test_error_injection(self):
        cp = FakeCloudProvider()
        cp.next_create_error = RuntimeError("boom")
        from karpenter_core_tpu.api.nodeclaim import NodeClaim

        try:
            cp.create(NodeClaim())
            assert False
        except RuntimeError:
            pass
        # error consumed; next create succeeds
        cp.create(NodeClaim())


class TestBudgets:
    def test_percentage_budget(self):
        from karpenter_core_tpu.api.nodepool import Budget

        assert Budget(nodes="10%").allowed_disruptions(50) == 5
        assert Budget(nodes="3").allowed_disruptions(50) == 3
        assert Budget(nodes="0").allowed_disruptions(50) == 0

    def test_cron_window(self):
        import calendar

        from karpenter_core_tpu.api.nodepool import Budget

        # active 09:00-10:00 UTC daily
        b = Budget(nodes="0", schedule="0 9 * * *", duration=3600.0)
        at_930 = calendar.timegm((2026, 7, 29, 9, 30, 0, 0, 0, 0))
        at_1130 = calendar.timegm((2026, 7, 29, 11, 30, 0, 0, 0, 0))
        assert b.is_active(at_930)
        assert not b.is_active(at_1130)

    def test_reason_filtering(self):
        from karpenter_core_tpu.api.nodepool import (
            Budget,
            NodePool,
            REASON_DRIFTED,
            REASON_UNDERUTILIZED,
        )

        np = NodePool()
        np.spec.disruption.budgets = [
            Budget(nodes="2", reasons=[REASON_DRIFTED]),
            Budget(nodes="5"),
        ]
        assert np.allowed_disruptions_by_reason(REASON_DRIFTED, 100) == 2
        assert np.allowed_disruptions_by_reason(REASON_UNDERUTILIZED, 100) == 5
