"""topoaware (ISSUE 20): rank- and network-topology-aware gang placement
with verified distance bounds.

Five layers of proof (the twin monitor/ledger layer lives in
tests/test_twin.py, the aware-vs-blind fleet comparison in bench cfg18):

* hop-metric units — the single-source network distance algebra
  (solver/gangs): hop_distance's pessimistic reporting levels, the SOUND
  placement_hop_bound (a missing rack label can never manufacture a
  violation), and the GL601 range clamps that keep hostile wire ints off
  the int32 planes;
* rack-catalog units — ops/topoplan.plan_racks lowers the label
  hierarchy to a hop matrix + slot/template domain planes, returns None
  on a rack-less catalog (the whole subsystem's disengage switch), and
  gang_anchors spreads gang demand across domain NEIGHBORHOODS so two
  gangs never stack onto capacity one zone cannot hold;
* off-by-default parity — problems without rack labels produce
  BYTE-IDENTICAL result wires with _prepare_topoaware surgically
  removed, and a racked catalog without gangs never reaches it;
* engaged solves — a comms-sensitive ranked gang on a racked
  interleaved-zone fleet lands inside its declared hop bound with ranks
  network-adjacent; an unsatisfiable bound strips the WHOLE gang
  (enforce_distance, atomically) rather than binding a straggler; a
  hops bound at the ceiling is soft and constrains nothing;
* verifier mutations — a forged placement provably exceeding its bound
  and a forged rank-scattered gang each reject with the typed
  gang_distance reason riding solver_result_rejected_total{reason},
  while a rack-less cluster view soundly skips (no false rejection).
"""
from __future__ import annotations

import copy

import numpy as np
import pytest

from tests.helpers import GIB, make_nodepool, make_pod

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.objects import ObjectMeta, Pod
from karpenter_core_tpu.cloudprovider.kwok import build_catalog
from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
    SimNode,
)
from karpenter_core_tpu.metrics import wiring as m
from karpenter_core_tpu.models.provisioner import DeviceScheduler
from karpenter_core_tpu.ops import topoplan
from karpenter_core_tpu.solver import codec
from karpenter_core_tpu.solver import gangs as gangmod
from karpenter_core_tpu.solver import verify as verifymod
from karpenter_core_tpu.solver.gangs import (
    GANG_ANNOTATION,
    GANG_MAX_HOPS_ANNOTATION,
    GANG_MIN_SIZE_ANNOTATION,
    GANG_RANK_ANNOTATION,
    MAX_HOP_DISTANCE,
    gang_max_hops,
    gang_rank,
    hop_distance,
    placement_hop_bound,
    pod_gang_rank,
    pod_gang_sig,
)
from karpenter_core_tpu.solver.verify import ResultVerifier

BASE_LABELS = {
    L.LABEL_OS: "linux",
    L.LABEL_ARCH: "amd64",
    L.CAPACITY_TYPE_LABEL_KEY: "on-demand",
    L.NODEPOOL_LABEL_KEY: "default",
}


def topo_labels(zone, superpod=None, rack=None):
    out = {L.LABEL_TOPOLOGY_ZONE: zone}
    if superpod:
        out[L.LABEL_TOPOLOGY_SUPERPOD] = superpod
    if rack:
        out[L.LABEL_TOPOLOGY_RACK] = rack
    return out


def racked_existing(n=8, with_topo=True, available_cpu=6.5):
    """Zones interleaved in slot order (the adversarial order for a
    distance-blind first-fit): per zone, racks of two nodes, one superpod.
    Fresh capacity (small_catalog) tops out at 2 cpu, so 3-cpu gang
    members can only land here."""
    nodes = []
    for i in range(n):
        zone = "zone-a" if i % 2 == 0 else "zone-b"
        zi = i // 2  # creation order within the zone
        labels = {
            **BASE_LABELS,
            L.LABEL_TOPOLOGY_ZONE: zone,
            L.LABEL_HOSTNAME: f"exist-{i}",
        }
        if with_topo:
            labels[L.LABEL_TOPOLOGY_RACK] = f"{zone}-r{zi // 2}"
            labels[L.LABEL_TOPOLOGY_SUPERPOD] = f"{zone}-s{zi // 4}"
        nodes.append(SimNode(
            name=f"exist-{i}",
            labels=labels,
            taints=[],
            available={
                "cpu": available_cpu, "memory": 8 * GIB, "pods": 100.0,
            },
            capacity={"cpu": 16.0, "memory": 16 * GIB, "pods": 110.0},
            initialized=True,
        ))
    return nodes


def ranked_gang(name="tgang", size=4, max_hops=2, cpu=3.0, ranks=True):
    pods = []
    for i in range(size):
        ann = {
            GANG_ANNOTATION: name,
            GANG_MIN_SIZE_ANNOTATION: str(size),
        }
        if max_hops is not None:
            ann[GANG_MAX_HOPS_ANNOTATION] = str(max_hops)
        if ranks:
            ann[GANG_RANK_ANNOTATION] = str(i)
        pods.append(Pod(
            metadata=ObjectMeta(name=f"{name}-{i}", annotations=ann),
            resource_requests={"cpu": cpu, "memory": 0.25 * GIB},
        ))
    return pods


def small_catalog():
    return build_catalog(cpu_grid=[1, 2])


def _wire(results):
    return codec.encode_solve_results(results, 0.0)


def _scheduler(existing, devices=1, verify=True):
    pools = [make_nodepool()]
    return DeviceScheduler(
        pools, {"default": list(small_catalog())},
        existing_nodes=list(existing), max_slots=64, devices=devices,
        verify=verify,
    )


# ---------------------------------------------------------------------------
# hop-metric units
# ---------------------------------------------------------------------------


class TestHopMetric:
    def test_hop_distance_levels(self):
        a = topo_labels("za", "za-s0", "za-r0")
        assert hop_distance(a, dict(a)) == 0
        assert hop_distance(a, topo_labels("za", "za-s0", "za-r1")) == 1
        assert hop_distance(a, topo_labels("za", "za-s1", "za-r9")) == 2
        assert hop_distance(a, topo_labels("zb", "zb-s0", "zb-r0")) == 3

    def test_hop_distance_missing_labels_are_pessimistic(self):
        # reporting metric: an unknown level can only RAISE the distance
        assert hop_distance({}, {}) == MAX_HOP_DISTANCE
        assert hop_distance(None, topo_labels("za")) == MAX_HOP_DISTANCE
        same_zone_no_rack = topo_labels("za")
        assert hop_distance(same_zone_no_rack, topo_labels("za")) == 2

    def test_placement_bound_skips_unattributable(self):
        # sound rejection bound: rack-less placements never count, and
        # <= 1 attributable placement proves nothing
        racked = topo_labels("za", "za-s0", "za-r0")
        assert placement_hop_bound([]) == 0
        assert placement_hop_bound([racked, topo_labels("zb"), None]) == 0

    def test_placement_bound_levels(self):
        r = lambda z, s, k: topo_labels(z, s, k)
        assert placement_hop_bound(
            [r("za", "s0", "r0"), r("za", "s0", "r0")]) == 0
        assert placement_hop_bound(
            [r("za", "s0", "r0"), r("za", "s0", "r1")]) == 1
        assert placement_hop_bound(
            [r("za", "s0", "r0"), r("za", "s1", "r2")]) == 2
        assert placement_hop_bound(
            [r("za", "s0", "r0"), r("zb", "s9", "r9")]) == MAX_HOP_DISTANCE

    def test_range_clamps_hold_hostile_ints(self):
        # the GL601-registered normalizers: every decode-net int headed
        # for an int32 plane passes one of these
        assert gang_rank(10 ** 30) == 1 << 20
        assert gang_rank(-5) == 0
        assert gang_max_hops(10 ** 30) == MAX_HOP_DISTANCE
        assert gang_max_hops(-2) == 0

    def test_annotation_parse_clamps_and_tolerates_garbage(self):
        p = ranked_gang(size=1, max_hops=None)[0]
        ann = p.metadata.annotations
        ann[GANG_MAX_HOPS_ANNOTATION] = "999999999999999999999999"
        ann[GANG_RANK_ANNOTATION] = "123456789012345678901234567890"
        assert pod_gang_sig(p)[4] == MAX_HOP_DISTANCE
        assert pod_gang_rank(p) == 1 << 20
        ann[GANG_MAX_HOPS_ANNOTATION] = "-7"
        assert pod_gang_sig(p)[4] == 0
        # malformed -> soft / absent, never a surprise hard bound
        ann[GANG_MAX_HOPS_ANNOTATION] = "garbage"
        ann[GANG_RANK_ANNOTATION] = "1e9"
        assert pod_gang_sig(p)[4] is None
        assert pod_gang_rank(p) is None


# ---------------------------------------------------------------------------
# rack-catalog units (ops/topoplan)
# ---------------------------------------------------------------------------


class TestRackPlan:
    def test_rackless_catalog_returns_none(self):
        # the subsystem's disengage switch: no rack label anywhere ->
        # None -> every downstream plane keeps its parity-neutral default
        assert topoplan.plan_racks(
            [topo_labels("za"), topo_labels("zb")], [topo_labels("za")], 2
        ) is None
        assert topoplan.plan_racks([], [], 0) is None

    def test_hop_matrix_and_domain_planes(self):
        nodes = [
            topo_labels("za", "za-s0", "za-r0"),
            topo_labels("za", "za-s0", "za-r1"),
            topo_labels("za", "za-s1", "za-r2"),
            topo_labels("zb", "zb-s0", "zb-r0"),
            topo_labels("za"),  # rack-less: unattributable slot
        ]
        tmpl = [topo_labels("za", "za-s0", "za-r0"), {}]
        rplan = topoplan.plan_racks(nodes, tmpl, n_slots=5)
        assert rplan is not None
        assert rplan.domains == sorted(rplan.domains)
        assert len(rplan.domains) == 4
        d = {t[2]: i for i, t in enumerate(rplan.domains)}
        assert rplan.hop[d["za-r0"], d["za-r0"]] == 0
        assert rplan.hop[d["za-r0"], d["za-r1"]] == 1  # same superpod
        assert rplan.hop[d["za-r0"], d["za-r2"]] == 2  # same zone
        assert rplan.hop[d["za-r0"], d["zb-r0"]] == 3  # cross zone
        assert (rplan.hop == rplan.hop.T).all()
        assert rplan.slot_domain[4] == topoplan.TOPO_UNKNOWN
        assert rplan.slot_domain[0] == d["za-r0"]
        assert rplan.tmpl_domain.tolist() == [
            d["za-r0"], topoplan.TOPO_UNKNOWN,
        ]

    def test_hop_from_anchor_clips_and_ceilings_unknown(self):
        nodes = [
            topo_labels("za", "za-s0", "za-r0"),
            topo_labels("za", "za-s0", "za-r1"),
            topo_labels("zb", "zb-s0", "zb-r0"),
            topo_labels("za"),  # unattributable
        ]
        rplan = topoplan.plan_racks(nodes, [], n_slots=4)
        anchor = int(rplan.slot_domain[0])
        row = topoplan.hop_from_anchor(rplan, anchor, max_hop=2)
        assert row.tolist() == [0, 1, 2, 2]  # cross-zone 3 clips; unknown
        # sits at the ceiling, so the level fill reaches it last


class TestGangAnchors:
    def _two_zone_plan(self):
        # per zone: two racks of two slots, one superpod -> any anchor's
        # radius-1 neighborhood holds 4 slots, the whole zone 4 slots
        nodes = []
        for zone in ("za", "zb"):
            for r in range(2):
                for _ in range(2):
                    nodes.append(
                        topo_labels(zone, f"{zone}-s0", f"{zone}-r{r}")
                    )
        return topoplan.plan_racks(nodes, [], n_slots=len(nodes))

    def test_single_gang_anchors_where_it_fits(self):
        rplan = self._two_zone_plan()
        anchors = topoplan.gang_anchors(rplan, ["g0"], [2])
        # a 2-slot gang fits one rack: radius 0, first domain in sorted
        # order wins the tie
        assert anchors["g0"] == 0

    def test_second_gang_spreads_to_the_other_zone(self):
        # the neighborhood debit: gang 0 consumes zone za's 4 slots, so
        # gang 1's smallest absorption radius lives in zone zb — the
        # regression that once stacked every gang onto one zone and let
        # enforce_distance strip the overflow gang
        rplan = self._two_zone_plan()
        anchors = topoplan.gang_anchors(rplan, ["g0", "g1"], [4, 4])
        zone_of = {i: t[0] for i, t in enumerate(rplan.domains)}
        assert zone_of[anchors["g0"]] != zone_of[anchors["g1"]]

    def test_template_only_catalog_anchors_on_templates(self):
        tmpl = [
            topo_labels("za", "za-s0", "za-r0"),
            topo_labels("zb", "zb-s0", "zb-r0"),
        ]
        rplan = topoplan.plan_racks([topo_labels("za")], tmpl, n_slots=1)
        anchors = topoplan.gang_anchors(rplan, ["g0"], [1])
        assert anchors["g0"] in range(len(rplan.domains))


# ---------------------------------------------------------------------------
# off-by-default parity
# ---------------------------------------------------------------------------


class TestOffByDefaultTopoParity:
    @pytest.mark.parametrize("devices", [1, 8])
    def test_rackless_gang_problem_byte_identical_wire(
        self, devices, monkeypatch
    ):
        # gangs WITHOUT rack labels anywhere: plan_racks disengages, so
        # surgically removing the preparation must not move a byte
        existing = racked_existing(with_topo=False)
        pods = ranked_gang(size=4, max_hops=2)
        live = _scheduler(existing, devices=devices).solve(
            copy.deepcopy(pods)
        )
        monkeypatch.setattr(
            DeviceScheduler, "_prepare_topoaware",
            lambda self, *a, **kw: None,
        )
        off = _scheduler(existing, devices=devices).solve(
            copy.deepcopy(pods)
        )
        assert _wire(live) == _wire(off)

    def test_racked_catalog_without_gangs_never_prepares(self, monkeypatch):
        def boom(self, *a, **kw):  # pragma: no cover - the assertion
            raise AssertionError("topoaware preparation on a gang-free solve")

        monkeypatch.setattr(DeviceScheduler, "_prepare_topoaware", boom)
        existing = racked_existing(with_topo=True)
        res = _scheduler(existing).solve(
            [make_pod(cpu=1.0, name=f"plain-{i}") for i in range(6)]
        )
        assert not res.pod_errors


# ---------------------------------------------------------------------------
# engaged solves
# ---------------------------------------------------------------------------


def _placement_labels(res, pods, existing):
    """gang member name -> the TRUE labels of the node it bound to."""
    truth = {n.name: dict(n.labels) for n in existing}
    out = {}
    for sim in res.existing_nodes:
        for p in sim.pods:
            out[p.metadata.name] = truth[sim.name]
    return out


class TestEngagedSolve:
    def test_gang_lands_inside_bound_with_ranks_adjacent(self):
        existing = racked_existing(with_topo=True)
        pods = ranked_gang(size=4, max_hops=2)
        sp = copy.deepcopy(pods)
        res = _scheduler(existing).solve(sp)
        assert not res.pod_errors
        placed = _placement_labels(res, sp, existing)
        labs = [placed[f"tgang-{i}"] for i in range(4)]
        # two members per node -> two nodes; the anchor plane keeps them
        # in one rack (bound 0 <= 2), far below the declared bound
        assert placement_hop_bound(labs) <= 2
        assert max(
            hop_distance(a, b)
            for i, a in enumerate(labs) for b in labs[i + 1:]
        ) <= 2
        # rank adjacency: rank-sorted members occupy their domains as
        # non-decreasing topo keys (the verifier's own re-derivation ran
        # too — verify=True — so this is belt and braces)
        keys = [gangmod.topo_sort_key(l) for l in labs]
        assert keys == sorted(keys)

    def test_unsatisfiable_bound_strips_the_whole_gang(self):
        # 1 member per node (available 3.5 cpu), bound 0 = one rack, but
        # racks hold two nodes: provably impossible -> the WHOLE gang
        # reports unschedulable (enforce_distance is atomic like the
        # atomicity backstop), never a bound straggler subset
        existing = racked_existing(with_topo=True, available_cpu=3.5)
        pods = ranked_gang(size=4, max_hops=0)
        sp = copy.deepcopy(pods)
        res = _scheduler(existing).solve(sp)
        assert set(res.pod_errors) == {p.uid for p in sp}
        assert all("hops" in msg for msg in res.pod_errors.values())
        assert not any(s.pods for s in res.existing_nodes)
        assert not res.new_node_claims

    def test_ceiling_bound_is_soft_and_constrains_nothing(self):
        # max-hops at MAX_HOP_DISTANCE constrains nothing (the hostile
        # over-large int clamp lands here too): same impossible-rack
        # geometry as above, yet the gang binds fine across racks
        existing = racked_existing(with_topo=True, available_cpu=3.5)
        pods = ranked_gang(size=4, max_hops=MAX_HOP_DISTANCE)
        res = _scheduler(existing).solve(copy.deepcopy(pods))
        assert not res.pod_errors

    def test_hostile_annotations_solve_and_encode(self):
        # codec clamp regression: astronomically large / negative wire
        # ints ride the annotation parse clamps (gang_rank /
        # gang_max_hops) into the int32 planes without overflow, and the
        # result wire encodes
        existing = racked_existing(with_topo=True)
        pods = ranked_gang(size=4, max_hops=None)
        for i, p in enumerate(pods):
            ann = p.metadata.annotations
            ann[GANG_MAX_HOPS_ANNOTATION] = "888888888888888888888888888"
            ann[GANG_RANK_ANNOTATION] = str(10 ** 30 + i)
        res = _scheduler(existing).solve(copy.deepcopy(pods))
        assert not res.pod_errors
        assert _wire(res)
        neg = ranked_gang(name="neg", size=2, max_hops=None)
        for p in neg:
            p.metadata.annotations[GANG_MAX_HOPS_ANNOTATION] = "-5"
            p.metadata.annotations[GANG_RANK_ANNOTATION] = "-9999999"
        res = _scheduler(existing).solve(copy.deepcopy(neg))
        # -5 clamps to bound 0 (one rack): 2 members fit one node
        assert not res.pod_errors
        assert _wire(res)


# ---------------------------------------------------------------------------
# verifier mutations
# ---------------------------------------------------------------------------


class TestVerifierTopoMutations:
    def _topo_solved(self):
        existing = racked_existing(with_topo=True)
        pods = ranked_gang(size=4, max_hops=2)
        sp = copy.deepcopy(pods)
        sched = _scheduler(existing, verify=False)
        res = sched.solve(sp)
        assert not res.pod_errors
        pools = [make_nodepool()]
        its = {"default": list(small_catalog())}
        verifier = ResultVerifier(pools, its, existing_nodes=existing)
        assert not verifier.verify(res, sp)  # precondition: clean
        return res, sp, pools, its, existing

    def _reasons(self, pools, its, existing, res, sp):
        violations = ResultVerifier(
            pools, its, existing_nodes=existing
        ).verify(res, sp)
        if violations:
            verifymod.reject(violations, path="test")
        return {v.reason for v in violations}

    def _move(self, res, pod_name, to_node):
        """Forge: move one placed pod between existing sims in place."""
        moved = None
        for sim in res.existing_nodes:
            for p in list(sim.pods):
                if p.metadata.name == pod_name:
                    sim.pods.remove(p)
                    moved = p
        assert moved is not None
        for sim in res.existing_nodes:
            if sim.name == to_node:
                sim.pods.append(moved)
                return
        raise AssertionError(f"no sim {to_node!r}")

    def test_forged_bound_exceeding_placement_is_rejected(self):
        res, sp, pools, its, existing = self._topo_solved()
        # one member re-homed across the zone boundary: the provable
        # bound jumps to 3, above the declared 2
        self._move(res, "tgang-3", "exist-1")  # exist-1 is zone-b
        before = dict(m.SOLVER_RESULT_REJECTED.values)
        reasons = self._reasons(pools, its, existing, res, sp)
        assert "gang_distance" in reasons, reasons
        moved = {
            k: v for k, v in m.SOLVER_RESULT_REJECTED.values.items()
            if dict(k).get("reason") == "gang_distance"
        }
        assert moved, "no gang_distance rejection counter moved"
        assert dict(m.SOLVER_RESULT_REJECTED.values) != before

    def test_forged_rank_scatter_is_rejected(self):
        res, sp, pools, its, existing = self._topo_solved()
        # re-deal the members so ranks 0,1 sit on rack r1 and ranks 2,3
        # on rack r0 of ONE zone: the hop bound stays satisfied (1 <= 2)
        # but rank-sorted members no longer occupy their domains as
        # contiguous non-decreasing runs
        for sim in res.existing_nodes:
            sim.pods = [
                p for p in sim.pods
                if not p.metadata.name.startswith("tgang-")
            ]
        by_name = {s.name: s for s in res.existing_nodes}
        by_rank = {pod_gang_rank(p): p for p in sp}
        # zone-a sims: exist-0/2 are rack za-r0, exist-4/6 rack za-r1
        by_name["exist-4"].pods.extend([by_rank[0], by_rank[1]])
        by_name["exist-0"].pods.extend([by_rank[2], by_rank[3]])
        before = sum(
            v for k, v in m.SOLVER_RESULT_REJECTED.values.items()
            if dict(k).get("reason") == "gang_distance"
        )
        reasons = self._reasons(pools, its, existing, res, sp)
        assert "gang_distance" in reasons, reasons
        after = sum(
            v for k, v in m.SOLVER_RESULT_REJECTED.values.items()
            if dict(k).get("reason") == "gang_distance"
        )
        assert after > before

    def test_rackless_cluster_view_skips_soundly(self):
        # the same zone-spanning forge, judged by a verifier whose
        # cluster view carries NO rack labels: unattributable placements
        # are skipped (placement_hop_bound is sound), never a false
        # gang_distance rejection
        res, sp, pools, its, _ = self._topo_solved()
        self._move(res, "tgang-3", "exist-1")
        rackless = racked_existing(with_topo=False)
        violations = ResultVerifier(
            pools, its, existing_nodes=rackless
        ).verify(res, sp)
        assert "gang_distance" not in {v.reason for v in violations}
