"""Container-level request derivation — ported scenario battery.

Re-expresses the reference's resources suite
(pkg/utils/resources/suite_test.go:38-602) against utils/resources.ceiling:
sidecar (restartPolicy=Always init) containers add to the running sum, each
non-restartable init container's needs stack on the sidecars started before
it, the pod total is the max of the two, and RuntimeClass overhead lands on
top (resources.go:96-162).
"""
from karpenter_core_tpu.api.objects import (
    CONTAINER_RESTART_ALWAYS,
    Container,
    ObjectMeta,
    Pod,
)
from karpenter_core_tpu.utils import resources as res

GI = 2.0**30


def c(cpu, mem_gi, restart=None, limits=None):
    rl = {"cpu": float(cpu), "memory": mem_gi * GI}
    return Container(
        resource_requests=dict(rl),
        resource_limits=dict(rl) if limits is None else limits,
        restart_policy=restart,
    )


def sidecar(cpu, mem_gi):
    return c(cpu, mem_gi, restart=CONTAINER_RESTART_ALWAYS)


def pod(containers=(), inits=(), overhead=None):
    return Pod(
        metadata=ObjectMeta(name="p"),
        containers=list(containers),
        init_containers=list(inits),
        overhead=dict(overhead or {}),
    )


def expect(p, cpu, mem_gi):
    reqs, lims = res.ceiling(p)
    assert reqs["cpu"] == cpu, (reqs["cpu"], cpu)
    assert reqs["memory"] == mem_gi * GI, (reqs["memory"] / GI, mem_gi)
    assert lims["cpu"] == cpu
    assert lims["memory"] == mem_gi * GI


# --- ported scenarios (suite_test.go:40-567) ---------------------------------


def test_sum_of_containers_and_sidecars():
    expect(pod([c(2, 1)], [sidecar(1, 2)]), 3, 3)


def test_containers_sidecars_inits_and_overhead():
    p = pod(
        [c(2, 1)],
        [c(4, 2), sidecar(3, 3)],
        overhead={"cpu": 5.0, "memory": 1 * GI},
    )
    expect(p, 10, 5)


def test_init_after_sidecar_exceeds_containers():
    expect(pod([c(2, 1)], [sidecar(4, 2), c(10, 2)]), 14, 4)


def test_init_after_sidecar_does_not_exceed_containers():
    expect(pod([c(2, 2)], [sidecar(4, 2), c(1, 1)]), 6, 4)


def test_init_after_multiple_sidecars_exceeds():
    p = pod(
        [c(3, 3)],
        [sidecar(2, 2), sidecar(1, 1), sidecar(3, 3), sidecar(5, 5), c(20, 20)],
    )
    expect(p, 31, 31)


def test_init_after_multiple_sidecars_does_not_exceed():
    p = pod(
        [c(3, 3)],
        [sidecar(2, 2), sidecar(1, 1), sidecar(3, 3), sidecar(5, 5), c(1, 1)],
    )
    expect(p, 14, 14)


def test_first_init_exceeds_all_sidecars_and_containers():
    p = pod(
        [c(3, 3)],
        [
            c(25, 25),
            sidecar(1, 1),
            c(3, 3),
            c(1, 1),
            sidecar(5, 5),
            c(1, 1),
            c(1, 1),
            sidecar(1, 1),
        ],
    )
    expect(p, 25, 25)


def test_multiple_interspersed_sidecars_and_inits():
    p = pod(
        [c(3, 3)],
        [
            c(2, 2),
            sidecar(1, 1),
            c(3, 3),
            c(1, 1),
            sidecar(5, 5),
            c(1, 1),
            c(1, 1),
            sidecar(1, 1),
            c(2, 1),
        ],
    )
    expect(p, 10, 10)


def test_first_init_exceeds_cpu_but_not_memory():
    p = pod([c(3, 3)], [c(25, 4), sidecar(1, 1), sidecar(5, 5)])
    expect(p, 25, 9)


def test_first_init_exceeds_memory_but_not_cpu():
    p = pod([c(3, 3)], [c(4, 25), sidecar(1, 1), sidecar(5, 5)])
    expect(p, 9, 25)


def test_init_after_sidecar_exceeds_cpu_but_not_memory():
    p = pod([c(2, 4)], [sidecar(4, 2), c(10, 2)])
    expect(p, 14, 6)


def test_init_after_sidecar_exceeds_memory_but_not_cpu():
    p = pod([c(10, 2)], [sidecar(4, 2), c(2, 4)])
    expect(p, 14, 6)


# --- resource merging (suite_test.go:569-601) --------------------------------


def test_limits_merge_into_requests_when_no_request():
    container = Container(resource_limits={"cpu": 2.0, "memory": 1 * GI})
    merged = res.merge_limits_into_requests(container)
    assert merged == {"cpu": 2.0, "memory": 1 * GI}


def test_limits_merge_into_requests_sidecar():
    container = Container(
        resource_limits={"cpu": 2.0, "memory": 1 * GI},
        restart_policy=CONTAINER_RESTART_ALWAYS,
    )
    p = pod([c(1, 1)], [container])
    assert p.resource_requests["cpu"] == 3.0
    assert p.resource_requests["memory"] == 2 * GI


def test_limits_do_not_fall_back_to_requests():
    # a container with requests but no limits contributes nothing to limits
    container = Container(resource_requests={"cpu": 2.0})
    p = pod([container])
    assert p.resource_requests["cpu"] == 2.0
    assert p.resource_limits.get("cpu", 0.0) == 0.0


# --- framework integration ---------------------------------------------------


def test_flat_request_path_still_works():
    p = Pod(metadata=ObjectMeta(name="p"), resource_requests={"cpu": 1.0})
    assert p.resource_requests == {"cpu": 1.0}


def test_derived_requests_flow_into_requests_for_pods():
    p = pod([c(2, 1)], [sidecar(1, 2)])
    total = res.requests_for_pods(p)
    assert total["cpu"] == 3.0
    assert total["memory"] == 3 * GI
    assert total["pods"] == 1.0


def test_scheduler_consumes_derived_requests():
    """A container-built pod schedules identically to its flat twin."""
    import copy

    from karpenter_core_tpu.api.nodepool import NodePool, NodePoolSpec
    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog
    from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
        Scheduler,
    )

    pool = NodePool(metadata=ObjectMeta(name="default"))
    pool.spec = NodePoolSpec()
    catalog = bench_catalog(120)

    flat = [
        Pod(metadata=ObjectMeta(name=f"f{i}"),
            resource_requests={"cpu": 3.0, "memory": 3 * GI})
        for i in range(20)
    ]
    built = [
        pod([c(2, 1)], [sidecar(1, 2)]) for _ in range(20)
    ]
    for i, p in enumerate(built):
        p.metadata.name = f"b{i}"

    s1 = Scheduler([copy.deepcopy(pool)], {"default": list(catalog)})
    s2 = Scheduler([copy.deepcopy(pool)], {"default": list(catalog)})
    r1 = s1.solve(flat)
    r2 = s2.solve(built)
    assert r1.all_pods_scheduled() and r2.all_pods_scheduled()
    assert r1.node_count() == r2.node_count()


def test_flat_requests_plus_overhead_add_not_replace():
    """Overhead on a flat-request pod lands on top of the provided requests
    (resources.go:124-126) — it must not zero them out."""
    p = Pod(
        metadata=ObjectMeta(name="p"),
        resource_requests={"cpu": 4.0},
        overhead={"cpu": 0.1},
    )
    assert p.resource_requests["cpu"] == 4.1


def test_node_limits_exporter_uses_derived_limits():
    from tests.helpers import make_nodepool
    from tests.test_e2e import new_operator, replicated

    from karpenter_core_tpu.metrics import wiring as m

    op = new_operator()
    op.kube.create(make_nodepool())
    p = pod([c(1, 1, limits={"cpu": 2.0, "memory": 2 * GI})])
    p.metadata.name = "lim0"
    op.kube.create(replicated(p))
    op.run_until_idle()
    assert m.NODES_POD_REQUESTS.value({"resource_type": "cpu"}) >= 1.0
    assert m.NODES_POD_LIMITS.value({"resource_type": "cpu"}) == 2.0
