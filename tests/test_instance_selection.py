"""Instance-type selection properties, mirroring the reference's
randomized instance-selection suite
(reference: pkg/controllers/provisioning/scheduling/
instance_selection_test.go:87-546 cheapest-instance matrix + enough-
resources property, :646-1481 scheduler-level minValues matrix).
"""
import copy
import random

import pytest

from tests.helpers import GIB, make_nodepool, make_pod

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.objects import NodeSelectorRequirement
from karpenter_core_tpu.cloudprovider.kwok import build_catalog
from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
    Scheduler,
)
from karpenter_core_tpu.models.provisioner import DeviceScheduler
from karpenter_core_tpu.scheduling import Requirements

CATALOG = build_catalog(cpu_grid=[1, 2, 4, 8, 16], mem_factors=[2, 4])


def cheapest_price(options, claim_requirements) -> float:
    """Min launchable price across the claim's remaining options
    (the reference's nodePrice over the scheduled node)."""
    best = float("inf")
    for it in options:
        offs = it.offerings.available().compatible(claim_requirements)
        cheapest = offs.cheapest()
        if cheapest is not None:
            best = min(best, cheapest.price)
    return best


def global_cheapest(pod, pool) -> float:
    """Min price over ALL catalog types compatible with pod+pool."""
    reqs = Requirements.from_pod(pod)
    reqs.add(
        *Requirements.from_node_selector_requirements(
            pool.spec.template.requirements
        ).values()
    )
    best = float("inf")
    for it in CATALOG:
        if it.requirements.intersects(reqs):  # non-empty = error list
            continue
        alloc = it.allocatable()
        if not all(
            alloc.get(k, 0.0) >= v for k, v in pod.resource_requests.items()
        ):
            continue
        joined = reqs.copy()
        joined.add(*it.requirements.values())
        offs = it.offerings.available().compatible(joined)
        cheapest = offs.cheapest()
        if cheapest is not None:
            best = min(best, cheapest.price)
    return best


CONSTRAINT_AXES = {
    "arch": (L.LABEL_ARCH, ["amd64", "arm64"]),
    "os": (L.LABEL_OS, ["linux", "windows"]),
    "zone": (L.LABEL_TOPOLOGY_ZONE, ["zone-a", "zone-b", "zone-c", "zone-d"]),
    "ct": (L.CAPACITY_TYPE_LABEL_KEY, ["spot", "on-demand"]),
}


def random_combo(rng):
    """A random (pod constraints, pool constraints) split over the axes —
    the cross-product the reference enumerates by hand."""
    pod_sel, pool_reqs = {}, []
    for axis, (key, values) in CONSTRAINT_AXES.items():
        where = rng.choice(["none", "pod", "pool"])
        if where == "pod":
            pod_sel[key] = rng.choice(values)
        elif where == "pool":
            chosen = rng.sample(values, rng.randint(1, len(values)))
            pool_reqs.append(NodeSelectorRequirement(key, "In", tuple(chosen)))
    return pod_sel, pool_reqs


class TestCheapestInstanceProperty:
    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    @pytest.mark.parametrize("seed", range(8))
    def test_schedules_on_one_of_the_cheapest(self, solver, seed):
        rng = random.Random(seed)
        pod_sel, pool_reqs = random_combo(rng)
        pool = make_nodepool(requirements=pool_reqs)
        pod = make_pod(cpu=0.5, memory_gib=1.0, node_selector=pod_sel)
        cls = Scheduler if solver == "greedy" else DeviceScheduler
        s = cls([pool], {"default": list(CATALOG)})
        res = s.solve([copy.deepcopy(pod)])
        assert res.all_pods_scheduled(), res.pod_errors
        (claim,) = res.new_node_claims
        got = cheapest_price(claim.instance_type_options, claim.requirements)
        want = global_cheapest(pod, pool)
        assert got == pytest.approx(want), (pod_sel, pool_reqs)

    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    def test_unsatisfiable_combo_fails(self, solver):
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement(L.LABEL_ARCH, "In", ("arm64",))
        ])
        pod = make_pod(cpu=0.5, node_selector={L.LABEL_ARCH: "amd64"})
        cls = Scheduler if solver == "greedy" else DeviceScheduler
        s = cls([pool], {"default": list(CATALOG)})
        res = s.solve([pod])
        assert not res.all_pods_scheduled()


class TestEnoughResourcesProperty:
    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    @pytest.mark.parametrize("seed", range(4))
    def test_every_option_fits_the_claims_requests(self, solver, seed):
        # randomized pod sizes (instance_selection_test.go:546-599): after
        # the solve, EVERY remaining option on every claim fits the claim's
        # cumulative requests
        rng = random.Random(100 + seed)
        pods = [
            make_pod(
                cpu=rng.choice([0.1, 0.5, 1.0, 3.0, 7.5]),
                memory_gib=rng.choice([0.25, 1.0, 4.0, 12.0]),
                name=f"r{i}",
            )
            for i in range(40)
        ]
        cls = Scheduler if solver == "greedy" else DeviceScheduler
        s = cls([make_nodepool()], {"default": list(CATALOG)})
        res = s.solve(pods)
        assert res.all_pods_scheduled(), res.pod_errors
        for claim in res.new_node_claims:
            for it in claim.instance_type_options:
                alloc = it.allocatable()
                for name, qty in claim.requests.items():
                    assert alloc.get(name, 0.0) >= qty - 1e-9, (
                        it.name, name, qty, alloc.get(name)
                    )


class TestSchedulerMinValues:
    def pool_with_min_values(self, min_values: int, key=L.LABEL_INSTANCE_TYPE,
                             operator="Exists", values=()):
        return make_nodepool(requirements=[
            NodeSelectorRequirement(
                key, operator, tuple(values), min_values=min_values
            )
        ])

    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    def test_claim_keeps_min_values_options(self, solver):
        # minValues=5 on the instance-type key: the materialized claim must
        # keep >=5 viable instance types (instance_selection_test.go:646)
        pool = self.pool_with_min_values(5)
        cls = Scheduler if solver == "greedy" else DeviceScheduler
        s = cls([pool], {"default": list(CATALOG)})
        res = s.solve([make_pod(cpu=0.5, name="p0")])
        assert res.all_pods_scheduled(), res.pod_errors
        (claim,) = res.new_node_claims
        names = {it.name for it in claim.instance_type_options}
        assert len(names) >= 5

    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    def test_unsatisfiable_min_values_fails(self, solver):
        # more distinct instance types demanded than the catalog holds
        pool = self.pool_with_min_values(len(CATALOG) + 1)
        cls = Scheduler if solver == "greedy" else DeviceScheduler
        s = cls([pool], {"default": list(CATALOG)})
        res = s.solve([make_pod(cpu=0.5, name="p0")])
        assert not res.all_pods_scheduled()

    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    def test_min_values_with_gt_operator(self, solver):
        # Gt over the kwok numeric cpu label: only types above the bound
        # remain, and minValues demands at least 2 of them
        # (instance_selection_test.go:723)
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement(
                "karpenter.kwok.sh/instance-cpu", "Gt", ("2",), min_values=2
            )
        ])
        cls = Scheduler if solver == "greedy" else DeviceScheduler
        s = cls([pool], {"default": list(CATALOG)})
        res = s.solve([make_pod(cpu=0.5, name="p0")])
        assert res.all_pods_scheduled(), res.pod_errors
        (claim,) = res.new_node_claims
        names = {it.name for it in claim.instance_type_options}
        assert len(names) >= 2
        for it in claim.instance_type_options:
            cpu_req = it.requirements.get("karpenter.kwok.sh/instance-cpu")
            (value,) = cpu_req.sorted_values()
            assert int(value) > 2, it.name

    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    def test_lt_operator_excludes_big_types(self, solver):
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement(
                "karpenter.kwok.sh/instance-cpu", "Lt", ("8",)
            )
        ])
        cls = Scheduler if solver == "greedy" else DeviceScheduler
        s = cls([pool], {"default": list(CATALOG)})
        res = s.solve([make_pod(cpu=0.5, name="p0")])
        assert res.all_pods_scheduled(), res.pod_errors
        (claim,) = res.new_node_claims
        for it in claim.instance_type_options:
            (value,) = it.requirements.get(
                "karpenter.kwok.sh/instance-cpu"
            ).sorted_values()
            assert int(value) < 8, it.name

    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    def test_gt_lt_band_unsatisfiable(self, solver):
        # Gt 4 ∧ Lt 8 over a {1,2,4,8,16} grid leaves nothing
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement(
                "karpenter.kwok.sh/instance-cpu", "Gt", ("4",)
            ),
            NodeSelectorRequirement(
                "karpenter.kwok.sh/instance-cpu", "Lt", ("8",)
            ),
        ])
        cls = Scheduler if solver == "greedy" else DeviceScheduler
        s = cls([pool], {"default": list(CATALOG)})
        res = s.solve([make_pod(cpu=0.5, name="p0")])
        assert not res.all_pods_scheduled()
