"""Orchestration fidelity: concurrent in-flight commands, TGP-enforced
pod deletion, priority-grouped drains (reference: orchestration/
queue.go:108-305, terminator/terminator.go:119-165).
"""
from tests.helpers import make_nodepool, make_pod
from tests.test_e2e import new_operator, replicated

from karpenter_core_tpu.api.objects import Node, Pod


class TestConcurrentCommands:
    def test_second_command_starts_while_first_in_flight(self):
        # two drifted nodes; with the first command's replacement still
        # uninitialized (lifecycle frozen — the disruption controller is
        # driven directly), the second command must start anyway
        # (orchestration/queue.go:108-141), and the marked_for_deletion /
        # HasAny guard keeps the candidate sets disjoint (queue.go:305)
        from karpenter_core_tpu.api.nodepool import Budget

        op = new_operator()
        pool = make_nodepool()
        # the default 10% budget allows only ONE concurrent disruption in a
        # two-node pool; widen it so concurrency is observable
        pool.spec.disruption.budgets = [Budget(nodes="100%")]
        op.kube.create(pool)
        op.kube.create(replicated(make_pod(cpu=9.0, name="w0")))
        op.kube.create(replicated(make_pod(cpu=9.0, name="w1")))
        op.run_until_idle()
        assert len(op.kube.list_nodes()) >= 2
        pool.spec.template.labels["drifted"] = "yes"
        op.kube.update(pool)
        # mature the Drifted conditions without running disruption
        op.run_until_idle(disrupt=False)

        op.disruption.reconcile()  # computes + executes command 1
        assert len(op.disruption.in_flight) == 1
        op.disruption.reconcile()  # cmd1 replacement not initialized yet
        assert len(op.disruption.in_flight) == 2, "second command stalled"
        sets = [
            {c.name for c in cmd.command.candidates}
            for cmd in op.disruption.in_flight
        ]
        assert not (sets[0] & sets[1]), sets
        # let the operator finish both commands
        op.run_until_idle()
        assert not op.disruption.in_flight
        assert all(p.node_name for p in op.kube.list_pods())


class TestTGPEnforcement:
    def test_expired_pod_force_deleted_despite_pdb(self):
        # a fully-blocking PDB would stall the drain forever; the claim's
        # terminationGracePeriod guarantees the node dies anyway, with the
        # pod force-deleted at deadline - podGracePeriod (terminator.go:140-165)
        op = new_operator()
        op.kube.create(make_nodepool())
        p = replicated(make_pod(cpu=0.5, name="w0", labels={"app": "web"}))
        p.termination_grace_period_seconds = 30.0
        op.kube.create(p)
        op.run_until_idle()
        claim = op.kube.list_nodeclaims()[0]
        claim.spec.termination_grace_period = 300.0
        op.kube.update(claim)
        from tests.test_pdb import make_pdb

        op.kube.create(make_pdb(min_available=1, app="web"))
        node = op.kube.list_nodes()[0]
        op.kube.delete(node)
        # PDB blocks the graceful drain: bounded reconciles (staying well
        # under the TGP deadline — run_until_idle would elapse the eviction
        # backoff timers all the way to the forced deadline) leave the node
        for _ in range(5):
            op.reconcile_once()
            op.clock.step(2.0)
        assert op.kube.get(Node, node.name) is not None
        assert op.kube.get(Pod, "w0") is not None
        # cross the force-delete threshold: deadline - podGracePeriod
        op.clock.step(300.0 - 30.0 + 1.0)
        op.run_until_idle()
        assert op.kube.get(Node, node.name) is None

    def test_graceful_drain_before_deadline(self):
        # without a PDB the drain completes long before the TGP deadline
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=0.5, name="w0")))
        op.run_until_idle()
        claim = op.kube.list_nodeclaims()[0]
        claim.spec.termination_grace_period = 300.0
        op.kube.update(claim)
        node = op.kube.list_nodes()[0]
        op.kube.delete(node)
        op.run_until_idle()
        assert op.kube.get(Node, node.name) is None
        # the pod was evicted (rebound elsewhere), not deleted
        assert op.kube.get(Pod, "w0") is not None


class TestPriorityGroupedDrain:
    def test_critical_pods_drain_last(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        crit = replicated(make_pod(cpu=0.5, name="crit"))
        crit.priority_class_name = "system-cluster-critical"
        op.kube.create(crit)
        op.kube.create(replicated(make_pod(cpu=0.5, name="plain")))
        op.run_until_idle()
        nodes = op.kube.list_nodes()
        assert len(nodes) == 1
        node = nodes[0]
        op.kube.delete(node)
        # first drain pass: only the non-critical pod is evicted
        op.reconcile_once()
        crit_pod = op.kube.get(Pod, "crit")
        plain_pod = op.kube.get(Pod, "plain")
        assert plain_pod.node_name != node.name  # evicted (pending or moved)
        assert crit_pod.node_name == node.name  # still there
        op.run_until_idle()
        assert op.kube.get(Node, node.name) is None
        assert all(p.node_name for p in op.kube.list_pods())


class TestTGPWithVolumes:
    def test_forced_drain_releases_volume_attachments(self):
        # a PDB-blocked volume pod force-deleted at the TGP deadline must
        # release its VolumeAttachment, or the node's detach-wait wedges
        # the termination forever
        from tests.test_pdb import make_pdb
        from tests.test_volumes import make_pvc, make_zonal_pv, pod_with_pvc

        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(make_zonal_pv("pv-1", "zone-a"))
        op.kube.create(make_pvc("c1", volume_name="pv-1"))
        p = replicated(pod_with_pvc("vol-pod", "c1"))
        p.metadata.labels["app"] = "web"
        p.termination_grace_period_seconds = 30.0
        op.kube.create(p)
        op.run_until_idle()
        claim = op.kube.list_nodeclaims()[0]
        claim.spec.termination_grace_period = 300.0
        op.kube.update(claim)
        op.kube.create(make_pdb(min_available=1, app="web"))
        node = op.kube.list_nodes()[0]
        op.kube.delete(node)
        # bounded reconciles below the TGP deadline (run_until_idle would
        # elapse eviction backoff all the way to the forced deadline)
        for _ in range(5):
            op.reconcile_once()
            op.clock.step(2.0)
        assert op.kube.get(Node, node.name) is not None  # PDB blocks drain
        op.clock.step(300.0)
        op.run_until_idle()
        # force-delete fired, the attachment released, the node finished
        assert op.kube.get(Node, node.name) is None
        assert not [
            va
            for va in op.kube.list_volume_attachments()
            if va.node_name == node.name
        ]


class TestEvictionBackoff:
    def test_429_retries_follow_exponential_curve(self):
        """PDB-blocked evictions retry at 1,2,4,8,10,10... seconds
        (the eviction queue's rate-limiter curve, terminator/eviction.go:95,
        orchestration/queue.go:50-54) instead of every reconcile pass."""
        from tests.test_pdb import make_pdb

        op = new_operator()
        op.kube.create(make_nodepool())
        p = replicated(make_pod(cpu=0.5, name="w0", labels={"app": "web"}))
        op.kube.create(p)
        op.run_until_idle()
        op.kube.create(make_pdb(min_available=1, app="web"))
        node = op.kube.list_nodes()[0]
        evictions = []
        orig = op.kube.evict

        def spying_evict(pod):
            evictions.append(op.clock.now())
            return orig(pod)

        op.kube.evict = spying_evict
        op.kube.delete(node)
        t0 = op.clock.now()
        # drive many passes with fine-grained clock steps; attempts must
        # thin out along the backoff curve, not fire every pass
        for _ in range(40):
            op.reconcile_once()
            op.clock.step(0.5)
        rel = [round(t - t0, 1) for t in evictions]
        assert len(rel) >= 4
        gaps = [round(b - a, 1) for a, b in zip(rel, rel[1:])]
        # first retry after ~1s, then ~2s, then ~4s (>= allows pass quantum)
        assert gaps[0] >= 1.0 and gaps[0] < 2.0, gaps
        assert gaps[1] >= 2.0 and gaps[1] < 3.0, gaps
        assert gaps[2] >= 4.0 and gaps[2] < 5.0, gaps
