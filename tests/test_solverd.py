"""solverd: the out-of-process TPU solver sidecar.

Three layers of proof:

* the wire codec round-trips the FULL scheduler input (object identity,
  volume state, topology context) and its results;
* conformance — one shared solve battery produces identical outcomes with
  ``--solver-mode=inproc`` and ``--solver-mode=sidecar``, including a
  test_e2e-style operator run and the consolidation sweep over the same
  seam;
* degradation — a killed and (separately) hung sidecar falls back to the
  host greedy path within the deadline with the fallback/circuit metrics
  incrementing, and a supervisor respawn resumes the device path without
  an operator restart.
"""
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tests.helpers import make_nodepool, make_pod

from karpenter_core_tpu.api.objects import OwnerReference, Pod
from karpenter_core_tpu.cloudprovider.fake import fake_instance_types
from karpenter_core_tpu.cloudprovider.kwok import KwokCloudProvider, build_catalog
from karpenter_core_tpu.kube.store import KubeStore
from karpenter_core_tpu.metrics import wiring as m
from karpenter_core_tpu.operator import Operator, Options
from karpenter_core_tpu.solver import codec, remote, service
from karpenter_core_tpu.solver.remote import (
    CircuitBreaker,
    FaultInjector,
    RemoteScheduler,
    RemoteSolverError,
    SolverClient,
    STATE_CLOSED,
    STATE_OPEN,
)
from karpenter_core_tpu.utils.clock import FakeClock

CATALOG = build_catalog(cpu_grid=[1, 2, 4, 8], mem_factors=[2, 4])


@pytest.fixture(scope="module")
def sidecar():
    """One in-thread solverd for the module (the jit cache is process-global
    anyway; per-test servers only add socket churn)."""
    srv = service.serve(0)
    yield srv
    srv.shutdown()
    srv.server_close()


def sidecar_addr(srv) -> str:
    return f"127.0.0.1:{srv.server_address[1]}"


def replicated(pod: Pod) -> Pod:
    pod.metadata.owner_references.append(
        OwnerReference(kind="ReplicaSet", name="rs", uid="rs-uid")
    )
    return pod


def new_operator(mode: str, addr: str = "", **opt_kwargs) -> Operator:
    clock = FakeClock()
    kube = KubeStore(clock)
    return Operator(
        kube=kube,
        cloud_provider=KwokCloudProvider(kube, CATALOG),
        clock=clock,
        options=Options(
            solver="tpu", solver_mode=mode, solver_addr=addr, **opt_kwargs
        ),
    )


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------


class TestWireCodec:
    def _problem(self):
        from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
            SimNode,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
            Topology,
        )
        from karpenter_core_tpu.scheduling.volumeusage import VolumeUsage

        pools = [make_nodepool(), make_nodepool(name="batch", weight=10)]
        catalog = fake_instance_types(4)
        # the same IT objects serve both pools: identity must survive
        instance_types = {"default": catalog, "batch": catalog[:2]}
        vu = VolumeUsage()
        vu.add_limit("ebs.csi", 4)
        vu.add({"ebs.csi": {"default/pvc-a"}})
        nodes = [
            SimNode(
                name="existing-0",
                labels={"karpenter.sh/nodepool": "default"},
                taints=[],
                available={"cpu": 3.0, "memory": 8.0 * 2**30},
                capacity={"cpu": 4.0, "memory": 16.0 * 2**30},
                daemon_requests={"cpu": 0.1},
                initialized=True,
                nodeclaim_name="claim-0",
                nodepool_name="default",
                volume_usage=vu,
            )
        ]
        bound = make_pod(cpu=0.5, name="bound-0")
        topo = Topology(
            domains={"topology.kubernetes.io/zone": {"z1", "z2"}},
            existing_pods=[(bound, {"kubernetes.io/hostname": "existing-0"},
                            "existing-0")],
            excluded_pod_uids={"uid-x"},
        )
        pods = [make_pod(cpu=1.0, name=f"p{i}") for i in range(3)]
        return pools, instance_types, nodes, pods, topo

    def test_solve_request_roundtrip(self):
        pools, instance_types, nodes, pods, topo = self._problem()
        data = codec.encode_solve_request(
            pools, instance_types, nodes, [], pods,
            topology=topo, max_slots=512,
        )
        back = codec.decode_solve_request(data)
        # the wire carries nodepools in canonical name order (the list is
        # hashed positionally by problem_fingerprint); DeviceScheduler
        # re-sorts by weight on its side, so only the SET must survive
        assert sorted(p.name for p in back["nodepools"]) == [
            "batch", "default",
        ]
        by_name = {p.name: p for p in back["nodepools"]}
        assert by_name["batch"].spec.weight == 10
        assert back["max_slots"] == 512
        # instance-type identity: shared objects decode to ONE object
        its = back["instance_types"]
        assert [it.name for it in its["default"]][:2] == [
            it.name for it in its["batch"]
        ]
        assert its["default"][0] is its["batch"][0]
        assert its["default"][0].offerings[0].zone == "test-zone-1"
        # SimNode + volume state
        (node,) = back["existing_nodes"]
        assert node.name == "existing-0"
        assert node.volume_usage.limits == {"ebs.csi": 4}
        assert node.volume_usage.volumes == {"ebs.csi": {"default/pvc-a"}}
        # topology context
        t = back["topology"]
        assert t.domains["topology.kubernetes.io/zone"] == {"z1", "z2"}
        assert t.excluded_pods == {"uid-x"}
        [(pod, labels, name)] = t.existing_pods
        assert (pod.metadata.name, name) == ("bound-0", "existing-0")
        assert [p.uid for p in back["pods"]] == [p.uid for p in pods]

    def test_requirements_decode_preserves_semantics(self):
        from karpenter_core_tpu.scheduling import Requirement, Requirements

        reqs = Requirements([
            Requirement.new("zone", "In", ["a", "b"]),
            Requirement.new("tier", "NotIn", ["gpu"]),
            Requirement.new("gen", "Gt", ["3"]),
        ])
        back = codec._decode_reqs(codec._encode_reqs(reqs))
        for key in reqs:
            assert back[key].complement == reqs[key].complement
            assert back[key].values == reqs[key].values
            assert back[key].greater_than == reqs[key].greater_than

    def test_frontier_response_roundtrip(self):
        frontier = [(True, 0, 0.0), (False, 2, 1.5), (True, 1, 0.25)]
        assert codec.decode_frontier_response(
            codec.encode_frontier_response(frontier)
        ) == frontier
        assert codec.decode_frontier_response(
            codec.encode_frontier_response(None)
        ) is None


# ---------------------------------------------------------------------------
# conformance: one battery, both modes
# ---------------------------------------------------------------------------


def _run_battery(op: Operator) -> dict:
    """The shared solve battery: plain pods, selector-pinned pods, then a
    second wave that must reuse the existing capacity."""
    op.kube.create(make_nodepool())
    for i in range(6):
        op.kube.create(replicated(make_pod(cpu=1.5, name=f"plain{i}")))
    for i in range(2):
        op.kube.create(replicated(make_pod(
            cpu=0.5, name=f"zonal{i}", zone_in=["zone-b"],
        )))
    op.run_until_idle(disrupt=False)
    first_nodes = len(op.kube.list_nodes())
    # second wave: small pods that fit into the launched capacity
    for i in range(2):
        op.kube.create(replicated(make_pod(cpu=0.25, name=f"late{i}")))
    op.run_until_idle(disrupt=False)
    pods = op.kube.list_pods()
    nodes = op.kube.list_nodes()
    return {
        "bound": sorted(p.metadata.name for p in pods if p.node_name),
        "unbound": sorted(p.metadata.name for p in pods if not p.node_name),
        "first_nodes": first_nodes,
        "nodes": len(nodes),
        "zonal_zone": sorted({
            n.metadata.labels.get("topology.kubernetes.io/zone")
            for n in nodes
            for p in pods
            if p.node_name == n.name and p.metadata.name.startswith("zonal")
        }),
    }


class TestConformance:
    def test_battery_identical_inproc_vs_sidecar(self, sidecar):
        inproc = _run_battery(new_operator("inproc"))
        solves_before = sidecar.daemon_.solves
        fallbacks_before = m.SOLVER_RPC_FALLBACKS.value({"endpoint": "solve"})
        remote_ = _run_battery(
            new_operator("sidecar", addr=sidecar_addr(sidecar))
        )
        assert remote_ == inproc
        assert inproc["unbound"] == []
        assert inproc["zonal_zone"] == ["zone-b"]
        # the sidecar actually served every solve (no silent fallback)
        assert sidecar.daemon_.solves > solves_before
        assert m.SOLVER_RPC_FALLBACKS.value(
            {"endpoint": "solve"}
        ) == fallbacks_before

    def test_direct_results_parity(self, sidecar):
        """RemoteScheduler's materialized Results match DeviceScheduler's
        structurally: same pod->group assignment, instance options, errors."""
        from karpenter_core_tpu.models.provisioner import DeviceScheduler

        pools = [make_nodepool()]
        catalog = fake_instance_types(5)
        pods = [make_pod(cpu=1.0, name=f"p{i}") for i in range(10)]
        pods += [make_pod(cpu=64.0, name="whale")]  # unschedulable
        local = DeviceScheduler(pools, {"default": catalog}).solve(pods)
        client = SolverClient(sidecar_addr(sidecar), timeout=120)
        rs = RemoteScheduler(client, pools, {"default": catalog})
        over_wire = rs.solve(pods)

        def shape(results):
            return {
                "groups": sorted(
                    tuple(sorted(p.metadata.name for p in c.pods))
                    for c in results.new_node_claims
                ),
                "options": sorted(
                    tuple(sorted(it.name for it in c.instance_type_options))
                    for c in results.new_node_claims
                ),
                "errors": set(results.pod_errors),
            }

        assert shape(over_wire) == shape(local)
        # materialized claims are bound to the CALLER's objects
        claim = over_wire.new_node_claims[0]
        assert all(it in catalog for it in claim.instance_type_options)
        assert all(p in pods for p in claim.pods)

    def test_consolidation_sweep_over_sidecar(self, sidecar):
        """Multi-node consolidation's device frontier crosses the RPC seam
        in sidecar mode and reaches the same decision as inproc."""

        def run(mode, addr=""):
            op = new_operator(mode, addr=addr)
            op.kube.create(make_nodepool())
            for i in range(4):
                op.kube.create(replicated(make_pod(cpu=1.2, name=f"c{i}")))
            op.run_until_idle(disrupt=False)
            # shrink the workload so the nodes consolidate
            for i in range(2):
                pod = op.kube.get(Pod, f"c{i}")
                pod.metadata.owner_references = []
                op.kube.delete(pod)
            op.clock.step(1.0)
            op.run_until_idle()
            return {
                "nodes": len(op.kube.list_nodes()),
                "bound": all(p.node_name for p in op.kube.list_pods()),
            }

        inproc = run("inproc")
        remote_ = run("sidecar", addr=sidecar_addr(sidecar))
        assert remote_ == inproc

    def test_e2e_operator_over_spawned_sidecar(self):
        """test_e2e-style run with the REAL subprocess sidecar under the
        supervisor (solver_addr empty -> the operator spawns and owns it)."""
        op = new_operator("sidecar")
        try:
            assert op.solver_supervisor is not None
            assert op.solver_supervisor.alive()
            op.kube.create(make_nodepool())
            for i in range(3):
                op.kube.create(replicated(make_pod(cpu=2.0, name=f"e{i}")))
            op.run_until_idle(disrupt=False)
            assert all(p.node_name for p in op.kube.list_pods())
            assert op.kube.list_nodes()
        finally:
            op.shutdown()
        assert not op.solver_supervisor.alive()


# ---------------------------------------------------------------------------
# degradation: kill, hang, breaker, supervised restart
# ---------------------------------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _HangingHandler(BaseHTTPRequestHandler):
    def log_message(self, *args):
        pass

    def do_POST(self):
        time.sleep(3.0)  # far past any client deadline used below


class TestDegradation:
    def test_dead_sidecar_degrades_to_greedy(self):
        """Kill shape: connection refused -> greedy fallback within the
        deadline; fallback + failure counters increment."""
        port = _free_port()  # nothing listens here
        client = SolverClient(
            f"127.0.0.1:{port}", timeout=0.5, max_retries=1, sleep=lambda s: None
        )
        pools = [make_nodepool()]
        pods = [make_pod(cpu=1.0, name=f"p{i}") for i in range(4)]
        rs = RemoteScheduler(client, pools, {"default": fake_instance_types(3)})
        fallbacks = m.SOLVER_RPC_FALLBACKS.value({"endpoint": "solve"})
        failures = m.SOLVER_RPC_FAILURES.value({"cause": "error"})
        t0 = time.perf_counter()
        results = rs.solve(pods)
        elapsed = time.perf_counter() - t0
        assert results.all_pods_scheduled()
        assert results.new_node_claims  # greedy placed them
        assert elapsed < 5.0
        assert m.SOLVER_RPC_FALLBACKS.value({"endpoint": "solve"}) == fallbacks + 1
        assert m.SOLVER_RPC_FAILURES.value({"cause": "error"}) == failures + 1

    def test_hung_sidecar_times_out_to_greedy(self):
        """Hang shape: the server accepts and never answers — the read
        deadline fires and the solve degrades within the budget."""
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), _HangingHandler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            client = SolverClient(
                f"127.0.0.1:{httpd.server_address[1]}",
                timeout=0.3, max_retries=0,
            )
            rs = RemoteScheduler(
                client, [make_nodepool()], {"default": fake_instance_types(3)}
            )
            timeouts = m.SOLVER_RPC_FAILURES.value({"cause": "timeout"})
            t0 = time.perf_counter()
            results = rs.solve([make_pod(cpu=1.0, name="h0")])
            elapsed = time.perf_counter() - t0
            assert results.all_pods_scheduled()
            assert elapsed < 2.0  # deadline + fallback, not the 3s hang
            assert m.SOLVER_RPC_FAILURES.value(
                {"cause": "timeout"}
            ) == timeouts + 1
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_injected_faults_then_recovery(self, sidecar):
        """Scripted faults (the fake.py pattern): two consecutive failed
        solves trip the breaker; while open, solves short-circuit to greedy
        without touching the wire; after the cooldown the half-open probe
        heals it and the device path resumes."""
        now = [0.0]
        breaker = CircuitBreaker(
            failure_threshold=2, cooldown=10.0, time_fn=lambda: now[0]
        )
        injector = FaultInjector(["error", "error", "error", "error"])
        client = SolverClient(
            sidecar_addr(sidecar), timeout=60, max_retries=1,
            breaker=breaker, fault_injector=injector, sleep=lambda s: None,
        )
        pools = [make_nodepool()]
        rs = RemoteScheduler(client, pools, {"default": fake_instance_types(3)})
        pods = [make_pod(cpu=1.0, name=f"f{i}") for i in range(2)]

        # solves 1+2: every attempt injected-fails; both degrade to greedy,
        # and the second consecutive call failure opens the breaker
        assert rs.solve(pods).all_pods_scheduled()
        assert breaker.state == STATE_CLOSED and breaker.failures == 1
        assert rs.solve(pods).all_pods_scheduled()
        assert breaker.state == STATE_OPEN
        # the gauge is tenant-labeled since the fleet gateway landed
        assert m.SOLVER_CIRCUIT_STATE.value(
            {"tenant": "default"}
        ) == float(STATE_OPEN)

        # solve 3: circuit open -> fast-fail, no transport, injector unused
        calls_before = injector.calls
        open_failures = m.SOLVER_RPC_FAILURES.value({"cause": "circuit_open"})
        assert rs.solve(pods).all_pods_scheduled()
        assert injector.calls == calls_before
        assert m.SOLVER_RPC_FAILURES.value(
            {"cause": "circuit_open"}
        ) == open_failures + 1

        # cooldown elapses; the schedule is exhausted (healthy transport):
        # the half-open probe succeeds and closes the circuit
        now[0] = 11.0
        injector.schedule.clear()
        solves_before = sidecar.daemon_.solves
        results = rs.solve(pods)
        assert results.all_pods_scheduled()
        assert breaker.state == STATE_CLOSED
        assert sidecar.daemon_.solves == solves_before + 1  # device path

    def test_half_open_probe_failure_reopens(self):
        now = [0.0]
        breaker = CircuitBreaker(
            failure_threshold=1, cooldown=5.0, time_fn=lambda: now[0]
        )
        port = _free_port()
        client = SolverClient(
            f"127.0.0.1:{port}", timeout=0.2, max_retries=3,
            breaker=breaker, sleep=lambda s: None,
        )
        with pytest.raises(RemoteSolverError):
            client.call("/solve", b"x")
        assert breaker.state == STATE_OPEN
        now[0] = 6.0
        with pytest.raises(RemoteSolverError):
            client.call("/solve", b"x")  # half-open probe: ONE attempt only
        assert breaker.state == STATE_OPEN

    def test_kill_fallback_then_supervised_restart_resumes_device(self):
        """The acceptance shape end-to-end: the sidecar dies mid-stream ->
        provisioning completes via greedy fallback within the deadline; the
        supervisor respawns it and the device path resumes through the NEW
        process, no operator restart."""
        op = new_operator("sidecar", batch_idle_duration=0.0)
        try:
            sup = op.solver_supervisor
            assert sup is not None
            # cheap failures for the test
            op.solver_client.timeout = 1.0
            op.solver_client.max_retries = 0
            op.solver_client.sleep = lambda s: None
            op.kube.create(make_nodepool())
            op.kube.create(replicated(make_pod(cpu=1.0, name="w0")))
            op.run_until_idle(disrupt=False)
            assert all(p.node_name for p in op.kube.list_pods())

            # kill the sidecar; hold the supervisor's backoff window open so
            # the next solve genuinely runs against a dead process
            sup._delay = 9999.0
            sup.proc.kill()
            sup.proc.wait(timeout=10)
            fallback_before = m.SOLVER_RPC_FALLBACKS.value(
                {"endpoint": "solve"}
            )
            op.kube.create(replicated(make_pod(cpu=1.0, name="w1")))
            t0 = time.perf_counter()
            op.run_until_idle(disrupt=False)
            elapsed = time.perf_counter() - t0
            # provisioning completed via greedy degradation, within deadline
            assert all(p.node_name for p in op.kube.list_pods())
            assert elapsed < 30.0
            assert m.SOLVER_RPC_FALLBACKS.value(
                {"endpoint": "solve"}
            ) > fallback_before
            assert not sup.alive()
            assert op.recorder.with_reason("SidecarUnavailable")

            # open the restart window: the supervisor respawns on the next
            # reconcile and the client follows the fresh address. Restore a
            # real deadline first — the fresh process pays jax import on its
            # first solve, which the 1s kill-phase timeout would misread as
            # a hang
            op.solver_client.timeout = 120.0
            restarts_before = sup.restarts
            sup._delay = 0.0
            sup._next_spawn_at = 0.0
            op.kube.create(replicated(make_pod(cpu=1.0, name="w2")))
            op.run_until_idle(disrupt=False)
            assert sup.restarts == restarts_before + 1
            assert sup.alive()
            assert op.solver_client.addr == sup.addr
            assert op.recorder.with_reason("SidecarRestarted")
            # a kill is a crash-restart; drain restarts label separately
            assert m.SOLVERD_RESTARTS.value({"cause": "crash"}) >= 1
            assert all(p.node_name for p in op.kube.list_pods())
            # device path resumed: later solves record no new fallbacks
            fallback_after = m.SOLVER_RPC_FALLBACKS.value(
                {"endpoint": "solve"}
            )
            op.kube.create(replicated(make_pod(cpu=1.0, name="w3")))
            op.run_until_idle(disrupt=False)
            assert all(p.node_name for p in op.kube.list_pods())
            assert m.SOLVER_RPC_FALLBACKS.value(
                {"endpoint": "solve"}
            ) == fallback_after
        finally:
            op.shutdown()


class TestSupervisor:
    STUB = (
        "import sys, time; print('listening on 127.0.0.1:1', flush=True); "
        "time.sleep(3600)"
    )
    CRASHER = "print('listening on 127.0.0.1:1', flush=True)"

    def _sup(self, code, **kwargs):
        import sys

        from karpenter_core_tpu.solver.supervisor import SolverSupervisor

        return SolverSupervisor(
            command=[sys.executable, "-u", "-c", code], **kwargs
        )

    def test_restart_with_backoff_on_crash_loop(self):
        now = [0.0]
        events = []
        sup = self._sup(
            self.CRASHER,
            backoff_initial=2.0,
            time_fn=lambda: now[0],
            on_event=lambda r, msg: events.append(r),
        )
        sup.start()
        sup.proc.wait(timeout=10)  # the crasher exits immediately
        assert sup.poll()  # first respawn is immediate
        assert "SidecarUnavailable" in events and "SidecarRestarted" in events
        sup.proc.wait(timeout=10)
        # second respawn must wait out the grown 2s backoff window
        assert not sup.poll()
        now[0] += 1.9
        assert not sup.poll()
        now[0] += 0.2
        assert sup.poll()
        assert sup.restarts == 2
        sup.stop()

    def test_stable_child_resets_backoff(self):
        now = [0.0]
        sup = self._sup(
            self.STUB,
            backoff_initial=1.0,
            stable_window=5.0,
            time_fn=lambda: now[0],
        )
        sup.start()
        sup._delay = 8.0  # pretend it crash-looped earlier
        now[0] = 6.0
        assert not sup.poll()  # alive; stability window elapsed
        assert sup._delay == 0.0
        sup.stop()

    def test_handshake_failure_raises(self):
        sup = self._sup("print('nope', flush=True)")
        with pytest.raises(RuntimeError):
            sup.start()


class TestRespawnStorm:
    """ISSUE 15 satellite: the respawn-storm alarm tells a melting tier
    (K+ respawns inside a sliding window) apart from routine crash-only
    churn — gauge flips, readyz degrades, and the alarm decays once the
    window slides past. Driven on a fake clock without subprocesses via
    the accounting seam (_note_respawn) poll() feeds."""

    def _sup(self, now, **kwargs):
        import sys

        from karpenter_core_tpu.solver.supervisor import SolverSupervisor

        return SolverSupervisor(
            command=[sys.executable, "-c", "pass"],
            time_fn=lambda: now[0],
            **kwargs,
        )

    def test_storm_trips_past_threshold_and_decays(self):
        from karpenter_core_tpu.metrics import wiring as m

        now = [0.0]
        sup = self._sup(
            now, storm_window=100.0, storm_threshold=3, member="7"
        )
        for i in range(3):
            now[0] = float(i * 10)
            sup._note_respawn(now[0])
        # exactly the threshold: churn, not a storm
        assert not sup.respawn_storm()
        assert m.SOLVERD_RESPAWN_STORM.value({"member": "7"}) == 0.0
        now[0] = 30.0
        sup._note_respawn(now[0])  # the K+1'th inside the window
        assert sup.respawn_storm()
        assert m.SOLVERD_RESPAWN_STORM.value({"member": "7"}) == 1.0
        # the window slides past the burst: alarm decays on its own
        now[0] = 131.0
        assert not sup.respawn_storm()
        assert m.SOLVERD_RESPAWN_STORM.value({"member": "7"}) == 0.0

    def test_fleet_aggregates_any_member_storm(self):
        from karpenter_core_tpu.solver.supervisor import FleetSupervisor

        now = [0.0]

        def factory(on_event=None, member="0", **kwargs):
            return self._sup(
                now, storm_window=50.0, storm_threshold=2, member=member
            )

        fleet = FleetSupervisor(3, supervisor_factory=factory)
        assert not fleet.respawn_storm()
        for t in (0.0, 5.0, 10.0):
            fleet.members[1]._note_respawn(t)
        assert fleet.respawn_storm()
        now[0] = 70.0
        assert not fleet.respawn_storm()

    def test_operator_readyz_degrades_during_storm(self):
        op = new_operator("greedy")
        op.kube.create(make_nodepool())
        op.run_until_idle()
        assert op.readyz()
        now = [0.0]
        sup = self._sup(now, storm_window=60.0, storm_threshold=1)
        op.solver_supervisor = sup
        sup._note_respawn(0.0)
        sup._note_respawn(1.0)
        assert not op.readyz()  # melting tier: degraded, loudly
        now[0] = 90.0
        assert op.readyz()


class TestSchedulerReuse:
    """PR 3: the sidecar caches DeviceSchedulers per problem fingerprint
    (everything but the pods), carrying the prepared-state caches across
    RPC calls. The cache must be invisible in the packings and must miss
    whenever the problem half actually changes."""

    # one live problem half, re-encoded per request like a real operator
    # (fresh objects would carry fresh uids — legitimately a new problem)
    POOLS = [make_nodepool()]
    CATALOG = fake_instance_types(5)
    ALT_CATALOG = fake_instance_types(3)

    def _request(self, pods, catalog=None, max_slots=64):
        catalog = catalog or self.CATALOG
        return codec.encode_solve_request(
            self.POOLS, {"default": list(catalog)}, [], [], pods,
            max_slots=max_slots,
        )

    def test_cached_and_fresh_solves_identical(self):
        daemon = service.SolverDaemon()
        pods = [make_pod(cpu=1.0, name=f"c{i}") for i in range(12)]
        body = self._request(pods)
        out1, _ = daemon.solve(body)
        assert len(daemon._sched_cache) == 1
        out2, _ = daemon.solve(body)
        assert len(daemon._sched_cache) == 1  # same fingerprint reused
        fresh_out, _ = service.SolverDaemon().solve(body)

        def shape(data):
            h = codec.decode_solve_results(data)
            return (
                sorted(
                    (tuple(sorted(c["pod_uids"])),
                     tuple(sorted(c["instance_types"])))
                    for c in h["claims"]
                ),
                sorted(h["errors"]),
            )

        assert shape(out1) == shape(out2) == shape(fresh_out)

    def test_pod_derived_topology_exclusions_do_not_churn_cache(self):
        """The provisioner builds each request's Topology with the PENDING
        pods' uids excluded, so the excluded list changes every reconcile.
        It must not change the fingerprint (or the scheduler cache would
        never hit in the real operator path) — and a cache hit must still
        see the request's live exclusions, not the cached ones."""
        from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
            Topology,
        )

        daemon = service.SolverDaemon()
        for r in range(3):
            pods = [make_pod(cpu=1.0, name=f"x{r}-{i}") for i in range(3 + r)]
            topo = Topology(
                domains={},
                excluded_pod_uids={p.uid for p in pods},
            )
            body = codec.encode_solve_request(
                self.POOLS, {"default": list(self.CATALOG)}, [], [], pods,
                topology=topo, max_slots=32,
            )
            out, _ = daemon.solve(body)
            assert codec.decode_solve_results(out)["errors"] == {}
        assert len(daemon._sched_cache) == 1
        # the cached scheduler carries the LAST request's context
        ctx = next(iter(daemon._sched_cache.values()))._topology_context
        assert all(uid.startswith("uid-") for uid in ctx.excluded_pods)

    def test_problem_change_misses_cache(self):
        daemon = service.SolverDaemon()
        pods = [make_pod(cpu=1.0, name=f"m{i}") for i in range(4)]
        daemon.solve(self._request(pods))
        # same problem, different pod mix: fingerprint unchanged
        daemon.solve(self._request(
            [make_pod(cpu=2.0, name=f"m2{i}") for i in range(6)]
        ))
        assert len(daemon._sched_cache) == 1
        # a different catalog IS a different problem
        daemon.solve(self._request(pods, catalog=self.ALT_CATALOG))
        assert len(daemon._sched_cache) == 2


# ---------------------------------------------------------------------------
# verified solves + crash-only device tier (ISSUE 8)
# ---------------------------------------------------------------------------


class _FixedResponseHandler(BaseHTTPRequestHandler):
    """Serves pre-baked bytes with a chosen status — the crafted-response
    seam for corrupt-wire / drain / quarantine client contracts."""

    status = 200
    payload = b""
    hits = None  # list shared with the test

    def log_message(self, *args):
        pass

    def do_POST(self):
        self.rfile.read(int(self.headers.get("Content-Length", 0) or 0))
        if self.hits is not None:
            self.hits.append(self.path)
        body = self.payload
        self.send_response(self.status)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(body)))
        self.send_header("X-Solver-Seconds", "0.001")
        self.end_headers()
        self.wfile.write(body)


def _fixed_server(status, payload, hits=None):
    handler = type(
        "Fixed", (_FixedResponseHandler,),
        {"status": status, "payload": payload, "hits": hits},
    )
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def _no_quarantine_client(addr, **kwargs):
    """A SolverClient whose quarantine never engages (strikes=huge): most
    degradation tests exercise N failures on ONE problem digest and must
    not have the quarantine short-circuit the path under test."""
    from karpenter_core_tpu.solver.fleet import PoisonQuarantine

    kwargs.setdefault("quarantine", PoisonQuarantine(strikes=10_000))
    return SolverClient(addr, **kwargs)


def _solve_problem(n=4):
    pools = [make_nodepool()]
    its = {"default": fake_instance_types(4)}
    pods = [make_pod(cpu=1.0, name=f"v{i}") for i in range(n)]
    return pools, its, pods


def _valid_result_header(pools, its, pods):
    """A structurally valid solve-result header for mutation: solve the
    problem in-proc and round-trip the results through the codec."""
    from karpenter_core_tpu.models.provisioner import DeviceScheduler

    res = DeviceScheduler(pools, dict(its), max_slots=32).solve(pods)
    data = codec.encode_solve_results(res, 0.01)
    return codec._json_header(data)


class TestCorruptWire:
    """Satellite: RemoteScheduler._materialize hardened against
    truncated/corrupt result wire — every malformed field takes the
    NORMAL degradation path (RemoteSolverError -> greedy fallback,
    breaker charged) instead of a TypeError escaping into the
    reconciler."""

    def _materialize_corrupt(self, mutate):
        from karpenter_core_tpu.models.provisioner import DeviceScheduler

        pools, its, pods = _solve_problem()
        res = DeviceScheduler(pools, dict(its), max_slots=32).solve(pods)
        # decode_solve_results converts requirements; the dict is exactly
        # what _materialize receives in production
        wire = codec.decode_solve_results(
            codec.encode_solve_results(res, 0.01)
        )
        assert wire["claims"], "scenario must produce claims"
        mutate(wire)
        client = _no_quarantine_client(
            "127.0.0.1:1", timeout=5, max_retries=0, sleep=lambda s: None
        )
        rs = RemoteScheduler(client, pools, its)
        with pytest.raises(RemoteSolverError) as exc:
            rs._materialize(wire, pods)
        assert exc.value.cause == "corrupt", exc.value

    def test_pod_uids_as_string_is_corrupt(self):
        # the nastiest shape: a string ITERATES (as characters), so the
        # claim would silently materialize empty without the check
        self._materialize_corrupt(
            lambda w: w["claims"][0].__setitem__("pod_uids", "uid-v0")
        )

    def test_requests_as_list_is_corrupt(self):
        self._materialize_corrupt(
            lambda w: w["claims"][0].__setitem__("requests", [1, 2])
        )

    def test_errors_as_list_is_corrupt(self):
        self._materialize_corrupt(lambda w: w.__setitem__("errors", []))

    def test_claims_as_dict_is_corrupt(self):
        self._materialize_corrupt(lambda w: w.__setitem__("claims", {}))

    def test_instance_types_as_ints_is_corrupt(self):
        self._materialize_corrupt(
            lambda w: w["claims"][0].__setitem__("instance_types", [1])
        )

    def test_raw_requirements_is_corrupt(self):
        # decode_solve_results normally converts these; a payload that
        # skips the conversion (or a truncated decode) must not land raw
        # dicts where Requirements algebra is expected
        self._materialize_corrupt(
            lambda w: w["claims"][0].__setitem__(
                "requirements", [{"key": "zone"}]
            )
        )

    def test_existing_entry_malformed_is_corrupt(self):
        self._materialize_corrupt(
            lambda w: w.__setitem__("existing", [{"node": 7, "pod_uids": []}])
        )

    def test_nonlist_existing_is_corrupt(self):
        self._materialize_corrupt(lambda w: w.__setitem__("existing", 3))

    def test_corrupt_content_charges_breaker_and_degrades(self):
        """End to end over HTTP: a 200 response whose content is malformed
        (valid npz+json container, corrupt fields) degrades to greedy AND
        charges the breaker — a sidecar producing garbage should open the
        circuit like a dead one."""
        pools, its, pods = _solve_problem()
        wire = _valid_result_header(pools, its, pods)
        wire["claims"][0]["pod_uids"] = 12345  # not a list of strings
        payload = codec._json_payload(wire)
        srv = _fixed_server(200, payload)
        try:
            client = _no_quarantine_client(
                f"127.0.0.1:{srv.server_address[1]}",
                timeout=5, max_retries=0, sleep=lambda s: None,
            )
            rs = RemoteScheduler(client, pools, its)
            failures = m.SOLVER_RPC_FAILURES.value({"cause": "corrupt"})
            res = rs.solve(pods)
            assert res.all_pods_scheduled()  # greedy fallback placed them
            assert client.breaker.failures == 1
            assert m.SOLVER_RPC_FAILURES.value(
                {"cause": "corrupt"}
            ) == failures + 1
        finally:
            srv.shutdown()
            srv.server_close()

    def test_truncated_wire_degrades_via_decode(self):
        """Bytes damaged below the container level fail in decode (not
        _materialize) and take the decode-cause degradation path."""
        from karpenter_core_tpu.chaos import ChaosSchedule, SolverChaos

        pools, its, pods = _solve_problem()
        wire = _valid_result_header(pools, its, pods)
        chaos = SolverChaos(ChaosSchedule())
        payload = chaos.corrupt(codec._json_payload(wire))
        srv = _fixed_server(200, payload)
        try:
            client = _no_quarantine_client(
                f"127.0.0.1:{srv.server_address[1]}",
                timeout=5, max_retries=0, sleep=lambda s: None,
            )
            rs = RemoteScheduler(client, pools, its)
            decode_failures = m.SOLVER_RPC_FAILURES.value({"cause": "decode"})
            res = rs.solve(pods)
            assert res.all_pods_scheduled()
            assert m.SOLVER_RPC_FAILURES.value(
                {"cause": "decode"}
            ) == decode_failures + 1
        finally:
            srv.shutdown()
            srv.server_close()


class TestResultVerificationOverWire:
    def test_bad_result_rejected_and_degraded(self):
        """A sidecar returning a structurally valid wire whose CONTENT is
        wrong (chaos bad_result: one placed pod silently dropped) is
        caught by the client's ResultVerifier: the solve degrades to
        greedy, the rejection metric moves, and every pod still lands."""
        from karpenter_core_tpu.chaos import ChaosSchedule, SolverChaos

        chaos = SolverChaos(ChaosSchedule(
            script={"solverd.solve": ["bad_result"]}
        ))
        daemon = service.SolverDaemon(chaos=chaos)
        srv = service.serve(0, daemon=daemon)
        try:
            pools, its, pods = _solve_problem(6)
            client = _no_quarantine_client(
                sidecar_addr(srv), timeout=120,
            )
            rs = RemoteScheduler(client, pools, its)
            rejected = m.SOLVER_RESULT_REJECTED.value(
                {"reason": "conservation", "path": "sidecar"}
            )
            fallbacks = m.SOLVER_RPC_FALLBACKS.value({"endpoint": "solve"})
            res = rs.solve(pods)
            assert res.all_pods_scheduled()
            assert chaos.injected.get("bad_result") == 1
            assert m.SOLVER_RESULT_REJECTED.value(
                {"reason": "conservation", "path": "sidecar"}
            ) == rejected + 1
            assert m.SOLVER_RPC_FALLBACKS.value(
                {"endpoint": "solve"}
            ) == fallbacks + 1
            # the chaos script is exhausted -> the next solve is healthy
            # and verification passes silently
            res = rs.solve(pods)
            assert res.all_pods_scheduled()
            assert m.SOLVER_RESULT_REJECTED.value(
                {"reason": "conservation", "path": "sidecar"}
            ) == rejected + 1
        finally:
            srv.shutdown()
            srv.server_close()


class TestDrainContract:
    def test_gateway_drain_flushes_queued_tickets(self):
        from karpenter_core_tpu.solver import fleet

        gw = fleet.FleetGateway(max_depth=8)
        holder = gw.submit("a")
        gw.await_grant(holder)  # owns the device
        outcomes = []

        def queued_request():
            ticket = gw.submit("b")
            try:
                gw.await_grant(ticket)
                outcomes.append("granted")
            except fleet.DrainError:
                outcomes.append("drained")

        t = threading.Thread(target=queued_request, daemon=True)
        t.start()
        for _ in range(200):
            if gw.depth() >= 2:
                break
            time.sleep(0.005)
        assert gw.drain() == 1  # the queued ticket flushed
        t.join(timeout=5)
        assert outcomes == ["drained"]
        with pytest.raises(fleet.DrainError):
            gw.submit("c")  # admission closed
        gw.release(holder, 0.01)  # the active step still releases cleanly
        gw.resume()
        gw.await_grant(gw.submit("d"))  # re-opened

    def test_client_treats_503_as_degrade_not_fault(self):
        srv = _fixed_server(503, b'{"error": "draining"}')
        try:
            pools, its, pods = _solve_problem()
            client = _no_quarantine_client(
                f"127.0.0.1:{srv.server_address[1]}",
                timeout=5, max_retries=2, sleep=lambda s: None,
            )
            rs = RemoteScheduler(client, pools, its)
            res = rs.solve(pods)
            assert res.all_pods_scheduled()
            # drain is an ANSWER: no breaker charge, no retries burned
            assert client.breaker.failures == 0
            assert client.breaker.state == STATE_CLOSED
        finally:
            srv.shutdown()
            srv.server_close()

    def test_drain_endpoint_and_healthz(self):
        daemon = service.SolverDaemon()
        state = daemon.drain()
        assert state == {"draining": True, "flushed": 0, "exiting": False}
        health = daemon.health()
        assert health["draining"] is True
        assert health["ready"] is False
        from karpenter_core_tpu.solver import fleet

        with pytest.raises(fleet.DrainError):
            daemon.solve(b"irrelevant")
        daemon.gateway.resume()
        assert daemon.health()["draining"] is False

    def test_drain_exit_fn_fires_after_idle(self):
        exits = []
        daemon = service.SolverDaemon(exit_fn=exits.append)
        state = daemon.drain()
        assert state["exiting"] is True
        for _ in range(200):
            if exits:
                break
            time.sleep(0.02)
        from karpenter_core_tpu.solver.supervisor import DRAIN_EXIT_CODE

        assert exits == [DRAIN_EXIT_CODE]


class TestWatchdog:
    def test_unit_trips_on_overrun_only(self):
        now = [0.0]
        trips, exits = [], []
        wd = service.DeviceWatchdog(
            5.0, on_trip=trips.append, exit_fn=exits.append,
            time_fn=lambda: now[0], poll_seconds=0,  # no monitor thread
        )
        wd.arm("step-1")
        assert not wd.check()
        now[0] = 4.9
        assert not wd.check()
        now[0] = 5.1
        assert wd.check()
        assert trips == ["step-1"] and wd.trips == 1
        from karpenter_core_tpu.solver.supervisor import WATCHDOG_EXIT_CODE

        assert exits == [WATCHDOG_EXIT_CODE]
        # disarmed after the trip: no double-fire
        assert not wd.check()

    def test_disarm_prevents_trip(self):
        now = [0.0]
        trips = []
        wd = service.DeviceWatchdog(
            1.0, on_trip=trips.append, time_fn=lambda: now[0],
            poll_seconds=0,
        )
        wd.arm()
        wd.disarm()
        now[0] = 100.0
        assert not wd.check()
        assert trips == []

    def test_wedged_device_step_trips_watchdog_and_drains(self):
        """The wedge shape end to end (in-thread): a chaos-wedged device
        step overruns the budget; the watchdog drains the gateway (a
        queued request answers 503, not silence) and invokes the
        crash-only exit hook."""
        from karpenter_core_tpu.chaos import ChaosSchedule, SolverChaos
        from karpenter_core_tpu.solver import fleet
        from karpenter_core_tpu.solver.supervisor import WATCHDOG_EXIT_CODE

        exits = []
        chaos = SolverChaos(ChaosSchedule(
            script={"solverd.solve": ["wedge:0.8"]}
        ))
        daemon = service.SolverDaemon(
            watchdog_seconds=0.15, chaos=chaos, exit_fn=exits.append,
        )
        pools, its, pods = _solve_problem(2)
        body = codec.encode_solve_request(pools, its, [], [], pods,
                                          max_slots=16)
        trips_before = m.SOLVERD_WATCHDOG_TRIPS.value()
        out, _dt = daemon.solve(body)  # wedged but completes (in-thread)
        assert codec.decode_solve_results(out)["errors"] == {}
        assert daemon.watchdog.trips == 1
        assert m.SOLVERD_WATCHDOG_TRIPS.value() == trips_before + 1
        assert exits == [WATCHDOG_EXIT_CODE]
        # the trip drained the gateway: new admissions are refused until
        # the (in tests, simulated) process restart
        with pytest.raises(fleet.DrainError):
            daemon.solve(body)
        assert daemon.health()["draining"] is True
        daemon.gateway.resume()
        # healthy again: the next solve passes and does not re-trip
        out, _dt = daemon.solve(body)
        assert daemon.watchdog.trips == 1


class TestPoisonQuarantine:
    def test_strikes_ttl_and_clear(self):
        from karpenter_core_tpu.solver.fleet import PoisonQuarantine

        now = [0.0]
        q = PoisonQuarantine(strikes=3, ttl=10.0, time_fn=lambda: now[0])
        assert not q.strike("fp1")
        assert not q.strike("fp1")
        assert not q.quarantined("fp1")
        assert q.strike("fp1")  # third strike quarantines
        assert q.quarantined("fp1")
        assert q.size() == 1
        now[0] = 10.1  # TTL elapses: fresh chance
        assert not q.quarantined("fp1")
        assert q.size() == 0
        # a success clears the streak
        assert not q.strike("fp2")
        q.clear("fp2")
        assert not q.strike("fp2")
        assert not q.strike("fp2")
        assert q.strike("fp2")  # 3 consecutive post-clear

    def test_stale_streaks_forgive(self):
        from karpenter_core_tpu.solver.fleet import PoisonQuarantine

        now = [0.0]
        q = PoisonQuarantine(strikes=2, ttl=5.0, time_fn=lambda: now[0])
        assert not q.strike("fp")
        now[0] = 6.0  # outside the window: the old strike expired
        assert not q.strike("fp")
        assert not q.quarantined("fp")

    def test_poison_is_immediate(self):
        from karpenter_core_tpu.solver.fleet import PoisonQuarantine

        q = PoisonQuarantine()
        q.poison("fp")
        assert q.quarantined("fp")

    def test_cap_bounds_both_maps(self):
        from karpenter_core_tpu.solver.fleet import PoisonQuarantine

        q = PoisonQuarantine(strikes=1, cap=8)
        for i in range(50):
            q.strike(f"fp{i}")
        assert q.size() <= 8

    def test_journal_recovers_inflight_crash(self, tmp_path):
        from karpenter_core_tpu.solver.fleet import PoisonQuarantine

        journal = str(tmp_path / "poison.json")
        # boot->wedge->die cycles: each boot recovers the PREVIOUS boot's
        # in-flight digest as a strike, so the Nth strike lands on boot N
        for boot in range(4):
            q = PoisonQuarantine(
                strikes=3, journal_path=journal, site="gateway"
            )
            if boot == 3:
                assert q.quarantined("fp-poison")
                return
            assert not q.quarantined("fp-poison")
            q.begin("fp-poison")  # ...and the process "dies" here

    def test_journal_clean_completion_never_strikes(self, tmp_path):
        from karpenter_core_tpu.solver.fleet import PoisonQuarantine

        journal = str(tmp_path / "poison.json")
        q = PoisonQuarantine(strikes=1, journal_path=journal)
        q.begin("fp")
        q.done("fp")
        q2 = PoisonQuarantine(strikes=1, journal_path=journal)
        assert not q2.quarantined("fp")

    def test_daemon_quarantines_crashing_problem(self):
        """Gateway-side: a problem whose device phase raises N times is
        refused pre-decode with QuarantinedError (HTTP 422) — it stops
        burning grants for every tenant."""
        from karpenter_core_tpu.solver import fleet

        daemon = service.SolverDaemon(
            quarantine=fleet.PoisonQuarantine(strikes=2, site="gateway"),
        )
        pools, its, pods = _solve_problem(2)
        body = codec.encode_solve_request(pools, its, [], [], pods,
                                          max_slots=16)
        # the daemon's cache key is the decoded fingerprint plus the
        # RESOLVED solver mode (relaxsolve, ISSUE 13)
        fp = codec.decode_solve_request(body)["fingerprint"] + "+mffd"

        class _Bomb:
            def update_topology_context(self, topo):
                pass

            def solve(self, pods):
                raise RuntimeError("chaos: poisoned problem")

        daemon._sched_cache.put(fp, _Bomb(), 64)
        for _ in range(2):
            with pytest.raises(RuntimeError):
                daemon.solve(body)
        routed = m.SOLVER_QUARANTINE_ROUTED.value({"site": "gateway"})
        with pytest.raises(fleet.QuarantinedError):
            daemon.solve(body)
        assert m.SOLVER_QUARANTINE_ROUTED.value(
            {"site": "gateway"}
        ) == routed + 1
        assert daemon.health()["quarantine_entries"] == 1

    def test_client_mirrors_gateway_422(self):
        """The 422 contract: the client degrades to greedy WITHOUT
        charging the breaker, quarantines locally, and the next solve for
        the same problem never touches the wire."""
        hits = []
        srv = _fixed_server(
            422, b'{"error": "quarantined", "fingerprint": "x"}', hits=hits
        )
        try:
            pools, its, pods = _solve_problem()
            client = SolverClient(
                f"127.0.0.1:{srv.server_address[1]}",
                timeout=5, max_retries=2, sleep=lambda s: None,
            )
            rs = RemoteScheduler(client, pools, its)
            routed = m.SOLVER_QUARANTINE_ROUTED.value({"site": "client"})
            res = rs.solve(pods)
            assert res.all_pods_scheduled()
            assert client.breaker.failures == 0
            assert len(hits) == 1  # no retries against a refusal
            res = rs.solve(pods)
            assert res.all_pods_scheduled()
            assert len(hits) == 1  # second solve short-circuited locally
            assert m.SOLVER_QUARANTINE_ROUTED.value(
                {"site": "client"}
            ) == routed + 1
        finally:
            srv.shutdown()
            srv.server_close()

    def test_client_quarantines_repeated_timeouts(self):
        """Client-side hang shape: N timeouts on one problem digest and
        the client stops burning RPC budget on it (straight to greedy)."""
        from karpenter_core_tpu.solver.fleet import PoisonQuarantine

        pools, its, pods = _solve_problem()
        injector = FaultInjector(["timeout"] * 10)
        client = SolverClient(
            "127.0.0.1:1", timeout=0.2, max_retries=0,
            fault_injector=injector, sleep=lambda s: None,
            quarantine=PoisonQuarantine(strikes=3, site="client"),
            # keep the breaker out of the way: with the default threshold
            # it would open first and hide whether the QUARANTINE stopped
            # the transport attempts
            breaker=CircuitBreaker(failure_threshold=100),
        )
        rs = RemoteScheduler(client, pools, its)
        for _ in range(3):
            assert rs.solve(pods).all_pods_scheduled()
        calls_before = injector.calls
        assert rs.solve(pods).all_pods_scheduled()
        # quarantined: the 4th solve made no transport attempt at all
        assert injector.calls == calls_before


class TestSupervisorDrainExit:
    HANDSHAKE = "print('listening on 127.0.0.1:1', flush=True); "

    def _sup(self, code, **kwargs):
        import sys

        from karpenter_core_tpu.solver.supervisor import SolverSupervisor

        return SolverSupervisor(
            command=[sys.executable, "-u", "-c", code], **kwargs
        )

    def test_drain_exit_respawns_immediately_without_backoff(self):
        from karpenter_core_tpu.solver.supervisor import DRAIN_EXIT_CODE

        now = [0.0]
        events = []
        sup = self._sup(
            self.HANDSHAKE + f"raise SystemExit({DRAIN_EXIT_CODE})",
            backoff_initial=5.0,
            time_fn=lambda: now[0],
            on_event=lambda r, msg: events.append(r),
        )
        crash_before = m.SOLVERD_RESTARTS.value({"cause": "crash"})
        drain_before = m.SOLVERD_RESTARTS.value({"cause": "drain"})
        sup.start()
        for round_ in range(3):
            sup.proc.wait(timeout=10)
            # every drain exit respawns on the NEXT poll, clock untouched:
            # no growing backoff window, ever
            assert sup.poll(), f"round {round_} did not respawn"
        assert sup._delay == 0.0
        assert m.SOLVERD_RESTARTS.value({"cause": "drain"}) == drain_before + 3
        assert m.SOLVERD_RESTARTS.value({"cause": "crash"}) == crash_before
        assert "SidecarDrained" in events
        assert "SidecarUnavailable" not in events
        sup.stop()

    def test_crash_exit_still_charges_backoff(self):
        now = [0.0]
        sup = self._sup(
            self.HANDSHAKE + "raise SystemExit(3)",
            backoff_initial=2.0,
            time_fn=lambda: now[0],
        )
        sup.start()
        sup.proc.wait(timeout=10)
        assert sup.poll()  # first crash respawn is immediate...
        sup.proc.wait(timeout=10)
        assert not sup.poll()  # ...the second waits out the 2s window
        assert sup._delay > 0
        sup.stop()

    def test_drain_method_against_real_sidecar(self):
        """The full lifecycle: POST /drain to a REAL spawned solverd, the
        child flushes and exits with DRAIN_EXIT_CODE, the supervisor
        respawns it immediately as cause=drain, and the device path
        serves again from the fresh process."""
        op = new_operator("sidecar")
        try:
            sup = op.solver_supervisor
            drain_before = m.SOLVERD_RESTARTS.value({"cause": "drain"})
            assert sup.drain(timeout=30)
            assert not sup.alive()
            assert sup.poll()  # immediate respawn, no backoff window
            assert sup.alive()
            assert m.SOLVERD_RESTARTS.value(
                {"cause": "drain"}
            ) == drain_before + 1
            op.solver_client.set_addr(sup.addr)
            op.kube.create(make_nodepool())
            op.kube.create(replicated(make_pod(cpu=1.0, name="dr0")))
            fallbacks = m.SOLVER_RPC_FALLBACKS.value({"endpoint": "solve"})
            op.run_until_idle(disrupt=False)
            assert all(p.node_name for p in op.kube.list_pods())
            # served by the RESPAWNED device path, not greedy fallback
            assert m.SOLVER_RPC_FALLBACKS.value(
                {"endpoint": "solve"}
            ) == fallbacks
        finally:
            op.shutdown()


class TestProfileToggle:
    def test_toggle_requires_configured_dir(self):
        daemon = service.SolverDaemon()
        state = daemon.toggle_profile(True)
        assert state == {
            "profiling": False, "profile_dir": None, "configured": False,
        }

    def test_profile_endpoint_toggles_and_wraps_solves(self, tmp_path):
        daemon = service.SolverDaemon(profile_dir=str(tmp_path))
        srv = service.serve(0, daemon=daemon)
        try:
            import json
            from urllib.request import Request, urlopen

            base = f"http://{sidecar_addr(srv)}"
            st = json.loads(urlopen(
                Request(f"{base}/profile", method="POST", data=b""),
                timeout=10,
            ).read())
            assert st["profiling"] is True
            # a solve under the toggle must succeed and emit a trace dir
            pods = [make_pod(cpu=1.0, name="prof0")]
            body = codec.encode_solve_request(
                [make_nodepool()], {"default": fake_instance_types(3)},
                [], [], pods, max_slots=16,
            )
            out, _ = daemon.solve(body)
            assert codec.decode_solve_results(out)["errors"] == {}
            assert any(tmp_path.iterdir()), "no profiler trace written"
            st = json.loads(urlopen(
                Request(f"{base}/profile?enable=0", method="POST", data=b""),
                timeout=10,
            ).read())
            assert st["profiling"] is False
            # GET reports without toggling
            st = json.loads(urlopen(f"{base}/profile", timeout=10).read())
            assert st["profiling"] is False
        finally:
            srv.shutdown()
            srv.server_close()
