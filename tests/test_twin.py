"""Digital twin (ISSUE 15): closed-loop determinism, invariant monitors,
fleet faults, and failing-scenario shrinking.

Tier-1 pins the whole contract at smoke scale:
* identical seed + scenario → byte-identical event trace AND ledger JSON,
  including a run with fleet faults (member murder, partition windows,
  segment-store amnesia) enabled;
* a clean scenario completes with zero invariant violations, zero
  verifier rejections and zero greedy fallbacks; a fault-storm scenario
  (ICE storm + member murder + partition) still completes with zero
  invariant violations — degradation rides the shed/quarantine/fallback
  ladder, never loses or double-places a pod;
* the shrinker minimizes an intentionally-injected invariant bug (the
  lose_bound_pod test hook) to a one-wave, one-cluster repro, and the
  COMMITTED fixture (tests/twin_fixtures/shrunk_lost_pod.json) replays
  the violation in well under 10 seconds.
"""
import json
import time
from pathlib import Path
from types import SimpleNamespace

import pytest

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.objects import (
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
)
from karpenter_core_tpu.kube.store import KubeStore
from karpenter_core_tpu.twin import (
    FleetFault,
    InvariantMonitor,
    Scenario,
    Storm,
    TestHook,
    VirtualClock,
    WorkloadWave,
    decode_scenario,
    encode_scenario,
    fuzz,
    replay,
    scenario_fingerprint,
    scenario_from_json,
    scenario_to_json,
    shrink,
)
from karpenter_core_tpu.twin.harness import TWIN_EPOCH, run_scenario
from karpenter_core_tpu.twin.workloads import pods_for_wave

FIXTURES = Path(__file__).parent / "twin_fixtures"

GIB = 2.0**30


def _clean_scenario(**overrides) -> Scenario:
    """~300 pods over 2 clusters, mixed Tesserae-shaped classes, no
    faults (the tier-1 smoke shape named by the ISSUE)."""
    base = dict(
        seed=3,
        clusters=2,
        duration=300.0,
        tick=30.0,
        solver="greedy",
        waves=(
            WorkloadWave(at=0.0, cluster=0, kind="serving", count=80,
                         min_available=4),
            WorkloadWave(at=0.0, cluster=1, kind="training", count=64,
                         gang_size=8, priority=100),
            WorkloadWave(at=30.0, cluster=0, kind="batch", count=80,
                         lifetime=180.0),
            WorkloadWave(at=60.0, cluster=1, kind="serving", count=48,
                         min_available=2),
            WorkloadWave(at=90.0, cluster=0, kind="batch", count=40),
        ),
    )
    base.update(overrides)
    return Scenario(**base)


def _storm_fleet_scenario(**overrides) -> Scenario:
    """The fault-storm shape from the acceptance criteria: ICE storm +
    chaos rates on the kube/cloud seams + fleet faults (murder of a
    member mid-run, an operator↔fleet partition window, segment-store
    amnesia), over a REAL in-thread solverd tier."""
    base = dict(
        seed=5,
        clusters=2,
        duration=300.0,
        tick=30.0,
        solver="tpu",
        fleet=2,
        wire="delta",
        rates={
            "kube.create.conflict": 0.05,
            "kube.update.conflict": 0.04,
            "cloud.create.insufficient_capacity": 0.03,
        },
        storms=(Storm(start=60.0, duration=90.0, cluster=0, head=4),),
        waves=(
            WorkloadWave(at=0.0, cluster=0, kind="serving", count=12,
                         min_available=2),
            WorkloadWave(at=30.0, cluster=1, kind="batch", count=12),
            WorkloadWave(at=150.0, cluster=0, kind="batch", count=8),
            WorkloadWave(at=210.0, cluster=1, kind="serving", count=8),
        ),
        fleet_faults=(
            FleetFault(at=90.0, kind="amnesia", member=0),
            FleetFault(at=120.0, kind="murder", member=1),
            FleetFault(at=180.0, kind="partition", cluster=0, duration=60.0),
        ),
    )
    base.update(overrides)
    return Scenario(**base)


# ---------------------------------------------------------------------------
# scenario codec
# ---------------------------------------------------------------------------


class TestScenarioCodec:
    def test_round_trip_and_fingerprint_stability(self):
        s = _storm_fleet_scenario()
        text = scenario_to_json(s)
        back = scenario_from_json(text)
        assert back == s
        assert scenario_to_json(back) == text
        assert scenario_fingerprint(back) == scenario_fingerprint(s)

    def test_encoding_is_construction_order_independent(self):
        a = _clean_scenario()
        b = Scenario(**{
            **encode_kwargs(a),
            "waves": tuple(reversed(a.waves)),
            "rates": dict(reversed(list(a.rates.items()))),
        })
        assert scenario_to_json(a) == scenario_to_json(b)

    def test_unknown_fields_and_kinds_reject(self):
        data = encode_scenario(_clean_scenario())
        data["bogus"] = 1
        with pytest.raises(ValueError, match="bogus"):
            decode_scenario(data)
        with pytest.raises(ValueError, match="wave kind"):
            run_scenario(Scenario(waves=(
                WorkloadWave(at=0.0, cluster=0, kind="mystery", count=1),
            )))

    def test_validation_rejects_out_of_range_targets(self):
        with pytest.raises(ValueError, match="outside"):
            run_scenario(Scenario(clusters=1, waves=(
                WorkloadWave(at=0.0, cluster=3, kind="batch", count=1),
            )))
        with pytest.raises(ValueError, match="fleet"):
            run_scenario(Scenario(fleet_faults=(
                FleetFault(at=0.0, kind="murder", member=0),
            )))
        # a hand-edited fixture with a bogus hook/storm target must fail
        # validation loudly, not IndexError mid-run
        with pytest.raises(ValueError, match="outside"):
            run_scenario(Scenario(clusters=2, hooks=(
                TestHook(at=0.0, kind="lose_bound_pod", cluster=5),
            )))
        with pytest.raises(ValueError, match="outside"):
            run_scenario(Scenario(clusters=1, storms=(
                Storm(start=0.0, duration=10.0, cluster=2),
            )))
        with pytest.raises(ValueError, match="multiple"):
            run_scenario(Scenario(clusters=1, waves=(
                WorkloadWave(at=0.0, cluster=0, kind="training", count=12,
                             gang_size=8),
            )))

    def test_wave_identity_is_content_derived(self):
        from karpenter_core_tpu.twin.scenario import wave_ids

        w1 = WorkloadWave(at=0.0, cluster=0, kind="serving", count=4)
        w2 = WorkloadWave(at=30.0, cluster=0, kind="batch", count=4)
        full = wave_ids((w1, w2))
        # dropping a sibling (the shrinker) re-rolls NOTHING: same id,
        # same pods, byte for byte
        assert wave_ids((w2,))[0] == full[1]
        a, _ = pods_for_wave(w2, full[1], seed=5)
        b, _ = pods_for_wave(w2, wave_ids((w2,))[0], seed=5)
        assert [(p.name, p.resource_requests) for p in a] == [
            (p.name, p.resource_requests) for p in b
        ]
        # identical duplicate waves disambiguate deterministically
        dup = wave_ids((w1, w1))
        assert dup[0] != dup[1] and dup == wave_ids((w1, w1))

    def test_reordered_construction_runs_identically(self):
        base = _clean_scenario(duration=120.0)
        flipped = Scenario(**{
            **encode_kwargs(base), "waves": tuple(reversed(base.waves)),
        })
        # the encoder sorts, so these share one fingerprint — and the
        # harness canonicalizes, so they must share one RUN
        assert scenario_fingerprint(base) == scenario_fingerprint(flipped)
        a = run_scenario(base)
        b = run_scenario(flipped)
        assert a.trace_json() == b.trace_json()
        assert a.ledger_json() == b.ledger_json()


def encode_kwargs(s: Scenario) -> dict:
    d = encode_scenario(s)
    d.pop("version")
    return {
        **d,
        "waves": s.waves,
        "storms": s.storms,
        "fleet_faults": s.fleet_faults,
        "hooks": s.hooks,
        "rates": dict(s.rates),
    }


class TestVirtualClock:
    def test_sleep_and_monotonic_ride_virtual_time(self):
        clock = VirtualClock(1000.0)
        assert clock.monotonic() == 1000.0
        clock.sleep(2.5)
        assert clock.now() == 1002.5
        clock.advance_to(1001.0)  # never backward
        assert clock.now() == 1002.5
        clock.advance_to(1010.0)
        assert clock.monotonic() == 1010.0


# ---------------------------------------------------------------------------
# the tier-1 smoke: clean run, fault storm, byte determinism
# ---------------------------------------------------------------------------


class TestTwinSmoke:
    def test_clean_scenario_zero_violations_zero_fallbacks(self):
        result = run_scenario(_clean_scenario())
        assert result.violations == []
        assert result.counters["result_rejected"] == 0
        assert result.counters["rpc_fallbacks"] == 0
        ledger = result.ledger.encode()
        # every workload class bound and accounted: 5 waves, 312 pods
        n_bound = sum(c["n"] for c in ledger["slo"].values())
        assert n_bound == 312
        assert set(ledger["slo"]) == {"batch", "serving", "training"}
        assert ledger["slo_misses"] == 0
        # the judge surface is live: $-cost accumulated, nodes peaked
        assert all(v > 0 for v in ledger["cost_dollar_hours"].values())
        assert all(v > 0 for v in ledger["peak_nodes"].values())
        assert ledger["ticks"] == 10

    def test_identical_seed_byte_identical_trace_and_ledger(self):
        scenario = _clean_scenario(rates={
            "kube.create.conflict": 0.08,
            # update/bind are the high-traffic seams (status writes every
            # pass, one bind per pod): faults reliably FIRE here
            "kube.update.conflict": 0.05,
            "kube.bind.conflict": 0.05,
            "cloud.create.insufficient_capacity": 0.04,
        }, storms=(Storm(start=30.0, duration=90.0, head=4),))
        a = run_scenario(scenario)
        b = run_scenario(scenario)
        assert a.trace_json() == b.trace_json()
        assert a.ledger_json() == b.ledger_json()
        # and chaos faults actually FIRED (the equality is not vacuous)
        assert a.ledger.utilization["chaos_injected"]["0"] > 0

    def test_different_seed_diverges(self):
        scenario = _clean_scenario(rates={"kube.create.conflict": 0.2})
        a = run_scenario(scenario)
        b = run_scenario(Scenario(**{
            **encode_kwargs(scenario), "seed": scenario.seed + 1
        }))
        # same shape, different seed: the chaos path must actually differ
        assert a.trace_json() != b.trace_json()


class TestTwinFleet:
    """The real solverd tier behind each operator's FleetRouter — the
    jax-backed half of the smoke (in-thread daemons, real HTTP/codec)."""

    def test_fault_storm_zero_invariant_violations_and_determinism(self):
        scenario = _storm_fleet_scenario()
        a = run_scenario(scenario)
        # ICE storm + murder + partition: the ladder degrades, the loop
        # converges, and no pod is ever lost or double-placed
        assert a.violations == []
        assert a.counters["result_rejected"] == 0
        # the murder/partition actually bit: some solves fell back
        assert a.counters["rpc_fallbacks"] > 0
        util = a.ledger.utilization
        assert sum(util["member_solves"].values()) > 0
        # identical seed: byte-identical trace AND ledger, fleet faults on
        b = run_scenario(scenario)
        assert a.trace_json() == b.trace_json()
        assert a.ledger_json() == b.ledger_json()

    def test_clean_fleet_run_zero_fallbacks(self):
        scenario = _storm_fleet_scenario(
            rates={}, storms=(), fleet_faults=(),
            duration=120.0,
            waves=(
                WorkloadWave(at=0.0, cluster=0, kind="serving", count=10,
                             min_available=2),
                WorkloadWave(at=30.0, cluster=1, kind="batch", count=10),
            ),
        )
        result = run_scenario(scenario)
        assert result.violations == []
        assert result.counters["rpc_fallbacks"] == 0
        assert result.counters["result_rejected"] == 0
        assert sum(
            result.ledger.utilization["member_solves"].values()
        ) > 0


def _elastic_scenario(**overrides) -> Scenario:
    """The fleetscale (ISSUE 17) closed-loop shape: an early surge that
    should grow the tier, then a long quiet tail that should shrink it
    back — over a REAL in-thread solverd tier with the autoscaler riding
    the twin's virtual clock."""
    base = dict(
        seed=11,
        clusters=2,
        duration=300.0,
        tick=30.0,
        solver="tpu",
        fleet=1,
        wire="delta",
        autoscale=True,
        fleet_min=1,
        fleet_max=2,
        waves=(
            WorkloadWave(at=0.0, cluster=0, kind="serving", count=12,
                         min_available=2),
            WorkloadWave(at=0.0, cluster=1, kind="batch", count=12,
                         lifetime=120.0),
            WorkloadWave(at=30.0, cluster=0, kind="batch", count=10,
                         lifetime=90.0),
            WorkloadWave(at=240.0, cluster=1, kind="batch", count=6),
        ),
    )
    base.update(overrides)
    return Scenario(**base)


class TestTwinElastic:
    """Closed-loop elasticity (fleetscale, ISSUE 17): the autoscaler's
    decisions are part of the deterministic trace, the elastic run must
    beat a fixed-size control on member-seconds, and faults racing a
    resize must neither wedge the loop nor break replay."""

    def test_surge_quiet_scales_both_ways_and_replays_byte_identically(self):
        scenario = _elastic_scenario()
        a = run_scenario(scenario)
        assert a.violations == []
        assert a.counters["result_rejected"] == 0
        # the loop actually closed: grew for the surge, shrank after
        decisions = [e for e in a.trace if e[3] == "autoscale"]
        assert any("up pressure=" in e[4] for e in decisions)
        assert any(e[4].startswith("down ") for e in decisions)
        assert a.ledger.peak_members == 2
        # elasticity is WORTH something: strictly fewer member-seconds
        # than the fixed-at-max control over the identical workload
        control = run_scenario(_elastic_scenario(
            autoscale=False, fleet_min=0, fleet_max=0, fleet=2,
        ))
        assert control.violations == []
        assert a.ledger.member_seconds < control.ledger.member_seconds
        # identical seed: byte-identical trace AND ledger, decisions and
        # member-seconds included
        b = run_scenario(scenario)
        assert a.trace_json() == b.trace_json()
        assert a.ledger_json() == b.ledger_json()

    def test_murder_during_elastic_run_stays_clean_and_deterministic(self):
        # member index 1 only exists if the autoscaler has grown the
        # tier by t=150; either way the run must replay byte-identically
        scenario = _elastic_scenario(
            fleet_faults=(FleetFault(at=150.0, kind="murder", member=1),),
        )
        a = run_scenario(scenario)
        assert a.violations == []
        assert a.counters["result_rejected"] == 0
        b = run_scenario(scenario)
        assert a.trace_json() == b.trace_json()
        assert a.ledger_json() == b.ledger_json()

    def test_codec_round_trips_the_elastic_fields(self):
        s = _elastic_scenario()
        back = scenario_from_json(scenario_to_json(s))
        assert back == s
        assert (back.autoscale, back.fleet_min, back.fleet_max) == (
            True, 1, 2,
        )
        # a pre-elastic encoding decodes with elasticity off
        plain = decode_scenario(
            {
                k: v
                for k, v in encode_scenario(s).items()
                if k not in ("autoscale", "fleet_min", "fleet_max")
            }
        )
        assert (plain.autoscale, plain.fleet_min, plain.fleet_max) == (
            False, 0, 0,
        )

    def test_validation_rejects_inconsistent_elastic_bounds(self):
        with pytest.raises(ValueError):
            run_scenario(Scenario(clusters=1, autoscale=True))  # no fleet
        with pytest.raises(ValueError):
            run_scenario(_elastic_scenario(fleet_min=3))  # fleet < min
        with pytest.raises(ValueError):
            run_scenario(_elastic_scenario(fleet_min=2, fleet_max=1))
        with pytest.raises(ValueError):
            run_scenario(_elastic_scenario(fleet_min=-1))
        with pytest.raises(ValueError):
            # min/max are autoscaler knobs: rejected when the loop is off
            run_scenario(_elastic_scenario(autoscale=False))
        # a fault may target any member the tier could GROW to…
        _elastic_scenario(
            fleet_faults=(FleetFault(at=60.0, kind="murder", member=1),),
        )
        # …but not beyond the max bound
        with pytest.raises(ValueError):
            run_scenario(_elastic_scenario(
                fleet_faults=(
                    FleetFault(at=60.0, kind="murder", member=2),
                ),
            ))


# ---------------------------------------------------------------------------
# invariant monitor units (stub op: the monitor only reads op.kube)
# ---------------------------------------------------------------------------


def _stub_op():
    store = KubeStore(VirtualClock(TWIN_EPOCH))
    return SimpleNamespace(kube=store), store


def _node(name: str, cpu: float = 4.0) -> Node:
    return Node(
        metadata=ObjectMeta(name=name),
        status=NodeStatus(
            capacity={"cpu": cpu, "memory": 8 * GIB},
            allocatable={"cpu": cpu, "memory": 8 * GIB},
        ),
    )


class TestInvariantMonitor:
    def test_gang_strand_flags_atomicity(self):
        op, store = _stub_op()
        wave = WorkloadWave(
            at=0.0, cluster=0, kind="training", count=4, gang_size=4
        )
        pods, _ = pods_for_wave(wave, "t0", seed=0)
        store.create(_node("n1", cpu=32.0))
        live = {}
        for pod in pods:
            store.create(pod)
            live[pod.name] = pod
        for pod in pods[:2]:  # bind HALF the gang: a strand
            store.bind(store.get(Pod, pod.name), "n1")
        monitor = InvariantMonitor()
        fresh = monitor.check(TWIN_EPOCH + 1.0, [op], {0: live})
        assert [v.invariant for v in fresh] == ["gang_atomicity"]
        assert "2/4" in fresh[0].detail

    def test_lost_pod_and_ghost_bind_flag_conservation(self):
        op, store = _stub_op()
        pod = Pod(metadata=ObjectMeta(name="p1"),
                  resource_requests={"cpu": 1.0})
        live = {"p1": pod, "p2": Pod(metadata=ObjectMeta(name="p2"))}
        store.create(pod)
        store.create(_node("n1"))
        store.bind(store.get(Pod, "p1"), "n1")
        ghost = store.get(Pod, "p1")
        ghost.node_name = "no-such-node"
        monitor = InvariantMonitor()
        fresh = monitor.check(TWIN_EPOCH + 1.0, [op], {0: live})
        kinds = sorted(v.invariant for v in fresh)
        assert kinds == ["pod_conservation", "pod_conservation"]
        details = " | ".join(v.detail for v in fresh)
        assert "vanished" in details and "ghost" in details

    def test_capacity_overcommit_flags(self):
        op, store = _stub_op()
        store.create(_node("n1", cpu=1.0))
        pod = Pod(metadata=ObjectMeta(name="big"),
                  resource_requests={"cpu": 4.0})
        store.create(pod)
        store.bind(store.get(Pod, "big"), "n1")
        monitor = InvariantMonitor()
        fresh = monitor.check(
            TWIN_EPOCH + 1.0, [op], {0: {"big": pod}}
        )
        assert any(v.invariant == "capacity" for v in fresh)

    def test_starved_pod_flags_after_max_pending(self):
        op, store = _stub_op()
        pod = Pod(metadata=ObjectMeta(name="stuck"))
        store.create(pod)
        monitor = InvariantMonitor(max_pending=100.0)
        assert monitor.check(
            TWIN_EPOCH + 50.0, [op], {0: {"stuck": pod}}
        ) == []
        fresh = monitor.check(
            TWIN_EPOCH + 200.0, [op], {0: {"stuck": pod}}
        )
        assert [v.invariant for v in fresh] == ["pod_conservation"]
        assert "pending" in fresh[0].detail


# ---------------------------------------------------------------------------
# topology-aware gangs (topoaware, ISSUE 20): distance-bound monitor,
# ledger hop accounting, and the racked closed loop
# ---------------------------------------------------------------------------


def _topo_node(name: str, zone: str, rack: str, cpu: float = 32.0) -> Node:
    node = _node(name, cpu=cpu)
    node.metadata.labels = {
        apilabels.LABEL_TOPOLOGY_ZONE: zone,
        apilabels.LABEL_TOPOLOGY_SUPERPOD: f"{zone}-s0",
        apilabels.LABEL_TOPOLOGY_RACK: rack,
    }
    return node


def _bounded_gang(max_hops: int = 0):
    """One 4-member gang declaring a hard hop bound, via the same wave
    generator the twin runs (annotations are the production contract)."""
    wave = WorkloadWave(
        at=0.0, cluster=0, kind="training", count=4, gang_size=4,
        max_hops=max_hops,
    )
    pods, _ = pods_for_wave(wave, "t0", seed=0)
    return pods


class TestGangDistanceMonitor:
    def test_bound_exceeding_placement_flags(self):
        op, store = _stub_op()
        # two racks in two ZONES: provable 3 hops against a 0-hop bound
        store.create(_topo_node("n1", "zone-a", "zone-a-r0"))
        store.create(_topo_node("n2", "zone-b", "zone-b-r0"))
        live = {}
        for i, pod in enumerate(_bounded_gang(max_hops=0)):
            store.create(pod)
            store.bind(store.get(Pod, pod.name), f"n{1 + i % 2}")
            live[pod.name] = pod
        monitor = InvariantMonitor()
        fresh = monitor.check(TWIN_EPOCH + 1.0, [op], {0: live})
        assert [v.invariant for v in fresh] == ["gang_distance"]
        assert "max-hops bound 0" in fresh[0].detail

    def test_placement_within_bound_is_clean(self):
        op, store = _stub_op()
        store.create(_topo_node("n1", "zone-a", "zone-a-r0"))
        store.create(_topo_node("n2", "zone-a", "zone-a-r0"))
        live = {}
        for i, pod in enumerate(_bounded_gang(max_hops=0)):
            store.create(pod)
            store.bind(store.get(Pod, pod.name), f"n{1 + i % 2}")
            live[pod.name] = pod
        monitor = InvariantMonitor()
        assert monitor.check(TWIN_EPOCH + 1.0, [op], {0: live}) == []

    def test_missing_rack_labels_skip_soundly(self):
        # rack-less nodes are unattributable: the sound bound must SKIP
        # them (soundness over completeness), never manufacture a
        # violation out of missing labels — even spanning two zones
        op, store = _stub_op()
        store.create(_node("n1"))
        store.create(_node("n2"))
        live = {}
        for i, pod in enumerate(_bounded_gang(max_hops=0)):
            store.create(pod)
            store.bind(store.get(Pod, pod.name), f"n{1 + i % 2}")
            live[pod.name] = pod
        monitor = InvariantMonitor()
        assert monitor.check(TWIN_EPOCH + 1.0, [op], {0: live}) == []

    def test_undeclared_bound_never_flags_distance(self):
        op, store = _stub_op()
        store.create(_topo_node("n1", "zone-a", "zone-a-r0"))
        store.create(_topo_node("n2", "zone-b", "zone-b-r0"))
        live = {}
        for i, pod in enumerate(_bounded_gang(max_hops=-1)):
            store.create(pod)
            store.bind(store.get(Pod, pod.name), f"n{1 + i % 2}")
            live[pod.name] = pod
        monitor = InvariantMonitor()
        assert monitor.check(TWIN_EPOCH + 1.0, [op], {0: live}) == []


def _topo_scenario(**overrides) -> Scenario:
    """Racked closed loop: a comms-sensitive training gang (hard hop
    bound + member ranks) competing with serving replicas under a PDB,
    on a catalog whose nodes carry deterministic rack labels."""
    base = dict(
        seed=9,
        clusters=1,
        duration=150.0,
        tick=30.0,
        solver="tpu",
        rack_size=2,
        waves=(
            WorkloadWave(at=0.0, cluster=0, kind="training", count=6,
                         gang_size=6, cpu=4.0, priority=100, max_hops=1),
            WorkloadWave(at=30.0, cluster=0, kind="serving", count=12,
                         min_available=2),
        ),
    )
    base.update(overrides)
    return Scenario(**base)


class TestTopoAwareTwin:
    def test_racked_run_respects_hard_bound_and_records_hops(self):
        result = run_scenario(_topo_scenario())
        # zero violations INCLUDES gang_distance and verifier_rejection:
        # the hard bound held at every stable tick and no accepted result
        # was rejected server-side
        assert result.violations == []
        assert result.counters["result_rejected"] == 0
        ledger = result.ledger.encode()
        # non-vacuous: the gang actually bound (all 6 members)
        assert ledger["slo"]["training"]["n"] == 6
        # ...and the hop accounting saw it: a recorded peak within the
        # declared bound (rack_size=2 packs the gang's nodes co-located)
        assert ledger["gang_max_hops"]["0"] <= 1
        assert ledger["straggler_gang_ticks"] == 0

    def test_rackless_run_ledger_keys_stay_constant(self):
        # off-by-default: without rack labels there is nothing to
        # attribute, so legacy scenarios' ledgers gain only constant keys
        result = run_scenario(_topo_scenario(
            rack_size=0,
            waves=(
                WorkloadWave(at=0.0, cluster=0, kind="training", count=6,
                             gang_size=6, cpu=4.0, priority=100),
            ),
        ))
        assert result.violations == []
        ledger = result.ledger.encode()
        assert ledger["gang_max_hops"] == {}
        assert ledger["straggler_gang_ticks"] == 0

    def test_racked_run_is_byte_deterministic(self):
        scenario = _topo_scenario()
        a = run_scenario(scenario)
        b = run_scenario(scenario)
        assert a.trace_json() == b.trace_json()
        assert a.ledger_json() == b.ledger_json()

    def test_max_hops_round_trips_and_validates(self):
        s = _topo_scenario()
        back = scenario_from_json(scenario_to_json(s))
        assert back == s
        assert back.waves[0].max_hops == 1 and back.rack_size == 2
        with pytest.raises(ValueError, match="max_hops"):
            run_scenario(_topo_scenario(waves=(
                WorkloadWave(at=0.0, cluster=0, kind="training", count=4,
                             gang_size=4, max_hops=9),
            )))
        with pytest.raises(ValueError, match="training"):
            run_scenario(_topo_scenario(waves=(
                WorkloadWave(at=0.0, cluster=0, kind="batch", count=4,
                             max_hops=1),
            )))


# ---------------------------------------------------------------------------
# the shrinker
# ---------------------------------------------------------------------------


def _buggy_scenario() -> Scenario:
    return Scenario(
        seed=11, clusters=2, duration=300.0, tick=30.0, solver="greedy",
        rates={
            "kube.create.conflict": 0.05,
            "kube.update.conflict": 0.05,
            "cloud.create.insufficient_capacity": 0.04,
        },
        storms=(Storm(start=30.0, duration=90.0, cluster=0, head=4),),
        waves=(
            WorkloadWave(at=0.0, cluster=0, kind="serving", count=20,
                         min_available=2),
            WorkloadWave(at=30.0, cluster=1, kind="training", count=16,
                         gang_size=4, priority=100),
            WorkloadWave(at=60.0, cluster=0, kind="batch", count=20,
                         lifetime=120.0),
        ),
        hooks=(TestHook(at=120.0, kind="lose_bound_pod", cluster=0),),
    )


class TestShrinker:
    def test_shrinks_injected_bug_to_minimal_scenario(self):
        small = shrink(_buggy_scenario(), max_runs=80)
        # the noise is gone: one cluster, one wave of one pod, no chaos
        assert small.clusters == 1
        assert small.rates == {}
        assert small.storms == ()
        assert len(small.waves) == 1
        assert small.waves[0].count == 1
        assert small.duration <= 150.0
        assert len(small.hooks) == 1  # the bug itself survives
        # and it still reproduces the violation
        result = run_scenario(small)
        assert [v.invariant for v in result.violations] == [
            "pod_conservation"
        ]

    def test_shrink_refuses_a_healthy_scenario(self):
        with pytest.raises(ValueError, match="does not violate"):
            shrink(_clean_scenario(duration=60.0, waves=(
                WorkloadWave(at=0.0, cluster=0, kind="batch", count=2),
            ), clusters=1))

    def test_committed_repro_replays_violation_fast(self):
        path = FIXTURES / "shrunk_lost_pod.json"
        t0 = time.perf_counter()
        result = replay(str(path))
        elapsed = time.perf_counter() - t0
        assert elapsed < 10.0, f"repro took {elapsed:.1f}s"
        assert [v.invariant for v in result.violations] == [
            "pod_conservation"
        ]
        # byte-deterministic replay: the fixture is a regression PIN
        again = replay(str(path))
        assert result.trace_json() == again.trace_json()
        assert result.ledger_json() == again.ledger_json()

    def test_nomination_overcommit_repro_stays_fixed(self):
        """The fuzzer's first real catch, pinned: under bind-conflict +
        launch-fault chaos, pods whose claim died re-solved into node
        capacity that nominated-but-unbound pods already owned — a
        per-node cpu overcommit. The shrunk scenario (this fixture, via
        twin/shrink.py) reproduced it in one cluster/two waves/30s; the
        fix (Provisioner._reserve_nominated + nominated-pod exclusion)
        must keep it violation-free."""
        result = replay(
            str(FIXTURES / "shrunk_nomination_overcommit.json")
        )
        assert result.violations == []

    def test_fixture_is_canonical_and_minimal(self):
        data = json.loads((FIXTURES / "shrunk_lost_pod.json").read_text())
        scenario = decode_scenario(data)
        assert scenario.clusters == 1
        assert len(scenario.waves) == 1
        assert scenario.waves[0].count == 1
        assert scenario.rates == {} and scenario.storms == ()


# ---------------------------------------------------------------------------
# fuzz soak + macro (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestTwinSoak:
    def test_fuzz_sweep_finds_no_violations_in_healthy_code(self):
        base = _clean_scenario(rates={
            "kube.create.conflict": 0.08,
            "kube.update.conflict": 0.05,
            "kube.bind.conflict": 0.05,
            "cloud.create.create_error": 0.05,
            "cloud.create.insufficient_capacity": 0.04,
            "cloud.delete.delete_error": 0.05,
        }, storms=(Storm(start=30.0, duration=120.0, head=6),))
        failing = fuzz(base, seeds=range(8), stop_after=0)
        assert failing == [], [
            (r.scenario.seed, r.first_violation()) for r in failing
        ]

    def test_fleet_fuzz_sweep_stays_clean(self):
        failing = fuzz(_storm_fleet_scenario(), seeds=range(3), stop_after=0)
        assert failing == [], [
            (r.scenario.seed, r.first_violation()) for r in failing
        ]

    def test_macro_run_ledger_sane(self):
        # thousands of pods over days of virtual churn in minutes of wall
        scenario = _clean_scenario(
            duration=3600.0 * 8, tick=600.0,
            waves=tuple(
                WorkloadWave(
                    at=600.0 * i, cluster=i % 2, kind=kind, count=count,
                    lifetime=7200.0 if kind != "serving" else 0.0,
                    min_available=2 if kind == "serving" else 0,
                    gang_size=8 if kind == "training" else 0,
                    priority=100 if kind == "training" else 0,
                )
                for i, (kind, count) in enumerate(
                    [("serving", 200), ("training", 160), ("batch", 400),
                     ("batch", 300), ("serving", 150), ("training", 80),
                     ("batch", 500), ("serving", 100)]
                )
            ),
        )
        result = run_scenario(scenario)
        assert result.violations == []
        ledger = result.ledger.encode()
        assert ledger["virtual_seconds"] == 3600.0 * 8
        assert sum(c["n"] for c in ledger["slo"].values()) == 1890
        assert all(v > 0 for v in ledger["cost_dollar_hours"].values())
