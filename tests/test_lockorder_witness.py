"""The runtime lock-order witness (utils/lockorder) and the soak that
pins the dynamic acquisition graph inside the static GL701 graph.

The fast tests are the negative control: they prove the witness actually
records nesting and that ``assert_within`` actually fails on a stray
edge — so the slow soak's "no stray edges" result can never be the
vacuous output of broken wiring. The soak itself drives the real
gateway/quarantine/cache objects from many threads and checks every
observed (held, acquired) pair against ``dataflow.get_locks`` over the
real solver tier — whose order graph is EMPTY by design, making the
assertion maximally strict: any runtime nesting at all is a finding.
"""
from __future__ import annotations

import threading
from pathlib import Path

import pytest

from karpenter_core_tpu.utils import lockorder

REPO_ROOT = Path(__file__).resolve().parent.parent


def _fresh():
    return lockorder.LockWitness()


# -- negative controls (fast) ------------------------------------------------


def test_nested_acquisition_records_edge_and_fails_empty_graph():
    w = _fresh()
    outer = lockorder.WitnessedLock(threading.Lock(), "A._lock", w)
    inner = lockorder.WitnessedLock(threading.Lock(), "B._lock", w)
    with outer:
        with inner:
            pass
    assert w.edges == {("A._lock", "B._lock")}
    with pytest.raises(AssertionError, match="A._lock -> B._lock"):
        w.assert_within(set())
    # the edge present in the static graph: clean
    w.assert_within({("A._lock", "B._lock")})


def test_reentrant_reacquire_records_no_edge():
    w = _fresh()
    lk = lockorder.WitnessedLock(threading.RLock(), "S._lock", w)
    with lk:
        with lk:
            pass
    assert w.edges == set()


def test_release_pops_lifo_and_tolerates_interleave():
    w = _fresh()
    a = lockorder.WitnessedLock(threading.Lock(), "A._lock", w)
    b = lockorder.WitnessedLock(threading.Lock(), "B._lock", w)
    a.acquire()
    b.acquire()
    a.release()  # out-of-order: must not corrupt the held stack
    c = lockorder.WitnessedLock(threading.Lock(), "C._lock", w)
    with c:
        pass
    b.release()
    assert ("B._lock", "C._lock") in w.edges
    assert ("A._lock", "C._lock") not in w.edges


def test_per_thread_stacks_do_not_cross():
    """Two threads each holding one lock is NOT an order edge."""
    w = _fresh()
    a = lockorder.WitnessedLock(threading.Lock(), "A._lock", w)
    b = lockorder.WitnessedLock(threading.Lock(), "B._lock", w)
    entered = threading.Event()
    done = threading.Event()

    def holder():
        with b:
            entered.set()
            done.wait(timeout=5)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert entered.wait(timeout=5)
    with a:
        pass
    done.set()
    t.join(timeout=5)
    assert w.edges == set()


def test_witness_proxy_passes_through(tmp_path):
    w = _fresh()
    raw = threading.Lock()
    proxy = lockorder.WitnessedLock(raw, "X._lock", w)
    assert proxy.acquire(timeout=1)
    assert raw.locked()  # passthrough attribute on the wrapped primitive
    proxy.release()
    assert not raw.locked()


def test_maybe_wrap_honors_env_flag(monkeypatch):
    class Holder:
        def __init__(self):
            self._lock = threading.Lock()

    monkeypatch.delenv(lockorder.ENV_FLAG, raising=False)
    h = Holder()
    assert lockorder.maybe_wrap(h, "_lock", "Holder._lock") is h._lock
    assert not isinstance(h._lock, lockorder.WitnessedLock)

    monkeypatch.setenv(lockorder.ENV_FLAG, "1")
    assert lockorder.enabled()
    wrapped = lockorder.maybe_wrap(h, "_lock", "Holder._lock")
    assert isinstance(wrapped, lockorder.WitnessedLock)
    assert h._lock is wrapped


# -- the soak: dynamic graph ⊆ static graph (slow) ---------------------------


def _static_lock_graph():
    from tools.graftlint import dataflow
    from tools.graftlint.engine import ParsedFile

    files = []
    for p in sorted(
        (REPO_ROOT / "karpenter_core_tpu" / "solver").glob("*.py")
    ):
        rel = str(p.relative_to(REPO_ROOT))
        files.append(ParsedFile(p, rel, p.read_text()))
    return dataflow.get_locks(files)


@pytest.mark.slow
def test_soak_runtime_order_stays_within_static_graph():
    from karpenter_core_tpu.solver import fleet

    df = _static_lock_graph()
    static_edges = set(df.order_edges)

    w = lockorder.LockWitness()
    gateway = fleet.FleetGateway(max_depth=64, p50_boot=0.001)
    quarantine = fleet.PoisonQuarantine(strikes=5, cap=32)
    cache = fleet.BoundedSchedulerCache(max_entries=16, max_bytes=1 << 20)
    lockorder.wrap(gateway, "_lock", "FleetGateway._lock", w)
    lockorder.wrap(quarantine, "_lock", "PoisonQuarantine._lock", w)
    lockorder.wrap(cache, "_lock", "BoundedSchedulerCache._lock", w)

    errors = []

    def worker(tenant):
        try:
            for i in range(40):
                fp = f"{tenant}-{i % 7}"
                try:
                    ticket = gateway.submit(tenant=tenant)
                except (fleet.ShedError, fleet.DrainError):
                    continue
                gateway.await_grant(ticket)
                try:
                    if quarantine.quarantined(fp):
                        quarantine.clear(fp)
                    if cache.get(fp) is None:
                        cache.put(fp, object(), approx_bytes=256)
                    quarantine.begin(fp)
                    quarantine.done(fp)
                    if i % 11 == 3:
                        quarantine.strike(fp, reason="soak")
                finally:
                    gateway.release(ticket, device_seconds=0.0005)
        except Exception as exc:  # pragma: no cover - failure reporting
            errors.append(exc)

    threads = [
        threading.Thread(target=worker, args=(f"tenant{k}",), daemon=True)
        for k in range(6)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads), "soak wedged"

    # the witness saw real traffic...
    assert gateway.snapshot()["grants"] >= 1
    # ...and every observed nesting exists in the static graph (which is
    # empty today: the tier takes one lock at a time, and this holds it
    # to that)
    w.assert_within(static_edges)
