"""Pin the device resource quantization invariants (models/provisioner
rvec/rvec_cap + the margin-free kernel floor in ops/ffd).

The cfg3 parity fix rests on: requests and capacities reach the device as
integer-valued float32 (milli-cpu, Mi-memory, Gi-ephemeral, unit counts),
so floor((alloc - req) / r) is exact and exact-boundary fits — the last
pod that exactly fills a node, which the greedy oracle's float64 math
accepts — are not shaved. A revert of any ceil/floor call site or a
margin reintroduction must fail here, not in an offline bench.
"""
import copy

import numpy as np
import pytest

from tests.helpers import GIB, make_nodepool, make_pod

from karpenter_core_tpu.api.objects import ObjectMeta, Pod
from karpenter_core_tpu.cloudprovider.types import (
    InstanceType,
    Offering,
    Offerings,
)
from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
    Scheduler,
)
from karpenter_core_tpu.models.provisioner import DeviceScheduler
from karpenter_core_tpu.scheduling import Requirements


def _one_type_catalog(cpu, mem_gib, pods=200.0):
    it = InstanceType(
        name="boundary-1x",
        requirements=Requirements.from_labels(
            {
                L.LABEL_INSTANCE_TYPE: "boundary-1x",
                L.LABEL_ARCH: "amd64",
                L.LABEL_OS: "linux",
            }
        ),
        offerings=Offerings(
            [
                Offering(
                    requirements=Requirements.from_labels(
                        {
                            L.LABEL_TOPOLOGY_ZONE: "zone-a",
                            L.CAPACITY_TYPE_LABEL_KEY: "on-demand",
                        }
                    ),
                    price=1.0,
                    available=True,
                )
            ]
        ),
        capacity={"cpu": cpu, "memory": mem_gib * GIB, "pods": pods},
    )
    return [it]


def _solve_both(pods, catalog, max_slots=64):
    pool = make_nodepool("default")
    g = Scheduler([copy.deepcopy(pool)], {"default": list(catalog)})
    gres = g.solve(copy.deepcopy(pods))
    d = DeviceScheduler(
        [pool], {"default": list(catalog)}, max_slots=max_slots
    )
    dres = d.solve(pods)
    return gres, dres


class TestQuantizationVectors:
    """rvec/rvec_cap rounding directions, observed through _prepare."""

    def _prep(self, catalog, pods):
        from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
            Topology,
        )

        pool = make_nodepool("default")
        d = DeviceScheduler([pool], {"default": list(catalog)}, max_slots=16)
        topo = Topology(domains={k: set(v) for k, v in d.domains_universe.items()})
        return d._prepare(pods, 16, topo)

    def test_exact_multiples_quantize_exactly(self):
        catalog = _one_type_catalog(cpu=4.0, mem_gib=8.0)
        pods = [make_pod(cpu=0.1, memory_gib=0.25, name="p0")]
        prep = self._prep(catalog, pods)
        names = prep.resource_names
        creq = prep.class_requests[0]
        assert creq[names.index("cpu")] == 100.0  # 0.1 core -> 100 milli
        assert creq[names.index("memory")] == 256.0  # 0.25 GiB -> 256 Mi
        alloc = np.asarray(prep.statics.it_alloc)[0]
        # allocatable (whatever overhead model) must be an exact integer
        assert alloc[names.index("cpu")] == np.floor(alloc[names.index("cpu")])
        assert alloc[names.index("memory")] == np.floor(
            alloc[names.index("memory")]
        )

    def test_sub_unit_request_ceils_capacity_floors(self):
        catalog = _one_type_catalog(cpu=4.0, mem_gib=8.0)
        # 0.1234567 cores = 123.4567 milli -> ceil 124
        pods = [
            Pod(
                metadata=ObjectMeta(name="odd"),
                resource_requests={"cpu": 0.1234567, "memory": 1000.0},
            )
        ]
        prep = self._prep(catalog, pods)
        names = prep.resource_names
        creq = prep.class_requests[0]
        assert creq[names.index("cpu")] == 124.0
        # 1000 bytes -> ceil to 1 Mi
        assert creq[names.index("memory")] == 1.0

    def test_float64_twins_are_quantized_integers(self):
        """The decode twins carry the device's integer units (unclamped
        float64) so repeated adds are EXACT — raw floats drift ~1e-13 on an
        exactly-full slot and falsely defer it to the per-pod host path
        (the r4 50k-topology decode cliff)."""
        catalog = _one_type_catalog(cpu=4.0, mem_gib=8.0)
        pods = [make_pod(cpu=0.1, memory_gib=0.25, name="p0")]
        prep = self._prep(catalog, pods)
        names = prep.resource_names
        creq64q = prep.class_requests64q[0]
        assert creq64q[names.index("cpu")] == 100.0  # milli, ceil
        assert creq64q[names.index("memory")] == 256.0  # Mi, ceil
        alloc64q = prep.it_alloc64q[0]
        assert alloc64q[names.index("cpu")] == np.floor(
            alloc64q[names.index("cpu")]
        )
        # 160 x 0.1-cpu adds stay integer-exact against a 16-cpu boundary
        acc = np.zeros_like(creq64q)
        for _ in range(160):
            acc = acc + creq64q
        assert acc[names.index("cpu")] == 16000.0


class TestExactBoundaryFits:
    """The device must not shave the last exact-fit pod (r4 cfg3 gap)."""

    def test_exact_cpu_fill_single_node(self):
        # allocatable cpu on this catalog shape: verify via the type itself,
        # then fill it exactly with 0.05-core pods
        catalog = _one_type_catalog(cpu=4.0, mem_gib=64.0)
        alloc_cpu = catalog[0].allocatable()["cpu"]
        n = int(round(alloc_cpu / 0.05))
        assert abs(n * 0.05 - alloc_cpu) < 1e-9
        pods = [
            Pod(
                metadata=ObjectMeta(name=f"p{i}"),
                resource_requests={"cpu": 0.05, "memory": 1.0 * 2**20},
            )
            for i in range(n)
        ]
        gres, dres = _solve_both(pods, catalog)
        assert dres.all_pods_scheduled()
        assert dres.node_count() <= gres.node_count()
        # 0.05 quantizes to 50 milli exactly; the device packs one node
        assert dres.node_count() == 1

    def test_exact_memory_fill_single_node(self):
        catalog = _one_type_catalog(cpu=64.0, mem_gib=8.0)
        alloc_mem = catalog[0].allocatable()["memory"]
        mi = alloc_mem / 2**20
        assert mi == int(mi), "catalog allocatable must be Mi-round for this test"
        per = 64  # Mi per pod
        n = int(mi // per)
        pods = [
            Pod(
                metadata=ObjectMeta(name=f"p{i}"),
                resource_requests={"cpu": 0.001, "memory": per * 2**20},
            )
            for i in range(n)
        ]
        gres, dres = _solve_both(pods, catalog)
        assert dres.all_pods_scheduled()
        assert dres.node_count() <= gres.node_count()

    def test_exact_fit_binds_one_quantum_over_does_not(self):
        """Regression pin for the ~1e-13 resource-boundary drift workaround
        (requests round UP, capacity rounds DOWN, float64 decode twins):

        * pods summing EXACTLY to a power-of-two allocatable must bind to
          one node — the r4 cfg3 cliff was this fit getting shaved;
        * one QUANTUM (1 milli-cpu) over must NOT bind — the rounding
          absorbs only sub-quantum noise, never a representable overshoot.
        """
        catalog = _one_type_catalog(cpu=8.0, mem_gib=64.0)
        alloc_cpu = catalog[0].allocatable()["cpu"]
        assert alloc_cpu == 8.0  # power-of-two boundary, no overhead model
        exact = [
            Pod(
                metadata=ObjectMeta(name=f"e{i}"),
                resource_requests={"cpu": 0.5, "memory": 1.0 * 2**20},
            )
            for i in range(16)  # 16 x 0.5 == 8.0 exactly
        ]
        gres, dres = _solve_both(exact, catalog)
        assert dres.all_pods_scheduled()
        assert gres.node_count() == 1
        assert dres.node_count() == 1

        over = [
            Pod(
                metadata=ObjectMeta(name=f"o{i}"),
                resource_requests={"cpu": 0.5, "memory": 1.0 * 2**20},
            )
            for i in range(15)
        ] + [
            Pod(
                metadata=ObjectMeta(name="o15"),
                # 0.501 cores: one milli-cpu past the exact fill
                resource_requests={"cpu": 0.501, "memory": 1.0 * 2**20},
            )
        ]
        gres, dres = _solve_both(over, catalog)
        assert dres.all_pods_scheduled()
        assert gres.node_count() == 2
        assert dres.node_count() == 2

    def test_one_ulp_over_is_absorbed_as_fixed_point_noise(self):
        """One float64 ULP past the boundary is BELOW the request quantum
        and inside the deliberate 1e-12 relative guard band: k8s
        resource.Quantity is fixed-point decimal (resources.go:28-66), so
        a true API quantity cannot express capacity+1ULP — the drift can
        only be float noise from host arithmetic, and the quantizer must
        swallow it rather than open a phantom second node. (The raw-float
        greedy oracle DOES trip on this adversarial non-decimal input;
        the device solver is the one matching the fixed-point model, so
        this asserts the device packing only.)"""
        catalog = _one_type_catalog(cpu=8.0, mem_gib=64.0)
        per = float(np.nextafter(0.5, 1.0))  # 0.5 + 1 ULP
        pods = [
            Pod(
                metadata=ObjectMeta(name=f"u{i}"),
                resource_requests={"cpu": per, "memory": 1.0 * 2**20},
            )
            for i in range(16)  # raw float sum: 8.000000000000002
        ]
        pool = make_nodepool("default")
        d = DeviceScheduler([pool], {"default": list(catalog)}, max_slots=64)
        dres = d.solve(pods)
        assert dres.all_pods_scheduled()
        assert dres.node_count() == 1

    def test_device_never_overpacks_vs_host_refit(self):
        """Sub-unit odd requests: device may quantize-conservative but the
        result must stay valid (every claim's float64 requests fit)."""
        catalog = _one_type_catalog(cpu=2.0, mem_gib=4.0)
        pods = [
            Pod(
                metadata=ObjectMeta(name=f"p{i}"),
                resource_requests={
                    "cpu": 0.0333,
                    "memory": 777777.0,  # odd bytes, sub-Mi
                },
            )
            for i in range(50)
        ]
        gres, dres = _solve_both(pods, catalog)
        assert dres.all_pods_scheduled()
        for c in dres.new_node_claims:
            best = max(
                (it.allocatable() for it in c.instance_type_options),
                key=lambda a: a.get("cpu", 0.0),
            )
            assert c.requests.get("cpu", 0.0) <= best["cpu"] + 1e-12
            assert c.requests.get("memory", 0.0) <= best["memory"] + 1e-9
