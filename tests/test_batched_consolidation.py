"""Device-batched multi-node consolidation: the vmapped prefix evaluation
must agree with per-prefix host simulation (BASELINE config 4 shape)."""
import pytest

from tests.helpers import make_nodepool, make_pod
from tests.test_disruption import new_operator, od_nodepool, replicated

from karpenter_core_tpu.api.objects import Pod
from karpenter_core_tpu.cloudprovider.kwok import KwokCloudProvider, build_catalog
from karpenter_core_tpu.controllers.disruption.helpers import (
    get_candidates,
    simulate_scheduling,
)
from karpenter_core_tpu.kube.store import KubeStore
from karpenter_core_tpu.models.consolidation import schedulability_frontier
from karpenter_core_tpu.operator import Operator, Options
from karpenter_core_tpu.utils.clock import FakeClock

CATALOG = build_catalog(cpu_grid=[1, 2, 4, 8, 16], mem_factors=[2, 4])


def underutilized_fleet(n_candidates: int, solver: str = "tpu"):
    """Build a fleet of underutilized nodes: big pods provisioned then
    swapped for small ones."""
    clock = FakeClock()
    kube = KubeStore(clock)
    op = Operator(
        kube=kube,
        cloud_provider=KwokCloudProvider(kube, CATALOG),
        clock=clock,
        options=Options(solver=solver),
    )
    op.kube.create(od_nodepool())
    for i in range(n_candidates):
        op.kube.create(replicated(make_pod(cpu=7.0, name=f"big{i}")))
        op.kube.create(replicated(make_pod(cpu=7.0, name=f"big{i}b")))
    op.run_until_idle(disrupt=False)
    for i in range(n_candidates):
        for name in (f"big{i}", f"big{i}b"):
            p = op.kube.get(Pod, name)
            p.metadata.owner_references = []
            op.kube.delete(p)
        op.kube.create(replicated(make_pod(cpu=0.2, name=f"small{i}")))
    op.run_until_idle(disrupt=False)
    return op


class TestFrontierParity:
    @pytest.mark.parametrize("n", [3, 6])
    def test_frontier_matches_host_simulation(self, n):
        op = underutilized_fleet(n)
        candidates = get_candidates(
            op.clock,
            op.cluster,
            op.kube,
            op.cloud_provider,
            lambda c: True,
        )
        candidates.sort(key=lambda c: c.disruption_cost)
        assert len(candidates) >= 2
        frontier = schedulability_frontier(
            op.provisioner, op.cluster, candidates
        )
        assert frontier is not None
        for p, (ok_device, n_new, price_lb) in enumerate(frontier):
            results = simulate_scheduling(
                op.provisioner, op.cluster, candidates[: p + 1]
            )
            ok_host = results.all_pods_scheduled()
            assert ok_device == ok_host, (p, results.pod_errors)
            if ok_host:
                assert n_new == results.node_count(), p
                if n_new:
                    # the bound is a positive finite price whenever a fresh
                    # node opens (its exact relation to the host replacement
                    # depends on matching packing, so only sanity is asserted)
                    assert 0.0 < price_lb < float("inf"), (p, price_lb)

    def test_topology_pods_fall_back(self):
        op = underutilized_fleet(2)
        # pin a spread pod onto the cluster: batched path must decline
        op.kube.create(
            replicated(make_pod(cpu=0.2, name="spready", spread_zone=True))
        )
        op.run_until_idle(disrupt=False)
        candidates = get_candidates(
            op.clock, op.cluster, op.kube, op.cloud_provider, lambda c: True
        )
        if candidates:
            assert (
                schedulability_frontier(op.provisioner, op.cluster, candidates)
                is None
            )


class TestEndToEndBatched:
    def test_tpu_solver_consolidates_fleet(self):
        op = underutilized_fleet(6, solver="tpu")
        cap_before = sum(
            n.status.capacity.get("cpu", 0) for n in op.kube.list_nodes()
        )
        op.run_until_idle(max_iters=200)
        assert all(p.node_name for p in op.kube.list_pods())
        cap_after = sum(
            n.status.capacity.get("cpu", 0) for n in op.kube.list_nodes()
        )
        assert cap_after < cap_before / 2, (cap_before, cap_after)

    def test_matches_greedy_solver_outcome(self):
        op_t = underutilized_fleet(4, solver="tpu")
        op_g = underutilized_fleet(4, solver="greedy")
        for op in (op_t, op_g):
            op.run_until_idle(max_iters=200)
        cap = lambda op: sum(
            n.status.capacity.get("cpu", 0) for n in op.kube.list_nodes()
        )
        assert cap(op_t) == cap(op_g)


class TestFrontierFallback:
    def test_empty_frontier_still_binary_searches(self, monkeypatch):
        """The device FFD is conservative (sub-unit quantization, first-fit),
        so an empty frontier must NOT suppress the host binary search
        (ADVICE r1 #3)."""
        from karpenter_core_tpu.controllers.disruption import methods

        op = underutilized_fleet(4, solver="tpu")
        monkeypatch.setattr(
            methods.MultiNodeConsolidation,
            "_device_frontier",
            lambda self, candidates: ([], []),
        )
        cap_before = sum(
            n.status.capacity.get("cpu", 0) for n in op.kube.list_nodes()
        )
        op.run_until_idle(max_iters=200)
        assert all(p.node_name for p in op.kube.list_pods())
        cap_after = sum(
            n.status.capacity.get("cpu", 0) for n in op.kube.list_nodes()
        )
        assert cap_after < cap_before / 2, (cap_before, cap_after)
