"""GOOD: the same daemon with every journal write moved off the exclusive
window — the device phase between await_grant and release touches no file
or network, and the state lock guards only in-memory counters."""
import json
import threading


class Gateway:
    def __init__(self):
        self._lock = threading.RLock()

    def await_grant(self, ticket):
        pass

    def release(self, ticket, seconds):
        pass


class Daemon:
    def __init__(self, gateway, journal_path):
        self.gateway = gateway
        self.journal_path = journal_path
        self._state_lock = threading.Lock()
        self.solves = 0

    def _write_journal(self, digest):
        with open(self.journal_path, "w") as f:
            json.dump({"inflight": [digest]}, f)

    def _solve_device(self, ticket):
        return ticket

    def solve(self, ticket, digest):
        self.gateway.await_grant(ticket)
        try:
            result = self._solve_device(ticket)
        finally:
            self.gateway.release(ticket, 0.0)
        self._write_journal(digest)  # off the window: after release
        return result

    def count(self, n):
        with self._state_lock:
            self.solves += n
        self._write_journal(str(n))  # off the lock
