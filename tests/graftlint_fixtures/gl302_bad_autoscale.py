"""GL302 bad, autoscaler shape: a control-loop class (streak counters,
cooldown stamps, an owning _state_lock) whose step path bumps the shared
hysteresis streaks OUTSIDE the lock — the exact class shape
solver/autoscale.py ships, with the discipline broken. A poller thread
and an HTTP handler thread stepping concurrently lose streak updates and
the tier double-scales."""
import threading


class TierAutoscaler:
    def __init__(self, tier, min_members, max_members):
        self.tier = tier
        self.min_members = min_members
        self.max_members = max_members
        self._state_lock = threading.Lock()
        self._up_streak = 0
        self._down_streak = 0
        self._last_scale_at = 0.0

    def step(self, now, pressure):
        if pressure >= 1.0:
            self._up_streak += 1  # two stepping threads read the same value
            with self._state_lock:
                self._down_streak = 0
        else:
            with self._state_lock:
                self._up_streak = 0
            self._down_streak = self._down_streak + 1  # same lost update
        with self._state_lock:
            self._last_scale_at = now

    def start(self, interval):
        threading.Thread(
            target=self.step, args=(0.0, 0.0), daemon=True
        ).start()
