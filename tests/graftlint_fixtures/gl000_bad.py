"""GL000 bad: a suppression with no justification."""


def encode_header(labels):
    # graftlint: disable=GL201
    return [k for k, _v in labels.items()]
