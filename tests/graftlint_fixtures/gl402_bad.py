"""GL402 bad: an emission site with no registered instrument."""
from karpenter_core_tpu.metrics import wiring as m


def record(n):
    m.PHANTOM_SERIES_TOTAL.inc(by=n)
