"""GL401 bad: one-sided wire fields and a missing decode twin."""


def _encode_blob(b) -> dict:
    return {"name": b.name, "size": b.size, "flags": b.flags}


def _decode_blob(d: dict):
    return (d["name"], d["size"])  # "flags" drops on the floor


def encode_orphan(o) -> dict:
    return {"payload": o.payload}  # no decode_orphan anywhere
