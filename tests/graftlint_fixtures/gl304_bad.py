"""BAD: journal file I/O while the exclusive device grant (and the daemon
state lock) is held — the ISSUE 8/9 review finding as a fixture. A
disk-full (or NFS-stalled) write here wedges every queued tenant behind
this grant."""
import json
import threading


class Gateway:
    def __init__(self):
        self._lock = threading.RLock()

    def await_grant(self, ticket):
        pass

    def release(self, ticket, seconds):
        pass


class Daemon:
    def __init__(self, gateway, journal_path):
        self.gateway = gateway
        self.journal_path = journal_path
        self._state_lock = threading.Lock()
        self.solves = 0

    def _write_journal(self, digest):
        with open(self.journal_path, "w") as f:
            json.dump({"inflight": [digest]}, f)

    def solve(self, ticket, digest):
        self.gateway.await_grant(ticket)
        try:
            self._write_journal(digest)  # file I/O inside the window
            return ticket
        finally:
            self.gateway.release(ticket, 0.0)

    def count(self, n):
        with self._state_lock:
            self.solves += n
            self._write_journal(str(n))  # file I/O under the state lock
