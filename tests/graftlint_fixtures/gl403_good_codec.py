# GL403 good: the same `priority` field addition done right — the wire
# version bumped to 3 and the sidecar lock (gl403_good_codec.lock.json)
# was regenerated with `--update-wire-lock`, so lock, version constant,
# and field set agree. A mixed deployment now fails EXPLICITLY on the
# version check instead of silently dropping the field. Lint corpus only
# — never imported.
import json

SOLVE_WIRE_VERSION = 3


def encode_solve_request(pods, max_slots, tenant, priority):
    header = {
        "version": SOLVE_WIRE_VERSION,
        "pods": pods,
        "max_slots": max_slots,
        "tenant": tenant,
        "priority": priority,
    }
    return json.dumps(header).encode()


def decode_solve_request(data):
    h = json.loads(data.decode())
    if h["version"] != SOLVE_WIRE_VERSION:
        raise ValueError("unsupported solve wire version")
    return {
        "pods": h["pods"],
        "max_slots": h["max_slots"],
        "tenant": h["tenant"],
        "priority": h["priority"],
    }
