"""GL301 good: every thread decides its shutdown behavior explicitly."""
import threading


def start_worker(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t


def start_blocking_worker(fn):
    # non-daemon on purpose: this one must finish before exit
    t = threading.Thread(target=fn, daemon=False)
    t.start()
    return t
