"""GL303 bad: one attribute, two lock disciplines."""
import threading


class Journal:
    def __init__(self):
        self._lock = threading.Lock()
        self.events = []

    def record(self, event):
        with self._lock:
            self.events.append(event)

    def reset(self):
        self.events = []  # bare write to a lock-guarded attribute

    def serve(self):
        threading.Thread(target=self.record, daemon=True).start()


class TwoLocks:
    """Same attribute, two different owning locks — also mixed."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state_lock = threading.Lock()
        self.count = 0

    def bump(self):
        with self._lock:
            self.count += 1

    def bump_elsewhere(self):
        with self._lock:
            self.count += 1

    def reset(self):
        with self._state_lock:  # wrong lock: no mutual exclusion vs bump
            self.count = 0

    def serve(self):
        threading.Thread(target=self.bump, daemon=True).start()
