"""GL302 bad, fair-queue shape: a gateway class (per-tenant queues, a
virtual clock, an admission counter) whose handler-thread entry points bump
shared counters OUTSIDE the owning lock — the exact class shape
solver/fleet.py ships, with the discipline broken."""
import threading
from collections import deque


class FairQueueGateway:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = 0
        self._vclock = 0.0
        self._queued = {}

    def submit(self, tenant):
        with self._lock:
            self._queued.setdefault(tenant, deque()).append(object())
        self._pending += 1  # two handler threads read the same old value

    def release(self, tenant, seconds):
        with self._lock:
            self._queued[tenant].popleft()
        self._vclock = self._vclock + seconds  # same lost-update shape

    def serve(self, tenant):
        threading.Thread(
            target=self.submit, args=(tenant,), daemon=True
        ).start()
