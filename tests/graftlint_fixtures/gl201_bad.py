"""GL201 bad: unordered iteration inside an encoding function."""


def encode_header(labels, tags):
    names = [k for k, _v in labels.items()]  # dict arrival order
    extras = []
    for t in set(tags):  # set order is undefined
        extras.append(t)
    return names + extras


def fingerprint(req):
    return tuple(v for v in req.values)  # Requirement.values is a set
