"""GL401 good: field parity, plus the passthrough-decode form."""
import json


def _encode_blob(b) -> dict:
    return {"name": b.name, "size": b.size, "flags": b.flags}


def _decode_blob(d: dict):
    return (d["name"], d["size"], d["flags"])


def encode_results(r) -> bytes:
    return json.dumps({"version": 1, "claims": r.claims}).encode()


def decode_results(data: bytes) -> dict:
    h = json.loads(data)
    if h.get("version") != 1:
        raise ValueError("skew")
    return h  # passthrough: every remaining key is consumed downstream
