"""GL302 bad: counter bumped outside the lock in a threaded module."""
import threading


class Daemon:
    def __init__(self):
        self._lock = threading.Lock()
        self.solves = 0
        self.cache = {}

    def handle(self, key, value):
        with self._lock:
            self.cache[key] = value
        self.solves += 1  # lost update under concurrent handlers

    def serve(self):
        threading.Thread(target=self.handle, daemon=True).start()
