# GL503 good: the sanctioned shapes. Host code fetches through
# jax.device_get on a sliced window (the transfer is explicit and sized),
# scalars concretize from the fetched host copy, and placement carries an
# explicit sharding so the multi-device path stays pre-sharded. Lint
# corpus only — never imported.
import jax
import numpy as np

from karpenter_core_tpu.ops.ffd import ffd_solve
from karpenter_core_tpu.parallel import mesh as pmesh


def fetch_planes(mesh, plane_np, used):
    plane = jax.device_put(plane_np, pmesh.axis_sharding(mesh, 2, 0))
    window = jax.device_get(plane[:used])  # explicit, windowed fetch
    host = np.asarray(window)
    head = int(window[0, 0])
    return host, head


def run_solve(mesh, state_np, classes, statics, n_slots):
    state = jax.device_put(
        state_np, pmesh.slot_shardings(mesh, state_np, n_slots)
    )
    return ffd_solve(state, classes, statics)
