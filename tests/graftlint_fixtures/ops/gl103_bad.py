"""GL103 bad: a jit entry point threads slot-state without donation."""
import jax


@jax.jit
def run_scan(state, classes):
    return state, classes
