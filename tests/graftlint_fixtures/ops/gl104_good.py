# GL104 good: the call site routes slot-state placement through
# parallel.mesh.slot_shardings before the SlotState jit entry runs, so
# the multi-device copy lands pre-sharded. Lint corpus only — never
# imported.
import jax

from karpenter_core_tpu.ops.ffd import ffd_solve
from karpenter_core_tpu.parallel import slot_mesh, slot_shardings


def run_solve(state_np, classes, statics, n_slots):
    mesh = slot_mesh(8)
    state = jax.device_put(state_np, slot_shardings(mesh, state_np, n_slots))
    return ffd_solve(state, classes, statics)
