"""GL102 good: static args, None checks, and shape branches are fine."""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("mode",))
def clamp(x, limit, mode, fallback=None):
    if mode:  # static arg: resolved at trace time
        x = jnp.abs(x)
    if fallback is None:  # structure check, not a tracer value
        fallback = limit
    if x.shape[0] > 1:  # shapes are trace-time constants
        x = x[:1]
    return jnp.where(x > limit, limit, x)  # tracer branch done on device
