# GL504 bad: hand-rolled slot-axis shape arithmetic over the device
# count — truncating floor-division sizing, a modulo remainder split, and
# a reshape that folds a device axis in front of the slot dim. All three
# work only while the slot count happens to divide the mesh; on any other
# device count they truncate or crash where parallel.mesh.pad_to_devices
# pads with inert slots. Lint corpus only — never imported.


def shard_by_hand(x, max_slots, n_devices):
    n = (max_slots // n_devices) * n_devices  # GL504: truncates
    folded = x.reshape(n_devices, -1)  # GL504: manual device fold
    tail = max_slots % n_devices  # GL504: remainder split
    return folded, x[:n], tail
