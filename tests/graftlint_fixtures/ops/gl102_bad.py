"""GL102 bad: Python branching on a tracer value."""
import jax
import jax.numpy as jnp


@jax.jit
def clamp(x, limit):
    if x > limit:  # tracer branch: trace error or baked-in branch
        return limit
    return jnp.abs(x)


# static_argnames are per-entry: `steps` is static HERE...
from functools import partial  # noqa: E402


@partial(jax.jit, static_argnames=("steps",))
def unrolled(x, steps):
    for _ in range(steps):
        x = x * 2.0
    return x


@jax.jit
def other(x, steps):
    if steps > 3:  # ...but NOT here: this steps is a tracer
        return x
    return -x
