# GL503 bad: four host materializations of slot-sharded values, each an
# implicit cross-device gather (or unannotated placement) on a real mesh:
# np.asarray of a sharded plane, a scalar int() concretization, a
# per-shard .addressable_data read, and the bare single-arg
# jax.device_put the retired GL104 used to catch. Lint corpus only —
# never imported.
import jax
import numpy as np

from karpenter_core_tpu.ops.ffd import ffd_solve
from karpenter_core_tpu.parallel import mesh as pmesh


def fetch_planes(mesh, plane_np):
    plane = jax.device_put(plane_np, pmesh.axis_sharding(mesh, 2, 0))
    host = np.asarray(plane)  # GL503: full gather
    head = int(plane[0, 0])  # GL503: scalar concretization
    shard0 = plane.addressable_data(0)  # GL503: per-shard host read
    return host, head, shard0


def run_solve(state_np, classes, statics):
    state = jax.device_put(state_np)  # GL503: bare put (was GL104)
    return ffd_solve(state, classes, statics)
