"""BAD: a fused-kernel wrapper (the ops/pallas_ffd.py shape) pads the
score plane to the block multiple and lets the inert padded rows vote in
the argmin that picks the fused step's winning slot — pad-provenance
content reaches a reduction inside the traced wrapper with no masking
step."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _scale_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


@jax.jit
def fused_pick(scores):
    padded = jnp.pad(scores, (0, 8))
    fused = pl.pallas_call(
        _scale_kernel,
        out_shape=jax.ShapeDtypeStruct(padded.shape, padded.dtype),
        interpret=True,
    )(padded)
    return fused, jnp.argmin(padded)
