"""GOOD: the padded content routes through a masking step (jnp.where with
a validity predicate and a neutral fill) before the reduction — padded
slots cannot vote."""
import jax
import jax.numpy as jnp


@jax.jit
def pick_slot(scores, n):
    padded = jnp.pad(scores, (0, 8))
    masked = jnp.where(jnp.arange(padded.shape[0]) < n, padded, 1e30)
    return jnp.argmin(masked)
