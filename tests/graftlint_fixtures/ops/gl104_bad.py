# GL104 bad: drives the SlotState jit entry with a bare single-arg
# jax.device_put — the placement bypasses parallel.mesh.slot_shardings,
# so on a multi-device mesh the state lands unannotated and every
# dispatch pays a reshard. Lint corpus only — never imported.
import jax

from karpenter_core_tpu.ops.ffd import ffd_solve


def run_solve(state_np, classes, statics):
    state = jax.device_put(state_np)  # no sharding: GL104
    return ffd_solve(state, classes, statics)
