"""GL101 bad: host syncs inside a traced region."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def solve(x):
    y = np.asarray(x)  # materializes the tracer on host
    total = jnp.sum(y)
    return float(total)  # concretizes a tracer


def helper(v):
    return v.item()  # device->host sync


def scan_root(xs):
    return jax.lax.scan(lambda c, x: (c + helper(x), c), 0.0, xs)
