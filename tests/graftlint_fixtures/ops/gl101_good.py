"""GL101 good: the traced region stays on device; host code may sync."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def solve(x):
    return jnp.sum(x * 2.0)


def host_decode(result):
    # not reachable from any traced root: numpy and .item() are fine here
    arr = np.asarray(result)
    return float(arr.sum()), arr.max().item()
