"""GL103 good: the slot-state carry is donated (or absent)."""
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def run_scan(state, classes):
    return state, classes


@jax.jit
def aggregate(takes, unplaced):
    return takes, unplaced
