"""BAD: an unmasked argmin over pad-provenance content inside a traced
region — the inert padded slots participate in the reduction, so a padded
row can win the argmin and steer the packing."""
import jax
import jax.numpy as jnp


@jax.jit
def pick_slot(scores):
    padded = jnp.pad(scores, (0, 8))
    return jnp.argmin(padded)
