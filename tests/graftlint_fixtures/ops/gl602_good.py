"""GOOD: the idiomatic forms — equality against the NAMED sentinel for
the gang-free gate, and the zero-boundary test only where no deeper
sentinel is positively live (a parameter whose values the analysis cannot
see stays silent: positive evidence only)."""
import numpy as np

GANG_FREE = -1
GANG_FALLBACK_STRADDLING = -2


def preempt_gate(unplaced):
    gang_of_class = np.full((8,), GANG_FREE, dtype=np.int32)
    gang_of_class[3] = GANG_FALLBACK_STRADDLING
    # gang-free is exactly GANG_FREE — never `< 0`
    eligible = (unplaced > 0) & (gang_of_class == GANG_FREE)
    return eligible


def kernel_gangs(gang_of_step):
    # selecting kernel-enforced gangs (>= 0) on a plane with no deeper
    # sentinel positively live here
    return gang_of_step >= 0
