"""BAD: sentinel-domain confusion on the gang planes — a zero-boundary
test while the -2 (fallback-straddling) sentinel is live conflates it
with -1 (gang-free), and a cross-domain comparison treats unrelated
sentinel spaces as one."""
import numpy as np

GANG_FREE = -1
GANG_FALLBACK_STRADDLING = -2


def preempt_gate(unplaced):
    gang_of_class = np.full((8,), GANG_FREE, dtype=np.int32)
    gang_of_class[3] = GANG_FALLBACK_STRADDLING
    # conflates gang-free with fallback-straddling: a preemption gated on
    # this would evict real workload for a gang the backstop may strip
    eligible = (unplaced > 0) & (gang_of_class < 0)
    return eligible


def joint_mask(gang_of_step, new_template):
    # gang indices and template indices are unrelated sentinel spaces
    return gang_of_step == new_template
