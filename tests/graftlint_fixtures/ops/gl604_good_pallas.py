"""GOOD: the same fused-kernel wrapper masks the padded plane (jnp.where
with a validity predicate and a neutral fill) before the winner
reduction — padded rows cannot vote, exactly the ffd_step masking the
pallas port carries through the kernel body unchanged."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _scale_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


@jax.jit
def fused_pick(scores):
    padded = jnp.pad(scores, (0, 8))
    fused = pl.pallas_call(
        _scale_kernel,
        out_shape=jax.ShapeDtypeStruct(padded.shape, padded.dtype),
        interpret=True,
    )(padded)
    masked = jnp.where(
        jnp.arange(padded.shape[0]) < scores.shape[0], padded, 1e30
    )
    return fused, jnp.argmin(masked)
