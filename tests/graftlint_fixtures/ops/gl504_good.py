# GL504 good: slot-axis sizing routed through
# parallel.mesh.pad_to_devices — uneven meshes pad with inert slots
# (kind=0 never takes, the parity-tested invariant) instead of
# truncating, and placement goes through the sharding API rather than a
# manual reshape fold. Lint corpus only — never imported.
import jax

from karpenter_core_tpu.parallel import mesh as pmesh


def shard_sanctioned(x_np, max_slots, n_devices):
    mesh = pmesh.slot_mesh(n_devices)
    n = pmesh.pad_to_devices(max_slots, n_devices)
    return n, jax.device_put(x_np, pmesh.axis_sharding(mesh, x_np.ndim, 0))
