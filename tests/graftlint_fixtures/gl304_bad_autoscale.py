"""GL304 bad, autoscaler shape: the scale-down actuator POSTs /drain to
the retiring member while the control loop's _state_lock is held. The
drain is network I/O with an unbounded tail (the member is flushing its
queue); holding the decide lock across it wedges every observer — and the
next step() — behind one slow member. The shipped TierAutoscaler decides
under the lock and actuates OUTSIDE it."""
import threading
from urllib.request import urlopen


class TierAutoscaler:
    def __init__(self, tier):
        self.tier = tier
        self._state_lock = threading.Lock()
        self._down_streak = 0

    def step(self, victim_addr):
        with self._state_lock:
            self._down_streak = 0
            urlopen(  # network I/O while the decide lock is held
                f"http://{victim_addr}/drain", data=b"{}"
            ).read()
