"""GL201 good (twin flavor): canonical iteration in the encoders; free
iteration outside the encode context."""


def encode_scenario(scenario):
    rows = []
    for key, rate in sorted(scenario.rates.items()):
        rows.append({"rate": rate, "seam": key})
    clusters = sorted(set(scenario.clusters_used))
    return {"clusters": clusters, "rates": rows}


def apply_waves(scenario):
    # not an encoding/fingerprint function: arrival order is fine here
    return {w.at: w for w in scenario.waves}
