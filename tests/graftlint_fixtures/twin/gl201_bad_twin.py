"""GL201 bad (twin flavor): unordered iteration inside a scenario/ledger
encoder — arrival order would leak into the committed repro fixture and
the byte-identical-ledger contract."""


def encode_scenario(scenario):
    rows = []
    for key, rate in scenario.rates.items():  # dict arrival order
        rows.append({"rate": rate, "seam": key})
    clusters = [c for c in set(scenario.clusters_used)]  # set order
    return {"clusters": clusters, "rates": rows}


def ledger_fingerprint(samples):
    return tuple(v for v in samples.values)  # set-attribute iteration
