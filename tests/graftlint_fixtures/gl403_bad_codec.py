# GL403 bad: the encoder grew a `priority` wire field (and its decode
# twin, so GL401 is satisfied) but SOLVE_WIRE_VERSION stayed at 2 — the
# sidecar lock (gl403_bad_codec.lock.json) still records the v2 field
# set without `priority`, so an old peer on the SAME version number
# silently drops the field. GL403 requires the bump. Lint corpus only —
# never imported.
import json

SOLVE_WIRE_VERSION = 2


def encode_solve_request(pods, max_slots, tenant, priority):
    header = {
        "version": SOLVE_WIRE_VERSION,
        "pods": pods,
        "max_slots": max_slots,
        "tenant": tenant,
        "priority": priority,  # new wire field, no version bump: GL403
    }
    return json.dumps(header).encode()


def decode_solve_request(data):
    h = json.loads(data.decode())
    if h["version"] != SOLVE_WIRE_VERSION:
        raise ValueError("unsupported solve wire version")
    return {
        "pods": h["pods"],
        "max_slots": h["max_slots"],
        "tenant": h["tenant"],
        "priority": h["priority"],
    }
