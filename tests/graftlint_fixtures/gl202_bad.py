"""GL202 bad: fingerprint hashing json without canonical key order."""
import hashlib
import json


def problem_fingerprint(header):
    return hashlib.sha256(json.dumps(header).encode()).hexdigest()
