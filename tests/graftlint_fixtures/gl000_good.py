"""GL000 good: suppressions carry their why."""


def encode_header(labels):
    # graftlint: disable=GL201 -- output feeds a set, order never observed
    return [k for k, _v in labels.items()]
