"""GL303 good: every write to the shared attribute holds the lock."""
import threading


class Journal:
    def __init__(self):
        self._lock = threading.Lock()
        self.events = []

    def record(self, event):
        with self._lock:
            self.events.append(event)

    def reset(self):
        with self._lock:
            self.events = []

    def serve(self):
        threading.Thread(target=self.record, daemon=True).start()
