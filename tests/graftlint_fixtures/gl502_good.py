# GL502 good: every SlotState field has exactly one SLOT_STATE_SPECS
# entry classifying its slot-axis placement (a dim index to shard, None
# to replicate) — the state definition and the sharding table in
# lockstep. Lint corpus only — never imported.
from typing import NamedTuple

import jax


class SlotState(NamedTuple):
    valmask: jax.Array  # [N, K, V]
    kind: jax.Array  # [N]
    overflow: jax.Array  # [] scalar, rides the carry on every device


SLOT_STATE_SPECS = {
    "valmask": 0,
    "kind": 0,
    "overflow": None,
}
