"""GL402 good: emission sites resolve to REGISTRY definitions."""
from karpenter_core_tpu.metrics.registry import REGISTRY

FIXTURE_EVENTS_TOTAL = REGISTRY.counter(
    "graftlint_fixture_events_total", "fixture-only instrument"
)


def record(n):
    FIXTURE_EVENTS_TOTAL.inc(by=n)
