"""GL304 good, autoscaler shape: decide under the _state_lock, actuate
outside it. The lock guards only the hysteresis bookkeeping; the /drain
POST (unbounded network tail — the member is flushing its queue) happens
after release, so a slow drain never blocks the next observation."""
import threading
from urllib.request import urlopen


class TierAutoscaler:
    def __init__(self, tier):
        self.tier = tier
        self._state_lock = threading.Lock()
        self._down_streak = 0

    def step(self, victim_addr):
        with self._state_lock:
            self._down_streak = 0
            drain = True
        if drain:
            urlopen(
                f"http://{victim_addr}/drain", data=b"{}"
            ).read()
