# GL502 bad: the SlotState definition and SLOT_STATE_SPECS have drifted
# in BOTH directions — `overflow` was added to the state without a
# placement classification, and the spec table still names a `retired`
# field the state no longer carries. Today this is a runtime raise on the
# first multi-device solve; GL502 makes it an edit-time lint error. Lint
# corpus only — never imported.
from typing import NamedTuple

import jax


class SlotState(NamedTuple):
    valmask: jax.Array  # [N, K, V]
    kind: jax.Array  # [N]
    overflow: jax.Array  # [] — missing from SLOT_STATE_SPECS: GL502


SLOT_STATE_SPECS = {
    "valmask": 0,
    "kind": 0,
    "retired": None,  # stale: not a SlotState field any more: GL502
}
