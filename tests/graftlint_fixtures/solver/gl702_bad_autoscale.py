"""GL702 bad, autoscaler shape (migrated from the retired GL302): a
control-loop class whose step path bumps the shared hysteresis streaks
OUTSIDE the owning ``_state_lock`` — the exact class shape
solver/autoscale.py ships, with the discipline broken. The majority of
each streak's write sites hold the lock (that IS the inferred guard);
the two bare read-modify-writes on the poller thread lose updates and
the tier double-scales."""
import threading


class TierAutoscaler:
    def __init__(self, tier, min_members, max_members):
        self.tier = tier
        self.min_members = min_members
        self.max_members = max_members
        self._state_lock = threading.Lock()
        self._up_streak = 0
        self._down_streak = 0
        self._last_scale_at = 0.0

    def step(self, now, pressure):
        if pressure >= 1.0:
            self._up_streak += 1  # two stepping threads read the same value
            with self._state_lock:
                self._down_streak = 0
        else:
            with self._state_lock:
                self._up_streak = 0
            self._down_streak = self._down_streak + 1  # same lost update
        with self._state_lock:
            self._last_scale_at = now

    def reset(self):
        with self._state_lock:
            self._up_streak = 0
            self._down_streak = 0

    def start(self, interval):
        threading.Thread(
            target=self.step, args=(0.0, 0.0), daemon=True
        ).start()
