"""GL702 good: the daemon-cache shape with one discipline — every write
to the counter and the cache holds ``_state_lock``, including the hot
path (whose lock arrives through the ``_record`` helper: the
interprocedural held set proves it, where the old lexical check saw a
bare call)."""
import threading


class SolverDaemonStub:
    def __init__(self):
        self._state_lock = threading.Lock()
        self.solves = 0
        self.plan_cache = {}

    def handle(self, key, plan):
        self._record(key, plan)

    def _record(self, key, plan):
        with self._state_lock:
            self.plan_cache[key] = plan
            self.solves += 1

    def reset(self):
        with self._state_lock:
            self.solves = 0
            self.plan_cache = {}

    def flush_stats(self):
        with self._state_lock:
            self.solves = 0

    def serve(self):
        threading.Thread(target=self.handle, daemon=True).start()
