"""GL701 good: the same gateway/coalescer seam with the cross-object
calls hoisted OUT of the critical sections — each lock is released
before the peer's lock is taken, so the acquired-while-held graph has no
edges between the two and stays acyclic."""
import threading


class TicketCoalescer:
    def __init__(self, gateway=None):
        self._lock = threading.RLock()
        self.waiters = {}
        self.gateway = gateway if gateway is not None else FleetGatewayStub()

    def admit(self, key, ticket):
        with self._lock:
            self.waiters[key] = ticket
        # lock released: the gateway kick happens order-free
        self.gateway.grant(key)

    def flush(self, key):
        with self._lock:
            self.waiters.pop(key, None)


class FleetGatewayStub:
    def __init__(self):
        self._lock = threading.RLock()
        self.granted = {}
        self.coalescer = TicketCoalescer()

    def grant(self, key):
        with self._lock:
            self.granted[key] = True

    def retune(self, key):
        with self._lock:
            stale = [k for k in self.granted if self.granted[k]]
        for k in stale:
            self.coalescer.flush(k)
