"""GL705 good: the critical section only touches memory — the rows are
snapshotted under the lock, then the pacing sleep and the journal write
run with the lock released, so waiters pay memory-speed costs only."""
import threading
import time


class StrikeJournal:
    def __init__(self, path):
        self.path = path
        self._lock = threading.Lock()
        self.rows = []

    def record(self, row):
        with self._lock:
            self.rows.append(row)
            snapshot = list(self.rows)
        time.sleep(0.05)
        with open(self.path, "w") as f:
            f.write("\n".join(snapshot))
