"""GL702 good, fair-queue shape: every read-modify-write on the
gateway's shared state (admission counter, virtual clock, tenant queues)
holds the owning lock — the discipline solver/fleet.py's FleetGateway
ships."""
import threading
from collections import deque


class FairQueueGateway:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = 0
        self._vclock = 0.0
        self._queued = {}

    def submit(self, tenant):
        with self._lock:
            self._queued.setdefault(tenant, deque()).append(object())
            self._pending += 1

    def release(self, tenant, seconds):
        with self._lock:
            self._queued[tenant].popleft()
            self._pending -= 1
            self._vclock = self._vclock + seconds

    def reset_epoch(self):
        with self._lock:
            self._pending = 0
            self._vclock = 0.0

    def credit(self, seconds):
        with self._lock:
            self._vclock = self._vclock + seconds

    def serve(self, tenant):
        threading.Thread(
            target=self.submit, args=(tenant,), daemon=True
        ).start()
        threading.Thread(
            target=self.release, args=(tenant, 0.0), daemon=True
        ).start()
