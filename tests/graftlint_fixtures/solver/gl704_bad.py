"""GL704 bad: three broken wait shapes on one queue. (1) ``wait`` under
an ``if`` instead of a ``while`` — a spurious wakeup or a stolen notify
returns with the queue still empty and ``pop`` raises; (2) ``notify_all``
outside the owning lock — the waiter can read the predicate, decide to
sleep, and miss the notify in the gap; (3) a timed ``Event.wait`` whose
result is discarded — a timeout is indistinguishable from the flag being
set, so the caller proceeds on failure."""
import threading


class WorkQueue:
    def __init__(self):
        self._cv = threading.Condition()
        self._ready = threading.Event()
        self.items = []

    def put(self, item):
        with self._cv:
            self.items.append(item)
            self._cv.notify()

    def get(self):
        with self._cv:
            if not self.items:
                self._cv.wait()  # spurious wakeup -> pop on empty
            return self.items.pop(0)

    def kick(self):
        self._cv.notify_all()  # no lock: the notify can be lost

    def poll(self):
        self._ready.wait(timeout=1.0)  # timeout looks like success
        return self.items
