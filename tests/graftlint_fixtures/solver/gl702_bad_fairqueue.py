"""GL702 bad, fair-queue shape (migrated from the retired GL302): a
gateway class (per-tenant queues, a virtual clock, an admission counter)
whose handler-thread entry points bump shared counters OUTSIDE the
owning lock — the exact class shape solver/fleet.py ships, with the
discipline broken. The locked majority of each counter's write sites
pins the inferred guard; the bare sites are the findings."""
import threading
from collections import deque


class FairQueueGateway:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = 0
        self._vclock = 0.0
        self._queued = {}

    def submit(self, tenant):
        with self._lock:
            self._queued.setdefault(tenant, deque()).append(object())
        self._pending += 1  # two handler threads read the same old value

    def release(self, tenant, seconds):
        with self._lock:
            self._queued[tenant].popleft()
            self._pending -= 1
        self._vclock = self._vclock + seconds  # same lost-update shape

    def reset_epoch(self):
        with self._lock:
            self._pending = 0
            self._vclock = 0.0

    def credit(self, seconds):
        with self._lock:
            self._vclock = self._vclock + seconds

    def serve(self, tenant):
        threading.Thread(
            target=self.submit, args=(tenant,), daemon=True
        ).start()
        threading.Thread(
            target=self.release, args=(tenant, 0.0), daemon=True
        ).start()
