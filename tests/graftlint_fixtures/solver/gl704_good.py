"""GL704 good: the same queue with the wait discipline intact — the
predicate re-check loop around ``wait``, the notify inside the owning
lock, and the timed wait's result branched on."""
import threading


class WorkQueue:
    def __init__(self):
        self._cv = threading.Condition()
        self._ready = threading.Event()
        self.items = []

    def put(self, item):
        with self._cv:
            self.items.append(item)
            self._cv.notify()

    def get(self):
        with self._cv:
            while not self.items:
                self._cv.wait()
            return self.items.pop(0)

    def kick(self):
        with self._cv:
            self._cv.notify_all()

    def poll(self):
        if not self._ready.wait(timeout=1.0):
            raise TimeoutError("queue never became ready")
        return self.items
