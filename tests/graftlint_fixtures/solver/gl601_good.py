"""GOOD: the same decode-to-device-plane path with the priority clamped
through the registered normalizer (utils/disruption.priority_tier) at the
decode net — the int32 store can no longer wrap."""
import numpy as np

from karpenter_core_tpu.utils.disruption import priority_tier


class EvictablePod:
    def __init__(self, uid, priority, cost):
        self.uid = uid
        self.priority = priority
        self.cost = cost


def _decode_sim_node(d):
    return [
        EvictablePod(
            uid=e["uid"],
            priority=priority_tier(int(e["priority"])),
            cost=float(e["cost"]),
        )
        for e in d.get("evictable", ())
    ]


def build_ev_planes(nodes):
    tier = np.full((4, 8), 0, dtype=np.int32)
    for ei, node in enumerate(nodes):
        for j, e in enumerate(node.evictable):
            tier[ei, j] = e.priority
    return tier
