"""GL701 bad: the gateway/coalescer ABBA seam. The coalescer admits a
ticket under ITS lock and kicks the gateway (which takes the gateway
lock inside ``grant``), while the gateway retunes under ITS lock and
flushes the coalescer (which takes the coalescer lock inside ``flush``)
— two threads, opposite orders, classic deadlock. The cycle only exists
interprocedurally: no single function nests both ``with`` blocks."""
import threading


class TicketCoalescer:
    def __init__(self, gateway=None):
        self._lock = threading.RLock()
        self.waiters = {}
        self.gateway = gateway if gateway is not None else FleetGatewayStub()

    def admit(self, key, ticket):
        with self._lock:
            self.waiters[key] = ticket
            self.gateway.grant(key)  # TicketCoalescer._lock -> gateway lock

    def flush(self, key):
        with self._lock:
            self.waiters.pop(key, None)


class FleetGatewayStub:
    def __init__(self):
        self._lock = threading.RLock()
        self.granted = {}
        self.coalescer = TicketCoalescer()

    def grant(self, key):
        with self._lock:
            self.granted[key] = True

    def retune(self, key):
        with self._lock:
            self.coalescer.flush(key)  # gateway lock -> TicketCoalescer._lock
