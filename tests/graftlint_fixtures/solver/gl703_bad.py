"""GL703 bad: the live guarded container escapes the lock. The member
registry's dict is guarded by ``_lock`` at every write site, but the
export path hands the LIVE dict to a publisher thread and the handoff
path aliases it onto a ticket another thread drains — the receiver
iterates/mutates it with no lock while the owner keeps writing
(RuntimeError: dictionary changed size during iteration, or worse,
silently torn reads)."""
import threading


class Ticket:
    def __init__(self):
        self.view = None
        self.done = threading.Event()


class MemberRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self.members = {}

    def add(self, name, meta):
        with self._lock:
            self.members[name] = meta

    def drop(self, name):
        with self._lock:
            self.members.pop(name, None)

    def export(self, publish):
        threading.Thread(
            target=publish, args=(self.members,), daemon=True
        ).start()  # the live dict crosses the thread boundary

    def hand_off(self, ticket):
        with self._lock:
            ticket.view = self.members  # aliases the guarded dict
        ticket.done.set()
