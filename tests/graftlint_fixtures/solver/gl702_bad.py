"""GL702 bad: the PR 5 daemon-cache shape. Every other write to the
solve counter holds ``_state_lock`` (the strict-majority inference), but
the handler-thread hot path bumps it bare — two handler threads read the
same old value and the lost update undercounts solves, exactly the class
of bug the PR 5 truthiness fix was adjacent to. The cache writes go
through a ``_record`` helper whose lock the old per-file lexical check
could not see; the held-set propagation can."""
import threading


class SolverDaemonStub:
    def __init__(self):
        self._state_lock = threading.Lock()
        self.solves = 0
        self.plan_cache = {}

    def handle(self, key, plan):
        self._record(key, plan)
        self.solves += 1  # bare RMW on a handler thread: lost update

    def _record(self, key, plan):
        with self._state_lock:
            self.plan_cache[key] = plan

    def reset(self):
        with self._state_lock:
            self.solves = 0
            self.plan_cache = {}

    def flush_stats(self):
        with self._state_lock:
            self.solves = 0

    def serve(self):
        threading.Thread(target=self.handle, daemon=True).start()
