"""GL705 bad: blocking work inside the critical section — a pacing sleep
and a journal write both sit lexically under the lock, so every thread
queued on it waits out the sleep plus the disk tail (disk-full, NFS
stall) before touching the rows."""
import threading
import time


class StrikeJournal:
    def __init__(self, path):
        self.path = path
        self._lock = threading.Lock()
        self.rows = []

    def record(self, row):
        with self._lock:
            self.rows.append(row)
            time.sleep(0.05)  # pacing delay charged to every waiter
            with open(self.path, "w") as f:
                f.write("\n".join(self.rows))
