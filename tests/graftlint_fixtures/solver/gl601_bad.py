"""BAD: the retro ISSUE 10 shape — a wire-decoded int64 priority flows
into the int32 evictable-tier plane with no normalizer/clip on the path.
The decode net casts with int() (unbounded) and the prep layer stores it
into an int32 array element, which WRAPS on overflow inside the exclusive
device window."""
import numpy as np


class EvictablePod:
    def __init__(self, uid, priority, cost):
        self.uid = uid
        self.priority = priority
        self.cost = cost


def _decode_sim_node(d):
    return [
        EvictablePod(
            uid=e["uid"],
            priority=int(e["priority"]),
            cost=float(e["cost"]),
        )
        for e in d.get("evictable", ())
    ]


def build_ev_planes(nodes):
    tier = np.full((4, 8), 0, dtype=np.int32)
    for ei, node in enumerate(nodes):
        for j, e in enumerate(node.evictable):
            tier[ei, j] = e.priority
    return tier
