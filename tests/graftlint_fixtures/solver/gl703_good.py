"""GL703 good: only SNAPSHOTS cross the thread boundary. The export and
handoff paths copy the guarded dict under the lock and pass the copy —
the receiver owns its snapshot outright and the registry's live dict
never aliases outside the guard."""
import threading


class Ticket:
    def __init__(self):
        self.view = None
        self.done = threading.Event()


class MemberRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self.members = {}

    def add(self, name, meta):
        with self._lock:
            self.members[name] = meta

    def drop(self, name):
        with self._lock:
            self.members.pop(name, None)

    def export(self, publish):
        with self._lock:
            snapshot = dict(self.members)
        threading.Thread(
            target=publish, args=(snapshot,), daemon=True
        ).start()

    def hand_off(self, ticket):
        with self._lock:
            ticket.view = dict(self.members)
        ticket.done.set()
