"""GL702 good, autoscaler shape: every read-modify-write on the control
loop's shared hysteresis state (streaks, cooldown stamps) holds the
owning ``_state_lock`` — the discipline solver/autoscale.py's
TierAutoscaler ships, where the whole decide body sits inside one locked
region."""
import threading


class TierAutoscaler:
    def __init__(self, tier, min_members, max_members):
        self.tier = tier
        self.min_members = min_members
        self.max_members = max_members
        self._state_lock = threading.Lock()
        self._up_streak = 0
        self._down_streak = 0
        self._last_scale_at = 0.0

    def step(self, now, pressure):
        with self._state_lock:
            if pressure >= 1.0:
                self._up_streak += 1
                self._down_streak = 0
            else:
                self._up_streak = 0
                self._down_streak = self._down_streak + 1
            self._last_scale_at = now

    def reset(self):
        with self._state_lock:
            self._up_streak = 0
            self._down_streak = 0

    def start(self, interval):
        threading.Thread(
            target=self.step, args=(0.0, 0.0), daemon=True
        ).start()
