"""GL301 bad: thread lifetime left to the default."""
import threading


def start_worker(fn):
    t = threading.Thread(target=fn)
    t.start()
    return t
