# GL501 good (batched entry): the sanctioned routing for the
# continuous-batching driver — the stacked [B, ...] SlotState is
# re-committed to the slot mesh through parallel.mesh's batched specs
# (batch axis replicated, slot axis sharded) before it reaches the
# batched SlotState jit entry, so the vmapped solve composes with the
# slot-axis pjit path by construction. Lint corpus only — never imported.
import jax
import jax.numpy as jnp
import numpy as np

from karpenter_core_tpu.ops.ffd import SlotState, ffd_solve_batched
from karpenter_core_tpu.parallel import mesh as pmesh


class DeviceScheduler:
    def __init__(self, mesh, n_slots):
        self._mesh = mesh
        self._n_slots = n_slots

    def _make_init_state(self, n_slots, k, v):
        return SlotState(
            valmask=np.ones((n_slots, k, v), dtype=bool),
            kind=np.zeros((n_slots,), dtype=np.int8),
        )

    def solve_batch(self, steps, statics, n_slots, k, v, n_problems):
        trees = [
            self._make_init_state(n_slots, k, v) for _ in range(n_problems)
        ]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)
        state = jax.device_put(
            stacked,
            pmesh.batched_slot_shardings(self._mesh, stacked, self._n_slots),
        )
        return ffd_solve_batched(state, steps, statics)
