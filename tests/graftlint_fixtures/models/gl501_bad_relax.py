# GL501 bad (relaxsolve, ISSUE 13): a DeviceScheduler-shaped relax pass
# hands the scored-fallback comparator (ops/relax.relax_score — a
# SlotState jit entry) state built straight from host numpy: nothing in
# its dataflow ever routed through parallel.mesh placement, so on a
# multi-device scheduler the score dispatch compiles against absent
# shardings and gathers the whole slot axis. Lint corpus only — never
# imported.
import numpy as np

from karpenter_core_tpu.ops.ffd import SlotState
from karpenter_core_tpu.ops.relax import relax_score


class DeviceScheduler:
    def _fake_final_state(self, n_slots):
        # every plane is host numpy: provenance {host}, never placed
        return SlotState(
            kind=np.full((n_slots,), 2, dtype=np.int8),
            template=np.zeros((n_slots,), dtype=np.int32),
            podcount=np.ones((n_slots,), dtype=np.int32),
        )

    def _relax_improve(self, tmpl_price, unplaced_bc, n_slots):
        state = self._fake_final_state(n_slots)
        return relax_score(state, tmpl_price, unplaced_bc)  # GL501
