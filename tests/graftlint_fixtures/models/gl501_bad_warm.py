# GL501 bad (incsolve, ISSUE 16): an incremental-replay-shaped relax
# pass warm-starts from the ledger and then re-scores the replayed
# packing — but builds the scorer's SlotState straight from the ledger's
# host-side record (numpy planes, provenance {host}): nothing routed
# through parallel.mesh placement, so on a multi-device scheduler the
# score dispatch compiles against absent shardings and gathers the whole
# slot axis. The warm vector being placed correctly does not excuse the
# state. Lint corpus only — never imported.
import numpy as np

from karpenter_core_tpu.ops.ffd import SlotState
from karpenter_core_tpu.ops.relax import relax_score


class DeviceScheduler:
    def _state_from_ledger(self, record, n_slots):
        # replayed planes decoded from the PackingLedger entry: host
        # numpy end to end, never placed
        return SlotState(
            kind=np.asarray(record["kind"], dtype=np.int8),
            template=np.asarray(record["template"], dtype=np.int32),
            podcount=np.asarray(record["podcount"], dtype=np.int32),
        )

    def _relax_warm_rescore(self, record, tmpl_price, unplaced_bc,
                            n_slots):
        state = self._state_from_ledger(record, n_slots)
        return relax_score(state, tmpl_price, unplaced_bc)  # GL501
