# GL501 bad (topoaware entry): a DeviceScheduler-shaped driver builds a
# SlotState from host numpy — and a ClassStep carrying the topoaware
# per-slot hop plane (topo_rank) straight from host numpy beside it — and
# hands both to the SlotState jit entry (ops/ffd.ffd_solve) without ever
# routing through parallel.mesh placement (slot_shardings / axis_sharding
# / topo_plane_shardings or an explicit device_put sharding), so on a
# multi-device mesh the level-grouped fill compiles against absent
# shardings and silently degrades to replicated copies.
# Lint corpus only — never imported.
import numpy as np

from karpenter_core_tpu.ops.ffd import ClassStep, SlotState, ffd_solve


class DeviceScheduler:
    def _make_topo_state(self, n_slots, k, v):
        # every plane is host numpy: provenance {host}, never placed
        return SlotState(
            valmask=np.ones((n_slots, k, v), dtype=bool),
            kind=np.zeros((n_slots,), dtype=np.int8),
        )

    def solve(self, statics, n_steps, n_slots, k, v):
        state = self._make_topo_state(n_slots, k, v)
        steps = ClassStep(
            count=np.zeros((n_steps,), dtype=np.int32),
            topo_rank=np.zeros((n_steps, n_slots), dtype=np.int32),
        )
        return ffd_solve(state, steps, statics, level_iters=32)  # GL501
