# GL501 good (topoaware entry): the sanctioned routing for the
# topology-aware solve — the SlotState commits to the slot mesh through
# parallel.mesh placement (slot_shardings) and the per-class hop plane
# (ClassStep.topo_rank, trailing slot axis) routes through
# topo_plane_shardings before the SlotState jit entry consumes them, so
# the level-grouped fill compiles against the real shardings by
# construction. Lint corpus only — never imported.
import jax
import numpy as np

from karpenter_core_tpu.ops.ffd import ClassStep, SlotState, ffd_solve
from karpenter_core_tpu.parallel import mesh as pmesh


class DeviceScheduler:
    def __init__(self, mesh, n_slots):
        self._mesh = mesh
        self._n_slots = n_slots

    def _make_topo_state(self, n_slots, k, v):
        host = SlotState(
            valmask=np.ones((n_slots, k, v), dtype=bool),
            kind=np.zeros((n_slots,), dtype=np.int8),
        )
        return jax.device_put(
            host, pmesh.slot_shardings(self._mesh, host, self._n_slots)
        )

    def solve(self, statics, n_steps, n_slots, k, v):
        state = self._make_topo_state(n_slots, k, v)
        topo_host = np.zeros((n_steps, n_slots), dtype=np.int32)
        topo_rank = jax.device_put(
            topo_host,
            pmesh.topo_plane_shardings(self._mesh, topo_host, self._n_slots),
        )
        steps = ClassStep(
            count=np.zeros((n_steps,), dtype=np.int32),
            topo_rank=topo_rank,
        )
        return ffd_solve(state, steps, statics, level_iters=32)
