# GL501 bad: a DeviceScheduler-shaped solve path hands a SlotState jit
# entry state built straight from host numpy — nothing in its dataflow
# ever routed through parallel.mesh placement (slot_shardings /
# axis_sharding / batch_sharding or an explicit device_put sharding), so
# the SPMD solve compiles against absent shardings and silently degrades
# to replicated copies. Lint corpus only — never imported.
import numpy as np

from karpenter_core_tpu.ops.ffd import SlotState, ffd_solve_donated


class DeviceScheduler:
    def _make_init_state(self, n_slots, k, v):
        # every plane is host numpy: provenance {host}, never placed
        return SlotState(
            valmask=np.ones((n_slots, k, v), dtype=bool),
            kind=np.zeros((n_slots,), dtype=np.int8),
        )

    def solve(self, steps, statics, n_slots, k, v):
        state = self._make_init_state(n_slots, k, v)
        return ffd_solve_donated(state, steps, statics)  # GL501
