# GL501 bad (gangsched entry): a DeviceScheduler-shaped driver builds a
# SlotState straight from host numpy and hands it to the gang-atomic
# SlotState jit entry (ops/gangsched.gang_solve) — nothing in its
# dataflow ever routed through parallel.mesh placement (slot_shardings /
# axis_sharding / gang_plane_shardings or an explicit device_put
# sharding), so on a multi-device mesh the gang-atomic scan compiles
# against absent shardings and silently degrades to replicated copies.
# Lint corpus only — never imported.
import numpy as np

from karpenter_core_tpu.ops.ffd import SlotState
from karpenter_core_tpu.ops.gangsched import gang_solve


class DeviceScheduler:
    def _make_gang_state(self, n_slots, k, v):
        # every plane is host numpy: provenance {host}, never placed
        return SlotState(
            valmask=np.ones((n_slots, k, v), dtype=bool),
            kind=np.zeros((n_slots,), dtype=np.int8),
        )

    def solve(self, steps, statics, gang_of_step, gang_min, n_slots, k, v):
        state = self._make_gang_state(n_slots, k, v)
        return gang_solve(
            state, steps, statics, gang_of_step, gang_min, level_iters=32
        )  # GL501
