# GL501 good (incsolve, ISSUE 16): the production warm-start shape —
# the ledger's prior choice lowers to a [C] warm_template index vector
# that rides the relax assignment planes through relax_plane_shardings
# (replicated: no slot axis), and the state the scorer consumes is the
# FINISHED solve's SlotState, whose planes were placed through the
# sanctioned parallel.mesh routes (_dev_slots -> axis_sharding) before
# the solve dispatch. Warm-starting changes where the contraction
# starts, never where the arrays live. Lint corpus only — never
# imported.
import jax
import numpy as np

from karpenter_core_tpu.ops.ffd import SlotState, ffd_solve_donated
from karpenter_core_tpu.ops.relax import relax_choose, relax_score
from karpenter_core_tpu.parallel import mesh as pmesh


class DeviceScheduler:
    def __init__(self, mesh):
        self._mesh = mesh
        self._relax_warm = None  # {class signature -> nodepool name}

    def _dev_slots(self, a):
        return jax.device_put(a, pmesh.axis_sharding(self._mesh, a.ndim, 0))

    def _make_init_state(self, n_slots):
        return SlotState(
            kind=self._dev_slots(np.zeros((n_slots,), dtype=np.int8)),
            template=self._dev_slots(np.full((n_slots,), -1, np.int32)),
            podcount=self._dev_slots(np.zeros((n_slots,), dtype=np.int32)),
        )

    def _warm_vec(self, classes, pool_to_tmpl, n_classes):
        wvec = np.full((n_classes,), -1, dtype=np.int32)
        for ci, cls in enumerate(classes[:n_classes]):
            si = pool_to_tmpl.get((self._relax_warm or {}).get(cls.signature))
            if si is not None:
                wvec[ci] = si
        return wvec

    def _relax_improve(self, steps, statics, planes, classes,
                       pool_to_tmpl, tmpl_price, unplaced_bc, n_slots):
        wvec = self._warm_vec(classes, pool_to_tmpl, len(classes))
        planes = planes + (wvec,)
        planes = jax.device_put(
            planes, pmesh.relax_plane_shardings(self._mesh, planes)
        )
        nt, ks, _changed = relax_choose(
            *planes, iters=8, num_gangs=0
        )
        init = self._make_init_state(n_slots)
        state, _takes, unplaced = ffd_solve_donated(init, steps, statics)
        return nt, ks, relax_score(state, tmpl_price, unplaced_bc)
