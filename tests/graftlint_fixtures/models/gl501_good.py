# GL501 good: both sanctioned routings. The DeviceScheduler shape places
# every slot-axis plane through a _dev_slots helper that resolves (one
# call away) to parallel.mesh.axis_sharding; the frontier_core shape
# commits the whole state with an explicit two-arg device_put placement.
# Lint corpus only — never imported.
import jax
import numpy as np

from karpenter_core_tpu.ops.ffd import SlotState, ffd_solve_donated
from karpenter_core_tpu.parallel import mesh as pmesh


class DeviceScheduler:
    def __init__(self, mesh):
        self._mesh = mesh

    def _dev_slots(self, a):
        return jax.device_put(a, pmesh.axis_sharding(self._mesh, a.ndim, 0))

    def _make_init_state(self, n_slots, k, v):
        return SlotState(
            valmask=self._dev_slots(np.ones((n_slots, k, v), dtype=bool)),
            kind=self._dev_slots(np.zeros((n_slots,), dtype=np.int8)),
        )

    def solve(self, steps, statics, n_slots, k, v):
        state = self._make_init_state(n_slots, k, v)
        return ffd_solve_donated(state, steps, statics)


def frontier_core(init_state_np, steps, statics, mesh):
    repl = pmesh.replicated(mesh)
    state = jax.device_put(
        init_state_np, jax.tree.map(lambda _: repl, init_state_np)
    )
    return ffd_solve_donated(state, steps, statics)
