# GL501 bad (batched entry): a DeviceScheduler-shaped batch driver builds
# a problem-stacked [B, ...] SlotState straight from host numpy and hands
# it to the batched SlotState jit entry — nothing in its dataflow ever
# routed through parallel.mesh placement (batched_slot_shardings /
# batched_step_shardings or an explicit device_put sharding), so on a
# multi-device mesh the vmapped solve compiles against absent shardings
# and the batch axis silently stops composing with the slot-axis pjit.
# Lint corpus only — never imported.
import numpy as np

from karpenter_core_tpu.ops.ffd import SlotState, ffd_solve_batched


class DeviceScheduler:
    def _make_stacked_state(self, n_problems, n_slots, k, v):
        # every plane is host numpy: provenance {host}, never placed
        return SlotState(
            valmask=np.ones((n_problems, n_slots, k, v), dtype=bool),
            kind=np.zeros((n_problems, n_slots), dtype=np.int8),
        )

    def solve_batch(self, steps, statics, n_slots, k, v, n_problems):
        state = self._make_stacked_state(n_problems, n_slots, k, v)
        return ffd_solve_batched(state, steps, statics)  # GL501
