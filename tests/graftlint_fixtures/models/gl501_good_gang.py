# GL501 good (gangsched entry): the sanctioned routing for the
# gang-atomic solve — the SlotState is committed to the slot mesh through
# parallel.mesh placement (slot_shardings) and the evictable-capacity
# planes route through gang_plane_shardings before either gangsched jit
# entry consumes them, so the SPMD solve compiles against the real
# shardings by construction. Lint corpus only — never imported.
import jax
import numpy as np

from karpenter_core_tpu.ops.ffd import SlotState
from karpenter_core_tpu.ops.gangsched import gang_solve, preempt_pass
from karpenter_core_tpu.parallel import mesh as pmesh


class DeviceScheduler:
    def __init__(self, mesh, n_slots):
        self._mesh = mesh
        self._n_slots = n_slots

    def _make_gang_state(self, n_slots, k, v):
        host = SlotState(
            valmask=np.ones((n_slots, k, v), dtype=bool),
            kind=np.zeros((n_slots,), dtype=np.int8),
        )
        return jax.device_put(
            host, pmesh.slot_shardings(self._mesh, host, self._n_slots)
        )

    def solve(self, steps, statics, gang_of_step, gang_min, n_slots, k, v):
        state = self._make_gang_state(n_slots, k, v)
        return gang_solve(
            state, steps, statics, gang_of_step, gang_min, level_iters=32
        )

    def preempt(self, steps, statics, tiers, gangs, unplaced, ev, n, k, v):
        state = self._make_gang_state(n, k, v)
        planes = jax.device_put(
            ev, pmesh.gang_plane_shardings(self._mesh, ev, self._n_slots)
        )
        return preempt_pass(
            state, steps, statics, tiers, gangs, unplaced, planes,
            node_rounds=8,
        )
