"""BAD: the eviction-cost ±9 regression shape — per-term clamps whose sum
(1 + [-1,1] + [-9,9] = [-9,11]) exceeds the outer [-10,10] clamp, so every
cost past the bound collapses onto 10.0 and the lower-order deletion-cost
tiebreak is erased among critical pods."""


def eviction_cost(deletion_cost, priority):
    cost = 1.0
    cost += min(max(float(deletion_cost) / 2.0 ** 27, -1.0), 1.0)
    cost += min(max(float(priority) / 2.0 ** 25, -9.0), 9.0)
    return min(max(cost, -10.0), 10.0)
