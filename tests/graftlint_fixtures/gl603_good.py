"""GOOD: per-term clamps sized so the sum (1 + [-1,1] + [-8,8] = [-8,10])
stays inside the outer [-10,10] contract — the total clamp is a backstop
the interior never exceeds, and every tiebreak term stays live."""


def eviction_cost(deletion_cost, priority):
    cost = 1.0
    cost += min(max(float(deletion_cost) / 2.0 ** 27, -1.0), 1.0)
    cost += min(max(float(priority) / 2.0 ** 25, -8.0), 8.0)
    return min(max(cost, -10.0), 10.0)
