"""GL201 good: canonical iteration, or code outside the encode context."""


def encode_header(labels, tags):
    names = [k for k, _v in sorted(labels.items())]
    extras = list(enumerate(sorted(set(tags))))
    return names, extras


def apply_defaults(labels):
    # not an encoding/fingerprint function: free to iterate naturally
    return {k: v or "none" for k, v in labels.items()}
