"""GL202 good: sort_keys=True, or json.dumps outside fingerprint code."""
import hashlib
import json


def problem_fingerprint(header):
    return hashlib.sha256(
        json.dumps(header, sort_keys=True).encode()
    ).hexdigest()


def render_debug(header):
    return json.dumps(header)  # presentation, not identity
