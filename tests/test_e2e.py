"""End-to-end: pending pods → scheduler → NodeClaims → kwok nodes → bound
pods, plus teardown. The KubeStore plays envtest's apiserver role and the
Operator drives every controller synchronously
(reference test strategy: SURVEY.md §4; pkg/test/expectations ExpectProvisioned).
"""
import pytest

from tests.helpers import GIB, make_nodepool, make_pod

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.nodeclaim import NodeClaim
from karpenter_core_tpu.api.objects import (
    DaemonSet,
    Node,
    NodeSelectorRequirement,
    ObjectMeta,
    OwnerReference,
    Pod,
)
from karpenter_core_tpu.cloudprovider.kwok import KwokCloudProvider, build_catalog
from karpenter_core_tpu.kube.store import KubeStore
from karpenter_core_tpu.operator import Operator, Options
from karpenter_core_tpu.utils.clock import FakeClock

CATALOG = build_catalog(cpu_grid=[1, 2, 4, 8, 16], mem_factors=[2, 4])


def new_operator(solver: str = "greedy", catalog=None):
    clock = FakeClock()
    kube = KubeStore(clock)
    provider = KwokCloudProvider(kube, catalog or CATALOG)
    return Operator(
        kube=kube,
        cloud_provider=provider,
        clock=clock,
        options=Options(solver=solver),
    )


def replicated(pod: Pod) -> Pod:
    """Mark the pod as owned so eviction returns it to Pending."""
    pod.metadata.owner_references.append(
        OwnerReference(kind="ReplicaSet", name="rs", uid="rs-uid")
    )
    return pod


class TestProvisioningE2E:
    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    def test_pending_pods_get_nodes_and_bind(self, solver):
        op = new_operator(solver)
        op.kube.create(make_nodepool())
        for i in range(20):
            op.kube.create(make_pod(cpu=1.0, name=f"p{i}"))
        op.run_until_idle()

        pods = op.kube.list_pods()
        assert all(p.node_name for p in pods), [
            p.name for p in pods if not p.node_name
        ]
        nodes = op.kube.list_nodes()
        assert nodes, "no nodes materialized"
        claims = op.kube.list_nodeclaims()
        assert all(c.is_launched() and c.is_registered() and c.is_initialized()
                   for c in claims)
        # every node carries the nodepool label and lost the unregistered taint
        for n in nodes:
            assert n.labels[L.NODEPOOL_LABEL_KEY] == "default"
            assert not any(t.key == L.UNREGISTERED_TAINT_KEY for t in n.taints)
            assert n.labels.get(L.NODE_REGISTERED_LABEL_KEY) == "true"

    def test_no_nodepool_leaves_pods_pending(self):
        op = new_operator()
        op.kube.create(make_pod(cpu=1.0, name="stuck"))
        op.run_until_idle()
        assert not op.kube.list_nodes()
        assert not op.kube.get(Pod, "stuck").node_name

    def test_second_batch_reuses_capacity(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="first"))
        op.run_until_idle()
        n_nodes = len(op.kube.list_nodes())
        # a small pod fits in the headroom of the existing node
        op.kube.create(make_pod(cpu=0.1, name="second"))
        op.run_until_idle()
        assert len(op.kube.list_nodes()) == n_nodes
        assert op.kube.get(Pod, "second").node_name

    def test_zone_restricted_pool(self):
        op = new_operator()
        op.kube.create(
            make_nodepool(
                requirements=[
                    NodeSelectorRequirement(
                        L.LABEL_TOPOLOGY_ZONE, "In", ("zone-b",)
                    )
                ]
            )
        )
        op.kube.create(make_pod(cpu=1.0))
        op.run_until_idle()
        (node,) = op.kube.list_nodes()
        assert node.labels[L.LABEL_TOPOLOGY_ZONE] == "zone-b"

    def test_daemonset_overhead_reserved(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        ds_pod = make_pod(cpu=0.5, name="ds-template")
        ds_pod.is_daemonset = True
        op.kube.create(DaemonSet(metadata=ObjectMeta(name="ds"),
                                 pod_template=ds_pod))
        op.kube.create(make_pod(cpu=1.0, name="app"))
        op.run_until_idle()
        (claim,) = op.kube.list_nodeclaims()
        # requested resources account for app pod + daemon overhead
        assert claim.spec.resources_requests.get("cpu", 0) >= 1.5


class TestNodePoolLimits:
    def test_limits_block_overprovisioning(self):
        op = new_operator()
        op.kube.create(make_nodepool(limits={"cpu": 2.0}))
        for i in range(40):
            op.kube.create(make_pod(cpu=1.0, name=f"p{i}"))
        op.run_until_idle()
        total_cpu = sum(
            n.status.capacity.get("cpu", 0.0) for n in op.kube.list_nodes()
        )
        assert total_cpu <= 2.0 + 16.0  # at most one claim past the limit
        assert any(not p.node_name for p in op.kube.list_pods())


class TestTerminationE2E:
    def test_node_delete_drains_and_reprovisions(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        for i in range(3):
            op.kube.create(replicated(make_pod(cpu=1.0, name=f"p{i}")))
        op.run_until_idle()
        node = op.kube.list_nodes()[0]
        victims = {p.name for p in op.cluster.pods_on_node(node.name)}
        assert victims

        op.kube.delete(node)
        op.run_until_idle()

        # node gone, pods rescheduled somewhere else
        assert node.name not in [n.name for n in op.kube.list_nodes()]
        for name in victims:
            p = op.kube.get(Pod, name)
            assert p.node_name and p.node_name != node.name

    def test_claim_delete_tears_down_node(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle()
        (claim,) = op.kube.list_nodeclaims()
        node_name = claim.status.node_name
        node = op.kube.get(Node, node_name)
        # claim deletion drives instance deletion; node object removal flows
        # through the termination finalizer
        op.kube.delete(claim)
        op.kube.delete(node)
        op.run_until_idle()
        assert op.kube.get(NodeClaim, claim.name) is None
        assert op.kube.get(Node, node_name) is None


class TestScaleSmoke:
    def test_500_pods_greedy(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        for i in range(500):
            op.kube.create(make_pod(cpu=0.5 + (i % 4) * 0.5, name=f"p{i}"))
        op.run_until_idle(max_iters=20)
        pods = op.kube.list_pods()
        assert all(p.node_name for p in pods)
        # packing sanity: shouldn't be one node per pod
        assert len(op.kube.list_nodes()) < 120
