"""Property tests: device feasibility kernels vs the host algebra oracle.

Random Requirements batches are encoded over a closed-world vocab and run
through ops/masks.compatible; every pair must agree with
Requirements.compatible / .intersects on the host.
"""
import random

import numpy as np
import pytest

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.objects import Pod, Taint, Toleration
from karpenter_core_tpu.ops import masks as dev
from karpenter_core_tpu.scheduling import Requirement, Requirements
from karpenter_core_tpu.scheduling.requirements import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
)
from karpenter_core_tpu.solver.vocab import Vocab, encode_requirements_batch

KEYS = [
    apilabels.LABEL_TOPOLOGY_ZONE,
    apilabels.LABEL_ARCH,
    apilabels.CAPACITY_TYPE_LABEL_KEY,
    "mycompany.io/team",
    "mycompany.io/tier",
    "size",
]
VALUES = {
    apilabels.LABEL_TOPOLOGY_ZONE: ["zone-a", "zone-b", "zone-c", "zone-d"],
    apilabels.LABEL_ARCH: ["amd64", "arm64"],
    apilabels.CAPACITY_TYPE_LABEL_KEY: ["spot", "on-demand"],
    "mycompany.io/team": ["infra", "web", "ml"],
    "mycompany.io/tier": ["1", "2", "3"],
    "size": ["1", "2", "4", "8", "16", "32"],
}


def random_requirement(rng: random.Random, key: str) -> Requirement:
    domain = VALUES[key]
    op = rng.choice(["In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"])
    if op in ("Gt", "Lt"):
        if not all(v.isdigit() for v in domain):
            op = "In"
        else:
            return Requirement.new(key, op, [rng.choice(domain)])
    k = rng.randint(1, len(domain))
    return Requirement.new(key, op, rng.sample(domain, k))


def random_requirements(rng: random.Random, min_keys=0, max_keys=4) -> Requirements:
    n = rng.randint(min_keys, max_keys)
    return Requirements(
        random_requirement(rng, key) for key in rng.sample(KEYS, n)
    )


@pytest.mark.parametrize("seed", range(5))
def test_compatible_matches_host(seed):
    rng = random.Random(seed)
    incoming = [random_requirements(rng) for _ in range(24)]
    receivers = [random_requirements(rng) for _ in range(24)]

    vocab = Vocab()
    for r in incoming + receivers:
        vocab.observe_requirements(r)
    # receivers' defined-value universe must include domains the pods
    # reference; also observe full domains (the provisioner's domain universe,
    # provisioner.go:251-283)
    for key, values in VALUES.items():
        for v in values:
            vocab.value_id(key, v)
    frozen = vocab.finalize()
    well_known = np.array(
        [k in apilabels.WELL_KNOWN_LABELS for k in frozen.key_names], dtype=bool
    )

    inc = encode_requirements_batch(frozen, incoming)
    rec = encode_requirements_batch(frozen, receivers)

    got = np.asarray(
        dev.compatible(
            inc.mask, inc.defines, inc.concrete, inc.negative, inc.gt, inc.lt,
            rec.mask, rec.defines, rec.concrete, rec.negative, rec.gt, rec.lt,
            well_known,
        )
    )
    got_intersects = np.asarray(
        dev.intersects(
            inc.mask, inc.defines, inc.concrete, inc.negative, inc.gt, inc.lt,
            rec.mask, rec.defines, rec.concrete, rec.negative, rec.gt, rec.lt,
        )
    )

    for i, pod_reqs in enumerate(incoming):
        for j, node_reqs in enumerate(receivers):
            want = node_reqs.is_compatible(
                pod_reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
            )
            assert got[i, j] == want, (
                f"compat mismatch incoming=({pod_reqs!r}) receiver=({node_reqs!r}): "
                f"device={got[i, j]} host={want}"
            )
            want_int = not node_reqs.intersects(pod_reqs)
            assert got_intersects[i, j] == want_int, (
                f"intersects mismatch incoming=({pod_reqs!r}) "
                f"receiver=({node_reqs!r}): device={got_intersects[i, j]} host={want_int}"
            )


def test_tolerates_matches_host():
    taints = [
        Taint(key="a", value="1", effect="NoSchedule"),
        Taint(key="b", value="", effect="NoExecute"),
        Taint(key="c", value="x", effect="NoSchedule"),
    ]
    pods = [
        Pod(),
        Pod(tolerations=[Toleration(operator="Exists")]),
        Pod(tolerations=[Toleration(key="a", operator="Equal", value="1")]),
        Pod(
            tolerations=[
                Toleration(key="a", operator="Exists"),
                Toleration(key="b", operator="Exists", effect="NoExecute"),
            ]
        ),
    ]
    entities = [[], [taints[0]], [taints[0], taints[1]], taints]

    TA = len(taints)
    pod_tol = np.array(
        [[any(t.tolerates(ta) for t in p.tolerations) for ta in taints] for p in pods]
    )
    ent = np.array([[ta in group for ta in taints] for group in entities])

    got = np.asarray(dev.tolerates(ent, pod_tol))
    from karpenter_core_tpu.scheduling.taints import Taints

    for i, p in enumerate(pods):
        for j, group in enumerate(entities):
            want = not Taints(group).tolerates(p)
            assert got[i, j] == want, f"pod {i} vs taints {j}"


def test_fits_matches_host():
    from karpenter_core_tpu.utils import resources as res

    rng = random.Random(0)
    reqs = np.array(
        [[rng.choice([0, 0.5, 1, 2, 4]), rng.choice([0, 1, 2, 8])] for _ in range(16)],
        dtype=np.float32,
    )
    alloc = np.array(
        [[rng.choice([0.5, 1, 2, 4]), rng.choice([1, 2, 8, -1])] for _ in range(12)],
        dtype=np.float32,
    )
    got = np.asarray(dev.fits(reqs, alloc))
    for i in range(16):
        for j in range(12):
            want = res.fits(
                {"cpu": float(reqs[i, 0]), "memory": float(reqs[i, 1])},
                {"cpu": float(alloc[j, 0]), "memory": float(alloc[j, 1])},
            )
            assert got[i, j] == want
