"""Delta wire + fleet routing (segmentstore, ISSUE 14).

Four batteries:

* **segment units** — SegmentStore TTL/LRU/byte-cap semantics on a fake
  clock, SentCache instance rebinding, and split/assemble exactness (the
  manifest path must reconstruct the full header VALUE-FOR-VALUE, which
  is what makes its solves wire-identical to full-path ones);
* **manifest parity** — the full fuzz corpus (all 14 seeds) plus
  topology-context, gang, and relax-mode problems solved through BOTH
  wire forms on fresh daemons, asserting the RESULT wire is identical
  (modulo the timing field) — the delta wire may never change a packing;
* **miss protocol** — a respawned/evicting sidecar answers the typed 409
  miss, the client repairs with ONE upload round (breaker untouched, no
  greedy fallback), and a store that cannot hold segments at all degrades
  to the FULL wire, still never to greedy;
* **fleet routing** — rendezvous affinity stability under member churn,
  spill-over under forced drain, degraded routing around an open breaker,
  the kill/respawn regression (a fleet member restart costs one re-upload,
  not a greedy fallback), and the two-operators-x-two-sidecars e2e.
"""
import copy
import json
import time

import pytest

from tests.helpers import make_nodepool, make_pod

from karpenter_core_tpu.metrics import wiring as m
from karpenter_core_tpu.solver import codec, remote, segments, service


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# SegmentStore / SentCache units
# ---------------------------------------------------------------------------


class TestSegmentStore:
    def _store(self, **kw):
        clock = FakeClock()
        kw.setdefault("ttl", 60.0)
        return segments.SegmentStore(time_fn=clock.now, **kw), clock

    def test_put_get_roundtrip_and_contains(self):
        store, _ = self._store()
        store.put("d1", b"abc")
        assert store.get("d1") == b"abc"
        assert "d1" in store and "d2" not in store
        assert store.total_bytes() == 3 and len(store) == 1

    def test_ttl_expiry_is_idle_based(self):
        store, clock = self._store(ttl=60.0)
        store.put("d1", b"abc")
        clock.advance(50)
        assert store.get("d1") == b"abc"  # reference refreshes the TTL
        clock.advance(50)
        assert store.get("d1") == b"abc"  # still warm: 50 < 60 since touch
        clock.advance(61)
        assert store.get("d1") is None  # idle past the TTL: expired
        assert store.stats()["evictions"].get("ttl") == 1

    def test_entry_cap_evicts_lru(self):
        store, _ = self._store(max_entries=2)
        store.put("a", b"1")
        store.put("b", b"2")
        assert store.get("a") == b"1"  # touch: b becomes the LRU
        store.put("c", b"3")
        assert store.get("b") is None and store.get("a") == b"1"
        assert store.stats()["evictions"].get("entries") == 1

    def test_byte_cap_is_strict(self):
        store, _ = self._store(max_bytes=10)
        store.put("a", b"x" * 6)
        store.put("b", b"y" * 6)  # 12 > 10: a evicts
        assert store.get("a") is None and store.get("b") is not None
        # even a single oversized segment may not pin more than the
        # budget — it serves (put succeeds) but does not stay resident
        store.put("big", b"z" * 64)
        assert store.get("big") is None
        assert store.stats()["evictions"].get("bytes", 0) >= 2

    def test_replacing_same_digest_does_not_double_count(self):
        store, _ = self._store()
        store.put("a", b"x" * 8)
        store.put("a", b"x" * 8)
        assert store.total_bytes() == 8

    def test_rejects_bad_bounds(self):
        with pytest.raises(ValueError):
            segments.SegmentStore(max_entries=0)
        with pytest.raises(ValueError):
            segments.SegmentStore(ttl=0)


class TestSentCache:
    def test_mark_known_and_instance_rebind_clears(self):
        sc = segments.SentCache()
        sc.rebind("inst-1")
        sc.mark(["d1", "d2"])
        assert sc.known("d1") and sc.known("d2")
        assert not sc.rebind("inst-1")  # same instance: no clear
        assert sc.known("d1")
        assert sc.rebind("inst-2")  # respawn: ledger resets
        assert not sc.known("d1") and len(sc) == 0

    def test_forget_drops_named_digests_only(self):
        sc = segments.SentCache()
        sc.mark(["d1", "d2", "d3"])
        sc.forget(["d2", "zzz"])
        assert sc.known("d1") and not sc.known("d2") and sc.known("d3")

    def test_digest_cap_is_lru(self):
        sc = segments.SentCache(max_digests=2)
        sc.mark(["a", "b"])
        sc.mark(["a"])  # touch
        sc.mark(["c"])
        assert sc.known("a") and sc.known("c") and not sc.known("b")


# ---------------------------------------------------------------------------
# split / assemble exactness + fingerprint derivability
# ---------------------------------------------------------------------------


def _sample_problem():
    from tests.test_codec_roundtrip import sample_problem

    return sample_problem()


def test_split_assemble_reconstructs_header_exactly():
    header = codec._encode_solve_header(**_sample_problem())
    plan = segments.split_solve_header(header)
    back = segments.assemble_solve_header(
        plan.listing, plan.inline, plan.pod_batch, plan.pod_member,
        plan.segments.get,
    )
    # canonical-bytes equality = value-for-value reconstruction (the
    # original header is JSON-pure by construction: it IS what the full
    # wire ships)
    assert segments.canonical_bytes(back) == segments.canonical_bytes(header)


def test_fingerprint_matches_across_wire_forms_and_derives_from_digests():
    problem = _sample_problem()
    header = codec._encode_solve_header(**problem)
    plan = segments.split_solve_header(header)
    full = codec.decode_solve_request(codec.encode_solve_request(**problem))
    assert plan.fingerprint == full["fingerprint"]
    # derivable from the digest listing alone — no content needed
    assert plan.fingerprint == segments.fingerprint_of_parts(
        plan.listing, plan.inline
    )
    store = segments.SegmentStore()
    man = codec.decode_solve_request(
        codec.encode_manifest_request(plan), segment_store=store
    )
    assert man["fingerprint"] == full["fingerprint"]
    assert man["wire_kind"] == "manifest" and full["wire_kind"] == "full"
    assert man["bucket"] == full["bucket"]


def test_fingerprint_excludes_pod_half_like_v4():
    base = _sample_problem()
    header = codec._encode_solve_header(**base)
    fp = segments.split_solve_header(header).fingerprint

    churned = dict(base)
    churned["pods"] = [make_pod(cpu=2.0, name="other") for _ in range(7)]
    churned["tenant"] = "tenant-b"
    churned["solver_mode"] = "ffd"
    h2 = codec._encode_solve_header(**churned)
    assert segments.split_solve_header(h2).fingerprint == fp

    recat = dict(base)
    recat["max_slots"] = 64
    h3 = codec._encode_solve_header(**recat)
    assert segments.split_solve_header(h3).fingerprint != fp


def test_node_churn_reships_a_small_fraction_of_segments():
    """The delta property at the unit level: replacing ~1% of a few
    hundred existing nodes dirties only their hash buckets — the changed
    segments' bytes are a small fraction of the total."""
    from tests.test_codec_roundtrip import sample_sim_node

    pools = [make_nodepool()]
    from karpenter_core_tpu.cloudprovider.kwok import build_catalog

    its = {"default": list(build_catalog(cpu_grid=[1, 2], mem_factors=[2]))}
    nodes = [sample_sim_node(f"node-{i:04d}") for i in range(300)]
    pods = [make_pod(cpu=0.5, name="p0")]
    h1 = codec._encode_solve_header(pools, its, nodes, [], pods)
    plan1 = segments.split_solve_header(h1)

    churned = list(nodes)
    for i in (7, 131, 288):  # ~1% replaced with fresh-named nodes
        churned[i] = sample_sim_node(f"node-new-{i}")
    h2 = codec._encode_solve_header(pools, its, churned, [], pods)
    plan2 = segments.split_solve_header(h2)

    changed = [d for d in plan2.segments if d not in plan1.segments]
    total = plan2.raw_bytes()
    shipped = plan2.raw_bytes(changed)
    assert shipped < 0.15 * total, (shipped, total)
    # the stable kinds share digests outright
    assert plan2.catalog_digest == plan1.catalog_digest


def test_request_digest_stable_across_upload_forms():
    header = codec._encode_solve_header(**_sample_problem())
    plan = segments.split_solve_header(header)
    with_uploads = codec.encode_manifest_request(plan)
    pure = codec.encode_manifest_request(plan, include=[])
    assert (
        codec.request_digest(with_uploads)
        == codec.request_digest(pure)
        == plan.core_digest
    )
    full = codec.encode_solve_request(**_sample_problem())
    import hashlib

    assert codec.request_digest(full) == hashlib.sha256(full).hexdigest()


def test_decode_attaches_problem_scale_approx_bytes():
    """The scheduler cache's byte-bound weight proxy must track the
    PROBLEM's scale on both wire forms: a steady-state manifest body is a
    few hundred bytes, and weighing cached DeviceSchedulers by it would
    let N delta-wire tenants pin N full schedulers past --cache-mib."""
    problem = _sample_problem()
    full_body = codec.encode_solve_request(**problem)
    full = codec.decode_solve_request(full_body)
    assert full["approx_bytes"] == len(full_body)
    plan = segments.split_solve_header(
        codec._encode_solve_header(**problem)
    )
    man = codec.decode_solve_request(
        codec.encode_manifest_request(plan),
        segment_store=segments.SegmentStore(),
    )
    assert man["approx_bytes"] == plan.raw_bytes()
    pure_manifest = codec.encode_manifest_request(plan, include=[])
    assert man["approx_bytes"] > len(pure_manifest)


def test_manifest_rejects_tampered_upload_and_bad_shapes():
    header = codec._encode_solve_header(**_sample_problem())
    plan = segments.split_solve_header(header)
    dg = next(iter(plan.segments))
    evil = segments.SegmentPlan(
        plan.listing,
        {**plan.segments, dg: plan.segments[dg] + b" "},
        plan.inline, plan.pod_batch, plan.pod_member, plan.catalog_digest,
    )
    body = codec.encode_manifest_request(evil)
    with pytest.raises(ValueError, match="does not hash"):
        codec.decode_solve_request(
            body, segment_store=segments.SegmentStore()
        )
    # a manifest without a configured store is a loud error, not a KeyError
    with pytest.raises(ValueError, match="segment store"):
        codec.decode_solve_request(codec.encode_manifest_request(plan))
    # malformed listing rows are decode-net ValueErrors
    with pytest.raises(ValueError):
        segments.check_manifest_parts([["nodes"]], {})
    with pytest.raises(ValueError):
        segments.check_manifest_parts([["alien-kind", "d" * 64]], {})


# ---------------------------------------------------------------------------
# manifest-path vs full-path result-wire parity (the acceptance battery)
# ---------------------------------------------------------------------------


def _result_view(out: bytes) -> dict:
    """The result wire minus its timing field — 'wire-identical results'
    means identical placements/claims/evictions, not identical clocks."""
    h = codec._json_header(out)
    h.pop("solve_seconds", None)
    return h


def _assert_both_forms_identical(pools, its, existing, ds, pods, **kw):
    full_body = codec.encode_solve_request(
        pools, its, existing, ds, pods, **kw
    )
    header = codec._encode_solve_header(
        pools, its, existing, ds, pods, **kw
    )
    plan = segments.split_solve_header(header)
    out_full, _ = service.SolverDaemon().solve(full_body)
    out_man, _ = service.SolverDaemon().solve(
        codec.encode_manifest_request(plan)
    )
    assert _result_view(out_full) == _result_view(out_man)
    return out_man


@pytest.mark.parametrize("seed", range(14))
def test_manifest_parity_all_fuzz_seeds(seed):
    from tests.test_fuzz_parity import fuzz_scenario

    pods, existing, pools, its = fuzz_scenario(seed)
    _assert_both_forms_identical(
        pools, its, existing, [], pods, max_slots=128
    )


def test_manifest_parity_with_topology_context():
    problem = _sample_problem()
    problem["pods"] = [make_pod(cpu=0.5, name=f"tp-{i}") for i in range(12)]
    out = _assert_both_forms_identical(
        problem["nodepools"], problem["instance_types"],
        problem["existing_nodes"], problem["daemonset_pods"],
        problem["pods"], topology=problem["topology"],
        max_slots=problem["max_slots"],
        unavailable_offerings=problem["unavailable_offerings"],
    )
    assert codec.decode_solve_results(out)["claims"]


def test_manifest_parity_gang_mode():
    from karpenter_core_tpu.solver import gangs

    pools = [make_nodepool()]
    from karpenter_core_tpu.cloudprovider.kwok import build_catalog

    its = {"default": list(build_catalog(cpu_grid=[2, 4], mem_factors=[2]))}
    pods = []
    for i in range(8):
        p = make_pod(cpu=0.5, name=f"g-{i}")
        p.metadata.annotations[gangs.GANG_ANNOTATION] = "gang-a"
        p.metadata.annotations[gangs.GANG_MIN_SIZE_ANNOTATION] = "8"
        pods.append(p)
    pods += [make_pod(cpu=0.5, name=f"plain-{i}") for i in range(4)]
    out = _assert_both_forms_identical(pools, its, [], [], pods)
    res = codec.decode_solve_results(out)
    placed = {u for c in res["claims"] for u in c["pod_uids"]}
    gang_uids = {p.uid for p in pods[:8]}
    # atomicity holds identically on both forms: all-or-nothing
    assert gang_uids <= placed or not (gang_uids & placed)


def test_manifest_parity_relax_mode():
    from tests.test_relaxsolve import two_pool_world

    pools, its = two_pool_world()
    pods = [make_pod(cpu=0.5, name=f"r-{i}") for i in range(24)]
    _assert_both_forms_identical(
        pools, its, [], [], pods, solver_mode="relax"
    )


# ---------------------------------------------------------------------------
# the miss / re-upload protocol
# ---------------------------------------------------------------------------


def _world(n_pods=12):
    from karpenter_core_tpu.cloudprovider.kwok import build_catalog

    pools = [make_nodepool()]
    its = {"default": list(build_catalog(cpu_grid=[1, 2, 4], mem_factors=[2]))}
    pods = [make_pod(cpu=0.5, name=f"p-{i}") for i in range(n_pods)]
    return pools, its, pods


def _served(daemon=None):
    srv = service.serve(0, daemon=daemon)
    return srv, f"127.0.0.1:{srv.server_address[1]}"


class TestMissProtocol:
    def test_warm_resolve_ships_manifest_only(self):
        pools, its, pods = _world()
        srv, addr = _served()
        try:
            client = remote.SolverClient(addr, timeout=120)
            rs = remote.RemoteScheduler(client, pools, its)
            assert rs.solve(pods).all_pods_scheduled()
            before = dict(m.SOLVER_SEGMENT_WIRE_BYTES.values)
            assert rs.solve(pods).all_pods_scheduled()
            after = dict(m.SOLVER_SEGMENT_WIRE_BYTES.values)
            key_seg = (("kind", "segment"),)
            key_man = (("kind", "manifest"),)
            assert after.get(key_seg, 0) == before.get(key_seg, 0), (
                "warm re-solve re-uploaded segments"
            )
            assert after.get(key_man, 0) > before.get(key_man, 0)
        finally:
            srv.shutdown()
            srv.server_close()

    def test_respawn_costs_one_reupload_not_a_fallback(self):
        """The satellite bugfix contract: a sidecar restart (fresh store,
        fresh instance id) surfaces as ONE typed miss + re-upload — the
        breaker is never charged and the solve never degrades to greedy."""
        pools, its, pods = _world()
        srv, addr = _served()
        try:
            client = remote.SolverClient(addr, timeout=120)
            rs = remote.RemoteScheduler(client, pools, its)
            assert rs.solve(pods).all_pods_scheduled()
            # "respawn": swap in a fresh store + instance id in place
            d = srv.daemon_
            d.segment_store = segments.SegmentStore()
            d.instance = "respawned-0001"
            fallbacks = m.SOLVER_RPC_FALLBACKS.value({"endpoint": "solve"})
            before = dict(m.SOLVER_SEGMENT_WIRE_BYTES.values)
            assert rs.solve(pods).all_pods_scheduled()
            after = dict(m.SOLVER_SEGMENT_WIRE_BYTES.values)
            assert client.breaker.state == remote.STATE_CLOSED
            assert m.SOLVER_RPC_FALLBACKS.value(
                {"endpoint": "solve"}
            ) == fallbacks, "a segment miss must never degrade to greedy"
            assert after.get((("kind", "segment"),), 0) > before.get(
                (("kind", "segment"),), 0
            ), "the re-upload round did not happen"
            assert after.get((("kind", "full"),), 0) == before.get(
                (("kind", "full"),), 0
            ), "a one-round miss must not fall back to the full wire"
            assert client.segcache.instance() == "respawned-0001"
        finally:
            srv.shutdown()
            srv.server_close()

    def test_unresolvable_store_falls_back_to_full_wire_never_greedy(self):
        class AmnesiacStore(segments.SegmentStore):
            """Accepts puts, remembers nothing — the pathological far
            side that can never assemble a manifest."""

            def get(self, digest):
                return None

        daemon = service.SolverDaemon(segment_store=AmnesiacStore())
        srv, addr = _served(daemon)
        try:
            client = remote.SolverClient(addr, timeout=120)
            rs = remote.RemoteScheduler(client, *_world()[:2])
            pods = _world()[2]
            fallbacks = m.SOLVER_RPC_FALLBACKS.value({"endpoint": "solve"})
            before = dict(m.SOLVER_SEGMENT_WIRE_BYTES.values)
            assert rs.solve(pods).all_pods_scheduled()
            after = dict(m.SOLVER_SEGMENT_WIRE_BYTES.values)
            assert after.get((("kind", "full"),), 0) > before.get(
                (("kind", "full"),), 0
            ), "second miss must degrade to the FULL wire"
            assert m.SOLVER_RPC_FALLBACKS.value(
                {"endpoint": "solve"}
            ) == fallbacks
            assert client.breaker.state == remote.STATE_CLOSED
        finally:
            srv.shutdown()
            srv.server_close()

    def test_store_eviction_on_live_instance_repairs_transparently(self):
        """An undersized store evicts problem A's segments while problem
        B solves; re-solving A hits the LIVE instance's typed miss and
        repairs with one upload round — no full-wire fallback, no breaker
        charge. (A store smaller than ONE problem's working set is the
        pathological case the AmnesiacStore test covers: that degrades to
        the full wire.)"""
        from karpenter_core_tpu.cloudprovider.kwok import build_catalog

        # one _world problem occupies 5 store entries (nodepools, catalog,
        # dspods, one pod batch, its listing blob); a 6-entry store holds
        # one problem but never two, so solving B must evict part of A's
        # set while A's shared segments (nodepools, dspods) survive
        daemon = service.SolverDaemon(
            segment_store=segments.SegmentStore(max_entries=6)
        )
        srv, addr = _served(daemon)
        try:
            client = remote.SolverClient(addr, timeout=120)
            pools, its, pods = _world()
            its_b = {
                "default": list(
                    build_catalog(cpu_grid=[2, 8], mem_factors=[4])
                )
            }
            pods_b = [make_pod(cpu=1.0, name=f"b-{i}") for i in range(6)]
            rs_a = remote.RemoteScheduler(client, pools, its)
            rs_b = remote.RemoteScheduler(client, pools, its_b)
            assert rs_a.solve(pods).all_pods_scheduled()
            assert rs_b.solve(pods_b).all_pods_scheduled()  # evicts A's set
            before = dict(m.SOLVER_SEGMENT_WIRE_BYTES.values)
            assert rs_a.solve(pods).all_pods_scheduled()
            after = dict(m.SOLVER_SEGMENT_WIRE_BYTES.values)
            assert client.breaker.state == remote.STATE_CLOSED
            assert after.get((("kind", "full"),), 0) == before.get(
                (("kind", "full"),), 0
            ), "a live-instance eviction miss must repair, not fall back"
            assert after.get((("kind", "segment"),), 0) > before.get(
                (("kind", "segment"),), 0
            )
        finally:
            srv.shutdown()
            srv.server_close()

    def test_healthz_reports_instance_and_segment_stats(self):
        srv, addr = _served()
        try:
            from urllib.request import urlopen

            h = json.loads(
                urlopen(f"http://{addr}/healthz", timeout=30).read()
            )
            assert h["instance"] == srv.daemon_.instance
            assert {"entries", "bytes", "evictions"} <= set(h["segments"])
        finally:
            srv.shutdown()
            srv.server_close()

    def test_wire_mode_full_never_sends_manifests(self):
        pools, its, pods = _world()
        srv, addr = _served()
        try:
            client = remote.SolverClient(addr, timeout=120, wire_mode="full")
            rs = remote.RemoteScheduler(client, pools, its)
            before = dict(m.SOLVER_SEGMENT_WIRE_BYTES.values)
            assert rs.solve(pods).all_pods_scheduled()
            after = dict(m.SOLVER_SEGMENT_WIRE_BYTES.values)
            assert after.get((("kind", "manifest"),), 0) == before.get(
                (("kind", "manifest"),), 0
            )
            assert after.get((("kind", "full"),), 0) > before.get(
                (("kind", "full"),), 0
            )
        finally:
            srv.shutdown()
            srv.server_close()


# ---------------------------------------------------------------------------
# fleet router
# ---------------------------------------------------------------------------


def _fake_members(n):
    return [
        remote.SolverClient(f"127.0.0.1:{9000 + i}", member=str(i))
        for i in range(n)
    ]


class TestFleetRouter:
    def test_affinity_is_deterministic_per_key(self):
        router = remote.FleetRouter(_fake_members(4))
        keys = [f"catalog-{i}" for i in range(32)]
        first = {k: router._pick(k) for k in keys}
        for _ in range(3):
            assert {k: router._pick(k) for k in keys} == first
        # a healthy fleet routes purely by affinity
        assert set(router.snapshot()["routed"]) == {"affinity"}

    def test_member_churn_remaps_only_the_dead_members_keys(self):
        """The rendezvous property: opening ONE member's breaker remaps
        exactly the keys it owned — every surviving member keeps its
        warm-cache keys."""
        router = remote.FleetRouter(_fake_members(4))
        keys = [f"catalog-{i}" for i in range(64)]
        before = {k: router._pick(k) for k in keys}
        dead = before[keys[0]]
        b = router.members[dead].breaker
        b.state = remote.STATE_OPEN
        b.opened_at = b.time_fn() + 10_000  # cooldown never elapses here
        after = {k: router._pick(k) for k in keys}
        for k in keys:
            if before[k] == dead:
                assert after[k] != dead
            else:
                assert after[k] == before[k], (
                    "a surviving member lost an affinity key"
                )
        assert router.snapshot()["routed"].get("degraded", 0) > 0

    def test_affinity_off_routes_least_loaded(self):
        router = remote.FleetRouter(_fake_members(3), affinity=False)
        picks = {router._pick("same-key") for _ in range(6)}
        assert router.snapshot()["routed"] == {"spill": 6}
        assert picks == {0}  # idle fleet: deterministic least-loaded tie

    def test_spill_over_under_forced_drain(self):
        pools, its, pods = _world()
        srvs = [service.serve(0) for _ in range(2)]
        try:
            members = [
                remote.SolverClient(
                    f"127.0.0.1:{s.server_address[1]}",
                    timeout=120, member=str(i),
                )
                for i, s in enumerate(srvs)
            ]
            router = remote.FleetRouter(members)
            rs = remote.RemoteScheduler(router, pools, its)
            assert rs.solve(pods).all_pods_scheduled()
            served = next(
                i for i, c in enumerate(members) if len(c.segcache) > 0
            )
            # drain the affinity member: the router must spill to the
            # other, the solve must succeed, no breaker charge anywhere
            srvs[served].daemon_.gateway.drain()
            fallbacks = m.SOLVER_RPC_FALLBACKS.value({"endpoint": "solve"})
            assert rs.solve(pods).all_pods_scheduled()
            assert router.snapshot()["routed"].get("spill", 0) >= 1
            assert m.SOLVER_RPC_FALLBACKS.value(
                {"endpoint": "solve"}
            ) == fallbacks
            assert all(
                c.breaker.state == remote.STATE_CLOSED for c in members
            )
            # aggregate health: one draining member, fleet still ready
            h = router.health()
            assert h["size"] == 2 and h["ready_members"] >= 1
        finally:
            for s in srvs:
                s.shutdown()
                s.server_close()

    def test_router_duck_types_the_client_surface(self):
        router = remote.FleetRouter(_fake_members(2), tenant="t")
        assert router.tenant == "t"
        assert router.wire_mode == "delta"
        assert router.quarantine is router.members[0].quarantine
        assert router.quarantine is router.members[1].quarantine
        assert router.breaker is router.members[0].breaker  # pre-routing
        with pytest.raises(ValueError):
            remote.FleetRouter([])


# ---------------------------------------------------------------------------
# supervised fleet: kill/respawn + two operators x two sidecars
# ---------------------------------------------------------------------------


def _wait_respawn(sup, client_or_router, member=None, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        restarted = sup.poll()
        if isinstance(restarted, list):
            if restarted:
                for i in restarted:
                    client_or_router.set_member_addr(i, sup.addrs[i])
                return True
        elif restarted:
            client_or_router.set_addr(sup.addr)
            return True
        time.sleep(0.1)
    return False


class TestFleetLifecycle:
    def test_member_kill_respawn_costs_one_reupload_not_greedy(self):
        """Kill/respawn regression (satellite): a REAL fleet-member
        process dies and respawns; the next solve through the router pays
        one miss/re-upload round — greedy fallbacks and the breaker both
        stay untouched."""
        from karpenter_core_tpu.solver.supervisor import SolverSupervisor

        pools, its, pods = _world()
        sup = SolverSupervisor(port=0, backoff_initial=0.05)
        addr = sup.start()
        try:
            member = remote.SolverClient(addr, timeout=120, member="0")
            router = remote.FleetRouter([member])
            rs = remote.RemoteScheduler(router, pools, its)
            assert rs.solve(pods).all_pods_scheduled()
            inst_before = member.segcache.instance()
            sup.proc.kill()
            sup.proc.wait(timeout=15)
            assert _wait_respawn(sup, router), "sidecar did not respawn"
            fallbacks = m.SOLVER_RPC_FALLBACKS.value({"endpoint": "solve"})
            before = dict(m.SOLVER_SEGMENT_WIRE_BYTES.values)
            assert rs.solve(pods).all_pods_scheduled()
            after = dict(m.SOLVER_SEGMENT_WIRE_BYTES.values)
            assert m.SOLVER_RPC_FALLBACKS.value(
                {"endpoint": "solve"}
            ) == fallbacks, "restart cost a greedy fallback"
            assert member.breaker.state == remote.STATE_CLOSED
            assert after.get((("kind", "segment"),), 0) > before.get(
                (("kind", "segment"),), 0
            ), "restart did not cost the expected re-upload"
            assert after.get((("kind", "full"),), 0) == before.get(
                (("kind", "full"),), 0
            )
            assert member.segcache.instance() not in ("", inst_before)
        finally:
            sup.stop()


def _operator(options_kw, catalog):
    from karpenter_core_tpu.cloudprovider.kwok import KwokCloudProvider
    from karpenter_core_tpu.kube.store import KubeStore
    from karpenter_core_tpu.operator import Operator, Options
    from karpenter_core_tpu.utils.clock import FakeClock as OpClock

    clock = OpClock()
    kube = KubeStore(clock)
    return Operator(
        kube=kube,
        cloud_provider=KwokCloudProvider(kube, catalog),
        clock=clock,
        options=Options(solver="tpu", **options_kw),
    )


def _replicated(pod):
    from karpenter_core_tpu.api.objects import OwnerReference

    pod.metadata.owner_references.append(
        OwnerReference(kind="ReplicaSet", name="rs", uid="rs-uid")
    )
    return pod


def _battery(op, prefix):
    op.kube.create(make_nodepool())
    for i in range(3):
        op.kube.create(_replicated(make_pod(cpu=1.5, name=f"{prefix}-p{i}")))
    op.kube.create(_replicated(
        make_pod(cpu=0.5, name=f"{prefix}-z0", zone_in=["zone-b"])
    ))
    op.run_until_idle(disrupt=False)
    pods = op.kube.list_pods()
    return {
        "bound": sorted(p.metadata.name for p in pods if p.node_name),
        "unbound": sorted(p.metadata.name for p in pods if not p.node_name),
        "nodes": len(op.kube.list_nodes()),
    }


@pytest.mark.slow
class TestTwoOperatorsTwoSidecars:
    def test_two_operators_share_one_two_member_fleet(self):
        """The fleet shape end-to-end: operator A spawns a 2-member fleet
        (--solver-fleet=2); operator B (different catalog, different
        tenant) points its router at the SAME two members via the
        comma-list --solver-addr. Each tenant reaches its in-proc parity
        through the shared fleet with zero greedy fallbacks, and the two
        catalogs' affinity keys route independently."""
        from karpenter_core_tpu.cloudprovider.kwok import build_catalog

        cat_a = build_catalog(cpu_grid=[1, 2, 4, 8], mem_factors=[2, 4])
        cat_b = build_catalog(cpu_grid=[2, 4, 16], mem_factors=[4])
        inproc_a = _battery(
            _operator(dict(solver_mode="inproc"), cat_a), "a"
        )
        inproc_b = _battery(
            _operator(dict(solver_mode="inproc"), cat_b), "b"
        )
        assert inproc_a["unbound"] == [] and inproc_b["unbound"] == []

        op_a = _operator(
            dict(
                solver_mode="sidecar", solver_fleet=2,
                solver_tenant="tenant-a",
            ),
            cat_a,
        )
        try:
            from karpenter_core_tpu.solver.remote import FleetRouter
            from karpenter_core_tpu.solver.supervisor import FleetSupervisor

            assert isinstance(op_a.solver_supervisor, FleetSupervisor)
            assert isinstance(op_a.solver_client, FleetRouter)
            addrs = op_a.solver_supervisor.addrs
            assert len(addrs) == 2 and addrs[0] != addrs[1]

            op_b = _operator(
                dict(
                    solver_mode="sidecar",
                    solver_addr=",".join(addrs),
                    solver_tenant="tenant-b",
                ),
                cat_b,
            )
            assert op_b.solver_supervisor is None  # borrowed, not owned
            assert isinstance(op_b.solver_client, FleetRouter)

            fallbacks = m.SOLVER_RPC_FALLBACKS.value({"endpoint": "solve"})
            remote_a = _battery(op_a, "a")
            remote_b = _battery(op_b, "b")
            assert remote_a == inproc_a
            assert remote_b == inproc_b
            assert m.SOLVER_RPC_FALLBACKS.value(
                {"endpoint": "solve"}
            ) == fallbacks
            # both routers placed by affinity, and the fleet aggregate
            # health sees two ready members
            assert op_a.solver_client.snapshot()["routed"].get(
                "affinity", 0
            ) > 0
            health = op_a.solver_client.health()
            assert health["ready_members"] == 2
        finally:
            op_a.shutdown()
