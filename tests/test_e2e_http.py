"""The controller stack end-to-end over the SECOND KubeClient.

The same Operator that drives the in-memory KubeStore drives an HTTP
apiserver in a separate process through HttpKubeClient — the e2e proof the
client seam is real (VERDICT r5 item 2; reference anchor: the envtest
harness controllers run against, pkg/test/environment.go:60-80). A second
independent client verifies the state landed on the server, not in any
client-local cache.
"""
import subprocess
import sys

import pytest

from tests.helpers import make_nodepool, make_pod

from karpenter_core_tpu.api.objects import Node, OwnerReference, Pod
from karpenter_core_tpu.cloudprovider.kwok import KwokCloudProvider, build_catalog
from karpenter_core_tpu.kube.httpclient import HttpKubeClient
from karpenter_core_tpu.operator import Operator, Options


@pytest.fixture()
def http_port():
    proc = subprocess.Popen(
        [sys.executable, "-m", "karpenter_core_tpu.kube.httpserver",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    line = proc.stdout.readline()
    assert "listening on" in line, line
    yield int(line.strip().rsplit(":", 1)[1])
    proc.terminate()
    proc.wait(timeout=10)


def replicated(pod: Pod) -> Pod:
    pod.metadata.owner_references.append(
        OwnerReference(kind="ReplicaSet", name="rs", uid="rs-uid")
    )
    return pod


def new_http_operator(port: int) -> Operator:
    client = HttpKubeClient("127.0.0.1", port)
    catalog = build_catalog(cpu_grid=[1, 2, 4, 8, 16], mem_factors=[2, 4])
    return Operator(
        kube=client,
        cloud_provider=KwokCloudProvider(client, catalog),
        # real wall clock; zero batch windows so passes make progress
        options=Options(batch_max_duration=0.0, batch_idle_duration=0.0),
    )


class TestProvisioningOverHttp:
    def test_pending_pods_provision_and_bind(self, http_port):
        op = new_http_operator(http_port)
        op.kube.create(make_nodepool())
        for i in range(5):
            op.kube.create(replicated(make_pod(cpu=3.0, name=f"h{i}")))
        op.run_until_idle(disrupt=False)
        pods = op.kube.list_pods()
        assert len(pods) == 5
        assert all(p.node_name for p in pods), [
            p.name for p in pods if not p.node_name
        ]
        assert len(op.kube.list_nodes()) >= 1
        # independent client sees the same server-side truth
        probe = HttpKubeClient("127.0.0.1", http_port)
        assert len(probe.list_nodes()) == len(op.kube.list_nodes())
        assert all(p.node_name for p in probe.list_pods())
        claims = probe.list_nodeclaims()
        assert claims and all(c.is_initialized() for c in claims)

    def test_node_deletion_drains_and_reschedules(self, http_port):
        op = new_http_operator(http_port)
        op.kube.create(make_nodepool())
        for i in range(4):
            op.kube.create(replicated(make_pod(cpu=3.0, name=f"d{i}")))
        op.run_until_idle(disrupt=False)
        nodes = op.kube.list_nodes()
        assert nodes
        victim = nodes[0]
        op.kube.delete(victim)
        op.run_until_idle(disrupt=False)
        assert op.kube.get(Node, victim.name) is None
        pods = op.kube.list_pods()
        assert all(p.node_name and p.node_name != victim.name for p in pods)

    def test_external_writer_surfaces_through_watch(self, http_port):
        op = new_http_operator(http_port)
        op.kube.create(make_nodepool())
        op.run_until_idle(disrupt=False)
        # a different process-side client creates a pod; the operator's
        # next poll must see it and provision
        other = HttpKubeClient("127.0.0.1", http_port)
        other.create(replicated(make_pod(cpu=2.0, name="ext0")))
        op.kube.poll()
        op.run_until_idle(disrupt=False)
        assert op.kube.get(Pod, "ext0").node_name


class TestTypedErrorRoundTrip:
    """The 409/429 contracts through httpserver + httpclient: the SAME
    typed errors the in-memory store raises must surface from the wire, so
    controllers (and the conflict-requeue/eviction-backoff paths built on
    them) behave identically over either client."""

    def test_stale_resource_version_round_trips_as_conflict(self, http_port):
        from karpenter_core_tpu.kube.store import ConflictError

        client = HttpKubeClient("127.0.0.1", http_port)
        client.create(make_pod(cpu=0.5, name="c0"))
        stale = client.get(Pod, "c0")
        # a second writer wins the race; the stale object's update must 409
        fresh = client.get(Pod, "c0")
        fresh.metadata.labels["winner"] = "true"
        client.update(fresh)
        stale.metadata.labels["winner"] = "false"
        with pytest.raises(ConflictError):
            client.update(stale)
        # and the winning write is untouched on the server
        assert client.get(Pod, "c0").metadata.labels["winner"] == "true"

    def test_create_of_existing_object_round_trips_as_conflict(
        self, http_port
    ):
        from karpenter_core_tpu.kube.store import ConflictError

        client = HttpKubeClient("127.0.0.1", http_port)
        client.create(make_pod(cpu=0.5, name="dup0"))
        with pytest.raises(ConflictError):
            client.create(make_pod(cpu=0.5, name="dup0"))

    def test_pdb_blocked_eviction_round_trips_as_429(self, http_port):
        from karpenter_core_tpu.api.objects import (
            LabelSelector,
            ObjectMeta,
            PodDisruptionBudget,
        )
        from karpenter_core_tpu.kube.store import TooManyRequestsError

        client = HttpKubeClient("127.0.0.1", http_port)
        client.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="block-all"),
            selector=LabelSelector(match_labels=(("app", "web"),)),
            min_available=1,
        ))
        pod = replicated(make_pod(cpu=0.5, name="e0", labels={"app": "web"}))
        client.create(pod)
        client.bind(pod, "some-node")
        with pytest.raises(TooManyRequestsError):
            client.evict(pod)
        # the pod survived the blocked eviction, still bound
        assert client.get(Pod, "e0").node_name == "some-node"
