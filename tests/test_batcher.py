"""Batcher window semantics (reference: batcher.go:33-110): 1s idle / 10s
max windows gate when the provisioner solves.
"""
from tests.helpers import make_nodepool, make_pod
from tests.test_e2e import new_operator

from karpenter_core_tpu.controllers.provisioning.batcher import Batcher
from karpenter_core_tpu.utils.clock import FakeClock


class TestBatcherUnit:
    def test_idle_window_closes_batch(self):
        clk = FakeClock()
        b = Batcher(clk, max_duration=10.0, idle_duration=1.0)
        b.trigger()
        assert not b.ready()
        clk.step(0.5)
        b.trigger()  # activity keeps the window open
        clk.step(0.9)
        assert not b.ready()
        clk.step(0.2)  # 1.1s since last trigger
        assert b.ready()

    def test_max_window_bounds_a_busy_stream(self):
        clk = FakeClock()
        b = Batcher(clk, max_duration=10.0, idle_duration=1.0)
        b.trigger()
        # continuous triggers every 0.5s never go idle...
        for _ in range(25):
            clk.step(0.5)
            b.trigger()
        # ...but 10s after the window opened, the batch closes regardless
        assert b.ready()

    def test_reset_reopens(self):
        clk = FakeClock()
        b = Batcher(clk, max_duration=10.0, idle_duration=1.0)
        b.trigger()
        clk.step(1.5)
        assert b.ready()
        b.reset()
        assert not b.ready() and not b.open
        b.trigger()
        assert b.open and not b.ready()

    def test_wait_remaining(self):
        clk = FakeClock()
        b = Batcher(clk, max_duration=10.0, idle_duration=1.0)
        assert b.wait_remaining() == 0.0
        b.trigger()
        assert abs(b.wait_remaining() - 1.0) < 1e-9
        # near the max window, the max bound dominates the idle bound
        for _ in range(19):
            clk.step(0.5)
            b.trigger()
        assert abs(b.wait_remaining() - 0.5) < 1e-9


class TestBatcherOperator:
    def test_no_solve_before_window_closes(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="p0"))
        # same-instant reconcile: the window is open but not closed
        op.reconcile_once()
        assert not op.kube.list_nodeclaims(), "solved inside the batch window"
        # idle window elapses -> the solve fires
        op.clock.step(1.1)
        op.reconcile_once()
        assert op.kube.list_nodeclaims()

    def test_stream_batches_into_one_solve(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        for i in range(5):
            op.kube.create(make_pod(cpu=0.5, name=f"p{i}"))
            op.reconcile_once()  # stream arrives within one window
        assert not op.kube.list_nodeclaims()
        op.clock.step(1.1)
        op.run_until_idle()
        # one batch -> one claim serves all five pods
        assert len(op.kube.list_nodeclaims()) == 1
        assert all(p.node_name for p in op.kube.list_pods())

    def test_run_until_idle_steps_the_window(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="p0"))
        op.run_until_idle()
        assert all(p.node_name for p in op.kube.list_pods())
