"""Disruption: emptiness, consolidation (single/multi), drift, budgets
(reference: pkg/controllers/disruption suites, 8,636 LoC — scenario parity
for the core decision paths)."""
import pytest

from tests.helpers import GIB, make_nodepool, make_pod

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.nodeclaim import COND_DRIFTED, NodeClaim
from karpenter_core_tpu.api.nodepool import Budget
from karpenter_core_tpu.api.objects import Node, OwnerReference, Pod
from karpenter_core_tpu.cloudprovider.kwok import build_catalog
from karpenter_core_tpu.kube.store import KubeStore
from karpenter_core_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_core_tpu.operator import Operator, Options
from karpenter_core_tpu.utils.clock import FakeClock

CATALOG = build_catalog(cpu_grid=[1, 2, 4, 8, 16], mem_factors=[2, 4])


def new_operator(feature_gates=None, catalog=None):
    clock = FakeClock()
    kube = KubeStore(clock)
    provider = KwokCloudProvider(kube, catalog or CATALOG)
    return Operator(
        kube=kube,
        cloud_provider=provider,
        clock=clock,
        options=Options(feature_gates=dict(feature_gates or {})),
    )


def replicated(pod: Pod) -> Pod:
    pod.metadata.owner_references.append(
        OwnerReference(kind="ReplicaSet", name="rs", uid="rs-uid")
    )
    return pod


def provision(op, pods):
    op.kube.create(make_nodepool())
    for p in pods:
        op.kube.create(replicated(p))
    op.run_until_idle(disrupt=False)
    assert all(p.node_name for p in op.kube.list_pods())


class TestEmptiness:
    def test_empty_node_deleted(self):
        op = new_operator()
        provision(op, [make_pod(cpu=1.0, name="p0")])
        # remove the workload entirely: node becomes empty + consolidatable
        pod = op.kube.get(Pod, "p0")
        pod.metadata.owner_references = []
        op.kube.delete(pod)
        op.run_until_idle()
        assert not op.kube.list_nodes()
        assert not op.kube.list_nodeclaims()

    def test_budget_zero_blocks_disruption(self):
        op = new_operator()
        pool = make_nodepool()
        pool.spec.disruption.budgets = [Budget(nodes="0")]
        op.kube.create(pool)
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle(disrupt=False)
        pod = op.kube.get(Pod, "p0")
        pod.metadata.owner_references = []
        op.kube.delete(pod)
        op.run_until_idle()
        assert len(op.kube.list_nodes()) == 1  # budget forbids the delete

    def test_consolidate_after_window(self):
        op = new_operator()
        pool = make_nodepool()
        from karpenter_core_tpu.api.duration import NillableDuration

        pool.spec.disruption.consolidate_after = NillableDuration(300.0)
        op.kube.create(pool)
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle(disrupt=False)
        pod = op.kube.get(Pod, "p0")
        pod.metadata.owner_references = []
        op.kube.delete(pod)
        op.run_until_idle()
        assert len(op.kube.list_nodes()) == 1  # window not elapsed
        op.clock.step(301.0)
        op.run_until_idle()
        assert not op.kube.list_nodes()


def od_nodepool():
    """On-demand-only pool: kwok otherwise launches spot (cheapest), and
    spot->spot consolidation is feature-gated off by default, exactly like
    the reference."""
    from karpenter_core_tpu.api.objects import NodeSelectorRequirement

    return make_nodepool(
        requirements=[
            NodeSelectorRequirement(
                L.CAPACITY_TYPE_LABEL_KEY, "In", ("on-demand",)
            )
        ]
    )


class TestConsolidation:
    def test_multi_node_consolidation_packs_down(self):
        # two barely-used nodes repack onto fewer
        op = new_operator()
        op.kube.create(od_nodepool())
        # force two nodes by provisioning in two waves
        op.kube.create(replicated(make_pod(cpu=7.0, name="big0")))
        op.kube.create(replicated(make_pod(cpu=7.0, name="big1")))
        op.run_until_idle(disrupt=False)
        assert len(op.kube.list_nodes()) >= 1
        # shrink the workload: delete the big pods, add two tiny ones
        for name in ("big0", "big1"):
            p = op.kube.get(Pod, name)
            p.metadata.owner_references = []
            op.kube.delete(p)
        op.kube.create(replicated(make_pod(cpu=0.2, name="small0")))
        op.kube.create(replicated(make_pod(cpu=0.2, name="small1")))
        op.run_until_idle(disrupt=False)
        n_before = len(op.kube.list_nodes())
        total_before = sum(
            n.status.capacity.get("cpu", 0) for n in op.kube.list_nodes()
        )
        op.run_until_idle()
        pods = [op.kube.get(Pod, "small0"), op.kube.get(Pod, "small1")]
        assert all(p is not None and p.node_name for p in pods)
        total_after = sum(
            n.status.capacity.get("cpu", 0) for n in op.kube.list_nodes()
        )
        assert total_after < total_before

    def test_replace_with_cheaper_node(self):
        # one big node hosting a small pod gets replaced by a cheaper one
        op = new_operator()
        op.kube.create(od_nodepool())
        op.kube.create(replicated(make_pod(cpu=12.0, name="big")))
        op.kube.create(replicated(make_pod(cpu=0.2, name="small")))
        op.run_until_idle(disrupt=False)
        big = op.kube.get(Pod, "big")
        big.metadata.owner_references = []
        op.kube.delete(big)
        op.run_until_idle()
        small = op.kube.get(Pod, "small")
        assert small.node_name
        nodes = op.kube.list_nodes()
        assert len(nodes) == 1
        assert nodes[0].status.capacity.get("cpu", 0) < 16.0

    def test_well_packed_cluster_is_stable(self):
        op = new_operator()
        provision(op, [make_pod(cpu=1.8, name=f"p{i}") for i in range(8)])
        nodes_before = {n.name for n in op.kube.list_nodes()}
        mutations_before = op.kube.mutations
        op.run_until_idle()
        # consolidation may repack once; afterwards it must go quiet
        op.run_until_idle()
        idle1 = op.kube.mutations
        op.run_until_idle()
        assert op.kube.mutations == idle1


class TestSpotToSpot:
    def test_gated_off_by_default(self):
        op = new_operator()
        op.kube.create(make_nodepool())  # spot (cheapest offering)
        op.kube.create(replicated(make_pod(cpu=12.0, name="big")))
        op.kube.create(replicated(make_pod(cpu=0.2, name="small")))
        op.run_until_idle(disrupt=False)
        big = op.kube.get(Pod, "big")
        big.metadata.owner_references = []
        op.kube.delete(big)
        nodes_before = [
            n.status.capacity.get("cpu", 0) for n in op.kube.list_nodes()
        ]
        op.run_until_idle()
        assert [
            n.status.capacity.get("cpu", 0) for n in op.kube.list_nodes()
        ] == nodes_before  # spot node kept: gate disabled

    def test_gate_enables_spot_replacement(self):
        op = new_operator(feature_gates={"SpotToSpotConsolidation": True})
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=12.0, name="big")))
        op.kube.create(replicated(make_pod(cpu=0.2, name="small")))
        op.run_until_idle(disrupt=False)
        big = op.kube.get(Pod, "big")
        big.metadata.owner_references = []
        op.kube.delete(big)
        op.run_until_idle()
        (node,) = op.kube.list_nodes()
        # replaced by a cheaper spot node from the 15-cheapest set
        assert node.status.capacity.get("cpu", 0) < 16.0
        assert node.labels[L.CAPACITY_TYPE_LABEL_KEY] == "spot"


class TestDrift:
    def test_drifted_node_replaced(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle(disrupt=False)
        (claim,) = op.kube.list_nodeclaims()
        old_node = claim.status.node_name
        # mutate the NodePool template -> static hash drift
        pool = op.kube.get(
            type(op.kube.list_nodepools()[0]), "default"
        )
        pool.spec.template.labels["fleet"] = "v2"
        op.kube.update(pool)
        op.run_until_idle()
        claims = op.kube.list_nodeclaims()
        assert claims, "drifted claim should be replaced, not just deleted"
        assert all(c.name != claim.name for c in claims)
        p = op.kube.get(Pod, "p0")
        assert p.node_name and p.node_name != old_node

    def test_drift_condition_set(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle(disrupt=False)
        pool = op.kube.list_nodepools()[0]
        pool.spec.template.labels["fleet"] = "v2"
        op.kube.update(pool)
        # drift reads the hash ANNOTATIONS (drift.go areStaticFieldsDrifted);
        # the hash controller refreshes the pool's annotation first
        op.nodepool_hash.reconcile(pool)
        (claim,) = op.kube.list_nodeclaims()
        op.nodeclaim_disruption.reconcile(claim)
        assert claim.conditions.is_true(COND_DRIFTED)


    def test_requirements_drift_on_new_pool_key(self):
        """Adding a requirement on a key the claim's labels never defined
        must mark the claim RequirementsDrifted (drift.go:144-154 uses
        Compatible's undefined-key rule, not just shared-key overlap)."""
        from karpenter_core_tpu.api.objects import NodeSelectorRequirement

        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle(disrupt=False)
        pool = op.kube.list_nodepools()[0]
        pool.spec.template.requirements.append(
            NodeSelectorRequirement("example.com/team", "In", ("ml",))
        )
        op.kube.update(pool)
        (claim,) = op.kube.list_nodeclaims()
        op.nodeclaim_disruption.reconcile(claim)
        assert claim.conditions.is_true(COND_DRIFTED)


    def test_well_known_requirement_does_not_churn(self):
        """A pool requirement on a well-known label the provider resolves
        (e.g. region) must NOT drift freshly-launched claims: launch stamps
        single-value requirement labels onto the claim (launch.go:122-133,
        kwok addInstanceLabels), so strict Compatible finds them defined."""
        from karpenter_core_tpu.api.objects import NodeSelectorRequirement

        op = new_operator()
        op.kube.create(
            make_nodepool(
                requirements=[
                    NodeSelectorRequirement(
                        L.LABEL_TOPOLOGY_REGION, "In", ("us-east1",)
                    )
                ]
            )
        )
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle(disrupt=False)
        (claim,) = op.kube.list_nodeclaims()
        assert claim.metadata.labels.get(L.LABEL_TOPOLOGY_REGION) == "us-east1"
        op.nodeclaim_disruption.reconcile(claim)
        assert not claim.conditions.is_true(COND_DRIFTED)


class TestDoNotDisrupt:
    def test_do_not_disrupt_pod_blocks_consolidation(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        p = replicated(make_pod(cpu=0.2, name="precious"))
        p.metadata.annotations[L.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        op.kube.create(p)
        op.kube.create(replicated(make_pod(cpu=12.0, name="big")))
        op.run_until_idle(disrupt=False)
        big = op.kube.get(Pod, "big")
        big.metadata.owner_references = []
        op.kube.delete(big)
        nodes_before = {n.name for n in op.kube.list_nodes()}
        op.run_until_idle()
        # the precious pod's node may not be disrupted
        assert op.kube.get(Pod, "precious").node_name in nodes_before

    def test_fewer_than_15_cheaper_options_declines(self):
        # single-node spot-to-spot needs >= 15 cheaper spot types or the
        # replacement would churn straight back (consolidation.go:48-49);
        # a thin catalog must keep the node
        from karpenter_core_tpu.cloudprovider.kwok import build_catalog

        thin = build_catalog(
            cpu_grid=[8, 16], mem_factors=[2], oses=["linux"],
            arches=["amd64"],
        )
        op = new_operator(
            feature_gates={"SpotToSpotConsolidation": True},
            catalog=thin,
        )
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=12.0, name="big")))
        op.kube.create(replicated(make_pod(cpu=0.2, name="small")))
        op.run_until_idle(disrupt=False)
        big = op.kube.get(Pod, "big")
        big.metadata.owner_references = []
        op.kube.delete(big)
        caps_before = sorted(
            n.status.capacity.get("cpu", 0) for n in op.kube.list_nodes()
        )
        op.run_until_idle()
        assert sorted(
            n.status.capacity.get("cpu", 0) for n in op.kube.list_nodes()
        ) == caps_before

    def test_replacement_claim_truncated_to_15_types(self):
        # the launched claim's instance-type flexibility stays inside the
        # 15-cheapest set so the launched node can't re-trigger
        # consolidation (consolidation.go:283-298)
        op = new_operator(feature_gates={"SpotToSpotConsolidation": True})
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=12.0, name="big")))
        op.kube.create(replicated(make_pod(cpu=0.2, name="small")))
        op.run_until_idle(disrupt=False)
        big = op.kube.get(Pod, "big")
        big.metadata.owner_references = []
        op.kube.delete(big)
        claims_before = {c.name for c in op.kube.list_nodeclaims()}
        op.run_until_idle()
        new_claims = [
            c for c in op.kube.list_nodeclaims()
            if c.name not in claims_before
        ]
        assert new_claims, "no replacement launched"
        for c in new_claims:
            it_req = next(
                (r for r in c.spec.requirements
                 if r.key == L.LABEL_INSTANCE_TYPE),
                None,
            )
            assert it_req is not None
            assert 0 < len(it_req.values) <= 15, len(it_req.values)


class TestCronBudgetWindows:
    def test_zero_budget_window_blocks_then_lifts(self):
        # a maintenance-freeze budget (nodes=0 during a cron window) blocks
        # consolidation while active and lifts when the window closes
        # (nodepool.go:353-367 Budget.IsActive end-to-end)
        import calendar

        from karpenter_core_tpu.api.nodepool import Budget

        window_start = calendar.timegm((2026, 7, 29, 9, 0, 0, 0, 0, 0))
        op = new_operator()
        op.clock.set(float(window_start) + 600.0)  # inside the window
        pool = make_nodepool()
        pool.spec.disruption.budgets = [
            Budget(nodes="0", schedule="0 9 * * *", duration=3600.0)
        ]
        op.kube.create(pool)
        op.kube.create(replicated(make_pod(cpu=7.0, name="big")))
        op.kube.create(replicated(make_pod(cpu=0.2, name="small")))
        op.run_until_idle(disrupt=False)
        big = op.kube.get(Pod, "big")
        big.metadata.owner_references = []
        op.kube.delete(big)
        nodes_before = len(op.kube.list_nodes())
        op.clock.step(60.0)
        op.run_until_idle()
        # frozen: the underutilized node survives the window
        assert len(op.kube.list_nodes()) == nodes_before
        # jump past the window end; consolidation proceeds
        op.clock.step(3600.0)
        op.run_until_idle()
        assert len(op.kube.list_nodes()) < nodes_before or sum(
            n.status.capacity.get("cpu", 0) for n in op.kube.list_nodes()
        ) < 16.0
        assert all(p.node_name for p in op.kube.list_pods())


class TestSingleNodeBounding:
    """singlenodeconsolidation.go:29-101: per-poll time budget + rotation."""

    def _method(self, clock):
        from types import SimpleNamespace

        from karpenter_core_tpu.controllers.disruption.controller import (
            DisruptionContext,
        )
        from karpenter_core_tpu.controllers.disruption.methods import (
            SingleNodeConsolidation,
        )

        ctx = DisruptionContext(
            kube=None, cluster=None, provisioner=None,
            cloud_provider=None, clock=clock,
        )
        method = SingleNodeConsolidation(ctx)
        evaluated = []

        def fake_compute(cands):
            from karpenter_core_tpu.controllers.disruption.types import Command

            evaluated.append(cands[0].state_node.name)
            clock.step(100.0)  # each host simulation "costs" 100s
            return Command(), None

        method.compute_consolidation = fake_compute
        return method, evaluated

    def _candidates(self, n):
        from types import SimpleNamespace

        from karpenter_core_tpu.controllers.disruption.types import Candidate

        return [
            Candidate(
                state_node=SimpleNamespace(name=f"n{i}"),
                node_claim=None,
                nodepool=SimpleNamespace(name="default"),
                instance_type=None,
                zone="zone-a",
                capacity_type="on-demand",
                reschedulable_pods=[object()],
                disruption_cost=float(i),
            )
            for i in range(n)
        ]

    def test_timeout_bounds_sims_per_poll(self):
        from karpenter_core_tpu.controllers.disruption.helpers import (
            BudgetMapping,
        )
        from karpenter_core_tpu.metrics.wiring import CONSOLIDATION_TIMEOUTS

        clock = FakeClock()
        method, evaluated = self._method(clock)
        before = CONSOLIDATION_TIMEOUTS.value(
            {"consolidation_type": "single"}
        )
        cmd = method.compute_command(BudgetMapping({}), self._candidates(50))
        assert cmd.decision == "no-op"
        # 180s budget / 100s per sim -> exactly 2 sims before the deadline
        assert evaluated == ["n0", "n1"]
        assert CONSOLIDATION_TIMEOUTS.value(
            {"consolidation_type": "single"}
        ) == before + 1

    def test_cursor_rotates_to_full_coverage(self):
        from karpenter_core_tpu.controllers.disruption.helpers import (
            BudgetMapping,
        )

        clock = FakeClock()
        method, evaluated = self._method(clock)
        cands = self._candidates(5)
        for _ in range(3):  # 3 polls x 2 sims each cover all 5 candidates
            method.compute_command(BudgetMapping({}), cands)
        assert set(evaluated) >= {f"n{i}" for i in range(5)}

    def test_no_timeout_evaluates_all_and_resets(self):
        from karpenter_core_tpu.controllers.disruption.helpers import (
            BudgetMapping,
        )

        clock = FakeClock()
        method, evaluated = self._method(clock)

        def cheap(cands):
            from karpenter_core_tpu.controllers.disruption.types import Command

            evaluated.append(cands[0].state_node.name)
            return Command(), None

        method.compute_consolidation = cheap
        method.compute_command(BudgetMapping({}), self._candidates(4))
        assert evaluated == ["n0", "n1", "n2", "n3"]
        assert method._resume_key is None

    def test_cursor_survives_candidate_churn(self):
        """The resume cursor anchors to a stable key (candidate name /
        cost), not an index into the re-sorted list: churn ahead of the
        cursor must not restart the sweep at the cheap prefix and starve
        the tail."""
        from karpenter_core_tpu.controllers.disruption.helpers import (
            BudgetMapping,
        )

        clock = FakeClock()
        method, evaluated = self._method(clock)
        cands = self._candidates(10)
        # poll 1: evaluates n0, n1; resume key -> n2
        method.compute_command(BudgetMapping({}), cands)
        assert evaluated == ["n0", "n1"]
        assert method._resume_key == ("n2", 2.0)
        # churn: n0 was consolidated away and two NEW cheap candidates
        # appear ahead of the cursor — an index-based cursor (2) would now
        # point at n1 and re-evaluate the head
        survivors = [c for c in cands if c.state_node.name != "n0"]
        fresh = self._candidates(2)
        for i, c in enumerate(fresh):
            c.state_node.name = f"fresh{i}"
            c.disruption_cost = 0.25 * (i + 1)
        churned = fresh + survivors
        method.compute_command(BudgetMapping({}), churned)
        # poll 2 resumes AT n2 — the remembered name — then walks the tail
        assert evaluated == ["n0", "n1", "n2", "n3"]
        # churn away the remembered candidate itself: resume falls back to
        # the first candidate at/after its remembered cost (n4 at 4.0)
        assert method._resume_key == ("n4", 4.0)
        survivors = [c for c in churned if c.state_node.name != "n4"]
        method.compute_command(BudgetMapping({}), survivors)
        assert evaluated == ["n0", "n1", "n2", "n3", "n5", "n6"]
