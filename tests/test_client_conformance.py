"""KubeClient conformance battery — every implementation must pass.

The contracts a controller relies on (mirrors what envtest guarantees the
reference, pkg/test/environment.go:60-80): CRUD with resource-version
conflict detection, finalizer-gated deletion, ordered watch events,
typed listings, the bind subresource, PDB-gated eviction (429), and
wire-fidelity of the full CRD surface. Parameterized over BOTH
implementations: the in-memory KubeStore and the HttpKubeClient talking
to the HTTP apiserver in a SEPARATE PROCESS.
"""
import subprocess
import sys
import time

import pytest

from tests.helpers import make_nodepool, make_pod

from karpenter_core_tpu.api.nodeclaim import NodeClaim
from karpenter_core_tpu.api.nodepool import Budget, Limits, NodePool
from karpenter_core_tpu.api.objects import (
    Node,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodDisruptionBudget,
    Toleration,
)
from karpenter_core_tpu.kube.store import (
    ConflictError,
    KubeStore,
    NotFoundError,
    TooManyRequestsError,
)


@pytest.fixture(scope="module")
def http_server():
    proc = subprocess.Popen(
        [sys.executable, "-m", "karpenter_core_tpu.kube.httpserver",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    line = proc.stdout.readline()
    assert "listening on" in line, line
    port = int(line.strip().rsplit(":", 1)[1])
    yield port
    proc.terminate()
    proc.wait(timeout=10)


@pytest.fixture(params=["store", "http"])
def client(request, http_server):
    if request.param == "store":
        yield KubeStore()
    else:
        from karpenter_core_tpu.kube.httpclient import HttpKubeClient

        c = HttpKubeClient("127.0.0.1", http_server)
        # isolate from prior tests on the shared server: drain + delete all
        c.poll()
        for lister in (c.list_pods, c.list_nodes, c.list_nodeclaims,
                       c.list_nodepools, c.list_pdbs):
            for obj in lister():
                obj.metadata.finalizers = []
                try:
                    c.update(obj)
                    c.delete(obj)
                except (NotFoundError, ConflictError):
                    pass
        yield c


def pump(client):
    poll = getattr(client, "poll", None)
    if poll:
        poll()


class TestCrud:
    def test_create_assigns_version_and_timestamp(self, client):
        pod = make_pod(cpu=1.0, name="c1")
        client.create(pod)
        assert pod.metadata.resource_version
        assert pod.metadata.creation_timestamp
        assert client.get(Pod, "c1") is not None

    def test_duplicate_create_conflicts(self, client):
        client.create(make_pod(cpu=1.0, name="dup"))
        with pytest.raises(ConflictError):
            client.create(make_pod(cpu=1.0, name="dup"))

    def test_get_missing_returns_none(self, client):
        assert client.get(Pod, "nope") is None

    def test_update_bumps_version_and_detects_staleness(self, client):
        pod = make_pod(cpu=1.0, name="u1")
        client.create(pod)
        rv1 = pod.metadata.resource_version
        pod.metadata.labels["x"] = "y"
        client.update(pod)
        assert pod.metadata.resource_version != rv1
        import copy

        stale = copy.deepcopy(client.get(Pod, "u1"))
        stale.metadata.resource_version = rv1  # stale writer
        with pytest.raises(ConflictError):
            client.update(stale)

    def test_update_missing_not_found(self, client):
        pod = make_pod(cpu=1.0, name="ghost")
        with pytest.raises(NotFoundError):
            client.update(pod)

    def test_delete_and_delete_missing(self, client):
        pod = make_pod(cpu=1.0, name="d1")
        client.create(pod)
        client.delete(pod)
        assert client.get(Pod, "d1") is None
        with pytest.raises(NotFoundError):
            client.delete(pod)

    def test_finalizer_gates_deletion(self, client):
        claim = NodeClaim(metadata=ObjectMeta(name="fc1"))
        claim.metadata.finalizers.append("karpenter.sh/termination")
        client.create(claim)
        client.delete(claim)
        held = client.get(NodeClaim, "fc1")
        assert held is not None
        assert held.metadata.deletion_timestamp is not None
        held.metadata.finalizers = []
        client.update(held)
        assert client.get(NodeClaim, "fc1") is None


class TestWatch:
    def test_events_ordered(self, client):
        events = []
        client.watch(lambda ev, kind, obj: events.append((ev, kind, obj.name)))
        pod = make_pod(cpu=1.0, name="w1")
        client.create(pod)
        pod.metadata.labels["a"] = "b"
        client.update(pod)
        client.delete(pod)
        pump(client)
        mine = [e for e in events if e[2] == "w1"]
        assert [e[0] for e in mine] == ["ADDED", "MODIFIED", "DELETED"]
        assert all(e[1] == "Pod" for e in mine)

    def test_mutations_counter_advances(self, client):
        before = client.mutations
        client.create(make_pod(cpu=1.0, name="w2"))
        pump(client)
        assert client.mutations > before


class TestListings:
    def test_typed_listings(self, client):
        client.create(make_pod(cpu=1.0, name="l1"))
        client.create(make_nodepool("lp"))
        node = Node(metadata=ObjectMeta(name="ln"), provider_id="prov-l1")
        client.create(node)
        assert "l1" in [p.name for p in client.list_pods()]
        assert "lp" in [p.name for p in client.list_nodepools()]
        assert "ln" in [n.name for n in client.list_nodes()]
        got = client.get_node_by_provider_id("prov-l1")
        assert got is not None and got.name == "ln"
        assert client.get_node_by_provider_id("missing") is None


class TestPodSubresources:
    def test_bind_sets_node_and_phase(self, client):
        pod = make_pod(cpu=1.0, name="b1")
        client.create(pod)
        node = Node(metadata=ObjectMeta(name="bn1"), provider_id="prov-b1")
        client.create(node)
        client.bind(pod, "bn1")
        assert pod.node_name == "bn1"
        assert pod.phase == "Running"
        assert client.get(Pod, "b1").node_name == "bn1"

    def test_evict_replicated_returns_to_pending(self, client):
        pod = make_pod(cpu=1.0, name="e1")
        pod.metadata.owner_references.append(
            OwnerReference(kind="ReplicaSet", name="rs", uid="rs-1")
        )
        client.create(pod)
        node = Node(metadata=ObjectMeta(name="en1"))
        client.create(node)
        client.bind(pod, "en1")
        client.evict(pod)
        fresh = client.get(Pod, "e1")
        assert fresh.node_name == ""
        assert fresh.phase == "Pending"

    def test_evict_bare_pod_deletes(self, client):
        pod = make_pod(cpu=1.0, name="e2")
        client.create(pod)
        client.evict(pod)
        assert client.get(Pod, "e2") is None

    def test_evict_pdb_blocked_raises_429(self, client):
        from karpenter_core_tpu.api.objects import LabelSelector

        pod = make_pod(cpu=1.0, name="e3", labels={"app": "guarded"})
        pod.metadata.owner_references.append(
            OwnerReference(kind="ReplicaSet", name="rs", uid="rs-3")
        )
        client.create(pod)
        node = Node(metadata=ObjectMeta(name="en3"))
        client.create(node)
        client.bind(pod, "en3")
        pdb = PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb3"),
            selector=LabelSelector(match_labels=(("app", "guarded"),)),
            min_available=1,
        )
        client.create(pdb)
        with pytest.raises(TooManyRequestsError):
            client.evict(pod)
        client.delete(pdb)
        client.evict(pod)  # unblocked after the budget goes away

    def test_evict_missing_not_found(self, client):
        pod = make_pod(cpu=1.0, name="e4")
        with pytest.raises(NotFoundError):
            client.evict(pod)


class TestWireFidelity:
    def test_nodepool_full_surface_roundtrip(self, client):
        pool = make_nodepool("fidelity")
        pool.spec.weight = 7
        pool.spec.limits = Limits()
        pool.spec.limits.update({"cpu": 100.0})
        pool.spec.disruption.budgets = [
            Budget(nodes="25%", schedule="0 9 * * *", duration=3600.0,
                   reasons=["Underutilized"]),
        ]
        pool.spec.template.labels["team"] = "infra"
        pool.conditions.set_true("Ready", "TestReason")
        client.create(pool)
        got = client.get(NodePool, "fidelity")
        assert got.spec.weight == 7
        assert dict(got.spec.limits) == {"cpu": 100.0}
        b = got.spec.disruption.budgets[0]
        assert (b.nodes, b.schedule, b.duration) == ("25%", "0 9 * * *", 3600.0)
        assert b.reasons == ["Underutilized"]
        assert got.conditions.is_true("Ready")
        assert got.static_hash() == pool.static_hash()

    def test_pod_full_surface_roundtrip(self, client):
        from karpenter_core_tpu.api import labels as L
        from karpenter_core_tpu.api.objects import (
            Affinity,
            Container,
            LabelSelector,
            NodeAffinity,
            NodeSelectorRequirement,
            NodeSelectorTerm,
            TopologySpreadConstraint,
        )

        pod = Pod(
            metadata=ObjectMeta(name="rich", labels={"app": "x"}),
            containers=[Container(resource_requests={"cpu": 1.5})],
            tolerations=[Toleration(key="k", operator="Exists",
                                    effect="NoSchedule")],
            affinity=Affinity(node_affinity=NodeAffinity(required=[
                NodeSelectorTerm(match_expressions=(
                    NodeSelectorRequirement(
                        L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a",)),
                ))
            ])),
            topology_spread_constraints=[TopologySpreadConstraint(
                max_skew=1,
                topology_key=L.LABEL_HOSTNAME,
                when_unsatisfiable="DoNotSchedule",
                label_selector=LabelSelector(match_labels=(("app", "x"),)),
            )],
        )
        client.create(pod)
        got = client.get(Pod, "rich")
        assert got.resource_requests["cpu"] == 1.5  # derived server-side too
        assert got.tolerations[0].key == "k"
        term = got.affinity.node_affinity.required[0]
        req = term.match_expressions[0]
        assert req.values == ("zone-a",)  # tuple preserved (hashability)
        tsc = got.topology_spread_constraints[0]
        assert tsc.label_selector.match_labels == (("app", "x"),)
        # requirements algebra works on the wire copy
        from karpenter_core_tpu.scheduling import Requirements

        reqs = Requirements.from_pod(got)
        assert reqs.get(L.LABEL_TOPOLOGY_ZONE).has("zone-a")


class TestCodecIdempotence:
    def test_overhead_not_reapplied_across_round_trips(self):
        """Wire state is authoritative: decode must not re-run request
        derivation, or overhead compounds once per codec hop."""
        from karpenter_core_tpu.kube import serial

        pod = Pod(
            metadata=ObjectMeta(name="oh"),
            resource_requests={"cpu": 4.0},
            overhead={"cpu": 0.1},
        )
        assert pod.resource_requests["cpu"] == 4.1
        for _ in range(3):
            pod = serial.decode(serial.encode(pod))
        assert pod.resource_requests["cpu"] == 4.1

    def test_container_pod_round_trip_stable(self):
        from karpenter_core_tpu.api.objects import Container
        from karpenter_core_tpu.kube import serial

        pod = Pod(
            metadata=ObjectMeta(name="cb"),
            containers=[Container(resource_requests={"cpu": 1.5})],
            overhead={"cpu": 0.25},
        )
        first = dict(pod.resource_requests)
        for _ in range(3):
            pod = serial.decode(serial.encode(pod))
        assert pod.resource_requests == first
