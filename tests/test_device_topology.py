"""Greedy-vs-device parity for the topology-aware kernel paths.

This is the suite the batching-deviation contracts in ops/ffd.py and
ops/topoplan.py point at: for each constraint shape the device solver
(class-batched scan + device count state + plane decode) must produce a
final state that (a) satisfies the constraints outright and (b) lands
within node-count tolerance of the greedy oracle
(reference semantics: topologygroup.go:181-342, scheduler.go:208-316).
Covers zone/hostname spread (water-fill sub-steps), affinity bootstrap,
hostname anti-affinity, existing-node seeding, and the deferred / fallback
decode paths (hostPort pods, non-trivial spread node filters).
"""
import copy
from collections import Counter

import pytest

from tests.helpers import GIB, make_diverse_pods, make_nodepool, make_pod
from tests.test_topology import (
    CATALOG,
    claim_zone,
    three_zone_pool,
    zone_counts,
)

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.objects import NodeSelectorRequirement
from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import SimNode
from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import Scheduler
from karpenter_core_tpu.models.provisioner import DeviceScheduler


def both_solve(pods, pools=None, existing=None, max_slots=64):
    pools = pools or [three_zone_pool()]
    g = Scheduler(pools, {p.name: CATALOG for p in pools},
                  existing_nodes=list(existing or []))
    rg = g.solve(copy.deepcopy(pods))
    d = DeviceScheduler(pools, {p.name: CATALOG for p in pools},
                        existing_nodes=list(existing or []),
                        max_slots=max_slots)
    rd = d.solve(copy.deepcopy(pods))
    return rg, rd


def assert_node_parity(rg, rd, tol=0):
    assert set(rg.pod_errors) == set(rd.pod_errors), (
        rg.pod_errors, rd.pod_errors)
    # one-sided: the device's host-floor-first ordering can BEAT the
    # oracle; it must never be worse by more than tol
    assert rd.node_count() <= rg.node_count() + tol, (
        f"device {rd.node_count()} vs greedy {rg.node_count()}")


def pods_per_node(res):
    """Pod lists per placement target (claims + touched existing nodes)."""
    out = [list(c.pods) for c in res.new_node_claims]
    out += [list(s.pods) for s in res.existing_nodes if s.pods]
    return out


class TestZoneSpreadParity:
    def test_even_spread(self):
        rg, rd = both_solve([make_pod(cpu=1.0, spread_zone=True)
                             for _ in range(9)])
        assert_node_parity(rg, rd)
        assert zone_counts(rd) == {"zone-a": 3, "zone-b": 3, "zone-c": 3}

    def test_skew_two(self):
        pods = [make_pod(cpu=1.0, spread_zone=True, max_skew=2)
                for _ in range(7)]
        rg, rd = both_solve(pods)
        assert_node_parity(rg, rd, tol=1)
        counts = zone_counts(rd)
        assert max(counts.values()) - min(counts.values() or [0]) <= 2, counts

    def test_waterfill_against_imbalanced_existing(self):
        # zone-a pre-loaded with 4 spread pods on an existing node; new
        # spread pods must water-fill zone-b/zone-c first (the multi-sub-step
        # carry path in _wf_quota)
        node = SimNode(
            name="existing-a",
            labels={L.LABEL_TOPOLOGY_ZONE: "zone-a",
                    L.LABEL_HOSTNAME: "existing-a",
                    L.LABEL_OS: "linux",
                    L.CAPACITY_TYPE_LABEL_KEY: "on-demand",
                    L.NODEPOOL_LABEL_KEY: "default"},
            taints=[],
            available={"cpu": 16.0, "memory": 32 * GIB, "pods": 110.0},
            initialized=True,
        )
        pods = [make_pod(cpu=0.5, spread_zone=True) for _ in range(8)]

        def mk():
            from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
                Topology, domain_universe,
            )
            pool = three_zone_pool()
            seeds = []
            for i in range(4):
                sp = make_pod(cpu=0.1, labels={"app": "spread"},
                              name=f"seed-{i}")
                sp.node_name = "existing-a"  # bound pods count for topology
                seeds.append((sp, dict(node.labels), "existing-a"))
            topo = Topology(
                domains={k: set(v) for k, v in domain_universe(
                    [pool], {"default": CATALOG}, [node]).items()},
                existing_pods=seeds,
            )
            return pool, topo

        pool, topo_g = mk()
        g = Scheduler([pool], {"default": CATALOG}, existing_nodes=[node],
                      topology=topo_g)
        rg = g.solve(copy.deepcopy(pods))
        pool2, topo_d = mk()
        d = DeviceScheduler([pool2], {"default": CATALOG},
                            existing_nodes=[node], topology=topo_d,
                            max_slots=64)
        rd = d.solve(copy.deepcopy(pods))
        assert rg.all_pods_scheduled() and rd.all_pods_scheduled(), (
            rg.pod_errors, rd.pod_errors)
        # zone-a starts at 4: the 8 new pods must lift b/c to 4 each under
        # maxSkew=1 (4/4/4); none lands in zone-a
        for res in (rg, rd):
            zc = zone_counts(res)
            assert zc.get("zone-b", 0) == 4 and zc.get("zone-c", 0) == 4, zc


class TestHostnameSpreadParity:
    def test_one_per_node(self):
        # maxSkew=1 on hostname with min floating at zero: every pod takes a
        # fresh hostname (topologygroup.go:235-238)
        pods = [make_pod(cpu=0.5, spread_hostname=True) for _ in range(5)]
        rg, rd = both_solve(pods)
        assert_node_parity(rg, rd)
        for group in pods_per_node(rd):
            assert sum(1 for p in group
                       if p.metadata.labels.get("app") == "spread") <= 1

    def test_mixed_with_generic(self):
        pods = [make_pod(cpu=0.5, spread_hostname=True) for _ in range(4)]
        pods += [make_pod(cpu=0.25, name=f"filler-{i}") for i in range(12)]
        rg, rd = both_solve(pods)
        assert_node_parity(rg, rd, tol=1)


class TestAntiAffinityParity:
    def test_self_anti_one_per_node(self):
        pods = [
            make_pod(cpu=0.5, labels={"app": "anti"},
                     anti_affinity_to={"app": "anti"},
                     affinity_key=L.LABEL_HOSTNAME,
                     name=f"anti-{i}")
            for i in range(6)
        ]
        rg, rd = both_solve(pods)
        assert_node_parity(rg, rd)
        for group in pods_per_node(rd):
            assert sum(1 for p in group
                       if p.metadata.labels.get("app") == "anti") <= 1

    def test_anti_copacks_with_fillers(self):
        # emptiest-first must co-pack fillers onto anti-opened nodes instead
        # of fragmenting (the r4 parity fix)
        pods = [
            make_pod(cpu=0.25, labels={"app": "anti"},
                     anti_affinity_to={"app": "anti"},
                     affinity_key=L.LABEL_HOSTNAME, name=f"anti-{i}")
            for i in range(4)
        ]
        pods += [make_pod(cpu=0.25, name=f"filler-{i}") for i in range(8)]
        rg, rd = both_solve(pods)
        assert_node_parity(rg, rd, tol=1)


class TestAffinityParity:
    def test_zone_affinity_bootstrap_colocates(self):
        # self-affinity on zone: first pod bootstraps a domain, the rest
        # must follow it (nextDomainAffinity topologygroup.go:253-300)
        pods = [
            make_pod(cpu=0.5, labels={"app": "web"},
                     affinity_to={"app": "web"}, name=f"web-{i}")
            for i in range(5)
        ]
        rg, rd = both_solve(pods)
        assert rg.all_pods_scheduled() and rd.all_pods_scheduled(), (
            rg.pod_errors, rd.pod_errors)
        for res in (rg, rd):
            zones = {claim_zone(c) for c in res.new_node_claims if c.pods}
            assert len(zones) == 1, zones
        assert_node_parity(rg, rd, tol=1)

    def test_affinity_follows_existing(self):
        # a target pod already running in zone-b pins the affinity domain
        node = SimNode(
            name="existing-b",
            labels={L.LABEL_TOPOLOGY_ZONE: "zone-b",
                    L.LABEL_HOSTNAME: "existing-b",
                    L.LABEL_OS: "linux",
                    L.CAPACITY_TYPE_LABEL_KEY: "on-demand",
                    L.NODEPOOL_LABEL_KEY: "default"},
            taints=[],
            available={"cpu": 2.0, "memory": 4 * GIB, "pods": 110.0},
            initialized=True,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
            Topology, domain_universe,
        )

        def solve(cls):
            pool = three_zone_pool()
            tgt = make_pod(cpu=0.1, labels={"app": "db"}, name="tgt")
            tgt.node_name = "existing-b"  # bound pods count for topology
            topo = Topology(
                domains={k: set(v) for k, v in domain_universe(
                    [pool], {"default": CATALOG}, [node]).items()},
                existing_pods=[(tgt, dict(node.labels), "existing-b")],
            )
            s = cls([pool], {"default": CATALOG}, existing_nodes=[node],
                    topology=topo)
            return s.solve([
                make_pod(cpu=4.0, affinity_to={"app": "db"},
                         name=f"follower-{i}") for i in range(3)
            ])

        rg, rd = solve(Scheduler), solve(DeviceScheduler)
        for res in (rg, rd):
            assert res.all_pods_scheduled(), res.pod_errors
            for c in res.new_node_claims:
                if c.pods:
                    assert claim_zone(c) == "zone-b"
        assert_node_parity(rg, rd, tol=1)


class TestFallbackPaths:
    def test_hostport_topology_pod_falls_back(self):
        # hostPort + topology constraints is host-fallback territory
        # (topoplan._eligibility); result must still satisfy both
        pods = [make_pod(cpu=0.5, spread_zone=True) for _ in range(6)]
        for i, p in enumerate(pods[:2]):
            p.host_ports = [("0.0.0.0", 8080, "TCP")]
        rg, rd = both_solve(pods)
        assert set(rg.pod_errors) == set(rd.pod_errors)
        # the two hostPort pods must sit on different nodes
        for res in (rg, rd):
            for group in pods_per_node(res):
                assert sum(1 for p in group if p.host_ports) <= 1
        assert_node_parity(rg, rd, tol=1)

    def test_spread_with_node_filter_is_host_only(self):
        # a spread whose pod carries zonal node-affinity: the TopologyGroup
        # gets a non-trivial node filter -> host-only group (topoplan)
        pods = [make_pod(cpu=0.5, spread_zone=True,
                         zone_in=["zone-a", "zone-b"]) for _ in range(4)]
        pods += [make_pod(cpu=0.5, name=f"plain-{i}") for i in range(4)]
        rg, rd = both_solve(pods)
        assert set(rg.pod_errors) == set(rd.pod_errors)
        for res in (rg, rd):
            zc = Counter()
            for c in res.new_node_claims:
                n = sum(1 for p in c.pods
                        if p.metadata.labels.get("app") == "spread")
                if n:
                    zc[claim_zone(c)] += n
            assert set(zc) <= {"zone-a", "zone-b"}, zc
            if zc:
                assert max(zc.values()) - min(zc.values()) <= 1, zc
        assert_node_parity(rg, rd, tol=1)


class TestDiverseMixParity:
    @pytest.mark.parametrize("seed", [2, 3, 4, 5, 6, 7])
    def test_diverse_mix_more_seeds(self, seed):
        pods = make_diverse_pods(48, seed=seed, with_topology=True)
        rg, rd = both_solve(pods)
        assert set(rg.pod_errors) == set(rd.pod_errors), (
            rg.pod_errors, rd.pod_errors)
        # constraint satisfaction on the device result
        for group in pods_per_node(rd):
            assert sum(1 for p in group
                       if p.metadata.labels.get("app") == "anti") <= 1
        if rg.node_count():
            assert abs(rd.node_count() - rg.node_count()) <= max(
                2, 0.15 * rg.node_count())


class TestMinDomainsParity:
    def test_min_domains_unsatisfied_caps_each_domain_at_skew(self):
        # minDomains=5 over a 3-zone universe: the global minimum pins at
        # zero while under-provisioned, so every domain caps at maxSkew
        # (topologygroup.go:229-249) — 2 pods with skew 1 land in TWO
        # different zones rather than stacking
        pods = []
        for i in range(2):
            p = make_pod(cpu=1.0, spread_zone=True)
            p.topology_spread_constraints = [
                type(p.topology_spread_constraints[0])(
                    max_skew=1,
                    topology_key=L.LABEL_TOPOLOGY_ZONE,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=p.topology_spread_constraints[0].label_selector,
                    min_domains=5,
                )
            ]
            pods.append(p)
        rg, rd = both_solve(pods)
        assert rg.all_pods_scheduled() and rd.all_pods_scheduled(), (
            rg.pod_errors, rd.pod_errors)
        for res in (rg, rd):
            zones = [claim_zone(c) for c in res.new_node_claims if c.pods]
            assert len(set(zones)) == 2, zones
        assert_node_parity(rg, rd, tol=1)

    def test_min_domains_unsatisfied_blocks_fourth_pod(self):
        # 3 zones, minDomains=5, skew 1: at most one pod per zone while the
        # minimum is pinned at zero -> the 4th pod cannot schedule
        def spread_pod():
            p = make_pod(cpu=1.0, spread_zone=True)
            p.topology_spread_constraints = [
                type(p.topology_spread_constraints[0])(
                    max_skew=1,
                    topology_key=L.LABEL_TOPOLOGY_ZONE,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=p.topology_spread_constraints[0].label_selector,
                    min_domains=5,
                )
            ]
            return p

        pods = [spread_pod() for _ in range(4)]
        rg, rd = both_solve(pods)
        assert len(rg.pod_errors) == 1, rg.pod_errors
        assert set(rg.pod_errors) == set(rd.pod_errors)

    def test_min_domains_satisfied_is_plain_spread(self):
        # minDomains <= zone count: normal spread semantics
        def spread_pod():
            p = make_pod(cpu=1.0, spread_zone=True)
            p.topology_spread_constraints = [
                type(p.topology_spread_constraints[0])(
                    max_skew=1,
                    topology_key=L.LABEL_TOPOLOGY_ZONE,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=p.topology_spread_constraints[0].label_selector,
                    min_domains=2,
                )
            ]
            return p

        pods = [spread_pod() for _ in range(6)]
        rg, rd = both_solve(pods)
        assert rg.all_pods_scheduled() and rd.all_pods_scheduled()
        for res in (rg, rd):
            zc = zone_counts(res)
            assert set(zc.values()) == {2}, zc


class TestNamespaceScoping:
    def test_affinity_defaults_to_own_namespace(self):
        # a required pod-affinity term without namespaces only sees pods in
        # the POD'S OWN namespace (topology.go _namespace_list); a target in
        # another namespace must not satisfy it
        from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
            Topology, domain_universe,
        )

        def solve(cls):
            pool = three_zone_pool()
            tgt = make_pod(cpu=0.1, labels={"app": "db"}, name="tgt")
            tgt.metadata.namespace = "other"
            tgt.node_name = "n1"
            topo = Topology(
                domains={k: set(v) for k, v in domain_universe(
                    [pool], {"default": CATALOG}, []).items()},
                existing_pods=[(
                    tgt,
                    {L.LABEL_TOPOLOGY_ZONE: "zone-b"},
                    "n1",
                )],
            )
            s = cls([pool], {"default": CATALOG}, topology=topo)
            follower = make_pod(
                cpu=0.5, affinity_to={"app": "db"}, name="follower",
                labels={"app": "follower"},
            )
            return s.solve([follower])

        rg, rd = solve(Scheduler), solve(DeviceScheduler)
        # the cross-namespace target is invisible: the self-unselected
        # affinity has no positive domain and no bootstrap -> unschedulable
        # (each solve builds its own pods, so compare counts not uids)
        assert not rg.all_pods_scheduled()
        assert not rd.all_pods_scheduled()
        assert len(rg.pod_errors) == len(rd.pod_errors) == 1

    def test_explicit_namespaces_cross_boundary(self):
        from karpenter_core_tpu.api.objects import (
            Affinity, LabelSelector, PodAffinity, PodAffinityTerm,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
            Topology, domain_universe,
        )

        def solve(cls):
            pool = three_zone_pool()
            tgt = make_pod(cpu=0.1, labels={"app": "db"}, name="tgt")
            tgt.metadata.namespace = "other"
            tgt.node_name = "n1"
            topo = Topology(
                domains={k: set(v) for k, v in domain_universe(
                    [pool], {"default": CATALOG}, []).items()},
                existing_pods=[(
                    tgt,
                    {L.LABEL_TOPOLOGY_ZONE: "zone-b"},
                    "n1",
                )],
            )
            s = cls([pool], {"default": CATALOG}, topology=topo)
            follower = make_pod(cpu=0.5, name="follower")
            follower.affinity = Affinity(pod_affinity=PodAffinity(required=[
                PodAffinityTerm(
                    topology_key=L.LABEL_TOPOLOGY_ZONE,
                    label_selector=LabelSelector(
                        match_labels=(("app", "db"),)
                    ),
                    namespaces=("other",),
                )
            ]))
            return s.solve([follower])

        rg, rd = solve(Scheduler), solve(DeviceScheduler)
        for res in (rg, rd):
            assert res.all_pods_scheduled(), res.pod_errors
            (claim,) = [c for c in res.new_node_claims if c.pods]
            assert claim_zone(claim) == "zone-b"


class TestScheduleAnywayDevice:
    def test_soft_spread_relaxes_on_device(self):
        # ScheduleAnyway zone spread with impossible skew over a one-zone
        # pool: the device relaxation loop must strip it and schedule
        # (preferences.go:38-57)
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement(L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a",))
        ])
        pods = []
        for _ in range(3):
            p = make_pod(cpu=1.0, spread_zone=True)
            p.topology_spread_constraints = [
                type(p.topology_spread_constraints[0])(
                    max_skew=1,
                    topology_key=L.LABEL_TOPOLOGY_ZONE,
                    when_unsatisfiable="ScheduleAnyway",
                    label_selector=p.topology_spread_constraints[0].label_selector,
                )
            ]
            pods.append(p)
        d = DeviceScheduler([pool], {"default": CATALOG}, max_slots=64)
        res = d.solve(pods)
        assert res.all_pods_scheduled(), res.pod_errors


class TestPreferredPodAffinityRelaxation:
    @pytest.mark.parametrize("cls", [Scheduler, DeviceScheduler])
    def test_unsatisfiable_preferred_pod_affinity_relaxes(self, cls):
        # preferred pod-affinity toward a label nothing carries: the
        # relaxation loop strips the soft term and the pod schedules
        # (preferences.go:38-57 order: preferred pod-affinity first)
        from karpenter_core_tpu.api.objects import (
            Affinity,
            LabelSelector,
            PodAffinity,
            PodAffinityTerm,
            WeightedPodAffinityTerm,
        )

        p = make_pod(cpu=1.0, name="soft")
        p.affinity = Affinity(pod_affinity=PodAffinity(preferred=[
            WeightedPodAffinityTerm(
                weight=100,
                pod_affinity_term=PodAffinityTerm(
                    topology_key=L.LABEL_TOPOLOGY_ZONE,
                    label_selector=LabelSelector(
                        match_labels=(("app", "ghost"),)
                    ),
                ),
            )
        ]))
        s = cls([three_zone_pool()], {"default": CATALOG}, max_slots=16) \
            if cls is DeviceScheduler else cls(
                [three_zone_pool()], {"default": CATALOG})
        res = s.solve([p])
        assert res.all_pods_scheduled(), res.pod_errors

    @pytest.mark.parametrize("cls", [Scheduler, DeviceScheduler])
    def test_satisfiable_preferred_pod_affinity_honored(self, cls):
        # a satisfiable soft term pulls the pod toward the target's zone
        from karpenter_core_tpu.api.objects import (
            Affinity,
            LabelSelector,
            PodAffinity,
            PodAffinityTerm,
            WeightedPodAffinityTerm,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
            Topology, domain_universe,
        )

        pool = three_zone_pool()
        tgt = make_pod(cpu=0.1, labels={"app": "db"}, name="tgt")
        tgt.node_name = "n1"
        topo = Topology(
            domains={k: set(v) for k, v in domain_universe(
                [pool], {"default": CATALOG}, []).items()},
            existing_pods=[(tgt, {L.LABEL_TOPOLOGY_ZONE: "zone-b"}, "n1")],
        )
        kwargs = {"max_slots": 16} if cls is DeviceScheduler else {}
        s = cls([pool], {"default": CATALOG}, topology=topo, **kwargs)
        p = make_pod(cpu=1.0, name="soft")
        p.affinity = Affinity(pod_affinity=PodAffinity(preferred=[
            WeightedPodAffinityTerm(
                weight=100,
                pod_affinity_term=PodAffinityTerm(
                    topology_key=L.LABEL_TOPOLOGY_ZONE,
                    label_selector=LabelSelector(
                        match_labels=(("app", "db"),)
                    ),
                ),
            )
        ]))
        res = s.solve([p])
        assert res.all_pods_scheduled(), res.pod_errors
        (claim,) = [c for c in res.new_node_claims if c.pods]
        assert claim_zone(claim) == "zone-b"


class TestMultiConstraintPods:
    def test_zone_and_hostname_spread_on_one_pod(self):
        # one pod owning BOTH a zone spread (water-fill sub-steps) and a
        # hostname spread (per-slot count caps): the kernel applies both
        # simultaneously — at most one per host AND balanced across zones
        pods = [
            make_pod(cpu=0.5, spread_zone=True, spread_hostname=True,
                     name=f"both-{i}")
            for i in range(6)
        ]
        rg, rd = both_solve(pods)
        assert rg.all_pods_scheduled() and rd.all_pods_scheduled(), (
            rg.pod_errors, rd.pod_errors)
        for res in (rg, rd):
            for group in pods_per_node(res):
                assert sum(
                    1 for p in group
                    if p.metadata.labels.get("app") == "spread"
                ) <= 1
            zc = zone_counts(res)
            assert max(zc.values()) - min(zc.values()) <= 1, zc
        assert_node_parity(rg, rd, tol=1)

    def test_spread_plus_anti_affinity_pod(self):
        # zone spread + hostname self-anti-affinity on the same pod
        pods = [
            make_pod(cpu=0.5, spread_zone=True,
                     anti_affinity_to={"app": "spread"},
                     affinity_key=L.LABEL_HOSTNAME,
                     name=f"sa-{i}")
            for i in range(6)
        ]
        rg, rd = both_solve(pods)
        assert rg.all_pods_scheduled() and rd.all_pods_scheduled(), (
            rg.pod_errors, rd.pod_errors)
        for res in (rg, rd):
            for group in pods_per_node(res):
                assert len(group) <= 1  # anti: one per host
            zc = zone_counts(res)
            assert max(zc.values()) - min(zc.values()) <= 1, zc
        assert_node_parity(rg, rd, tol=1)


class TestInverseAntiAffinityDevice:
    @pytest.mark.parametrize("cls", [Scheduler, DeviceScheduler])
    def test_existing_guard_excludes_its_zone(self, cls):
        # an EXISTING pod with anti-affinity to app=web parks in zone-a; a
        # new app=web pod must land elsewhere even though it carries no
        # constraints of its own (topology.go:224-269 inverse topologies),
        # on the device path via the inverse owner/sel swap in topoplan
        from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
            Topology, domain_universe,
        )

        pool = three_zone_pool()
        existing_node = SimNode(
            name="existing-a",
            labels={
                L.NODEPOOL_LABEL_KEY: "default",
                L.LABEL_TOPOLOGY_ZONE: "zone-a",
            },
            taints=[],
            available={"cpu": 16.0, "memory": 32 * GIB, "pods": 100.0},
        )
        guard = make_pod(
            cpu=1.0, labels={"app": "guard"}, anti_affinity_to={"app": "web"}
        )
        guard.node_name = "existing-a"
        guard.phase = "Running"
        topo = Topology(
            domains=domain_universe(
                [pool], {"default": CATALOG}, [existing_node]
            ),
            existing_pods=[(guard, dict(existing_node.labels), "existing-a")],
        )
        kwargs = {"max_slots": 16} if cls is DeviceScheduler else {}
        s = cls([pool], {"default": CATALOG},
                existing_nodes=[existing_node], topology=topo, **kwargs)
        res = s.solve([make_pod(cpu=1.0, labels={"app": "web"}, name="web")])
        assert res.all_pods_scheduled(), res.pod_errors
        assert not res.existing_nodes[0].pods
        (claim,) = [c for c in res.new_node_claims if c.pods]
        assert not claim.requirements.get(L.LABEL_TOPOLOGY_ZONE).has("zone-a")
