"""Randomized differential fuzzing: greedy oracle vs device solver on
fully mixed scenarios — diverse pod shapes, topology constraints,
tolerated taints, node selectors, existing nodes with live capacity, and
PVC-backed volumes (SURVEY §4 blueprint item (a), widened to every
constraint family at once).

Invariants per seed:
* identical unschedulable-pod sets,
* pod conservation (every scheduled pod lands exactly once),
* node-count within the greedy-parity tolerance,
* constraint satisfaction checked on the DEVICE result directly
  (anti-affinity, taint tolerance, zone pins),
* the ResultVerifier (solver/verify.py) accepts the device result — the
  false-positive guard: verification runs inside every production solve,
  so a verifier that rejects legitimate packings silently degrades the
  whole fleet to greedy. The mutation battery below is its twin: every
  way of corrupting a VALID result must be rejected with the right
  reason, or the verifier is a no-op wearing a trust anchor's name.
"""
import copy
import random

import pytest

from tests.helpers import GIB, make_nodepool, make_pod, selector_for

from karpenter_core_tpu.utils.resources import pod_requests

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.objects import (
    CONTAINER_RESTART_ALWAYS,
    Container,
    NodeSelectorRequirement,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from karpenter_core_tpu.cloudprovider.kwok import build_catalog
from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
    SimNode,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
    Scheduler,
)
from karpenter_core_tpu.models.provisioner import DeviceScheduler

CATALOG = build_catalog(cpu_grid=[1, 2, 4, 8, 16], mem_factors=[2, 4])

ZONES = ("zone-a", "zone-b", "zone-c")


def random_pods(rng, n):
    pods = []
    for i in range(n):
        cpu = rng.choice([0.1, 0.25, 0.5, 1.0, 2.0, 4.0])
        mem = rng.choice([0.25, 0.5, 1.0, 2.0])
        kind = rng.randrange(12)
        kwargs = {}
        if kind == 1:
            kwargs["zone_in"] = rng.sample(ZONES, rng.randint(1, 2))
        elif kind == 2:
            kwargs["node_selector"] = {L.LABEL_OS: "linux"}
        elif kind == 3:
            kwargs["spread_zone"] = True
        elif kind == 4:
            kwargs["spread_hostname"] = True
        elif kind == 5:
            kwargs["labels"] = {"app": "anti"}
            kwargs["anti_affinity_to"] = {"app": "anti"}
            kwargs["affinity_key"] = L.LABEL_HOSTNAME
        elif kind == 6:
            kwargs["tolerations"] = [
                Toleration(key="batch", operator="Exists", effect="NoSchedule")
            ]
        pod = make_pod(cpu, mem, name=f"f{i}", **kwargs)
        # families beyond make_pod's surface (VERDICT r5 item 6 extension)
        if kind == 8:  # capacity-type / arch spread
            key = rng.choice([L.CAPACITY_TYPE_LABEL_KEY, L.LABEL_ARCH])
            pod.metadata.labels["app"] = "ctspread"
            pod.topology_spread_constraints = [TopologySpreadConstraint(
                max_skew=1, topology_key=key,
                when_unsatisfiable="DoNotSchedule",
                label_selector=selector_for({"app": "ctspread"}),
            )]
        elif kind == 9:  # soft zone spread (relaxation path)
            pod.metadata.labels["app"] = "softspread"
            pod.topology_spread_constraints = [TopologySpreadConstraint(
                max_skew=1, topology_key=L.LABEL_TOPOLOGY_ZONE,
                when_unsatisfiable="ScheduleAnyway",
                label_selector=selector_for({"app": "softspread"}),
            )]
        elif kind == 10:  # minDomains zone spread
            pod.metadata.labels["app"] = "mindom"
            pod.topology_spread_constraints = [TopologySpreadConstraint(
                max_skew=1, topology_key=L.LABEL_TOPOLOGY_ZONE,
                when_unsatisfiable="DoNotSchedule",
                label_selector=selector_for({"app": "mindom"}),
                min_domains=rng.choice([2, 3]),
            )]
        elif kind == 11:  # container-built twin of a flat pod
            pod.containers = [Container(
                resource_requests={"cpu": cpu / 2, "memory": mem * GIB})]
            pod.init_containers = [Container(
                resource_requests={"cpu": cpu / 2},
                restart_policy=CONTAINER_RESTART_ALWAYS,
            )]
            pod.resource_requests = pod_requests(pod)
        pods.append(pod)
    return pods


def random_existing(rng, k):
    nodes = []
    for i in range(k):
        zone = rng.choice(ZONES)
        cpu = rng.choice([4.0, 8.0, 16.0])
        nodes.append(SimNode(
            name=f"exist-{i}",
            labels={
                L.LABEL_TOPOLOGY_ZONE: zone,
                L.LABEL_HOSTNAME: f"exist-{i}",
                L.LABEL_OS: "linux",
                L.LABEL_ARCH: "amd64",
                L.CAPACITY_TYPE_LABEL_KEY: "on-demand",
                L.NODEPOOL_LABEL_KEY: "default",
            },
            taints=[Taint(key="batch", effect="NoSchedule")]
            if rng.random() < 0.3
            else [],
            available={
                "cpu": cpu * rng.uniform(0.3, 1.0),
                "memory": cpu * 2 * GIB,
                "pods": 110.0,
            },
            capacity={"cpu": cpu, "memory": cpu * 2 * GIB, "pods": 110.0},
            initialized=True,
        ))
    return nodes


def check_device_invariants(res, existing):
    # capacity: every fresh claim's cumulative requests fit at least one of
    # its surviving instance-type options (guards the one-sided node bound:
    # "denser than greedy" must come from packing, not dropped capacity)
    for c in res.new_node_claims:
        assert c.instance_type_options, c.requests
        fits_one = any(
            all(
                c.requests.get(name, 0.0) <= it.allocatable().get(name, 0.0)
                * (1 + 1e-9) + 1e-6
                for name in c.requests
            )
            for it in c.instance_type_options
        )
        assert fits_one, (c.requests, [it.name for it in c.instance_type_options])
    groups = [(c.requirements, list(c.pods), None) for c in res.new_node_claims]
    groups += [
        (s.requirements, list(s.pods), s.node) for s in res.existing_nodes
    ]
    for reqs, pods, node in groups:
        antis = [p for p in pods if p.metadata.labels.get("app") == "anti"]
        assert len(antis) <= 1, [p.name for p in antis]
        # hostname-spread skew: fresh nodes are always creatable so the
        # domain min floats at zero — per-node count <= maxSkew
        hspread = [
            p for p in pods
            if any(
                t.topology_key == L.LABEL_HOSTNAME
                for t in p.topology_spread_constraints
            )
        ]
        assert len(hspread) <= 1, [p.name for p in hspread]
        if node is not None and node.taints:
            from karpenter_core_tpu.scheduling import Taints

            for p in pods:
                assert not Taints(node.taints).tolerates(p), (
                    f"{p.name} intolerant of {node.name}"
                )
        zone_req = reqs.get(L.LABEL_TOPOLOGY_ZONE)
        for p in pods:
            if p.affinity and p.affinity.node_affinity:
                terms = p.affinity.node_affinity.required
                for term in terms[:1]:
                    for expr in term.match_expressions:
                        if expr.key == L.LABEL_TOPOLOGY_ZONE and zone_req:
                            allowed = set(expr.values)
                            assert set(zone_req.sorted_values()) <= allowed, (
                                p.name, zone_req, allowed
                            )


def fuzz_scenario(seed):
    rng = random.Random(1000 + seed)
    pods = random_pods(rng, rng.randint(30, 80))
    existing = random_existing(rng, rng.randint(0, 4))
    pools = [make_nodepool(requirements=[
        NodeSelectorRequirement(L.LABEL_TOPOLOGY_ZONE, "In", ZONES)
    ])]
    its = {"default": list(CATALOG)}
    return pods, existing, pools, its


@pytest.mark.parametrize("seed", range(14))
def test_fuzz_mixed_scenarios(seed):
    from karpenter_core_tpu.metrics import wiring as m

    pods, existing, pools, its = fuzz_scenario(seed)

    g = Scheduler(copy.deepcopy(pools), its,
                  existing_nodes=copy.deepcopy(existing))
    rg = g.solve(copy.deepcopy(pods))
    rejected_before = dict(m.SOLVER_RESULT_REJECTED.values)
    # verification ON (the production default): a fuzz seed that trips the
    # verifier is a false positive — the solve would silently degrade
    d = DeviceScheduler(copy.deepcopy(pools), its,
                        existing_nodes=copy.deepcopy(existing),
                        max_slots=128)
    rd = d.solve(copy.deepcopy(pods))
    assert dict(m.SOLVER_RESULT_REJECTED.values) == rejected_before, (
        "verifier false-positive on a legitimate device result"
    )

    assert set(rg.pod_errors) == set(rd.pod_errors), (
        rg.pod_errors, rd.pod_errors
    )
    placed_g = sum(len(c.pods) for c in rg.new_node_claims) + sum(
        len(s.pods) for s in rg.existing_nodes
    )
    placed_d = sum(len(c.pods) for c in rd.new_node_claims) + sum(
        len(s.pods) for s in rd.existing_nodes
    )
    assert placed_g == placed_d == len(pods) - len(rg.pod_errors)
    if rg.node_count():
        # one-sided: the host-floor-first class ordering lets the device
        # BEAT the oracle's node count; it must never be meaningfully worse
        assert rd.node_count() <= rg.node_count() + max(
            2, 0.2 * rg.node_count()
        ), f"greedy={rg.node_count()} device={rd.node_count()}"
    check_device_invariants(rd, existing)


# ---------------------------------------------------------------------------
# ResultVerifier: false-positive guard + mutation battery
# ---------------------------------------------------------------------------

from karpenter_core_tpu.solver.verify import ResultVerifier  # noqa: E402


@pytest.mark.parametrize("seed", range(14))
def test_verifier_accepts_every_fuzz_seed(seed):
    """Direct false-positive guard: BOTH solvers' results on every fuzz
    seed verify clean (the greedy oracle is feasible by construction, so a
    violation on its result is always a verifier bug)."""
    pods, existing, pools, its = fuzz_scenario(seed)

    d = DeviceScheduler(copy.deepcopy(pools), its,
                        existing_nodes=copy.deepcopy(existing),
                        max_slots=128, verify=False)
    dp = copy.deepcopy(pods)
    rd = d.solve(dp)
    violations = ResultVerifier(
        pools, its, existing_nodes=copy.deepcopy(existing)
    ).verify(rd, dp)
    assert not violations, [str(v) for v in violations]

    g = Scheduler(copy.deepcopy(pools), its,
                  existing_nodes=copy.deepcopy(existing))
    gp = copy.deepcopy(pods)
    rg = g.solve(gp)
    violations = ResultVerifier(
        pools, its, existing_nodes=copy.deepcopy(existing)
    ).verify(rg, gp)
    assert not violations, [str(v) for v in violations]


class TestVerifierMutations:
    """Corrupt a VALID device result in k distinct ways; each mutation
    class must be rejected with its own reason — the detection
    contract the chaos layer and the optimizing-backend roadmap item
    both lean on."""

    SEED = 1003  # a seed whose solve yields multiple multi-pod claims

    def _solved(self):
        pods, existing, pools, its = fuzz_scenario(self.SEED)
        d = DeviceScheduler(copy.deepcopy(pools), its,
                            existing_nodes=copy.deepcopy(existing),
                            max_slots=128, verify=False)
        sp = copy.deepcopy(pods)
        res = d.solve(sp)
        verifier = ResultVerifier(
            pools, its, existing_nodes=copy.deepcopy(existing)
        )
        # precondition: the unmutated result is clean
        assert not verifier.verify(res, sp)
        return res, sp, pools, its, existing

    def _reasons(self, verifier, res, sp):
        return {v.reason for v in verifier.verify(res, sp)}

    def test_dropped_pod_is_conservation(self):
        res, sp, pools, its, existing = self._solved()
        claim = next(c for c in res.new_node_claims if c.pods)
        claim.pods.pop()
        reasons = self._reasons(
            ResultVerifier(pools, its, existing_nodes=existing), res, sp
        )
        assert "conservation" in reasons, reasons

    def test_double_place_is_detected(self):
        res, sp, pools, its, existing = self._solved()
        donor = next(c for c in res.new_node_claims if c.pods)
        other = next(c for c in res.new_node_claims if c is not donor)
        other.pods.append(donor.pods[0])
        reasons = self._reasons(
            ResultVerifier(pools, its, existing_nodes=existing), res, sp
        )
        assert "double_place" in reasons, reasons

    def test_overpacked_node_is_capacity(self):
        res, sp, pools, its, existing = self._solved()
        claims = [c for c in res.new_node_claims if c.pods]
        assert len(claims) >= 2, "scenario must yield multiple claims"
        target = claims[0]
        for c in claims[1:]:
            target.pods.extend(c.pods)
            c.pods = []
        reasons = self._reasons(
            ResultVerifier(pools, its, existing_nodes=existing), res, sp
        )
        assert "capacity" in reasons, reasons

    def test_violated_zone_pin_is_selector(self):
        from karpenter_core_tpu.scheduling import Requirement

        res, sp, pools, its, existing = self._solved()
        mutated = False
        for c in res.new_node_claims:
            for p in c.pods:
                if not (p.affinity and p.affinity.node_affinity
                        and p.affinity.node_affinity.required):
                    continue
                exprs = [
                    e
                    for t in p.affinity.node_affinity.required
                    for e in t.match_expressions
                    if e.key == L.LABEL_TOPOLOGY_ZONE
                ]
                if exprs and len(exprs[0].values) < len(ZONES):
                    forbidden = sorted(
                        set(ZONES) - set(exprs[0].values)
                    )[0]
                    c.requirements[L.LABEL_TOPOLOGY_ZONE] = Requirement.new(
                        L.LABEL_TOPOLOGY_ZONE, "In", [forbidden]
                    )
                    mutated = True
                    break
            if mutated:
                break
        assert mutated, "scenario must contain a zone-pinned pod"
        reasons = self._reasons(
            ResultVerifier(pools, its, existing_nodes=existing), res, sp
        )
        assert "selector" in reasons, reasons

    def test_stale_offering_is_offering(self):
        res, sp, pools, its, existing = self._solved()
        claim = next(c for c in res.new_node_claims if c.pods)
        # ICE every offering of every surviving option AFTER the solve —
        # exactly the staleness shape: the packing references capacity
        # that stocked out between solve and verification
        iced = frozenset(
            o.key(it.name)
            for it in claim.instance_type_options
            for o in it.offerings
        )
        reasons = self._reasons(
            ResultVerifier(
                pools, its, existing_nodes=existing,
                unavailable_offerings=iced,
            ),
            res, sp,
        )
        assert "offering" in reasons, reasons

    def test_unknown_pod_uid_is_structure(self):
        res, sp, pools, its, existing = self._solved()
        claim = next(c for c in res.new_node_claims if c.pods)
        claim.pods.append(make_pod(cpu=0.1, name="stranger"))
        reasons = self._reasons(
            ResultVerifier(pools, its, existing_nodes=existing), res, sp
        )
        assert "structure" in reasons, reasons
