"""Differential tests: DeviceScheduler (TPU class-FFD solve) vs the greedy
host oracle. Node-count parity and zero constraint violations on identical
inputs (SURVEY.md §4 blueprint item (a))."""
import pytest

from helpers import GIB, make_diverse_pods, make_nodepool, make_pod

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.objects import NodeSelectorRequirement, Taint, Toleration
from karpenter_core_tpu.cloudprovider.kwok import build_catalog
from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import SimNode
from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import Scheduler
from karpenter_core_tpu.models.provisioner import DeviceScheduler

CATALOG = build_catalog(cpu_grid=[1, 2, 4, 8, 16], mem_factors=[2, 4])  # 40 types


def both(nodepools=None, existing=None, daemons=None, catalog=None):
    nodepools = nodepools or [make_nodepool()]
    catalog = catalog or CATALOG
    its = {np.name: list(catalog) for np in nodepools}
    greedy = Scheduler(nodepools, its, existing_nodes=existing, daemonset_pods=daemons)
    device = DeviceScheduler(
        nodepools, its, existing_nodes=existing, daemonset_pods=daemons,
        max_slots=64,
    )
    return greedy, device


def assert_parity(pods_factory, nodepools=None, existing=None, exact=True):
    import copy

    greedy, device = both(
        nodepools=copy.deepcopy(nodepools) if nodepools else None,
        existing=copy.deepcopy(existing) if existing else None,
    )
    g = greedy.solve(pods_factory())
    d = device.solve(pods_factory())
    assert g.all_pods_scheduled() == d.all_pods_scheduled(), (
        f"scheduled mismatch: greedy={g.pod_errors} device={d.pod_errors}"
    )
    if exact:
        assert g.node_count() == d.node_count(), (
            f"node count: greedy={g.node_count()} device={d.node_count()}"
        )
    # pods conservation
    g_pods = sum(len(c.pods) for c in g.new_node_claims) + sum(
        len(n.pods) for n in g.existing_nodes
    )
    d_pods = sum(len(c.pods) for c in d.new_node_claims) + sum(
        len(n.pods) for n in d.existing_nodes
    )
    assert g_pods == d_pods
    return g, d


class TestParityBasic:
    def test_single_pod(self):
        assert_parity(lambda: [make_pod(cpu=1.0)])

    def test_homogeneous_small(self):
        assert_parity(
            lambda: [make_pod(cpu=0.5, memory_gib=1.0, name=f"p{i}") for i in range(50)]
        )

    def test_homogeneous_large_batch(self):
        assert_parity(
            lambda: [make_pod(cpu=2.0, memory_gib=2.0, name=f"p{i}") for i in range(500)]
        )

    def test_two_sizes(self):
        def pods():
            return [make_pod(cpu=4.0, name=f"big{i}") for i in range(20)] + [
                make_pod(cpu=0.25, name=f"small{i}") for i in range(100)
            ]

        assert_parity(pods)

    def test_unschedulable_huge_pod(self):
        g, d = assert_parity(lambda: [make_pod(cpu=10000.0)])
        assert not d.all_pods_scheduled()


class TestParityRequirements:
    def test_arch_selector(self):
        assert_parity(
            lambda: [
                make_pod(node_selector={L.LABEL_ARCH: "arm64"}, name=f"p{i}")
                for i in range(30)
            ]
        )

    def test_zone_partition(self):
        def pods():
            out = []
            for i in range(30):
                out.append(make_pod(cpu=0.5, zone_in=["zone-a"], name=f"a{i}"))
                out.append(make_pod(cpu=0.5, zone_in=["zone-b"], name=f"b{i}"))
            return out

        assert_parity(pods)

    def test_mixed_constrained_unconstrained(self):
        def pods():
            return (
                [make_pod(cpu=1.0, name=f"free{i}") for i in range(25)]
                + [
                    make_pod(
                        cpu=1.0,
                        node_selector={L.LABEL_OS: "linux"},
                        name=f"lin{i}",
                    )
                    for i in range(25)
                ]
            )

        assert_parity(pods)

    def test_nodepool_requirements(self):
        np_ = make_nodepool(
            requirements=[
                NodeSelectorRequirement(L.LABEL_ARCH, "In", ("amd64",)),
                NodeSelectorRequirement(
                    L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a", "zone-b")
                ),
            ]
        )
        assert_parity(
            lambda: [make_pod(cpu=1.0, name=f"p{i}") for i in range(40)],
            nodepools=[np_],
        )

    def test_custom_label_nodepool(self):
        np_ = make_nodepool()
        np_.spec.template.labels = {"mycompany.io/team": "infra"}
        assert_parity(
            lambda: [
                make_pod(
                    node_selector={"mycompany.io/team": "infra"}, name=f"p{i}"
                )
                for i in range(10)
            ]
            + [make_pod(name=f"q{i}") for i in range(10)],
            nodepools=[np_],
        )

    def test_incompatible_selector_fails_both(self):
        g, d = assert_parity(
            lambda: [make_pod(node_selector={L.LABEL_ARCH: "riscv"})]
        )
        assert not d.all_pods_scheduled()


class TestParityTaints:
    def test_tainted_pool(self):
        np_ = make_nodepool(
            taints=[Taint(key="dedicated", value="ml", effect="NoSchedule")]
        )
        tol = [Toleration(key="dedicated", operator="Equal", value="ml")]
        assert_parity(
            lambda: [make_pod(tolerations=tol, name=f"t{i}") for i in range(10)]
            + [make_pod(name=f"n{i}") for i in range(5)],
            nodepools=[np_],
        )

    def test_two_pools_taint_split(self):
        plain = make_nodepool("plain")
        tainted = make_nodepool(
            "tainted", taints=[Taint(key="gpu", value="", effect="NoSchedule")]
        )
        assert_parity(
            lambda: [make_pod(name=f"p{i}") for i in range(20)],
            nodepools=[plain, tainted],
        )


class TestParityExisting:
    def _nodes(self, n=2, cpu=8.0):
        return [
            SimNode(
                name=f"existing-{i}",
                labels={
                    L.LABEL_ARCH: "amd64",
                    L.LABEL_OS: "linux",
                    L.LABEL_TOPOLOGY_ZONE: "zone-a",
                    L.NODEPOOL_LABEL_KEY: "default",
                    L.LABEL_INSTANCE_TYPE: "s-8x-amd64-linux",
                },
                taints=[],
                available={"cpu": cpu, "memory": 16 * GIB, "pods": 100.0},
                capacity={"cpu": cpu, "memory": 16 * GIB, "pods": 110.0},
            )
            for i in range(n)
        ]

    def test_fill_existing_first(self):
        assert_parity(
            lambda: [make_pod(cpu=1.0, name=f"p{i}") for i in range(10)],
            existing=self._nodes(),
        )

    def test_overflow_to_new(self):
        assert_parity(
            lambda: [make_pod(cpu=2.0, name=f"p{i}") for i in range(30)],
            existing=self._nodes(),
        )

    def test_tainted_existing_skipped(self):
        nodes = self._nodes(1)
        nodes[0].taints = [Taint(key="x", effect="NoSchedule")]
        assert_parity(
            lambda: [make_pod(cpu=1.0, name=f"p{i}") for i in range(5)],
            existing=nodes,
        )


class TestRegressions:
    def test_relaxation_keeps_earlier_placements(self):
        # 8 plain pods + 1 pod with unsatisfiable preferred affinity: the
        # relax round must re-solve the world, not just the failed pod
        from karpenter_core_tpu.api.objects import (
            Affinity,
            NodeAffinity,
            NodeSelectorRequirement,
            NodeSelectorTerm,
            PreferredSchedulingTerm,
        )

        plain = [make_pod(cpu=0.5, name=f"plain{i}") for i in range(8)]
        fussy = make_pod(cpu=0.5, name="fussy")
        fussy.affinity = Affinity(
            node_affinity=NodeAffinity(
                preferred=[
                    PreferredSchedulingTerm(
                        weight=1,
                        preference=NodeSelectorTerm(
                            match_expressions=(
                                NodeSelectorRequirement(
                                    L.LABEL_TOPOLOGY_ZONE, "In", ("nope",)
                                ),
                            )
                        ),
                    )
                ]
            )
        )
        device = both()[1]
        res = device.solve(plain + [fussy])
        assert res.all_pods_scheduled(), res.pod_errors
        placed = sum(len(c.pods) for c in res.new_node_claims) + sum(
            len(n.pods) for n in res.existing_nodes
        )
        assert placed == 9

    def test_empty_catalog_with_existing_nodes(self):
        nodes = [
            SimNode(
                name="only",
                labels={L.NODEPOOL_LABEL_KEY: "default"},
                taints=[],
                available={"cpu": 4.0, "memory": 8 * GIB, "pods": 10.0},
            )
        ]
        device = DeviceScheduler(
            [make_nodepool()], {"default": []}, existing_nodes=nodes, max_slots=8
        )
        res = device.solve([make_pod(cpu=1.0)])
        assert res.all_pods_scheduled(), res.pod_errors
        assert res.node_count() == 0
        assert len(res.existing_nodes[0].pods) == 1

    def test_more_existing_nodes_than_slots_grows(self):
        nodes = [
            SimNode(
                name=f"n{i}",
                labels={L.NODEPOOL_LABEL_KEY: "default"},
                taints=[],
                available={"cpu": 4.0, "memory": 8 * GIB, "pods": 10.0},
            )
            for i in range(3)
        ]
        device = DeviceScheduler(
            [make_nodepool()], {"default": CATALOG}, existing_nodes=nodes,
            max_slots=2,
        )
        res = device.solve([make_pod(cpu=1.0)])
        assert res.all_pods_scheduled(), res.pod_errors


class TestParityScale:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_diverse_mix(self, seed):
        # node counts may differ slightly (first-fit vs emptiest-first within
        # class); require <= 10% deviation and full schedulability
        import copy

        pods = make_diverse_pods(300, seed=seed)
        greedy, device = both()
        g = greedy.solve(copy.deepcopy(pods))
        d = device.solve(copy.deepcopy(pods))
        assert g.all_pods_scheduled()
        assert d.all_pods_scheduled()
        assert abs(g.node_count() - d.node_count()) <= max(
            1, int(0.1 * g.node_count())
        ), f"greedy={g.node_count()} device={d.node_count()}"

    def test_no_divergence_failures(self):
        device = both()[1]
        res = device.solve(make_diverse_pods(200, seed=7))
        assert not any(
            "divergence" in msg for msg in res.pod_errors.values()
        ), res.pod_errors


class TestHostPorts:
    """hostPort pods must take the per-pod add() path with HostPortUsage
    conflict checks (nodeclaim.go add path); the class signature therefore
    separates pods by host_ports (ADVICE r1 #1)."""

    def test_hostport_pods_form_own_class(self):
        from karpenter_core_tpu.solver.snapshot import group_pods

        a = make_pod(cpu=1.0, name="plain")
        b = make_pod(cpu=1.0, name="ported")
        b.host_ports = [("", 80, "TCP")]
        assert len(group_pods([a, b])) == 2

    def test_same_hostport_never_coplaced(self):
        def pods():
            out = []
            for i in range(3):
                p = make_pod(cpu=0.1, name=f"hp{i}")
                p.host_ports = [("", 8080, "TCP")]
                out.append(p)
            # identical port-free twins that must NOT absorb the ported ones
            out.extend(make_pod(cpu=0.1, name=f"plain{i}") for i in range(3))
            return out

        g, d = assert_parity(pods)
        for res in (g, d):
            for claim in res.new_node_claims:
                ported = sum(1 for p in claim.pods if p.host_ports)
                assert ported <= 1, [p.metadata.name for p in claim.pods]


class TestDaemonOverheadParity:
    def test_daemon_overhead_reduces_fresh_capacity(self):
        # a fat daemonset pod joins every fresh node's requests
        # (scheduler.go:358-364 -> the kernel's tmpl_overhead tensor);
        # both solvers must open the same number of nodes
        daemon = make_pod(cpu=3.0, memory_gib=2.0, name="ds")
        daemon.is_daemonset = True
        pods_factory = lambda: [
            make_pod(cpu=4.0, memory_gib=1.0, name=f"w{i}") for i in range(8)
        ]
        import copy

        its = {"default": list(CATALOG)}
        g = Scheduler([make_nodepool()], its,
                      daemonset_pods=[copy.deepcopy(daemon)])
        rg = g.solve(pods_factory())
        d = DeviceScheduler([make_nodepool()], its,
                            daemonset_pods=[copy.deepcopy(daemon)],
                            max_slots=64)
        rd = d.solve(pods_factory())
        assert rg.all_pods_scheduled() and rd.all_pods_scheduled()
        assert rg.node_count() == rd.node_count()
        # the daemon's cpu is reserved: 16-cpu nodes fit 3 workers (12+3=15)
        # not 4 (16+3=19)
        for c in rd.new_node_claims:
            assert c.requests["cpu"] <= max(
                it.allocatable()["cpu"] for it in c.instance_type_options
            ) + 1e-9

    def test_intolerant_daemon_excluded_from_tainted_pool(self):
        # the daemon does not tolerate the pool taint -> no overhead there
        # (_daemon_compatible, scheduler.go:366-386)
        daemon = make_pod(cpu=3.0, name="ds")
        daemon.is_daemonset = True
        pool = make_nodepool(
            name="tainted",
            taints=[Taint(key="batch", value="", effect="NoSchedule")],
        )
        pods = [
            make_pod(
                cpu=4.0,
                name=f"w{i}",
                tolerations=[Toleration(
                    key="batch", operator="Exists", effect="NoSchedule"
                )],
            )
            for i in range(4)
        ]
        import copy

        its = {"tainted": list(CATALOG)}
        # baseline: no daemonset at all
        g0 = Scheduler([copy.deepcopy(pool)], its)
        r0 = g0.solve(copy.deepcopy(pods))
        g = Scheduler([copy.deepcopy(pool)], its,
                      daemonset_pods=[copy.deepcopy(daemon)])
        rg = g.solve(copy.deepcopy(pods))
        d = DeviceScheduler([copy.deepcopy(pool)], its,
                            daemonset_pods=[copy.deepcopy(daemon)],
                            max_slots=64)
        rd = d.solve(copy.deepcopy(pods))
        assert r0.all_pods_scheduled()
        assert rg.all_pods_scheduled() and rd.all_pods_scheduled()
        # the intolerant daemon contributes NO overhead: both solvers match
        # the daemonless baseline exactly
        assert rg.node_count() == rd.node_count() == r0.node_count()
        for c in rd.new_node_claims:
            assert all(v == 0.0 for v in c.daemon_resources.values())

    def test_overhead_exceeding_type_never_pollutes_itmask(self):
        # regression (r4 review): an instance type whose allocatable cannot
        # even hold the daemon overhead on a dim the pod class does not
        # request must not survive in a fresh slot's viable set — it would
        # later win the per-IT headroom max for cpu-only classes and
        # over-commit the slot, mass-deferring pods to the host fallback
        from karpenter_core_tpu.metrics import wiring as m
        from karpenter_core_tpu.cloudprovider.types import (
            InstanceType,
            Offering,
            Offerings,
        )
        from karpenter_core_tpu.scheduling import Requirements

        def it(name, cpu, mem_gib):
            reqs = Requirements.from_labels({
                L.LABEL_INSTANCE_TYPE: name,
                L.LABEL_ARCH: "amd64",
                L.LABEL_OS: "linux",
            })
            return InstanceType(
                name=name,
                requirements=reqs,
                offerings=Offerings([
                    Offering(
                        requirements=Requirements.from_labels({
                            L.LABEL_TOPOLOGY_ZONE: "zone-a",
                            L.CAPACITY_TYPE_LABEL_KEY: "on-demand",
                        }),
                        price=cpu * 0.01,
                        available=True,
                    )
                ]),
                capacity={"cpu": float(cpu), "memory": mem_gib * GIB,
                          "pods": 110.0},
            )

        catalog = [it("big-cpu-tiny-mem", 100, 1.2), it("balanced", 16, 32.0)]
        daemon = make_pod(cpu=0.5, memory_gib=2.0, name="ds")
        daemon.is_daemonset = True
        pods = [
            make_pod(cpu=3.0, memory_gib=0.2, name=f"a{i}") for i in range(4)
        ] + [
            make_pod(cpu=1.0, memory_gib=0.001, name=f"b{i}")
            for i in range(10)
        ]
        before = sum(m.SOLVER_HOST_FALLBACK_PODS.values.values())
        d = DeviceScheduler(
            [make_nodepool()], {"default": catalog},
            daemonset_pods=[daemon], max_slots=64,
        )
        res = d.solve(pods)
        assert res.all_pods_scheduled(), res.pod_errors
        after = sum(m.SOLVER_HOST_FALLBACK_PODS.values.values())
        assert after == before, "device placement regressed to host fallback"
        for c in res.new_node_claims:
            for t in c.instance_type_options:
                assert t.allocatable()["memory"] >= 2.0 * GIB


class TestDeviceLimits:
    def test_limit_overflow_is_visible_not_silent(self):
        # limits are enforced at claim-creation time on the device path:
        # the overflow pods stay pending WITH FailedScheduling events
        # (never a silent livelock), and the launched claims respect the
        # pool limit
        from tests.test_e2e import new_operator, replicated
        from karpenter_core_tpu.api.objects import Node

        for solver in ("greedy", "tpu"):
            op = new_operator(solver)
            op.kube.create(make_nodepool(limits={"cpu": 32.0}))
            for i in range(6):
                op.kube.create(replicated(make_pod(cpu=9.0, name=f"p{i}")))
            op.run_until_idle()
            nodes = op.kube.list_nodes()
            total_cpu = sum(
                n.status.capacity.get("cpu", 0.0) for n in nodes
            )
            assert total_cpu <= 32.0 + 1e-9, (solver, total_cpu)
            bound = [p for p in op.kube.list_pods() if p.node_name]
            pending = [p for p in op.kube.list_pods() if not p.node_name]
            assert pending, solver
            assert len(bound) + len(pending) == 6
            # the overflow surfaced: FailedScheduling events exist
            failures = op.recorder.with_reason("FailedScheduling")
            assert failures, f"{solver}: limit overflow was silent"

    def test_no_limits_unbounded_parity(self):
        assert_parity(lambda: [make_pod(cpu=9.0, name=f"p{i}")
                               for i in range(6)])


class TestWeightedPoolsDevice:
    def test_weighted_pool_preferred_on_device(self):
        plain = make_nodepool("plain", weight=0)
        preferred = make_nodepool("preferred", weight=10)
        its = {"plain": list(CATALOG), "preferred": list(CATALOG)}
        d = DeviceScheduler([plain, preferred], its, max_slots=16)
        res = d.solve([make_pod(cpu=1.0, name="p0")])
        assert res.all_pods_scheduled(), res.pod_errors
        assert res.new_node_claims[0].template.nodepool_name == "preferred"

    def test_weight_ties_break_by_name(self):
        # equal weights: template order falls back to pool name
        a = make_nodepool("a-pool", weight=5)
        b = make_nodepool("b-pool", weight=5)
        its = {"a-pool": list(CATALOG), "b-pool": list(CATALOG)}
        for cls in (Scheduler, DeviceScheduler):
            kwargs = {"max_slots": 16} if cls is DeviceScheduler else {}
            s = cls([b, a], its, **kwargs)
            res = s.solve([make_pod(cpu=1.0, name="p0")])
            assert res.all_pods_scheduled()
            assert res.new_node_claims[0].template.nodepool_name == "a-pool"
