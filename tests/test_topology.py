"""Topology spread / pod-affinity / anti-affinity semantics, mirroring the
reference's topology suite scenarios
(reference: pkg/controllers/provisioning/scheduling/topology_test.go)."""
import pytest

from tests.helpers import GIB, make_diverse_pods, make_nodepool, make_pod

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.objects import (
    NodeSelectorRequirement,
    ObjectMeta,
    Pod,
)
from karpenter_core_tpu.cloudprovider.kwok import build_catalog
from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import SimNode
from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import Scheduler
from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
    Topology,
    domain_universe,
)
from karpenter_core_tpu.models.provisioner import DeviceScheduler

CATALOG = build_catalog(cpu_grid=[1, 2, 4, 8, 16], mem_factors=[2, 4])

THREE_ZONES = NodeSelectorRequirement(
    L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a", "zone-b", "zone-c")
)


def three_zone_pool():
    return make_nodepool(requirements=[THREE_ZONES])


def claim_zone(claim) -> str:
    req = claim.requirements.get(L.LABEL_TOPOLOGY_ZONE)
    vals = req.sorted_values()
    assert len(vals) == 1, f"zone not collapsed: {req!r}"
    return vals[0]


def zone_counts(res) -> dict:
    counts = {}
    for claim in res.new_node_claims:
        counts[claim_zone(claim)] = counts.get(claim_zone(claim), 0) + len(claim.pods)
    for sim in res.existing_nodes:
        if sim.pods:
            z = sim.node.labels.get(L.LABEL_TOPOLOGY_ZONE)
            counts[z] = counts.get(z, 0) + len(sim.pods)
    return counts


class TestZoneSpread:
    def test_even_spread_across_zones(self):
        s = Scheduler([three_zone_pool()], {"default": CATALOG})
        res = s.solve([make_pod(cpu=1.0, spread_zone=True) for _ in range(9)])
        assert res.all_pods_scheduled(), res.pod_errors
        assert zone_counts(res) == {"zone-a": 3, "zone-b": 3, "zone-c": 3}

    def test_max_skew_two(self):
        s = Scheduler([three_zone_pool()], {"default": CATALOG})
        res = s.solve(
            [make_pod(cpu=1.0, spread_zone=True, max_skew=2) for _ in range(4)]
        )
        assert res.all_pods_scheduled(), res.pod_errors
        counts = zone_counts(res)
        assert max(counts.values()) - min(counts.values() or [0]) <= 2

    def test_spread_counts_only_selected_pods(self):
        # unselected pods (different app label) don't count toward skew
        s = Scheduler([three_zone_pool()], {"default": CATALOG})
        spread = [
            make_pod(cpu=1.0, labels={"app": "web"}, spread_zone=True)
            for _ in range(3)
        ]
        others = [make_pod(cpu=1.0) for _ in range(6)]
        res = s.solve(spread + others)
        assert res.all_pods_scheduled(), res.pod_errors

    def test_zone_spread_respects_node_affinity_filter(self):
        # pod restricted to zone-a+b spreads over those two only
        s = Scheduler([three_zone_pool()], {"default": CATALOG})
        res = s.solve(
            [
                make_pod(
                    cpu=1.0, spread_zone=True, zone_in=["zone-a", "zone-b"]
                )
                for _ in range(4)
            ]
        )
        assert res.all_pods_scheduled(), res.pod_errors
        counts = zone_counts(res)
        assert set(counts) == {"zone-a", "zone-b"}
        assert counts["zone-a"] == counts["zone-b"] == 2


class TestHostnameSpread:
    def test_one_pod_per_node(self):
        s = Scheduler([make_nodepool()], {"default": CATALOG})
        res = s.solve([make_pod(cpu=1.0, spread_hostname=True) for _ in range(5)])
        assert res.all_pods_scheduled(), res.pod_errors
        assert res.node_count() == 5
        assert all(len(c.pods) == 1 for c in res.new_node_claims)


class TestPodAffinity:
    def test_self_affinity_single_zone(self):
        s = Scheduler([three_zone_pool()], {"default": CATALOG})
        res = s.solve(
            [
                make_pod(
                    cpu=1.0, labels={"app": "db"}, affinity_to={"app": "db"}
                )
                for _ in range(4)
            ]
        )
        assert res.all_pods_scheduled(), res.pod_errors
        assert len(zone_counts(res)) == 1  # all co-located

    def test_affinity_follows_committed_target(self):
        # web pods co-locate with the db pod, whose zone IS determined
        # (zone_in pins it); db schedules first (bigger cpu sorts first)
        s = Scheduler([three_zone_pool()], {"default": CATALOG})
        db = make_pod(cpu=4.0, labels={"app": "db"}, zone_in=["zone-b"])
        webs = [
            make_pod(cpu=1.0, labels={"app": "web"}, affinity_to={"app": "db"})
            for _ in range(3)
        ]
        res = s.solve([db] + webs)
        assert res.all_pods_scheduled(), res.pod_errors
        assert set(zone_counts(res)) == {"zone-b"}

    def test_affinity_to_uncommitted_target_fails(self):
        # late committal: the target's zone is undetermined within the batch,
        # so affinity pods cannot schedule (topology_test.go "unconstrained
        # target")
        s = Scheduler([three_zone_pool()], {"default": CATALOG})
        db = make_pod(cpu=4.0, labels={"app": "db"})
        webs = [
            make_pod(cpu=1.0, labels={"app": "web"}, affinity_to={"app": "db"})
            for _ in range(2)
        ]
        res = s.solve([db] + webs)
        assert len(res.pod_errors) == 2

    def test_affinity_to_absent_target_fails(self):
        s = Scheduler([three_zone_pool()], {"default": CATALOG})
        res = s.solve(
            [
                make_pod(cpu=1.0, affinity_to={"app": "nonexistent"})
                for _ in range(3)
            ]
        )
        assert len(res.pod_errors) == 3


class TestPodAntiAffinity:
    def test_hostname_anti_affinity_separates_nodes(self):
        # hostname domains are single-valued per claim, so self anti-affinity
        # on hostname fully resolves in one batch (topology_test.go:1764)
        s = Scheduler([make_nodepool()], {"default": CATALOG})
        res = s.solve(
            [
                make_pod(
                    cpu=1.0,
                    labels={"app": "aa"},
                    anti_affinity_to={"app": "aa"},
                    affinity_key=L.LABEL_HOSTNAME,
                )
                for _ in range(4)
            ]
        )
        assert res.all_pods_scheduled(), res.pod_errors
        assert res.node_count() == 4
        assert all(len(c.pods) == 1 for c in res.new_node_claims)

    def test_zone_anti_affinity_late_committal(self):
        # a zone-anti pod's claim could land in any zone, so it blocks all of
        # them for this batch: only one of three schedules
        # (topology_test.go:2132 "pod anti-affinity with a zone topology")
        s = Scheduler([three_zone_pool()], {"default": CATALOG})
        res = s.solve(
            [
                make_pod(
                    cpu=1.0, labels={"app": "aa"}, anti_affinity_to={"app": "aa"}
                )
                for _ in range(3)
            ]
        )
        assert len(res.pod_errors) == 2
        assert "anti-affinity" in next(iter(res.pod_errors.values()))

    def test_zone_anti_affinity_committed_zones_resolve(self):
        # when each anti pod pins its own zone, all three schedule distinctly
        s = Scheduler([three_zone_pool()], {"default": CATALOG})
        res = s.solve(
            [
                make_pod(
                    cpu=1.0,
                    labels={"app": "aa"},
                    anti_affinity_to={"app": "aa"},
                    zone_in=[z],
                )
                for z in ["zone-a", "zone-b", "zone-c"]
            ]
        )
        assert res.all_pods_scheduled(), res.pod_errors
        assert set(zone_counts(res)) == {"zone-a", "zone-b", "zone-c"}

    def test_inverse_anti_affinity_blocks_new_pod(self):
        # an EXISTING pod with anti-affinity to app=web parks in zone-a; a new
        # app=web pod must land elsewhere even though it has no constraints
        # (topology.go:224-269 inverse topologies)
        pool = three_zone_pool()
        existing_node = SimNode(
            name="existing-a",
            labels={
                L.NODEPOOL_LABEL_KEY: "default",
                L.LABEL_TOPOLOGY_ZONE: "zone-a",
            },
            taints=[],
            available={"cpu": 16.0, "memory": 32 * GIB, "pods": 100.0},
        )
        guard = make_pod(
            cpu=1.0, labels={"app": "guard"}, anti_affinity_to={"app": "web"}
        )
        guard.node_name = "existing-a"
        guard.phase = "Running"
        topo = Topology(
            domains=domain_universe([pool], {"default": CATALOG}, [existing_node]),
            existing_pods=[(guard, dict(existing_node.labels), "existing-a")],
        )
        s = Scheduler(
            [pool], {"default": CATALOG},
            existing_nodes=[existing_node], topology=topo,
        )
        res = s.solve([make_pod(cpu=1.0, labels={"app": "web"})])
        assert res.all_pods_scheduled(), res.pod_errors
        # placed on a new claim whose admissible zones exclude zone-a
        assert not res.existing_nodes[0].pods
        (claim,) = res.new_node_claims
        assert not claim.requirements.get(L.LABEL_TOPOLOGY_ZONE).has("zone-a")


class TestRelaxation:
    def test_schedule_anyway_spread_relaxes(self):
        # 1-zone pool, ScheduleAnyway zone spread with impossible skew across
        # registered domains relaxes away (preferences.go:38-57)
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(L.LABEL_TOPOLOGY_ZONE, "In", ("zone-a",))
            ]
        )
        s = Scheduler([pool], {"default": CATALOG})
        pods = []
        for _ in range(3):
            p = make_pod(cpu=1.0, spread_zone=True)
            pods.append(p)
        # make the constraint soft
        for p in pods:
            p.topology_spread_constraints = [
                type(p.topology_spread_constraints[0])(
                    max_skew=1,
                    topology_key=L.LABEL_TOPOLOGY_ZONE,
                    when_unsatisfiable="ScheduleAnyway",
                    label_selector=p.topology_spread_constraints[0].label_selector,
                )
            ]
        res = s.solve(pods)
        assert res.all_pods_scheduled(), res.pod_errors


class TestDeviceParityTopology:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_diverse_topology_mix(self, seed):
        import copy

        pods = make_diverse_pods(60, seed=seed, with_topology=True)
        g = Scheduler([three_zone_pool()], {"default": CATALOG})
        rg = g.solve(copy.deepcopy(pods))
        d = DeviceScheduler([three_zone_pool()], {"default": CATALOG}, max_slots=64)
        rd = d.solve(copy.deepcopy(pods))
        assert set(rg.pod_errors) == set(rd.pod_errors), (
            rg.pod_errors,
            rd.pod_errors,
        )
        if rg.node_count():
            assert abs(rd.node_count() - rg.node_count()) <= max(
                2, 0.15 * rg.node_count()
            )
