"""Periphery controllers: expiration, GC, consistency, nodepool
counter/hash/readiness/validation, node health, events, metrics
(reference: SURVEY.md §2.8-2.9 inventory)."""
import pytest

from tests.helpers import GIB, make_nodepool, make_pod
from tests.test_e2e import new_operator, replicated

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.duration import NillableDuration
from karpenter_core_tpu.api.nodeclaim import COND_CONSISTENT_STATE_FOUND, NodeClaim
from karpenter_core_tpu.api.nodepool import (
    COND_NODEPOOL_VALIDATION_SUCCEEDED,
    NodePool,
)
from karpenter_core_tpu.api.objects import (
    Node,
    NodeSelectorRequirement,
    Pod,
    Taint,
)
from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_core_tpu.cloudprovider.types import RepairPolicy
from karpenter_core_tpu.events import Event, Recorder
from karpenter_core_tpu.kube.store import KubeStore
from karpenter_core_tpu.metrics import Registry
from karpenter_core_tpu.operator import Operator, Options
from karpenter_core_tpu.utils.clock import FakeClock


class TestExpiration:
    def test_expired_claim_replaced(self):
        op = new_operator()
        pool = make_nodepool()
        pool.spec.template.expire_after = NillableDuration(3600.0)
        op.kube.create(pool)
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle(disrupt=False)
        (claim,) = op.kube.list_nodeclaims()
        op.clock.step(3601.0)
        op.run_until_idle(disrupt=False)
        claims = op.kube.list_nodeclaims()
        assert all(c.name != claim.name for c in claims)
        # pod rescheduled onto the replacement
        assert op.kube.get(Pod, "p0").node_name

    def test_never_expires_by_default(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle(disrupt=False)
        op.clock.step(365 * 24 * 3600.0)
        op.run_until_idle(disrupt=False)
        assert len(op.kube.list_nodeclaims()) == 1


class TestGarbageCollection:
    def test_claim_with_vanished_instance_removed(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle(disrupt=False)
        (claim,) = op.kube.list_nodeclaims()
        # instance vanishes behind karpenter's back
        node = op.kube.get_node_by_provider_id(claim.status.provider_id)
        node.metadata.finalizers = []
        op.kube.delete(node)
        op.clock.step(121.0)  # next 2-minute GC sweep
        op.run_until_idle(disrupt=False)
        assert all(
            c.name != claim.name for c in op.kube.list_nodeclaims()
        )


class TestConsistency:
    def test_shrunk_capacity_flagged(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle(disrupt=False)
        (claim,) = op.kube.list_nodeclaims()
        node = op.kube.get(Node, claim.status.node_name)
        node.status.capacity["cpu"] = node.status.capacity["cpu"] / 2
        op.reconcile_once(disrupt=False)
        assert claim.conditions.is_false(COND_CONSISTENT_STATE_FOUND)
        assert op.recorder.with_reason("FailedConsistencyCheck")


class TestNodePoolControllers:
    def test_counter_aggregates_capacity(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle(disrupt=False)
        pool = op.kube.list_nodepools()[0]
        assert pool.status.resources.get("nodes") == 1.0
        assert pool.status.resources.get("cpu", 0) > 0

    def test_hash_annotation_maintained(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.reconcile_once(disrupt=False)
        pool = op.kube.list_nodepools()[0]
        assert (
            pool.metadata.annotations[L.NODEPOOL_HASH_ANNOTATION_KEY]
            == pool.static_hash()
        )

    def test_invalid_pool_not_provisioned_from(self):
        op = new_operator()
        pool = make_nodepool(
            requirements=[NodeSelectorRequirement("team", "In", ())]  # invalid
        )
        op.kube.create(pool)
        op.kube.create(make_pod(cpu=1.0, name="p0"))
        op.run_until_idle(disrupt=False)
        assert pool.conditions.is_false(COND_NODEPOOL_VALIDATION_SUCCEEDED)
        assert not op.kube.list_nodes()


class TestNodeHealth:
    def test_unhealthy_node_repaired_after_toleration(self):
        op = new_operator()
        op.options.feature_gates["NodeRepair"] = True
        op.node_health.enabled = True
        op.cloud_provider.repair_policies = lambda: [
            RepairPolicy(
                condition_type="Ready",
                condition_status="False",
                toleration_duration=600.0,
            )
        ]
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle(disrupt=False)
        (node,) = op.kube.list_nodes()
        node.status.conditions = [("Ready", "False")]
        op.reconcile_once(disrupt=False)  # first observation starts the window
        assert op.kube.get(Node, node.name) is not None
        op.clock.step(601.0)
        op.run_until_idle(disrupt=False)
        # node + claim torn down; replacement comes up for the pod
        assert node.name not in {n.name for n in op.kube.list_nodes()}

    def test_circuit_breaker_blocks_mass_repair(self):
        op = new_operator()
        op.node_health.enabled = True
        op.cloud_provider.repair_policies = lambda: [
            RepairPolicy(
                condition_type="Ready",
                condition_status="False",
                toleration_duration=0.0,
            )
        ]
        op.kube.create(make_nodepool())
        for i in range(4):
            op.kube.create(replicated(make_pod(cpu=7.0, name=f"p{i}")))
        op.run_until_idle(disrupt=False)
        nodes = op.kube.list_nodes()
        assert len(nodes) >= 2
        # everything goes unhealthy at once: systemic, don't repair
        for n in nodes:
            n.status.conditions = [("Ready", "False")]
        op.clock.step(1.0)
        op.reconcile_once(disrupt=False)
        op.reconcile_once(disrupt=False)
        assert len(op.kube.list_nodes()) == len(nodes)


class TestEventsAndMetrics:
    def test_recorder_dedupes_within_ttl(self):
        clock = FakeClock()
        r = Recorder(clock)
        e = dict(involved_object="Node/n1", type="Normal", reason="X", message="m")
        r.publish(Event(**e))
        r.publish(Event(**e))
        assert len(r.events) == 1
        clock.step(121.0)
        r.publish(Event(**e))
        assert len(r.events) == 2

    def test_metrics_registry_renders(self):
        reg = Registry()
        c = reg.counter("pods_scheduled_total", "total pods scheduled")
        c.inc({"nodepool": "default"}, by=3)
        h = reg.histogram("scheduling_duration_seconds")
        h.observe(0.3)
        text = reg.render()
        assert 'karpenter_pods_scheduled_total{nodepool="default"} 3' in text
        assert "karpenter_scheduling_duration_seconds_bucket" in text
        assert h.percentile(0.5) == 0.5


class TestNodePoolValidationMatrix:
    """CEL-adjacent runtime validation matrix (reference
    pkg/apis/v1/*_cel_test.go scenarios, enforced by the validation
    controller rather than the apiserver)."""

    def _ready(self, mutate):
        from karpenter_core_tpu.api.nodepool import (
            COND_NODEPOOL_VALIDATION_SUCCEEDED,
        )

        op = new_operator()
        pool = make_nodepool()
        mutate(pool)
        op.kube.create(pool)
        op.run_until_idle(disrupt=False)
        return not op.kube.list_nodepools()[0].conditions.is_false(
            COND_NODEPOOL_VALIDATION_SUCCEEDED
        )

    def test_empty_taint_key_rejected(self):
        from karpenter_core_tpu.api.objects import Taint

        assert not self._ready(
            lambda p: p.spec.template.taints.append(
                Taint(key="", effect="NoSchedule")
            )
        )

    def test_in_operator_without_values_rejected(self):
        from karpenter_core_tpu.api.objects import NodeSelectorRequirement

        assert not self._ready(
            lambda p: p.spec.template.requirements.append(
                NodeSelectorRequirement("size", "In", ())
            )
        )

    def test_gt_with_non_integer_rejected(self):
        from karpenter_core_tpu.api.objects import NodeSelectorRequirement

        assert not self._ready(
            lambda p: p.spec.template.requirements.append(
                NodeSelectorRequirement("size", "Gt", ("big",))
            )
        )

    def test_restricted_label_rejected(self):
        assert not self._ready(
            lambda p: p.spec.template.labels.update(
                {"kubernetes.io/hostname": "x"}
            )
        )

    def test_budget_schedule_without_duration_rejected(self):
        from karpenter_core_tpu.api.nodepool import Budget

        assert not self._ready(
            lambda p: p.spec.disruption.budgets.append(
                Budget(nodes="1", schedule="0 9 * * *")
            )
        )

    def test_valid_pool_ready(self):
        assert self._ready(lambda p: None)


class TestLivenessTTL:
    def test_unregistered_claim_reaped_after_ttl(self):
        # a claim whose machine never joins is reaped after the 15-min
        # registration TTL (liveness.go:41), and the pods re-provision onto
        # a fresh claim once a working provider path exists
        from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_core_tpu.cloudprovider.kwok import build_catalog
        from karpenter_core_tpu.controllers.nodeclaim.lifecycle import (
            REGISTRATION_TTL,
        )
        from karpenter_core_tpu.kube.store import KubeStore
        from karpenter_core_tpu.operator import Operator, Options
        from karpenter_core_tpu.utils.clock import FakeClock

        clock = FakeClock()
        kube = KubeStore(clock)
        provider = FakeCloudProvider(
            build_catalog(cpu_grid=[1, 2, 4], mem_factors=[2])
        )
        op = Operator(
            kube=kube, cloud_provider=provider, clock=clock,
            options=Options(),
        )
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="p0"))
        op.run_until_idle(max_iters=10)
        # fake provider creates instances but no Node ever registers
        claims = op.kube.list_nodeclaims()
        assert claims and not claims[0].is_registered()
        name = claims[0].name
        op.clock.step(REGISTRATION_TTL + 1.0)
        op.run_until_idle(max_iters=10)
        from karpenter_core_tpu.api.nodeclaim import NodeClaim

        assert op.kube.get(NodeClaim, name) is None, "liveness did not reap"


class TestPodEventsConsolidatable:
    def test_pod_churn_resets_the_consolidatable_window(self):
        # consolidateAfter counts from the LAST pod event: fresh churn on a
        # node defers Consolidatable; quiet time matures it
        # (podevents/controller.go:41-99, disruption/consolidation.go:40-78)
        from karpenter_core_tpu.api.nodeclaim import COND_CONSOLIDATABLE
        from karpenter_core_tpu.controllers.nodeclaim.disruption import (
            POD_EVENT_DEDUPE,
        )

        from karpenter_core_tpu.api.duration import NillableDuration

        op = new_operator()
        pool = make_nodepool()
        pool.spec.disruption.consolidate_after = NillableDuration(30.0)
        op.kube.create(pool)
        op.kube.create(make_pod(cpu=1.0, name="p0"))
        op.run_until_idle(disrupt=False)
        claim = op.kube.list_nodeclaims()[0]
        assert claim.status.last_pod_event_time is not None

        # churn within the dedupe window does not re-stamp
        stamped = claim.status.last_pod_event_time
        op.kube.create(make_pod(cpu=0.1, name="p1"))
        op.run_until_idle(disrupt=False)
        assert claim.status.last_pod_event_time == stamped

        # churn after the dedupe window re-stamps and defers consolidation
        op.clock.step(POD_EVENT_DEDUPE + 1.0)
        op.kube.create(make_pod(cpu=0.1, name="p2"))
        op.run_until_idle(disrupt=False)
        assert claim.status.last_pod_event_time > stamped
        assert not claim.conditions.is_true(COND_CONSOLIDATABLE)

        # quiet time past consolidateAfter matures the condition
        op.clock.step(40.0)
        op.run_until_idle(disrupt=False)
        claim = op.kube.list_nodeclaims()[0]
        assert claim.conditions.is_true(COND_CONSOLIDATABLE)


class TestGarbageCollectionLeakedInstance:
    def test_leaked_cloud_instance_terminated(self):
        # direction 2 of the GC sweep: a cloud instance with no owning
        # NodeClaim (leaked — e.g. the claim was force-deleted) terminates
        # (garbagecollection/controller.go:59-116)
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="p0"))
        op.run_until_idle(disrupt=False)
        claim = op.kube.list_nodeclaims()[0]
        pid = claim.status.provider_id
        assert any(
            c.status.provider_id == pid for c in op.cloud_provider.list()
        )
        # drop the claim object without running the termination flow
        claim.metadata.finalizers = []
        op.kube.delete(claim)
        op.clock.step(121.0)  # past the sweep interval
        op.run_until_idle()
        assert not any(
            c.status.provider_id == pid for c in op.cloud_provider.list()
        ), "leaked instance survived the GC sweep"


class TestCrdArtifacts:
    """CRD schema artifacts (reference pkg/apis/crds/) stay current with
    the dataclasses that generate them."""

    def test_checked_in_crds_match_generator(self):
        import os
        import sys

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        sys.path.insert(0, os.path.join(root, "tools"))
        try:
            import gen_crds
        finally:
            sys.path.pop(0)
        for fname, text in gen_crds.render().items():
            path = os.path.join(gen_crds.OUT_DIR, fname)
            assert os.path.exists(path), f"missing CRD artifact {fname}"
            with open(path) as f:
                assert f.read() == text, (
                    f"{fname} stale — rerun python tools/gen_crds.py"
                )

    def test_crd_schema_covers_spec_surface(self):
        import os

        import yaml

        from karpenter_core_tpu.api import crds as _crds_pkg  # noqa: F401

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        path = os.path.join(
            root, "karpenter_core_tpu", "api", "crds",
            "karpenter.sh_nodepools.yaml",
        )
        with open(path) as f:
            doc = yaml.safe_load(f)
        props = doc["spec"]["versions"][0]["schema"]["openAPIV3Schema"][
            "properties"
        ]
        spec = props["spec"]["properties"]
        assert set(spec) >= {"template", "disruption", "limits", "weight"}
        disruption = spec["disruption"]["properties"]
        assert "budgets" in disruption and "consolidate_after" in disruption
