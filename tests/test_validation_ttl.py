"""Consolidation validation TTL (reference: validation.go:56-215,
consolidation.go:46): commands wait 15s, then re-validate against fresh
cluster state before executing.
"""
from tests.helpers import make_nodepool, make_pod
from tests.test_e2e import new_operator, replicated

from karpenter_core_tpu.controllers.disruption.validation import (
    CONSOLIDATION_TTL,
)


def consolidate_ready(op):
    """Mature the Consolidatable condition and run the disruption poll."""
    op.clock.step(40.0)
    op.run_until_idle()


def build_underutilized_cluster(op, n_pods=6):
    """Pods sized so several nodes come up, then most pods are deleted,
    leaving underutilized nodes for consolidation."""
    op.kube.create(make_nodepool())
    pods = [
        replicated(make_pod(cpu=3.0, name=f"w{i}")) for i in range(n_pods)
    ]
    for p in pods:
        op.kube.create(p)
    op.run_until_idle()
    return pods


class TestValidationTTL:
    def test_command_waits_ttl_then_executes(self):
        op = new_operator()
        pods = build_underutilized_cluster(op)
        nodes_before = len(op.kube.list_nodes())
        assert nodes_before >= 2
        # delete most workload: nodes become consolidatable
        for p in pods[2:]:
            op.kube.delete(p)
        op.clock.step(40.0)
        # drive manual reconciles (no clock movement inside) until a
        # command is computed; it must be HELD, not executed
        for _ in range(10):
            op.reconcile_once()
            if op.disruption.pending:
                break
        assert op.disruption.pending
        n_nodes = len(op.kube.list_nodes())
        op.reconcile_once()
        assert op.disruption.pending, "executed before the TTL"
        assert len(op.kube.list_nodes()) == n_nodes
        # run_until_idle steps the fake clock through the TTL; the command
        # validates and executes
        op.run_until_idle()
        assert len(op.kube.list_nodes()) < nodes_before
        assert all(p.node_name for p in op.kube.list_pods())

    def test_pods_arriving_during_ttl_abort_command(self):
        op = new_operator()
        pods = build_underutilized_cluster(op)
        nodes_before = len(op.kube.list_nodes())
        for p in pods[2:]:
            op.kube.delete(p)
        op.clock.step(40.0)
        # drive until a command is pending (but TTL not elapsed)
        for _ in range(10):
            op.reconcile_once()
            if op.disruption.pending:
                break
        assert op.disruption.pending
        held = list(op.disruption.pending)
        # a burst of pending pods lands inside the validation window,
        # large enough that the candidates' capacity is needed again
        for i in range(8):
            op.kube.create(replicated(make_pod(cpu=3.0, name=f"burst-{i}")))
        # elapse the TTL; validation must reject the stale command
        op.clock.step(CONSOLIDATION_TTL + 1.0)
        op.reconcile_once()
        assert op.disruption.pending != held
        # no candidate node was deleted by the aborted command: the burst
        # pods bind, and nothing thrashes
        op.run_until_idle()
        assert all(p.node_name for p in op.kube.list_pods())

    def test_drift_executes_without_ttl(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="w0")))
        op.run_until_idle()
        claim = op.kube.list_nodeclaims()[0]
        # force drift via nodepool hash change
        pool = op.kube.list_nodepools()[0]
        pool.spec.template.labels["drifted"] = "yes"
        op.kube.update(pool)
        op.run_until_idle()
        # drift disruption proceeded: old claim replaced without TTL stall
        claims = op.kube.list_nodeclaims()
        assert claim.name not in {c.name for c in claims}
        assert all(p.node_name for p in op.kube.list_pods())

    def test_concurrent_pending_commands_share_one_window(self):
        """Two independent commands (one emptiness, one consolidation) wait
        out their TTLs simultaneously — per-command clocks, not one pending
        slot serializing at a command per 15s."""
        op = new_operator()
        pool = make_nodepool()
        # the default 10% budget allows only ONE concurrent disruption in a
        # two-node cluster; widen it so concurrency is observable
        from karpenter_core_tpu.api.nodepool import Budget

        pool.spec.disruption.budgets = [Budget(nodes="100%")]
        op.kube.create(pool)
        # two 12-cpu pods split across two 16-cpu nodes; the small pod
        # first-fits onto node1. Deleting the bigs leaves node1
        # underutilized (consolidation command) and node2 empty (emptiness
        # command) — two candidates, two independent commands.
        pods = [
            replicated(make_pod(cpu=12.0, name="big0")),
            replicated(make_pod(cpu=12.0, name="big1")),
            replicated(make_pod(cpu=0.6, name="small")),
        ]
        for p in pods:
            op.kube.create(p)
        op.run_until_idle()
        nodes_before = len(op.kube.list_nodes())
        assert nodes_before >= 2
        Pod = __import__(
            "karpenter_core_tpu.api.objects", fromlist=["Pod"]
        ).Pod
        for name in ("big0", "big1"):
            big = op.kube.get(Pod, name)
            big.metadata.owner_references = []
            op.kube.delete(big)
        op.clock.step(40.0)
        # drive reconciles WITHOUT advancing past the TTL: both commands
        # must stack up pending (their candidates do not overlap)
        for _ in range(12):
            op.reconcile_once()
            if len(op.disruption.pending) >= 2:
                break
        assert len(op.disruption.pending) >= 2, (
            f"only {len(op.disruption.pending)} pending; serialized"
        )
        names = [
            c.name for p in op.disruption.pending for c in p.command.candidates
        ]
        assert len(names) == len(set(names)), "double-disruption overlap"
        # one shared window elapses -> BOTH execute on the next pass
        op.clock.step(CONSOLIDATION_TTL + 1.0)
        op.reconcile_once()
        assert not op.disruption.pending
        op.run_until_idle()
        assert len(op.kube.list_nodes()) < nodes_before
        assert all(p.node_name for p in op.kube.list_pods())
