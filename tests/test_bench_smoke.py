"""Tier-1-safe fast-bench smoke (ISSUE 3 satellite).

bench.py is the driver's only window into round-over-round performance; a
broken harness (import error, schema drift, a config that asserts) is
invisible until a round burns its TPU budget discovering it. This runs the
harness end-to-end as a subprocess — BENCH_FAST=1 primary-only, tiny
BENCH_PODS/BENCH_TYPES, CPU backend — and asserts it exits 0 with one
well-formed JSON line carrying the schema downstream tooling reads,
including the PR-3 per-phase breakdown.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_fast_bench_emits_well_formed_json():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env={
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "BENCH_FAST": "1",
            "BENCH_PODS": "64",
            "BENCH_TYPES": "40",
        },
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = None
    for cand in reversed(proc.stdout.strip().splitlines()):
        try:
            line = json.loads(cand)
            break
        except ValueError:
            continue
    assert line is not None, f"no JSON line in bench output: {proc.stdout[-500:]}"

    assert line["metric"] == "solve_throughput_64pods_40types"
    assert line["unit"] == "pods/sec"
    assert line["value"] > 0
    assert isinstance(line["budget_ok"], bool)
    primary = line["detail"]["primary"]
    for key in ("p50_solve_s", "p99_solve_s", "cold_solve_s", "pods_per_sec",
                "nodes", "warm_times_s"):
        assert key in primary, key
    # the per-phase breakdown rides every _solve_bench config
    phases = primary["phases"]
    for key in ("plan_s", "prepare_s", "kernel_s", "decode_s",
                "fetch_bytes", "h2d_bytes", "used_slots"):
        assert key in phases, key
    assert phases["fetch_bytes"] > 0
    # slots touched on device can exceed final claims (sparse-tail repack
    # drops empty claims) but never undershoot them
    assert phases["used_slots"] >= primary["nodes"] > 0
    # every config's phases block is backend-attributable (ISSUE 13)
    assert phases["solver_mode"] == "ffd"
    # ... and kernel-attributable (ISSUE 18): the default is the classic
    # XLA lowering, untouched by the pallas landing
    assert phases["kernel_backend"] == "xla"
    # the tiny cfg12 proves the relaxsolve backend end-to-end: both
    # modes solved, deltas recorded, and the acceptance gate holds even
    # at smoke scale (the two-pool construction makes the win structural)
    cfg12 = line["detail"]["cfg12_relax"]
    for key in ("ffd", "relax", "nodes_delta", "cost_delta", "p50_ratio",
                "node_improved", "cost_improved", "relax_ok"):
        assert key in cfg12["cfg3_shape"] or key in cfg12, key
    for shape in ("cfg3_shape", "cfg11_shape"):
        assert cfg12[shape]["nodes_delta"] < 0, (shape, cfg12[shape])
        assert cfg12[shape]["cost_delta"] < 0, (shape, cfg12[shape])
        assert cfg12[shape]["ffd"]["phases"]["solver_mode"] == "ffd"
        assert cfg12[shape]["relax"]["phases"]["solver_mode"] == "relax"
    assert cfg12["relax_ok"] is True, cfg12

    # the tiny cfg13 proves the delta wire + fleet router end-to-end
    # (ISSUE 14): manifest-path solves parity the full path, the byte
    # schema is recorded, and the router keeps caches hot under affinity
    cfg13 = line["detail"]["cfg13_delta"]
    wire = cfg13["wire"]
    for key in ("full_wire_bytes_per_resolve",
                "delta_wire_bytes_per_resolve", "delta_ratio", "delta_ok",
                "parity_ok", "result_nodes_delta"):
        assert key in wire, key
    assert wire["parity_ok"] is True
    assert wire["result_nodes_delta"] == 0
    # a smoke-sized snapshot has too little stable problem half for the
    # full-scale <=10% gate, but the delta must already beat the full wire
    assert wire["delta_ratio"] < 1.0, wire
    fleet = cfg13["fleet"]
    assert "x1" in fleet and "x2" in fleet
    for phase in fleet.values():
        assert phase["aggregate_pods_per_sec"] > 0
    assert cfg13["affinity_cache_ok"] is True, cfg13

    # the tiny cfg14 proves the closed-loop digital twin end-to-end
    # (ISSUE 15): both scenarios ran the full operator loop on the
    # virtual clock, the ledger schema is whole, the clean run degraded
    # nothing, and NO scenario violated an invariant
    cfg14 = line["detail"]["cfg14_twin"]
    assert cfg14["twin_ok"] is True, cfg14
    for phase_name in ("clean", "fault_storm"):
        phase = cfg14[phase_name]
        for key in ("wall_s", "virtual_s", "compression_x", "pods_bound",
                    "cost_dollar_hours", "peak_nodes", "slo", "slo_misses",
                    "preemption_evictions", "utilization",
                    "invariant_violations", "rpc_fallbacks",
                    "verifier_rejections"):
            assert key in phase, (phase_name, key)
        assert phase["invariant_violations"] == 0, phase
        assert phase["pods_bound"] > 0
        assert phase["cost_dollar_hours"] > 0
        assert phase["compression_x"] > 1.0  # days-in-minutes contract
        assert set(phase["slo"]) == {"batch", "serving", "training"}
    assert cfg14["clean"]["rpc_fallbacks"] == 0
    # faults actually FIRED during the storm (the zero-violations gate
    # is not vacuous; draws alone count every healthy call too)
    storm_injected = cfg14["fault_storm"]["utilization"]["chaos_injected"]
    assert sum(int(v) for v in storm_injected.values()) > 0

    # the tiny cfg15 proves the incremental re-solve engine end-to-end
    # (ISSUE 16): churn rounds actually replayed (warm/partial), node
    # count matched the fresh daemon exactly, the self-verify pass never
    # discarded a replay, and the client-facing rejection counter never
    # moved. The 5x p50 gate is judged at full scale — a tiny fresh
    # solve costs ~nothing to beat — so incremental_ok is only required
    # to be present (and boolean) here.
    cfg15 = line["detail"]["cfg15_incremental"]
    for key in ("p50_fresh_resolve_s", "p50_incremental_resolve_s",
                "speedup_x", "node_delta_pct_max", "outcomes",
                "replayed_rounds", "incremental_rejected",
                "verifier_rejections", "ledger", "incremental_ok"):
        assert key in cfg15, key
    assert cfg15["replayed_rounds"] > 0, cfg15
    assert cfg15["node_delta_pct_max"] <= 2.0, cfg15
    assert cfg15["incremental_rejected"] == 0, cfg15
    assert cfg15["verifier_rejections"] == 0, cfg15
    assert cfg15["ledger"]["entries"] > 0
    assert isinstance(cfg15["incremental_ok"], bool)

    # the tiny cfg16 proves the elastic solver tier end-to-end
    # (ISSUE 17): the autoscaler grew and shrank a live tier, the
    # member-seconds saving against the fixed-at-max control cleared the
    # floor, resizing cost nothing at the wire (no miss rounds, no
    # fallbacks, no breaker opened), and the brownout ladder climbed and
    # descended strictly in order with the verifier untouched. The p99
    # comparison is scale-sensitive (tiny queues round to zero), so
    # p99_ok/elastic_ok are only required to be present (and boolean).
    cfg16 = line["detail"]["cfg16_elastic"]
    for key in ("autoscaled", "fixed", "member_seconds_saving_pct",
                "saving_ok", "p99_ok", "resize_cost_ok", "brownout",
                "elastic_ok"):
        assert key in cfg16, key
    assert cfg16["saving_ok"] is True, cfg16
    assert cfg16["resize_cost_ok"] is True, cfg16
    auto = cfg16["autoscaled"]
    assert max(auto["sizes"]) > 1 and min(auto["sizes"]) == 1, auto
    assert auto["remapped_lineages"] > 0, auto
    assert auto["miss_rounds"] == 0 and auto["fallbacks"] == 0, auto
    assert auto["open_breakers"] == 0, auto
    ladder = cfg16["brownout"]
    assert ladder["rung_order"] == [1, 2, 3, 2, 1, 0], ladder
    assert ladder["brownout_order_ok"] is True
    assert ladder["relax_served_as_ffd"] > 0 and ladder["relax_scheduled"]
    assert ladder["restored"] is True
    assert ladder["verifier_rejections"] == 0, ladder
    assert isinstance(cfg16["p99_ok"], bool)
    assert isinstance(cfg16["elastic_ok"], bool)

    # the tiny cfg17 proves the pallas kernel seam end-to-end (ISSUE
    # 18): both backends solved both shapes, the result wire matched
    # byte-for-byte, and the used-slot fetch window moved identical
    # device bytes under either kernel (the aggregate_takes windowing is
    # host-side and backend-agnostic). This smoke runs on the CPU
    # backend, so pallas ran in interpret mode: the latency verdicts
    # must be null (not a vacuous pass OR fail) with the speedup_note
    # explaining why — the cfg8 precedent.
    cfg17 = line["detail"]["cfg17_pallas"]
    for key in ("backend", "primary", "topology", "parity_ok",
                "primary_p50_target_ok", "topology_halved_ok"):
        assert key in cfg17, key
    assert cfg17["parity_ok"] is True, cfg17
    for shape_name in ("primary", "topology"):
        shape = cfg17[shape_name]
        assert shape["wire_parity_ok"] is True, (shape_name, shape)
        assert shape["fetch_dev_bytes_parity_ok"] is True, (
            shape_name, shape)
        assert shape["nodes_delta_pallas_vs_xla"] == 0, (
            shape_name, shape)
        # each half attributes its numbers to its kernel backend
        assert shape["xla"]["phases"]["kernel_backend"] == "xla"
        assert shape["pallas"]["phases"]["kernel_backend"] == "pallas"
    assert cfg17["backend"] == "cpu"
    assert cfg17["primary_p50_target_ok"] is None
    assert cfg17["topology_halved_ok"] is None
    assert "interpret mode" in cfg17["speedup_note"]

    # the tiny cfg11 gangsched smoke (ISSUE 10): preemption fired, every
    # gang stayed atomic, and the eviction set stayed minimal
    gangs = line["detail"]["cfg11_gangs"]
    for key in ("p50_solve_s", "preemption_count", "eviction_minimality",
                "gangs", "gangs_placed", "gang_atomicity_violations",
                "unschedulable", "p50_vs_cfg1"):
        assert key in gangs, key
    assert gangs["preemption_count"] > 0
    assert gangs["gang_atomicity_violations"] == 0
    assert gangs["gang_atomicity_ok"] is True
    assert gangs["eviction_minimality_ok"] is True
    assert gangs["gangs_placed"] > 0

    # the tiny cfg18 topoaware smoke (ISSUE 20): the identical gang
    # problem solved distance-aware vs distance-blind on a racked
    # 2-zone fleet — the aware run lands strictly fewer intra-gang hops
    # at equal-or-better node count, never provably exceeds the declared
    # hard max-hops bound on an accepted placement, and places every
    # gang in both runs (the comparison is not vacuous)
    topo = line["detail"]["cfg18_topoaware"]
    for key in ("max_hops_bound", "aware", "blind", "p50_ratio",
                "gangs_placed_ok", "topo_hops_ok", "hard_bound_ok"):
        assert key in topo, key
    assert topo["gangs_placed_ok"] is True, topo
    assert topo["topo_hops_ok"] is True, topo
    assert topo["hard_bound_ok"] is True, topo
    aware, blind = topo["aware"], topo["blind"]
    assert aware["max_intra_gang_hops"] < blind["max_intra_gang_hops"]
    assert aware["node_count"] <= blind["node_count"]
    assert aware["provable_hop_bound"] <= topo["max_hops_bound"]
    for half in (aware, blind):
        for key in ("p50_solve_s", "max_intra_gang_hops",
                    "provable_hop_bound", "gangs_placed", "node_count",
                    "cost_dollars_per_hour", "unschedulable"):
            assert key in half, key
        assert half["unschedulable"] == 0, half
