"""PodDisruptionBudget end-to-end: limits math, candidate gating, and
PDB-rate-limited drains (reference: pkg/utils/pdb/pdb.go:33-118,
disruption types.go:71-117, terminator/eviction.go:95-176).
"""
import pytest

from tests.helpers import make_nodepool, make_pod
from tests.test_e2e import new_operator, replicated

from karpenter_core_tpu.api.objects import (
    LabelSelector,
    ObjectMeta,
    Pod,
    PodDisruptionBudget,
)
from karpenter_core_tpu.kube.store import TooManyRequestsError
from karpenter_core_tpu.utils.pdb import Limits


def selector(**labels):
    return LabelSelector(match_labels=tuple(sorted(labels.items())))


def make_pdb(name="pdb", min_available=None, max_unavailable=None,
             policy="IfHealthyBudget", **labels):
    return PodDisruptionBudget(
        metadata=ObjectMeta(name=name),
        selector=selector(**labels),
        min_available=min_available,
        max_unavailable=max_unavailable,
        unhealthy_pod_eviction_policy=policy,
    )


def running_pod(name, **labels):
    p = make_pod(cpu=0.1, name=name, labels=labels)
    p.phase = "Running"
    p.node_name = "n1"
    return replicated(p)


class TestLimitsMath:
    def test_min_available_absolute(self):
        op = new_operator()
        for i in range(3):
            op.kube.create(running_pod(f"w{i}", app="web"))
        op.kube.create(make_pdb(min_available=2, app="web"))
        limits = Limits.from_kube(op.kube)
        assert limits.items[0].disruptions_allowed == 1

    def test_min_available_percent_rounds_up(self):
        op = new_operator()
        for i in range(3):
            op.kube.create(running_pod(f"w{i}", app="web"))
        op.kube.create(make_pdb(min_available="50%", app="web"))
        # desired = ceil(1.5) = 2 -> allowed 1
        assert Limits.from_kube(op.kube).items[0].disruptions_allowed == 1

    def test_max_unavailable_percent_rounds_up(self):
        op = new_operator()
        for i in range(4):
            op.kube.create(running_pod(f"w{i}", app="web"))
        op.kube.create(make_pdb(max_unavailable="30%", app="web"))
        # ceil(1.2) = 2 unavailable allowed (roundUp=true in policy/v1)
        assert Limits.from_kube(op.kube).items[0].disruptions_allowed == 2

    def test_zero_budget_blocks(self):
        op = new_operator()
        op.kube.create(running_pod("w0", app="web"))
        op.kube.create(make_pdb(min_available=1, app="web"))
        limits = Limits.from_kube(op.kube)
        pod = op.kube.list_pods()[0]
        assert limits.can_evict_pods([pod]) is not None

    def test_always_allow_ignores_unhealthy(self):
        op = new_operator()
        p = running_pod("w0", app="web")
        p.phase = "Pending"
        p.node_name = ""
        op.kube.create(p)
        op.kube.create(
            make_pdb(min_available=1, policy="AlwaysAllow", app="web")
        )
        limits = Limits.from_kube(op.kube)
        assert limits.can_evict_pods([op.kube.list_pods()[0]]) is None

    def test_unrelated_pods_unaffected(self):
        op = new_operator()
        op.kube.create(running_pod("w0", app="web"))
        op.kube.create(make_pdb(min_available=1, app="web"))
        other = make_pod(cpu=0.1, name="other", labels={"app": "db"})
        other.phase = "Running"
        assert Limits.from_kube(op.kube).can_evict_pods([other]) is None


class TestEvictionGate:
    def test_store_evict_429(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(
            cpu=0.5, name="w0", labels={"app": "web"})))
        op.run_until_idle()
        op.kube.create(make_pdb(min_available=1, app="web"))
        pod = op.kube.get(Pod, "w0")
        with pytest.raises(TooManyRequestsError):
            op.kube.evict(pod)

    def test_evict_allowed_with_headroom(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        for i in range(2):
            op.kube.create(replicated(make_pod(
                cpu=0.5, name=f"w{i}", labels={"app": "web"})))
        op.run_until_idle()
        op.kube.create(make_pdb(min_available=1, app="web"))
        op.kube.evict(op.kube.get(Pod, "w0"))  # allowed: 2 healthy, 1 needed


class TestCandidateGating:
    def test_pdb_blocked_node_is_not_disrupted(self):
        # empty-ish node carrying only a fully-protected workload must not
        # be consolidated (the VERDICT gap: "Disruption can currently evict
        # every replica of a protected workload at once")
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(
            cpu=0.1, name="w0", labels={"app": "web"})))
        op.run_until_idle()
        op.kube.create(make_pdb(min_available=1, app="web"))
        n_before = len(op.kube.list_nodes())
        assert n_before == 1
        # let consolidation condition mature
        op.clock.step(60.0)
        op.run_until_idle()
        op.clock.step(600.0)
        op.run_until_idle()
        # node survives: its only pod is PDB-protected
        assert len(op.kube.list_nodes()) == 1
        assert op.kube.get(Pod, "w0").node_name


class TestRateLimitedDrain:
    def test_drain_respects_budget_over_time(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        pods = [
            replicated(make_pod(cpu=0.3, name=f"w{i}", labels={"app": "web"}))
            for i in range(3)
        ]
        for p in pods:
            op.kube.create(p)
        op.run_until_idle()
        nodes = op.kube.list_nodes()
        assert len(nodes) == 1
        op.kube.create(make_pdb(min_available=2, app="web"))
        # delete the node: drain may evict only 1 pod per pass; evicted pods
        # rebind to a replacement node, restoring budget for the next pass
        op.kube.delete(nodes[0])
        op.run_until_idle()
        # eventually the node drains fully and goes away; all pods run
        assert op.kube.get(type(nodes[0]), nodes[0].name) is None
        assert all(p.node_name for p in op.kube.list_pods())
