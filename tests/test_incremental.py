"""incsolve (ISSUE 16): the churn-proportional incremental re-solve
engine behind the Solver seam.

The battery pins the contract layers separately:

* replay fidelity — every fuzz seed plus the topology/gang/relax shapes
  re-solved through the incremental path must be byte-identical (modulo
  solve_seconds) to the fresh answer, with the client-facing rejection
  counter UNMOVED (the engine's self-verify must never masquerade as a
  wire/device corruption);
* churn proportionality — pinned classes never re-enter the scan: the
  engine's dirty/pinned accounting proves only the churned class paid;
* the drift controller — the interval forces periodic full solves, and a
  replayed packing regressing past the node bound resets instead of
  ratcheting;
* amnesia — a fresh daemon (respawned member) misses and solves fully,
  never wrongly; the client clears its prev-fingerprint on every
  degradation so a recovered sidecar is never asked to warm-start from
  a solve it neither performed nor remembers;
* bounds — the PackingLedger is LRU in entries and bytes.
"""
import copy

import pytest

from tests.helpers import make_nodepool, make_pod
from tests.test_fuzz_parity import fuzz_scenario

from karpenter_core_tpu.cloudprovider.fake import fake_instance_types
from karpenter_core_tpu.metrics import wiring as m
from karpenter_core_tpu.solver import codec, service
from karpenter_core_tpu.solver import incremental as incsolve
from karpenter_core_tpu.solver.gangs import GANG_ANNOTATION


def _strip(data: bytes) -> dict:
    h = codec.decode_solve_results(data)
    h.pop("solve_seconds", None)
    return h


def _fp(body: bytes) -> str:
    return codec.problem_fingerprint(codec._json_header(body))


def _encode(pools, its, existing, ds, pods, **kw) -> bytes:
    return codec.encode_solve_request(
        copy.deepcopy(pools), its, copy.deepcopy(existing),
        copy.deepcopy(ds), copy.deepcopy(pods), **kw
    )


def _outcomes():
    return dict(m.SOLVER_INCREMENTAL.values)


# ---------------------------------------------------------------------------
# replay fidelity: warm replays are byte-identical to fresh solves
# ---------------------------------------------------------------------------


class TestWarmReplayParity:
    @pytest.mark.parametrize("seed", range(14))
    def test_fuzz_seed_warm_parity(self, seed):
        pods, existing, pools, its = fuzz_scenario(seed)
        daemon = service.SolverDaemon()
        body = _encode(pools, its, existing, [], pods, max_slots=128)
        rejected = dict(m.SOLVER_RESULT_REJECTED.values)
        inc = _encode(
            pools, its, existing, [], pods, max_slots=128,
            prev_fingerprint=_fp(body),
        )
        out1, _ = daemon.solve(inc)
        assert daemon.incremental.last["outcome"] == "full"
        assert daemon.incremental.last["reason"] == "miss"
        out2, _ = daemon.solve(inc)
        assert daemon.incremental.last["outcome"] == "warm", (
            daemon.incremental.last
        )
        assert _strip(out1) == _strip(out2)
        # the trust anchor's client-facing counter never moves for a
        # replay: self-verify rejections are a degradation, not a reject
        assert dict(m.SOLVER_RESULT_REJECTED.values) == rejected

    def test_topology_problem_warm_parity(self):
        from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (  # noqa: E501
            Topology,
        )

        pools = [make_nodepool()]
        its = {"default": fake_instance_types(4)}
        pods = [
            make_pod(cpu=0.5, name=f"sp{i}", spread_zone=True)
            for i in range(6)
        ]
        topo = Topology(domains={"topology.kubernetes.io/zone": {
            "zone-a": 0, "zone-b": 0,
        }})
        daemon = service.SolverDaemon()
        body = _encode(pools, its, [], [], pods, topology=topo)
        inc = _encode(
            pools, its, [], [], pods, topology=topo,
            prev_fingerprint=_fp(body),
        )
        out1, _ = daemon.solve(inc)
        out2, _ = daemon.solve(inc)
        assert daemon.incremental.last["outcome"] == "warm"
        assert _strip(out1) == _strip(out2)

    def test_gang_problem_warm_parity(self):
        pools = [make_nodepool()]
        its = {"default": fake_instance_types(4)}
        pods = []
        for i in range(4):
            p = make_pod(cpu=1.0, name=f"g{i}")
            p.metadata.annotations[GANG_ANNOTATION] = "job-1"
            pods.append(p)
        daemon = service.SolverDaemon()
        body = _encode(pools, its, [], [], pods)
        inc = _encode(
            pools, its, [], [], pods, prev_fingerprint=_fp(body)
        )
        out1, _ = daemon.solve(inc)
        out2, _ = daemon.solve(inc)
        assert daemon.incremental.last["outcome"] == "warm"
        assert _strip(out1) == _strip(out2)

    def test_relax_problem_warm_parity_and_mode_keyed_ledger(self):
        pools = [make_nodepool()]
        its = {"default": fake_instance_types(4)}
        pods = [make_pod(cpu=1.0, name=f"r{i}") for i in range(8)]
        daemon = service.SolverDaemon()
        for mode in ("ffd", "relax"):
            body = _encode(pools, its, [], [], pods, solver_mode=mode)
            inc = _encode(
                pools, its, [], [], pods, solver_mode=mode,
                prev_fingerprint=_fp(body),
            )
            out1, _ = daemon.solve(inc)
            assert daemon.incremental.last["outcome"] == "full"
            out2, _ = daemon.solve(inc)
            assert daemon.incremental.last["outcome"] == "warm"
            assert _strip(out1) == _strip(out2)
        # the raw fingerprint is mode-blind; the ledger key must not be
        # (an ffd packing replayed for a relax request would dodge the
        # optimizer the client asked for)
        assert daemon.incremental.ledger.stats()["entries"] == 2


# ---------------------------------------------------------------------------
# churn proportionality: pinned classes never re-enter the scan
# ---------------------------------------------------------------------------


class TestChurnSequences:
    # geometry chosen so the two classes exactly fill SEPARATE 8-cpu
    # nodes: churn in class b then touches no node holding class a, so
    # class a must stay pinned (a shared node would legitimately dirty
    # both classes — that conservatism is covered by the drift tests)
    POOLS = [make_nodepool()]
    ITS = {"default": fake_instance_types(4)}

    def _pods(self, big, small):
        return (
            [make_pod(cpu=1.0, name=f"a{i}") for i in range(big)]
            + [make_pod(cpu=2.0, name=f"b{i}") for i in range(small)]
        )

    def test_count_change_dirties_only_that_class(self):
        daemon = service.SolverDaemon()
        pods = self._pods(8, 4)
        body = _encode(self.POOLS, self.ITS, [], [], pods)
        inc = _encode(
            self.POOLS, self.ITS, [], [], pods,
            prev_fingerprint=_fp(body),
        )
        daemon.solve(inc)
        grown = self._pods(8, 5)  # class b grows, class a untouched
        out, _ = daemon.solve(_encode(
            self.POOLS, self.ITS, [], [], grown,
            prev_fingerprint=_fp(body),
        ))
        last = daemon.incremental.last
        assert last["outcome"] == "partial", last
        assert last["dirty_classes"] == 1
        assert last["dirty_pods"] == 5      # all of class b re-enters
        assert last["pinned_pods"] == 8     # class a never re-enters
        # every current pod is accounted for in the merged result
        h = _strip(out)
        placed = {u for c in h["claims"] for u in c["pod_uids"]}
        placed |= {u for s in h["existing"] for u in s["pod_uids"]}
        assert placed == {p.uid for p in grown}

    def test_steady_churn_rounds_stay_incremental(self):
        # chained lineage, as the real client drives it: each round
        # names the previous round's fingerprint, not the original's
        daemon = service.SolverDaemon()
        pods = self._pods(8, 4)
        body = _encode(self.POOLS, self.ITS, [], [], pods)
        prev = _fp(body)
        daemon.solve(_encode(
            self.POOLS, self.ITS, [], [], pods, prev_fingerprint=prev
        ))
        before = _outcomes()
        for round_ in range(4):
            pods = self._pods(8, 4 + round_ + 1)
            body = _encode(self.POOLS, self.ITS, [], [], pods)
            daemon.solve(_encode(
                self.POOLS, self.ITS, [], [], pods,
                prev_fingerprint=prev,
            ))
            prev = _fp(body)
            last = daemon.incremental.last
            assert last["outcome"] == "partial", last
            assert last["pinned_pods"] == 8
        delta = {
            k: _outcomes().get(k, 0) - before.get(k, 0)
            for k in _outcomes()
        }
        assert delta.get((("outcome", "partial"),), 0) == 4

    def test_new_class_is_dirty_alone(self):
        daemon = service.SolverDaemon()
        pods = self._pods(8, 0)
        body = _encode(self.POOLS, self.ITS, [], [], pods)
        daemon.solve(_encode(
            self.POOLS, self.ITS, [], [], pods,
            prev_fingerprint=_fp(body),
        ))
        withnew = pods + [make_pod(cpu=4.0, name="new0")]
        daemon.solve(_encode(
            self.POOLS, self.ITS, [], [], withnew,
            prev_fingerprint=_fp(body),
        ))
        last = daemon.incremental.last
        assert last["outcome"] == "partial", last
        assert (last["dirty_classes"], last["dirty_pods"]) == (1, 1)
        assert last["pinned_pods"] == 8


# ---------------------------------------------------------------------------
# drift controller
# ---------------------------------------------------------------------------


class TestDriftController:
    POOLS = [make_nodepool()]
    ITS = {"default": fake_instance_types(4)}

    def _daemon(self, **kw):
        return service.SolverDaemon(
            incremental=incsolve.IncrementalEngine(**kw)
        )

    def test_interval_forces_periodic_full_solves(self):
        daemon = self._daemon(full_interval=3)
        pods = [make_pod(cpu=1.0, name=f"d{i}") for i in range(6)]
        body = _encode(self.POOLS, self.ITS, [], [], pods)
        inc = _encode(
            self.POOLS, self.ITS, [], [], pods,
            prev_fingerprint=_fp(body),
        )
        seen = []
        for _ in range(7):
            daemon.solve(inc)
            seen.append(daemon.incremental.last["outcome"])
        assert seen == [
            "full", "warm", "warm", "drift_reset", "warm", "warm",
            "drift_reset",
        ]

    def test_node_regression_resets_instead_of_ratcheting(self):
        daemon = self._daemon()
        # big pods: one claim each, so the claim count is legible
        pods = [make_pod(cpu=8.0, name=f"n{i}") for i in range(3)]
        body = _encode(self.POOLS, self.ITS, [], [], pods)
        inc = _encode(
            self.POOLS, self.ITS, [], [], pods,
            prev_fingerprint=_fp(body),
        )
        daemon.solve(inc)
        engine = daemon.incremental
        entry = next(iter(engine.ledger._entries.values()))
        assert entry.node_count >= 2
        # simulate a stale baseline: the last full solve (claims to) have
        # needed zero nodes, so any replay carrying claims regresses
        entry.baseline_nodes = 0
        grown = pods + [make_pod(cpu=0.5, name="tiny")]
        out, _ = daemon.solve(_encode(
            self.POOLS, self.ITS, [], [], grown,
            prev_fingerprint=_fp(body),
        ))
        assert engine.last["outcome"] == "drift_reset"
        assert engine.last["reason"] == "node_regression"
        # the served answer is the fresh solve, not the regressed replay
        placed = {
            u for c in _strip(out)["claims"] for u in c["pod_uids"]
        }
        assert placed == {p.uid for p in grown}

    def test_tampered_replay_is_rejected_by_self_verify(self):
        daemon = self._daemon()
        pods = [make_pod(cpu=1.0, name=f"v{i}") for i in range(6)]
        body = _encode(self.POOLS, self.ITS, [], [], pods)
        inc = _encode(
            self.POOLS, self.ITS, [], [], pods,
            prev_fingerprint=_fp(body),
        )
        daemon.solve(inc)
        engine = daemon.incremental
        entry = next(iter(engine.ledger._entries.values()))
        # sabotage the remembered packing: drop a placed pod so the
        # replay under-covers (the exact wrong-bind shape the verifier
        # exists to catch)
        for c in entry.claims:
            if c["pod_uids"]:
                c["pod_uids"] = c["pod_uids"][1:]
                break
        rejected = dict(m.SOLVER_RESULT_REJECTED.values)
        out, _ = daemon.solve(inc)
        assert engine.last["outcome"] == "rejected"
        assert engine.last["reason"].startswith("verify:")
        # degraded to a fresh (correct) solve, and the client-facing
        # rejection counter never moved
        placed = {
            u for c in _strip(out)["claims"] for u in c["pod_uids"]
        }
        assert placed == {p.uid for p in pods}
        assert dict(m.SOLVER_RESULT_REJECTED.values) == rejected


# ---------------------------------------------------------------------------
# amnesia: a respawned member misses and solves fully, never wrongly
# ---------------------------------------------------------------------------


class TestAmnesia:
    POOLS = [make_nodepool()]
    ITS = {"default": fake_instance_types(4)}

    def test_fresh_daemon_with_prev_fingerprint_solves_full(self):
        pods = [make_pod(cpu=1.0, name=f"m{i}") for i in range(5)]
        body = _encode(self.POOLS, self.ITS, [], [], pods)
        inc = _encode(
            self.POOLS, self.ITS, [], [], pods,
            prev_fingerprint=_fp(body),
        )
        first = service.SolverDaemon()
        out1, _ = first.solve(inc)
        out1b, _ = first.solve(inc)
        assert first.incremental.last["outcome"] == "warm"
        # the member restarts: empty ledger == miss == full solve, and
        # determinism makes the answer identical anyway
        respawned = service.SolverDaemon()
        out2, _ = respawned.solve(inc)
        assert respawned.incremental.last["outcome"] == "full"
        assert respawned.incremental.last["reason"] == "miss"
        assert _strip(out1) == _strip(out2)

    def test_no_incremental_daemon_never_enters_engine(self):
        pods = [make_pod(cpu=1.0, name=f"x{i}") for i in range(4)]
        body = _encode(self.POOLS, self.ITS, [], [], pods)
        inc = _encode(
            self.POOLS, self.ITS, [], [], pods,
            prev_fingerprint=_fp(body),
        )
        daemon = service.SolverDaemon(incremental=False)
        before = _outcomes()
        out, _ = daemon.solve(inc)
        assert _outcomes() == before
        assert daemon.health()["incremental"] == {"enabled": False}
        placed = {
            u for c in _strip(out)["claims"] for u in c["pod_uids"]
        }
        assert placed == {p.uid for p in pods}

    def test_request_without_prev_fingerprint_bypasses_engine(self):
        pods = [make_pod(cpu=1.0, name=f"y{i}") for i in range(4)]
        daemon = service.SolverDaemon()
        before = _outcomes()
        daemon.solve(_encode(self.POOLS, self.ITS, [], [], pods))
        assert _outcomes() == before
        assert daemon.incremental.ledger.stats()["entries"] == 0


# ---------------------------------------------------------------------------
# the client contract: prev-fingerprint memory + degradation clearing
# ---------------------------------------------------------------------------


class TestClientContract:
    def test_remote_scheduler_round_trip_warms_daemon(self):
        from karpenter_core_tpu.solver.remote import (
            RemoteScheduler,
            SolverClient,
        )

        daemon = service.SolverDaemon()
        srv = service.serve(0, daemon=daemon)
        try:
            addr = f"127.0.0.1:{srv.server_address[1]}"
            client = SolverClient(addr)
            pools = [make_nodepool()]
            its = {"default": fake_instance_types(4)}
            pods = [make_pod(cpu=1.0, name=f"c{i}") for i in range(5)]

            def solve_once():
                # the provisioner rebuilds the RemoteScheduler per solve;
                # prev-fingerprint memory must live on the durable client
                rs = RemoteScheduler(
                    client, copy.deepcopy(pools), its,
                    device_scheduler_opts={"incremental": True},
                )
                return rs.solve(copy.deepcopy(pods))

            assert client.prev_fingerprint == ""
            before = _outcomes()
            solve_once()
            assert client.prev_fingerprint
            assert _outcomes() == before  # first request named no prior
            solve_once()  # names the first: miss, records the packing
            assert daemon.incremental.last["outcome"] == "full"
            assert daemon.incremental.last["reason"] == "miss"
            solve_once()  # names the second: replay
            assert daemon.incremental.last["outcome"] == "warm"
        finally:
            srv.shutdown()
            srv.server_close()

    def test_non_incremental_client_sends_no_reference(self):
        from karpenter_core_tpu.solver.remote import (
            RemoteScheduler,
            SolverClient,
        )

        daemon = service.SolverDaemon()
        srv = service.serve(0, daemon=daemon)
        try:
            addr = f"127.0.0.1:{srv.server_address[1]}"
            client = SolverClient(addr)
            client.prev_fingerprint = "stale"
            pools = [make_nodepool()]
            its = {"default": fake_instance_types(4)}
            pods = [make_pod(cpu=1.0, name=f"z{i}") for i in range(4)]
            before = _outcomes()
            RemoteScheduler(client, pools, its).solve(pods)
            RemoteScheduler(client, pools, its).solve(pods)
            assert _outcomes() == before
        finally:
            srv.shutdown()
            srv.server_close()

    def test_degradation_clears_the_reference(self):
        import socket

        from karpenter_core_tpu.solver.remote import (
            RemoteScheduler,
            SolverClient,
        )

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()  # nothing listens: connection refused
        client = SolverClient(
            f"127.0.0.1:{port}", timeout=0.5, max_retries=1,
            sleep=lambda _s: None,
        )
        client.prev_fingerprint = "doomed"
        pools = [make_nodepool()]
        its = {"default": fake_instance_types(3)}
        pods = [make_pod(cpu=1.0, name=f"f{i}") for i in range(3)]
        rs = RemoteScheduler(
            client, pools, its,
            device_scheduler_opts={"incremental": True},
        )
        results = rs.solve(pods)
        assert results.all_pods_scheduled()  # greedy fallback served
        # the next request must NOT name a predecessor the fleet never
        # acknowledged — degradation resets the lineage
        assert client.prev_fingerprint == ""

    def test_fleet_router_carries_the_memory(self):
        # digest affinity pins a snapshot's lineage to one member, so one
        # reference slot on the router suffices — and an incremental
        # RemoteScheduler over a fleet warms that member's ledger
        from karpenter_core_tpu.solver.remote import (
            FleetRouter,
            RemoteScheduler,
            SolverClient,
        )

        daemon = service.SolverDaemon()
        srv = service.serve(0, daemon=daemon)
        try:
            addr = f"127.0.0.1:{srv.server_address[1]}"
            router = FleetRouter([SolverClient(addr)])
            assert router.prev_fingerprint == ""
            pools = [make_nodepool()]
            its = {"default": fake_instance_types(4)}
            pods = [make_pod(cpu=1.0, name=f"fl{i}") for i in range(5)]
            for _ in range(3):
                RemoteScheduler(
                    router, copy.deepcopy(pools), its,
                    device_scheduler_opts={"incremental": True},
                ).solve(copy.deepcopy(pods))
            assert router.prev_fingerprint
            assert daemon.incremental.last["outcome"] == "warm"
        finally:
            srv.shutdown()
            srv.server_close()


# ---------------------------------------------------------------------------
# ledger bounds + observability
# ---------------------------------------------------------------------------


def _entry(key: str, nbytes: int = 100) -> incsolve.LedgerEntry:
    return incsolve.LedgerEntry(
        key=key, core_digest="c", topo_digest="t", node_digests={},
        label_aware=False, classes={}, claims=[], existing=[], errors={},
        evictions={}, node_count=0, baseline_nodes=0, nbytes=nbytes,
    )


class TestPackingLedger:
    def test_entry_bound_evicts_lru(self):
        led = incsolve.PackingLedger(max_entries=2)
        led.remember(_entry("a"))
        led.remember(_entry("b"))
        led.get("a")  # refresh a: b becomes the eviction victim
        led.remember(_entry("c"))
        assert led.get("b") is None
        assert led.get("a") is not None and led.get("c") is not None
        assert led.evictions == {"entries": 1}

    def test_byte_bound_evicts_but_keeps_newest(self):
        led = incsolve.PackingLedger(max_entries=10, max_bytes=250)
        led.remember(_entry("a", nbytes=100))
        led.remember(_entry("b", nbytes=100))
        led.remember(_entry("big", nbytes=1000))  # alone over the bound
        assert led.get("a") is None and led.get("b") is None
        assert led.get("big") is not None  # never evict down to zero
        assert led.evictions["bytes"] == 2

    def test_rewrite_replaces_bytes_not_duplicates(self):
        led = incsolve.PackingLedger()
        led.remember(_entry("a", nbytes=100))
        led.remember(_entry("a", nbytes=300))
        stats = led.stats()
        assert (stats["entries"], stats["bytes"]) == (1, 300)

    def test_gauges_track_residency(self):
        led = incsolve.PackingLedger()
        led.remember(_entry("a", nbytes=128))
        assert m.SOLVER_LEDGER_ENTRIES.values[()] == 1.0
        assert m.SOLVER_LEDGER_BYTES.values[()] == 128.0

    def test_healthz_exposes_engine_stats(self):
        daemon = service.SolverDaemon()
        h = daemon.health()["incremental"]
        assert h["enabled"] is True
        assert h["full_interval"] == incsolve.DEFAULT_FULL_INTERVAL
        assert set(h["ledger"]) >= {"entries", "bytes", "evictions"}


# ---------------------------------------------------------------------------
# the twin as drift judge: a churning day, incremental vs fresh
# ---------------------------------------------------------------------------


class TestTwinDriftJudge:
    """The closed loop is where warm-start packing could quietly rot:
    each replay seeds the next, so per-solve parity doesn't by itself
    bound a day of compounding. The twin runs the same churning day
    twice — incremental on and off — and judges the node-count integral
    (ledger.node_seconds), the ISSUE's node-quality surface."""

    def _day(self, incremental: bool):
        from karpenter_core_tpu.twin.scenario import (
            Scenario,
            WorkloadWave,
        )

        # a simulated day at 30-minute ticks: a standing serving base
        # plus a trickle of short-lived batch waves — every tick a few
        # pods arrive and a few expire, the steady low-churn regime the
        # incremental path exists for
        half_hour = 1800.0
        waves = [
            WorkloadWave(at=0.0, cluster=0, kind="serving", count=16,
                         min_available=2),
        ]
        for i in range(1, 46):
            waves.append(WorkloadWave(
                at=i * half_hour, cluster=0, kind="batch", count=2,
                lifetime=3 * half_hour,
            ))
        return Scenario(
            seed=11,
            clusters=1,
            duration=86400.0,
            tick=half_hour,
            solver="tpu",
            fleet=1,
            incremental=incremental,
            waves=tuple(waves),
        )

    @pytest.mark.slow
    def test_day_of_churn_node_quality_within_two_percent(self):
        from karpenter_core_tpu.twin.harness import run_scenario

        inc = run_scenario(self._day(incremental=True))
        fresh = run_scenario(self._day(incremental=False))

        # the engine actually carried the day (non-vacuous) and never
        # served a packing the verifier wouldn't stand behind
        assert inc.counters["incremental_warm"] > 0
        assert inc.counters["result_rejected"] == 0
        assert inc.violations == []
        assert fresh.counters["incremental_total"] == 0

        inc_ns = inc.ledger.node_seconds[0]
        fresh_ns = fresh.ledger.node_seconds[0]
        assert fresh_ns > 0
        # node-quality drift: the day's node-count integral must stay
        # within 2% of the fresh-solve twin (the acceptance bound)
        assert abs(inc_ns - fresh_ns) <= 0.02 * fresh_ns, (
            inc_ns, fresh_ns
        )
        # and nothing binds late because of replays
        assert inc.ledger.slo_misses == fresh.ledger.slo_misses

    def test_incremental_scenario_requires_fleet(self):
        from karpenter_core_tpu.twin.scenario import (
            Scenario,
            WorkloadWave,
            validate_scenario,
        )

        s = Scenario(
            incremental=True,
            waves=(WorkloadWave(at=0.0, cluster=0, kind="batch",
                                count=2),),
        )
        with pytest.raises(ValueError, match="fleet"):
            validate_scenario(s)

    def test_incremental_survives_scenario_codec(self):
        from karpenter_core_tpu.twin.scenario import (
            Scenario,
            WorkloadWave,
            decode_scenario,
            encode_scenario,
        )

        s = Scenario(
            solver="tpu", fleet=1, incremental=True,
            waves=(WorkloadWave(at=0.0, cluster=0, kind="batch",
                                count=2),),
        )
        assert decode_scenario(encode_scenario(s)).incremental is True
        # absent on the wire decodes to off: old encodings stay valid
        old = {
            k: v for k, v in encode_scenario(s).items()
            if k != "incremental"
        }
        assert decode_scenario(old).incremental is False
