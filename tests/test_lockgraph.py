"""Unit tests for the third dataflow domain (LockDataflow) and the GL7xx
lockgraph family mechanics: held-set propagation through helpers,
cross-object cycle detection, guard-inference majority/tie behavior,
thread reachability over Thread/HTTP-handler entries, suppression, and
the project verdict-cache bust on a rule-hash change.

The fixture-pair battery in test_graftlint.py proves each rule fires/
stays quiet end to end; these tests pin the DOMAIN's answers directly,
so a refactor cannot keep the rules green by making every query
vacuously empty.
"""
from __future__ import annotations

import json
import textwrap
from pathlib import Path

from tools.graftlint import dataflow, run
from tools.graftlint.engine import ParsedFile

FIXTURES = Path(__file__).parent / "graftlint_fixtures"


def _parse(sources: dict) -> list:
    """ParsedFiles from {relpath: source} (dedented, synthetic paths)."""
    return [
        ParsedFile(Path("/synthetic") / rel, rel, textwrap.dedent(src))
        for rel, src in sources.items()
    ]


def _locks(sources: dict) -> dataflow.LockDataflow:
    return dataflow.LockDataflow(_parse(sources))


# -- held-set propagation ----------------------------------------------------


def test_held_set_propagates_through_locked_helper():
    """The PackingLedger shape: the public method takes the lock and
    delegates to a ``_locked`` helper — the helper's write site must
    carry the caller's lock in its may-held set."""
    df = _locks({"solver/ledger.py": """\
        import threading


        class Ledger:
            def __init__(self):
                self._lock = threading.Lock()
                self.rows = []

            def remember(self, row):
                with self._lock:
                    self._append_locked(row)

            def _append_locked(self, row):
                self.rows.append(row)
        """})
    sites = df.write_sites[("Ledger", "rows")]
    assert len(sites) == 1
    assert sites[0].held == frozenset({"Ledger._lock"})
    assert df.inferred_guards["Ledger"]["rows"] == "Ledger._lock"


def test_held_set_propagates_two_frames_deep():
    df = _locks({"solver/deep.py": """\
        import threading


        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = {}

            def put(self, k, v):
                with self._lock:
                    self._put_locked(k, v)

            def _put_locked(self, k, v):
                self._really_put(k, v)

            def _really_put(self, k, v):
                self.items[k] = v
        """})
    sites = df.write_sites[("Store", "items")]
    assert sites[0].held == frozenset({"Store._lock"})


def test_entry_held_union_over_call_sites():
    """May-held joins by UNION: a helper called both with and without
    the lock carries the lock in its (over-approximate) entry set — so
    GL702 stays silent on it (sound polarity), never noisy."""
    df = _locks({"solver/union.py": """\
        import threading


        class Mixed:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def locked_path(self):
                with self._lock:
                    self._bump()

            def bare_path(self):
                self._bump()

            def _bump(self):
                self.n += 1
        """})
    sites = df.write_sites[("Mixed", "n")]
    assert sites[0].held == frozenset({"Mixed._lock"})


# -- the order graph and cycles ----------------------------------------------


def test_cross_object_cycle_detected():
    """The gateway/coalescer ABBA seam: the cycle closes only through
    constructor-typed cross-object calls, never inside one function."""
    pf_path = FIXTURES / "solver" / "gl701_bad.py"
    pf = ParsedFile(pf_path, "solver/gl701_bad.py", pf_path.read_text())
    df = dataflow.LockDataflow([pf])
    assert df.cycles() == [
        ["FleetGatewayStub._lock", "TicketCoalescer._lock"]
    ]
    vias = {
        via
        for (src, dst), wits in df.order_edges.items()
        for (_rel, _line, via) in wits
    }
    assert "nested" in vias


def test_hoisted_calls_leave_graph_acyclic():
    pf_path = FIXTURES / "solver" / "gl701_good.py"
    pf = ParsedFile(pf_path, "solver/gl701_good.py", pf_path.read_text())
    df = dataflow.LockDataflow([pf])
    assert df.cycles() == []


def test_nonreentrant_self_reacquire_is_self_deadlock():
    df = _locks({"solver/reacquire.py": """\
        import threading


        class Wedge:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    self.n += 1
        """})
    assert any(
        lid == "Wedge._lock" and "re-acquired" in reason
        for lid, _rel, _line, reason in df.self_deadlocks
    )


def test_rlock_self_reacquire_is_fine():
    """The SegmentStore/_locked-helper idiom: RLock re-entry is the
    designed discipline, not a deadlock."""
    df = _locks({"solver/reentrant.py": """\
        import threading


        class Store:
            def __init__(self):
                self._lock = threading.RLock()
                self.n = 0

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    self.n += 1
        """})
    assert df.self_deadlocks == []
    assert df.cycles() == []


def test_join_while_holding_needed_lock_is_self_deadlock():
    """stop() joins the poll thread while holding the lock the poll
    body needs — the join can never return."""
    df = _locks({"solver/joiner.py": """\
        import threading


        class Poller:
            def __init__(self):
                self._lock = threading.Lock()
                self.ticks = 0
                self._thread = threading.Thread(
                    target=self._loop, daemon=True
                )

            def _loop(self):
                with self._lock:
                    self.ticks += 1

            def stop(self):
                with self._lock:
                    self._thread.join()
        """})
    assert any(
        lid == "Poller._lock" and "joins a thread" in reason
        for lid, _rel, _line, reason in df.self_deadlocks
    )


def test_wait_for_event_whose_setter_needs_held_lock():
    df = _locks({"solver/waiter.py": """\
        import threading


        class Handoff:
            def __init__(self):
                self._lock = threading.Lock()
                self._done = threading.Event()
                self.result = None

            def consume(self):
                with self._lock:
                    self._done.wait()

            def produce(self, value):
                with self._lock:
                    self.result = value
                    self._done.set()
        """})
    assert any(
        lid == "Handoff._lock" and "waker needs" in reason
        for lid, _rel, _line, reason in df.self_deadlocks
    )


# -- guard inference ---------------------------------------------------------


def test_guard_inference_majority_and_tie():
    df = _locks({"solver/guards.py": """\
        import threading


        class Majority:
            def __init__(self):
                self._lock = threading.Lock()
                self.hits = 0

            def a(self):
                with self._lock:
                    self.hits += 1

            def b(self):
                with self._lock:
                    self.hits = 0

            def c(self):
                self.hits -= 1


        class Tie:
            def __init__(self):
                self._lock = threading.Lock()
                self.hits = 0

            def a(self):
                with self._lock:
                    self.hits += 1

            def b(self):
                self.hits = 0
        """})
    # 2-of-3 locked: the lock IS the inferred guard
    assert df.inferred_guards["Majority"]["hits"] == "Majority._lock"
    # 1-of-2: no strict majority, no inference — GL702 stays silent
    assert "hits" not in df.inferred_guards.get("Tie", {})


def test_guard_inference_two_lock_tie_infers_nothing():
    df = _locks({"solver/twolocks.py": """\
        import threading


        class Split:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self.n = 0

            def via_a(self):
                with self._a:
                    self.n += 1

            def via_b(self):
                with self._b:
                    self.n += 1
        """})
    assert "n" not in df.inferred_guards.get("Split", {})


def test_same_lock_attr_name_does_not_merge_across_classes():
    """Both classes name their lock ``_lock``; identity is (class, attr)
    so neither an order edge nor a guard crosses between them."""
    df = _locks({"solver/two_classes.py": """\
        import threading


        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def add(self, x):
                with self._lock:
                    self.items.append(x)


        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def add(self, x):
                with self._lock:
                    self.items.append(x)
        """})
    assert df.inferred_guards["A"]["items"] == "A._lock"
    assert df.inferred_guards["B"]["items"] == "B._lock"
    assert df.order_edges == {}


# -- thread reachability -----------------------------------------------------


def test_thread_target_and_callees_reachable():
    files = _parse({"solver/reach.py": """\
        import threading


        class Daemon:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def serve(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                self._tick_once()

            def _tick_once(self):
                self.n += 1

            def offline_report(self):
                return self.n
        """})
    df = dataflow.LockDataflow(files)
    pf = files[0]
    by_name = {
        fn.name: fn
        for fn in pf.walk(__import__("ast").FunctionDef)
    }
    assert df.thread_reachable(pf, by_name["_loop"])
    assert df.thread_reachable(pf, by_name["_tick_once"])
    assert not df.thread_reachable(pf, by_name["offline_report"])
    assert not df.thread_reachable(pf, by_name["serve"])


def test_http_handler_entry_reaches_daemon_via_loose_tail():
    """The solverd seam: the handler reaches the daemon through
    ``self.server.daemon.solve_once()`` — an attribute chain precise
    resolution cannot type, caught by the stoplisted name-tail
    fallback."""
    files = _parse({"solver/httpd.py": """\
        from http.server import BaseHTTPRequestHandler


        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                self.server.daemon.solve_once()


        class Daemon:
            def __init__(self):
                self.n = 0

            def solve_once(self):
                self.n += 1
        """})
    df = dataflow.LockDataflow(files)
    pf = files[0]
    import ast as _ast

    by_name = {fn.name: fn for fn in pf.walk(_ast.FunctionDef)}
    assert df.thread_reachable(pf, by_name["do_POST"])
    assert df.thread_reachable(pf, by_name["solve_once"])


# -- rule mechanics ----------------------------------------------------------


def test_gl702_suppression_with_justification(tmp_path):
    d = tmp_path / "graftlint_fixtures"
    d.mkdir()
    src = (FIXTURES / "solver" / "gl702_bad.py").read_text()
    src = src.replace(
        "self.solves += 1  # bare RMW on a handler thread: lost update",
        "# graftlint: disable=GL702 -- deliberate lock-free fast path:\n"
        "        # the counter is advisory and drift is acceptable here\n"
        "        self.solves += 1",
    )
    f = d / "gl702_suppressed.py"
    f.write_text(src)
    result = run([str(f)], use_baseline=False, rule_ids=["GL702"])
    assert not result.new
    assert len(result.suppressed) == 1


def test_gl704_subprocess_timed_wait_not_flagged(tmp_path):
    """``proc.wait(timeout=...)`` is a subprocess wait, not an Event —
    GL704's timed-wait check keys on known Event/Condition attrs and
    must stay silent (the supervisor leans on this shape)."""
    d = tmp_path / "graftlint_fixtures"
    d.mkdir()
    (d / "procwait.py").write_text(textwrap.dedent("""\
        import subprocess


        class Super:
            def __init__(self):
                self.proc = subprocess.Popen(["sleep", "1"])

            def reap(self):
                self.proc.wait(timeout=10)
        """))
    result = run([str(d)], use_baseline=False, rule_ids=["GL704"])
    assert not result.new


def test_gl701_message_names_the_cycle():
    result = run(
        [str(FIXTURES / "solver" / "gl701_bad.py")],
        use_baseline=False,
        rule_ids=["GL701"],
    )
    assert result.new
    for f, _src in result.new:
        assert " -> " in f.message


def test_solver_tier_clean_under_lockgraph():
    """The tentpole sweep, pinned: the whole solver tier satisfies
    GL701–GL705 (the one deliberate exception carries its inline
    justification and lands in suppressed, not new)."""
    result = run(
        ["karpenter_core_tpu/solver", "karpenter_core_tpu/utils"],
        use_baseline=False,
        rule_ids=["GL701", "GL702", "GL703", "GL704", "GL705"],
    )
    assert result.ok, "\n".join(f.render() for f, _ in result.new)


def test_lock_domain_queries_survive_reparse():
    """The domain is content-hash cached across run() calls while every
    run hands the rules freshly parsed nodes — warm-run queries must
    answer identically (fids are (relpath, line, name), never id())."""
    path = str(FIXTURES / "solver" / "gl705_bad.py")
    cold = run([path], use_baseline=False, rule_ids=["GL705"])
    warm = run([path], use_baseline=False, rule_ids=["GL705"])
    assert [(f, s) for f, s in warm.new] == [(f, s) for f, s in cold.new]
    assert len(cold.new) == 2


def test_project_verdict_cache_busts_on_rule_hash_change(tmp_path):
    """GL7xx findings ride the project verdict cache: a warm run
    reproduces them without re-running, and a rule-implementation change
    (hash flip) re-computes rather than serving stale verdicts."""
    import tools.graftlint.engine as engine

    cache = tmp_path / "cache.json"
    target = str(FIXTURES / "solver")
    cold = run([target], use_baseline=False, cache_path=cache)
    assert any(f.rule.startswith("GL7") for f, _ in cold.new)
    data = json.loads(cache.read_text())
    assert "__project__" in data

    warm = run([target], use_baseline=False, cache_path=cache)
    assert warm.cache_hits == warm.files
    assert [(f, s) for f, s in warm.new] == [(f, s) for f, s in cold.new]

    old = engine._rules_hash
    engine._RULES_HASH = None
    try:
        engine._rules_hash = lambda: "lockgraph-changed"
        busted = run([target], use_baseline=False, cache_path=cache)
        assert busted.cache_hits == 0
        assert [(f, s) for f, s in busted.new] == [
            (f, s) for f, s in cold.new
        ]
    finally:
        engine._rules_hash = old
        engine._RULES_HASH = None
