"""Ported NodeClaim lifecycle scenario blocks
(reference: pkg/controllers/nodeclaim/lifecycle/{launch,registration,
initialization,liveness,termination}_test.go families): launch error
taxonomy, registration taint/label sync, initialization gating on
readiness/startup taints/resources, the registration-liveness TTL, and
finalizer semantics for unlaunched claims.
"""
import pytest

from tests.helpers import make_nodepool, make_pod

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.nodeclaim import NodeClaim
from karpenter_core_tpu.api.objects import Node, NodeStatus, ObjectMeta, Taint
from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_core_tpu.cloudprovider.types import (
    CreateError,
    InsufficientCapacityError,
    NodeClassNotReadyError,
)
from karpenter_core_tpu.controllers.nodeclaim.lifecycle import (
    REGISTRATION_TTL,
    NodeClaimLifecycle,
)
from karpenter_core_tpu.kube.store import KubeStore
from karpenter_core_tpu.scheduling.taints import UNREGISTERED_NO_EXECUTE_TAINT
from karpenter_core_tpu.state.cluster import Cluster
from karpenter_core_tpu.utils.clock import FakeClock


def harness():
    clock = FakeClock()
    kube = KubeStore(clock)
    provider = FakeCloudProvider()
    cluster = Cluster(kube, clock)
    return NodeClaimLifecycle(kube, cluster, provider, clock), kube, provider, clock


def make_claim(kube, name="c1", labels=None):
    claim = NodeClaim(metadata=ObjectMeta(
        name=name, labels={L.NODEPOOL_LABEL_KEY: "default",
                           **(labels or {})},
    ))
    kube.create(claim)
    return claim


def join_node(kube, claim, ready=True, allocatable=None, taints=()):
    """The machine comes online: a Node with the claim's provider id and
    the unregistered taint (what kwok/a real bootstrap produces)."""
    node = Node(
        metadata=ObjectMeta(name=f"node-{claim.name}"),
        provider_id=claim.status.provider_id,
        taints=[UNREGISTERED_NO_EXECUTE_TAINT] + list(taints),
        status=NodeStatus(
            capacity={"cpu": 4.0},
            allocatable=dict(
                {"cpu": 3.5} if allocatable is None else allocatable
            ),
            conditions=[("Ready", "True" if ready else "False")],
        ),
    )
    kube.create(node)
    return node


class TestLaunch:
    def test_launched_condition_set_after_create(self):
        lc, kube, provider, clock = harness()
        claim = make_claim(kube)
        lc.reconcile(claim)
        assert claim.status.provider_id
        assert provider.create_calls

    def test_insufficient_capacity_deletes_claim(self):
        lc, kube, provider, clock = harness()
        claim = make_claim(kube)
        provider.next_create_error = InsufficientCapacityError("no spot")
        lc.reconcile(claim)  # terminal: delete (held by the finalizer)
        lc.reconcile(claim)  # finalize pass releases it
        assert kube.get(NodeClaim, claim.name) is None

    def test_node_class_not_ready_deletes_claim(self):
        lc, kube, provider, clock = harness()
        claim = make_claim(kube)
        provider.next_create_error = NodeClassNotReadyError("class pending")
        lc.reconcile(claim)
        lc.reconcile(claim)  # finalize pass
        assert kube.get(NodeClaim, claim.name) is None

    def test_create_error_sets_condition_and_retries(self):
        lc, kube, provider, clock = harness()
        claim = make_claim(kube)
        provider.next_create_error = CreateError("quota exceeded")
        lc.reconcile(claim)
        held = kube.get(NodeClaim, claim.name)
        assert held is not None  # not terminal
        cond = held.conditions.get("Launched")
        assert cond is not None and cond.status == "False"
        assert "quota exceeded" in cond.message
        lc.reconcile(held)  # provider recovered: launch proceeds
        assert held.is_launched()

    def test_finalizer_added_on_first_reconcile(self):
        lc, kube, provider, clock = harness()
        claim = make_claim(kube)
        lc.reconcile(claim)
        assert L.TERMINATION_FINALIZER in claim.metadata.finalizers


class TestRegistration:
    def test_unregistered_taint_removed_and_labels_synced(self):
        lc, kube, provider, clock = harness()
        claim = make_claim(kube, labels={"team": "infra"})
        claim.spec.taints = [Taint(key="workload", value="gpu",
                                   effect="NoSchedule")]
        lc.reconcile(claim)
        node = join_node(kube, claim)
        lc.reconcile(claim)
        assert claim.is_registered()
        assert all(
            t.key != UNREGISTERED_NO_EXECUTE_TAINT.key for t in node.taints
        )
        assert node.metadata.labels[L.NODE_REGISTERED_LABEL_KEY] == "true"
        assert node.metadata.labels["team"] == "infra"
        assert any(t.key == "workload" for t in node.taints)
        assert L.TERMINATION_FINALIZER in node.metadata.finalizers

    def test_startup_taints_synced_once(self):
        lc, kube, provider, clock = harness()
        claim = make_claim(kube)
        claim.spec.startup_taints = [Taint(key="boot", value="",
                                           effect="NoSchedule")]
        lc.reconcile(claim)
        node = join_node(kube, claim)
        lc.reconcile(claim)
        assert any(t.key == "boot" for t in node.taints)
        # the kubelet clears the startup taint; registration must NOT
        # re-add it (claim already registered)
        node.taints = [t for t in node.taints if t.key != "boot"]
        kube.update(node)
        lc.reconcile(claim)
        assert all(t.key != "boot" for t in node.taints)


class TestInitialization:
    def _registered(self, ready=True, allocatable=None, startup=()):
        lc, kube, provider, clock = harness()
        claim = make_claim(kube)
        claim.spec.startup_taints = list(startup)
        lc.reconcile(claim)
        node = join_node(kube, claim, ready=ready, allocatable=allocatable)
        lc.reconcile(claim)
        assert claim.is_registered()
        return lc, kube, claim, node

    def test_not_initialized_while_not_ready(self):
        lc, kube, claim, node = self._registered(ready=False)
        lc.reconcile(claim)
        assert not claim.is_initialized()

    def test_not_initialized_without_registered_resources(self):
        lc, kube, claim, node = self._registered(allocatable={})
        lc.reconcile(claim)
        assert not claim.is_initialized()

    def test_not_initialized_until_startup_taints_clear(self):
        startup = [Taint(key="boot", value="", effect="NoSchedule")]
        lc, kube, claim, node = self._registered(startup=startup)
        lc.reconcile(claim)
        assert not claim.is_initialized()
        node.taints = [t for t in node.taints if t.key != "boot"]
        kube.update(node)
        lc.reconcile(claim)
        assert claim.is_initialized()
        assert node.metadata.labels[L.NODE_INITIALIZED_LABEL_KEY] == "true"

    def test_initializes_when_all_gates_pass(self):
        lc, kube, claim, node = self._registered()
        lc.reconcile(claim)
        assert claim.is_initialized()


class TestLiveness:
    def test_unregistered_claim_reaped_after_ttl(self):
        lc, kube, provider, clock = harness()
        claim = make_claim(kube)
        lc.reconcile(claim)  # launched, but no node ever joins
        clock.step(REGISTRATION_TTL + 1.0)
        lc.reconcile(claim)
        lc.reconcile(claim)  # finalize pass
        assert kube.get(NodeClaim, claim.name) is None

    def test_registered_claim_survives_ttl(self):
        lc, kube, provider, clock = harness()
        claim = make_claim(kube)
        lc.reconcile(claim)
        join_node(kube, claim)
        lc.reconcile(claim)
        clock.step(REGISTRATION_TTL + 1.0)
        lc.reconcile(claim)
        assert kube.get(NodeClaim, claim.name) is not None

    def test_claim_within_ttl_keeps_waiting(self):
        lc, kube, provider, clock = harness()
        claim = make_claim(kube)
        lc.reconcile(claim)
        clock.step(REGISTRATION_TTL / 2)
        lc.reconcile(claim)
        assert kube.get(NodeClaim, claim.name) is not None


class TestFinalize:
    def test_unlaunched_claim_skips_provider_delete(self):
        lc, kube, provider, clock = harness()
        claim = make_claim(kube)
        claim.metadata.finalizers.append(L.TERMINATION_FINALIZER)
        kube.update(claim)
        kube.delete(claim)  # sets deletion timestamp (finalizer held)
        lc.reconcile(claim)
        assert provider.delete_calls == []
        assert kube.get(NodeClaim, claim.name) is None

    def test_launched_claim_deletes_instance(self):
        lc, kube, provider, clock = harness()
        claim = make_claim(kube)
        lc.reconcile(claim)
        kube.delete(claim)
        lc.reconcile(claim)
        assert len(provider.delete_calls) == 1
        assert kube.get(NodeClaim, claim.name) is None


class TestLivenessBackstop:
    def test_perpetually_failing_launch_reaped_after_ttl(self):
        """A launch that fails with CreateError on every pass must not
        retry forever: the TTL backstop reaps the never-registered claim
        (liveness.go:41 keys on Registered, not Launched)."""
        lc, kube, provider, clock = harness()
        claim = make_claim(kube)
        for _ in range(3):
            provider.next_create_error = CreateError("quota exceeded")
            lc.reconcile(claim)
        assert kube.get(NodeClaim, claim.name) is not None
        clock.step(REGISTRATION_TTL + 1.0)
        provider.next_create_error = CreateError("quota exceeded")
        lc.reconcile(claim)
        lc.reconcile(claim)  # finalize pass
        assert kube.get(NodeClaim, claim.name) is None

    def test_typed_create_error_condition_fields_used(self):
        lc, kube, provider, clock = harness()
        claim = make_claim(kube)
        provider.next_create_error = CreateError(
            "api timeout", condition_reason="ImageNotReady",
            condition_message="AMI still pending",
        )
        lc.reconcile(claim)
        cond = claim.conditions.get("Launched")
        assert cond.reason == "ImageNotReady"
        assert cond.message == "AMI still pending"

    def test_instance_created_before_condition_is_still_deleted(self):
        """Provider wrote provider_id but the Launched condition never
        landed: finalize must still delete the instance (keyed on
        provider_id, not the condition)."""
        lc, kube, provider, clock = harness()
        claim = make_claim(kube)
        claim.metadata.finalizers.append(L.TERMINATION_FINALIZER)
        claim.status.provider_id = "fake-instance-1"
        kube.update(claim)
        kube.delete(claim)
        lc.reconcile(claim)
        assert len(provider.delete_calls) == 1


class TestDriftScenarios:
    """Ported drift detection families (nodeclaim/disruption/drift_test.go):
    hash gating, hash-version migration, stale instance types, offering
    compatibility, precedence."""

    def _op(self):
        from tests.test_disruption import new_operator, provision

        op = new_operator()
        provision(op, [make_pod(cpu=1.0, name="w0")])
        (claim,) = op.kube.list_nodeclaims()
        (pool,) = op.kube.list_nodepools()
        return op, pool, claim

    def _mutate_pool(self, op, pool):
        pool.spec.template.labels["drifted"] = "yes"
        op.kube.update(pool)
        op.nodepool_hash.reconcile(pool)

    def test_static_hash_drift_detected(self):
        op, pool, claim = self._op()
        self._mutate_pool(op, pool)
        op.nodeclaim_disruption.reconcile(claim)
        assert claim.conditions.is_true("Drifted")
        assert claim.conditions.get("Drifted").reason == "NodePoolDrifted"

    def test_no_drift_without_pool_hash_annotation(self):
        op, pool, claim = self._op()
        pool.spec.template.labels["drifted"] = "yes"
        pool.metadata.annotations.pop(
            L.NODEPOOL_HASH_ANNOTATION_KEY, None
        )
        op.kube.update(pool)  # hash controller NOT run
        op.nodeclaim_disruption.reconcile(claim)
        assert not claim.conditions.is_true("Drifted")

    def test_no_drift_without_claim_hash_annotation(self):
        op, pool, claim = self._op()
        claim.metadata.annotations.pop(L.NODEPOOL_HASH_ANNOTATION_KEY, None)
        self._mutate_pool(op, pool)
        op.nodeclaim_disruption.reconcile(claim)
        assert not claim.conditions.is_true("Drifted")

    def test_no_drift_on_hash_version_mismatch(self):
        op, pool, claim = self._op()
        claim.metadata.annotations[
            L.NODEPOOL_HASH_VERSION_ANNOTATION_KEY
        ] = "v1-legacy"
        pool.spec.template.labels["drifted"] = "yes"
        op.kube.update(pool)
        # refresh the pool hash WITHOUT migrating claims (bypass the hash
        # controller's migration to isolate the version gate)
        pool.metadata.annotations[L.NODEPOOL_HASH_ANNOTATION_KEY] = (
            pool.static_hash()
        )
        op.nodeclaim_disruption.reconcile(claim)
        assert not claim.conditions.is_true("Drifted")

    def test_hash_version_migration_prevents_false_drift(self):
        op, pool, claim = self._op()
        # simulate an old-version stamp: the hash controller must re-stamp
        # the claim instead of letting drift fire (hash/controller.go:70-124)
        claim.metadata.annotations[
            L.NODEPOOL_HASH_VERSION_ANNOTATION_KEY
        ] = "v1-legacy"
        claim.metadata.annotations[L.NODEPOOL_HASH_ANNOTATION_KEY] = "stale"
        pool.metadata.annotations[
            L.NODEPOOL_HASH_VERSION_ANNOTATION_KEY
        ] = "v1-legacy"
        op.nodepool_hash.reconcile(pool)
        op.nodeclaim_disruption.reconcile(claim)
        assert not claim.conditions.is_true("Drifted")

    def test_hash_version_migration_keeps_drifted_claims_drifted(self):
        """hash/controller.go:102-113: a claim already marked Drifted keeps
        its STALE HASH through the version migration (re-stamping would
        erase the real config difference) but still gets the new hash
        VERSION — otherwise the version gate would un-drift it forever."""
        op, pool, claim = self._op()
        self._mutate_pool(op, pool)
        op.nodeclaim_disruption.reconcile(claim)
        assert claim.conditions.is_true("Drifted")
        stale_hash = claim.metadata.annotations[L.NODEPOOL_HASH_ANNOTATION_KEY]
        # a hash-version rollout lands while the claim is Drifted
        claim.metadata.annotations[
            L.NODEPOOL_HASH_VERSION_ANNOTATION_KEY
        ] = "v1-legacy"
        pool.metadata.annotations[
            L.NODEPOOL_HASH_VERSION_ANNOTATION_KEY
        ] = "v1-legacy"
        op.nodepool_hash.reconcile(pool)
        # hash NOT re-stamped (the drift evidence survives) ...
        assert (
            claim.metadata.annotations[L.NODEPOOL_HASH_ANNOTATION_KEY]
            == stale_hash
        )
        # ... but the VERSION is migrated, so the drift check still fires
        from karpenter_core_tpu.api.labels import HASH_VERSION

        assert (
            claim.metadata.annotations[L.NODEPOOL_HASH_VERSION_ANNOTATION_KEY]
            == HASH_VERSION
        )
        op.nodeclaim_disruption.reconcile(claim)
        assert claim.conditions.is_true("Drifted")

    def test_drift_clears_when_pool_reverts(self):
        op, pool, claim = self._op()
        self._mutate_pool(op, pool)
        op.nodeclaim_disruption.reconcile(claim)
        assert claim.conditions.is_true("Drifted")
        del pool.spec.template.labels["drifted"]
        op.kube.update(pool)
        op.nodepool_hash.reconcile(pool)
        op.nodeclaim_disruption.reconcile(claim)
        assert not claim.conditions.is_true("Drifted")

    def test_requirements_drift(self):
        from karpenter_core_tpu.api.objects import NodeSelectorRequirement

        op, pool, claim = self._op()
        zone = claim.metadata.labels[L.LABEL_TOPOLOGY_ZONE]
        other = "zone-b" if zone != "zone-b" else "zone-c"
        pool.spec.template.requirements = [NodeSelectorRequirement(
            L.LABEL_TOPOLOGY_ZONE, "In", (other,))]
        op.kube.update(pool)
        op.nodepool_hash.reconcile(pool)
        op.nodeclaim_disruption.reconcile(claim)
        assert claim.conditions.is_true("Drifted")
        # requirements are excluded from static_hash, so the reason is
        # deterministically the requirements check
        assert claim.conditions.get("Drifted").reason == "RequirementsDrifted"

    def test_instance_type_gone_drifts(self):
        op, pool, claim = self._op()
        claim.metadata.labels[L.LABEL_INSTANCE_TYPE] = "retired-type"
        op.nodeclaim_disruption.reconcile(claim)
        assert claim.conditions.is_true("Drifted")
        assert claim.conditions.get("Drifted").reason == "InstanceTypeNotFound"

    def test_offering_incompatible_drifts(self):
        op, pool, claim = self._op()
        # the claim's committed zone no longer has any available offering
        # for its instance type
        claim.metadata.labels[L.LABEL_TOPOLOGY_ZONE] = "zone-that-left"
        op.nodeclaim_disruption.reconcile(claim)
        assert claim.conditions.is_true("Drifted")
        assert claim.conditions.get("Drifted").reason == "InstanceTypeNotFound"

    def test_no_drift_when_nodepool_missing(self):
        op, pool, claim = self._op()
        claim.metadata.labels[L.NODEPOOL_LABEL_KEY] = "ghost"
        op.nodeclaim_disruption.reconcile(claim)
        assert not claim.conditions.is_true("Drifted")

    def test_static_drift_takes_precedence_over_provider(self):
        op, pool, claim = self._op()
        # inject provider-level drift alongside static drift: the static
        # reason must win (drift.go checks static before cloud provider)
        orig = op.cloud_provider.is_drifted
        op.cloud_provider.is_drifted = lambda c: "CloudProviderDrifted"
        try:
            self._mutate_pool(op, pool)
            op.nodeclaim_disruption.reconcile(claim)
            assert claim.conditions.get("Drifted").reason == "NodePoolDrifted"
        finally:
            op.cloud_provider.is_drifted = orig
