"""Auto-generated wire round-trip battery for solver/codec.py.

The codec is a pair of hand-written encode/decode paths; the failure mode
is a field that lands on one side only (the ``unavailable_offerings``
near-miss PR 2 fixed by hand, now also machine-checked by graftlint's
GL401). This battery closes the loop at runtime:

* PAIRING — every ``encode_X``/``_encode_X`` in the module has a decode
  twin (introspected from the module, so a new codec entry registers
  itself into this test or fails it);
* FIELD COVERAGE — the field sets of every wire dataclass (SimNode,
  InstanceType, Offering, Requirement, OfferingKey) are pinned against
  the exact sets the codec serializes, so adding a dataclass field
  without touching the codec fails here by construction — even though no
  sample can populate a field that didn't exist when the sample was
  written;
* ROUND TRIP — encode→decode over richly-populated samples is
  field-for-field identical, driven by dataclass/slots introspection
  rather than hand-listed asserts.
"""
from __future__ import annotations

import dataclasses
import inspect

import numpy as np
import pytest

from tests.helpers import make_nodepool, make_pod

from karpenter_core_tpu.cloudprovider.fake import fake_instance_types
from karpenter_core_tpu.cloudprovider.types import (
    InstanceType,
    Offering,
    OfferingKey,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
    SimNode,
)
from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
    Topology,
)
from karpenter_core_tpu.scheduling.requirement import Requirement
from karpenter_core_tpu.scheduling.volumeusage import VolumeUsage
from karpenter_core_tpu.solver import codec


# ---------------------------------------------------------------------------
# introspected deep equality
# ---------------------------------------------------------------------------


def deep_eq(a, b, path="$"):
    """Field-for-field equality via introspection; returns a list of
    difference descriptions (empty = equal)."""
    diffs = []
    if dataclasses.is_dataclass(a) and not isinstance(a, type):
        if type(a) is not type(b):
            return [f"{path}: type {type(a).__name__} != {type(b).__name__}"]
        for f in dataclasses.fields(a):
            if f.name.startswith("_"):
                continue  # caches, not wire state
            diffs += deep_eq(
                getattr(a, f.name), getattr(b, f.name), f"{path}.{f.name}"
            )
        return diffs
    if isinstance(a, Requirement):
        if not isinstance(b, Requirement):
            return [f"{path}: {type(b).__name__} is not a Requirement"]
        for slot in Requirement.__slots__:
            diffs += deep_eq(
                getattr(a, slot), getattr(b, slot), f"{path}.{slot}"
            )
        return diffs
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            return [f"{path}: arrays differ"]
        return []
    if isinstance(a, dict):
        if not isinstance(b, dict):
            return [f"{path}: {type(b).__name__} is not a dict"]
        if set(a) != set(b):
            return [f"{path}: keys {sorted(a)} != {sorted(b)}"]
        for k in a:
            diffs += deep_eq(a[k], b[k], f"{path}[{k!r}]")
        return diffs
    if isinstance(a, (set, frozenset)):
        if set(a) != set(b):
            return [f"{path}: sets differ ({a} != {b})"]
        return []
    if isinstance(a, (list, tuple)):
        if len(a) != len(b):
            return [f"{path}: length {len(a)} != {len(b)}"]
        for i, (x, y) in enumerate(zip(a, b)):
            diffs += deep_eq(x, y, f"{path}[{i}]")
        return diffs
    if (
        type(a) is type(b)
        and hasattr(a, "__dict__")
        and not isinstance(a, (str, int, float, bool))
    ):
        # plain objects (VolumeUsage, API objects' helpers): compare their
        # public attributes; underscore attrs are caches/derived state
        for k in sorted(set(vars(a)) | set(vars(b))):
            if k.startswith("_"):
                continue
            diffs += deep_eq(
                vars(a).get(k), vars(b).get(k), f"{path}.{k}"
            )
        return diffs
    if a != b:
        return [f"{path}: {a!r} != {b!r}"]
    return []


def assert_deep_eq(a, b, what):
    diffs = deep_eq(a, b)
    assert not diffs, f"{what} round-trip drift:\n" + "\n".join(diffs[:20])


# ---------------------------------------------------------------------------
# samples
# ---------------------------------------------------------------------------


def sample_requirement() -> Requirement:
    return Requirement(
        "topology.kubernetes.io/zone",
        complement=True,
        values={"z3", "z1"},
        greater_than=2,
        less_than=9,
        min_values=2,
    )


def sample_volume_usage() -> VolumeUsage:
    vu = VolumeUsage()
    vu.add_limit("ebs.csi", 4)
    vu.add_limit("nfs.csi", 2)
    vu.volumes = {"ebs.csi": {"default/pvc-a", "default/pvc-b"}}
    return vu


def sample_sim_node(name="existing-0") -> SimNode:
    from karpenter_core_tpu.api.objects import Taint
    from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
        EvictablePod,
    )

    return SimNode(
        name=name,
        labels={"karpenter.sh/nodepool": "default", "k": "v"},
        taints=[Taint(key="dedicated", value="gpu", effect="NoSchedule")],
        available={"cpu": 3.0, "memory": 8.0 * 2**30},
        capacity={"cpu": 4.0, "memory": 16.0 * 2**30},
        daemon_requests={"cpu": 0.1},
        initialized=False,
        nodeclaim_name="claim-0",
        nodepool_name="default",
        volume_usage=sample_volume_usage(),
        # gangsched: the evictable-capacity view rides the solve wire, in
        # the encoder's canonical (cost, uid) order so the round-trip is
        # an exact deep-equality (the encoder sorts; relist order is not
        # part of the wire)
        evictable=(
            EvictablePod(
                uid="victim-2", priority=-5,
                requests={"cpu": 1.0, "memory": 1.0 * 2**30}, cost=0.25,
            ),
            EvictablePod(
                uid="victim-1", priority=0,
                requests={"cpu": 0.5}, cost=1.0,
            ),
        ),
    )


def sample_topology() -> Topology:
    bound = make_pod(cpu=0.5, name="bound-0")
    return Topology(
        domains={"topology.kubernetes.io/zone": {"z1", "z2"}},
        existing_pods=[
            (bound, {"kubernetes.io/hostname": "existing-0"}, "existing-0")
        ],
        excluded_pod_uids=["uid-1", "uid-2"],
    )


def sample_problem() -> dict:
    catalog = fake_instance_types(4)
    return dict(
        nodepools=[make_nodepool(), make_nodepool(name="batch", weight=10)],
        # the same IT objects serve both pools: identity must survive
        instance_types={"default": catalog, "batch": catalog[:2]},
        existing_nodes=[sample_sim_node()],
        daemonset_pods=[make_pod(cpu=0.1, name="ds-0")],
        pods=[make_pod(cpu=1.0, name=f"p-{i}") for i in range(3)],
        topology=sample_topology(),
        max_slots=128,
        unavailable_offerings=frozenset(
            {OfferingKey("fake-2x", "z1", "spot")}
        ),
        # a non-default tenant so the fleet-gateway identity provably
        # survives the wire (the default would also pass a dropped field)
        tenant="tenant-a",
        # a non-default backend so the relaxsolve mode selector provably
        # survives the wire (ISSUE 13; same reasoning as the tenant)
        solver_mode="relax",
        # a non-empty prior-solve reference so the incsolve warm-start
        # key provably survives the wire (ISSUE 16; same reasoning) —
        # empty means "no predecessor" and is omitted from the header
        prev_fingerprint="a" * 24 + "+mrelax",
    )


# ---------------------------------------------------------------------------
# pairing + coverage (introspected)
# ---------------------------------------------------------------------------


def _codec_functions():
    return {
        name: fn
        for name, fn in vars(codec).items()
        if inspect.isfunction(fn)
    }


def test_every_encoder_has_a_decoder_and_vice_versa():
    fns = _codec_functions()
    for name in fns:
        if name.lstrip("_").startswith("encode_"):
            twin = name.replace("encode_", "decode_", 1)
            assert twin in fns, f"{name} has no {twin}"
        if name.lstrip("_").startswith("decode_"):
            twin = name.replace("decode_", "encode_", 1)
            assert twin in fns, f"{name} has no {twin}"


# every top-level encode entry must appear here; the test below fails the
# moment codec grows one this battery doesn't exercise
_ROUNDTRIPPED_ENTRIES = {
    "encode_request",
    "encode_response",
    "encode_solve_request",
    "encode_solve_results",
    "encode_frontier_request",
    "encode_frontier_response",
    # delta wire (ISSUE 14): round-tripped by the manifest parity battery
    # in tests/test_segments.py (manifest-path vs full-path equivalence
    # over the fuzz corpus) plus the unit roundtrip below
    "encode_manifest_request",
}


def test_roundtrip_battery_covers_every_top_level_entry():
    fns = _codec_functions()
    top = {n for n in fns if n.startswith("encode_")}
    missing = top - _ROUNDTRIPPED_ENTRIES
    assert not missing, (
        f"new top-level codec entries without a round-trip test: {missing}"
    )


# wire dataclass field pins: adding a field to one of these types without
# teaching the codec (and the samples above) trips the assertion
_WIRE_FIELDS = {
    SimNode: {
        "name", "labels", "taints", "available", "capacity",
        "daemon_requests", "initialized", "nodeclaim_name",
        "nodepool_name", "volume_usage", "evictable",
    },
    InstanceType: {"name", "requirements", "offerings", "capacity", "overhead"},
    Offering: {"requirements", "price", "available"},
    OfferingKey: {"instance_type", "zone", "capacity_type"},
}


def test_wire_dataclass_fields_are_covered():
    for cls, covered in _WIRE_FIELDS.items():
        if dataclasses.is_dataclass(cls):
            actual = {
                f.name
                for f in dataclasses.fields(cls)
                if not f.name.startswith("_")
            }
        else:  # NamedTuple
            actual = set(cls._fields)
        assert actual == covered, (
            f"{cls.__name__} fields changed: {sorted(actual ^ covered)} —"
            " update solver/codec.py AND this battery together"
        )
    assert set(Requirement.__slots__) == {
        "key", "complement", "values", "greater_than", "less_than",
        "min_values",
    }, "Requirement grew a slot: update codec._encode_req/_decode_req too"


# ---------------------------------------------------------------------------
# round trips
# ---------------------------------------------------------------------------


def test_requirement_roundtrip():
    r = sample_requirement()
    assert_deep_eq(
        r, codec._decode_req(codec._encode_req(r)), "Requirement"
    )


def test_instance_type_roundtrip():
    it = fake_instance_types(3)[2]
    back = codec._decode_instance_type(codec._encode_instance_type(it))
    assert_deep_eq(it, back, "InstanceType")


def test_sim_node_roundtrip():
    n = sample_sim_node()
    assert_deep_eq(n, codec._decode_sim_node(codec._encode_sim_node(n)), "SimNode")


def test_volume_usage_roundtrip():
    vu = sample_volume_usage()
    back = codec._decode_volume_usage(codec._encode_volume_usage(vu))
    assert_deep_eq(vu.limits, back.limits, "VolumeUsage.limits")
    assert_deep_eq(vu.volumes, back.volumes, "VolumeUsage.volumes")
    assert codec._decode_volume_usage(codec._encode_volume_usage(None)) is None


def test_topology_roundtrip():
    topo = sample_topology()
    back = codec._decode_topology(codec._encode_topology(topo))
    assert_deep_eq(topo.domains, back.domains, "Topology.domains")
    assert_deep_eq(
        topo.excluded_pods, back.excluded_pods, "Topology.excluded_pods"
    )
    assert len(back.existing_pods) == 1
    pod, labels, name = back.existing_pods[0]
    assert_deep_eq(topo.existing_pods[0][0], pod, "Topology existing pod")
    assert labels == topo.existing_pods[0][1] and name == "existing-0"
    assert codec._decode_topology(codec._encode_topology(None)) is None


def test_solve_request_roundtrip_field_for_field():
    """Every parameter of encode_solve_request must survive to the decoded
    kwargs dict under the same name — introspected from the signature, so
    a new parameter without a decode counterpart fails here."""
    problem = sample_problem()
    data = codec.encode_solve_request(**problem)
    decoded = codec.decode_solve_request(data)
    for param in inspect.signature(codec.encode_solve_request).parameters:
        assert param in decoded, (
            f"encode_solve_request param {param!r} missing from decode —"
            " the field only landed on one side of the wire"
        )
        if param == "topology":
            t, b = problem[param], decoded[param]
            assert_deep_eq(t.domains, b.domains, "topology.domains")
            assert_deep_eq(
                t.excluded_pods, b.excluded_pods, "topology.excluded"
            )
            continue
        got, want = decoded[param], problem[param]
        if param in ("nodepools", "existing_nodes", "daemonset_pods"):
            # these wire lists travel in canonical sorted order (they are
            # hashed positionally by problem_fingerprint); their decode
            # semantics are order-insensitive, so compare canonically
            def _key(o):
                return getattr(o, "name", "") or o.metadata.name

            got = sorted(got, key=_key)
            want = sorted(want, key=_key)
        assert_deep_eq(want, got, f"solve.{param}")
    assert decoded["fingerprint"] == codec.problem_fingerprint(
        codec._json_header(data)
    )
    # instance-type object identity survives the table encoding
    its = decoded["instance_types"]
    assert its["batch"][0] is its["default"][0]
    assert its["batch"][1] is its["default"][1]


def test_evictable_priority_clamps_at_the_decode_net():
    """A hostile/corrupt wire priority far past int32 must clamp at decode
    (utils/disruption.priority_tier — the legitimate encoder side already
    ships a tier): unclamped it would overflow the int32 EvPlanes tensor
    INSIDE the exclusive device window, a crash charged as poison where a
    cheap rejection belongs."""
    from karpenter_core_tpu.utils.disruption import priority_tier

    problem = sample_problem()
    data = codec.encode_solve_request(**problem)
    header = codec._json_header(data)
    ev = header["existing_nodes"][0]["evictable"]
    assert ev, "sample node lost its evictable view"
    ev[0]["priority"] = 10**18
    decoded = codec.decode_solve_request(codec._json_payload(header))
    prio = decoded["existing_nodes"][0].evictable[0].priority
    assert prio == priority_tier(10**18)
    import numpy as np

    np.full((1,), prio, dtype=np.int32)  # the EvPlanes store must not raise


def test_manifest_request_roundtrip_matches_full_decode():
    """The delta wire's top-level entry (ISSUE 14): a manifest body
    decodes to the SAME problem dict as the full wire — fingerprint,
    bucket, pod order, node set — through a fresh segment store. The
    deeper equivalences (result-wire parity over the fuzz corpus, the
    miss protocol) live in tests/test_segments.py."""
    from karpenter_core_tpu.solver import segments as segmod

    problem = sample_problem()
    full = codec.decode_solve_request(
        codec.encode_solve_request(**problem)
    )
    plan = segmod.split_solve_header(
        codec._encode_solve_header(**problem)
    )
    man = codec.decode_manifest_request(
        codec.encode_manifest_request(plan),
        segment_store=segmod.SegmentStore(),
    )
    assert man["fingerprint"] == full["fingerprint"] == plan.fingerprint
    assert man["bucket"] == full["bucket"]
    assert man["wire_kind"] == "manifest" and full["wire_kind"] == "full"
    assert [p.uid for p in man["pods"]] == [p.uid for p in full["pods"]]
    assert [n.name for n in man["existing_nodes"]] == [
        n.name for n in full["existing_nodes"]
    ]
    assert man["tenant"] == full["tenant"]
    assert man["solver_mode"] == full["solver_mode"]
    assert man["unavailable_offerings"] == full["unavailable_offerings"]


def test_solve_request_wire_bytes_are_canonical():
    """Same logical problem, different host-side dict insertion order ->
    byte-identical wire (and therefore an identical problem fingerprint):
    the property the GL201 sweep of codec/vocab established."""
    problem = sample_problem()
    problem["existing_nodes"] = problem["existing_nodes"] + [
        sample_sim_node("existing-1")
    ]
    flipped = dict(problem)
    flipped["instance_types"] = dict(
        reversed(list(problem["instance_types"].items()))
    )
    flipped["nodepools"] = list(reversed(problem["nodepools"]))
    flipped["existing_nodes"] = list(reversed(problem["existing_nodes"]))
    flipped["daemonset_pods"] = list(reversed(problem["daemonset_pods"]))
    a = codec.encode_solve_request(**problem)
    b = codec.encode_solve_request(**flipped)
    assert codec.problem_fingerprint(
        codec._json_header(a)
    ) == codec.problem_fingerprint(codec._json_header(b))


def test_solve_results_roundtrip():
    from types import SimpleNamespace as NS

    catalog = fake_instance_types(2)
    results = NS(
        new_node_claims=[
            NS(
                template=NS(nodepool_name="default"),
                instance_type_options=catalog,
                requirements={
                    sample_requirement().key: sample_requirement(),
                },
                requests={"cpu": 2.0},
                pods=[NS(uid="u-1"), NS(uid="u-2")],
            )
        ],
        existing_nodes=[NS(name="existing-0", pods=[NS(uid="u-3")])],
        pod_errors={"u-9": "unschedulable"},
    )
    decoded = codec.decode_solve_results(
        codec.encode_solve_results(results, solve_seconds=0.25)
    )
    claim = decoded["claims"][0]
    assert claim["nodepool"] == "default"
    assert claim["instance_types"] == [it.name for it in catalog]
    assert claim["pod_uids"] == ["u-1", "u-2"]
    assert claim["requests"] == {"cpu": 2.0}
    assert_deep_eq(
        sample_requirement(),
        claim["requirements"][sample_requirement().key],
        "claim requirements",
    )
    assert decoded["existing"] == [
        {"node": "existing-0", "pod_uids": ["u-3"]}
    ]
    assert decoded["errors"] == {"u-9": "unschedulable"}
    assert decoded["solve_seconds"] == 0.25


def test_frontier_request_roundtrip():
    problem = sample_problem()
    kwargs = dict(
        nodepools=problem["nodepools"],
        instance_types=problem["instance_types"],
        cand_nodes=[sample_sim_node("cand-0")],
        keep_nodes=[sample_sim_node("keep-0")],
        daemonset_pods=problem["daemonset_pods"],
        base_pods=problem["pods"][:1],
        candidate_pods=[problem["pods"][1:]],
        max_slots=64,
        tenant="tenant-a",
    )
    decoded = codec.decode_frontier_request(
        codec.encode_frontier_request(**kwargs)
    )
    for param in inspect.signature(
        codec.encode_frontier_request
    ).parameters:
        assert param in decoded
        assert_deep_eq(kwargs[param], decoded[param], f"frontier.{param}")


def test_frontier_response_roundtrip():
    frontier = [(True, 0, 0.0), (False, 3, 12.5)]
    assert codec.decode_frontier_response(
        codec.encode_frontier_response(frontier)
    ) == frontier
    assert codec.decode_frontier_response(
        codec.encode_frontier_response(None)
    ) is None


def test_snapshot_request_response_roundtrip():
    from karpenter_core_tpu.solver.snapshot import encode_snapshot

    pods = [make_pod(cpu=1.0, name=f"p-{i}") for i in range(4)]
    snap, _extra, _taints = encode_snapshot(pods, fake_instance_types(3))
    data = codec.encode_request(
        snap.vocab,
        snap.resource_names,
        snap.class_masks,
        snap.class_requests,
        snap.class_counts,
        snap.it_masks,
        snap.it_allocatable,
    )
    vocab, names, cm, creq, ccnt, im, alloc = codec.decode_request(data)
    assert names == snap.resource_names
    assert vocab.fingerprint() == snap.vocab.fingerprint()
    for got, want in (
        (cm.mask, snap.class_masks.mask),
        (cm.gt, snap.class_masks.gt),
        (im.mask, snap.it_masks.mask),
        (creq, snap.class_requests),
        (ccnt, snap.class_counts),
        (alloc, snap.it_allocatable),
    ):
        assert np.array_equal(got, want)

    takes = np.arange(12, dtype=np.int32).reshape(3, 4)
    unplaced = np.array([0, 1, 0], dtype=np.int32)
    slot_template = np.array([0, -1, 2, 1], dtype=np.int32)
    t2, u2, s2 = codec.decode_response(
        codec.encode_response(takes, unplaced, slot_template)
    )
    assert np.array_equal(t2, takes)
    assert np.array_equal(u2, unplaced)
    assert np.array_equal(s2, slot_template)


def test_version_skew_is_explicit_everywhere():
    """Every decoder rejects a foreign wire version loudly (the GL401
    finding this PR fixed on decode_request)."""
    problem = sample_problem()
    blob = codec.encode_solve_request(**problem)
    hacked = codec._json_payload(
        {**codec._json_header(blob), "version": 99}
    )
    with pytest.raises(ValueError, match="version"):
        codec.decode_solve_request(hacked)

    snap_blob = codec._json_payload({"version": 99})
    with pytest.raises(ValueError, match="version"):
        codec.decode_request(snap_blob)
