"""Chaos-hardened reconcile: fault-isolated controllers, the
unavailable-offerings (ICE) cache, and the seeded chaos harness.

Three failure domains under test end-to-end:
* a controller exception is isolated to its own requeue backoff — the pass
  survives, the error is observable (metric + Warning event), and repeated
  crash-looping degrades readyz;
* a capacity stockout (typed ICE) marks the offering unavailable for a TTL
  so the re-solve lands on the next-cheapest AVAILABLE offering on BOTH
  solve paths — no create→ICE→delete livelock — and the offering returns
  to service after expiry;
* under a seeded schedule of store conflicts, 429s, latency, ICE storms and
  provider create/delete faults the operator still converges: all
  provisionable pods bound, nothing leaked, and identical seeds replay
  identical event traces.
"""
import itertools

import pytest

from tests.helpers import make_nodepool, make_pod
from tests.test_e2e import CATALOG, new_operator, replicated
from tests.test_soak import assert_coherent

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.chaos import (
    ChaosCloudProvider,
    ChaosKubeClient,
    ChaosSchedule,
    IceStorm,
)
from karpenter_core_tpu.cloudprovider.kwok import KwokCloudProvider, build_catalog
from karpenter_core_tpu.cloudprovider.types import OfferingKey
from karpenter_core_tpu.cloudprovider.unavailableofferings import (
    UNAVAILABLE_OFFERINGS_TTL,
    UnavailableOfferings,
)
from karpenter_core_tpu.kube.store import ConflictError, KubeStore
from karpenter_core_tpu.operator import (
    CRASHLOOP_THRESHOLD,
    Operator,
    Options,
)
from karpenter_core_tpu.utils.clock import FakeClock


def _reset_claim_counter():
    """Claim names draw from a process-global counter; reproducibility
    assertions compare event traces across runs, so each run restarts it."""
    from karpenter_core_tpu.controllers.provisioning.scheduling import (
        nodeclaimtemplate,
    )

    nodeclaimtemplate._claim_counter = itertools.count(1)


def _bound_offering(op, pod_name: str) -> OfferingKey:
    from karpenter_core_tpu.api.objects import Node, Pod

    pod = op.kube.get(Pod, pod_name)
    assert pod is not None and pod.node_name, f"{pod_name} not bound"
    node = op.kube.get(Node, pod.node_name)
    return OfferingKey(
        node.labels[L.LABEL_INSTANCE_TYPE],
        node.labels[L.LABEL_TOPOLOGY_ZONE],
        node.labels[L.CAPACITY_TYPE_LABEL_KEY],
    )


class TestUnavailableOfferings:
    def test_mark_expire_and_snapshot(self):
        clock = FakeClock()
        cache = UnavailableOfferings(clock)
        key = OfferingKey("c-1x", "zone-a", "spot")
        assert not cache.is_unavailable(key)
        cache.mark(key)
        assert cache.is_unavailable(key)
        # plain tuples are the same identity (the wire decodes to tuples)
        assert cache.is_unavailable(("c-1x", "zone-a", "spot"))
        assert cache.snapshot() == frozenset([key])
        clock.step(UNAVAILABLE_OFFERINGS_TTL - 1.0)
        assert cache.is_unavailable(key)
        # re-marking refreshes the TTL
        cache.mark(key)
        clock.step(2.0)
        assert cache.is_unavailable(key)
        clock.step(UNAVAILABLE_OFFERINGS_TTL)
        assert not cache.is_unavailable(key)
        assert cache.snapshot() == frozenset()

    def test_default_operator_shares_one_cache_with_its_provider(self):
        """Regression: UnavailableOfferings is falsy when empty (len 0), so
        `passed_cache or own_cache` silently split lifecycle's cache from
        the provider's create-pick cache. Every construction path must end
        with ONE shared instance."""
        op = Operator(clock=FakeClock())  # default kwok provider
        assert op.cloud_provider.unavailable_offerings is op.unavailable_offerings
        assert op.lifecycle.unavailable_offerings is op.unavailable_offerings
        assert op.provisioner.unavailable_offerings is op.unavailable_offerings
        # externally-built provider: the operator adopts ITS cache
        op2 = new_operator()
        assert (
            op2.cloud_provider.unavailable_offerings
            is op2.unavailable_offerings
        )


class TestCapacityStockout:
    """The acceptance scenario: cheapest offering ICE'd -> pods land on the
    next-cheapest AVAILABLE offering within one re-solve, on both paths."""

    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    def test_stockout_resolves_to_next_cheapest(self, solver):
        # discover what an unconstrained run picks (the cheapest offering)
        probe = new_operator(solver)
        probe.kube.create(make_nodepool())
        probe.kube.create(make_pod(cpu=1.0, name="probe"))
        probe.run_until_idle()
        cheapest = _bound_offering(probe, "probe")

        # fresh world with that offering's capacity actually out
        op = new_operator(solver)
        op.cloud_provider.stockouts.add(cheapest)
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="p0"))
        iters = op.run_until_idle(max_iters=60)
        assert iters < 60, "stockout livelocked the reconcile loop"

        landed = _bound_offering(op, "p0")
        assert landed != cheapest
        # exactly one create->ICE->cache round, not a livelock
        ice_events = op.recorder.with_reason("InsufficientCapacity")
        assert len(ice_events) == 1, [e.message for e in ice_events]
        assert op.unavailable_offerings.is_unavailable(cheapest)
        # exactly one claim survives (the failed one was deleted)
        assert len(op.kube.list_nodeclaims()) == 1

        # TTL expiry returns the offering to service: capacity is back and
        # the cache entry lapses, so a new pod lands on the cheapest again
        op.cloud_provider.stockouts.clear()
        op.clock.step(UNAVAILABLE_OFFERINGS_TTL + 1.0)
        op.kube.create(make_pod(cpu=1.0, name="p1"))
        op.run_until_idle(max_iters=60)
        assert not op.unavailable_offerings.is_unavailable(cheapest)
        assert _bound_offering(op, "p1") == cheapest

    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    def test_pinned_pod_fails_when_its_only_offering_is_iced(self, solver):
        """A pod pinned to the stocked-out zone+capacity-type must FAIL the
        solve (no offering), not get placed onto the masked row — this
        exercises the greedy offering filter and the device off_avail
        tensor mask directly."""
        op = new_operator(solver)
        op.kube.create(make_nodepool())
        # pin to zone-a spot, then mark every (it, zone-a, spot) unavailable
        for it in CATALOG:
            op.unavailable_offerings.mark(
                OfferingKey(it.name, "zone-a", L.CAPACITY_TYPE_SPOT),
                ttl=10_000.0,
            )
        pod = make_pod(
            cpu=1.0,
            name="pinned",
            zone_in=["zone-a"],
            node_selector={L.CAPACITY_TYPE_LABEL_KEY: L.CAPACITY_TYPE_SPOT},
        )
        op.kube.create(pod)
        op.run_until_idle(max_iters=40)
        from karpenter_core_tpu.api.objects import Pod

        assert not op.kube.get(Pod, "pinned").node_name
        assert not op.kube.list_nodeclaims()

    def test_codec_round_trips_unavailable_offerings(self):
        from karpenter_core_tpu.solver import codec

        keys = frozenset(
            [
                OfferingKey("c-1x-amd64-linux", "zone-a", "spot"),
                OfferingKey("m-2x-arm64-linux", "zone-c", "on-demand"),
            ]
        )
        data = codec.encode_solve_request(
            [], {}, [], [], [], unavailable_offerings=keys
        )
        out = codec.decode_solve_request(data)
        assert out["unavailable_offerings"] == keys


class TestReconcileIsolation:
    def _broken(self, op, controller_attr="garbage_collection"):
        calls = {"n": 0}

        def boom():
            calls["n"] += 1
            raise RuntimeError("chaos monkey")

        getattr(op, controller_attr).reconcile = boom
        return calls

    def test_exception_is_isolated_and_observable(self):
        from karpenter_core_tpu.metrics import wiring as m

        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="p0"))
        calls = self._broken(op)
        before = m.RECONCILE_ERRORS.value(
            {"controller": "nodeclaim.gc", "error": "RuntimeError"}
        )
        op.run_until_idle()  # the pass survives; provisioning proceeds
        assert all(p.node_name for p in op.kube.list_pods())
        assert calls["n"] >= 1
        assert m.RECONCILE_ERRORS.value(
            {"controller": "nodeclaim.gc", "error": "RuntimeError"}
        ) > before
        events = [
            e for e in op.recorder.with_reason("ReconcileError")
            if e.involved_object == "Controller/nodeclaim.gc"
        ]
        assert events and events[0].type == "Warning"

    def test_backoff_skips_until_elapsed(self):
        op = new_operator()
        calls = self._broken(op)
        op.reconcile_once()
        assert calls["n"] == 1
        op.reconcile_once()  # same instant: still on 1s backoff
        assert calls["n"] == 1
        op.clock.step(1.01)
        op.reconcile_once()
        assert calls["n"] == 2

    def test_crash_loop_flips_readyz_and_recovery_restores_it(self):
        op = new_operator()
        assert op.readyz()
        calls = self._broken(op)
        for _ in range(CRASHLOOP_THRESHOLD):
            op.reconcile_once()
            op.clock.step(120.0)  # past any backoff
        assert calls["n"] == CRASHLOOP_THRESHOLD
        assert not op.readyz()
        # controller recovers -> next clean pass clears the fault state
        op.garbage_collection.reconcile = lambda: None
        op.reconcile_once()
        assert op.readyz()

    def test_broken_object_does_not_starve_controller_siblings(self):
        """One perpetually-broken claim must not stop the lifecycle
        controller from reconciling OTHER claims, and must not flip readyz
        while the controller demonstrably still works (the fault state
        clears on the next successful invocation)."""
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="p0"))

        real = op.lifecycle.reconcile
        broken_name = {"value": None}

        def selective(claim):
            # break the FIRST claim seen, forever; others reconcile fine
            if broken_name["value"] in (None, claim.name):
                broken_name["value"] = claim.name
                raise RuntimeError("broken object")
            return real(claim)

        op.lifecycle.reconcile = selective
        op.run_until_idle(max_iters=60)
        # a second pod arrives: its fresh claim must still launch and bind
        # even though the first claim keeps crashing its reconciler
        op.kube.create(make_pod(cpu=1.0, name="p1"))
        op.run_until_idle(max_iters=60)
        from karpenter_core_tpu.api.objects import Pod

        assert op.kube.get(Pod, "p1").node_name
        assert op.readyz()

    def test_fault_clears_when_failing_workload_vanishes(self):
        """A controller crash-looping on one object must not pin readyz
        false after that object (and all its workload) is gone — the stale
        fault entry drops on the first pass with nothing to reconcile."""
        op = new_operator()
        pool = make_nodepool()
        op.kube.create(pool)

        def boom(p):
            raise RuntimeError("bad pool")

        op.nodepool_hash.reconcile = boom
        for _ in range(CRASHLOOP_THRESHOLD):
            op.reconcile_once()
            op.clock.step(120.0)
        assert not op.readyz()
        op.kube.delete(pool)  # the failing workload vanishes
        op.clock.step(120.0)
        op.reconcile_once()
        assert op.readyz()

    def test_conflicts_requeue_but_never_crash_loop(self):
        """Injected optimistic-lock conflicts in ANY controller back off
        like errors but must not degrade readyz — they are expected
        races, not crashes (the termination-consistency story applied
        uniformly)."""
        op = new_operator()

        def race():
            raise ConflictError("stale resource_version")

        op.garbage_collection.reconcile = race
        for _ in range(CRASHLOOP_THRESHOLD + 2):
            op.reconcile_once()
            op.clock.step(120.0)
        assert op.readyz()
        # but they ARE observable as reconcile errors
        assert op.recorder.with_reason("ReconcileError")

    def test_provisioning_failure_does_not_kill_the_pass(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="p0"))

        def explode():
            raise RuntimeError("solver meltdown")

        original = op.provisioner.provision
        op.provisioner.provision = explode
        op.reconcile_once()  # must not raise
        assert not any(p.node_name for p in op.kube.list_pods())
        # recovery: the batcher self-heal window re-solves the pending pods
        op.provisioner.provision = original
        op.clock.step(2.0)
        op.run_until_idle()
        assert all(p.node_name for p in op.kube.list_pods())


class TestTerminationConflict:
    def test_stale_resource_version_is_requeued_not_raised(self):
        """Regression: a ConflictError on the termination controller's
        node/claim writes used to propagate (and kill the pass); it now
        requeues — drop the pass, retry against the fresh object."""
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle()
        from karpenter_core_tpu.api.objects import Node

        node = op.kube.list_nodes()[0]
        op.kube.delete(node)  # finalizer holds it; termination drains

        real_update = op.kube.update
        state = {"raised": False}

        def stale_once(obj):
            if isinstance(obj, Node) and not state["raised"]:
                state["raised"] = True
                raise ConflictError("stale resource_version (chaos)")
            return real_update(obj)

        op.kube.update = stale_once
        op.termination.reconcile(node)  # must not raise
        assert state["raised"]
        op.kube.update = real_update
        op.run_until_idle()
        assert node.name not in {n.name for n in op.kube.list_nodes()}


class TestHttpClientRetry:
    def _client(self, responses, fail_after=None):
        from karpenter_core_tpu.kube.httpclient import HttpKubeClient

        c = HttpKubeClient("127.0.0.1", 1, retry_backoff=0.001)
        log = {"attempts": [], "sleeps": []}
        queue = list(responses)

        def fake(method, path, payload=None):
            log["attempts"].append((method, path))
            return queue.pop(0)

        c._do_request = fake
        c._sleep = log["sleeps"].append
        return c, log

    def test_get_retries_transient_5xx_then_succeeds(self):
        c, log = self._client([
            (503, {"error": "apiserver warming"}),
            (429, {"error": "slow down"}),
            (200, {"items": []}),
        ])
        assert c.list_pods() == []
        assert len(log["attempts"]) == 3
        # exponential: 1x, then 2x the base backoff
        assert log["sleeps"] == [0.001, 0.002]

    def test_get_retry_budget_is_bounded(self):
        from karpenter_core_tpu.kube.store import TooManyRequestsError

        c, log = self._client([(429, {"error": "n"})] * 4)
        with pytest.raises(TooManyRequestsError):
            c.list_pods()
        assert len(log["attempts"]) == 4  # 1 + GET_RETRIES

    def test_writes_are_never_retried(self):
        c, log = self._client([(503, {"error": "blip"})])
        with pytest.raises(RuntimeError):
            c._request("POST", "/bind", {"name": "p"})
        assert len(log["attempts"]) == 1
        assert log["sleeps"] == []


# -- the seeded chaos harness ------------------------------------------------


def _chaos_operator(seed: int, solver: str = "greedy", storms=(), rates=None):
    _reset_claim_counter()
    clock = FakeClock()
    store = KubeStore(clock)
    schedule = ChaosSchedule(
        seed=seed,
        rates=rates
        if rates is not None
        else {
            "kube.create.conflict": 0.08,
            "kube.update.conflict": 0.05,
            "kube.update.too_many_requests": 0.03,
            "kube.bind.conflict": 0.05,
            "kube.delete.too_many_requests": 0.04,
            "kube.evict.latency": 0.10,
            "cloud.create.create_error": 0.06,
            "cloud.create.insufficient_capacity": 0.04,
            "cloud.delete.delete_error": 0.06,
        },
    )
    # the operator reconciles through the chaotic client; the provider
    # materializes its fake nodes on the raw store (a provider is its own
    # system, not a client of the apiserver under test)
    provider = ChaosCloudProvider(
        KwokCloudProvider(store, CATALOG), schedule, storms=storms, clock=clock
    )
    kube = ChaosKubeClient(store, schedule)
    op = Operator(
        kube=kube,
        cloud_provider=provider,
        clock=clock,
        options=Options(solver=solver),
    )
    # workload churn (the test's own creates/deletes) models users whose
    # requests already landed: it goes through the raw store, while every
    # controller write rides the chaotic client
    return op, schedule, store


def _run_chaos_scenario(seed: int, solver: str = "greedy", waves: int = 3,
                        pods_per_wave: int = 4):
    cheapest = CATALOG[0].name  # ICE storm over a slice of the catalog
    storm = IceStorm(
        start=1_000_000.0 + 5.0,
        duration=90.0,
        offerings=tuple(
            OfferingKey(it.name, zone, ct)
            for it in CATALOG[:6]
            for zone in ("zone-a", "zone-b")
            for ct in (L.CAPACITY_TYPE_SPOT,)
        ),
    )
    assert cheapest  # storm covers the head of the catalog
    op, schedule, store = _chaos_operator(seed, solver=solver, storms=[storm])
    store.create(make_nodepool())
    serial = 0
    for wave in range(waves):
        for _ in range(pods_per_wave):
            store.create(replicated(make_pod(
                cpu=[0.5, 1.0, 2.0][serial % 3], name=f"w{serial}"
            )))
            serial += 1
        op.run_until_idle(max_iters=400)
        op.clock.step(61.0)  # past backoff caps and into/through the storm
        op.run_until_idle(max_iters=400)
    # storm over + caches expired: the world must settle coherent
    op.clock.step(UNAVAILABLE_OFFERINGS_TTL + 1.0)
    op.run_until_idle(max_iters=400)
    return op, schedule


class TestChaosSmoke:
    """Tier-1 fixed-seed smoke: convergence invariants under the full fault
    mix. reconcile_once never raises by construction of the isolation
    wrapper — the run itself would fail loudly if it did."""

    def test_converges_under_faults(self):
        op, schedule = _run_chaos_scenario(seed=42)
        assert schedule.draws > 0
        assert_coherent(op)
        assert op.readyz()

    def test_identical_seeds_reproduce_identical_event_traces(self):
        def trace(op):
            return [
                (e.involved_object, e.reason, e.message, e.timestamp)
                for e in op.recorder.events
            ]

        op1, s1 = _run_chaos_scenario(seed=7)
        op2, s2 = _run_chaos_scenario(seed=7)
        assert s1.draws == s2.draws
        assert trace(op1) == trace(op2)
        assert {n.name for n in op1.kube.list_nodes()} == {
            n.name for n in op2.kube.list_nodes()
        }

    def test_scripted_faults_consume_in_order(self):
        # the remote.py FaultInjector contract, generalized per seam
        s = ChaosSchedule(
            seed=0,
            script={"kube.create": ["conflict", "ok", "too_many_requests"]},
        )
        faults = [
            s.next_fault("kube.create", ChaosKubeClient.WRITE_FAULTS)
            for _ in range(4)
        ]
        assert faults == ["conflict", "ok", "too_many_requests", "ok"]


class TestSeamStreams:
    """Per-seam child RNG streams (ISSUE 15 satellite): each seam's fault
    sequence is a pure function of (seed, seam, its own rate keys) — the
    monotonicity the twin's shrinker leans on when it drops one fault
    class from a failing scenario."""

    RATES_A = {"kube.create.conflict": 0.3, "kube.create.latency": 0.2}
    RATES_B = {"cloud.create.create_error": 0.25}
    KUBE_FAULTS = ChaosKubeClient.WRITE_FAULTS
    CLOUD_FAULTS = ("create_error", "insufficient_capacity")

    def _cloud_seq(self, schedule, n=40):
        return [
            schedule.next_fault("cloud.create", self.CLOUD_FAULTS)
            for _ in range(n)
        ]

    def test_editing_one_seam_leaves_another_seams_sequence_identical(self):
        both = ChaosSchedule(seed=9, rates={**self.RATES_A, **self.RATES_B})
        # interleave heavy kube.create traffic between cloud draws
        ref = []
        for _ in range(40):
            both.next_fault("kube.create", self.KUBE_FAULTS)
            ref.append(both.next_fault("cloud.create", self.CLOUD_FAULTS))
        # (a) REMOVE the kube seam's rates entirely: cloud unchanged
        solo = ChaosSchedule(seed=9, rates=dict(self.RATES_B))
        assert self._cloud_seq(solo) == ref
        # (b) kube seam present but drawn a DIFFERENT number of times:
        # cloud's stream must not shift (the pre-ISSUE-15 failure mode)
        skewed = ChaosSchedule(seed=9, rates={**self.RATES_A, **self.RATES_B})
        for _ in range(7):
            skewed.next_fault("kube.create", self.KUBE_FAULTS)
        assert self._cloud_seq(skewed) == ref

    def test_same_seed_same_seam_replays(self):
        a = ChaosSchedule(seed=4, rates=dict(self.RATES_B))
        b = ChaosSchedule(seed=4, rates=dict(self.RATES_B))
        assert self._cloud_seq(a) == self._cloud_seq(b)
        c = ChaosSchedule(seed=5, rates=dict(self.RATES_B))
        assert self._cloud_seq(c) != self._cloud_seq(a)

    def test_seam_draw_ledger(self):
        s = ChaosSchedule(seed=0, rates=dict(self.RATES_B))
        self._cloud_seq(s, n=5)
        s.next_fault("kube.create", self.KUBE_FAULTS)
        assert s.seam_draws == {"cloud.create": 5, "kube.create": 1}
        assert s.draws == 6


# ---------------------------------------------------------------------------
# device-tier chaos (ISSUE 8): wedged solves, corrupt wire, poison pills
# ---------------------------------------------------------------------------


def _device_chaos_rig(schedule: ChaosSchedule, watchdog_seconds=0.0,
                      wedge_seconds=0.4, quarantine_strikes=3):
    """Operator (sidecar mode, FakeClock) wired to an IN-THREAD chaotic
    solverd: the SolverChaos injector perturbs the device tier while the
    operator reconciles through it. Returns (op, daemon, chaos, srv)."""
    from karpenter_core_tpu.chaos import SolverChaos
    from karpenter_core_tpu.solver import fleet, service

    chaos = SolverChaos(schedule, wedge_seconds=wedge_seconds)
    daemon = service.SolverDaemon(
        watchdog_seconds=watchdog_seconds,
        chaos=chaos,
        quarantine=fleet.PoisonQuarantine(
            strikes=quarantine_strikes, site="gateway"
        ),
    )
    srv = service.serve(0, daemon=daemon)
    addr = f"127.0.0.1:{srv.server_address[1]}"
    _reset_claim_counter()
    clock = FakeClock()
    kube = KubeStore(clock)
    op = Operator(
        kube=kube,
        cloud_provider=KwokCloudProvider(kube, CATALOG),
        clock=clock,
        options=Options(
            solver="tpu", solver_mode="sidecar", solver_addr=addr,
            solver_timeout=60.0,
        ),
    )
    # degradations must be cheap in-test: no real backoff sleeps
    op.solver_client.sleep = lambda s: None
    op.solver_client.max_retries = 0
    return op, daemon, chaos, srv


class TestDeviceTierChaosSmoke:
    """Tier-1 fixed-script smoke: one corrupt wire + one lying result +
    one poison crash, every pod still binds, and the device path (not a
    stuck breaker) serves the clean tail."""

    def test_corrupt_and_lying_results_degrade_then_recover(self):
        from karpenter_core_tpu.metrics import wiring as m
        from karpenter_core_tpu.solver.remote import STATE_CLOSED

        schedule = ChaosSchedule(seed=5, script={
            "solverd.solve": ["corrupt_wire", "bad_result", "crash"],
        })
        op, daemon, chaos, srv = _device_chaos_rig(schedule)
        try:
            op.kube.create(make_nodepool())
            rejected_before = m.SOLVER_RESULT_REJECTED.value(
                {"reason": "conservation", "path": "sidecar"}
            )
            for wave in range(4):
                for i in range(3):
                    op.kube.create(replicated(make_pod(
                        cpu=1.0, name=f"dc{wave}-{i}"
                    )))
                op.run_until_idle(max_iters=200, disrupt=False)
            assert all(p.node_name for p in op.kube.list_pods())
            # each scripted fault consumed and survived
            assert chaos.injected == {
                "corrupt_wire": 1, "bad_result": 1, "crash": 1,
            }
            assert m.SOLVER_RESULT_REJECTED.value(
                {"reason": "conservation", "path": "sidecar"}
            ) == rejected_before + 1
            # the breaker recovered: the clean tail runs the device path
            assert op.solver_client.breaker.state == STATE_CLOSED
            assert_coherent(op)
        finally:
            op.shutdown()
            srv.shutdown()
            srv.server_close()


@pytest.mark.slow
class TestDeviceTierChaosSoak:
    """The acceptance soak: seeded wedge + crash (poison) + corrupt wire +
    lying results, plus real-sidecar murder, while a second clean tenant
    shares the same solverd. The operator must keep reaching greedy-parity
    node counts, the breaker must recover, and the unaffected tenant's
    queue wait must stay bounded."""

    def test_soak_device_faults_reach_greedy_parity(self):
        import random
        import threading as _threading

        from karpenter_core_tpu.metrics import wiring as m
        from karpenter_core_tpu.solver import remote
        from karpenter_core_tpu.solver.remote import STATE_CLOSED

        # the script guarantees each fault class fires at least once —
        # with a concurrent tenant the RATE draws interleave
        # nondeterministically, so "did crash ever fire" must not hang on
        # the dice; the rates then keep the pressure on for the rest
        schedule = ChaosSchedule(
            seed=1234,
            script={"solverd.solve": [
                "crash", "corrupt_wire", "bad_result", "wedge:0.4",
            ]},
            rates={
                "solverd.solve.wedge": 0.04,
                "solverd.solve.crash": 0.12,
                "solverd.solve.corrupt_wire": 0.12,
                "solverd.solve.bad_result": 0.12,
            },
        )
        op, daemon, chaos, srv = _device_chaos_rig(
            schedule, watchdog_seconds=0.15, wedge_seconds=0.4
        )
        rng = random.Random(77)
        # replayed into the greedy-parity twin WAVE BY WAVE: incremental
        # provisioning packs into whatever already launched, so a one-shot
        # twin would undercount nodes and fail every honest run
        pod_waves = []

        # the unaffected tenant: a clean problem hammered through its own
        # RemoteScheduler at the SAME gateway (distinct tenant id; chaos
        # draws hit it too — that's life on a shared sidecar — but its
        # QUEUE WAIT is what fairness must bound)
        stop = _threading.Event()
        tenant_errors = []

        def clean_tenant():
            try:
                pools = [make_nodepool(name="tenant-b")]
                its = {"tenant-b": list(CATALOG)}
                client = remote.SolverClient(
                    f"127.0.0.1:{srv.server_address[1]}",
                    timeout=60, max_retries=0, sleep=lambda s: None,
                    tenant="tenant-b",
                )
                rs = remote.RemoteScheduler(client, pools, its)
                pods = [make_pod(cpu=0.5, name=f"tb{i}") for i in range(6)]
                while not stop.is_set():
                    res = rs.solve(pods)
                    assert res.all_pods_scheduled()
            except Exception as e:  # surfaced after join
                tenant_errors.append(repr(e))

        hammer = _threading.Thread(target=clean_tenant, daemon=True)
        hammer.start()
        try:
            op.kube.create(make_nodepool())
            serial = 0
            for cycle in range(8):
                wave = []
                for _ in range(rng.randint(2, 5)):
                    cpu = rng.choice([0.5, 1.0, 2.0])
                    wave.append((f"dv{serial}", cpu))
                    op.kube.create(replicated(make_pod(
                        cpu=cpu, name=f"dv{serial}"
                    )))
                    serial += 1
                pod_waves.append(wave)
                op.run_until_idle(max_iters=400, disrupt=False)
                # a watchdog trip drained the in-thread gateway: the
                # "supervisor respawn" for an in-thread daemon is resume()
                if daemon.gateway.draining():
                    daemon.gateway.resume()
                op.run_until_idle(max_iters=400, disrupt=False)
                assert all(p.node_name for p in op.kube.list_pods()), (
                    f"cycle {cycle}: unbound pods despite degradation paths"
                )
            # quiet tail: chaos off, breaker must close and the device
            # path must serve again
            schedule.rates = {}
            for i in range(2):
                op.kube.create(replicated(make_pod(
                    cpu=1.0, name=f"tail{i}"
                )))
            pod_waves.append([(f"tail{i}", 1.0) for i in range(2)])
            op.run_until_idle(max_iters=400, disrupt=False)
            assert all(p.node_name for p in op.kube.list_pods())
            assert op.solver_client.breaker.state == STATE_CLOSED
        finally:
            stop.set()
            hammer.join(timeout=30)
            op.shutdown()
            srv.shutdown()
            srv.server_close()
        assert not tenant_errors, tenant_errors

        # at least some of each fault class actually fired
        assert chaos.injected.get("crash", 0) > 0
        assert chaos.injected.get("corrupt_wire", 0) > 0
        assert chaos.injected.get("bad_result", 0) > 0

        # greedy-parity twin: the same pod stream, same wave structure, on
        # a clean greedy operator; the chaos run may BEAT it (device
        # packing) but must not be meaningfully worse
        _reset_claim_counter()
        clock = FakeClock()
        kube = KubeStore(clock)
        twin = Operator(
            kube=kube, cloud_provider=KwokCloudProvider(kube, CATALOG),
            clock=clock, options=Options(solver="greedy"),
        )
        kube.create(make_nodepool())
        for wave in pod_waves:
            for name, cpu in wave:
                kube.create(replicated(make_pod(cpu=cpu, name=name)))
            twin.run_until_idle(max_iters=400, disrupt=False)
        greedy_nodes = len(twin.kube.list_nodes())
        chaos_nodes = len(op.kube.list_nodes())
        assert chaos_nodes <= greedy_nodes + max(2, 0.2 * greedy_nodes), (
            f"chaos={chaos_nodes} greedy={greedy_nodes}"
        )

        # the unaffected tenant's queue wait stayed bounded: fairness
        # holds even while the chaotic tenant burned faults
        snap = daemon.gateway.snapshot()
        waits = snap["tenants"].get("tenant-b", {})
        if waits.get("n"):
            bound = 3.0 * 2 * max(snap["device_p50_s"], 0.05)
            assert waits["wait_p99_s"] <= bound + 1.0, (waits, snap)

    def test_sidecar_murder_soak(self):
        """Murder wave: a REAL spawned sidecar killed repeatedly mid-run;
        provisioning keeps completing (greedy fallback inside the
        deadline), the supervisor respawns it, and the device path comes
        back each time."""
        from tests.test_solverd import new_operator as solverd_operator

        from karpenter_core_tpu.metrics import wiring as m

        op = solverd_operator("sidecar", batch_idle_duration=0.0)
        try:
            sup = op.solver_supervisor
            op.solver_client.max_retries = 0
            op.solver_client.sleep = lambda s: None
            op.kube.create(make_nodepool())
            for round_ in range(3):
                op.solver_client.timeout = 120.0
                op.kube.create(replicated(make_pod(
                    cpu=1.0, name=f"mm{round_}-alive"
                )))
                op.run_until_idle(disrupt=False)
                assert all(p.node_name for p in op.kube.list_pods())
                # murder; hold the respawn window shut so the next solve
                # really runs against a dead process
                op.solver_client.timeout = 1.0
                sup._delay = 9999.0
                sup.proc.kill()
                sup.proc.wait(timeout=10)
                fb = m.SOLVER_RPC_FALLBACKS.value({"endpoint": "solve"})
                op.kube.create(replicated(make_pod(
                    cpu=1.0, name=f"mm{round_}-dead"
                )))
                op.run_until_idle(disrupt=False)
                assert all(p.node_name for p in op.kube.list_pods())
                assert m.SOLVER_RPC_FALLBACKS.value(
                    {"endpoint": "solve"}
                ) > fb
                # open the window: the supervisor brings it back
                sup._delay = 0.0
                sup._next_spawn_at = 0.0
                assert sup.poll()
                op.solver_client.set_addr(sup.addr)
            assert m.SOLVERD_RESTARTS.value({"cause": "crash"}) >= 3
            assert_coherent(op)
        finally:
            op.shutdown()


@pytest.mark.slow
class TestChaosSoak:
    """The long soak: heavier churn, both solve paths, repeated storms."""

    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    def test_soak_converges(self, solver):
        import random

        rng = random.Random(99)
        storm_offerings = tuple(
            OfferingKey(it.name, zone, ct)
            for it in CATALOG[:10]
            for zone in ("zone-a", "zone-b", "zone-c")
            for ct in (L.CAPACITY_TYPE_SPOT, L.CAPACITY_TYPE_ON_DEMAND)
        )
        storms = [
            IceStorm(start=1_000_000.0 + 50.0 + i * 400.0, duration=120.0,
                     offerings=storm_offerings)
            for i in range(3)
        ]
        op, _, store = _chaos_operator(99, solver=solver, storms=storms)
        store.create(make_nodepool())
        live = {}
        serial = 0
        for cycle in range(10):
            for _ in range(rng.randint(2, 6)):
                name = f"s{serial}"
                serial += 1
                p = replicated(make_pod(
                    cpu=rng.choice([0.25, 0.5, 1.0, 2.0]),
                    memory_gib=rng.choice([0.5, 1.0, 2.0]),
                    name=name,
                ))
                store.create(p)
                live[name] = p
            for name in rng.sample(
                sorted(live), min(len(live), rng.randint(0, 4))
            ):
                from karpenter_core_tpu.api.objects import Pod

                pod = store.get(Pod, name)
                if pod is not None:
                    store.delete(pod)
                del live[name]
            op.run_until_idle(max_iters=400)
            op.clock.step(rng.choice([5.0, 61.0, 400.0]))
            op.run_until_idle(max_iters=400)
            assert_coherent(op)
        op.clock.step(UNAVAILABLE_OFFERINGS_TTL + 1.0)
        op.run_until_idle(max_iters=400)
        assert_coherent(op)
        assert op.readyz()
