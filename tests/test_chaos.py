"""Chaos-hardened reconcile: fault-isolated controllers, the
unavailable-offerings (ICE) cache, and the seeded chaos harness.

Three failure domains under test end-to-end:
* a controller exception is isolated to its own requeue backoff — the pass
  survives, the error is observable (metric + Warning event), and repeated
  crash-looping degrades readyz;
* a capacity stockout (typed ICE) marks the offering unavailable for a TTL
  so the re-solve lands on the next-cheapest AVAILABLE offering on BOTH
  solve paths — no create→ICE→delete livelock — and the offering returns
  to service after expiry;
* under a seeded schedule of store conflicts, 429s, latency, ICE storms and
  provider create/delete faults the operator still converges: all
  provisionable pods bound, nothing leaked, and identical seeds replay
  identical event traces.
"""
import itertools

import pytest

from tests.helpers import make_nodepool, make_pod
from tests.test_e2e import CATALOG, new_operator, replicated
from tests.test_soak import assert_coherent

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.chaos import (
    ChaosCloudProvider,
    ChaosKubeClient,
    ChaosSchedule,
    IceStorm,
)
from karpenter_core_tpu.cloudprovider.kwok import KwokCloudProvider, build_catalog
from karpenter_core_tpu.cloudprovider.types import OfferingKey
from karpenter_core_tpu.cloudprovider.unavailableofferings import (
    UNAVAILABLE_OFFERINGS_TTL,
    UnavailableOfferings,
)
from karpenter_core_tpu.kube.store import ConflictError, KubeStore
from karpenter_core_tpu.operator import (
    CRASHLOOP_THRESHOLD,
    Operator,
    Options,
)
from karpenter_core_tpu.utils.clock import FakeClock


def _reset_claim_counter():
    """Claim names draw from a process-global counter; reproducibility
    assertions compare event traces across runs, so each run restarts it."""
    from karpenter_core_tpu.controllers.provisioning.scheduling import (
        nodeclaimtemplate,
    )

    nodeclaimtemplate._claim_counter = itertools.count(1)


def _bound_offering(op, pod_name: str) -> OfferingKey:
    from karpenter_core_tpu.api.objects import Node, Pod

    pod = op.kube.get(Pod, pod_name)
    assert pod is not None and pod.node_name, f"{pod_name} not bound"
    node = op.kube.get(Node, pod.node_name)
    return OfferingKey(
        node.labels[L.LABEL_INSTANCE_TYPE],
        node.labels[L.LABEL_TOPOLOGY_ZONE],
        node.labels[L.CAPACITY_TYPE_LABEL_KEY],
    )


class TestUnavailableOfferings:
    def test_mark_expire_and_snapshot(self):
        clock = FakeClock()
        cache = UnavailableOfferings(clock)
        key = OfferingKey("c-1x", "zone-a", "spot")
        assert not cache.is_unavailable(key)
        cache.mark(key)
        assert cache.is_unavailable(key)
        # plain tuples are the same identity (the wire decodes to tuples)
        assert cache.is_unavailable(("c-1x", "zone-a", "spot"))
        assert cache.snapshot() == frozenset([key])
        clock.step(UNAVAILABLE_OFFERINGS_TTL - 1.0)
        assert cache.is_unavailable(key)
        # re-marking refreshes the TTL
        cache.mark(key)
        clock.step(2.0)
        assert cache.is_unavailable(key)
        clock.step(UNAVAILABLE_OFFERINGS_TTL)
        assert not cache.is_unavailable(key)
        assert cache.snapshot() == frozenset()

    def test_default_operator_shares_one_cache_with_its_provider(self):
        """Regression: UnavailableOfferings is falsy when empty (len 0), so
        `passed_cache or own_cache` silently split lifecycle's cache from
        the provider's create-pick cache. Every construction path must end
        with ONE shared instance."""
        op = Operator(clock=FakeClock())  # default kwok provider
        assert op.cloud_provider.unavailable_offerings is op.unavailable_offerings
        assert op.lifecycle.unavailable_offerings is op.unavailable_offerings
        assert op.provisioner.unavailable_offerings is op.unavailable_offerings
        # externally-built provider: the operator adopts ITS cache
        op2 = new_operator()
        assert (
            op2.cloud_provider.unavailable_offerings
            is op2.unavailable_offerings
        )


class TestCapacityStockout:
    """The acceptance scenario: cheapest offering ICE'd -> pods land on the
    next-cheapest AVAILABLE offering within one re-solve, on both paths."""

    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    def test_stockout_resolves_to_next_cheapest(self, solver):
        # discover what an unconstrained run picks (the cheapest offering)
        probe = new_operator(solver)
        probe.kube.create(make_nodepool())
        probe.kube.create(make_pod(cpu=1.0, name="probe"))
        probe.run_until_idle()
        cheapest = _bound_offering(probe, "probe")

        # fresh world with that offering's capacity actually out
        op = new_operator(solver)
        op.cloud_provider.stockouts.add(cheapest)
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="p0"))
        iters = op.run_until_idle(max_iters=60)
        assert iters < 60, "stockout livelocked the reconcile loop"

        landed = _bound_offering(op, "p0")
        assert landed != cheapest
        # exactly one create->ICE->cache round, not a livelock
        ice_events = op.recorder.with_reason("InsufficientCapacity")
        assert len(ice_events) == 1, [e.message for e in ice_events]
        assert op.unavailable_offerings.is_unavailable(cheapest)
        # exactly one claim survives (the failed one was deleted)
        assert len(op.kube.list_nodeclaims()) == 1

        # TTL expiry returns the offering to service: capacity is back and
        # the cache entry lapses, so a new pod lands on the cheapest again
        op.cloud_provider.stockouts.clear()
        op.clock.step(UNAVAILABLE_OFFERINGS_TTL + 1.0)
        op.kube.create(make_pod(cpu=1.0, name="p1"))
        op.run_until_idle(max_iters=60)
        assert not op.unavailable_offerings.is_unavailable(cheapest)
        assert _bound_offering(op, "p1") == cheapest

    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    def test_pinned_pod_fails_when_its_only_offering_is_iced(self, solver):
        """A pod pinned to the stocked-out zone+capacity-type must FAIL the
        solve (no offering), not get placed onto the masked row — this
        exercises the greedy offering filter and the device off_avail
        tensor mask directly."""
        op = new_operator(solver)
        op.kube.create(make_nodepool())
        # pin to zone-a spot, then mark every (it, zone-a, spot) unavailable
        for it in CATALOG:
            op.unavailable_offerings.mark(
                OfferingKey(it.name, "zone-a", L.CAPACITY_TYPE_SPOT),
                ttl=10_000.0,
            )
        pod = make_pod(
            cpu=1.0,
            name="pinned",
            zone_in=["zone-a"],
            node_selector={L.CAPACITY_TYPE_LABEL_KEY: L.CAPACITY_TYPE_SPOT},
        )
        op.kube.create(pod)
        op.run_until_idle(max_iters=40)
        from karpenter_core_tpu.api.objects import Pod

        assert not op.kube.get(Pod, "pinned").node_name
        assert not op.kube.list_nodeclaims()

    def test_codec_round_trips_unavailable_offerings(self):
        from karpenter_core_tpu.solver import codec

        keys = frozenset(
            [
                OfferingKey("c-1x-amd64-linux", "zone-a", "spot"),
                OfferingKey("m-2x-arm64-linux", "zone-c", "on-demand"),
            ]
        )
        data = codec.encode_solve_request(
            [], {}, [], [], [], unavailable_offerings=keys
        )
        out = codec.decode_solve_request(data)
        assert out["unavailable_offerings"] == keys


class TestReconcileIsolation:
    def _broken(self, op, controller_attr="garbage_collection"):
        calls = {"n": 0}

        def boom():
            calls["n"] += 1
            raise RuntimeError("chaos monkey")

        getattr(op, controller_attr).reconcile = boom
        return calls

    def test_exception_is_isolated_and_observable(self):
        from karpenter_core_tpu.metrics import wiring as m

        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="p0"))
        calls = self._broken(op)
        before = m.RECONCILE_ERRORS.value(
            {"controller": "nodeclaim.gc", "error": "RuntimeError"}
        )
        op.run_until_idle()  # the pass survives; provisioning proceeds
        assert all(p.node_name for p in op.kube.list_pods())
        assert calls["n"] >= 1
        assert m.RECONCILE_ERRORS.value(
            {"controller": "nodeclaim.gc", "error": "RuntimeError"}
        ) > before
        events = [
            e for e in op.recorder.with_reason("ReconcileError")
            if e.involved_object == "Controller/nodeclaim.gc"
        ]
        assert events and events[0].type == "Warning"

    def test_backoff_skips_until_elapsed(self):
        op = new_operator()
        calls = self._broken(op)
        op.reconcile_once()
        assert calls["n"] == 1
        op.reconcile_once()  # same instant: still on 1s backoff
        assert calls["n"] == 1
        op.clock.step(1.01)
        op.reconcile_once()
        assert calls["n"] == 2

    def test_crash_loop_flips_readyz_and_recovery_restores_it(self):
        op = new_operator()
        assert op.readyz()
        calls = self._broken(op)
        for _ in range(CRASHLOOP_THRESHOLD):
            op.reconcile_once()
            op.clock.step(120.0)  # past any backoff
        assert calls["n"] == CRASHLOOP_THRESHOLD
        assert not op.readyz()
        # controller recovers -> next clean pass clears the fault state
        op.garbage_collection.reconcile = lambda: None
        op.reconcile_once()
        assert op.readyz()

    def test_broken_object_does_not_starve_controller_siblings(self):
        """One perpetually-broken claim must not stop the lifecycle
        controller from reconciling OTHER claims, and must not flip readyz
        while the controller demonstrably still works (the fault state
        clears on the next successful invocation)."""
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="p0"))

        real = op.lifecycle.reconcile
        broken_name = {"value": None}

        def selective(claim):
            # break the FIRST claim seen, forever; others reconcile fine
            if broken_name["value"] in (None, claim.name):
                broken_name["value"] = claim.name
                raise RuntimeError("broken object")
            return real(claim)

        op.lifecycle.reconcile = selective
        op.run_until_idle(max_iters=60)
        # a second pod arrives: its fresh claim must still launch and bind
        # even though the first claim keeps crashing its reconciler
        op.kube.create(make_pod(cpu=1.0, name="p1"))
        op.run_until_idle(max_iters=60)
        from karpenter_core_tpu.api.objects import Pod

        assert op.kube.get(Pod, "p1").node_name
        assert op.readyz()

    def test_fault_clears_when_failing_workload_vanishes(self):
        """A controller crash-looping on one object must not pin readyz
        false after that object (and all its workload) is gone — the stale
        fault entry drops on the first pass with nothing to reconcile."""
        op = new_operator()
        pool = make_nodepool()
        op.kube.create(pool)

        def boom(p):
            raise RuntimeError("bad pool")

        op.nodepool_hash.reconcile = boom
        for _ in range(CRASHLOOP_THRESHOLD):
            op.reconcile_once()
            op.clock.step(120.0)
        assert not op.readyz()
        op.kube.delete(pool)  # the failing workload vanishes
        op.clock.step(120.0)
        op.reconcile_once()
        assert op.readyz()

    def test_conflicts_requeue_but_never_crash_loop(self):
        """Injected optimistic-lock conflicts in ANY controller back off
        like errors but must not degrade readyz — they are expected
        races, not crashes (the termination-consistency story applied
        uniformly)."""
        op = new_operator()

        def race():
            raise ConflictError("stale resource_version")

        op.garbage_collection.reconcile = race
        for _ in range(CRASHLOOP_THRESHOLD + 2):
            op.reconcile_once()
            op.clock.step(120.0)
        assert op.readyz()
        # but they ARE observable as reconcile errors
        assert op.recorder.with_reason("ReconcileError")

    def test_provisioning_failure_does_not_kill_the_pass(self):
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="p0"))

        def explode():
            raise RuntimeError("solver meltdown")

        original = op.provisioner.provision
        op.provisioner.provision = explode
        op.reconcile_once()  # must not raise
        assert not any(p.node_name for p in op.kube.list_pods())
        # recovery: the batcher self-heal window re-solves the pending pods
        op.provisioner.provision = original
        op.clock.step(2.0)
        op.run_until_idle()
        assert all(p.node_name for p in op.kube.list_pods())


class TestTerminationConflict:
    def test_stale_resource_version_is_requeued_not_raised(self):
        """Regression: a ConflictError on the termination controller's
        node/claim writes used to propagate (and kill the pass); it now
        requeues — drop the pass, retry against the fresh object."""
        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle()
        from karpenter_core_tpu.api.objects import Node

        node = op.kube.list_nodes()[0]
        op.kube.delete(node)  # finalizer holds it; termination drains

        real_update = op.kube.update
        state = {"raised": False}

        def stale_once(obj):
            if isinstance(obj, Node) and not state["raised"]:
                state["raised"] = True
                raise ConflictError("stale resource_version (chaos)")
            return real_update(obj)

        op.kube.update = stale_once
        op.termination.reconcile(node)  # must not raise
        assert state["raised"]
        op.kube.update = real_update
        op.run_until_idle()
        assert node.name not in {n.name for n in op.kube.list_nodes()}


class TestHttpClientRetry:
    def _client(self, responses, fail_after=None):
        from karpenter_core_tpu.kube.httpclient import HttpKubeClient

        c = HttpKubeClient("127.0.0.1", 1, retry_backoff=0.001)
        log = {"attempts": [], "sleeps": []}
        queue = list(responses)

        def fake(method, path, payload=None):
            log["attempts"].append((method, path))
            return queue.pop(0)

        c._do_request = fake
        c._sleep = log["sleeps"].append
        return c, log

    def test_get_retries_transient_5xx_then_succeeds(self):
        c, log = self._client([
            (503, {"error": "apiserver warming"}),
            (429, {"error": "slow down"}),
            (200, {"items": []}),
        ])
        assert c.list_pods() == []
        assert len(log["attempts"]) == 3
        # exponential: 1x, then 2x the base backoff
        assert log["sleeps"] == [0.001, 0.002]

    def test_get_retry_budget_is_bounded(self):
        from karpenter_core_tpu.kube.store import TooManyRequestsError

        c, log = self._client([(429, {"error": "n"})] * 4)
        with pytest.raises(TooManyRequestsError):
            c.list_pods()
        assert len(log["attempts"]) == 4  # 1 + GET_RETRIES

    def test_writes_are_never_retried(self):
        c, log = self._client([(503, {"error": "blip"})])
        with pytest.raises(RuntimeError):
            c._request("POST", "/bind", {"name": "p"})
        assert len(log["attempts"]) == 1
        assert log["sleeps"] == []


# -- the seeded chaos harness ------------------------------------------------


def _chaos_operator(seed: int, solver: str = "greedy", storms=(), rates=None):
    _reset_claim_counter()
    clock = FakeClock()
    store = KubeStore(clock)
    schedule = ChaosSchedule(
        seed=seed,
        rates=rates
        if rates is not None
        else {
            "kube.create.conflict": 0.08,
            "kube.update.conflict": 0.05,
            "kube.update.too_many_requests": 0.03,
            "kube.bind.conflict": 0.05,
            "kube.delete.too_many_requests": 0.04,
            "kube.evict.latency": 0.10,
            "cloud.create.create_error": 0.06,
            "cloud.create.insufficient_capacity": 0.04,
            "cloud.delete.delete_error": 0.06,
        },
    )
    # the operator reconciles through the chaotic client; the provider
    # materializes its fake nodes on the raw store (a provider is its own
    # system, not a client of the apiserver under test)
    provider = ChaosCloudProvider(
        KwokCloudProvider(store, CATALOG), schedule, storms=storms, clock=clock
    )
    kube = ChaosKubeClient(store, schedule)
    op = Operator(
        kube=kube,
        cloud_provider=provider,
        clock=clock,
        options=Options(solver=solver),
    )
    # workload churn (the test's own creates/deletes) models users whose
    # requests already landed: it goes through the raw store, while every
    # controller write rides the chaotic client
    return op, schedule, store


def _run_chaos_scenario(seed: int, solver: str = "greedy", waves: int = 3,
                        pods_per_wave: int = 4):
    cheapest = CATALOG[0].name  # ICE storm over a slice of the catalog
    storm = IceStorm(
        start=1_000_000.0 + 5.0,
        duration=90.0,
        offerings=tuple(
            OfferingKey(it.name, zone, ct)
            for it in CATALOG[:6]
            for zone in ("zone-a", "zone-b")
            for ct in (L.CAPACITY_TYPE_SPOT,)
        ),
    )
    assert cheapest  # storm covers the head of the catalog
    op, schedule, store = _chaos_operator(seed, solver=solver, storms=[storm])
    store.create(make_nodepool())
    serial = 0
    for wave in range(waves):
        for _ in range(pods_per_wave):
            store.create(replicated(make_pod(
                cpu=[0.5, 1.0, 2.0][serial % 3], name=f"w{serial}"
            )))
            serial += 1
        op.run_until_idle(max_iters=400)
        op.clock.step(61.0)  # past backoff caps and into/through the storm
        op.run_until_idle(max_iters=400)
    # storm over + caches expired: the world must settle coherent
    op.clock.step(UNAVAILABLE_OFFERINGS_TTL + 1.0)
    op.run_until_idle(max_iters=400)
    return op, schedule


class TestChaosSmoke:
    """Tier-1 fixed-seed smoke: convergence invariants under the full fault
    mix. reconcile_once never raises by construction of the isolation
    wrapper — the run itself would fail loudly if it did."""

    def test_converges_under_faults(self):
        op, schedule = _run_chaos_scenario(seed=42)
        assert schedule.draws > 0
        assert_coherent(op)
        assert op.readyz()

    def test_identical_seeds_reproduce_identical_event_traces(self):
        def trace(op):
            return [
                (e.involved_object, e.reason, e.message, e.timestamp)
                for e in op.recorder.events
            ]

        op1, s1 = _run_chaos_scenario(seed=7)
        op2, s2 = _run_chaos_scenario(seed=7)
        assert s1.draws == s2.draws
        assert trace(op1) == trace(op2)
        assert {n.name for n in op1.kube.list_nodes()} == {
            n.name for n in op2.kube.list_nodes()
        }

    def test_scripted_faults_consume_in_order(self):
        # the remote.py FaultInjector contract, generalized per seam
        s = ChaosSchedule(
            seed=0,
            script={"kube.create": ["conflict", "ok", "too_many_requests"]},
        )
        faults = [
            s.next_fault("kube.create", ChaosKubeClient.WRITE_FAULTS)
            for _ in range(4)
        ]
        assert faults == ["conflict", "ok", "too_many_requests", "ok"]


@pytest.mark.slow
class TestChaosSoak:
    """The long soak: heavier churn, both solve paths, repeated storms."""

    @pytest.mark.parametrize("solver", ["greedy", "tpu"])
    def test_soak_converges(self, solver):
        import random

        rng = random.Random(99)
        storm_offerings = tuple(
            OfferingKey(it.name, zone, ct)
            for it in CATALOG[:10]
            for zone in ("zone-a", "zone-b", "zone-c")
            for ct in (L.CAPACITY_TYPE_SPOT, L.CAPACITY_TYPE_ON_DEMAND)
        )
        storms = [
            IceStorm(start=1_000_000.0 + 50.0 + i * 400.0, duration=120.0,
                     offerings=storm_offerings)
            for i in range(3)
        ]
        op, _, store = _chaos_operator(99, solver=solver, storms=storms)
        store.create(make_nodepool())
        live = {}
        serial = 0
        for cycle in range(10):
            for _ in range(rng.randint(2, 6)):
                name = f"s{serial}"
                serial += 1
                p = replicated(make_pod(
                    cpu=rng.choice([0.25, 0.5, 1.0, 2.0]),
                    memory_gib=rng.choice([0.5, 1.0, 2.0]),
                    name=name,
                ))
                store.create(p)
                live[name] = p
            for name in rng.sample(
                sorted(live), min(len(live), rng.randint(0, 4))
            ):
                from karpenter_core_tpu.api.objects import Pod

                pod = store.get(Pod, name)
                if pod is not None:
                    store.delete(pod)
                del live[name]
            op.run_until_idle(max_iters=400)
            op.clock.step(rng.choice([5.0, 61.0, 400.0]))
            op.run_until_idle(max_iters=400)
            assert_coherent(op)
        op.clock.step(UNAVAILABLE_OFFERINGS_TTL + 1.0)
        op.run_until_idle(max_iters=400)
        assert_coherent(op)
        assert op.readyz()
