"""fleetd: the multi-tenant solve gateway inside solverd (solver/fleet.py).

Five layers of proof:

* gateway units (fake clock, scripted device times): admission bounds,
  deadline-aware shedding with Retry-After estimates, weighted fair
  grant order, the provisioning-ahead-of-sweeps priority lane, expiry of
  stale queued work, depth/abandon accounting;
* bounded scheduler cache: LRU in entries AND approximate bytes, strict
  bounds, eviction metrics;
* pipeline split / chaos: one tenant's wedged HOST phase (slow decode)
  never blocks another tenant's device access — the starvation shape the
  old single-FIFO-lock daemon had;
* transport contract: the sidecar sheds with 429 + Retry-After, the
  client honors Retry-After in its backoff, never charges the breaker
  for a shed, and degrades the solve to host greedy (node-count parity
  with a pure greedy solve);
* multi-operator e2e: two full Operators share ONE spawned sidecar with
  distinct catalogs (distinct fingerprints), each reaching node-count
  parity with its own in-proc run, with per-tenant counters visible on
  the shared /metrics surface.
"""
import threading
import time

import pytest

from tests.helpers import make_nodepool, make_pod

from karpenter_core_tpu.api.objects import OwnerReference, Pod
from karpenter_core_tpu.cloudprovider.fake import fake_instance_types
from karpenter_core_tpu.cloudprovider.kwok import (
    KwokCloudProvider,
    build_catalog,
)
from karpenter_core_tpu.kube.store import KubeStore
from karpenter_core_tpu.metrics import wiring as m
from karpenter_core_tpu.operator import Operator, Options
from karpenter_core_tpu.solver import codec, fleet, remote, service
from karpenter_core_tpu.solver.fleet import (
    BoundedSchedulerCache,
    FleetGateway,
    LANE_SOLVE,
    LANE_SWEEP,
    ShedError,
    parse_tenant_weights,
)
from karpenter_core_tpu.utils.clock import FakeClock


# ---------------------------------------------------------------------------
# gateway units
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def _drain_one(gw, tenant, lane=LANE_SOLVE, device_seconds=1.0,
               deadline=None):
    """submit -> grant -> release on the calling thread (empty gateway:
    the grant is immediate)."""
    t = gw.submit(tenant, lane, deadline)
    gw.await_grant(t)
    gw.release(t, device_seconds)
    return t


class _Waiter(threading.Thread):
    """A handler-thread stand-in: queues a ticket, records its grant, and
    releases a scripted device time."""

    def __init__(self, gw, ticket, order, device_seconds=1.0):
        super().__init__(daemon=True)
        self.gw = gw
        self.ticket = ticket
        self.order = order
        self.device_seconds = device_seconds
        self.error = None

    def run(self):
        try:
            self.gw.await_grant(self.ticket)
            # grants are exclusive: between our grant and our release no
            # other waiter can append, so list order IS grant order
            self.order.append((self.ticket.tenant, self.ticket.lane))
            self.gw.release(self.ticket, self.device_seconds)
        except ShedError as e:
            self.error = e


def _queued_depth(gw):
    with gw._lock:
        return sum(
            len(q) for lanes in gw._queued.values() for q in lanes.values()
        )


def _run_contended(gw, tickets, device_seconds=1.0):
    """Hold the device with a blocker, queue every ticket, then release
    the blocker and let the fair scheduler drain them; returns grant
    order."""
    blocker = gw.submit("blocker", LANE_SOLVE)
    gw.await_grant(blocker)
    order = []
    waiters = [_Waiter(gw, t, order, device_seconds) for t in tickets]
    for w in waiters:
        w.start()
    deadline = time.monotonic() + 10
    while _queued_depth(gw) < len(tickets):
        assert time.monotonic() < deadline, "waiters never queued"
        time.sleep(0.001)
    gw.release(blocker, 0.0)
    for w in waiters:
        w.join(timeout=10)
        assert not w.is_alive(), "waiter never granted"
    return order, waiters


class TestFairQueue:
    def test_empty_gateway_grants_immediately(self):
        gw = FleetGateway(time_fn=_Clock())
        t = _drain_one(gw, "a")
        assert t.state == "done"
        assert gw.depth() == 0

    def test_equal_weights_alternate_under_contention(self):
        gw = FleetGateway(max_depth=32, time_fn=_Clock())
        tickets = [
            gw.submit("a" if i % 2 == 0 else "b", LANE_SOLVE)
            for i in range(8)
        ]
        order, _ = _run_contended(gw, tickets, device_seconds=1.0)
        tenants = [t for t, _lane in order]
        # equal weights + equal device cost -> strict alternation (ties
        # break on tenant name, so "a" leads)
        assert tenants == ["a", "b"] * 4
        assert gw.depth() == 0

    def test_weighted_tenant_gets_proportional_share(self):
        gw = FleetGateway(
            max_depth=32, weights={"heavy": 3.0}, time_fn=_Clock()
        )
        tickets = [gw.submit("heavy", LANE_SOLVE) for _ in range(6)]
        tickets += [gw.submit("light", LANE_SOLVE) for _ in range(6)]
        order, _ = _run_contended(gw, tickets, device_seconds=1.0)
        # in the first 4 grants after the tie-opener, weight-3 'heavy'
        # takes ~3 device slots for every 1 of 'light'
        first = [t for t, _ in order[:4]]
        assert first.count("heavy") == 3, order

    def test_chatty_tenant_cannot_starve_quiet_one(self):
        """The monopoly shape: 9 queued requests from one tenant vs 1 from
        another — the quiet tenant is granted second, not tenth."""
        gw = FleetGateway(max_depth=32, time_fn=_Clock())
        tickets = [gw.submit("chatty", LANE_SOLVE) for _ in range(9)]
        tickets.append(gw.submit("quiet", LANE_SOLVE))
        order, _ = _run_contended(gw, tickets, device_seconds=1.0)
        tenants = [t for t, _lane in order]
        assert tenants.index("quiet") <= 1, tenants

    def test_solve_lane_preempts_sweep_lane(self):
        """Provisioning ahead of consolidation: queued sweeps wait until
        every pending solve (ANY tenant's) has been granted."""
        gw = FleetGateway(max_depth=32, time_fn=_Clock())
        tickets = [
            gw.submit("a", LANE_SWEEP),
            gw.submit("a", LANE_SWEEP),
            gw.submit("b", LANE_SOLVE),
            gw.submit("c", LANE_SOLVE),
        ]
        order, _ = _run_contended(gw, tickets, device_seconds=1.0)
        lanes = [lane for _t, lane in order]
        assert lanes == [LANE_SOLVE, LANE_SOLVE, LANE_SWEEP, LANE_SWEEP]

    def test_stale_sweep_grant_does_not_roll_vclock_back(self):
        """A sweep queued early (vtime 0) but held behind the solve lane
        is granted with a stale vtime: the virtual clock must be monotone
        or the idle-rejoin bump re-opens the retroactive-credit hole."""
        gw = FleetGateway(max_depth=32, time_fn=_Clock())
        tickets = [gw.submit("c", LANE_SWEEP)]
        tickets += [gw.submit("a", LANE_SOLVE) for _ in range(3)]
        order, _ = _run_contended(gw, tickets, device_seconds=10.0)
        assert [lane for _t, lane in order] == [
            LANE_SOLVE, LANE_SOLVE, LANE_SOLVE, LANE_SWEEP,
        ]
        # a's three grants advanced the clock to 20; c's stale-vtime
        # grant must not drag it back to 0
        assert gw._vclock >= 20.0

    def test_per_tenant_state_is_bounded(self):
        """Tenant ids are client-supplied: a client that varies its id
        must hit the state cap, not leak vtime/wait-sample entries for
        the shared sidecar's lifetime."""
        gw = FleetGateway(max_depth=4, time_fn=_Clock())
        for i in range(fleet.TENANT_STATE_CAP + 200):
            _drain_one(gw, f"ephemeral-{i}", device_seconds=0.001)
        assert len(gw._vtime) <= fleet.TENANT_STATE_CAP
        assert len(gw._wait_samples) <= fleet.TENANT_STATE_CAP
        assert not gw._queued  # empty lane dicts are always dropped

    def test_idle_tenant_rejoins_at_current_vclock(self):
        """An idle period is not a credit voucher: a tenant returning
        after others burned device time shares fairly from NOW instead of
        monopolizing until its vtime catches up."""
        clock = _Clock()
        gw = FleetGateway(max_depth=32, time_fn=clock)
        for _ in range(5):
            _drain_one(gw, "busy", device_seconds=10.0)
        assert gw._vtime["busy"] == pytest.approx(50.0)
        tickets = [gw.submit("newcomer", LANE_SOLVE) for _ in range(2)]
        tickets.append(gw.submit("busy", LANE_SOLVE))
        order, _ = _run_contended(gw, tickets, device_seconds=10.0)
        # the newcomer is bumped to the busy tenant's vclock, so 'busy'
        # gets a grant within the first two instead of after all of
        # newcomer's backlog
        tenants = [t for t, _lane in order]
        assert tenants.index("busy") <= 1, tenants


class TestAdmission:
    def test_capacity_shed_with_retry_after(self):
        gw = FleetGateway(max_depth=2, time_fn=_Clock())
        gw.submit("a", LANE_SOLVE)
        gw.submit("a", LANE_SOLVE)
        shed_before = m.SOLVERD_SHED.value(
            {"tenant": "b", "reason": "capacity"}
        )
        with pytest.raises(ShedError) as e:
            gw.submit("b", LANE_SOLVE)
        assert e.value.reason == "capacity"
        assert e.value.retry_after > 0
        assert m.SOLVERD_SHED.value(
            {"tenant": "b", "reason": "capacity"}
        ) == shed_before + 1
        assert gw.saturated()

    def test_deadline_shed_uses_observed_p50(self):
        clock = _Clock()
        gw = FleetGateway(max_depth=8, time_fn=clock)
        # no observations yet: the boot prior admits a tight deadline
        # only if it covers the prior
        assert gw.device_p50() == fleet.DEVICE_P50_BOOT
        for _ in range(4):
            _drain_one(gw, "a", device_seconds=2.0)
        assert gw.device_p50() == pytest.approx(2.0)
        # deadline below one device p50: hopeless, shed immediately
        with pytest.raises(ShedError) as e:
            gw.submit("a", LANE_SOLVE, deadline=1.0)
        assert e.value.reason == "deadline"
        # the estimate names the gap: wait >= p50 - deadline
        assert e.value.retry_after >= 1.0
        # a deadline that covers the estimate is admitted
        t = gw.submit("a", LANE_SOLVE, deadline=5.0)
        gw.await_grant(t)
        gw.release(t, 2.0)

    def test_deadline_estimate_scales_with_backlog(self):
        clock = _Clock()
        gw = FleetGateway(max_depth=8, time_fn=clock)
        for _ in range(4):
            _drain_one(gw, "a", device_seconds=1.0)
        # 3 admitted ahead: estimate ~4s, so a 2s deadline sheds even
        # though it covers a single solo device time
        for _ in range(3):
            gw.submit("a", LANE_SOLVE)
        with pytest.raises(ShedError) as e:
            gw.submit("b", LANE_SOLVE, deadline=2.0)
        assert e.value.reason == "deadline"

    def test_queued_ticket_expires_at_dispatch(self):
        """A deadline that lapses while queued sheds at grant time — the
        device never burns time on an answer the client stopped waiting
        for — and the next live ticket is granted instead."""
        clock = _Clock()
        gw = FleetGateway(max_depth=8, time_fn=clock)
        blocker = gw.submit("a", LANE_SOLVE)
        gw.await_grant(blocker)
        doomed = gw.submit("b", LANE_SOLVE, deadline=5.0)
        live = gw.submit("c", LANE_SOLVE)
        order = []
        w_doomed = _Waiter(gw, doomed, order)
        w_live = _Waiter(gw, live, order)
        w_doomed.start()
        w_live.start()
        deadline = time.monotonic() + 10
        while _queued_depth(gw) < 2:
            assert time.monotonic() < deadline
            time.sleep(0.001)
        clock.now = 6.0  # b's deadline lapsed while queued
        gw.release(blocker, 0.5)
        w_doomed.join(timeout=10)
        w_live.join(timeout=10)
        assert isinstance(w_doomed.error, ShedError)
        assert w_doomed.error.reason == "expired"
        assert w_live.error is None
        assert order == [("c", LANE_SOLVE)]
        assert gw.depth() == 0

    def test_abandon_returns_admission_slot(self):
        gw = FleetGateway(max_depth=2, time_fn=_Clock())
        t1 = gw.submit("a", LANE_SOLVE)
        t2 = gw.submit("a", LANE_SOLVE)
        with pytest.raises(ShedError):
            gw.submit("a", LANE_SOLVE)
        gw.abandon(t2)  # pre-grant failure (decode error)
        gw.await_grant(t1)
        gw.abandon(t1)  # granted-phase failure: frees the device too
        t3 = gw.submit("a", LANE_SOLVE)
        gw.await_grant(t3)
        gw.release(t3, 0.1)
        assert gw.depth() == 0

    def test_depth_gauge_tracks_pending(self):
        gw = FleetGateway(max_depth=4, time_fn=_Clock())
        t = gw.submit("a", LANE_SOLVE)
        assert m.SOLVERD_QUEUE_DEPTH.value() == 1.0
        gw.await_grant(t)
        gw.release(t, 0.1)
        assert m.SOLVERD_QUEUE_DEPTH.value() == 0.0

    def test_snapshot_reports_and_resets(self):
        gw = FleetGateway(max_depth=2, time_fn=_Clock())
        _drain_one(gw, "a", device_seconds=0.5)
        gw.submit("a", LANE_SOLVE)
        gw.submit("a", LANE_SOLVE)
        with pytest.raises(ShedError):
            gw.submit("b", LANE_SOLVE)
        snap = gw.snapshot(reset=True)
        assert snap["grants"] == 1
        assert snap["sheds"] == {"capacity": 1}
        assert snap["tenants"]["a"]["n"] == 1
        assert snap["depth"] == 2
        assert gw.snapshot()["grants"] == 0

    def test_rejects_bad_configuration(self):
        with pytest.raises(ValueError):
            FleetGateway(max_depth=0)
        gw = FleetGateway()
        with pytest.raises(ValueError):
            gw.submit("a", "express")


class TestTenantWeightsParse:
    def test_parses_and_defaults(self):
        assert parse_tenant_weights("") == {}
        assert parse_tenant_weights("a=3,b=1.5") == {"a": 3.0, "b": 1.5}
        assert parse_tenant_weights(" a=2 , b=1 ") == {"a": 2.0, "b": 1.0}

    @pytest.mark.parametrize("bad", ["a", "a=", "=2", "a=zero", "a=0", "a=-1"])
    def test_rejects_malformed(self, bad):
        with pytest.raises(ValueError):
            parse_tenant_weights(bad)


# ---------------------------------------------------------------------------
# bounded scheduler cache
# ---------------------------------------------------------------------------


class TestBoundedSchedulerCache:
    def test_entry_bound_evicts_lru(self):
        cache = BoundedSchedulerCache(max_entries=2, max_bytes=1 << 30)
        cache.put("fp-a", "sched-a", 10)
        cache.put("fp-b", "sched-b", 10)
        assert cache.get("fp-a") == "sched-a"  # refresh a: b is now LRU
        evictions = m.SOLVERD_SCHED_CACHE_EVICTIONS.value(
            {"reason": "entries"}
        )
        cache.put("fp-c", "sched-c", 10)
        assert len(cache) == 2
        assert "fp-b" not in cache and "fp-a" in cache and "fp-c" in cache
        assert cache.evictions == {"entries": 1}
        assert m.SOLVERD_SCHED_CACHE_EVICTIONS.value(
            {"reason": "entries"}
        ) == evictions + 1

    def test_byte_bound_is_strict(self):
        cache = BoundedSchedulerCache(max_entries=8, max_bytes=100)
        cache.put("fp-a", "sched-a", 60)
        cache.put("fp-b", "sched-b", 60)  # 120 > 100: a evicts
        assert "fp-a" not in cache and "fp-b" in cache
        assert cache.total_bytes() == 60
        assert cache.evictions == {"bytes": 1}
        # a single oversized entry may not pin more than the budget: it
        # serves this request but is not retained
        cache.put("fp-huge", "sched-huge", 500)
        assert len(cache) == 0 and cache.total_bytes() == 0
        assert m.SOLVERD_SCHED_CACHE_BYTES.value() == 0.0

    def test_replacing_entry_adjusts_bytes(self):
        cache = BoundedSchedulerCache(max_entries=4, max_bytes=100)
        cache.put("fp-a", "sched-a", 40)
        cache.put("fp-a", "sched-a2", 70)
        assert cache.total_bytes() == 70
        assert cache.get("fp-a") == "sched-a2"
        assert len(cache) == 1

    def test_values_view_and_gauges(self):
        cache = BoundedSchedulerCache(max_entries=4, max_bytes=1 << 20)
        cache.put("fp-a", "sched-a", 7)
        assert cache.values() == ["sched-a"]
        assert m.SOLVERD_SCHED_CACHE_ENTRIES.value() == 1.0
        assert m.SOLVERD_SCHED_CACHE_BYTES.value() == 7.0

    def test_rejects_bad_configuration(self):
        with pytest.raises(ValueError):
            BoundedSchedulerCache(max_entries=0)


# ---------------------------------------------------------------------------
# the daemon's pipeline split + chaos starvation
# ---------------------------------------------------------------------------

CATALOG = build_catalog(cpu_grid=[1, 2, 4, 8], mem_factors=[2, 4])


class _SlowHostDaemon(service.SolverDaemon):
    """Chaos seam: wedge ONE tenant's host phase (decode) for a scripted
    delay — the hung-tenant shape. The device must keep serving everyone
    else, which only the host/device pipeline split makes true."""

    def __init__(self, host_delays, **kwargs):
        super().__init__(**kwargs)
        self.host_delays = dict(host_delays)

    def _decode_solve(self, body):
        problem = super()._decode_solve(body)
        delay = self.host_delays.get(problem["tenant"], 0.0)
        if delay:
            time.sleep(delay)
        return problem


def _solve_body(pods, catalog=None, tenant="default", pool_name="default"):
    return codec.encode_solve_request(
        [make_nodepool(name=pool_name)],
        {pool_name: list(catalog or fake_instance_types(3))},
        [], [], pods, max_slots=32, tenant=tenant,
    )


class TestPipelineSplit:
    def test_empty_cache_and_gateway_are_adopted(self):
        """An EMPTY BoundedSchedulerCache is falsy (len 0) but the daemon
        must still adopt it — truthiness adoption would silently replace
        the operator's configured bounds with the defaults (and leave the
        caller's handle pointing at a cache the daemon never fills)."""
        cache = BoundedSchedulerCache(max_entries=2)
        gw = FleetGateway(max_depth=3)
        daemon = service.SolverDaemon(gateway=gw, sched_cache=cache)
        assert daemon._sched_cache is cache
        assert daemon.gateway is gw
        daemon.solve(_solve_body([make_pod(cpu=1.0, name="adopt0")]))
        assert len(cache) == 1  # the solve landed in OUR cache

    def test_release_charges_full_device_occupancy(self):
        """The fairness charge and the admission p50 must cover the WHOLE
        exclusive section — on a cache miss that includes DeviceScheduler
        construction/prepare, not just the kernel — or cache-churning
        tenants systematically under-pay for the device they hold."""
        daemon = service.SolverDaemon()
        charges = []
        orig_release = daemon.gateway.release

        def recording_release(ticket, seconds):
            charges.append(seconds)
            orig_release(ticket, seconds)

        daemon.gateway.release = recording_release
        _out, kernel = daemon.solve(
            _solve_body([make_pod(cpu=1.0, name="occ0")])
        )
        assert charges and kernel > 0
        assert charges[0] >= kernel  # construction + prepare included
        assert daemon.gateway.device_p50() >= kernel

    def test_wire_tenant_reaches_gateway_accounting(self):
        daemon = service.SolverDaemon()
        body = _solve_body(
            [make_pod(cpu=1.0, name="t0")], tenant="wire-tenant"
        )
        before = m.SOLVERD_TENANT_SOLVES.value(
            {"tenant": "wire-tenant", "endpoint": "solve"}
        )
        out, _dt = daemon.solve(body)
        assert codec.decode_solve_results(out)["errors"] == {}
        assert m.SOLVERD_TENANT_SOLVES.value(
            {"tenant": "wire-tenant", "endpoint": "solve"}
        ) == before + 1
        # the transport header wins over the wire field when present
        daemon.solve(body, tenant="header-tenant")
        assert m.SOLVERD_TENANT_SOLVES.value(
            {"tenant": "header-tenant", "endpoint": "solve"}
        ) >= 1

    def test_hung_tenant_host_phase_does_not_starve_others(self):
        """One tenant's requests hang (1s each in decode) while the other
        tenant keeps solving: the victim's queue waits stay bounded at
        milliseconds because a host-phase hang never holds the device."""
        daemon = _SlowHostDaemon({"hog": 1.0})
        victim_pods = [make_pod(cpu=1.0, name="v0")]
        victim_body = _solve_body(victim_pods, tenant="victim")
        daemon.solve(victim_body)  # pay the jit compile outside the clock

        errors = []

        def hog():
            try:
                for i in range(2):
                    daemon.solve(_solve_body(
                        [make_pod(cpu=1.0, name=f"h{i}")], tenant="hog",
                    ))
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        hog_thread = threading.Thread(target=hog, daemon=True)
        hog_thread.start()
        time.sleep(0.05)  # the hog is now wedged inside its host phase
        victim_times = []
        for _ in range(4):
            t0 = time.perf_counter()
            out, _dt = daemon.solve(victim_body)
            victim_times.append(time.perf_counter() - t0)
            assert codec.decode_solve_results(out)["errors"] == {}
        hog_thread.join(timeout=30)
        assert not errors
        # 4 victim solves completed well inside ONE hog host-phase hang:
        # with the old whole-request lock each would wait out the 1s hang
        assert max(victim_times) < 0.75, victim_times
        snap = daemon.gateway.snapshot()
        assert snap["tenants"]["victim"]["wait_p99_s"] < 0.5, snap


# ---------------------------------------------------------------------------
# transport contract: 429 + Retry-After, greedy degradation, healthz
# ---------------------------------------------------------------------------


class TestOverloadTransport:
    def _saturated_daemon(self):
        """A live daemon whose admission queue is full (two parked
        tickets), so every arriving request sheds."""
        daemon = service.SolverDaemon(
            gateway=FleetGateway(max_depth=2, time_fn=_Clock())
        )
        parked = [
            daemon.gateway.submit("parked", LANE_SOLVE) for _ in range(2)
        ]
        return daemon, parked

    def test_shed_degrades_to_greedy_with_parity(self):
        daemon, parked = self._saturated_daemon()
        srv = service.serve(0, daemon=daemon)
        try:
            addr = f"127.0.0.1:{srv.server_address[1]}"
            sleeps = []
            client = remote.SolverClient(
                addr, timeout=30, max_retries=1,
                sleep=sleeps.append, tenant="tenant-shed",
            )
            pools = [make_nodepool()]
            catalog = fake_instance_types(3)
            pods = [make_pod(cpu=1.0, name=f"s{i}") for i in range(4)]
            rs = remote.RemoteScheduler(client, pools, {"default": catalog})
            sheds = m.SOLVER_RPC_FAILURES.value({"cause": "shed"})
            fallbacks = m.SOLVER_RPC_FALLBACKS.value({"endpoint": "solve"})
            results = rs.solve(pods)
            # degraded to the host greedy path: everything placed, and
            # the placement IS the greedy one (node-count parity)
            assert results.all_pods_scheduled()
            from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
                Scheduler,
            )

            greedy = Scheduler(pools, {"default": catalog}).solve(pods)
            assert results.node_count() == greedy.node_count()
            assert m.SOLVER_RPC_FAILURES.value(
                {"cause": "shed"}
            ) == sheds + 1
            assert m.SOLVER_RPC_FALLBACKS.value(
                {"endpoint": "solve"}
            ) == fallbacks + 1
            # the retry slept the SERVER's estimate, not the fixed backoff
            assert len(sleeps) == 1
            assert sleeps[0] == pytest.approx(
                daemon.gateway.device_p50() * 2
            )
            # a shed is regulation, not a fault: the breaker stays closed
            assert client.breaker.state == remote.STATE_CLOSED
            assert client.breaker.failures == 0
        finally:
            for t in parked:
                daemon.gateway.abandon(t)
            srv.shutdown()
            srv.server_close()

    def test_retry_after_past_budget_degrades_immediately(self):
        daemon, parked = self._saturated_daemon()
        # park a deep backlog so the server's Retry-After estimate (the
        # backlog drain time) exceeds the client's whole solve budget
        daemon.gateway.max_depth = 50
        parked += [
            daemon.gateway.submit("parked", LANE_SOLVE) for _ in range(40)
        ]
        daemon.gateway.max_depth = 42
        srv = service.serve(0, daemon=daemon)
        try:
            addr = f"127.0.0.1:{srv.server_address[1]}"
            sleeps = []
            client = remote.SolverClient(
                addr, timeout=1.0, max_retries=3, sleep=sleeps.append,
            )
            with pytest.raises(remote.RemoteSolverError) as e:
                client.call("/solve", b"irrelevant")
            assert e.value.cause == "shed"
            assert e.value.retry_after is not None
            # waiting 42 x p50 >= the 1s budget: zero retries were burned
            assert sleeps == []
        finally:
            for t in parked:
                daemon.gateway.abandon(t)
            srv.shutdown()
            srv.server_close()

    def test_healthz_reports_overloaded_not_dead(self):
        from urllib.request import urlopen
        import json as _json

        daemon, parked = self._saturated_daemon()
        daemon.ready = True
        srv = service.serve(0, daemon=daemon)
        try:
            base = f"http://127.0.0.1:{srv.server_address[1]}"
            health = _json.loads(urlopen(f"{base}/healthz", timeout=10).read())
            # alive (HTTP 200 — the supervisor must NOT respawn into a
            # load spike) but not ready, with the queue visible
            assert health["ok"] is True
            assert health["ready"] is False
            assert health["overloaded"] is True
            assert health["queue_depth"] == 2
            assert health["queue_capacity"] == 2
            daemon.gateway.abandon(parked.pop())
            health = _json.loads(urlopen(f"{base}/healthz", timeout=10).read())
            assert health["ready"] is True and health["queue_depth"] == 1
        finally:
            for t in parked:
                daemon.gateway.abandon(t)
            srv.shutdown()
            srv.server_close()

    def test_fingerprint_ignores_tenant(self):
        """Two operators watching identical clusters share one cached
        scheduler: the fingerprint is content-addressed, tenancy is the
        gateway's concern."""
        pods = [make_pod(cpu=1.0, name="fp0")]
        pools = [make_nodepool()]  # ONE problem half, two tenants
        a = codec.encode_solve_request(
            pools, {"default": CATALOG}, [], [], pods,
            tenant="tenant-a",
        )
        b = codec.encode_solve_request(
            pools, {"default": CATALOG}, [], [], pods,
            tenant="tenant-b",
        )
        fa = codec.problem_fingerprint(codec._json_header(a))
        fb = codec.problem_fingerprint(codec._json_header(b))
        assert fa == fb
        assert codec.decode_solve_request(a)["tenant"] == "tenant-a"
        assert codec.decode_solve_request(b)["tenant"] == "tenant-b"


# ---------------------------------------------------------------------------
# multi-operator e2e: two Operators, one spawned sidecar
# ---------------------------------------------------------------------------

CATALOG_A = CATALOG
CATALOG_B = build_catalog(cpu_grid=[2, 4, 16], mem_factors=[4])


def replicated(pod: Pod) -> Pod:
    pod.metadata.owner_references.append(
        OwnerReference(kind="ReplicaSet", name="rs", uid="rs-uid")
    )
    return pod


def _operator(mode, catalog, tenant, addr="") -> Operator:
    clock = FakeClock()
    kube = KubeStore(clock)
    return Operator(
        kube=kube,
        cloud_provider=KwokCloudProvider(kube, catalog),
        clock=clock,
        options=Options(
            solver="tpu", solver_mode=mode, solver_addr=addr,
            solver_tenant=tenant,
        ),
    )


def _battery(op: Operator, prefix: str) -> dict:
    op.kube.create(make_nodepool())
    for i in range(3):
        op.kube.create(replicated(
            make_pod(cpu=1.5, name=f"{prefix}-p{i}")
        ))
    op.kube.create(replicated(
        make_pod(cpu=0.5, name=f"{prefix}-z0", zone_in=["zone-b"])
    ))
    op.run_until_idle(disrupt=False)
    pods = op.kube.list_pods()
    return {
        "bound": sorted(p.metadata.name for p in pods if p.node_name),
        "unbound": sorted(p.metadata.name for p in pods if not p.node_name),
        "nodes": len(op.kube.list_nodes()),
    }


class TestMultiOperatorE2E:
    def test_two_operators_share_one_spawned_sidecar(self):
        """The fleet shape: operator A spawns and owns the sidecar;
        operator B (different catalog, different tenant) points at the
        same address. Each tenant's placements reach node-count parity
        with its own in-proc run, no cross-contamination, and the shared
        sidecar's /metrics ledger carries BOTH tenants."""
        inproc_a = _battery(_operator("inproc", CATALOG_A, "x"), "a")
        inproc_b = _battery(_operator("inproc", CATALOG_B, "x"), "b")
        assert inproc_a["unbound"] == [] and inproc_b["unbound"] == []

        op_a = _operator("sidecar", CATALOG_A, "tenant-a")
        try:
            assert op_a.solver_supervisor is not None
            addr = op_a.solver_supervisor.addr
            op_b = _operator("sidecar", CATALOG_B, "tenant-b", addr=addr)
            assert op_b.solver_supervisor is None  # borrowed, not owned
            fallbacks = m.SOLVER_RPC_FALLBACKS.value({"endpoint": "solve"})
            # interleave the two tenants against the one device
            remote_a = _battery(op_a, "a")
            remote_b = _battery(op_b, "b")
            assert remote_a == inproc_a
            assert remote_b == inproc_b
            # the sidecar really served both (no silent greedy fallback)
            assert m.SOLVER_RPC_FALLBACKS.value(
                {"endpoint": "solve"}
            ) == fallbacks
            # per-tenant ledger on the SHARED metrics surface
            from urllib.request import urlopen

            metrics = urlopen(
                f"http://{addr}/metrics", timeout=30
            ).read().decode()
            for tenant in ("tenant-a", "tenant-b"):
                line = (
                    "karpenter_solverd_tenant_solves_total"
                    f'{{endpoint="solve",tenant="{tenant}"}}'
                )
                assert line in metrics, f"missing ledger for {tenant}"
            # distinct catalogs = distinct fingerprints: the bounded
            # cache holds entries for both tenants' problems
            assert (
                "karpenter_solverd_scheduler_cache_entries" in metrics
            )
        finally:
            op_a.shutdown()
