"""Functional tests for the greedy host scheduler (the parity oracle),
covering the core behaviors of the reference's provisioning suite."""
import pytest

from helpers import GIB, make_diverse_pods, make_nodepool, make_pod

from karpenter_core_tpu.api import labels as L
from karpenter_core_tpu.api.objects import NodeSelectorRequirement, Taint, Toleration
from karpenter_core_tpu.cloudprovider.kwok import build_catalog
from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import SimNode
from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import Scheduler


def make_scheduler(nodepools=None, catalog=None, existing=None, daemons=None):
    nodepools = nodepools or [make_nodepool()]
    catalog = catalog if catalog is not None else build_catalog()
    return Scheduler(
        nodepools,
        {np.name: list(catalog) for np in nodepools},
        existing_nodes=existing,
        daemonset_pods=daemons,
    )


class TestBasicPacking:
    def test_single_pod_single_node(self):
        s = make_scheduler()
        res = s.solve([make_pod(cpu=1.0)])
        assert res.all_pods_scheduled()
        assert res.node_count() == 1
        assert len(res.new_node_claims[0].pods) == 1

    def test_many_small_pods_pack_onto_one_node(self):
        s = make_scheduler()
        # 10 x 0.1 cpu easily fits a single small instance
        res = s.solve([make_pod(cpu=0.1, memory_gib=0.1) for _ in range(10)])
        assert res.all_pods_scheduled()
        assert res.node_count() == 1

    def test_pods_larger_than_any_instance_fail(self):
        s = make_scheduler()
        res = s.solve([make_pod(cpu=10000.0)])
        assert not res.all_pods_scheduled()
        assert res.node_count() == 0

    def test_ffd_opens_multiple_nodes(self):
        # max instance = 256 cpu; 300 x 2cpu needs at least 3 nodes worth
        s = make_scheduler()
        res = s.solve([make_pod(cpu=2.0, memory_gib=0.5) for _ in range(300)])
        assert res.all_pods_scheduled()
        total_cpu = 300 * 2.0
        assert res.node_count() >= 2
        # sanity: packed pods count matches
        assert sum(len(c.pods) for c in res.new_node_claims) == 300

    def test_pod_count_limit_respected(self):
        # 1-cpu instance allows 16 pods; 40 tiny pods need >= 2 nodes if
        # scheduler picks the smallest; FFD narrows instance types instead
        s = make_scheduler()
        res = s.solve([make_pod(cpu=0.001, memory_gib=0.01) for _ in range(2000)])
        assert res.all_pods_scheduled()
        for claim in res.new_node_claims:
            pods_limit = min(
                it.allocatable()["pods"] for it in claim.instance_type_options
            )
            assert len(claim.pods) <= pods_limit


class TestRequirements:
    def test_node_selector_restricts_instance_types(self):
        s = make_scheduler()
        res = s.solve(
            [make_pod(node_selector={L.LABEL_ARCH: L.ARCHITECTURE_ARM64})]
        )
        assert res.all_pods_scheduled()
        for it in res.new_node_claims[0].instance_type_options:
            assert it.requirements.get(L.LABEL_ARCH).has("arm64")

    def test_incompatible_selector_fails(self):
        s = make_scheduler()
        res = s.solve([make_pod(node_selector={L.LABEL_ARCH: "riscv"})])
        assert not res.all_pods_scheduled()

    def test_nodepool_requirements_partition(self):
        np = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    L.LABEL_ARCH, "In", (L.ARCHITECTURE_AMD64,)
                )
            ]
        )
        s = make_scheduler([np])
        res = s.solve([make_pod(node_selector={L.LABEL_ARCH: "arm64"})])
        assert not res.all_pods_scheduled()

    def test_zone_affinity(self):
        s = make_scheduler()
        res = s.solve([make_pod(zone_in=["zone-b"])])
        assert res.all_pods_scheduled()
        claim = res.new_node_claims[0]
        assert claim.requirements.get(L.LABEL_TOPOLOGY_ZONE).sorted_values() == [
            "zone-b"
        ]

    def test_incompatible_pods_open_separate_nodes(self):
        s = make_scheduler()
        res = s.solve(
            [
                make_pod(cpu=0.1, name="a", zone_in=["zone-a"]),
                make_pod(cpu=0.1, name="b", zone_in=["zone-b"]),
            ]
        )
        assert res.all_pods_scheduled()
        assert res.node_count() == 2

    def test_custom_label_on_nodepool(self):
        np = make_nodepool()
        np.spec.template.labels = {"mycompany.io/team": "infra"}
        s = make_scheduler([np])
        res = s.solve(
            [make_pod(node_selector={"mycompany.io/team": "infra"})]
        )
        assert res.all_pods_scheduled()
        res2 = make_scheduler([np]).solve(
            [make_pod(node_selector={"mycompany.io/team": "web"})]
        )
        assert not res2.all_pods_scheduled()


class TestTaints:
    def test_tainted_nodepool_needs_toleration(self):
        np = make_nodepool(
            taints=[Taint(key="dedicated", value="ml", effect="NoSchedule")]
        )
        s = make_scheduler([np])
        res = s.solve([make_pod()])
        assert not res.all_pods_scheduled()

        s2 = make_scheduler([np])
        res2 = s2.solve(
            [
                make_pod(
                    tolerations=[
                        Toleration(key="dedicated", operator="Equal", value="ml")
                    ]
                )
            ]
        )
        assert res2.all_pods_scheduled()

    def test_weighted_nodepool_preference(self):
        plain = make_nodepool("plain", weight=0)
        preferred = make_nodepool("preferred", weight=10)
        s = make_scheduler([plain, preferred])
        res = s.solve([make_pod()])
        assert res.all_pods_scheduled()
        assert res.new_node_claims[0].template.nodepool_name == "preferred"


class TestExistingNodes:
    def _existing(self, cpu=4.0):
        return SimNode(
            name="existing-1",
            labels={
                L.LABEL_ARCH: "amd64",
                L.LABEL_OS: "linux",
                L.LABEL_TOPOLOGY_ZONE: "zone-a",
                L.NODEPOOL_LABEL_KEY: "default",
            },
            taints=[],
            available={"cpu": cpu, "memory": 8 * GIB, "pods": 100.0},
            capacity={"cpu": cpu, "memory": 8 * GIB, "pods": 110.0},
        )

    def test_pods_prefer_existing_capacity(self):
        s = make_scheduler(existing=[self._existing()])
        res = s.solve([make_pod(cpu=1.0)])
        assert res.all_pods_scheduled()
        assert res.node_count() == 0
        assert len(res.existing_nodes[0].pods) == 1

    def test_overflow_opens_new_node(self):
        s = make_scheduler(existing=[self._existing(cpu=1.0)])
        res = s.solve([make_pod(cpu=0.8), make_pod(cpu=0.8)])
        assert res.all_pods_scheduled()
        assert res.node_count() == 1
        assert len(res.existing_nodes[0].pods) == 1

    def test_tainted_existing_node_skipped(self):
        node = self._existing()
        node.taints = [Taint(key="x", effect="NoSchedule")]
        s = make_scheduler(existing=[node])
        res = s.solve([make_pod(cpu=1.0)])
        assert res.all_pods_scheduled()
        assert res.node_count() == 1
        assert not res.existing_nodes[0].pods


class TestLimits:
    def test_limits_cap_node_creation(self):
        np = make_nodepool(limits={"cpu": 4.0})
        s = make_scheduler([np])
        # each pod needs its own 2-cpu+ node because of hostname spread? no —
        # use big pods: 3 pods x 3 cpu; max capacity 4 cpu per the limit
        res = s.solve([make_pod(cpu=3.0, name=f"p{i}") for i in range(3)])
        # pessimistic subtractMax: the first node consumes the whole 4-cpu
        # budget, remaining pods fail
        assert not res.all_pods_scheduled()
        assert res.node_count() >= 1

    def test_no_limits_unbounded(self):
        s = make_scheduler()
        res = s.solve([make_pod(cpu=3.0, name=f"p{i}") for i in range(5)])
        assert res.all_pods_scheduled()


class TestRelaxation:
    def test_preferred_affinity_relaxed_on_failure(self):
        from karpenter_core_tpu.api.objects import (
            Affinity,
            NodeAffinity,
            NodeSelectorRequirement,
            NodeSelectorTerm,
            PreferredSchedulingTerm,
        )

        pod = make_pod()
        pod.affinity = Affinity(
            node_affinity=NodeAffinity(
                preferred=[
                    PreferredSchedulingTerm(
                        weight=1,
                        preference=NodeSelectorTerm(
                            match_expressions=(
                                NodeSelectorRequirement(
                                    L.LABEL_TOPOLOGY_ZONE, "In", ("nonexistent-zone",)
                                ),
                            )
                        ),
                    )
                ]
            )
        )
        s = make_scheduler()
        res = s.solve([pod])
        # fails with the preference, relaxes, then schedules
        assert res.all_pods_scheduled()

    def test_impossible_required_affinity_still_fails(self):
        pod = make_pod(zone_in=["nonexistent-zone"])
        s = make_scheduler()
        res = s.solve([pod])
        assert not res.all_pods_scheduled()


class TestScale:
    def test_diverse_500_pods(self):
        s = make_scheduler()
        pods = make_diverse_pods(500, seed=42)
        res = s.solve(pods)
        assert res.all_pods_scheduled()
        assert (
            sum(len(c.pods) for c in res.new_node_claims)
            + sum(len(n.pods) for n in res.existing_nodes)
            == 500
        )
