"""Continuous cross-tenant solve batching (ISSUE 9).

Five layers of proof:

* batched-vs-solo parity battery: every problem in a mixed batch yields
  BYTE-IDENTICAL result wire vs solving it alone — on the single-device
  path and on the conftest-forced 8-device virtual mesh (the batch axis
  replicates over the slot mesh, so vmap must compose with the PR 6
  pjit-over-slots path without perturbing a single placement);
* per-problem isolation: a poisoned batch member fails alone (solve_batch
  outcome isolation, and end-to-end through the daemon where the chaos
  crash strikes only the leader's digest while its batch-mates succeed);
* gateway coalescer units (fake clock): bucket/fingerprint matching, fair
  scan order, expired-member shedding, pod-weighted fairness shares via
  release_batch, batch stats;
* the shed-estimator regression (ISSUE 9 satellite): admission divides
  the backlog by the observed problems-per-GRANT, so a gateway that
  batches 4-deep admits deadlines the per-request model would shed;
* jit-cache bounds: a soak of randomly-sized problems through solve_batch
  compiles a bounded set of batched kernels (power-of-two batch pad x
  bucketed tensor shapes), asserted via jax.monitoring.
"""
from __future__ import annotations

import copy
import threading
import time

import jax
import pytest

from tests.helpers import make_nodepool, make_pod

from karpenter_core_tpu.cloudprovider.fake import fake_instance_types
from karpenter_core_tpu.cloudprovider.kwok import build_catalog
from karpenter_core_tpu.metrics import wiring as m
from karpenter_core_tpu.models.provisioner import (
    DeviceScheduler,
    solve_batch,
)
from karpenter_core_tpu.solver import codec, fleet, service
from karpenter_core_tpu.solver.fleet import FleetGateway


def _catalog():
    return build_catalog()[:16]


def _problem(name, n_pods, cpu_step=0.25, spread=False):
    """One tenant's problem: a distinct pool name (distinct fingerprint)
    over a same-shaped catalog — the fleet traffic shape batching
    targets."""
    pool = make_nodepool(name=name)
    pods = []
    for i in range(n_pods):
        if spread and i % 3 == 0:
            pods.append(
                make_pod(cpu=cpu_step, name=f"{name}-{i}",
                         spread_hostname=True, labels={"app": name})
            )
        else:
            pods.append(
                make_pod(cpu=cpu_step * (1 + i % 4),
                         memory_gib=0.5 * (1 + i % 3),
                         name=f"{name}-{i}")
            )
    return pool, pods


def _scheduler(pool, name, devices=1, max_slots=64):
    return DeviceScheduler(
        [pool], {name: list(_catalog())}, max_slots=max_slots,
        devices=devices,
    )


def _wire(results):
    # solve_seconds is timing, not packing: pin it so wire comparison is
    # exact over the decision content
    return codec.encode_solve_results(results, 0.0)


class TestBatchedSolveParity:
    def test_mixed_batch_byte_identical_wire(self):
        """Three distinct problems coalesced into one vmapped dispatch
        produce, per problem, the byte-identical result wire of a solo
        solve."""
        specs = [("pa", 20, 0.25), ("pb", 24, 0.3), ("pc", 20, 0.2)]
        probs = {n: _problem(n, k, c) for n, k, c in specs}
        solo = {}
        for n, _k, _c in specs:
            pool, pods = probs[n]
            res = _scheduler(pool, n).solve(copy.deepcopy(pods))
            assert res.all_pods_scheduled(), res.pod_errors
            solo[n] = _wire(res)

        entries = [
            (_scheduler(probs[n][0], n), copy.deepcopy(probs[n][1]))
            for n, _k, _c in specs
        ]
        outcomes, stats = solve_batch(entries)
        # all three shared ONE vmapped dispatch (equal shape buckets)
        assert stats["batched_dispatches"] == 1
        assert stats["batched_problems"] == 3
        assert stats["padded_rows"] == 1  # 3 -> padded 4
        for (n, _k, _c), (status, res) in zip(specs, outcomes):
            assert status == "ok", res
            assert res.all_pods_scheduled(), res.pod_errors
            assert _wire(res) == solo[n]

    def test_topology_member_and_shape_split(self):
        """A topology-spread problem batches with a plain one only when
        shapes agree; when they diverge the driver splits into solo
        dispatches — either way every member's wire matches its solo
        twin."""
        pool_t, pods_t = _problem("pt", 18, spread=True)
        pool_p, pods_p = _problem("pp", 18)
        solo_t = _wire(_scheduler(pool_t, "pt").solve(copy.deepcopy(pods_t)))
        solo_p = _wire(_scheduler(pool_p, "pp").solve(copy.deepcopy(pods_p)))
        outcomes, stats = solve_batch([
            (_scheduler(pool_t, "pt"), copy.deepcopy(pods_t)),
            (_scheduler(pool_p, "pp"), copy.deepcopy(pods_p)),
        ])
        assert [s for s, _ in outcomes] == ["ok", "ok"]
        assert _wire(outcomes[0][1]) == solo_t
        assert _wire(outcomes[1][1]) == solo_p
        # every dispatch was answered, batched or split
        assert stats["dispatches"] >= 1

    def test_sharded_mesh_batch_vs_single_device(self):
        """The batched path on the forced 8-device virtual mesh (batch
        axis replicated, slot axis sharded) reproduces the single-device
        solo wire byte-for-byte."""
        specs = [("sa", 22), ("sb", 26), ("sc", 22)]
        probs = {n: _problem(n, k) for n, k in specs}
        solo = {
            n: _wire(_scheduler(probs[n][0], n).solve(
                copy.deepcopy(probs[n][1])
            ))
            for n, _k in specs
        }
        entries = [
            (
                _scheduler(probs[n][0], n, devices=8),
                copy.deepcopy(probs[n][1]),
            )
            for n, _k in specs
        ]
        outcomes, stats = solve_batch(entries)
        assert stats["batched_problems"] == 3
        for (n, _k), (status, res) in zip(specs, outcomes):
            assert status == "ok", res
            assert _wire(res) == solo[n]

    def test_batch_of_one_matches_solo(self):
        """solve_batch([single]) IS the solo path (same generator, same
        donating kernels) — the daemon routes every grant through it."""
        pool, pods = _problem("one", 16)
        solo = _wire(_scheduler(pool, "one").solve(copy.deepcopy(pods)))
        outcomes, stats = solve_batch(
            [(_scheduler(pool, "one"), copy.deepcopy(pods))]
        )
        assert outcomes[0][0] == "ok"
        assert _wire(outcomes[0][1]) == solo
        assert stats["batched_dispatches"] == 0

    def test_distinct_scheduler_instances_required(self):
        pool, pods = _problem("dup", 8)
        sched = _scheduler(pool, "dup")
        with pytest.raises(ValueError, match="distinct"):
            solve_batch([(sched, list(pods)), (sched, list(pods))])

    def test_poisoned_member_fails_alone(self):
        """A member whose device-side prepare blows up gets an isolated
        ("error", exc) outcome; its batch-mates complete with solo-parity
        results."""

        class _Poisoned(DeviceScheduler):
            def _class_steps(self, prep):
                raise RuntimeError("poisoned problem")

        pool_a, pods_a = _problem("ia", 20)
        pool_b, pods_b = _problem("ib", 20)
        pool_x, pods_x = _problem("ix", 20)
        solo_a = _wire(_scheduler(pool_a, "ia").solve(copy.deepcopy(pods_a)))
        solo_b = _wire(_scheduler(pool_b, "ib").solve(copy.deepcopy(pods_b)))
        poisoned = _Poisoned(
            [pool_x], {"ix": list(_catalog())}, max_slots=64
        )
        outcomes, _stats = solve_batch([
            (_scheduler(pool_a, "ia"), copy.deepcopy(pods_a)),
            (poisoned, copy.deepcopy(pods_x)),
            (_scheduler(pool_b, "ib"), copy.deepcopy(pods_b)),
        ])
        assert outcomes[0][0] == "ok" and _wire(outcomes[0][1]) == solo_a
        assert outcomes[2][0] == "ok" and _wire(outcomes[2][1]) == solo_b
        status, err = outcomes[1]
        assert status == "error"
        assert "poisoned problem" in repr(err)


class TestBatchedJitCacheBounded:
    def test_soak_of_random_sizes_compiles_bounded(self):
        """Randomly-sized problems through solve_batch: after the warm-up
        sweep, repeat batches inside the same shape buckets compile ZERO
        new kernels (power-of-two batch pad x bucketed tensor axes keep
        the jit key space finite)."""
        import random

        rng = random.Random(7)

        def entry(i, n_pods):
            name = f"soak{i}"
            pool, pods = _problem(name, n_pods)
            return (_scheduler(pool, name), pods)

        def batch(tag, sizes):
            return [
                entry(f"{tag}{j}", n) for j, n in enumerate(sizes)
            ]

        # warm: batch sizes 2 and 3 (both pad shapes), pod counts across
        # the 17..31 class/level bucket window
        for tag, sizes in (("w0", [20, 24]), ("w1", [18, 22, 26])):
            outcomes, _ = solve_batch(batch(tag, sizes))
            assert all(s == "ok" for s, _ in outcomes)

        from karpenter_core_tpu.ops.ffd import ffd_solve_batched

        compiles = []

        def listener(name, **kw):
            if name == "/jax/compilation_cache/compile_requests_use_cache":
                compiles.append(name)

        jax.monitoring.register_event_listener(listener)
        try:
            cache_before = ffd_solve_batched._cache_size()
            for i in range(4):
                sizes = [rng.randrange(18, 28) for _ in range(rng.choice((2, 3)))]
                outcomes, stats = solve_batch(batch(f"s{i}", sizes))
                assert all(s == "ok" for s, _ in outcomes)
                assert stats["batched_problems"] == len(sizes)
            assert ffd_solve_batched._cache_size() == cache_before
            assert compiles == [], (
                f"{len(compiles)} new compilations across the soak"
            )
        finally:
            from jax._src import monitoring as _monitoring

            _monitoring._unregister_event_listener_by_callback(listener)


# ---------------------------------------------------------------------------
# gateway coalescer units


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def tick(self, dt):
        self.now += dt


def _ready_ticket(gw, tenant, bucket="bk", fp=None, deadline=None):
    """Submit + queue a ticket from a worker thread (await_grant blocks
    while another ticket holds the device)."""
    t = gw.submit(tenant, fleet.LANE_SOLVE, deadline)
    t.bucket = bucket
    t.fingerprint = fp or f"fp-{tenant}"
    t.payload = (b"", {"pods": [None] * 4}, f"dg-{tenant}")
    th = threading.Thread(target=lambda: _swallow(gw, t), daemon=True)
    th.start()
    for _ in range(200):
        if t.state in ("queued", "batched", "shed", "drained"):
            break
        time.sleep(0.005)
    return t


def _swallow(gw, ticket):
    try:
        gw.await_grant(ticket)
    except Exception:
        pass


class TestGatewayCoalescer:
    def test_collect_batch_same_bucket_distinct_fingerprints(self):
        clock = FakeClock()
        gw = FleetGateway(max_depth=16, time_fn=clock, max_batch=8)
        leader = gw.submit("lead")
        leader.bucket, leader.fingerprint = "bk", "fp-lead"
        gw.await_grant(leader)  # device free: granted immediately
        t_match = _ready_ticket(gw, "ta")
        t_dup = _ready_ticket(gw, "tb", fp="fp-lead")  # leader's problem
        t_other = _ready_ticket(gw, "tc", bucket="other")
        members = gw.collect_batch(leader)
        assert members == [t_match]
        assert t_match.state == "batched"
        # the non-matching tickets stay queued for their own grants
        assert t_dup.state == "queued"
        assert t_other.state == "queued"
        gw.release_batch([(leader, 0.5), (t_match, 0.5)], 0.1)
        assert gw.batch_stats()["coalesced"] == 1

    def test_collect_batch_sheds_expired_members(self):
        clock = FakeClock()
        gw = FleetGateway(max_depth=16, time_fn=clock, max_batch=8)
        leader = gw.submit("lead")
        leader.bucket, leader.fingerprint = "bk", "fp-lead"
        gw.await_grant(leader)
        t_dead = _ready_ticket(gw, "ta", deadline=1.0)
        clock.tick(5.0)  # its deadline lapses while queued
        t_live = _ready_ticket(gw, "tb")
        members = gw.collect_batch(leader)
        assert members == [t_live]
        assert t_dead.state == "shed"
        gw.release_batch([(leader, 1.0), (t_live, 1.0)], 0.1)

    def test_release_batch_charges_pod_weighted_shares(self):
        clock = FakeClock()
        gw = FleetGateway(max_depth=16, time_fn=clock, max_batch=8)
        leader = gw.submit("big")
        leader.bucket, leader.fingerprint = "bk", "fp-big"
        gw.await_grant(leader)
        member = _ready_ticket(gw, "small")
        assert gw.collect_batch(leader) == [member]
        # 3:1 pod weighting of a 2.0s grant -> 1.5s vs 0.5s of vclock
        gw.release_batch([(leader, 3.0), (member, 1.0)], 2.0)
        assert gw._vtime["big"] == pytest.approx(1.5)
        assert gw._vtime["small"] == pytest.approx(0.5)
        # ONE per-grant observation, not one per problem
        assert gw.device_p50() == pytest.approx(2.0)
        assert gw.depth() == 0

    def test_collect_batch_respects_limit_and_lane(self):
        clock = FakeClock()
        gw = FleetGateway(max_depth=16, time_fn=clock, max_batch=3)
        leader = gw.submit("lead")
        leader.bucket, leader.fingerprint = "bk", "fp-lead"
        gw.await_grant(leader)
        ts = [_ready_ticket(gw, f"t{i}") for i in range(4)]
        sweep = gw.submit("sw", fleet.LANE_SWEEP)
        sweep.bucket, sweep.fingerprint = "bk", "fp-sw"
        members = gw.collect_batch(leader)  # max_batch=3 -> 2 members
        assert len(members) == 2
        assert all(t.state == "batched" for t in members)
        assert sum(t.state == "queued" for t in ts) == 2
        gw.release_batch(
            [(leader, 1.0)] + [(t, 1.0) for t in members], 0.1
        )
        for t in ts:
            gw.abandon(t)
        gw.abandon(sweep)

    def test_compatible_queued_counts_fillable_batch(self):
        """The window short-circuit: same-bucket distinct-fingerprint
        queued tickets count; the leader's own fingerprint, duplicates,
        and other buckets do not."""
        clock = FakeClock()
        gw = FleetGateway(max_depth=16, time_fn=clock, max_batch=8)
        leader = gw.submit("lead")
        leader.bucket, leader.fingerprint = "bk", "fp-lead"
        gw.await_grant(leader)
        assert gw.compatible_queued(leader) == 0
        _ready_ticket(gw, "ta")
        _ready_ticket(gw, "tb", fp="fp-lead")  # leader's own problem
        _ready_ticket(gw, "tc", bucket="other")
        _ready_ticket(gw, "td", fp="fp-ta")  # duplicate of ta's problem
        _ready_ticket(gw, "te")
        assert gw.compatible_queued(leader) == 2  # ta + te
        nobucket = fleet.Ticket("x", fleet.LANE_SOLVE, 0.0, None)
        assert gw.compatible_queued(nobucket) == 0
        gw.release(leader, 0.01)

    def test_member_outcome_handoff(self):
        gw = FleetGateway(max_depth=4)
        t = gw.submit("x")
        gw.finish_batched(t, result=("res", 0.1))
        assert gw.await_batched(t) == ("res", 0.1)
        t2 = gw.submit("y")
        gw.finish_batched(t2, error=RuntimeError("isolated"))
        with pytest.raises(RuntimeError, match="isolated"):
            gw.await_batched(t2)
        gw.abandon(t)
        gw.abandon(t2)


class TestShedEstimatorBatchAware:
    """ISSUE 9 satellite: admission divides the backlog by the observed
    problems-per-grant. A gateway whose grants each served 4 problems in
    1s must ADMIT a deadline the one-grant-per-request model would shed —
    over-shedding while batching raises effective throughput was the
    regression this pins."""

    def _seed_history(self, gw, batch_size, grants=6, seconds=1.0):
        for _ in range(grants):
            ts = [gw.submit(f"h{i}") for i in range(batch_size)]
            gw.await_grant(ts[0])
            gw.release_batch([(t, 1.0) for t in ts], seconds)

    def test_batched_history_admits_what_serial_model_sheds(self):
        clock = FakeClock()
        gw = FleetGateway(max_depth=32, time_fn=clock, max_batch=8)
        self._seed_history(gw, batch_size=4)
        assert gw.device_p50() == pytest.approx(1.0)
        # 8 requests pending; per-request model says (8+1)*1.0 = 9s
        backlog = [gw.submit(f"b{i}") for i in range(8)]
        # deadline 4s: per-grant model (9/4 grants ~ 2.25s) admits
        probe = gw.submit("probe", deadline=4.0)
        assert probe.state == "pending"
        for t in [probe] + backlog:
            gw.abandon(t)

    def test_serial_history_still_sheds(self):
        """Negative control: identical load, identical deadline, but the
        observed history is one problem per grant — the shed must still
        fire (the fix must not simply loosen admission)."""
        clock = FakeClock()
        gw = FleetGateway(max_depth=32, time_fn=clock)
        self._seed_history(gw, batch_size=1)
        backlog = [gw.submit(f"b{i}") for i in range(8)]
        with pytest.raises(fleet.ShedError) as ei:
            gw.submit("probe", deadline=4.0)
        assert ei.value.reason == "deadline"
        for t in backlog:
            gw.abandon(t)


# ---------------------------------------------------------------------------
# daemon end-to-end


def _solve_body(tenant, n_pods=6):
    pods = [
        make_pod(cpu=0.5 * (1 + i % 2), name=f"{tenant}-{i}")
        for i in range(n_pods)
    ]
    return codec.encode_solve_request(
        [make_nodepool(name=tenant)],
        {tenant: fake_instance_types(3)},
        [], [], pods, max_slots=32, tenant=tenant,
    )


def _decoded_minus_timing(out_bytes):
    d = codec.decode_solve_results(out_bytes)
    d.pop("solve_seconds", None)
    return d


def _run_coalesced(daemon, gw, bodies):
    """Deterministic coalescing: park the device, queue every request,
    release the park so one leader collects the rest."""
    park = gw.submit("zzz-park", fleet.LANE_SOLVE)
    gw.await_grant(park)
    outs, errs = {}, {}

    def run(tn, b):
        try:
            outs[tn] = daemon.solve(b)[0]
        except Exception as e:  # surfaced by the caller
            errs[tn] = e

    threads = [
        threading.Thread(target=run, args=(tn, b), daemon=True)
        for tn, b in bodies.items()
    ]
    for t in threads:
        t.start()
    for _ in range(400):
        if gw.preparing() == 0 and gw.depth() == len(bodies) + 1:
            break
        time.sleep(0.005)
    gw.release(park, 0.01)
    for t in threads:
        t.join(120)
    return outs, errs


class TestDaemonBatchedE2E:
    def test_coalesced_results_match_unbatched_daemon(self):
        bodies = {tn: _solve_body(tn) for tn in ("ea", "eb", "ec")}
        # reference: a batching-disabled daemon (the PR 5 serialized path)
        solo_daemon = service.SolverDaemon(gateway=FleetGateway(max_depth=8))
        solo = {
            tn: _decoded_minus_timing(solo_daemon.solve(b)[0])
            for tn, b in bodies.items()
        }

        gw = FleetGateway(max_depth=8, max_batch=4)
        daemon = service.SolverDaemon(gateway=gw)
        size_before = sum(m.SOLVERD_BATCH_SIZE.totals.values())
        outs, errs = _run_coalesced(daemon, gw, bodies)
        assert not errs, errs
        assert gw.batch_stats()["coalesced"] == 2
        # the grant's batch size histogram moved (3-problem grant)
        assert sum(m.SOLVERD_BATCH_SIZE.totals.values()) > size_before
        for tn, out in outs.items():
            assert _decoded_minus_timing(out) == solo[tn]
        # healthz surfaces the batch stats
        health = daemon.health()
        assert health["batch"]["coalesced"] == 2
        assert health["batch"]["max_batch"] == 4

    def test_chaos_crash_fails_leader_alone(self):
        """The device-tier chaos crash targets the leader's problem: the
        leader answers its 500 and takes the poison strike; its collected
        batch-mates still solve and answer clean — the batch-isolated
        failure contract, end to end."""
        from karpenter_core_tpu.chaos import ChaosSchedule, SolverChaos

        schedule = ChaosSchedule(
            script={"solverd.solve": ["crash", "ok", "ok"]}
        )
        chaos = SolverChaos(schedule)
        gw = FleetGateway(max_depth=8, max_batch=4)
        daemon = service.SolverDaemon(gateway=gw, chaos=chaos)
        # tenants sort by vtime then name: "ca" leads deterministically
        bodies = {tn: _solve_body(tn) for tn in ("ca", "cb", "cc")}
        digests = {
            tn: __import__("hashlib").sha256(b).hexdigest()
            for tn, b in bodies.items()
        }
        outs, errs = _run_coalesced(daemon, gw, bodies)
        assert set(errs) == {"ca"}, (errs, list(outs))
        assert "chaos" in repr(errs["ca"])
        for tn in ("cb", "cc"):
            assert _decoded_minus_timing(outs[tn])["errors"] == {}
        # the poison strike landed on the leader's digest ONLY
        assert digests["ca"] in daemon.quarantine._strike_counts
        for tn in ("cb", "cc"):
            assert digests[tn] not in daemon.quarantine._strike_counts

    def test_preparing_counts_decoding_requests(self):
        gw = FleetGateway(max_depth=4, max_batch=4)
        t = gw.submit("p0")
        assert gw.preparing() == 1  # submitted, not yet queued
        gw.await_grant(t)
        assert gw.preparing() == 0  # granted
        gw.release(t, 0.01)

    def test_preparing_is_lane_scoped(self):
        """A mid-decode SWEEP request must not make a solve leader hold
        the device idle for the batching window: preparing() counts only
        the solve lane by default."""
        gw = FleetGateway(max_depth=8, max_batch=4)
        sweep = gw.submit("sw", fleet.LANE_SWEEP)
        assert gw.preparing() == 0
        assert gw.preparing(fleet.LANE_SWEEP) == 1
        solve = gw.submit("so")
        assert gw.preparing() == 1
        gw.abandon(sweep)
        gw.abandon(solve)
        assert gw.preparing() == 0
        assert gw.preparing(fleet.LANE_SWEEP) == 0

    def test_member_marker_survives_release_overwrite(self):
        """The daemon branches member-vs-leader on the ONE-WAY
        batched_member marker: release_batch flips a member's state to
        "done" possibly before its handler thread wakes, and a state
        check racing past that overwrite would take the leader path
        without holding the grant."""
        clock = FakeClock()
        gw = FleetGateway(max_depth=8, time_fn=clock, max_batch=4)
        leader = gw.submit("lead")
        leader.bucket, leader.fingerprint = "bk", "fp-lead"
        gw.await_grant(leader)
        member = _ready_ticket(gw, "mm")
        assert gw.collect_batch(leader) == [member]
        assert member.batched_member is True
        gw.finish_batched(member, result=("r", 0.0))
        gw.release_batch([(leader, 1.0), (member, 1.0)], 0.1)
        assert member.state == "done"  # overwritten by release_batch...
        assert member.batched_member is True  # ...the marker survives

    def test_batch_disabled_gateway_never_coalesces(self):
        """max_batch=1 (the FleetGateway default): the leader path must
        not collect anyone — PR 5 semantics exactly."""
        bodies = {tn: _solve_body(tn) for tn in ("da", "db")}
        gw = FleetGateway(max_depth=8)  # defaults: batching off
        daemon = service.SolverDaemon(gateway=gw)
        outs, errs = _run_coalesced(daemon, gw, bodies)
        assert not errs, errs
        assert gw.batch_stats()["coalesced"] == 0
        assert gw.batch_stats()["mean_size"] == 1.0


class TestDrainRacesCoalescedBatch:
    def test_drain_flushes_queued_members_to_greedy_cleanly(self):
        """A scale-down drain (fleetscale, ISSUE 17) racing an in-flight
        coalesced batch: every ticket queued behind the active grant is
        flushed with the drain refusal — each client degrades to greedy
        (every pod still placed), the breaker takes NO charge, the
        quarantine records NO strike, and the gateway's admission ledgers
        return to zero once the grant releases."""
        from karpenter_core_tpu.solver.remote import (
            STATE_CLOSED,
            RemoteScheduler,
            SolverClient,
        )

        gw = FleetGateway(max_depth=8, max_batch=4)
        daemon = service.SolverDaemon(gateway=gw)
        srv = service.serve(0, daemon=daemon)
        try:
            addr = f"127.0.0.1:{srv.server_address[1]}"
            client = SolverClient(addr, timeout=120, member="0")
            # the in-flight grant the coalescing batch queues behind
            park = gw.submit("zzz-park", fleet.LANE_SOLVE)
            gw.await_grant(park)
            tenants = ("qa", "qb", "qc")
            results, errs = {}, {}

            def run(tn):
                pods = [
                    make_pod(cpu=0.5, name=f"{tn}-{i}") for i in range(4)
                ]
                rs = RemoteScheduler(
                    client,
                    [make_nodepool(name=tn)],
                    {tn: fake_instance_types(3)},
                )
                try:
                    results[tn] = rs.solve(pods)
                except Exception as e:  # surfaced by the caller
                    errs[tn] = e

            threads = [
                threading.Thread(target=run, args=(tn,), daemon=True)
                for tn in tenants
            ]
            fallbacks = m.SOLVER_RPC_FALLBACKS.value({"endpoint": "solve"})
            for t in threads:
                t.start()
            for _ in range(800):
                if gw.preparing() == 0 and gw.depth() == len(tenants) + 1:
                    break
                time.sleep(0.005)
            assert gw.depth() == len(tenants) + 1, "batch never queued"
            flushed = gw.drain()  # what POST /drain runs
            gw.release(park, 0.01)
            for t in threads:
                t.join(120)
            assert not errs, errs
            assert flushed == len(tenants)
            for tn in tenants:
                assert results[tn].all_pods_scheduled()
            # answered refusals: greedy serves, nothing is CHARGED
            assert m.SOLVER_RPC_FALLBACKS.value(
                {"endpoint": "solve"}
            ) == fallbacks + len(tenants)
            assert client.breaker.state == STATE_CLOSED
            assert client.breaker.failures == 0
            assert daemon.quarantine._strike_counts == {}
            # the flush left no residue in the admission ledgers
            assert gw.depth() == 0 and gw.preparing() == 0
            assert gw._active is None and gw._batched_inflight == 0
            assert gw.draining()
        finally:
            srv.shutdown()
            srv.server_close()


class TestBatchFlagPlumbing:
    def test_operator_flags_parse_and_validate(self):
        from karpenter_core_tpu.operator import Options

        opts = Options.parse([])
        assert opts.solver_max_batch == fleet.DEFAULT_MAX_BATCH
        assert opts.solver_batch_window_ms == fleet.DEFAULT_BATCH_WINDOW_MS
        opts = Options.parse(
            ["--solver-max-batch", "4", "--solver-batch-window-ms", "0"]
        )
        assert opts.solver_max_batch == 4
        assert opts.solver_batch_window_ms == 0.0
        assert Options.parse(
            [], env={"KARPENTER_SOLVER_MAX_BATCH": "16"}
        ).solver_max_batch == 16
        with pytest.raises(ValueError, match="solver-max-batch"):
            Options.parse(["--solver-max-batch", "0"])
        with pytest.raises(ValueError, match="batch-window-ms"):
            Options.parse(["--solver-batch-window-ms", "-1"])

    def test_supervisor_spawn_argv_carries_batching(self):
        from karpenter_core_tpu.solver.supervisor import default_command

        cmd = default_command(0, max_batch=4, batch_window_ms=1.5)
        assert cmd[cmd.index("--max-batch") + 1] == "4"
        assert cmd[cmd.index("--batch-window-ms") + 1] == "1.5"
        bare = default_command(0)
        assert "--max-batch" not in bare
        assert "--batch-window-ms" not in bare


class TestProblemBucket:
    def test_same_shape_different_content_share_bucket(self):
        """Two tenants with different catalogs/pools of the SAME shape
        land in one bucket (the cross-tenant coalescing predicate), while
        a materially different problem shape does not."""
        d1 = codec.decode_solve_request(_solve_body("ba"))
        d2 = codec.decode_solve_request(_solve_body("bb"))
        assert d1["fingerprint"] != d2["fingerprint"]
        assert d1["bucket"] == d2["bucket"]
        d3 = codec.decode_solve_request(_solve_body("bc", n_pods=40))
        assert d3["bucket"] != d1["bucket"]  # pod-count bucket differs
