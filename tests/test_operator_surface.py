"""Operator-surface periphery: options flag/env parsing, logging, CLI
entry, hydration, cloud-provider metrics decorator, health probes
(reference: options.go:85-144, logging.go:35-79, kwok/main.go:28-47,
hydration/controller.go:41-78, cloudprovider/metrics).
"""
import pytest

from tests.helpers import make_nodepool, make_pod
from tests.test_e2e import new_operator

from karpenter_core_tpu.operator import Options


class TestOptionsParse:
    def test_defaults(self):
        o = Options.parse([], env={})
        assert o.solver == "greedy" and o.batch_max_duration == 10.0

    def test_flags_space_and_equals(self):
        o = Options.parse(
            ["--solver", "tpu", "--batch-max-duration=5",
             "--batch-idle-duration", "0.5", "--log-level=debug"],
            env={},
        )
        assert o.solver == "tpu"
        assert o.batch_max_duration == 5.0
        assert o.batch_idle_duration == 0.5
        assert o.log_level == "debug"

    def test_env_fallback_and_flag_priority(self):
        env = {"KARPENTER_SOLVER": "tpu", "KARPENTER_BATCH_MAX_DURATION": "3"}
        o = Options.parse([], env=env)
        assert o.solver == "tpu" and o.batch_max_duration == 3.0
        o2 = Options.parse(["--solver", "greedy"], env=env)
        assert o2.solver == "greedy"  # flag wins over env

    def test_feature_gates_string(self):
        o = Options.parse(
            ["--feature-gates", "NodeRepair=true,SpotToSpot=false"], env={}
        )
        assert o.feature_gates == {"NodeRepair": True, "SpotToSpot": False}

    def test_unknown_solver_rejected(self):
        with pytest.raises(ValueError):
            Options.parse(["--solver", "quantum"], env={})

    def test_solver_mode_flags(self):
        o = Options.parse([], env={})
        assert o.solver_mode == "inproc"
        assert o.solver_addr == "" and o.solver_timeout == 30.0
        o = Options.parse(
            ["--solver", "tpu", "--solver-mode", "sidecar",
             "--solver-addr=127.0.0.1:8181", "--solver-timeout", "5"],
            env={},
        )
        assert o.solver_mode == "sidecar"
        assert o.solver_addr == "127.0.0.1:8181"
        assert o.solver_timeout == 5.0
        assert Options.parse(
            [],
            env={"KARPENTER_SOLVER": "tpu",
                 "KARPENTER_SOLVER_MODE": "sidecar"},
        ).solver_mode == "sidecar"
        with pytest.raises(ValueError):
            Options.parse(["--solver-mode", "carrier-pigeon"], env={})
        # sidecar without the tpu solver would silently run greedy in-proc
        with pytest.raises(ValueError):
            Options.parse(["--solver-mode", "sidecar"], env={})

    def test_fleet_tenancy_flags(self):
        o = Options.parse([], env={})
        assert o.solver_tenant == "default"
        assert o.solver_queue_depth == 16
        assert o.solver_tenant_weights == ""
        o = Options.parse(
            ["--solver-tenant", "blue", "--solver-queue-depth=8",
             "--solver-tenant-weights", "blue=3,green=1"],
            env={},
        )
        assert o.solver_tenant == "blue"
        assert o.solver_queue_depth == 8
        assert o.solver_tenant_weights == "blue=3,green=1"
        assert Options.parse(
            [], env={"KARPENTER_SOLVER_TENANT": "green"}
        ).solver_tenant == "green"
        # gateway sizing/identity errors surface at the flag boundary, not
        # inside a respawned sidecar's argparse
        with pytest.raises(ValueError, match="must be positive"):
            Options.parse(["--solver-queue-depth", "0"], env={})
        with pytest.raises(ValueError, match="non-empty"):
            Options.parse(["--solver-tenant", ""], env={})
        with pytest.raises(ValueError):
            Options.parse(["--solver-tenant-weights", "blue=-1"], env={})
        with pytest.raises(ValueError):
            Options.parse(["--solver-tenant-weights", "blue"], env={})

    def test_fleet_and_wire_flags(self):
        # delta wire + horizontally scaled solver tier (ISSUE 14)
        o = Options.parse([], env={})
        assert o.solver_fleet == 1
        assert o.solver_wire == "delta"
        o = Options.parse(
            ["--solver-fleet", "4", "--solver-wire=full"], env={}
        )
        assert o.solver_fleet == 4 and o.solver_wire == "full"
        assert Options.parse(
            [], env={"KARPENTER_SOLVER_FLEET": "2"}
        ).solver_fleet == 2
        with pytest.raises(ValueError, match="solver-fleet"):
            Options.parse(["--solver-fleet", "0"], env={})
        with pytest.raises(ValueError, match="wire mode"):
            Options.parse(["--solver-wire", "chunky"], env={})
        # fleet sizing governs SPAWNED children; silently ignoring it
        # next to an external address would fake a fleet
        with pytest.raises(ValueError, match="cannot combine"):
            Options.parse(
                ["--solver-fleet", "2", "--solver-addr", "h:1"], env={}
            )
        # an external fleet IS expressible: the comma-list address
        o = Options.parse(
            ["--solver-addr", "h:1,h:2"], env={}
        )
        assert o.solver_addr == "h:1,h:2" and o.solver_fleet == 1

    def test_unknown_flag_rejected(self):
        # a typo'd flag must error, not silently swallow the next flag
        with pytest.raises(ValueError):
            Options.parse(["--verbose", "--solver", "tpu"], env={})

    @pytest.mark.parametrize("flag", [
        "--solver-timeout", "--batch-max-duration", "--poll-interval",
    ])
    @pytest.mark.parametrize("value", ["0", "-1", "-0.5"])
    def test_non_positive_durations_rejected(self, flag, value):
        with pytest.raises(ValueError, match="must be positive"):
            Options.parse([flag, value], env={})

    def test_non_positive_duration_rejected_from_env(self):
        with pytest.raises(ValueError, match="must be positive"):
            Options.parse([], env={"KARPENTER_SOLVER_TIMEOUT": "0"})

    def test_loop_flags_both_forms(self):
        o = Options.parse(
            ["--poll-interval=2.5", "--max-iters", "7"], env={}
        )
        assert o.poll_interval == 2.5 and o.max_iters == 7


class TestLogging:
    def test_configure_levels_and_nop(self):
        import logging as stdlib_logging

        from karpenter_core_tpu.logging import configure, nop_logger

        logger = configure("debug")
        assert logger.level == stdlib_logging.DEBUG
        configure("error")
        assert logger.level == stdlib_logging.ERROR
        nop = nop_logger()
        assert not nop.isEnabledFor(stdlib_logging.CRITICAL)


class TestCLI:
    def test_main_runs_bounded_loop(self, capsys):
        from karpenter_core_tpu.main import main

        assert main(["--solver", "greedy", "--max-iters", "2",
                     "--poll-interval", "0.01"]) == 0


class TestHydration:
    def test_nodeclass_label_backfilled(self):
        from karpenter_core_tpu.api.nodeclaim import NodeClassRef

        op = new_operator()
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="p0"))
        op.run_until_idle()
        claim = op.kube.list_nodeclaims()[0]
        # a pre-existing (old-version) claim: nodeClassRef set, label absent
        claim.spec.node_class_ref = NodeClassRef(
            group="karpenter.kwok.sh", kind="KWOKNodeClass", name="default"
        )
        op.kube.update(claim)
        op.run_until_idle()
        key = "karpenter.kwok.sh/kwoknodeclass"
        claim = op.kube.get(type(claim), claim.name)
        assert claim.metadata.labels.get(key) == "default"
        node = op.kube.get_node_by_provider_id(claim.status.provider_id)
        assert node.metadata.labels.get(key) == "default"


class TestCloudProviderMetrics:
    def test_decorator_records_durations_and_errors(self):
        from karpenter_core_tpu.cloudprovider.metrics import (
            METHOD_DURATION,
            METHOD_ERRORS,
            MetricsDecorator,
        )

        class Boom(Exception):
            pass

        class FakeProvider:
            name = "fake"

            def get_instance_types(self, nodepool):
                return ["it"]

            def delete(self, claim):
                raise Boom("nope")

        p = MetricsDecorator(FakeProvider())
        assert p.name == "fake"  # non-wrapped attrs forward
        assert p.get_instance_types(None) == ["it"]
        labels = {"method": "get_instance_types", "provider": "FakeProvider"}
        assert METHOD_DURATION.totals.get(
            tuple(sorted(labels.items()))
        )
        with pytest.raises(Boom):
            p.delete(None)
        err_labels = {
            "method": "delete", "provider": "FakeProvider", "error": "Boom",
        }
        assert METHOD_ERRORS.value(err_labels) == 1


class TestHealthProbes:
    def test_ready_after_sync(self):
        op = new_operator()
        assert op.healthz()
        op.kube.create(make_nodepool())
        op.kube.create(make_pod(cpu=1.0, name="p0"))
        op.run_until_idle()
        assert op.readyz()


class TestProfilingHook:
    def test_profile_solves_writes_pprof(self, tmp_path):
        from tests.helpers import make_nodepool, make_pod
        from tests.test_e2e import new_operator, replicated

        op = new_operator()
        op.provisioner.profile_solves = 1
        op.provisioner.profile_dir = str(tmp_path)
        op.kube.create(make_nodepool())
        op.kube.create(replicated(make_pod(cpu=1.0, name="p0")))
        op.run_until_idle()
        files = [f.name for f in tmp_path.iterdir()]
        assert "solve-0.pprof" in files
        import pstats

        stats = pstats.Stats(str(tmp_path / "solve-0.pprof"))
        assert stats.total_calls > 0

    def test_profile_flags_parse(self):
        from karpenter_core_tpu.operator import Options

        opts = Options.parse(
            ["--profile-solves", "3", "--profile-dir", "/tmp/x"]
        )
        assert opts.profile_solves == 3
        assert opts.profile_dir == "/tmp/x"


class TestHealthServer:
    def test_probes_and_metrics_served(self):
        import urllib.request

        from tests.helpers import make_nodepool, make_pod
        from tests.test_e2e import new_operator, replicated

        from karpenter_core_tpu.healthserver import start_health_server

        op = new_operator()
        srv = start_health_server(op, port=0)
        try:
            port = srv.server_address[1]

            def get(path):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5
                ) as r:
                    return r.status, r.read().decode()

            assert get("/healthz")[0] == 200
            assert get("/readyz")[0] == 200
            op.kube.create(make_nodepool())
            op.kube.create(replicated(make_pod(cpu=1.0, name="h0")))
            op.run_until_idle()
            code, text = get("/metrics")
            assert code == 200
            assert "karpenter_provisioner_scheduling_duration_seconds" in text
            assert "karpenter_cluster_state_node_count" in text
        finally:
            srv.shutdown()
            srv.server_close()

    def test_health_port_flag_parses(self):
        from karpenter_core_tpu.operator import Options

        assert Options.parse(["--health-port", "8081"]).health_port == 8081
