"""Generate CRD manifests for the API surface from the dataclasses.

The reference ships generated CustomResourceDefinition YAML
(pkg/apis/crds/karpenter.sh_nodepools.yaml, _nodeclaims.yaml) produced by
controller-gen from struct tags; here the dataclasses are the source of
truth, so this walks their fields/types into openAPIV3Schema properties.
Run from the repo root:

    python tools/gen_crds.py          # rewrites karpenter_core_tpu/api/crds/

tests/test_periphery.py asserts the checked-in artifacts are current.
"""
from __future__ import annotations

import dataclasses
import os
import sys
import typing

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import yaml  # noqa: E402

from karpenter_core_tpu.api.duration import NillableDuration  # noqa: E402
from karpenter_core_tpu.api.nodeclaim import NodeClaim  # noqa: E402
from karpenter_core_tpu.api.nodepool import Limits, NodePool  # noqa: E402
from karpenter_core_tpu.api.status import ConditionSet  # noqa: E402

OUT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "karpenter_core_tpu", "api", "crds",
)

_PRIMITIVES = {
    str: {"type": "string"},
    int: {"type": "integer"},
    float: {"type": "number"},
    bool: {"type": "boolean"},
}


def _schema(tp, seen: tuple) -> dict:
    origin = typing.get_origin(tp)
    args = typing.get_args(tp)
    if tp in _PRIMITIVES:
        return dict(_PRIMITIVES[tp])
    if tp is NillableDuration:
        return {
            "type": "string",
            "description": "duration in seconds; 'Never' disables",
            "x-nillable-duration": True,
        }
    if tp is ConditionSet:
        return {
            "type": "array",
            "items": {
                "type": "object",
                "properties": {
                    "type": {"type": "string"},
                    "status": {"type": "string"},
                    "reason": {"type": "string"},
                    "message": {"type": "string"},
                    "lastTransitionTime": {"type": "number"},
                },
            },
        }
    if tp is Limits or origin is dict or tp is dict:
        return {"type": "object", "additionalProperties": True}
    if origin in (list, tuple) or tp in (list, tuple):
        item = _schema(args[0], seen) if args else {}
        return {"type": "array", "items": item}
    if origin is typing.Union:
        non_none = [a for a in args if a is not type(None)]
        if len(non_none) == 1:
            s = _schema(non_none[0], seen)
            s["nullable"] = True
            return s
        return {}
    if dataclasses.is_dataclass(tp):
        if tp in seen:  # recursion guard (Pod inside DaemonSet etc.)
            return {"type": "object", "x-ref": tp.__name__}
        try:
            hints = typing.get_type_hints(tp)
        except Exception:
            hints = {}
        props = {}
        for f in dataclasses.fields(tp):
            props[f.name] = _schema(hints.get(f.name, f.type), seen + (tp,))
        return {"type": "object", "properties": props}
    return {}


def crd(cls, plural: str, scope: str = "Cluster") -> dict:
    # resolve string annotations (from __future__ annotations) to types
    hints = typing.get_type_hints(cls)
    props = {
        f.name: _schema(hints.get(f.name, f.type), (cls,))
        for f in dataclasses.fields(cls)
    }
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.karpenter.sh"},
        "spec": {
            "group": "karpenter.sh",
            "names": {
                "kind": cls.__name__,
                "listKind": f"{cls.__name__}List",
                "plural": plural,
                "singular": cls.__name__.lower(),
            },
            "scope": scope,
            "versions": [{
                "name": "v1",
                "served": True,
                "storage": True,
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "properties": props,
                }},
            }],
        },
    }


def render() -> dict:
    """filename -> yaml text for every CRD artifact."""
    out = {}
    for cls, plural in ((NodePool, "nodepools"), (NodeClaim, "nodeclaims")):
        text = yaml.safe_dump(
            crd(cls, plural), sort_keys=True, default_flow_style=False
        )
        out[f"karpenter.sh_{plural}.yaml"] = text
    return out


def main() -> None:
    os.makedirs(OUT_DIR, exist_ok=True)
    for fname, text in render().items():
        with open(os.path.join(OUT_DIR, fname), "w") as f:
            f.write(text)
        print(f"wrote {fname}")


if __name__ == "__main__":
    main()
