"""Diagnose the cfg3 topology parity gap: dump per-node packing for the
device solver vs the greedy oracle on the identical pod set and diff the
fleet composition. Run: JAX_PLATFORMS=cpu python tools/diag_cfg3.py [n]
"""
from __future__ import annotations

import collections
import copy
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from karpenter_core_tpu.cloudprovider.kwok import bench_catalog  # noqa: E402


def kind_of(pod_name: str) -> int:
    return int(pod_name[1:]) % 6


KIND_NAMES = ["generic", "zonal-aff", "selector", "spread-z", "spread-h", "anti-h"]


def describe(claims, tag):
    print(f"\n=== {tag}: {len(claims)} nodes ===")
    rows = []
    for c in claims:
        kinds = collections.Counter(kind_of(p.metadata.name) for p in c.pods)
        cpu = c.requests.get("cpu", 0.0)
        mem = c.requests.get("memory", 0.0) / 2**30
        # cheapest viable instance type = what provision() would pick
        best = None
        for it in c.instance_type_options:
            offs = it.offerings.available().compatible(c.requirements)
            for o in offs:
                if best is None or o.price < best[1]:
                    best = (it, o.price)
        it_name = best[0].name if best else "?"
        itc = best[0].capacity if best else {}
        rows.append(
            dict(
                npods=len(c.pods),
                cpu=cpu,
                mem=mem,
                it=it_name,
                itcpu=itc.get("cpu", 0),
                itmem=itc.get("memory", 0) / 2**30,
                price=best[1] if best else 0,
                kinds=dict(sorted(kinds.items())),
            )
        )
    rows.sort(key=lambda r: (-r["npods"], r["it"]))
    total_price = sum(r["price"] for r in rows)
    it_hist = collections.Counter(r["it"] for r in rows)
    fill_cpu = [r["cpu"] / r["itcpu"] for r in rows if r["itcpu"]]
    fill_mem = [r["mem"] / r["itmem"] for r in rows if r["itmem"]]
    print(f"total price {total_price:.3f}")
    print("instance types:", dict(it_hist.most_common()))
    print(
        "fill cpu avg %.3f mem avg %.3f"
        % (sum(fill_cpu) / len(fill_cpu), sum(fill_mem) / len(fill_mem))
    )
    # nodes by dominant kind content
    kind_nodes = collections.Counter()
    for r in rows:
        key = tuple(sorted(r["kinds"].items()))
        kind_nodes[key] += 1
    print("node kind-compositions (top 25):")
    for key, n in kind_nodes.most_common(25):
        lbl = ",".join(f"{KIND_NAMES[k]}x{v}" for k, v in key)
        print(f"  {n:4d}  {lbl}")
    return rows


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 5000
    pods = bench._topology_pods(n)
    pools = [bench._pool()]
    catalog = bench_catalog(400)

    from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
        Scheduler,
    )
    from karpenter_core_tpu.models.provisioner import DeviceScheduler

    its = {p.name: list(catalog) for p in pools}
    g = Scheduler(copy.deepcopy(pools), its)
    gres = g.solve(copy.deepcopy(pods))
    assert gres.all_pods_scheduled(), list(gres.pod_errors.items())[:3]

    d = DeviceScheduler(pools, its, max_slots=2048)
    dres = d.solve(pods)
    assert dres.all_pods_scheduled(), list(dres.pod_errors.items())[:3]

    grows = describe(gres.new_node_claims, "greedy")
    drows = describe(dres.new_node_claims, "device")
    print(
        f"\nDELTA: device {len(drows)} - greedy {len(grows)} = "
        f"{len(drows) - len(grows)}"
    )


if __name__ == "__main__":
    main()
