"""graftlint engine: rule registry, suppressions, baseline, runner.

The solver's correctness rests on invariants pytest cannot see — canonical
iteration order feeding fingerprints, host-sync-free jit regions, lock
discipline around the threaded solverd, encode/decode field parity on the
wire. graftlint machine-checks them on every diff. This module is the
project-agnostic half: file loading, the rule-author API, inline
suppressions, the frozen baseline, and the CLI runner. The invariants
themselves live in ``tools/graftlint/rules/`` (one module per family).

Rule-author API
---------------
Subclass :class:`Rule` and decorate with :func:`register`::

    from tools.graftlint.engine import Rule, register

    @register
    class NoSleepInReconcile(Rule):
        id = "GL501"
        name = "reconcile-sleep"
        rationale = "time.sleep in a reconciler stalls the whole pass"

        def applies(self, pf):           # optional file filter
            return "controllers/" in pf.relpath

        def check(self, pf):             # per-file rule
            for node in pf.walk(ast.Call):
                if pf.call_name(node) == "time.sleep":
                    yield self.finding(pf, node, "time.sleep in reconcile path")

Project-scope rules (cross-file: parity checks) set ``scope = "project"``
and implement ``check_project(files)`` instead. Import the module from
``tools/graftlint/rules/__init__.py`` so registration runs.

Suppressions
------------
``# graftlint: disable=GL201 -- <justification>`` on the flagged line (or a
standalone comment on the line above) silences that rule there. The
justification after ``--`` is mandatory: a bare disable is itself reported
as GL000. ``disable=all`` silences every rule for the line.

Baseline
--------
``tools/graftlint/baseline.json`` freezes reviewed pre-existing violations
(fingerprinted by rule + path + source text, so unrelated edits don't shift
them). ``--baseline`` rewrites it from the current findings; anything not
in it fails the run. The repo policy (ISSUE 4) is an EMPTY baseline for the
shipped rule families — real violations get fixed or inline-justified.
"""
from __future__ import annotations

import ast
import hashlib
import io
import json
import re
import time
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"
# incremental-mode finding cache (gitignored): per-file results keyed on
# (content hash, rule-set hash), so an unchanged file never re-runs the
# file-scope rules. Project-scope rules re-run whenever ANY scanned file
# (or any rule source) changes — their joint verdict is cached under the
# reserved _PROJECT_CACHE_KEY entry keyed on the whole scanned set.
CACHE_PATH = Path(__file__).resolve().parent / ".finding_cache.json"
_PROJECT_CACHE_KEY = "__project__"

# single source of truth for the tier-1 wall-time budget: the test gate
# (tests/test_graftlint.py) and bench.py --lint both enforce this value
LINT_BUDGET_SECONDS = 10.0

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+?)\s*(?:--\s*(\S.*))?$"
)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int
    message: str

    def fingerprint(self, source_line: str) -> str:
        """Line-number-independent identity for baseline entries."""
        return f"{self.rule}|{self.path}|{source_line.strip()}"

    def stable_id(self, source_line: str) -> str:
        """Short content-addressed finding id for machine formats (CI
        annotation dedup, editor integrations): line-number independent,
        so a finding keeps its id across unrelated edits above it."""
        return hashlib.sha1(self.fingerprint(source_line).encode()).hexdigest()[:16]

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


class ParsedFile:
    """One source file plus the per-file artifacts every rule shares."""

    def __init__(self, path: Path, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._gl_parent = parent  # type: ignore[attr-defined]
        # line -> (rule ids | {"all"}, has_justification). Parsed from
        # COMMENT tokens only — a string literal containing the disable
        # syntax (docs, error messages) must neither suppress nor trip
        # GL000.
        self.suppressions: Dict[int, Tuple[set, bool]] = {}
        self.comment_lines: set = set()
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type != tokenize.COMMENT:
                    continue
                lineno = tok.start[0]
                if tok.start[1] == 0 or not self.lines[
                    lineno - 1
                ][: tok.start[1]].strip():
                    self.comment_lines.add(lineno)  # standalone comment
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    rules = {
                        r.strip() for r in m.group(1).split(",") if r.strip()
                    }
                    self.suppressions[lineno] = (rules, m.group(2) is not None)
        except tokenize.TokenError:
            pass  # ast.parse above succeeded; treat the tail as comment-free

    def walk(self, *types) -> Iterator[ast.AST]:
        for node in ast.walk(self.tree):
            if not types or isinstance(node, types):
                yield node

    def parents(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = getattr(node, "_gl_parent", None)
        while cur is not None:
            yield cur
            cur = getattr(cur, "_gl_parent", None)

    def enclosing_function(self, node: ast.AST):
        for p in self.parents(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return p
        return None

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for p in self.parents(node):
            if isinstance(p, ast.ClassDef):
                return p
        return None

    def call_name(self, node: ast.Call) -> str:
        """Dotted name of a call target: ``time.sleep``, ``sorted`` — ''
        when the callee is not a plain name/attribute chain."""
        return dotted_name(node.func)

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def is_suppressed(self, finding: Finding) -> bool:
        """Same-line disable, or a disable anywhere in the contiguous
        standalone-comment block immediately above the flagged line (so a
        justification may wrap over several comment lines)."""
        candidates = [finding.line]
        lineno = finding.line - 1
        while lineno >= 1 and lineno in self.comment_lines:
            candidates.append(lineno)
            lineno -= 1
        for ln in candidates:
            entry = self.suppressions.get(ln)
            if entry is None:
                continue
            rules, _ = entry
            if finding.rule in rules or "all" in rules:
                return True
        return False


def dotted_name(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class Rule:
    """Base class for graftlint rules; see the module docstring."""

    id: str = ""
    name: str = ""
    rationale: str = ""
    scope: str = "file"  # "file" | "project"

    def applies(self, pf: ParsedFile) -> bool:
        return True

    def check(self, pf: ParsedFile) -> Iterable[Finding]:
        return ()

    def check_project(self, files: List[ParsedFile]) -> Iterable[Finding]:
        return ()

    def finding(self, pf: ParsedFile, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=self.id,
            path=pf.relpath,
            line=getattr(node, "lineno", 1),
            message=message,
        )


RULES: Dict[str, Rule] = {}


def register(cls):
    inst = cls()
    if not inst.id or not inst.name:
        raise ValueError(f"rule {cls.__name__} needs id and name")
    if inst.id in RULES:
        raise ValueError(f"duplicate rule id {inst.id}")
    RULES[inst.id] = inst
    return cls


def _collect_files(paths: List[str]) -> List[ParsedFile]:
    files: List[ParsedFile] = []
    seen = set()
    for raw in paths:
        p = Path(raw)
        if not p.is_absolute():
            p = REPO_ROOT / p
        if not p.exists():
            # a typo'd path must fail the gate, not lint zero files green
            raise SystemExit(f"graftlint: path not found: {raw}")
        candidates = [p] if p.is_file() else sorted(p.rglob("*.py"))
        for f in candidates:
            if "__pycache__" in f.parts or f in seen:
                continue
            seen.add(f)
            try:
                rel = f.resolve().relative_to(REPO_ROOT).as_posix()
            except ValueError:
                rel = f.as_posix()
            source = f.read_text()
            try:
                files.append(ParsedFile(f, rel, source))
            except SyntaxError as e:
                raise SystemExit(f"graftlint: cannot parse {rel}: {e}")
    if not files:
        raise SystemExit(
            f"graftlint: no Python files found under {', '.join(paths)}"
        )
    return files


@dataclass
class RunResult:
    new: List[Tuple[Finding, str]]  # (finding, source line)
    baselined: List[Finding]
    suppressed: List[Finding]
    files: int
    rule_seconds: Dict[str, float]
    cache_hits: int = 0
    cache_misses: int = 0

    @property
    def ok(self) -> bool:
        return not self.new


def _load_baseline(path: Optional[Path] = None) -> Dict[str, int]:
    path = path or BASELINE_PATH
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    return dict(data.get("entries", {}))


def _bad_suppression_findings(pf: ParsedFile) -> List[Finding]:
    out = []
    for lineno, (rules, has_why) in sorted(pf.suppressions.items()):
        unknown = {
            r for r in rules if r != "all" and r not in RULES and r != "GL000"
        }
        if not has_why:
            out.append(Finding(
                "GL000", pf.relpath, lineno,
                "suppression without justification: write"
                " '# graftlint: disable=RULE -- why'",
            ))
        if unknown:
            out.append(Finding(
                "GL000", pf.relpath, lineno,
                f"suppression names unknown rule(s): {', '.join(sorted(unknown))}",
            ))
    return out


_RULES_HASH: Optional[str] = None


def _rules_hash() -> str:
    """Content hash of the whole lint implementation (engine, dataflow,
    every rule module, the wire lock): the incremental cache's rule-set
    key, so ANY rule change busts every cached entry."""
    global _RULES_HASH
    if _RULES_HASH is None:
        h = hashlib.sha256()
        root = Path(__file__).resolve().parent
        for p in sorted(root.rglob("*.py")) + sorted(root.glob("*.lock.json")):
            if "__pycache__" in p.parts:
                continue
            h.update(p.name.encode())
            h.update(p.read_bytes())
        _RULES_HASH = h.hexdigest()
    return _RULES_HASH


def _file_scope_results(pf: ParsedFile, rule_ids: Optional[List[str]] = None) -> dict:
    """Run every file-scope rule (plus GL000) over one parsed file and
    partition by inline suppression. Returns a JSON-serializable dict —
    the unit the incremental cache stores and the --jobs workers ship."""
    from tools.graftlint import rules as _rules  # noqa: F401 (registration)

    res = {
        "relpath": pf.relpath,
        "new": [],  # [rule, line, message, source line]
        "suppressed": [],  # [rule, line, message]
        "rule_seconds": {},
    }
    active = [
        r for rid, r in sorted(RULES.items())
        if r.scope != "project" and (rule_ids is None or rid in rule_ids)
    ]
    for rule in active:
        t0 = time.perf_counter()
        if rule.applies(pf):
            for f in rule.check(pf):
                if pf.is_suppressed(f):
                    res["suppressed"].append([f.rule, f.line, f.message])
                else:
                    res["new"].append(
                        [f.rule, f.line, f.message, pf.source_line(f.line)]
                    )
        res["rule_seconds"][rule.id] = time.perf_counter() - t0
    if rule_ids is None or "GL000" in rule_ids:
        t0 = time.perf_counter()
        for f in _bad_suppression_findings(pf):
            res["new"].append([f.rule, f.line, f.message, pf.source_line(f.line)])
        res["rule_seconds"]["GL000"] = time.perf_counter() - t0
    return res


def _lint_file_worker(job: Tuple[str, str, str]) -> dict:
    """--jobs N worker: parse one file and run the file-scope rules in a
    separate process. The SOURCE ships from the parent (which already
    read and content-hashed it for the cache key) — re-reading here would
    let an edit between the two reads store findings for new content
    under the old content's hash."""
    path_str, rel, source = job
    pf = ParsedFile(Path(path_str), rel, source)
    return _file_scope_results(pf)


def _load_cache(path: Path) -> dict:
    if not path.exists():
        return {}
    try:
        data = json.loads(path.read_text())
    except (ValueError, OSError):
        return {}
    return data if isinstance(data, dict) else {}


def changed_relpaths(base: Optional[str] = None) -> set:
    """Repo-relative paths changed vs the merge-base (``--changed-only``).

    ``base`` defaults to the merge-base of HEAD with the first of
    origin/main, origin/master, main, master that resolves. The set is
    working-tree honest: committed + staged + unstaged diffs against the
    base, plus untracked files. Returns an empty set when git is
    unavailable — the caller then lints nothing file-scoped, which is the
    right answer for "what did I change" on a clean tree."""
    import subprocess

    def _git(*args) -> Optional[str]:
        try:
            r = subprocess.run(
                ["git", *args], cwd=REPO_ROOT, capture_output=True,
                text=True, timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        return r.stdout if r.returncode == 0 else None

    if base is None:
        for cand in ("origin/main", "origin/master", "main", "master"):
            out = _git("merge-base", "HEAD", cand)
            if out and out.strip():
                base = out.strip()
                break
    changed = set()
    if base is not None:
        out = _git("diff", "--name-only", base)
        if out:
            changed |= {ln.strip() for ln in out.splitlines() if ln.strip()}
    out = _git("ls-files", "--others", "--exclude-standard")
    if out:
        changed |= {ln.strip() for ln in out.splitlines() if ln.strip()}
    return {p for p in changed if p.endswith(".py")}


def run(
    paths: List[str],
    use_baseline: bool = True,
    rule_ids: Optional[List[str]] = None,
    baseline_path: Optional[Path] = None,
    jobs: int = 1,
    cache_path: Optional[Path] = None,
    restrict_to: Optional[set] = None,
) -> RunResult:
    """Run every registered rule over ``paths``; returns the partitioned
    findings. ``rule_ids`` restricts the pass (rule unit tests).

    Incremental mode: with ``cache_path`` set (and no rule restriction),
    file-scope findings are cached per file keyed on (content hash,
    rule-set hash) — an unchanged file costs one dict lookup. Project-
    scope rules (cross-file parity, the sharding/rangecheck dataflow
    families) re-run whenever any scanned file or rule changes; their
    joint verdict is cached per scanned-set content. ``jobs > 1`` fans
    the uncached file-scope work over a process pool.

    ``restrict_to`` (a set of repo-relative paths — ``--changed-only``
    passes the merge-base diff) limits the FILE-scope rules to those
    files; project-scope rules still parse and check the full ``paths``
    set, because their verdicts (wire locks, cross-file routing, the
    attribute-summary joins) depend on files the diff didn't touch.
    """
    from tools.graftlint import rules as _rules  # noqa: F401 (registration)

    files = _collect_files(paths)
    if rule_ids is not None:
        unknown = set(rule_ids) - set(RULES) - {"GL000"}
        if unknown:
            # same policy as a typo'd path: fail the gate, don't run zero
            # rules green
            raise SystemExit(
                f"graftlint: unknown rule id(s): {', '.join(sorted(unknown))}"
            )
    rule_seconds: Dict[str, float] = {}
    by_rel = {pf.relpath: pf for pf in files}

    # -- file-scope rules: cache, then (possibly parallel) execution -------
    caching = cache_path is not None and rule_ids is None
    cache_data = _load_cache(cache_path) if caching else {}
    rhash = _rules_hash() if caching else ""
    # project-scope verdict cache: the project rules' findings depend on
    # exactly (the full scanned set's content, the rule-set hash) — with
    # both unchanged, a warm run skips the dataflow index builds and the
    # cross-file fixpoints entirely (what keeps the warm incremental run
    # ≈1s as the project-rule families grow). Any file edit, add, delete
    # or rule change flips the key.
    project_key = None
    project_cached = None
    if caching and all(not pf.relpath.startswith("/") for pf in files):
        ph = hashlib.sha256()
        for pf in sorted(files, key=lambda p: p.relpath):
            ph.update(pf.relpath.encode())
            ph.update(hashlib.sha256(pf.source.encode()).digest())
        project_key = ph.hexdigest() + ":" + rhash
        ent = cache_data.get(_PROJECT_CACHE_KEY)
        if isinstance(ent, dict) and ent.get("key") == project_key:
            project_cached = ent
    per_file: Dict[str, dict] = {}
    file_keys: Dict[str, str] = {}
    cache_hits = cache_misses = 0
    pending: List[ParsedFile] = []
    for pf in files:
        if restrict_to is not None and pf.relpath not in restrict_to:
            continue  # --changed-only: file-scope skipped, not cached
        if caching:
            if pf.relpath.startswith("/"):
                # out-of-repo path (ad-hoc lint of tmp fixtures): lint
                # fresh every time, never absorb into the repo cache
                cache_misses += 1
            else:
                key = (
                    hashlib.sha256(pf.source.encode()).hexdigest()
                    + ":"
                    + rhash
                )
                file_keys[pf.relpath] = key
                ent = cache_data.get(pf.relpath)
                if isinstance(ent, dict) and ent.get("key") == key:
                    per_file[pf.relpath] = ent
                    cache_hits += 1
                    continue
                cache_misses += 1
        pending.append(pf)

    if jobs > 1 and rule_ids is None and len(pending) > 1:
        import multiprocessing as mp
        import sys
        from concurrent.futures import ProcessPoolExecutor

        # fork under a loaded (multithreaded) JAX runtime can deadlock;
        # the standalone CLI never imports jax, but in-process callers
        # (pytest, bench.py) do — pay the spawn cost there
        ctx = mp.get_context("spawn" if "jax" in sys.modules else "fork")
        with ProcessPoolExecutor(max_workers=jobs, mp_context=ctx) as ex:
            for res in ex.map(
                _lint_file_worker,
                [(str(pf.path), pf.relpath, pf.source) for pf in pending],
            ):
                per_file[res["relpath"]] = res
    else:
        for pf in pending:
            per_file[pf.relpath] = _file_scope_results(pf, rule_ids)
    for res in per_file.values():
        for rid, dt in res.get("rule_seconds", {}).items():
            rule_seconds[rid] = rule_seconds.get(rid, 0.0) + dt

    # -- project-scope rules: over the full parsed set (verdict-cached) ----
    proj_new_rows: List[list] = []
    proj_sup_rows: List[list] = []
    if project_cached is not None:
        proj_new_rows = list(project_cached.get("new", []))
        proj_sup_rows = list(project_cached.get("suppressed", []))
        # keep every project rule id present in the timing report at 0.0:
        # a warm bench.py --lint must show the shardcheck/rangecheck
        # families as cached-cheap, not as silently vanished — warm and
        # cold JSON lines stay shape-comparable
        for rid, r in sorted(RULES.items()):
            if r.scope == "project" and (rule_ids is None or rid in rule_ids):
                rule_seconds[rid] = 0.0
    else:
        active_project = [
            r for rid, r in sorted(RULES.items())
            if r.scope == "project" and (rule_ids is None or rid in rule_ids)
        ]
        for rule in active_project:
            t0 = time.perf_counter()
            for f in rule.check_project(files):
                pf = by_rel.get(f.path)
                if pf is None:
                    continue
                if pf.is_suppressed(f):
                    proj_sup_rows.append([f.rule, f.path, f.line, f.message])
                else:
                    proj_new_rows.append(
                        [f.rule, f.path, f.line, f.message,
                         pf.source_line(f.line)]
                    )
            rule_seconds[rule.id] = time.perf_counter() - t0

    if caching:
        fresh = {
            rel: {
                "key": file_keys[rel],
                "new": res["new"],
                "suppressed": res["suppressed"],
                # timings are run-local, not part of the cached verdict
            }
            for rel, res in per_file.items()
            if rel in file_keys
        }
        # MERGE into the loaded cache (a subset-path run must not evict
        # the full-tree entries it didn't scan), pruning entries whose
        # file no longer exists — deleted/renamed files are never scanned
        # again, so without the prune their entries would live forever
        merged_cache = {
            rel: ent
            for rel, ent in cache_data.items()
            if isinstance(ent, dict)
            and rel != _PROJECT_CACHE_KEY
            and not rel.startswith("/")
            and (REPO_ROOT / rel).exists()
        }
        merged_cache.update(fresh)
        if project_key is not None:
            merged_cache[_PROJECT_CACHE_KEY] = {
                "key": project_key,
                "new": proj_new_rows,
                "suppressed": proj_sup_rows,
            }
        try:
            cache_path.write_text(json.dumps(merged_cache, sort_keys=True))
        except OSError:
            pass  # a read-only checkout lints fine, just never warm

    # -- merge, baseline ---------------------------------------------------
    merged_new: List[Tuple[Finding, str]] = []
    suppressed: List[Finding] = []
    for rel, res in per_file.items():
        for rid, line, msg, src in res["new"]:
            merged_new.append((Finding(rid, rel, line, msg), src))
        for rid, line, msg in res["suppressed"]:
            suppressed.append(Finding(rid, rel, line, msg))
    for rid, path, line, msg, src in proj_new_rows:
        merged_new.append((Finding(rid, path, line, msg), src))
    for rid, path, line, msg in proj_sup_rows:
        suppressed.append(Finding(rid, path, line, msg))

    baseline = _load_baseline(baseline_path) if use_baseline else {}
    budget = dict(baseline)
    new: List[Tuple[Finding, str]] = []
    baselined: List[Finding] = []
    for f, src in sorted(
        merged_new, key=lambda t: (t[0].path, t[0].line, t[0].rule)
    ):
        fp = f.fingerprint(src)
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            baselined.append(f)
            continue
        new.append((f, src))
    suppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    return RunResult(
        new, baselined, suppressed, len(files), rule_seconds,
        cache_hits, cache_misses,
    )


def write_baseline(result: RunResult, path: Optional[Path] = None) -> int:
    """Freeze the current new findings into the baseline file. Callers run
    with use_baseline=False first so every occurrence lands in ``new``."""
    entries: Dict[str, int] = {}
    for f, src in result.new:
        fp = f.fingerprint(src)
        entries[fp] = entries.get(fp, 0) + 1
    (path or BASELINE_PATH).write_text(
        json.dumps({"entries": entries}, indent=2, sort_keys=True) + "\n"
    )
    return len(entries)


def _unique_ids(result: RunResult) -> List[Tuple[Finding, str, str]]:
    """(finding, source line, stable id) with duplicate-line findings
    disambiguated by an occurrence suffix — ids stay stable and unique."""
    seen: Dict[str, int] = {}
    out = []
    for f, src in result.new:
        base = f.stable_id(src)
        n = seen.get(base, 0)
        seen[base] = n + 1
        out.append((f, src, base if n == 0 else f"{base}-{n + 1}"))
    return out


def _render_json(result: RunResult) -> str:
    return json.dumps(
        {
            "schema": "graftlint-json/1",
            "findings": [
                {
                    "id": fid,
                    "rule": f.rule,
                    "path": f.path,
                    "line": f.line,
                    "message": f.message,
                }
                for f, _src, fid in _unique_ids(result)
            ],
            "summary": {
                "files": result.files,
                "new": len(result.new),
                "baselined": len(result.baselined),
                "suppressed": len(result.suppressed),
                "cache_hits": result.cache_hits,
                "cache_misses": result.cache_misses,
                "rule_seconds": {
                    rid: round(dt, 4)
                    for rid, dt in sorted(result.rule_seconds.items())
                },
            },
        },
        indent=2,
        sort_keys=True,
    )


def _render_sarif(result: RunResult) -> str:
    used = sorted({f.rule for f, _src in result.new})
    rules_meta = [
        {
            "id": rid,
            "name": RULES[rid].name if rid in RULES else "suppression-hygiene",
            "shortDescription": {
                "text": RULES[rid].rationale
                if rid in RULES
                else "suppression without justification",
            },
        }
        for rid in used
    ]
    return json.dumps(
        {
            "$schema": (
                "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json"
            ),
            "version": "2.1.0",
            "runs": [
                {
                    "tool": {
                        "driver": {
                            "name": "graftlint",
                            "informationUri": "tools/graftlint",
                            "rules": rules_meta,
                        }
                    },
                    "results": [
                        {
                            "ruleId": f.rule,
                            "level": "error",
                            "message": {"text": f.message},
                            "locations": [
                                {
                                    "physicalLocation": {
                                        "artifactLocation": {"uri": f.path},
                                        "region": {"startLine": f.line},
                                    }
                                }
                            ],
                            "partialFingerprints": {"graftlint/v1": fid},
                        }
                        for f, _src, fid in _unique_ids(result)
                    ],
                }
            ],
        },
        indent=2,
        sort_keys=True,
    )


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="project-native static analysis for karpenter-core-tpu",
    )
    ap.add_argument(
        "paths", nargs="*", default=[],
        help="files/dirs to lint (default: karpenter_core_tpu)",
    )
    ap.add_argument(
        "--baseline", action="store_true",
        help="rewrite tools/graftlint/baseline.json from current findings",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument(
        "--timing", action="store_true", help="per-rule wall time report"
    )
    ap.add_argument(
        "--rule", action="append", default=None,
        help="restrict to one rule id (repeatable)",
    )
    ap.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="output format (json/sarif carry stable finding ids)",
    )
    ap.add_argument(
        "--jobs", type=int, default=1, metavar="N",
        help="run the file-scope rules over N worker processes",
    )
    ap.add_argument(
        "--no-cache", action="store_true",
        help="disable the incremental per-file finding cache",
    )
    ap.add_argument(
        "--changed-only", action="store_true",
        help="file-scope rules run only over files changed vs the"
        " merge-base (committed+staged+unstaged+untracked);"
        " project-scope rules still check the full tree",
    )
    ap.add_argument(
        "--base", default=None, metavar="REF",
        help="diff base for --changed-only (default: merge-base of HEAD"
        " with origin/main or main)",
    )
    ap.add_argument(
        "--update-wire-lock", action="store_true",
        help="regenerate tools/graftlint/wire_schema.lock.json from"
        " solver/codec.py (refuses a field-set change without a wire"
        " version bump)",
    )
    args = ap.parse_args(argv)

    from tools.graftlint import rules as _rules  # noqa: F401

    if args.update_wire_lock:
        from tools.graftlint.rules.parity import (
            WIRE_LOCK_PATH,
            update_wire_lock,
        )

        n = update_wire_lock()
        print(
            f"graftlint: {WIRE_LOCK_PATH.name} rewritten with"
            f" {n} locked encoder(s)"
        )
        return 0

    if args.list_rules:
        for rid, r in sorted(RULES.items()):
            print(f"{rid}  {r.name:24s} {r.rationale}")
        return 0

    if args.baseline and (args.rule or args.paths):
        # a rule- or path-restricted regeneration would silently drop
        # every other rule's/path's frozen entries from the file
        raise SystemExit(
            "graftlint: --baseline regenerates over the full default tree;"
            " it cannot be combined with --rule or explicit paths"
        )

    paths = args.paths or ["karpenter_core_tpu"]
    restrict = None
    if args.changed_only:
        restrict = changed_relpaths(args.base)
    result = run(
        paths,
        use_baseline=not args.baseline,
        rule_ids=args.rule,
        jobs=max(1, args.jobs),
        cache_path=None if (args.no_cache or args.rule) else CACHE_PATH,
        restrict_to=restrict,
    )

    if args.baseline:
        n = write_baseline(result)
        print(f"graftlint: baseline rewritten with {n} entr{'y' if n == 1 else 'ies'}")
        return 0

    if args.format == "json":
        print(_render_json(result))
        return 0 if result.ok else 1
    if args.format == "sarif":
        print(_render_sarif(result))
        return 0 if result.ok else 1

    for f, _src in result.new:
        print(f.render())
    if args.timing:
        for rid, dt in sorted(
            result.rule_seconds.items(), key=lambda kv: -kv[1]
        ):
            print(f"# {rid}: {dt * 1000:.1f} ms")
    print(
        f"graftlint: {len(result.new)} finding(s)"
        f" ({len(result.baselined)} baselined,"
        f" {len(result.suppressed)} suppressed)"
        f" across {result.files} file(s), {len(result.rule_seconds)} rule(s)"
        + (
            f", cache {result.cache_hits}/{result.cache_hits + result.cache_misses} hit"
            if result.cache_hits + result.cache_misses
            else ""
        )
    )
    return 0 if result.ok else 1
